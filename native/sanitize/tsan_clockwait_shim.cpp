// TSan-build-only shim: route pthread_cond_clockwait through the
// intercepted pthread_cond_timedwait.
//
// This toolchain's libstdc++ inlines a direct pthread_cond_clockwait
// call (glibc 2.30+) for every steady-clock condition_variable
// wait_for/wait_until, but GCC 10's libtsan ships NO interceptor for it
// (added in GCC 11).  ThreadSanitizer therefore never sees the mutex
// release/reacquire inside the wait: every cv handoff in the tree —
// Channel::recv_until/send_until, Oneshot::wait, the proposer and
// quorum-waiter stake waits, the sidecar probe backoff — reports as a
// "double lock of a mutex" plus data races on everything the channel
// carried (617 reports on a baseline run, all of this one shape; a
// 15-line obviously-correct cv program reproduces it).
//
// The fix is to give TSan a wait it DOES understand: translate the
// absolute clockid deadline to a CLOCK_REALTIME deadline and call
// pthread_cond_timedwait, whose interceptor models the mutex hand-off
// correctly.  The conversion inherits realtime-clock skew for the
// duration of one wait slice — irrelevant for tests, and this object is
// linked ONLY into -DGRAFT_SANITIZE=thread builds (CMakeLists.txt /
// scripts/native_sanitize.sh), never into production binaries.
//
// Defining the symbol in the link unit preempts the versioned libc
// reference, so no LD_PRELOAD is needed.

#include <pthread.h>
#include <time.h>

extern "C" int pthread_cond_clockwait(pthread_cond_t* cond,
                                      pthread_mutex_t* mtx,
                                      clockid_t clockid,
                                      const struct timespec* abstime) {
  struct timespec now_clock;
  struct timespec now_rt;
  struct timespec target;
  clock_gettime(clockid, &now_clock);
  clock_gettime(CLOCK_REALTIME, &now_rt);
  long long rel_ns =
      (abstime->tv_sec - now_clock.tv_sec) * 1000000000LL +
      (abstime->tv_nsec - now_clock.tv_nsec);
  if (rel_ns < 0) rel_ns = 0;
  long long tgt_ns =
      now_rt.tv_sec * 1000000000LL + now_rt.tv_nsec + rel_ns;
  target.tv_sec = static_cast<time_t>(tgt_ns / 1000000000LL);
  target.tv_nsec = static_cast<long>(tgt_ns % 1000000000LL);
  return pthread_cond_timedwait(cond, mtx, &target);
}

#include "consensus/core.hpp"

#include <algorithm>
#include <atomic>
#include <chrono>
#include <deque>
#include <functional>
#include <map>
#include <random>
#include <set>
#include <thread>

#include "common/log.hpp"
#include "common/metrics.hpp"
#include "crypto/sidecar_client.hpp"

namespace hotstuff {
namespace consensus {

namespace {

// grafttrace: one machine-parseable span line per consensus hot-path
// stage, keyed on block digest + round so obs/trace.py can stitch the
// per-block commit critical path across replica logs.  Disabled cost is
// the one relaxed atomic load in log_trace_enabled() — digest
// serialization is only paid when tracing is on.
void trace_stage(const char* stage, const Block& block) {
  if (!log_trace_enabled()) return;
  LOG_INFO("consensus::core")
      << "TRACE stage=" << stage << " block=" << block.digest().to_base64()
      << " round=" << block.round;
}

// The replica state machine (one instance on one thread).
class CoreImpl {
 public:
  CoreImpl(PublicKey name, Committee committee,
           SignatureService signature_service, Store store,
           std::shared_ptr<LeaderElector> leader_elector,
           std::shared_ptr<MempoolDriver> mempool_driver,
           std::shared_ptr<Synchronizer> synchronizer, Parameters params,
           ChannelPtr<CoreEvent> rx_event,
           ChannelPtr<ProposerMessage> tx_proposer,
           ChannelPtr<Block> tx_commit)
      : name_(name),
        committee_(std::move(committee)),
        signature_service_(std::move(signature_service)),
        store_(std::move(store)),
        leader_elector_(std::move(leader_elector)),
        mempool_driver_(std::move(mempool_driver)),
        synchronizer_(std::move(synchronizer)),
        params_(params),
        chain_depth_(params.chain_depth),
        rx_event_(std::move(rx_event)),
        tx_proposer_(std::move(tx_proposer)),
        tx_commit_(std::move(tx_commit)),
        aggregator_(committee_),
        jitter_rng_(jitter_seed(name)) {}

  void run() {
    // Crash recovery first: a restarted replica resumes at its persisted
    // round with its voting-safety watermark intact.
    restore_state();
    // Bootstrap: timer armed; leader of round 1 proposes immediately
    // (core.rs:438-444).
    reset_timer();
    if (name_ == leader_elector_->get_leader(round_)) {
      generate_proposal(std::nullopt);
    }
    while (true) {
      CoreEvent event;
      auto status = rx_event_->recv_until(&event, timer_deadline_);
      if (status == RecvStatus::kClosed) return;
      if (status == RecvStatus::kTimeout) {
        local_timeout_round();
        flush_state();
        continue;
      }
      auto ev_start = std::chrono::steady_clock::now();
      VerifyResult result = VerifyResult::good();
      if (event.kind == CoreEvent::Kind::kLoopback) {
        // Loopback blocks re-enter after handle_proposal fully verified
        // them; they were suspended for ancestor/payload sync only, and
        // the synchronizer loops back the SAME bytes it suspended.
        // VERIFIES(block)
        result = process_block(event.block);
      } else if (event.kind == CoreEvent::Kind::kVerdict) {
        result = handle_verdict(event.block, event.verdict);
      } else if (event.kind == CoreEvent::Kind::kTcVerdict) {
        result = resolve_tc_batch(event.tc_round, event.tc_gen,
                                  event.verdict);
      } else {
        switch (event.message.kind) {
          case ConsensusMessage::Kind::kPropose:
            result = handle_proposal(event.message.block);
            break;
          case ConsensusMessage::Kind::kVote:
            result = handle_vote(event.message.vote);
            break;
          case ConsensusMessage::Kind::kTimeout:
            result = handle_timeout(event.message.timeout);
            break;
          case ConsensusMessage::Kind::kTC:
            result = handle_tc(event.message.tc);
            break;
          default:
            LOG_WARN("consensus::core") << "unexpected protocol message";
        }
      }
      flush_state();
      auto ev_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                       std::chrono::steady_clock::now() - ev_start)
                       .count();
      if (ev_ms > 500) {
        LOG_WARN("consensus::core")
            << "SLOW event kind=" << int(event.kind)
            << " msg_kind=" << int(event.message.kind) << " took " << ev_ms
            << " ms";
      }
      if (!result.ok()) {
        LOG_WARN("consensus::core") << result.error;
      }
    }
  }

 private:
  // -- timer ---------------------------------------------------------------

  // Per-node deterministic jitter seed: fold the public key's bytes so
  // every replica draws a DIFFERENT (but reproducible) jitter sequence —
  // the point of pacemaker jitter is desynchronizing the committee's
  // timeout waves, which a shared seed would defeat.
  static uint64_t jitter_seed(const PublicKey& name) {
    uint64_t seed = 0x9e3779b97f4a7c15ULL;
    for (size_t i = 0; i < name.data.size(); i++) {
      seed = seed * 131 + name.data[i];
    }
    return seed;
  }

  // graftview pacemaker: exponential backoff with a cap on consecutive
  // no-progress rounds (schedule in config.hpp backoff_delay_ms), plus
  // seeded jitter at depth >= 1.  Depth 0 — every healthy round — arms
  // after exactly timeout_delay, today's behavior.
  void reset_timer() {
    uint64_t delay = backoff_delay_ms(params_, consecutive_timeouts_);
    if (consecutive_timeouts_ > 0 && params_.timeout_jitter_pct > 0) {
      uint64_t span = delay * params_.timeout_jitter_pct / 100;
      if (span > 0) delay += jitter_rng_() % (span + 1);
    }
    timer_deadline_ = std::chrono::steady_clock::now() +
                      std::chrono::milliseconds(delay);
  }

  // Any certificate progress re-arms the pacemaker at depth 0.
  void note_progress() { consecutive_timeouts_ = 0; }

  // -- persistence ---------------------------------------------------------

  void store_block(const Block& block) {
    store_.write(block.digest().to_bytes(), block.to_bytes());
  }

  // -- voting safety (core.rs:99-146) --------------------------------------

  void increase_last_voted_round(Round target) {
    if (target > last_voted_round_) {
      last_voted_round_ = target;
      // Safety-critical ordering: the vote/timeout signed under this
      // watermark must not leave the node before the watermark reaches the
      // WAL. persist + read-back barrier (the store thread handles
      // commands in order, so the read completing proves the append ran).
      // Scope: protects against process crashes; power-loss safety would
      // need fdatasync per vote (see store.cpp wal_append).
      persist_state();
      store_.read(state_key());
    }
  }

  std::optional<Vote> make_vote(const Block& block) {
    bool safety_rule_1 = block.round > last_voted_round_;
    bool safety_rule_2 = block.qc.round + 1 == block.round;
    if (block.tc) {
      bool can_extend = block.tc->round + 1 == block.round;
      auto rounds = block.tc->high_qc_rounds();
      can_extend &= block.qc.round >=
                    *std::max_element(rounds.begin(), rounds.end());
      safety_rule_2 |= can_extend;
    }
    if (!(safety_rule_1 && safety_rule_2)) return std::nullopt;
    increase_last_voted_round(block.round);
    return Vote::make(block, name_, signature_service_);
  }

  // -- commit (core.rs:148-187) --------------------------------------------

  VerifyResult commit(const Block& block) {
    if (last_committed_round_ >= block.round) return VerifyResult::good();

    // Commit the full chain up to this block (needed after view changes).
    std::deque<Block> to_commit;
    Block parent = block;
    while (last_committed_round_ + 1 < parent.round) {
      auto ancestor = synchronizer_->get_parent_block(parent);
      if (!ancestor) {
        return VerifyResult::bad("missing ancestor during commit");
      }
      to_commit.push_front(*ancestor);
      parent = std::move(*ancestor);
    }
    to_commit.push_back(block);
    // Oldest first; `block` last (matches the reference's pop_back order
    // after push_front of ancestors, core.rs:155-166).
    std::sort(to_commit.begin(), to_commit.end(),
              [](const Block& a, const Block& b) { return a.round < b.round; });

    last_committed_round_ = block.round;
    state_dirty_ = true;
    note_progress();
    // Commit-keyed aggregator GC (graftdag): vote/timeout state at or
    // below the committed round is dead regardless of the round clock —
    // advance_round's cleanup misses it on catch-up commit walks.
    size_t gc = aggregator_.gc_committed(last_committed_round_);
    if (gc > 0) {
      LOG_DEBUG("consensus::core")
          << "Garbage-collected aggregation state for " << gc
          << " committed round(s)";
    }

    for (const Block& b : to_commit) {
      trace_stage("commit", b);
      NodeMetrics::instance().note_commit();
      if (!b.payload.empty()) {
        LOG_INFO("consensus::core") << "Committed B" << b.round;
        // NOTE: These log entries are used to compute performance
        // (hotstuff_tpu/harness/logs.py commit regex).
        for (const Digest& x : b.payload) {
          LOG_INFO("consensus::core")
              << "Committed B" << b.round << " -> " << x.to_base64();
        }
      }
      tx_commit_->send(b);
    }
    return VerifyResult::good();
  }

  // -- round advancement ---------------------------------------------------

  void update_high_qc(const QC& qc) {
    if (qc.round > high_qc_.round) {
      high_qc_ = qc;
      state_dirty_ = true;
      // QC progress: the pacemaker's backoff depth resets (a TC advance
      // deliberately does NOT — consecutive view changes keep backing
      // off until a certificate or commit proves the system is moving).
      note_progress();
    }
  }

  void advance_round(Round round) {
    if (round < round_) return;
    round_ = round + 1;
    reset_timer();
    LOG_DEBUG("consensus::core") << "Moved to round " << round_;
    aggregator_.cleanup(round_);
    tc_batches_.erase(tc_batches_.begin(), tc_batches_.lower_bound(round_));
    tc_inline_rounds_.erase(tc_inline_rounds_.begin(),
                            tc_inline_rounds_.lower_bound(round_));
    state_dirty_ = true;
  }

  // -- crash-recovery state (EXCEEDS the reference: core.rs:112 leaves
  // round/last_voted_round/high_qc volatile with an acknowledged TODO, so
  // an upstream replica can double-vote after a crash+restart) -----------

  static Bytes state_key() {
    // 7 bytes: cannot collide with block/payload keys (32-byte digests).
    return Bytes{'c', 's', 't', 'a', 't', 'e', '\x01'};
  }

  void persist_state() {
    Writer w;
    w.u64(round_);
    w.u64(last_voted_round_);
    w.u64(last_committed_round_);
    high_qc_.serialize(&w);
    store_.write(state_key(), std::move(w.out));
    state_dirty_ = false;
  }

  // Liveness state (round, high QC, commit watermark) persists once per
  // handled event, not once per mutation — losing the tail of it is
  // benign (the replica resyncs), unlike the voting watermark above.
  void flush_state() {
    if (state_dirty_) persist_state();
  }

  void restore_state() {
    auto bytes = store_.read(state_key());
    if (!bytes) return;
    Round round, last_voted, last_committed;
    QC high_qc;
    try {
      Reader r(*bytes);
      round = r.u64();
      last_voted = r.u64();
      last_committed = r.u64();
      high_qc = QC::deserialize(&r);
    } catch (const std::exception& e) {
      // All-or-nothing: a torn/incompatible record must not leave
      // partially restored state behind.
      LOG_ERROR("consensus::core")
          << "corrupt persisted state ignored: " << e.what();
      return;
    }
    round_ = round;
    last_voted_round_ = last_voted;
    last_committed_round_ = last_committed;
    high_qc_ = std::move(high_qc);
    LOG_INFO("consensus::core")
        << "Restored consensus state: round " << round_ << ", last voted "
        << last_voted_round_ << ", high QC round " << high_qc_.round;
  }

  void process_qc(const QC& qc) {
    advance_round(qc.round);
    update_high_qc(qc);
  }

  void generate_proposal(std::optional<TC> tc) {
    ProposerMessage msg;
    msg.kind = ProposerMessage::Kind::kMake;
    msg.round = round_;
    msg.qc = high_qc_;
    msg.tc = std::move(tc);
    tx_proposer_->send(std::move(msg));
  }

  void cleanup_proposer(const Block& b0, const Block& b1, const Block& block) {
    ProposerMessage msg;
    msg.kind = ProposerMessage::Kind::kCleanup;
    for (const auto* b : {&b0, &b1, &block}) {
      msg.digests.insert(msg.digests.end(), b->payload.begin(),
                         b->payload.end());
    }
    tx_proposer_->send(std::move(msg));
  }

  // -- timeouts / view change (core.rs:195-296) ----------------------------

  void local_timeout_round() {
    LOG_WARN("consensus::core") << "Timeout reached for round " << round_;
    consecutive_timeouts_++;  // backoff depth; reset on QC/commit progress
    increase_last_voted_round(round_);
    Timeout timeout =
        Timeout::make(high_qc_, round_, name_, signature_service_);
    reset_timer();
    std::vector<Address> addresses;
    for (const auto& [_, addr] : committee_.broadcast_addresses(name_)) {
      addresses.push_back(addr);
    }
    network_.broadcast(addresses, ConsensusMessage::timeout_msg(timeout));
    VerifyResult r = handle_timeout(timeout);
    if (!r.ok()) LOG_WARN("consensus::core") << r.error;
  }

  // graftview: optimistic batched TC assembly.  Arriving timeouts are
  // admitted into the aggregator after structure/stake checks only — the
  // per-sender host signature verify that used to run inline here was the
  // N=100 fault-path wall (one synchronous ed25519 per timeout on the
  // core thread, during the exact storm the system is trying to survive).
  // Once 2f+1 stake accumulates, the candidate set's own signatures are
  // verified in ONE batch: asynchronously through the sidecar when it has
  // pipeline room (all honest timeouts for a round share the
  // (round, high_qc_round) digest, so the batch is QC-shaped and rides
  // the warmed verify path + verdict cache), else one synchronous
  // verify_batch_multi (sidecar or host loop).  A failed batch ejects the
  // bad signers per-signature host-side and re-arms with later arrivals.
  VerifyResult handle_timeout(const Timeout& timeout) {
    if (timeout.round < round_) return VerifyResult::good();
    // Bounded aggregation: a flood of timeouts for round r + 10^9 must
    // not allocate per-round state forever.  Dropped count is logged on
    // powers of two so a storm costs O(log n) log lines.
    if (timeout.round > round_ + params_.timeout_future_horizon) {
      dropped_future_timeouts_++;
      if ((dropped_future_timeouts_ & (dropped_future_timeouts_ - 1)) == 0) {
        LOG_WARN("consensus::core")
            << "Dropped " << dropped_future_timeouts_
            << " future-round timeout(s) beyond horizon (round "
            << timeout.round << " > " << round_ << " + "
            << params_.timeout_future_horizon << ")";
      }
      return VerifyResult::good();
    }
    if (committee_.stake(timeout.author) == 0) {
      return VerifyResult::bad("unknown timeout author: " +
                               timeout.author.to_base64());
    }
    // The embedded high QC is self-certifying (its own signature quorum),
    // so verifying and processing it before the timeout's own signature
    // is safe — and during a view change the 2f+1 timeouts typically all
    // carry the same high QC: one cached verification instead of 2f+1.
    VerifyResult valid = verify_qc_cached(timeout.high_qc);
    if (!valid.ok()) return valid;
    process_qc(timeout.high_qc);
    if (timeout.round < round_) return VerifyResult::good();  // QC moved us

    // A lost batch verdict (the reply channel was full) must delay TC
    // formation by one expiry, never wedge it: re-resolve as a transport
    // failure (host per-signature) before admitting more arrivals.
    auto inflight = tc_batches_.find(timeout.round);
    if (inflight != tc_batches_.end() &&
        std::chrono::steady_clock::now() >= inflight->second.expires) {
      LOG_WARN("consensus::core")
          << "TC batch verdict for round " << timeout.round
          << " expired; resolving on host";
      VerifyResult r = resolve_tc_batch(timeout.round,
                                        inflight->second.gen, std::nullopt);
      if (!r.ok()) return r;
      // The host resolve may have sealed the TC and advanced the round:
      // this timeout is then stale and must not re-create aggregation
      // state for a round the cleanup already dropped.
      if (timeout.round < round_) return VerifyResult::good();
    }

    // Optimism is per round and one strike: once a batch for this round
    // ejected ANY signer, later arrivals verify inline (the old per-sig
    // admission, pre-verified entries).  Without this, a spoofer racing
    // the genuine authors could re-occupy the reopened slots with fresh
    // garbage bytes faster than the backed-off honest re-broadcasts
    // return, starving TC formation batch after batch; with it, a
    // Byzantine flood wastes exactly one batch round-trip per round
    // before the round degrades to the unspoofable path.
    bool inline_verify = tc_inline_rounds_.count(timeout.round) != 0;
    if (inline_verify) {
      VerifyResult own = timeout.verify_own(committee_);
      if (!own.ok()) return own;
    }
    auto added = aggregator_.add_timeout(timeout, inline_verify);
    if (!added.error.empty()) return VerifyResult::bad(added.error);
    if (added.tc) return finish_tc(std::move(*added.tc));
    if (!added.candidates.empty()) {
      return dispatch_tc_batch(timeout.round, std::move(added.candidates));
    }
    return VerifyResult::good();
  }

  // One batched verification launch over a round's unverified timeout
  // candidates.  Async when the sidecar has pipeline room (the verdict
  // loops back as a kTcVerdict event); synchronous otherwise.
  VerifyResult dispatch_tc_batch(
      Round round, std::vector<Aggregator::TimeoutVote> cands) {
    uint64_t gen = ++tc_batch_gen_;
    std::vector<std::tuple<Digest, PublicKey, Signature>> items;
    items.reserve(cands.size());
    for (const auto& c : cands) {
      items.emplace_back(Timeout::vote_digest(round, c.high_qc_round),
                         c.author, c.signature);
    }
    LOG_DEBUG("consensus::core")
        << "Batched TC verify for round " << round << ": " << items.size()
        << " timeout signature(s), one launch";
    if (Signature::async_available()) {
      int deadline_ms = 2 * TpuVerifier::kRecvTimeoutMs;
      tc_batches_[round] = TcBatch{
          gen, std::move(cands),
          std::chrono::steady_clock::now() +
              std::chrono::milliseconds(deadline_ms)};
      auto ch = rx_event_;
      // Context tag (protocol v5): the round's shared timeout digest —
      // one stable tag per (round, high_qc wave), so the sidecar's stage
      // spans for the view-change batch are joinable like a block's.
      Digest ctx = Timeout::vote_digest(round, round);
      Signature::verify_batch_multi_async(
          std::move(items),
          [ch, round, gen](std::optional<bool> ok) {
            ch->try_send(CoreEvent::tc_verdict(round, gen, ok));
          },
          /*bulk=*/false, &ctx);
      return VerifyResult::good();
    }
    // Synchronous path: still ONE batch (a connected sidecar without
    // async budget, or the host loop), resolved inline.  The checked
    // variant distinguishes "the BLS remainder was unreachable" (nullopt
    // — re-arm, don't eject) from a definitive verdict;
    // allow_redispatch=false bounds the resolve->dispatch recursion to
    // one round-trip per resolve chain.
    tc_batches_[round] =
        TcBatch{gen, std::move(cands), std::chrono::steady_clock::now()};
    std::optional<bool> ok = Signature::verify_batch_multi_checked(items);
    return resolve_tc_batch(round, gen, ok, /*allow_redispatch=*/false);
  }

  // Completion of a batched TC verify.  ok=true: every candidate's
  // signature held — seal.  ok=false/nullopt: find the bad signers by
  // per-signature HOST verification (bit-equivalent to the verify_own
  // the optimistic path skipped) and eject exactly those, so the
  // accepted set is identical to what per-signature admission would
  // have built.  EXCEPT under scheme=bls, where nullopt means the
  // sidecar was unreachable — the 192-byte signatures are UNKNOWN, not
  // forged (per-signature "fallback" would just re-ask the dead sidecar
  // and read every honest one as false, ejecting + one-striking the
  // whole candidate set for the outage) — so that case diverts to
  // resolve_tc_outage below.
  VerifyResult resolve_tc_batch(Round round, uint64_t gen,
                                std::optional<bool> ok,
                                bool allow_redispatch = true) {
    auto it = tc_batches_.find(round);
    if (it == tc_batches_.end() || it->second.gen != gen) {
      return VerifyResult::good();  // stale verdict: round re-armed/moved
    }
    std::vector<Aggregator::TimeoutVote> cands = std::move(it->second.cands);
    tc_batches_.erase(it);
    if (!ok.has_value() && current_scheme() == Scheme::kBls) {
      return resolve_tc_outage(round, std::move(cands), allow_redispatch);
    }
    std::vector<PublicKey> verified, ejected;
    if (ok.has_value() && *ok) {
      // The sidecar's batch verdict covered every candidate signature.
      // VERIFIES(device-verdict)
      verified.reserve(cands.size());
      for (const auto& c : cands) verified.push_back(c.author);
    } else {
      for (const auto& c : cands) {
        if (c.signature.verify(Timeout::vote_digest(round, c.high_qc_round),
                               c.author)) {
          verified.push_back(c.author);
        } else {
          ejected.push_back(c.author);
        }
      }
      if (!ejected.empty()) {
        LOG_WARN("consensus::core")
            << "Ejected " << ejected.size()
            << " invalid timeout signer(s) for round " << round
            << " (batched TC verify failed; per-signature fallback)";
        // One strike: this round's later arrivals verify inline (see
        // handle_timeout) so re-spoofed slots cannot waste another
        // batch.  Bounded by the same horizon/advance cleanup as the
        // batches themselves.
        tc_inline_rounds_.insert(round);
      }
    }
    auto res = aggregator_.resolve_timeouts(round, verified, ejected);
    if (!res.error.empty()) return VerifyResult::bad(res.error);
    if (res.tc) return finish_tc(std::move(*res.tc));
    if (!res.candidates.empty()) {
      // Arrivals during the batch flight completed another quorum.
      return dispatch_tc_batch(round, std::move(res.candidates));
    }
    return VerifyResult::good();
  }

  // The scheme=bls sidecar-outage arm of resolve_tc_batch: host-verify
  // the 64-byte Ed25519 fallback entries now (sidecar-down signers keep
  // the view change live through them — see Signature::sign), defer the
  // BLS remainder.  A TC can form from fallback signatures alone while
  // every sidecar is dark; deferred BLS entries re-verify when one
  // answers again.
  VerifyResult resolve_tc_outage(Round round,
                                 std::vector<Aggregator::TimeoutVote> cands,
                                 bool allow_redispatch) {
    std::vector<PublicKey> verified, ejected;
    size_t deferred = 0;
    for (const auto& c : cands) {
      if (c.signature.data.size() != 64) {
        deferred++;  // BLS: unknown under the outage, stays a candidate
      } else if (c.signature.verify(
                     Timeout::vote_digest(round, c.high_qc_round),
                     c.author)) {
        verified.push_back(c.author);
      } else {
        ejected.push_back(c.author);
      }
    }
    LOG_WARN("consensus::core")
        << "TC batch for round " << round << " hit a sidecar outage: "
        << verified.size() + ejected.size()
        << " fallback signature(s) resolved on host, " << deferred
        << " BLS signature(s) deferred (unknown, not ejected)";
    if (!ejected.empty()) tc_inline_rounds_.insert(round);
    auto res = aggregator_.resolve_timeouts(round, verified, ejected);
    if (!res.error.empty()) return VerifyResult::bad(res.error);
    if (res.tc) return finish_tc(std::move(*res.tc));
    if (res.candidates.empty()) return VerifyResult::good();
    TpuVerifier* tpu = TpuVerifier::instance();
    if (allow_redispatch && tpu && tpu->connected()) {
      // The sidecar recovered (or answered other traffic since): one
      // fresh dispatch.  Its own inline resolve runs with redispatch
      // disabled, bounding the resolve->dispatch recursion.
      return dispatch_tc_batch(round, std::move(res.candidates));
    }
    // Still down: re-arm already-expired, so the NEXT timeout arrival
    // for this round re-resolves (handle_timeout's expiry branch) —
    // host-verifying any new fallback arrivals and re-probing the
    // sidecar, paced by the pacemaker's re-broadcasts.
    uint64_t gen = ++tc_batch_gen_;
    tc_batches_[round] = TcBatch{gen, std::move(res.candidates),
                                 std::chrono::steady_clock::now()};
    return VerifyResult::good();
  }

  // TC-driven round advance: the ONE emitter of the "View change" line
  // (a frozen grammar hotstuff_tpu/harness/logs.py mines for the
  // view-change notes and the strict leader-cascade assertion — change
  // both sides together), shared by the formed-here and received paths.
  void advance_round_via_tc(Round tc_round) {
    Round prev = round_;
    advance_round(tc_round);
    if (round_ > prev) {
      LOG_INFO("consensus::core")
          << "View change: round " << prev << " -> " << round_ << " via TC";
    }
  }

  // A TC sealed from batch-verified timeouts: certify, advance, share.
  VerifyResult finish_tc(TC tc) {
    // NOTE: the "Formed TC" phrasing is mined by logs.py too.
    LOG_INFO("consensus::core")
        << "Formed TC for round " << tc.round << " (" << tc.votes.size()
        << " timeouts, batched verify)";
    cert_insert(tc.content_digest());
    advance_round_via_tc(tc.round);
    std::vector<Address> addresses;
    for (const auto& [_, addr] : committee_.broadcast_addresses(name_)) {
      addresses.push_back(addr);
    }
    network_.broadcast(addresses, ConsensusMessage::tc_msg(tc));
    if (name_ == leader_elector_->get_leader(round_)) {
      generate_proposal(std::move(tc));
    }
    return VerifyResult::good();
  }

  VerifyResult handle_tc(const TC& tc) {
    if (tc.round < round_) return VerifyResult::good();  // stale: skip
    // The reference skips verification here (core.rs:429-435), which lets
    // any peer — or one corrupted frame — advance our round arbitrarily
    // (observed in round 2 as a node jumping to round 97 during a stalled
    // run). Verify before trusting the round number.
    VerifyResult valid = verify_tc_cached(tc);
    if (!valid.ok()) return valid;
    advance_round_via_tc(tc.round);
    if (name_ == leader_elector_->get_leader(round_)) {
      generate_proposal(tc);
    }
    return VerifyResult::good();
  }

  // -- votes → QC (core.rs:232-255) ----------------------------------------

  VerifyResult handle_vote(const Vote& vote) {
    if (vote.round < round_) return VerifyResult::good();
    VerifyResult valid = vote.verify(committee_);
    if (!valid.ok()) return valid;

    auto added = aggregator_.add_vote(vote);
    if (!added.error.empty()) return VerifyResult::bad(added.error);
    if (added.qc) {
      // Formed from individually verified votes: no re-verification needed
      // when these exact bytes come back embedded in a proposal.
      cert_insert(added.qc->content_digest());
      process_qc(*added.qc);
      if (name_ == leader_elector_->get_leader(round_)) {
        generate_proposal(std::nullopt);
      }
    }
    return VerifyResult::good();
  }

  // -- block processing (core.rs:339-428) ----------------------------------

  VerifyResult process_block(const Block& block) {
    // Require the two ancestors: b0 <- |qc0; b1| <- |qc1; block|.
    auto ancestors = synchronizer_->get_ancestors(block);
    if (!ancestors) {
      LOG_DEBUG("consensus::core")
          << "Processing of " << block.digest().to_base64()
          << " suspended: missing parent";
      return VerifyResult::good();
    }
    auto& [b0, b1] = *ancestors;

    store_block(block);
    cleanup_proposer(b0, b1, block);

    // Commit rule (core.rs:363-366), generalized to a k-chain (graftdag).
    // 2-chain: b0 commits once its direct descendant b1 is certified in
    // the next round (block.qc certifies b1, so this processing event is
    // the earliest proof).  3-chain (upstream HotStuff; the variant
    // behind the reference's benchmark/data/3-chain results) requires
    // THREE consecutive certified rounds g0 <- b0 <- b1.  Any k >= 2
    // walks k-2 further generations below b0, requiring consecutive
    // rounds the whole way; deeper pipelines trade commit latency for
    // leaders never waiting on their own chain's commit to propose.
    if (b0.round + 1 == b1.round) {
      std::optional<Block> candidate = b0;
      for (uint32_t depth = 2; candidate && depth < chain_depth_; depth++) {
        if (candidate->round == 0) {
          candidate.reset();  // genesis has no parent to walk
          break;
        }
        auto parent = synchronizer_->get_parent_block(*candidate);
        // nullopt fires a sync request; the commit() catch-up walk of a
        // later block commits the ancestor once it arrives.  A round gap
        // (view change inside the window) breaks the chain: no commit.
        if (parent && parent->round + 1 == candidate->round) {
          candidate = std::move(*parent);
        } else {
          candidate.reset();
        }
      }
      if (candidate) {
        mempool_driver_->cleanup(candidate->round);
        VerifyResult r = commit(*candidate);
        if (!r.ok()) return r;
      }
    }

    // Bad leaders could send blocks from the far future.
    if (block.round != round_) return VerifyResult::good();

    if (auto vote = make_vote(block)) {
      PublicKey next_leader = leader_elector_->get_leader(round_ + 1);
      if (next_leader == name_) {
        return handle_vote(*vote);
      }
      auto address = committee_.address(next_leader);
      if (address) {
        network_.send(*address, ConsensusMessage::vote_msg(*vote));
      }
    }
    return VerifyResult::good();
  }

  // -- certificate-verification cache + async dispatch ---------------------

  // Remembers certificates whose signature batches already verified, so a
  // certificate is verified once per node, not once per message carrying
  // it.  Keys are content digests over the FULL serialized certificate —
  // any byte difference (notably a tampered vote set under an unchanged
  // (hash, round)) misses the cache and re-verifies.  During a view
  // change the 2f+1 timeouts typically embed byte-identical copies of the
  // same high QC (everyone forwards the bytes they received), so this
  // still collapses 2f+1 re-verifications into one — the difference
  // between O(n) and O(n^2) signature work at N=100.
  bool cert_cached(const Digest& d) const {
    return verified_certs_.count(d) != 0;
  }

  void cert_insert(const Digest& d) {
    if (!verified_certs_.insert(d).second) return;
    verified_certs_fifo_.push_back(d);
    if (verified_certs_fifo_.size() > kCertCacheCap) {
      verified_certs_.erase(verified_certs_fifo_.front());
      verified_certs_fifo_.pop_front();
    }
  }

  // VERIFIES(qc)
  VerifyResult verify_qc_cached(const QC& qc) {
    if (qc.is_genesis()) return VerifyResult::good();
    Digest d = qc.content_digest();
    if (cert_cached(d)) return VerifyResult::good();
    VerifyResult r = qc.verify(committee_);
    if (r.ok()) cert_insert(d);
    return r;
  }

  // VERIFIES(tc)
  VerifyResult verify_tc_cached(const TC& tc) {
    Digest d = tc.content_digest();
    if (cert_cached(d)) return VerifyResult::good();
    VerifyResult r = tc.verify(committee_);
    if (r.ok()) cert_insert(d);
    return r;
  }

  // graftdag: synchronous availability-certificate verification through
  // the same content-digest cache the QC/TC arms use (structure was
  // already checked by handle_proposal's Block::check_certs).
  // VERIFIES(batch-certificate)
  VerifyResult verify_cert_cached(const mempool::BatchCertificate& cert) {
    Digest d = cert.content_digest();
    if (cert_cached(d)) return VerifyResult::good();
    if (!Signature::verify_batch(cert.ack_digest(), cert.votes)) {
      return VerifyResult::bad("invalid signature in batch certificate " +
                               cert.digest.to_base64());
    }
    cert_insert(d);
    return VerifyResult::good();
  }

  // Join state for a proposal whose verification spans MULTIPLE async
  // ops (BLS QC+TC, or an Ed25519 QC/TC batch alongside a cert batch).
  //
  // graftsync: the atomics are the synchronization (acq_rel on the
  // decrement publishes all_ok/transport_fail to the last callback); ch
  // and block are written before any callback is registered and only
  // READ afterwards — the thread-start/submit edge is the
  // happens-before.
  struct VerdictJoin {
    std::atomic<int> remaining;      // SHARED_OK(atomic join counter)
    std::atomic<bool> all_ok{true};  // SHARED_OK(atomic)
    std::atomic<bool> transport_fail{false};  // SHARED_OK(atomic)
    ChannelPtr<CoreEvent> ch;  // SHARED_OK(written pre-registration)
    Block block;               // SHARED_OK(written pre-registration)
  };

  static std::function<void(std::optional<bool>)> join_completion(
      std::shared_ptr<VerdictJoin> join) {
    return [join](std::optional<bool> ok) {
      // A transport failure makes the joint verdict nullopt (unless a
      // definitive reject already landed): handle_verdict then
      // re-verifies synchronously instead of rejecting an honest
      // block because the sidecar died mid-flight.  Ordering: each
      // callback's relaxed stores are published to the LAST
      // decrementer through the acq_rel RMW chain on `remaining`
      // (release on every decrement, acquire on the one that reads
      // 1), so the final loads may stay relaxed.
      if (!ok.has_value()) {
        join->transport_fail.store(true, std::memory_order_relaxed);
      } else if (!*ok) {
        join->all_ok.store(false, std::memory_order_relaxed);
      }
      if (join->remaining.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        bool all_ok = join->all_ok.load(std::memory_order_relaxed);
        std::optional<bool> verdict(all_ok);
        if (all_ok &&
            join->transport_fail.load(std::memory_order_relaxed)) {
          verdict = std::nullopt;
        }
        CoreEvent e = CoreEvent::verdict_of(join->block, verdict);
        join->ch->try_send(std::move(e));
      }
    };
  }

  // Attempts to dispatch the proposal's outstanding certificate signature
  // batches to the device asynchronously.  Returns true if dispatched (the
  // proposal is suspended; a kVerdict event resumes it), false if the
  // caller must verify synchronously.  Structural checks and the block's
  // own (cheap, host) signature were already done by handle_proposal.
  // `need_certs` lists the block's availability certificates (graftdag)
  // whose signature batches are not yet cached.
  //
  // The completion callbacks run on the sidecar reply thread: they push
  // the verdict into the Core's own event channel and nothing else.
  // try_send: if the Core's queue is full the verdict is dropped and the
  // proposal stays suspended until its pending entry expires — the
  // leader's re-proposal or a sync request then re-verifies, identical to
  // dropping any other message under overload.
  bool try_dispatch_verify(
      const Block& block, bool need_qc, bool need_tc,
      const std::vector<const mempool::BatchCertificate*>& need_certs) {
    if (!Signature::async_available()) return false;
    auto ch = rx_event_;
    if (current_scheme() == Scheme::kBls) {
      // QC and TC go as SEPARATE ops: the sidecar pre-compiles the
      // common-digest pairing (QC shape) and the quorum-size multi-digest
      // pairing (TC shape) individually; one concatenated multi-digest
      // batch of 2x quorum would be an unwarmed shape, pushing an honest
      // view-change proposal onto the slow host pairing path.
      TpuVerifier* tpu = TpuVerifier::instance();
      if (!tpu) return false;
      // Batch ACKs are host-Ed25519 under EVERY scheme (sign_host), so a
      // cert batch can never ride the BLS opcodes — cert-carrying blocks
      // take the synchronous path, which verifies the 64-byte records on
      // the host.
      if (!need_certs.empty()) return false;
      // Mixed certificates — any 64-byte Ed25519 fallback signature
      // (signed during a peer's sidecar outage, see Signature::sign) —
      // take the synchronous path, which partitions host/device; the
      // BLS opcodes' fixed-size records would read the mix as malformed
      // and reject an honest block.
      if (need_qc) {
        for (const auto& [pk, sig] : block.qc.votes) {
          if (sig.data.size() == 64) return false;
        }
      }
      if (need_tc) {
        for (const auto& [d, pk, sig] : block.tc->vote_items()) {
          if (sig.data.size() == 64) return false;
        }
      }
      auto join = std::make_shared<VerdictJoin>();
      join->remaining = (need_qc ? 1 : 0) + (need_tc ? 1 : 0);
      join->ch = ch;
      join->block = block;
      auto complete = join_completion(join);
      // graftscope: the block digest rides both BLS verify RPCs as the
      // protocol v5 context tag (EdDSA parity, ROADMAP item 2), so
      // scheme=bls stage spans join this block's trace segment too.
      // As below, the frame is built before each call returns, so the
      // stack digest is safe to pass by pointer.
      Digest ctx = block.digest();
      if (need_qc) {
        tpu->bls_verify_votes_async(block.qc.digest(), block.qc.votes,
                                    complete, &ctx);
      }
      if (need_tc) {
        tpu->bls_verify_multi_async(block.tc->vote_items(), complete, &ctx);
      }
      return true;
    }
    // Ed25519: QC/TC votes ride one combined multi-digest batch (padded
    // power-of-two buckets; every shape is pre-warmed).
    std::vector<std::tuple<Digest, PublicKey, Signature>> items;
    if (need_qc) {
      auto qi = block.qc.vote_items();
      items.insert(items.end(), qi.begin(), qi.end());
    }
    if (need_tc) {
      auto ti = block.tc->vote_items();
      items.insert(items.end(), ti.begin(), ti.end());
    }
    // graftscope: the block digest rides the verify RPC as the protocol
    // v5 context tag, so the sidecar's admit/queue/pack/dispatch/device/
    // reply spans for this batch join this block's verify segment in the
    // merged trace (the frame is built before this call returns, so the
    // stack digest is safe to pass by pointer).
    Digest ctx = block.digest();
    if (need_certs.empty()) {
      Block copy = block;
      Signature::verify_batch_multi_async(
          std::move(items),
          [ch, copy](std::optional<bool> ok) mutable {
            CoreEvent e = CoreEvent::verdict_of(std::move(copy), ok);
            ch->try_send(std::move(e));
          },
          /*bulk=*/false, &ctx);
      return true;
    }
    // graftdag: the availability-certificate batch goes as a SEPARATE op
    // under its OWN context tag — the ack-domain derivation of the block
    // digest — so the sidecar's stage spans for ordering certificates
    // are distinguishable from the vote batch in the merged trace.  Each
    // cert is QC-shaped (2f+1 signatures over one common ack digest), so
    // the batch lands on the warmed RLC verify path.
    auto join = std::make_shared<VerdictJoin>();
    join->remaining = (items.empty() ? 0 : 1) + 1;
    join->ch = ch;
    join->block = block;
    auto complete = join_completion(join);
    if (!items.empty()) {
      Signature::verify_batch_multi_async(std::move(items), complete,
                                          /*bulk=*/false, &ctx);
    }
    // VERIFIES(batch-certificate)
    std::vector<std::tuple<Digest, PublicKey, Signature>> cert_items;
    for (const auto* cert : need_certs) {
      auto ci = cert->vote_items();
      cert_items.insert(cert_items.end(), ci.begin(), ci.end());
    }
    Digest cert_ctx = mempool::BatchCertificate::ack_digest_of(ctx);
    Signature::verify_batch_multi_async(std::move(cert_items), complete,
                                        /*bulk=*/false, &cert_ctx);
    return true;
  }

  // Completion loopback of an async certificate verification.
  VerifyResult handle_verdict(const Block& block,
                              std::optional<bool> verdict) {
    trace_stage("verify_reply", block);
    pending_verify_.erase(block.digest());
    if (!verdict.has_value()) {
      // Transport failure: the sidecar is backed off, so the synchronous
      // path below resolves on the host without re-stalling the Core.
      LOG_WARN("consensus::core")
          << "async verify transport failure; re-verifying on host";
      return handle_proposal(block);
    }
    if (!*verdict) {
      return VerifyResult::bad("invalid certificate signatures in block " +
                               block.digest().to_base64());
    }
    // The device judged every certificate signature in this block good
    // (the !*verdict reject above is the other half of the gate).
    // VERIFIES(device-verdict)
    if (!block.qc.is_genesis()) cert_insert(block.qc.content_digest());
    if (block.tc) cert_insert(block.tc->content_digest());
    for (const auto& cert : block.certs) {
      cert_insert(cert.content_digest());
    }
    return proposal_postverify(block);
  }

  // Everything handle_proposal does after the block is fully verified.
  VerifyResult proposal_postverify(const Block& block) {
    process_qc(block.qc);
    if (block.tc) advance_round(block.tc->round);

    // graftdag: a cert-carrying block's availability was PROVEN by its
    // (just verified) certificates — 2f+1 signed for stored bytes, so
    // f+1 honest replicas can serve every batch.  Vote without
    // possession; missing bytes are fetched in the background from the
    // certificate signers instead of suspending the block behind a
    // payload round trip.
    if (!block.certs.empty()) {
      mempool_driver_->prefetch(block);
      return process_block(block);
    }

    // Payload availability; suspends the block if batches are missing.
    if (!mempool_driver_->verify(block)) {
      LOG_DEBUG("consensus::core")
          << "Processing of " << block.digest().to_base64()
          << " suspended: missing payload";
      return VerifyResult::good();
    }
    return process_block(block);
  }

  VerifyResult handle_proposal(const Block& block) {
    trace_stage("proposal", block);
    // Leader check (core.rs:399-406).
    if (block.author != leader_elector_->get_leader(block.round)) {
      return VerifyResult::bad("wrong leader for round " +
                               std::to_string(block.round));
    }
    Digest bd = block.digest();
    auto pending = pending_verify_.find(bd);
    if (pending != pending_verify_.end()) {
      // Fresh: duplicate of an in-flight proposal, drop it.  Stale (the
      // verdict event was lost, e.g. dropped by a full event queue): the
      // re-delivered proposal takes over and re-verifies.
      if (std::chrono::steady_clock::now() < pending->second) {
        return VerifyResult::good();
      }
      pending_verify_.erase(pending);
    }

    // Host-cheap checks first: author, the block's own signature, and the
    // certificates' structural (stake/reuse/quorum) rules.
    if (committee_.stake(block.author) == 0) {
      return VerifyResult::bad("unknown block author: " +
                               block.author.to_base64());
    }
    if (current_scheme() != Scheme::kBls &&
        !block.signature.verify(bd, block.author)) {
      return VerifyResult::bad("invalid block signature");
    }
    bool need_qc =
        !block.qc.is_genesis() && !cert_cached(block.qc.content_digest());
    bool need_tc = block.tc && !cert_cached(block.tc->content_digest());
    if (need_qc) {
      VerifyResult r = block.qc.verify_structure(committee_);
      if (!r.ok()) return r;
    }
    if (need_tc) {
      VerifyResult r = block.tc->verify_structure(committee_);
      if (!r.ok()) return r;
    }
    // graftdag: availability-certificate shape + stake structure (host
    // cheap), then collect the certs whose signature batches still need
    // verification — cached ones (a re-proposal after a view change
    // re-carries the same certs) skip the device round trip entirely.
    VerifyResult cr = block.check_certs(committee_);
    if (!cr.ok()) return cr;
    std::vector<const mempool::BatchCertificate*> need_certs;
    for (const auto& cert : block.certs) {
      if (!cert_cached(cert.content_digest())) need_certs.push_back(&cert);
    }

    // Under scheme=bls the block's own signature is a pairing too — it
    // stays on the synchronous path below (one extra sidecar op per block;
    // the QC/TC batches are what scale with committee size).
    if (current_scheme() == Scheme::kBls &&
        !block.signature.verify(bd, block.author)) {
      return VerifyResult::bad("invalid block signature");
    }

    if ((need_qc || need_tc || !need_certs.empty()) &&
        try_dispatch_verify(block, need_qc, need_tc, need_certs)) {
      trace_stage("verify_submit", block);
      // The expiry covers a lost verdict event: transport failures arrive
      // well inside the scheme's sidecar deadline, so anything older is
      // gone for good and the next delivery of the block must re-verify.
      int deadline_ms = current_scheme() == Scheme::kBls
                            ? 2 * TpuVerifier::kBlsRecvTimeoutMs
                            : 2 * TpuVerifier::kRecvTimeoutMs;
      pending_verify_[bd] = std::chrono::steady_clock::now() +
                            std::chrono::milliseconds(deadline_ms);
      LOG_DEBUG("consensus::core")
          << "Processing of " << bd.to_base64()
          << " suspended: certificate verify in flight";
      return VerifyResult::good();
    }

    // Synchronous path (no sidecar / at pipeline cap / nothing to check).
    if (need_qc) {
      VerifyResult r = verify_qc_cached(block.qc);
      if (!r.ok()) return r;
    }
    if (need_tc) {
      VerifyResult r = verify_tc_cached(*block.tc);
      if (!r.ok()) return r;
    }
    for (const auto* cert : need_certs) {
      VerifyResult r = verify_cert_cached(*cert);
      if (!r.ok()) return r;
    }
    return proposal_postverify(block);
  }

  // -- state ---------------------------------------------------------------

  PublicKey name_;
  Committee committee_;
  SignatureService signature_service_;
  Store store_;
  std::shared_ptr<LeaderElector> leader_elector_;
  std::shared_ptr<MempoolDriver> mempool_driver_;
  std::shared_ptr<Synchronizer> synchronizer_;
  Parameters params_;
  uint32_t chain_depth_ = 2;
  bool state_dirty_ = false;
  ChannelPtr<CoreEvent> rx_event_;
  ChannelPtr<ProposerMessage> tx_proposer_;
  ChannelPtr<Block> tx_commit_;

  Round round_ = 1;
  Round last_voted_round_ = 0;
  Round last_committed_round_ = 0;
  QC high_qc_;
  Aggregator aggregator_;
  SimpleSender network_;
  std::chrono::steady_clock::time_point timer_deadline_;

  // graftview pacemaker + batched TC assembly (all core-thread-owned).
  // consecutive_timeouts_ is the backoff depth; the rng draws the seeded
  // per-node jitter; tc_batches_ tracks the one in-flight batched verify
  // per round (generation-tagged so a stale verdict after an expiry
  // re-arm cannot resolve the wrong snapshot), bounded by the same
  // future-round horizon that bounds the aggregator.
  struct TcBatch {
    uint64_t gen = 0;
    std::vector<Aggregator::TimeoutVote> cands;
    std::chrono::steady_clock::time_point expires;
  };
  uint32_t consecutive_timeouts_ = 0;
  uint64_t dropped_future_timeouts_ = 0;
  uint64_t tc_batch_gen_ = 0;
  std::map<Round, TcBatch> tc_batches_;
  // Rounds whose optimism expired (a batch ejected someone): later
  // timeout arrivals for these rounds verify inline at admission.
  std::set<Round> tc_inline_rounds_;
  std::mt19937_64 jitter_rng_;

  // Async-verify bookkeeping: block digests with a device verdict in
  // flight (value = expiry, after which a re-delivered copy re-verifies),
  // and the FIFO-bounded set of certificates already verified.
  static constexpr size_t kCertCacheCap = 1024;
  std::map<Digest, std::chrono::steady_clock::time_point> pending_verify_;
  std::set<Digest> verified_certs_;
  std::deque<Digest> verified_certs_fifo_;
};

}  // namespace

std::thread Core::spawn(PublicKey name, Committee committee,
                        SignatureService signature_service, Store store,
                        std::shared_ptr<LeaderElector> leader_elector,
                        std::shared_ptr<MempoolDriver> mempool_driver,
                        std::shared_ptr<Synchronizer> synchronizer,
                        Parameters parameters,
                        ChannelPtr<CoreEvent> rx_event,
                        ChannelPtr<ProposerMessage> tx_proposer,
                        ChannelPtr<Block> tx_commit) {
  return std::thread([=] {
    set_thread_name("core");
    CoreImpl core(name, std::move(committee), std::move(signature_service),
                  std::move(store), std::move(leader_elector),
                  std::move(mempool_driver), std::move(synchronizer),
                  parameters, std::move(rx_event), std::move(tx_proposer),
                  std::move(tx_commit));
    core.run();
  });
}

}  // namespace consensus
}  // namespace hotstuff

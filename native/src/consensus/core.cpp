#include "consensus/core.hpp"

#include <algorithm>
#include <atomic>
#include <deque>
#include <map>
#include <set>
#include <thread>

#include "common/log.hpp"
#include "common/metrics.hpp"
#include "crypto/sidecar_client.hpp"

namespace hotstuff {
namespace consensus {

namespace {

// grafttrace: one machine-parseable span line per consensus hot-path
// stage, keyed on block digest + round so obs/trace.py can stitch the
// per-block commit critical path across replica logs.  Disabled cost is
// the one relaxed atomic load in log_trace_enabled() — digest
// serialization is only paid when tracing is on.
void trace_stage(const char* stage, const Block& block) {
  if (!log_trace_enabled()) return;
  LOG_INFO("consensus::core")
      << "TRACE stage=" << stage << " block=" << block.digest().to_base64()
      << " round=" << block.round;
}

// The replica state machine (one instance on one thread).
class CoreImpl {
 public:
  CoreImpl(PublicKey name, Committee committee,
           SignatureService signature_service, Store store,
           std::shared_ptr<LeaderElector> leader_elector,
           std::shared_ptr<MempoolDriver> mempool_driver,
           std::shared_ptr<Synchronizer> synchronizer, uint64_t timeout_delay,
           uint32_t chain_depth, ChannelPtr<CoreEvent> rx_event,
           ChannelPtr<ProposerMessage> tx_proposer,
           ChannelPtr<Block> tx_commit)
      : name_(name),
        committee_(std::move(committee)),
        signature_service_(std::move(signature_service)),
        store_(std::move(store)),
        leader_elector_(std::move(leader_elector)),
        mempool_driver_(std::move(mempool_driver)),
        synchronizer_(std::move(synchronizer)),
        timeout_delay_(timeout_delay),
        chain_depth_(chain_depth),
        rx_event_(std::move(rx_event)),
        tx_proposer_(std::move(tx_proposer)),
        tx_commit_(std::move(tx_commit)),
        aggregator_(committee_) {}

  void run() {
    // Crash recovery first: a restarted replica resumes at its persisted
    // round with its voting-safety watermark intact.
    restore_state();
    // Bootstrap: timer armed; leader of round 1 proposes immediately
    // (core.rs:438-444).
    reset_timer();
    if (name_ == leader_elector_->get_leader(round_)) {
      generate_proposal(std::nullopt);
    }
    while (true) {
      CoreEvent event;
      auto status = rx_event_->recv_until(&event, timer_deadline_);
      if (status == RecvStatus::kClosed) return;
      if (status == RecvStatus::kTimeout) {
        local_timeout_round();
        flush_state();
        continue;
      }
      VerifyResult result = VerifyResult::good();
      if (event.kind == CoreEvent::Kind::kLoopback) {
        result = process_block(event.block);
      } else if (event.kind == CoreEvent::Kind::kVerdict) {
        result = handle_verdict(event.block, event.verdict);
      } else {
        switch (event.message.kind) {
          case ConsensusMessage::Kind::kPropose:
            result = handle_proposal(event.message.block);
            break;
          case ConsensusMessage::Kind::kVote:
            result = handle_vote(event.message.vote);
            break;
          case ConsensusMessage::Kind::kTimeout:
            result = handle_timeout(event.message.timeout);
            break;
          case ConsensusMessage::Kind::kTC:
            result = handle_tc(event.message.tc);
            break;
          default:
            LOG_WARN("consensus::core") << "unexpected protocol message";
        }
      }
      flush_state();
      if (!result.ok()) {
        LOG_WARN("consensus::core") << result.error;
      }
    }
  }

 private:
  // -- timer ---------------------------------------------------------------

  void reset_timer() {
    timer_deadline_ = std::chrono::steady_clock::now() +
                      std::chrono::milliseconds(timeout_delay_);
  }

  // -- persistence ---------------------------------------------------------

  void store_block(const Block& block) {
    store_.write(block.digest().to_bytes(), block.to_bytes());
  }

  // -- voting safety (core.rs:99-146) --------------------------------------

  void increase_last_voted_round(Round target) {
    if (target > last_voted_round_) {
      last_voted_round_ = target;
      // Safety-critical ordering: the vote/timeout signed under this
      // watermark must not leave the node before the watermark reaches the
      // WAL. persist + read-back barrier (the store thread handles
      // commands in order, so the read completing proves the append ran).
      // Scope: protects against process crashes; power-loss safety would
      // need fdatasync per vote (see store.cpp wal_append).
      persist_state();
      store_.read(state_key());
    }
  }

  std::optional<Vote> make_vote(const Block& block) {
    bool safety_rule_1 = block.round > last_voted_round_;
    bool safety_rule_2 = block.qc.round + 1 == block.round;
    if (block.tc) {
      bool can_extend = block.tc->round + 1 == block.round;
      auto rounds = block.tc->high_qc_rounds();
      can_extend &= block.qc.round >=
                    *std::max_element(rounds.begin(), rounds.end());
      safety_rule_2 |= can_extend;
    }
    if (!(safety_rule_1 && safety_rule_2)) return std::nullopt;
    increase_last_voted_round(block.round);
    return Vote::make(block, name_, signature_service_);
  }

  // -- commit (core.rs:148-187) --------------------------------------------

  VerifyResult commit(const Block& block) {
    if (last_committed_round_ >= block.round) return VerifyResult::good();

    // Commit the full chain up to this block (needed after view changes).
    std::deque<Block> to_commit;
    Block parent = block;
    while (last_committed_round_ + 1 < parent.round) {
      auto ancestor = synchronizer_->get_parent_block(parent);
      if (!ancestor) {
        return VerifyResult::bad("missing ancestor during commit");
      }
      to_commit.push_front(*ancestor);
      parent = std::move(*ancestor);
    }
    to_commit.push_back(block);
    // Oldest first; `block` last (matches the reference's pop_back order
    // after push_front of ancestors, core.rs:155-166).
    std::sort(to_commit.begin(), to_commit.end(),
              [](const Block& a, const Block& b) { return a.round < b.round; });

    last_committed_round_ = block.round;
    state_dirty_ = true;

    for (const Block& b : to_commit) {
      trace_stage("commit", b);
      NodeMetrics::instance().note_commit();
      if (!b.payload.empty()) {
        LOG_INFO("consensus::core") << "Committed B" << b.round;
        // NOTE: These log entries are used to compute performance
        // (hotstuff_tpu/harness/logs.py commit regex).
        for (const Digest& x : b.payload) {
          LOG_INFO("consensus::core")
              << "Committed B" << b.round << " -> " << x.to_base64();
        }
      }
      tx_commit_->send(b);
    }
    return VerifyResult::good();
  }

  // -- round advancement ---------------------------------------------------

  void update_high_qc(const QC& qc) {
    if (qc.round > high_qc_.round) {
      high_qc_ = qc;
      state_dirty_ = true;
    }
  }

  void advance_round(Round round) {
    if (round < round_) return;
    reset_timer();
    round_ = round + 1;
    LOG_DEBUG("consensus::core") << "Moved to round " << round_;
    aggregator_.cleanup(round_);
    state_dirty_ = true;
  }

  // -- crash-recovery state (EXCEEDS the reference: core.rs:112 leaves
  // round/last_voted_round/high_qc volatile with an acknowledged TODO, so
  // an upstream replica can double-vote after a crash+restart) -----------

  static Bytes state_key() {
    // 7 bytes: cannot collide with block/payload keys (32-byte digests).
    return Bytes{'c', 's', 't', 'a', 't', 'e', '\x01'};
  }

  void persist_state() {
    Writer w;
    w.u64(round_);
    w.u64(last_voted_round_);
    w.u64(last_committed_round_);
    high_qc_.serialize(&w);
    store_.write(state_key(), std::move(w.out));
    state_dirty_ = false;
  }

  // Liveness state (round, high QC, commit watermark) persists once per
  // handled event, not once per mutation — losing the tail of it is
  // benign (the replica resyncs), unlike the voting watermark above.
  void flush_state() {
    if (state_dirty_) persist_state();
  }

  void restore_state() {
    auto bytes = store_.read(state_key());
    if (!bytes) return;
    Round round, last_voted, last_committed;
    QC high_qc;
    try {
      Reader r(*bytes);
      round = r.u64();
      last_voted = r.u64();
      last_committed = r.u64();
      high_qc = QC::deserialize(&r);
    } catch (const std::exception& e) {
      // All-or-nothing: a torn/incompatible record must not leave
      // partially restored state behind.
      LOG_ERROR("consensus::core")
          << "corrupt persisted state ignored: " << e.what();
      return;
    }
    round_ = round;
    last_voted_round_ = last_voted;
    last_committed_round_ = last_committed;
    high_qc_ = std::move(high_qc);
    LOG_INFO("consensus::core")
        << "Restored consensus state: round " << round_ << ", last voted "
        << last_voted_round_ << ", high QC round " << high_qc_.round;
  }

  void process_qc(const QC& qc) {
    advance_round(qc.round);
    update_high_qc(qc);
  }

  void generate_proposal(std::optional<TC> tc) {
    ProposerMessage msg;
    msg.kind = ProposerMessage::Kind::kMake;
    msg.round = round_;
    msg.qc = high_qc_;
    msg.tc = std::move(tc);
    tx_proposer_->send(std::move(msg));
  }

  void cleanup_proposer(const Block& b0, const Block& b1, const Block& block) {
    ProposerMessage msg;
    msg.kind = ProposerMessage::Kind::kCleanup;
    for (const auto* b : {&b0, &b1, &block}) {
      msg.digests.insert(msg.digests.end(), b->payload.begin(),
                         b->payload.end());
    }
    tx_proposer_->send(std::move(msg));
  }

  // -- timeouts / view change (core.rs:195-296) ----------------------------

  void local_timeout_round() {
    LOG_WARN("consensus::core") << "Timeout reached for round " << round_;
    increase_last_voted_round(round_);
    Timeout timeout =
        Timeout::make(high_qc_, round_, name_, signature_service_);
    reset_timer();
    std::vector<Address> addresses;
    for (const auto& [_, addr] : committee_.broadcast_addresses(name_)) {
      addresses.push_back(addr);
    }
    network_.broadcast(addresses, ConsensusMessage::timeout_msg(timeout));
    VerifyResult r = handle_timeout(timeout);
    if (!r.ok()) LOG_WARN("consensus::core") << r.error;
  }

  VerifyResult handle_timeout(const Timeout& timeout) {
    if (timeout.round < round_) return VerifyResult::good();
    // Own signature first, then the embedded high QC through the verified
    // cache: during a view change the 2f+1 timeouts typically all carry
    // the same high QC — one signature batch instead of 2f+1.
    VerifyResult valid = timeout.verify_own(committee_);
    if (!valid.ok()) return valid;
    valid = verify_qc_cached(timeout.high_qc);
    if (!valid.ok()) return valid;

    process_qc(timeout.high_qc);

    auto added = aggregator_.add_timeout(timeout);
    if (!added.error.empty()) return VerifyResult::bad(added.error);
    if (added.tc) {
      // Formed from individually verified timeouts (see the QC analogue in
      // handle_vote).
      cert_insert(added.tc->content_digest());
      advance_round(added.tc->round);
      std::vector<Address> addresses;
      for (const auto& [_, addr] : committee_.broadcast_addresses(name_)) {
        addresses.push_back(addr);
      }
      network_.broadcast(addresses, ConsensusMessage::tc_msg(*added.tc));
      if (name_ == leader_elector_->get_leader(round_)) {
        generate_proposal(std::move(added.tc));
      }
    }
    return VerifyResult::good();
  }

  VerifyResult handle_tc(const TC& tc) {
    if (tc.round < round_) return VerifyResult::good();  // stale: skip
    // The reference skips verification here (core.rs:429-435), which lets
    // any peer — or one corrupted frame — advance our round arbitrarily
    // (observed in round 2 as a node jumping to round 97 during a stalled
    // run). Verify before trusting the round number.
    VerifyResult valid = verify_tc_cached(tc);
    if (!valid.ok()) return valid;
    advance_round(tc.round);
    if (name_ == leader_elector_->get_leader(round_)) {
      generate_proposal(tc);
    }
    return VerifyResult::good();
  }

  // -- votes → QC (core.rs:232-255) ----------------------------------------

  VerifyResult handle_vote(const Vote& vote) {
    if (vote.round < round_) return VerifyResult::good();
    VerifyResult valid = vote.verify(committee_);
    if (!valid.ok()) return valid;

    auto added = aggregator_.add_vote(vote);
    if (!added.error.empty()) return VerifyResult::bad(added.error);
    if (added.qc) {
      // Formed from individually verified votes: no re-verification needed
      // when these exact bytes come back embedded in a proposal.
      cert_insert(added.qc->content_digest());
      process_qc(*added.qc);
      if (name_ == leader_elector_->get_leader(round_)) {
        generate_proposal(std::nullopt);
      }
    }
    return VerifyResult::good();
  }

  // -- block processing (core.rs:339-428) ----------------------------------

  VerifyResult process_block(const Block& block) {
    // Require the two ancestors: b0 <- |qc0; b1| <- |qc1; block|.
    auto ancestors = synchronizer_->get_ancestors(block);
    if (!ancestors) {
      LOG_DEBUG("consensus::core")
          << "Processing of " << block.digest().to_base64()
          << " suspended: missing parent";
      return VerifyResult::good();
    }
    auto& [b0, b1] = *ancestors;

    store_block(block);
    cleanup_proposer(b0, b1, block);

    // Commit rule (core.rs:363-366). 2-chain: b0 commits once its direct
    // descendant b1 is certified in the next round (block.qc certifies b1,
    // so this processing event is the earliest proof). 3-chain (upstream
    // HotStuff; the variant behind the reference's benchmark/data/3-chain
    // results): commit requires THREE consecutive certified rounds
    // g0 <- b0 <- b1, so the candidate is one generation older and lands
    // one round later than 2-chain.
    if (chain_depth_ == 3) {
      if (b0.round + 1 == b1.round) {
        auto g0 = synchronizer_->get_parent_block(b0);
        // nullopt fires a sync request; the commit() catch-up walk of a
        // later block commits g0 once it arrives.
        if (g0 && g0->round + 1 == b0.round) {
          mempool_driver_->cleanup(g0->round);
          VerifyResult r = commit(*g0);
          if (!r.ok()) return r;
        }
      }
    } else if (b0.round + 1 == b1.round) {
      mempool_driver_->cleanup(b0.round);
      VerifyResult r = commit(b0);
      if (!r.ok()) return r;
    }

    // Bad leaders could send blocks from the far future.
    if (block.round != round_) return VerifyResult::good();

    if (auto vote = make_vote(block)) {
      PublicKey next_leader = leader_elector_->get_leader(round_ + 1);
      if (next_leader == name_) {
        return handle_vote(*vote);
      }
      auto address = committee_.address(next_leader);
      if (address) {
        network_.send(*address, ConsensusMessage::vote_msg(*vote));
      }
    }
    return VerifyResult::good();
  }

  // -- certificate-verification cache + async dispatch ---------------------

  // Remembers certificates whose signature batches already verified, so a
  // certificate is verified once per node, not once per message carrying
  // it.  Keys are content digests over the FULL serialized certificate —
  // any byte difference (notably a tampered vote set under an unchanged
  // (hash, round)) misses the cache and re-verifies.  During a view
  // change the 2f+1 timeouts typically embed byte-identical copies of the
  // same high QC (everyone forwards the bytes they received), so this
  // still collapses 2f+1 re-verifications into one — the difference
  // between O(n) and O(n^2) signature work at N=100.
  bool cert_cached(const Digest& d) const {
    return verified_certs_.count(d) != 0;
  }

  void cert_insert(const Digest& d) {
    if (!verified_certs_.insert(d).second) return;
    verified_certs_fifo_.push_back(d);
    if (verified_certs_fifo_.size() > kCertCacheCap) {
      verified_certs_.erase(verified_certs_fifo_.front());
      verified_certs_fifo_.pop_front();
    }
  }

  VerifyResult verify_qc_cached(const QC& qc) {
    if (qc.is_genesis()) return VerifyResult::good();
    Digest d = qc.content_digest();
    if (cert_cached(d)) return VerifyResult::good();
    VerifyResult r = qc.verify(committee_);
    if (r.ok()) cert_insert(d);
    return r;
  }

  VerifyResult verify_tc_cached(const TC& tc) {
    Digest d = tc.content_digest();
    if (cert_cached(d)) return VerifyResult::good();
    VerifyResult r = tc.verify(committee_);
    if (r.ok()) cert_insert(d);
    return r;
  }

  // Attempts to dispatch the proposal's outstanding certificate signature
  // batches to the device asynchronously.  Returns true if dispatched (the
  // proposal is suspended; a kVerdict event resumes it), false if the
  // caller must verify synchronously.  Structural checks and the block's
  // own (cheap, host) signature were already done by handle_proposal.
  //
  // The completion callbacks run on the sidecar reply thread: they push
  // the verdict into the Core's own event channel and nothing else.
  // try_send: if the Core's queue is full the verdict is dropped and the
  // proposal stays suspended until its pending entry expires — the
  // leader's re-proposal or a sync request then re-verifies, identical to
  // dropping any other message under overload.
  bool try_dispatch_verify(const Block& block, bool need_qc, bool need_tc) {
    if (!Signature::async_available()) return false;
    auto ch = rx_event_;
    if (current_scheme() == Scheme::kBls) {
      // QC and TC go as SEPARATE ops: the sidecar pre-compiles the
      // common-digest pairing (QC shape) and the quorum-size multi-digest
      // pairing (TC shape) individually; one concatenated multi-digest
      // batch of 2x quorum would be an unwarmed shape, pushing an honest
      // view-change proposal onto the slow host pairing path.
      TpuVerifier* tpu = TpuVerifier::instance();
      if (!tpu) return false;
      struct Join {
        // graftsync: the two atomics are the synchronization (acq_rel
        // on the decrement publishes all_ok to the last callback); ch
        // and block are written before either callback is registered
        // and only READ afterwards — the thread-start/submit edge is
        // the happens-before.
        std::atomic<int> remaining;      // SHARED_OK(atomic join counter)
        std::atomic<bool> all_ok{true};  // SHARED_OK(atomic)
        ChannelPtr<CoreEvent> ch;  // SHARED_OK(written pre-registration)
        Block block;               // SHARED_OK(written pre-registration)
      };
      auto join = std::make_shared<Join>();
      join->remaining = (need_qc ? 1 : 0) + (need_tc ? 1 : 0);
      join->ch = ch;
      join->block = block;
      auto complete = [join](std::optional<bool> ok) {
        // Transport failure is a definitive reject under BLS (no host
        // pairing exists) — same policy as the synchronous path.
        // Ordering: each callback's relaxed all_ok store is published
        // to the LAST decrementer through the acq_rel RMW chain on
        // `remaining` (release on every decrement, acquire on the one
        // that reads 1), so the final load may stay relaxed.
        if (!ok.value_or(false)) {
          join->all_ok.store(false, std::memory_order_relaxed);
        }
        if (join->remaining.fetch_sub(1, std::memory_order_acq_rel) == 1) {
          CoreEvent e = CoreEvent::verdict_of(
              join->block, join->all_ok.load(std::memory_order_relaxed));
          join->ch->try_send(std::move(e));
        }
      };
      if (need_qc) {
        tpu->bls_verify_votes_async(block.qc.digest(), block.qc.votes,
                                    complete);
      }
      if (need_tc) {
        tpu->bls_verify_multi_async(block.tc->vote_items(), complete);
      }
      return true;
    }
    // Ed25519: one combined multi-digest batch (padded power-of-two
    // buckets; every shape is pre-warmed).
    std::vector<std::tuple<Digest, PublicKey, Signature>> items;
    if (need_qc) {
      auto qi = block.qc.vote_items();
      items.insert(items.end(), qi.begin(), qi.end());
    }
    if (need_tc) {
      auto ti = block.tc->vote_items();
      items.insert(items.end(), ti.begin(), ti.end());
    }
    Block copy = block;
    // graftscope: the block digest rides the verify RPC as the protocol
    // v5 context tag, so the sidecar's admit/queue/pack/dispatch/device/
    // reply spans for this batch join this block's verify segment in the
    // merged trace (the frame is built before this call returns, so the
    // stack digest is safe to pass by pointer).
    Digest ctx = block.digest();
    Signature::verify_batch_multi_async(
        std::move(items),
        [ch, copy](std::optional<bool> ok) mutable {
          CoreEvent e = CoreEvent::verdict_of(std::move(copy), ok);
          ch->try_send(std::move(e));
        },
        &ctx);
    return true;
  }

  // Completion loopback of an async certificate verification.
  VerifyResult handle_verdict(const Block& block,
                              std::optional<bool> verdict) {
    trace_stage("verify_reply", block);
    pending_verify_.erase(block.digest());
    if (!verdict.has_value()) {
      // Transport failure: the sidecar is backed off, so the synchronous
      // path below resolves on the host without re-stalling the Core.
      LOG_WARN("consensus::core")
          << "async verify transport failure; re-verifying on host";
      return handle_proposal(block);
    }
    if (!*verdict) {
      return VerifyResult::bad("invalid certificate signatures in block " +
                               block.digest().to_base64());
    }
    if (!block.qc.is_genesis()) cert_insert(block.qc.content_digest());
    if (block.tc) cert_insert(block.tc->content_digest());
    return proposal_postverify(block);
  }

  // Everything handle_proposal does after the block is fully verified.
  VerifyResult proposal_postverify(const Block& block) {
    process_qc(block.qc);
    if (block.tc) advance_round(block.tc->round);

    // Payload availability; suspends the block if batches are missing.
    if (!mempool_driver_->verify(block)) {
      LOG_DEBUG("consensus::core")
          << "Processing of " << block.digest().to_base64()
          << " suspended: missing payload";
      return VerifyResult::good();
    }
    return process_block(block);
  }

  VerifyResult handle_proposal(const Block& block) {
    trace_stage("proposal", block);
    // Leader check (core.rs:399-406).
    if (block.author != leader_elector_->get_leader(block.round)) {
      return VerifyResult::bad("wrong leader for round " +
                               std::to_string(block.round));
    }
    Digest bd = block.digest();
    auto pending = pending_verify_.find(bd);
    if (pending != pending_verify_.end()) {
      // Fresh: duplicate of an in-flight proposal, drop it.  Stale (the
      // verdict event was lost, e.g. dropped by a full event queue): the
      // re-delivered proposal takes over and re-verifies.
      if (std::chrono::steady_clock::now() < pending->second) {
        return VerifyResult::good();
      }
      pending_verify_.erase(pending);
    }

    // Host-cheap checks first: author, the block's own signature, and the
    // certificates' structural (stake/reuse/quorum) rules.
    if (committee_.stake(block.author) == 0) {
      return VerifyResult::bad("unknown block author: " +
                               block.author.to_base64());
    }
    if (current_scheme() != Scheme::kBls &&
        !block.signature.verify(bd, block.author)) {
      return VerifyResult::bad("invalid block signature");
    }
    bool need_qc =
        !block.qc.is_genesis() && !cert_cached(block.qc.content_digest());
    bool need_tc = block.tc && !cert_cached(block.tc->content_digest());
    if (need_qc) {
      VerifyResult r = block.qc.verify_structure(committee_);
      if (!r.ok()) return r;
    }
    if (need_tc) {
      VerifyResult r = block.tc->verify_structure(committee_);
      if (!r.ok()) return r;
    }

    // Under scheme=bls the block's own signature is a pairing too — it
    // stays on the synchronous path below (one extra sidecar op per block;
    // the QC/TC batches are what scale with committee size).
    if (current_scheme() == Scheme::kBls &&
        !block.signature.verify(bd, block.author)) {
      return VerifyResult::bad("invalid block signature");
    }

    if ((need_qc || need_tc) &&
        try_dispatch_verify(block, need_qc, need_tc)) {
      trace_stage("verify_submit", block);
      // The expiry covers a lost verdict event: transport failures arrive
      // well inside the scheme's sidecar deadline, so anything older is
      // gone for good and the next delivery of the block must re-verify.
      int deadline_ms = current_scheme() == Scheme::kBls
                            ? 2 * TpuVerifier::kBlsRecvTimeoutMs
                            : 2 * TpuVerifier::kRecvTimeoutMs;
      pending_verify_[bd] = std::chrono::steady_clock::now() +
                            std::chrono::milliseconds(deadline_ms);
      LOG_DEBUG("consensus::core")
          << "Processing of " << bd.to_base64()
          << " suspended: certificate verify in flight";
      return VerifyResult::good();
    }

    // Synchronous path (no sidecar / at pipeline cap / nothing to check).
    if (need_qc) {
      VerifyResult r = verify_qc_cached(block.qc);
      if (!r.ok()) return r;
    }
    if (need_tc) {
      VerifyResult r = verify_tc_cached(*block.tc);
      if (!r.ok()) return r;
    }
    return proposal_postverify(block);
  }

  // -- state ---------------------------------------------------------------

  PublicKey name_;
  Committee committee_;
  SignatureService signature_service_;
  Store store_;
  std::shared_ptr<LeaderElector> leader_elector_;
  std::shared_ptr<MempoolDriver> mempool_driver_;
  std::shared_ptr<Synchronizer> synchronizer_;
  uint64_t timeout_delay_;
  uint32_t chain_depth_ = 2;
  bool state_dirty_ = false;
  ChannelPtr<CoreEvent> rx_event_;
  ChannelPtr<ProposerMessage> tx_proposer_;
  ChannelPtr<Block> tx_commit_;

  Round round_ = 1;
  Round last_voted_round_ = 0;
  Round last_committed_round_ = 0;
  QC high_qc_;
  Aggregator aggregator_;
  SimpleSender network_;
  std::chrono::steady_clock::time_point timer_deadline_;

  // Async-verify bookkeeping: block digests with a device verdict in
  // flight (value = expiry, after which a re-delivered copy re-verifies),
  // and the FIFO-bounded set of certificates already verified.
  static constexpr size_t kCertCacheCap = 1024;
  std::map<Digest, std::chrono::steady_clock::time_point> pending_verify_;
  std::set<Digest> verified_certs_;
  std::deque<Digest> verified_certs_fifo_;
};

}  // namespace

std::thread Core::spawn(PublicKey name, Committee committee,
                        SignatureService signature_service, Store store,
                        std::shared_ptr<LeaderElector> leader_elector,
                        std::shared_ptr<MempoolDriver> mempool_driver,
                        std::shared_ptr<Synchronizer> synchronizer,
                        uint64_t timeout_delay, uint32_t chain_depth,
                        ChannelPtr<CoreEvent> rx_event,
                        ChannelPtr<ProposerMessage> tx_proposer,
                        ChannelPtr<Block> tx_commit) {
  return std::thread([=] {
    set_thread_name("core");
    CoreImpl core(name, std::move(committee), std::move(signature_service),
                  std::move(store), std::move(leader_elector),
                  std::move(mempool_driver), std::move(synchronizer),
                  timeout_delay, chain_depth, std::move(rx_event),
                  std::move(tx_proposer), std::move(tx_commit));
    core.run();
  });
}

}  // namespace consensus
}  // namespace hotstuff

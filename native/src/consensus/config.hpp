// Consensus configuration: protocol tunables + committee with stake/address
// book (consensus/src/config.rs:10-85 in the reference).
#pragma once

#include <map>
#include <optional>
#include <vector>

#include "common/json.hpp"
#include "common/log.hpp"
#include "crypto/crypto.hpp"
#include "network/socket.hpp"

namespace hotstuff {
namespace consensus {

using Stake = uint32_t;
using Round = uint64_t;

struct Parameters {
  uint64_t timeout_delay = 5'000;      // ms
  uint64_t sync_retry_delay = 10'000;  // ms
  // Commit-rule depth: 2 = 2-chain HotStuff (the reference's main branch),
  // 3 = 3-chain (the variant behind benchmark/data/3-chain/ in the
  // reference's published results; one extra round of commit latency).
  // graftdag generalizes the commit walk to any k >= 2 (capped at 8 —
  // beyond that the extra latency buys nothing): a block commits once k
  // consecutive certified rounds sit on top of it, so deeper pipelines
  // keep proposing on the newest QC while older rounds finish committing.
  uint32_t chain_depth = 2;
  // graftdag: proposals carry availability certificates instead of
  // relying on best-effort payload dissemination, and the proposer
  // pipelines rounds without blocking on per-proposal broadcast ACKs
  // (votes prove delivery).  Must match the mempool's dag knob.
  bool dag = false;
  // graftview pacemaker hardening.  The view-change timer backs off
  // exponentially on CONSECUTIVE no-progress rounds (reset on any QC
  // advance or commit): delay(k) = min(cap, timeout_delay * (factor_pct /
  // 100)^k), plus seeded per-node jitter of up to jitter_pct% for k >= 1
  // so a storm's re-broadcast waves desynchronize instead of colliding.
  // Defaults preserve today's behavior at depth 1 (the first timeout of a
  // round fires after exactly timeout_delay, no jitter).
  uint64_t timeout_backoff_factor_pct = 200;  // 200 = x2 per depth
  uint64_t timeout_backoff_cap = 60'000;      // ms
  uint64_t timeout_jitter_pct = 10;           // % of the backed-off delay
  // Bounded timeout aggregation: timeouts for rounds further than this
  // ahead of the local round are dropped (with a logged count) instead of
  // allocating aggregation state — the attacker-controlled `round` key
  // must not be able to grow the aggregator map without limit.
  uint64_t timeout_future_horizon = 1'000;    // rounds

  static Parameters from_json(const Json& j) {
    Parameters p;
    if (auto* v = j.find("timeout_delay")) p.timeout_delay = v->as_u64();
    if (auto* v = j.find("sync_retry_delay")) p.sync_retry_delay = v->as_u64();
    if (auto* v = j.find("chain_depth")) {
      p.chain_depth = uint32_t(v->as_u64());
      if (p.chain_depth < 2 || p.chain_depth > 8)
        throw std::runtime_error("chain_depth must be in [2, 8]");
    }
    if (auto* v = j.find("dag")) p.dag = v->as_bool();
    if (auto* v = j.find("timeout_backoff_factor_pct")) {
      p.timeout_backoff_factor_pct = v->as_u64();
      if (p.timeout_backoff_factor_pct < 100)
        throw std::runtime_error(
            "timeout_backoff_factor_pct must be >= 100 (100 = no backoff)");
    }
    if (auto* v = j.find("timeout_backoff_cap")) {
      p.timeout_backoff_cap = v->as_u64();
    }
    if (auto* v = j.find("timeout_jitter_pct")) {
      p.timeout_jitter_pct = v->as_u64();
      if (p.timeout_jitter_pct > 100)
        throw std::runtime_error("timeout_jitter_pct must be <= 100");
    }
    if (auto* v = j.find("timeout_future_horizon")) {
      p.timeout_future_horizon = v->as_u64();
      if (p.timeout_future_horizon == 0)
        throw std::runtime_error("timeout_future_horizon must be >= 1");
    }
    return p;
  }

  void log() const {
    // NOTE: These log entries are used to compute performance
    // (hotstuff_tpu/harness/logs.py config regexes).
    LOG_INFO("consensus::config")
        << "Timeout delay set to " << timeout_delay << " ms";
    LOG_INFO("consensus::config")
        << "Sync retry delay set to " << sync_retry_delay << " ms";
    LOG_INFO("consensus::config")
        << "Chain depth set to " << chain_depth;
    // Optional line: absent in legacy runs, so the frozen log grammar
    // (hotstuff_tpu/harness/logs.py) is unchanged when the knob is off.
    if (dag) {
      LOG_INFO("consensus::config") << "Dag certified proposals enabled";
    }
    LOG_INFO("consensus::config")
        << "Timeout backoff factor set to " << timeout_backoff_factor_pct
        << " pct";
    LOG_INFO("consensus::config")
        << "Timeout backoff cap set to " << timeout_backoff_cap << " ms";
    LOG_INFO("consensus::config")
        << "Timeout jitter set to " << timeout_jitter_pct << " pct";
    LOG_INFO("consensus::config")
        << "Timeout future horizon set to " << timeout_future_horizon
        << " rounds";
  }
};

// The pacemaker's pre-jitter delay schedule at a given no-progress depth
// (depth 0 = the round's first timer arming).  Free function so the
// schedule is unit-testable without spinning a Core thread; the Core adds
// its seeded jitter on top for depth >= 1.
inline uint64_t backoff_delay_ms(const Parameters& p, uint32_t depth) {
  uint64_t cap = p.timeout_backoff_cap > p.timeout_delay
                     ? p.timeout_backoff_cap
                     : p.timeout_delay;
  double delay = double(p.timeout_delay);
  double factor = double(p.timeout_backoff_factor_pct) / 100.0;
  for (uint32_t i = 0; i < depth; i++) {
    delay *= factor;
    if (delay >= double(cap)) return cap;
  }
  uint64_t out = uint64_t(delay);
  return out > cap ? cap : (out < 1 ? 1 : out);
}

struct Authority {
  Stake stake = 1;
  Address address;
  Bytes bls_pubkey;  // optional 96-byte uncompressed G1 (scheme=bls)
};

class Committee {
 public:
  Committee() = default;
  Committee(std::map<PublicKey, Authority> authorities, uint64_t epoch)
      : authorities_(std::move(authorities)), epoch_(epoch) {}

  static Committee from_json(const Json& j);
  Json to_json() const;

  size_t size() const { return authorities_.size(); }

  const std::map<PublicKey, Authority>& authorities() const {
    return authorities_;
  }

  Stake stake(const PublicKey& name) const {
    auto it = authorities_.find(name);
    return it == authorities_.end() ? 0 : it->second.stake;
  }

  Stake total_stake() const {
    Stake total = 0;
    for (const auto& [_, a] : authorities_) total += a.stake;
    return total;
  }

  Stake quorum_threshold() const { return 2 * total_stake() / 3 + 1; }

  std::optional<Address> address(const PublicKey& name) const {
    auto it = authorities_.find(name);
    if (it == authorities_.end()) return std::nullopt;
    return it->second.address;
  }

  std::vector<std::pair<PublicKey, Address>> broadcast_addresses(
      const PublicKey& myself) const {
    std::vector<std::pair<PublicKey, Address>> out;
    for (const auto& [name, a] : authorities_) {
      if (name != myself) out.emplace_back(name, a.address);
    }
    return out;
  }

  // Sorted keys (std::map iteration order) — the leader-election domain.
  std::vector<PublicKey> sorted_keys() const {
    std::vector<PublicKey> keys;
    keys.reserve(authorities_.size());
    for (const auto& [name, _] : authorities_) keys.push_back(name);
    return keys;
  }

 private:
  std::map<PublicKey, Authority> authorities_;
  uint64_t epoch_ = 1;
};

}  // namespace consensus
}  // namespace hotstuff

// MempoolDriver + PayloadWaiter: checks a block's payload batches are in
// storage; missing payloads trigger a mempool Synchronize command and
// suspend the block on notify_read of every missing digest, looping it back
// to the core once complete (consensus/src/mempool.rs:15-170 in the
// reference).
#pragma once

#include <memory>
#include <thread>

#include "common/channel.hpp"
#include "consensus/messages.hpp"
#include "mempool/messages.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace consensus {

struct CoreEvent;

class MempoolDriver {
 public:
  MempoolDriver(Store store,
                ChannelPtr<mempool::ConsensusMempoolMessage> tx_mempool,
                ChannelPtr<CoreEvent> tx_loopback);
  // Closes the waiter channel and joins the payload-waiter thread.
  ~MempoolDriver();
  MempoolDriver(const MempoolDriver&) = delete;
  MempoolDriver& operator=(const MempoolDriver&) = delete;

  // Called from the core thread: true when all payload batches are local.
  bool verify(const Block& block);

  // graftdag: background fetch for a CERT-CARRYING block — the
  // certificates already prove availability, so the core votes without
  // possession and this only starts pulling the missing bytes, targeted
  // at each certificate's signers (they signed for stored bytes).
  // Never suspends the block.
  void prefetch(const Block& block);

  void cleanup(Round round);

 private:
  struct WaiterMessage {
    enum class Kind { kWait, kCleanup, kComplete } kind;
    std::vector<Digest> missing;  // kWait
    Block block;                  // kWait
    Round round = 0;              // kCleanup
    Digest completed;             // kComplete (internal: payload arrived)
  };

  // graftsync: verify()/cleanup() run on the core thread, the waiter
  // lambda on thread_, notify_read completions on the store thread —
  // every member they share synchronizes through the Store/Channel
  // internals, so no mutex lives here (the per-block join counter in
  // the .cpp is the one atomic, acq_rel at its decrement).
  Store store_;  // SHARED_OK(channel-backed handle)
  ChannelPtr<mempool::ConsensusMempoolMessage> tx_mempool_;  // SHARED_OK(Channel)
  ChannelPtr<WaiterMessage> tx_payload_waiter_;  // SHARED_OK(Channel)
  std::thread thread_;  // SHARED_OK(set in ctor, joined in dtor)
};

}  // namespace consensus
}  // namespace hotstuff

// Consensus core: the 2-chain HotStuff replica state machine — proposal
// handling, voting safety rules, QC/TC aggregation, the 2-chain commit rule,
// and timeout/view-change (consensus/src/core.rs:26-468 in the reference).
#pragma once

#include <memory>
#include <thread>

#include "common/channel.hpp"
#include "consensus/aggregator.hpp"
#include "consensus/leader.hpp"
#include "consensus/mempool_driver.hpp"
#include "consensus/messages.hpp"
#include "consensus/synchronizer.hpp"
#include "network/simple_sender.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace consensus {

// Unified input event for the core's select loop (rx_message + rx_loopback
// of the reference, core.rs:438-467).  kVerdict is the completion loopback
// of an ASYNC certificate verification: the Core dispatches a proposal's
// QC/TC signature batch to the device, keeps processing other events, and
// resumes the suspended proposal when the verdict arrives — the same
// suspend/resume shape as a missing-parent sync (core.rs:348-354), applied
// to the verify latency the reference pays synchronously
// (messages.rs:180-198).
struct CoreEvent {
  enum class Kind { kMessage, kLoopback, kVerdict, kTcVerdict };
  Kind kind = Kind::kMessage;
  ConsensusMessage message;  // kMessage
  Block block;               // kLoopback, kVerdict
  // kVerdict: true/false = device verdict on the block's certificates;
  // nullopt = transport failure, re-verify synchronously (host fallback).
  std::optional<bool> verdict;
  // kTcVerdict (graftview): completion loopback of a BATCHED timeout-set
  // verification — the round whose TC candidate set was launched, the
  // batch generation (stale verdicts for a re-armed round are ignored),
  // and the overall verdict (nullopt = transport failure; false = at
  // least one bad signer — the Core ejects per-signature host-side).
  Round tc_round = 0;
  uint64_t tc_gen = 0;

  static CoreEvent loopback(Block b) {
    CoreEvent e;
    e.kind = Kind::kLoopback;
    e.block = std::move(b);
    return e;
  }
  static CoreEvent msg(ConsensusMessage m) {
    CoreEvent e;
    e.kind = Kind::kMessage;
    e.message = std::move(m);
    return e;
  }
  static CoreEvent verdict_of(Block b, std::optional<bool> ok) {
    CoreEvent e;
    e.kind = Kind::kVerdict;
    e.block = std::move(b);
    e.verdict = ok;
    return e;
  }
  static CoreEvent tc_verdict(Round round, uint64_t gen,
                              std::optional<bool> ok) {
    CoreEvent e;
    e.kind = Kind::kTcVerdict;
    e.tc_round = round;
    e.tc_gen = gen;
    e.verdict = ok;
    return e;
  }
};

struct ProposerMessage {
  enum class Kind { kMake, kCleanup };
  Kind kind = Kind::kMake;
  Round round = 0;                // kMake
  QC qc;                          // kMake
  std::optional<TC> tc;           // kMake
  std::vector<Digest> digests;    // kCleanup
};

class Core {
 public:
  // Returns the replica thread; it exits when rx_event is closed.
  // `parameters` carries every consensus tunable (timeout/backoff
  // schedule, chain depth, aggregation bounds) — graftview replaced the
  // old (timeout_delay, chain_depth) argument pair so the pacemaker
  // knobs flow through without widening this signature again.
  static std::thread spawn(PublicKey name, Committee committee,
                           SignatureService signature_service, Store store,
                           std::shared_ptr<LeaderElector> leader_elector,
                           std::shared_ptr<MempoolDriver> mempool_driver,
                           std::shared_ptr<Synchronizer> synchronizer,
                           Parameters parameters,
                           ChannelPtr<CoreEvent> rx_event,
                           ChannelPtr<ProposerMessage> tx_proposer,
                           ChannelPtr<Block> tx_commit);
};

}  // namespace consensus
}  // namespace hotstuff

// Consensus helper: serves SyncRequest messages by reading the block from
// storage and replying with a Propose message
// (consensus/src/helper.rs:15-68 in the reference).
#pragma once

#include <thread>

#include "common/channel.hpp"
#include "consensus/messages.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace consensus {

class Helper {
 public:
  // Returns the actor thread; exits when rx_request is closed and drained.
  static std::thread spawn(Committee committee, Store store,
                    ChannelPtr<std::pair<Digest, PublicKey>> rx_request);
};

}  // namespace consensus
}  // namespace hotstuff

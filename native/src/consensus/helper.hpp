// Consensus helper: serves SyncRequest messages by reading the block from
// storage and replying with a Propose message
// (consensus/src/helper.rs:15-68 in the reference).
#pragma once

#include "common/channel.hpp"
#include "consensus/messages.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace consensus {

class Helper {
 public:
  static void spawn(Committee committee, Store store,
                    ChannelPtr<std::pair<Digest, PublicKey>> rx_request);
};

}  // namespace consensus
}  // namespace hotstuff

#include "consensus/consensus.hpp"

#include "common/log.hpp"
#include "consensus/helper.hpp"
#include "consensus/mempool_driver.hpp"
#include "consensus/synchronizer.hpp"

namespace hotstuff {
namespace consensus {

std::unique_ptr<Consensus> Consensus::spawn(
    PublicKey name, Committee committee, Parameters parameters,
    SignatureService signature_service, Store store, Store batch_store,
    ChannelPtr<mempool::PayloadRef> rx_mempool,
    ChannelPtr<mempool::ConsensusMempoolMessage> tx_mempool,
    ChannelPtr<Block> tx_commit) {
  parameters.log();

  auto c = std::unique_ptr<Consensus>(new Consensus());

  auto tx_core = make_channel<CoreEvent>();
  auto tx_proposer_cmd = make_channel<ProposerMessage>();
  auto tx_helper = make_channel<std::pair<Digest, PublicKey>>();

  // Network ingress: ACK only proposals, route sync requests to the helper
  // (consensus.rs:126-162).
  auto address = committee.address(name);
  if (!address) throw std::runtime_error("our key is not in the committee");
  if (!c->receiver_.spawn(
          *address,
          [tx_core, tx_helper](ConnectionWriter& writer, Bytes msg) {
            // Handlers run on the shared reactor thread: channel pushes
            // must be try_send — a blocking send on a full channel would
            // stall every connection in the process.  Dropping under
            // overload is the async network model; the synchronizer's
            // sync requests and peer re-broadcasts recover.
            try {
              ConsensusMessage m = ConsensusMessage::deserialize(msg);
              if (m.kind == ConsensusMessage::Kind::kSyncRequest) {
                if (!tx_helper->try_send({m.sync_digest, m.sync_from})) {
                  LOG_WARN("consensus::consensus")
                      << "helper overloaded; dropping sync request";
                }
              } else {
                if (m.kind == ConsensusMessage::Kind::kPropose) {
                  writer.send(std::string("Ack"));
                }
                if (!tx_core->try_send(CoreEvent::msg(std::move(m)))) {
                  LOG_WARN("consensus::consensus")
                      << "core overloaded; dropping consensus message";
                }
              }
            } catch (const std::exception& e) {
              // Anything thrown while parsing attacker-controlled bytes
              // (SerdeError, bad_alloc from a hostile length, ...) must not
              // escape this connection thread.
              LOG_WARN("consensus::consensus")
                  << "Serialization failure: " << e.what();
            }
            return true;
          },
          "consensus::receiver")) {
    throw std::runtime_error("failed to bind " + address->str());
  }
  LOG_INFO("consensus::consensus")
      << "Node " << name.to_base64() << " listening to consensus messages on "
      << address->str();

  auto leader_elector = std::make_shared<LeaderElector>(committee);
  auto mempool_driver =
      std::make_shared<MempoolDriver>(batch_store, tx_mempool, tx_core);
  auto synchronizer = std::make_shared<Synchronizer>(
      name, committee, store, tx_core, parameters.sync_retry_delay);

  c->closers_.push_back([tx_core] { tx_core->close(); });
  c->closers_.push_back([tx_proposer_cmd] { tx_proposer_cmd->close(); });
  c->closers_.push_back([tx_helper] { tx_helper->close(); });
  c->closers_.push_back([rx_mempool] { rx_mempool->close(); });
  c->closers_.push_back([tx_commit] { tx_commit->close(); });

  // Core's thread owns the last refs to the synchronizer and mempool driver
  // (their inner threads join in their destructors when Core's lambda state
  // is destroyed at thread exit).
  c->threads_.push_back(Core::spawn(
      name, committee, signature_service, store, leader_elector,
      mempool_driver, synchronizer, parameters, tx_core, tx_proposer_cmd,
      tx_commit));

  c->threads_.push_back(Proposer::spawn(name, committee, signature_service,
                                        parameters.dag, rx_mempool,
                                        tx_proposer_cmd, tx_core,
                                        c->stop_flag_));

  c->threads_.push_back(Helper::spawn(committee, store, tx_helper));

  return c;
}

void Consensus::stop() {
  if (stopped_) return;
  stopped_ = true;
  stop_flag_->store(true, std::memory_order_relaxed);
  for (auto& close : closers_) close();
  receiver_.stop();
  for (auto& t : threads_) {
    if (t.joinable()) t.join();
  }
}

Consensus::~Consensus() { stop(); }

}  // namespace consensus
}  // namespace hotstuff

#include "consensus/messages.hpp"

#include <set>

namespace hotstuff {
namespace consensus {

// ---------------------------------------------------------------------------
// QC
// ---------------------------------------------------------------------------

const QC& QC::genesis() {
  static const QC g{};
  return g;
}

Digest QC::digest() const {
  // hash || round LE, SHA-512/32 (messages.rs:202-208).
  return DigestBuilder().update(hash.data).update_u64_le(round).finalize();
}

namespace {

// Stake/reuse/quorum accounting shared by QC and TC structural checks.
// `label` tags error strings ("QC"/"TC").
//
// Beyond the reference (messages.rs:184-195), when all voting stakes are
// equal this also rejects NON-MINIMAL certificates (more votes than the
// quorum needs): a Byzantine leader can otherwise pad a certificate to all
// n votes, a shape the verify sidecar never pre-compiled, forcing every
// honest verifier onto the slow host path at once — a cheap targeted
// stall.  Honest aggregators seal at exactly the quorum under equal
// stakes, so the guard never fires on honest traffic; with mixed stakes
// minimality isn't well-defined (an aggregator may legitimately overshoot
// depending on arrival order), so the guard deactivates.
template <typename VoteList, typename GetAuthority>
VerifyResult check_vote_stakes(const VoteList& votes, GetAuthority author_of,
                               const Committee& committee,
                               const char* label) {
  Stake weight = 0;
  Stake min_stake = 0;
  bool equal_stakes = true;
  std::set<PublicKey> used;
  for (const auto& v : votes) {
    const PublicKey& name = author_of(v);
    if (used.count(name)) {
      return VerifyResult::bad(std::string("authority reuse in ") + label +
                               ": " + name.to_base64());
    }
    Stake stake = committee.stake(name);
    if (stake == 0) {
      return VerifyResult::bad(std::string("unknown authority in ") + label +
                               ": " + name.to_base64());
    }
    used.insert(name);
    weight += stake;
    if (min_stake == 0) {
      min_stake = stake;
    } else if (stake != min_stake) {
      equal_stakes = false;
    }
  }
  if (weight < committee.quorum_threshold()) {
    return VerifyResult::bad(std::string(label) + " requires a quorum");
  }
  if (equal_stakes && min_stake > 0 &&
      weight - min_stake >= committee.quorum_threshold()) {
    return VerifyResult::bad(std::string(label) +
                             " carries more votes than a quorum");
  }
  return VerifyResult::good();
}

}  // namespace

// VERIFIES(stake-structure)
VerifyResult QC::verify_structure(const Committee& committee) const {
  return check_vote_stakes(
      votes, [](const auto& v) -> const PublicKey& { return v.first; },
      committee, "QC");
}

std::vector<std::tuple<Digest, PublicKey, Signature>> QC::vote_items()
    const {
  Digest d = digest();
  std::vector<std::tuple<Digest, PublicKey, Signature>> items;
  items.reserve(votes.size());
  for (const auto& [pk, sig] : votes) items.emplace_back(d, pk, sig);
  return items;
}

Digest QC::content_digest() const {
  Writer w;
  serialize(&w);
  return DigestBuilder().update(w.out).finalize();
}

// VERIFIES(qc)
VerifyResult QC::verify(const Committee& committee) const {
  VerifyResult r = verify_structure(committee);
  if (!r.ok()) return r;
  // The TPU kernel target: batch-verify the quorum's signatures over the
  // vote digest (crypto/src/lib.rs:210-223 analogue; device dispatch in
  // Signature::verify_batch).
  if (!Signature::verify_batch(digest(), votes)) {
    return VerifyResult::bad("invalid signature in QC");
  }
  return VerifyResult::good();
}

void QC::serialize(Writer* w) const {
  hash.serialize(w);
  w->u64(round);
  w->u64(votes.size());
  for (const auto& [pk, sig] : votes) {
    pk.serialize(w);
    sig.serialize(w);
  }
}

QC QC::deserialize(Reader* r) {
  QC qc;
  qc.hash = Digest::deserialize(r);
  qc.round = r->u64();
  uint64_t n = r->seq_len(96);
  qc.votes.reserve(n);
  for (uint64_t i = 0; i < n; i++) {
    PublicKey pk = PublicKey::deserialize(r);
    Signature sig = Signature::deserialize(r);
    qc.votes.emplace_back(pk, sig);
  }
  return qc;
}

// ---------------------------------------------------------------------------
// TC
// ---------------------------------------------------------------------------

std::vector<Round> TC::high_qc_rounds() const {
  std::vector<Round> rounds;
  rounds.reserve(votes.size());
  for (const auto& [pk, sig, r] : votes) {
    (void)pk;
    (void)sig;
    rounds.push_back(r);
  }
  return rounds;
}

// VERIFIES(stake-structure)
VerifyResult TC::verify_structure(const Committee& committee) const {
  return check_vote_stakes(
      votes,
      [](const auto& v) -> const PublicKey& { return std::get<0>(v); },
      committee, "TC");
}

std::vector<std::tuple<Digest, PublicKey, Signature>> TC::vote_items()
    const {
  // Each timeout vote signed (round, its own high_qc round) — distinct
  // digests per vote (messages.rs:307-313).
  std::vector<std::tuple<Digest, PublicKey, Signature>> items;
  items.reserve(votes.size());
  for (const auto& [author, sig, high_qc_round] : votes) {
    items.emplace_back(Timeout::vote_digest(round, high_qc_round), author,
                       sig);
  }
  return items;
}

Digest TC::content_digest() const {
  Writer w;
  serialize(&w);
  return DigestBuilder().update(w.out).finalize();
}

// VERIFIES(tc)
VerifyResult TC::verify(const Committee& committee) const {
  VerifyResult r = verify_structure(committee);
  if (!r.ok()) return r;
  // The reference verifies timeout votes sequentially (messages.rs:
  // 307-313); here they go through one multi-digest batch (one device
  // launch with the sidecar installed, host loop otherwise).
  if (!Signature::verify_batch_multi(vote_items())) {
    return VerifyResult::bad("invalid signature in TC");
  }
  return VerifyResult::good();
}

void TC::serialize(Writer* w) const {
  w->u64(round);
  w->u64(votes.size());
  for (const auto& [pk, sig, r] : votes) {
    pk.serialize(w);
    sig.serialize(w);
    w->u64(r);
  }
}

TC TC::deserialize(Reader* r) {
  TC tc;
  tc.round = r->u64();
  uint64_t n = r->seq_len(104);
  tc.votes.reserve(n);
  for (uint64_t i = 0; i < n; i++) {
    PublicKey pk = PublicKey::deserialize(r);
    Signature sig = Signature::deserialize(r);
    Round round = r->u64();
    tc.votes.emplace_back(pk, sig, round);
  }
  return tc;
}

// ---------------------------------------------------------------------------
// Block
// ---------------------------------------------------------------------------

const Block& Block::genesis() {
  static const Block g{};
  return g;
}

Digest Block::digest() const {
  // author || round LE || payload digests || qc.hash (messages.rs:78-90).
  DigestBuilder b;
  b.update(author.data).update_u64_le(round);
  for (const auto& d : payload) b.update(d.data);
  b.update(qc.hash.data);
  return b.finalize();
}

// VERIFIES(stake-structure)
VerifyResult Block::check_certs(const Committee& committee) const {
  if (certs.empty()) return VerifyResult::good();
  if (certs.size() != payload.size()) {
    return VerifyResult::bad("certificate list does not match payload");
  }
  for (size_t i = 0; i < certs.size(); i++) {
    if (certs[i].digest != payload[i]) {
      return VerifyResult::bad("certificate digest mismatch at index " +
                               std::to_string(i));
    }
    std::string err = certs[i].check(committee);
    if (!err.empty()) return VerifyResult::bad(std::move(err));
  }
  return VerifyResult::good();
}

// VERIFIES(block)
VerifyResult Block::verify(const Committee& committee) const {
  if (committee.stake(author) == 0) {
    return VerifyResult::bad("unknown block author: " + author.to_base64());
  }
  if (!signature.verify(digest(), author)) {
    return VerifyResult::bad("invalid block signature");
  }
  if (!qc.is_genesis()) {
    VerifyResult r = qc.verify(committee);
    if (!r.ok()) return r;
  }
  if (tc) {
    VerifyResult r = tc->verify(committee);
    if (!r.ok()) return r;
  }
  // graftdag: synchronous fallback for availability certificates (the hot
  // path dispatches their signature batches through the Core instead).
  VerifyResult r = check_certs(committee);
  if (!r.ok()) return r;
  // VERIFIES(batch-certificate)
  for (const auto& cert : certs) {
    if (!Signature::verify_batch(cert.ack_digest(), cert.votes)) {
      return VerifyResult::bad("invalid signature in batch certificate");
    }
  }
  return VerifyResult::good();
}

void Block::serialize(Writer* w) const {
  qc.serialize(w);
  w->u8(tc ? 1 : 0);
  if (tc) tc->serialize(w);
  author.serialize(w);
  w->u64(round);
  w->u64(payload.size());
  for (const auto& d : payload) d.serialize(w);
  w->u64(certs.size());
  for (const auto& c : certs) c.serialize(w);
  signature.serialize(w);
}

Block Block::deserialize(Reader* r) {
  Block b;
  b.qc = QC::deserialize(r);
  if (r->u8()) b.tc = TC::deserialize(r);
  b.author = PublicKey::deserialize(r);
  b.round = r->u64();
  uint64_t n = r->seq_len(32);
  b.payload.reserve(n);
  for (uint64_t i = 0; i < n; i++) b.payload.push_back(Digest::deserialize(r));
  // Min serialized certificate: 32-byte digest + 8-byte vote count.
  uint64_t nc = r->seq_len(40);
  b.certs.reserve(nc);
  for (uint64_t i = 0; i < nc; i++) {
    b.certs.push_back(mempool::BatchCertificate::deserialize(r));
  }
  b.signature = Signature::deserialize(r);
  return b;
}

// ---------------------------------------------------------------------------
// Vote
// ---------------------------------------------------------------------------

Vote Vote::make(const Block& block, const PublicKey& author,
                const SignatureService& service) {
  Vote v;
  v.hash = block.digest();
  v.round = block.round;
  v.author = author;
  v.signature = service.request_signature(v.digest());
  return v;
}

Digest Vote::digest() const {
  return DigestBuilder().update(hash.data).update_u64_le(round).finalize();
}

// VERIFIES(sig)
VerifyResult Vote::verify(const Committee& committee) const {
  if (committee.stake(author) == 0) {
    return VerifyResult::bad("unknown vote author: " + author.to_base64());
  }
  if (!signature.verify(digest(), author)) {
    return VerifyResult::bad("invalid vote signature");
  }
  return VerifyResult::good();
}

void Vote::serialize(Writer* w) const {
  hash.serialize(w);
  w->u64(round);
  author.serialize(w);
  signature.serialize(w);
}

Vote Vote::deserialize(Reader* r) {
  Vote v;
  v.hash = Digest::deserialize(r);
  v.round = r->u64();
  v.author = PublicKey::deserialize(r);
  v.signature = Signature::deserialize(r);
  return v;
}

// ---------------------------------------------------------------------------
// Timeout
// ---------------------------------------------------------------------------

Timeout Timeout::make(QC high_qc, Round round, const PublicKey& author,
                      const SignatureService& service) {
  Timeout t;
  t.high_qc = std::move(high_qc);
  t.round = round;
  t.author = author;
  t.signature = service.request_signature(t.digest());
  return t;
}

Digest Timeout::vote_digest(Round round, Round high_qc_round) {
  // round LE || high_qc.round LE (messages.rs:267-273).
  return DigestBuilder()
      .update_u64_le(round)
      .update_u64_le(high_qc_round)
      .finalize();
}

Digest Timeout::digest() const { return vote_digest(round, high_qc.round); }

// VERIFIES(sig)
VerifyResult Timeout::verify_own(const Committee& committee) const {
  if (committee.stake(author) == 0) {
    return VerifyResult::bad("unknown timeout author: " + author.to_base64());
  }
  if (!signature.verify(digest(), author)) {
    return VerifyResult::bad("invalid timeout signature");
  }
  return VerifyResult::good();
}

// VERIFIES(sig)
VerifyResult Timeout::verify(const Committee& committee) const {
  VerifyResult r = verify_own(committee);
  if (!r.ok()) return r;
  if (!high_qc.is_genesis()) {
    r = high_qc.verify(committee);
    if (!r.ok()) return r;
  }
  return VerifyResult::good();
}

void Timeout::serialize(Writer* w) const {
  high_qc.serialize(w);
  w->u64(round);
  author.serialize(w);
  signature.serialize(w);
}

Timeout Timeout::deserialize(Reader* r) {
  Timeout t;
  t.high_qc = QC::deserialize(r);
  t.round = r->u64();
  t.author = PublicKey::deserialize(r);
  t.signature = Signature::deserialize(r);
  return t;
}

// ---------------------------------------------------------------------------
// ConsensusMessage envelope
// ---------------------------------------------------------------------------

Bytes ConsensusMessage::serialize() const {
  Writer w;
  w.tag(static_cast<uint32_t>(kind));
  switch (kind) {
    case Kind::kPropose: block.serialize(&w); break;
    case Kind::kVote: vote.serialize(&w); break;
    case Kind::kTimeout: timeout.serialize(&w); break;
    case Kind::kTC: tc.serialize(&w); break;
    case Kind::kSyncRequest:
      sync_digest.serialize(&w);
      sync_from.serialize(&w);
      break;
  }
  return std::move(w.out);
}

ConsensusMessage ConsensusMessage::deserialize(const Bytes& data) {
  Reader r(data);
  ConsensusMessage m;
  uint32_t tag = r.tag();
  switch (tag) {
    case 0:
      m.kind = Kind::kPropose;
      m.block = Block::deserialize(&r);
      break;
    case 1:
      m.kind = Kind::kVote;
      m.vote = Vote::deserialize(&r);
      break;
    case 2:
      m.kind = Kind::kTimeout;
      m.timeout = Timeout::deserialize(&r);
      break;
    case 3:
      m.kind = Kind::kTC;
      m.tc = TC::deserialize(&r);
      break;
    case 4:
      m.kind = Kind::kSyncRequest;
      m.sync_digest = Digest::deserialize(&r);
      m.sync_from = PublicKey::deserialize(&r);
      break;
    default:
      throw SerdeError("bad ConsensusMessage tag");
  }
  return m;
}

Bytes ConsensusMessage::propose(const Block& b) {
  ConsensusMessage m;
  m.kind = Kind::kPropose;
  m.block = b;
  return m.serialize();
}

Bytes ConsensusMessage::vote_msg(const Vote& v) {
  ConsensusMessage m;
  m.kind = Kind::kVote;
  m.vote = v;
  return m.serialize();
}

Bytes ConsensusMessage::timeout_msg(const Timeout& t) {
  ConsensusMessage m;
  m.kind = Kind::kTimeout;
  m.timeout = t;
  return m.serialize();
}

Bytes ConsensusMessage::tc_msg(const TC& tc) {
  ConsensusMessage m;
  m.kind = Kind::kTC;
  m.tc = tc;
  return m.serialize();
}

Bytes ConsensusMessage::sync_request(const Digest& digest,
                                     const PublicKey& from) {
  ConsensusMessage m;
  m.kind = Kind::kSyncRequest;
  m.sync_digest = digest;
  m.sync_from = from;
  return m.serialize();
}

// ---------------------------------------------------------------------------
// Committee JSON
// ---------------------------------------------------------------------------

Json Committee::to_json() const {
  Json auths = Json::object();
  for (const auto& [name, a] : authorities_) {
    Json entry = Json::object();
    entry.set("stake", Json(int64_t(a.stake)));
    entry.set("address", Json(a.address.str()));
    if (!a.bls_pubkey.empty()) {
      entry.set("bls_pubkey", Json(base64_encode(a.bls_pubkey)));
    }
    auths.set(name.to_base64(), std::move(entry));
  }
  Json j = Json::object();
  j.set("authorities", std::move(auths));
  j.set("epoch", Json(int64_t(epoch_)));
  return j;
}

Committee Committee::from_json(const Json& j) {
  std::map<PublicKey, Authority> authorities;
  for (const auto& [name_b64, entry] : j.at("authorities").members()) {
    PublicKey name;
    if (!PublicKey::from_base64(name_b64, &name)) {
      throw JsonError("bad public key in consensus committee: " + name_b64);
    }
    Authority a;
    a.stake = static_cast<Stake>(entry.at("stake").as_u64());
    auto addr = Address::parse(entry.at("address").as_string());
    if (!addr) throw JsonError("bad address in consensus committee");
    a.address = *addr;
    if (auto* v = entry.find("bls_pubkey")) {
      if (!base64_decode(v->as_string(), &a.bls_pubkey) ||
          a.bls_pubkey.size() != 96) {
        throw JsonError("bad bls_pubkey in consensus committee");
      }
    }
    authorities.emplace(name, std::move(a));
  }
  uint64_t epoch = j.find("epoch") ? j.at("epoch").as_u64() : 1;
  return Committee(std::move(authorities), epoch);
}

}  // namespace consensus
}  // namespace hotstuff

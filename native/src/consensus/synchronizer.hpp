// Consensus synchronizer: resolves a block's ancestors from storage; on a
// miss it registers a notify_read waiter, sends a SyncRequest to the block
// author, and re-broadcasts stale requests on a 5 s timer; delivered blocks
// loop back into the core (consensus/src/synchronizer.rs:24-150 in the
// reference).
#pragma once

#include <optional>
#include <thread>
#include <utility>

#include "common/channel.hpp"
#include "consensus/messages.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace consensus {

struct CoreEvent;

class Synchronizer {
 public:
  Synchronizer(PublicKey name, Committee committee, Store store,
               ChannelPtr<CoreEvent> tx_loopback, uint64_t sync_retry_delay);
  // Closes the inner channel and joins the waiter thread.
  ~Synchronizer();
  Synchronizer(const Synchronizer&) = delete;
  Synchronizer& operator=(const Synchronizer&) = delete;

  // Called from the core thread. nullopt = missing, sync requested, the
  // block will loop back when its parent is available.
  std::optional<Block> get_parent_block(const Block& block);
  std::optional<std::pair<Block, Block>> get_ancestors(const Block& block);

 private:
  struct SyncCommand {
    enum class Kind { kRequest, kDelivered } kind = Kind::kRequest;
    Block block;  // kRequest: block whose parent is missing;
                  // kDelivered: suspended block whose parent arrived
  };

  Store store_;
  ChannelPtr<SyncCommand> inner_;
  std::thread thread_;
};

}  // namespace consensus
}  // namespace hotstuff

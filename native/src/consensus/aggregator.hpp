// Vote/timeout aggregation into QCs/TCs at 2f+1 stake, with authority-reuse
// rejection and per-round garbage collection
// (consensus/src/aggregator.rs:13-139 in the reference).
#pragma once

#include <map>
#include <memory>
#include <set>

#include "consensus/messages.hpp"

namespace hotstuff {
namespace consensus {

class Aggregator {
 public:
  explicit Aggregator(Committee committee)
      : committee_(std::move(committee)) {}

  // Returns a QC when this vote completes a quorum; error when the
  // authority already voted for this (round, digest).
  struct AddResult {
    std::string error;  // authority reuse
    std::optional<QC> qc;
  };
  AddResult add_vote(const Vote& vote);

  struct AddTimeoutResult {
    std::string error;
    std::optional<TC> tc;
  };
  AddTimeoutResult add_timeout(const Timeout& timeout);

  // Drop aggregation state for rounds < round.
  void cleanup(Round round);

 private:
  struct QCMaker {
    Stake weight = 0;
    std::vector<std::pair<PublicKey, Signature>> votes;
    std::set<PublicKey> used;
  };
  struct TCMaker {
    Stake weight = 0;
    std::vector<std::tuple<PublicKey, Signature, Round>> votes;
    std::set<PublicKey> used;
  };

  Committee committee_;
  std::map<Round, std::map<Digest, QCMaker>> votes_aggregators_;
  std::map<Round, TCMaker> timeouts_aggregators_;
};

}  // namespace consensus
}  // namespace hotstuff

// Vote/timeout aggregation into QCs/TCs at 2f+1 stake, with authority-reuse
// rejection and per-round garbage collection
// (consensus/src/aggregator.rs:13-139 in the reference).
//
// graftview: timeout aggregation is OPTIMISTIC — timeouts are admitted
// after structure/stake checks only (their own signatures UNVERIFIED), and
// once 2f+1 stake accumulates the pending candidate set is handed back to
// the Core for ONE batched signature verification (the sidecar launch that
// replaced the per-sender host verify of handle_timeout).  Signers the
// batch rejects are EJECTED: their entry is removed (the authority slot
// reopens, so a spoofed timeout cannot permanently lock out the genuine
// author), the exact rejected signature bytes are remembered (bounded) so
// a Byzantine re-send is dropped on arrival, and aggregation re-arms with
// the next arrivals — one bad timeout can delay TC formation by a batch
// round-trip, never prevent it.
//
// Threading: owned exclusively by the consensus Core thread (OWNED_BY is
// documentation, not locking — the Core serializes every call).
#pragma once

#include <map>
#include <memory>
#include <set>

#include "consensus/messages.hpp"

namespace hotstuff {
namespace consensus {

class Aggregator {
 public:
  explicit Aggregator(Committee committee)
      : committee_(std::move(committee)) {}

  // One admitted-but-unverified timeout vote: what the Core's batched TC
  // verify launch needs to rebuild the signed digest per candidate
  // (Timeout::vote_digest(round, high_qc_round)).
  struct TimeoutVote {
    PublicKey author;
    Signature signature;
    Round high_qc_round = 0;
  };

  // Returns a QC when this vote completes a quorum; error when the
  // authority already voted for this (round, digest).
  struct AddResult {
    std::string error;  // authority reuse
    std::optional<QC> qc;
  };
  AddResult add_vote(const Vote& vote);

  // add_timeout / resolve_timeouts outcome: at most one of `tc` (a sealed
  // certificate, built from VERIFIED entries only) or `candidates` (2f+1
  // stake is present but some entries are unverified — verify these in one
  // batch, then call resolve_timeouts with the verdicts).  While a batch
  // is in flight no further candidate set is issued for that round.
  struct AddTimeoutResult {
    std::string error;
    std::optional<TC> tc;
    std::vector<TimeoutVote> candidates;
  };
  // `pre_verified` marks a timeout whose own signature the caller already
  // checked (the no-sidecar synchronous path keeps working unchanged).
  AddTimeoutResult add_timeout(const Timeout& timeout,
                               bool pre_verified = false);

  // Batched-verify verdicts for a round's in-flight candidate set:
  // `verified` authors' entries become sealable, `ejected` authors'
  // entries are removed and their signature bytes blacklisted (bounded).
  // Returns a TC when verified stake reaches the quorum, or a fresh
  // candidate set when unverified arrivals (admitted during the flight)
  // still complete one.
  AddTimeoutResult resolve_timeouts(Round round,
                                    const std::vector<PublicKey>& verified,
                                    const std::vector<PublicKey>& ejected);

  // Drop aggregation state for rounds < round.
  void cleanup(Round round);

  // graftdag: drop aggregation state for rounds <= last_committed — a
  // committed round can never need another QC or TC, whatever the local
  // round says.  With pipelined chained rounds (chain_depth > 2) commits
  // land generations behind the proposal front, so this GC is keyed on
  // the COMMIT watermark rather than the round clock: it holds even on
  // paths where the round does not advance (catch-up commit walks), and
  // documents the invariant cleanup() only covers incidentally.  Returns
  // the number of rounds whose state was dropped (telemetry).
  size_t gc_committed(Round last_committed);

  // Total timeout entries ejected by failed batch verdicts (telemetry;
  // the Core logs it with the round that ejected).
  uint64_t ejected_total() const { return ejected_total_; }

 private:
  struct QCMaker {
    Stake weight = 0;
    std::vector<std::pair<PublicKey, Signature>> votes;
    std::set<PublicKey> used;
  };
  // Per-entry verification state rides with the vote: `verified` entries
  // are the only ones a sealed TC may carry.
  struct TimeoutEntry {
    PublicKey author;
    Signature signature;
    Round high_qc_round = 0;
    bool verified = false;
  };
  struct TCMaker {
    Stake weight = 0;           // admitted stake (verified + pending)
    Stake verified_weight = 0;  // batch- or pre-verified stake
    std::vector<TimeoutEntry> entries;  // OWNED_BY(core thread)
    std::set<PublicKey> used;           // OWNED_BY(core thread)
    // Digests of (author || signature) pairs a batch verdict ejected:
    // the same bad bytes re-sent are refused at admission instead of
    // costing another batch round-trip.  Populated only on MIXED batch
    // outcomes (an all-fail batch reads as a verifier outage — see
    // resolve_timeouts) and bounded (kRejectedCap) so a signature-
    // flooding adversary cannot grow it without limit — past the cap
    // new rejects are simply not remembered (they re-eject at the next
    // batch, costing the attacker a round-trip each time).
    std::set<Digest> rejected;          // OWNED_BY(core thread)
    bool batch_inflight = false;
  };

  // Rejected-signature memory per round: 4 slots per authority is enough
  // for honest re-sends while keeping the worst case a small multiple of
  // the committee size.
  static constexpr size_t kRejectedCapPerAuthority = 4;

  static Digest signature_id(const PublicKey& author, const Signature& sig);
  // Shared sealing/candidate logic for add_timeout and resolve_timeouts.
  void maybe_complete(Round round, TCMaker& maker, AddTimeoutResult* out);

  Committee committee_;
  std::map<Round, std::map<Digest, QCMaker>> votes_aggregators_;
  std::map<Round, TCMaker> timeouts_aggregators_;
  uint64_t ejected_total_ = 0;
};

}  // namespace consensus
}  // namespace hotstuff

// Proposer: buffers payload refs (digest + optional availability
// certificate) from the mempool; on Make it builds and signs a block,
// reliably broadcasts it, loops it back to the core, and blocks until
// 2f+1 stake has ACKed the proposal (the reference's control system,
// consensus/src/proposer.rs:19-143).
//
// graftdag: in dag mode a proposal carries the payload's availability
// CERTIFICATES — constant-size proof the batches are retrievable — and
// the blocking per-proposal ACK wait is skipped entirely: the
// ReliableSender keeps retransmitting un-ACKed proposals, and the votes
// the block gathers are the delivery proof that matters.  The proposer
// thread is then free to pipeline round r+1's block while round r's is
// still in flight.
#pragma once

#include <atomic>
#include <memory>
#include <thread>

#include "common/channel.hpp"
#include "consensus/core.hpp"

namespace hotstuff {
namespace consensus {

class Proposer {
 public:
  // Two independent inputs, as in the reference (proposer.rs:125-141):
  // rx_mempool carries the payload-ref flood from the processors and may
  // back-pressure them; rx_message carries the core's Make/Cleanup commands
  // and must never be wedged behind digests (sharing one queue deadlocks
  // the whole committee under load: core blocked on proposer, proposer
  // blocked on peers' ACKs, peers' receivers blocked on their cores).
  // Returns the actor thread; exits when rx_message is closed. `stop`
  // breaks an in-progress 2f+1 ACK wait at teardown.
  static std::thread spawn(PublicKey name, Committee committee,
                           SignatureService signature_service, bool dag,
                           ChannelPtr<mempool::PayloadRef> rx_mempool,
                           ChannelPtr<ProposerMessage> rx_message,
                           ChannelPtr<CoreEvent> tx_loopback,
                           std::shared_ptr<std::atomic<bool>> stop);
};

}  // namespace consensus
}  // namespace hotstuff

// Proposer: buffers payload digests from the mempool; on Make it builds and
// signs a block, reliably broadcasts it, loops it back to the core, and
// blocks until 2f+1 stake has ACKed the proposal (the reference's control
// system, consensus/src/proposer.rs:19-143).
#pragma once

#include "common/channel.hpp"
#include "consensus/core.hpp"

namespace hotstuff {
namespace consensus {

// Unified input: mempool digests + core commands (the reference selects
// over rx_mempool and rx_message, proposer.rs:125-141).
struct ProposerEvent {
  enum class Kind { kDigest, kCommand } kind = Kind::kDigest;
  Digest digest;            // kDigest
  ProposerMessage command;  // kCommand
};

class Proposer {
 public:
  static void spawn(PublicKey name, Committee committee,
                    SignatureService signature_service,
                    ChannelPtr<ProposerEvent> rx_event,
                    ChannelPtr<CoreEvent> tx_loopback);
};

}  // namespace consensus
}  // namespace hotstuff

#include "consensus/mempool_driver.hpp"

#include <atomic>
#include <map>
#include <thread>

#include "common/log.hpp"
#include "consensus/core.hpp"

namespace hotstuff {
namespace consensus {

MempoolDriver::MempoolDriver(
    Store store, ChannelPtr<mempool::ConsensusMempoolMessage> tx_mempool,
    ChannelPtr<CoreEvent> tx_loopback)
    : store_(store),
      tx_mempool_(tx_mempool),
      // Unbounded: kComplete loopbacks come from store-thread callbacks and
      // must neither block nor be dropped (a lost completion wedges the
      // block; the pending map dedups future kWaits).
      tx_payload_waiter_(make_channel<WaiterMessage>(SIZE_MAX)) {
  auto rx = tx_payload_waiter_;
  thread_ = std::thread([store, rx, tx_loopback]() mutable {
    set_thread_name("payload-wait");
    struct Pending {
      Round round;
      Block block;
      std::shared_ptr<std::atomic<int>> remaining;
    };
    std::map<Digest, Pending> pending;

    while (true) {
      auto msg = rx->recv();
      if (!msg) return;
      switch (msg->kind) {
        case WaiterMessage::Kind::kWait: {
          Digest block_digest = msg->block.digest();
          if (pending.count(block_digest)) break;
          Pending p;
          p.round = msg->block.round;
          p.remaining =
              std::make_shared<std::atomic<int>>(int(msg->missing.size()));
          p.block = std::move(msg->block);
          auto remaining = p.remaining;
          pending.emplace(block_digest, std::move(p));
          for (const auto& digest : msg->missing) {
            // notify_read callbacks run on the store thread; the last one
            // loops a kComplete command back into this channel
            // (consensus/src/mempool.rs:110-125 try_join_all analogue).
            store.notify_read(digest.to_bytes())
                .on_ready([rx, remaining, block_digest](const Bytes&) {
                  // acq_rel: the last decrementer must observe every
                  // earlier callback's effects before looping the
                  // kComplete command back (the channel send would
                  // order it anyway; the RMW states the intent).
                  if (remaining->fetch_sub(
                          1, std::memory_order_acq_rel) == 1) {
                    WaiterMessage done;
                    done.kind = WaiterMessage::Kind::kComplete;
                    done.completed = block_digest;
                    rx->send(std::move(done));  // unbounded: never blocks
                  }
                });
          }
          break;
        }
        case WaiterMessage::Kind::kComplete: {
          auto it = pending.find(msg->completed);
          if (it == pending.end()) break;  // cancelled by cleanup
          tx_loopback->send(CoreEvent::loopback(std::move(it->second.block)));
          pending.erase(it);
          break;
        }
        case WaiterMessage::Kind::kCleanup: {
          for (auto it = pending.begin(); it != pending.end();) {
            if (it->second.round <= msg->round) {
              it = pending.erase(it);
            } else {
              ++it;
            }
          }
          break;
        }
      }
    }
  });
}

MempoolDriver::~MempoolDriver() {
  tx_payload_waiter_->close();
  if (thread_.joinable()) thread_.join();
}

bool MempoolDriver::verify(const Block& block) {
  std::vector<Digest> missing;
  for (const auto& digest : block.payload) {
    if (!store_.read(digest.to_bytes())) missing.push_back(digest);
  }
  if (missing.empty()) return true;

  mempool::ConsensusMempoolMessage sync;
  sync.kind = mempool::ConsensusMempoolMessage::Kind::kSynchronize;
  sync.digests = missing;
  sync.target = block.author;
  tx_mempool_->send(std::move(sync));

  WaiterMessage wait;
  wait.kind = WaiterMessage::Kind::kWait;
  wait.missing = std::move(missing);
  wait.block = block;
  tx_payload_waiter_->send(std::move(wait));
  return false;
}

void MempoolDriver::prefetch(const Block& block) {
  // One Synchronize per certified batch, holders = that batch's own cert
  // signers — a signer of batch A need not hold batch B, so requests are
  // not pooled across certificates.  No store read happens here: the
  // batch store's queue is dominated by ~500 KB writes, and a blocking
  // read round trip per cert on the CORE thread wedged consensus for
  // seconds under load.  The mempool synchronizer does the "do we
  // already hold it" check on its own thread and only then requests from
  // the network; its pending map dedups re-sent digests and its retry
  // timer (lucky broadcast) backstops requests that go unanswered.
  for (size_t i = 0; i < block.certs.size(); i++) {
    const auto& cert = block.certs[i];
    mempool::ConsensusMempoolMessage sync;
    sync.kind = mempool::ConsensusMempoolMessage::Kind::kSynchronize;
    sync.digests.push_back(cert.digest);
    sync.target = block.author;
    sync.holders.reserve(cert.votes.size());
    for (const auto& [signer, sig] : cert.votes) {
      (void)sig;
      sync.holders.push_back(signer);
    }
    tx_mempool_->send(std::move(sync));
  }
}

void MempoolDriver::cleanup(Round round) {
  mempool::ConsensusMempoolMessage msg;
  msg.kind = mempool::ConsensusMempoolMessage::Kind::kCleanup;
  msg.round = round;
  tx_mempool_->send(std::move(msg));

  WaiterMessage wait;
  wait.kind = WaiterMessage::Kind::kCleanup;
  wait.round = round;
  tx_payload_waiter_->send(std::move(wait));
}

}  // namespace consensus
}  // namespace hotstuff

#include "consensus/helper.hpp"

#include <thread>

#include "common/log.hpp"
#include "network/simple_sender.hpp"

namespace hotstuff {
namespace consensus {

std::thread Helper::spawn(Committee committee, Store store,
                   ChannelPtr<std::pair<Digest, PublicKey>> rx_request) {
  return std::thread([committee = std::move(committee), store,
               rx_request]() mutable {
    set_thread_name("cons-helper");
    SimpleSender network;
    while (auto req = rx_request->recv()) {
      const auto& [digest, origin] = *req;
      auto address = committee.address(origin);
      if (!address) {
        LOG_WARN("consensus::helper")
            << "Received sync request from unknown authority: "
            << origin.to_base64();
        continue;
      }
      auto bytes = store.read(digest.to_bytes());
      if (bytes) {
        Block block = Block::from_bytes(*bytes);
        network.send(*address, ConsensusMessage::propose(block));
      }
    }
  });
}

}  // namespace consensus
}  // namespace hotstuff

// Round-robin leader election over the sorted public keys
// (consensus/src/leader.rs:7-21 in the reference).
#pragma once

#include "consensus/config.hpp"

namespace hotstuff {
namespace consensus {

class LeaderElector {
 public:
  explicit LeaderElector(const Committee& committee)
      : keys_(committee.sorted_keys()) {}

  PublicKey get_leader(Round round) const {
    return keys_[round % keys_.size()];
  }

 private:
  std::vector<PublicKey> keys_;
};

}  // namespace consensus
}  // namespace hotstuff

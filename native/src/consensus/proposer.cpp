#include "consensus/proposer.hpp"

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <map>
#include <mutex>
#include <optional>
#include <thread>

#include "common/log.hpp"
#include "network/reliable_sender.hpp"

namespace hotstuff {
namespace consensus {

namespace {

using PayloadBuffer =
    std::map<Digest, std::optional<mempool::BatchCertificate>>;

void make_block(const PublicKey& name, const Committee& committee,
                const SignatureService& signature_service, bool dag,
                ReliableSender* network, PayloadBuffer* buffer,
                Round round, QC qc, std::optional<TC> tc,
                Channel<CoreEvent>* tx_loopback,
                const std::atomic<bool>& stop) {
  Block block;
  block.qc = std::move(qc);
  block.tc = std::move(tc);
  block.author = name;
  block.round = round;
  block.payload.reserve(buffer->size());
  block.certs.reserve(buffer->size());
  bool all_certified = true;
  for (auto& [digest, cert] : *buffer) {
    block.payload.push_back(digest);
    if (cert) {
      block.certs.push_back(std::move(*cert));
    } else {
      all_certified = false;
    }
  }
  // A block either certifies its WHOLE payload or none of it (the shape
  // invariant every verifier enforces, Block::check_certs).  A mixed
  // buffer — possible only across a dag-knob flip mid-run — degrades to
  // a legacy payload-sync block rather than an invalid one.
  if (!all_certified) block.certs.clear();
  buffer->clear();
  block.signature = signature_service.request_signature(block.digest());

  if (!block.payload.empty()) {
    LOG_INFO("consensus::proposer") << "Created B" << block.round;
    // NOTE: These log entries are used to compute performance
    // (hotstuff_tpu/harness/logs.py proposal regex).
    for (const Digest& x : block.payload) {
      LOG_INFO("consensus::proposer")
          << "Created B" << block.round << " -> " << x.to_base64();
    }
  }

  // Reliable-broadcast the proposal and loop it back (proposer.rs:85-121).
  auto peers = committee.broadcast_addresses(name);
  std::vector<Address> addresses;
  addresses.reserve(peers.size());
  for (const auto& [_, addr] : peers) addresses.push_back(addr);
  Bytes message = ConsensusMessage::propose(block);
  auto handlers = network->broadcast(addresses, message);

  tx_loopback->send(CoreEvent::loopback(block));

  // graftdag: the proposal's payload is a list of certified digests —
  // every batch already has 2f+1 signed availability — so there is
  // nothing the per-proposal ACK wait still guarantees.  Dropping the
  // handlers releases the wait (the ReliableSender retransmits un-ACKed
  // proposals regardless), and the proposer can pipeline the next
  // round's block immediately instead of serializing rounds behind the
  // slowest ACK quorum — the leader-bottleneck fix this mode is for.
  if (dag) return;

  // Legacy: wait for 2f+1 cumulative stake of ACKs — backpressure so a
  // leader cannot outrun the committee's ability to RECEIVE payloads it
  // will need bytes for.
  auto m = std::make_shared<std::mutex>();
  auto cv = std::make_shared<std::condition_variable>();
  auto total = std::make_shared<Stake>(committee.stake(name));
  for (size_t i = 0; i < peers.size(); i++) {
    Stake stake = committee.stake(peers[i].first);
    handlers[i].on_ready([m, cv, total, stake](const Bytes& reply) {
      // Empty bytes = cancelled send (teardown/full backlog), not an ACK.
      if (reply.empty()) return;
      std::lock_guard<std::mutex> lk(*m);
      *total += stake;
      cv->notify_one();
    });
  }
  Stake quorum = committee.quorum_threshold();
  std::unique_lock<std::mutex> lk(*m);
  // Bounded waits so teardown (stop set, peers gone) can't wedge the
  // proposer inside its backpressure wait; live ACKs wake us immediately.
  while (*total < quorum && !stop.load(std::memory_order_relaxed)) {
    cv->wait_for(lk, std::chrono::milliseconds(50));
  }
}

}  // namespace

std::thread Proposer::spawn(PublicKey name, Committee committee,
                            SignatureService signature_service, bool dag,
                            ChannelPtr<mempool::PayloadRef> rx_mempool,
                            ChannelPtr<ProposerMessage> rx_message,
                            ChannelPtr<CoreEvent> tx_loopback,
                            std::shared_ptr<std::atomic<bool>> stop) {
  return std::thread([name, committee = std::move(committee),
                      signature_service = std::move(signature_service), dag,
                      rx_mempool, rx_message, tx_loopback,
                      stop = std::move(stop)]() mutable {
    set_thread_name("proposer");
    ReliableSender network(stop);
    PayloadBuffer buffer;
    auto absorb = [&buffer](mempool::PayloadRef&& ref) {
      buffer.emplace(ref.digest, std::move(ref.cert));
    };
    while (true) {
      // Select: block on the command channel, opportunistically draining
      // the payload-ref flood each iteration; refs are also drained right
      // before a command so Make sees the freshest payload set.  The poll
      // interval only bounds how long refs sit in the channel while NO
      // command arrives (they are consumed exclusively by Make) — at 1 ms
      // it cost 1000 wakeups/s per node, ~25% of a core across a
      // 100-validator single-host committee; 100 ms is behaviorally
      // identical and invisible in the profile.
      ProposerMessage cmd;
      auto status = rx_message->recv_until(
          &cmd, std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(100));
      mempool::PayloadRef ref;
      while (rx_mempool->try_recv(&ref)) absorb(std::move(ref));
      if (status == RecvStatus::kClosed) return;
      if (status == RecvStatus::kTimeout) continue;
      if (cmd.kind == ProposerMessage::Kind::kMake) {
        // Idle-race throttle: with no payload ready, wait for the mempool
        // instead of burning a full proposal round on an empty block.
        // Without this, an idle committee races rounds at pure sig-op
        // speed and starves the rest of the node for CPU (the reference
        // races too, but its geo-replicated RTT hides it; on a saturated
        // single host, profiled empty-round racing at a 100-validator
        // committee burned 68% of the core on consensus messaging alone).
        // Any payload ref ends the wait immediately, so a loaded
        // committee never pays it; 400 ms caps empty rounds at ~2.5/s
        // and keeps a 2.5x margin under the smallest timeout (>= 1 s) a
        // benchmark configures — do not raise it toward the timeout
        // floor.
        if (buffer.empty()) {
          mempool::PayloadRef first;
          if (rx_mempool->recv_until(
                  &first, std::chrono::steady_clock::now() +
                              std::chrono::milliseconds(400)) ==
              RecvStatus::kOk) {
            absorb(std::move(first));
            mempool::PayloadRef more;
            while (rx_mempool->try_recv(&more)) absorb(std::move(more));
          }
        }
        make_block(name, committee, signature_service, dag, &network,
                   &buffer, cmd.round, std::move(cmd.qc), std::move(cmd.tc),
                   tx_loopback.get(), *stop);
      } else {
        for (const Digest& d : cmd.digests) buffer.erase(d);
      }
    }
  });
}

}  // namespace consensus
}  // namespace hotstuff

#include "consensus/proposer.hpp"

#include <condition_variable>
#include <mutex>
#include <set>
#include <thread>

#include "common/log.hpp"
#include "network/reliable_sender.hpp"

namespace hotstuff {
namespace consensus {

namespace {

void make_block(const PublicKey& name, const Committee& committee,
                const SignatureService& signature_service,
                ReliableSender* network, std::set<Digest>* buffer,
                Round round, QC qc, std::optional<TC> tc,
                Channel<CoreEvent>* tx_loopback) {
  Block block;
  block.qc = std::move(qc);
  block.tc = std::move(tc);
  block.author = name;
  block.round = round;
  block.payload.assign(buffer->begin(), buffer->end());
  buffer->clear();
  block.signature = signature_service.request_signature(block.digest());

  if (!block.payload.empty()) {
    LOG_INFO("consensus::proposer") << "Created B" << block.round;
    // NOTE: These log entries are used to compute performance
    // (hotstuff_tpu/harness/logs.py proposal regex).
    for (const Digest& x : block.payload) {
      LOG_INFO("consensus::proposer")
          << "Created B" << block.round << " -> " << x.to_base64();
    }
  }

  // Reliable-broadcast the proposal, loop it back, then wait for 2f+1
  // cumulative stake of ACKs (proposer.rs:85-121).
  auto peers = committee.broadcast_addresses(name);
  std::vector<Address> addresses;
  addresses.reserve(peers.size());
  for (const auto& [_, addr] : peers) addresses.push_back(addr);
  Bytes message = ConsensusMessage::propose(block);
  auto handlers = network->broadcast(addresses, message);

  tx_loopback->send(CoreEvent::loopback(block));

  auto m = std::make_shared<std::mutex>();
  auto cv = std::make_shared<std::condition_variable>();
  auto total = std::make_shared<Stake>(committee.stake(name));
  for (size_t i = 0; i < peers.size(); i++) {
    Stake stake = committee.stake(peers[i].first);
    handlers[i].on_ready([m, cv, total, stake](const Bytes&) {
      std::lock_guard<std::mutex> lk(*m);
      *total += stake;
      cv->notify_one();
    });
  }
  Stake quorum = committee.quorum_threshold();
  std::unique_lock<std::mutex> lk(*m);
  cv->wait(lk, [&] { return *total >= quorum; });
}

}  // namespace

void Proposer::spawn(PublicKey name, Committee committee,
                     SignatureService signature_service,
                     ChannelPtr<ProposerEvent> rx_event,
                     ChannelPtr<CoreEvent> tx_loopback) {
  std::thread([name, committee = std::move(committee),
               signature_service = std::move(signature_service), rx_event,
               tx_loopback]() mutable {
    ReliableSender network;
    std::set<Digest> buffer;
    while (auto event = rx_event->recv()) {
      switch (event->kind) {
        case ProposerEvent::Kind::kDigest:
          buffer.insert(event->digest);
          break;
        case ProposerEvent::Kind::kCommand:
          if (event->command.kind == ProposerMessage::Kind::kMake) {
            make_block(name, committee, signature_service, &network, &buffer,
                       event->command.round, std::move(event->command.qc),
                       std::move(event->command.tc), tx_loopback.get());
          } else {
            for (const Digest& d : event->command.digests) buffer.erase(d);
          }
          break;
      }
    }
  }).detach();
}

}  // namespace consensus
}  // namespace hotstuff

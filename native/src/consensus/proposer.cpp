#include "consensus/proposer.hpp"

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <mutex>
#include <set>
#include <thread>

#include "common/log.hpp"
#include "network/reliable_sender.hpp"

namespace hotstuff {
namespace consensus {

namespace {

void make_block(const PublicKey& name, const Committee& committee,
                const SignatureService& signature_service,
                ReliableSender* network, std::set<Digest>* buffer,
                Round round, QC qc, std::optional<TC> tc,
                Channel<CoreEvent>* tx_loopback,
                const std::atomic<bool>& stop) {
  Block block;
  block.qc = std::move(qc);
  block.tc = std::move(tc);
  block.author = name;
  block.round = round;
  block.payload.assign(buffer->begin(), buffer->end());
  buffer->clear();
  block.signature = signature_service.request_signature(block.digest());

  if (!block.payload.empty()) {
    LOG_INFO("consensus::proposer") << "Created B" << block.round;
    // NOTE: These log entries are used to compute performance
    // (hotstuff_tpu/harness/logs.py proposal regex).
    for (const Digest& x : block.payload) {
      LOG_INFO("consensus::proposer")
          << "Created B" << block.round << " -> " << x.to_base64();
    }
  }

  // Reliable-broadcast the proposal, loop it back, then wait for 2f+1
  // cumulative stake of ACKs (proposer.rs:85-121).
  auto peers = committee.broadcast_addresses(name);
  std::vector<Address> addresses;
  addresses.reserve(peers.size());
  for (const auto& [_, addr] : peers) addresses.push_back(addr);
  Bytes message = ConsensusMessage::propose(block);
  auto handlers = network->broadcast(addresses, message);

  tx_loopback->send(CoreEvent::loopback(block));

  auto m = std::make_shared<std::mutex>();
  auto cv = std::make_shared<std::condition_variable>();
  auto total = std::make_shared<Stake>(committee.stake(name));
  for (size_t i = 0; i < peers.size(); i++) {
    Stake stake = committee.stake(peers[i].first);
    handlers[i].on_ready([m, cv, total, stake](const Bytes& reply) {
      // Empty bytes = cancelled send (teardown/full backlog), not an ACK.
      if (reply.empty()) return;
      std::lock_guard<std::mutex> lk(*m);
      *total += stake;
      cv->notify_one();
    });
  }
  Stake quorum = committee.quorum_threshold();
  std::unique_lock<std::mutex> lk(*m);
  // Bounded waits so teardown (stop set, peers gone) can't wedge the
  // proposer inside its backpressure wait; live ACKs wake us immediately.
  while (*total < quorum && !stop.load(std::memory_order_relaxed)) {
    cv->wait_for(lk, std::chrono::milliseconds(50));
  }
}

}  // namespace

std::thread Proposer::spawn(PublicKey name, Committee committee,
                            SignatureService signature_service,
                            ChannelPtr<Digest> rx_mempool,
                            ChannelPtr<ProposerMessage> rx_message,
                            ChannelPtr<CoreEvent> tx_loopback,
                            std::shared_ptr<std::atomic<bool>> stop) {
  return std::thread([name, committee = std::move(committee),
                      signature_service = std::move(signature_service),
                      rx_mempool, rx_message, tx_loopback,
                      stop = std::move(stop)]() mutable {
    set_thread_name("proposer");
    ReliableSender network(stop);
    std::set<Digest> buffer;
    while (true) {
      // Select: block on the command channel, opportunistically draining
      // the digest flood each iteration; digests are also drained right
      // before a command so Make sees the freshest payload set.  The poll
      // interval only bounds how long digests sit in the channel while NO
      // command arrives (they are consumed exclusively by Make) — at 1 ms
      // it cost 1000 wakeups/s per node, ~25% of a core across a
      // 100-validator single-host committee; 100 ms is behaviorally
      // identical and invisible in the profile.
      ProposerMessage cmd;
      auto status = rx_message->recv_until(
          &cmd, std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(100));
      Digest digest;
      while (rx_mempool->try_recv(&digest)) buffer.insert(digest);
      if (status == RecvStatus::kClosed) return;
      if (status == RecvStatus::kTimeout) continue;
      if (cmd.kind == ProposerMessage::Kind::kMake) {
        // Idle-race throttle: with no payload ready, wait for the mempool
        // instead of burning a full proposal round on an empty block.
        // Without this, an idle committee races rounds at pure sig-op
        // speed and starves the rest of the node for CPU (the reference
        // races too, but its geo-replicated RTT hides it; on a saturated
        // single host, profiled empty-round racing at a 100-validator
        // committee burned 68% of the core on consensus messaging alone).
        // Any digest ends the wait immediately, so a loaded committee
        // never pays it; 400 ms caps empty rounds at ~2.5/s and keeps a
        // 2.5x margin under the smallest timeout (>= 1 s) a benchmark
        // configures — do not raise it toward the timeout floor.
        if (buffer.empty()) {
          Digest digest;
          if (rx_mempool->recv_until(
                  &digest, std::chrono::steady_clock::now() +
                               std::chrono::milliseconds(400)) ==
              RecvStatus::kOk) {
            buffer.insert(digest);
            Digest more;
            while (rx_mempool->try_recv(&more)) buffer.insert(more);
          }
        }
        make_block(name, committee, signature_service, &network, &buffer,
                   cmd.round, std::move(cmd.qc), std::move(cmd.tc),
                   tx_loopback.get(), *stop);
      } else {
        for (const Digest& d : cmd.digests) buffer.erase(d);
      }
    }
  });
}

}  // namespace consensus
}  // namespace hotstuff

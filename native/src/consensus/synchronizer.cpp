#include "consensus/synchronizer.hpp"

#include <chrono>
#include <map>
#include <set>
#include <thread>

#include "common/log.hpp"
#include "consensus/core.hpp"
#include "network/simple_sender.hpp"

namespace hotstuff {
namespace consensus {

namespace {
constexpr auto kTimerAccuracy = std::chrono::milliseconds(5000);

uint64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}
}  // namespace

Synchronizer::Synchronizer(PublicKey name, Committee committee, Store store,
                           ChannelPtr<CoreEvent> tx_loopback,
                           uint64_t sync_retry_delay)
    : store_(store),
      // Unbounded: store-thread completion callbacks must never block, and a
      // dropped kDelivered would wedge its block forever (the pending-set
      // dedup prevents re-registration). Size is bounded in practice by the
      // number of distinct suspended blocks.
      inner_(make_channel<SyncCommand>(SIZE_MAX)) {
  auto inner = inner_;
  thread_ = std::thread([name, committee = std::move(committee), store,
                         tx_loopback, sync_retry_delay, inner]() mutable {
    set_thread_name("cons-sync");
    SimpleSender network;
    std::set<Digest> pending;              // block digests being resolved
    std::map<Digest, uint64_t> requests;   // parent digest -> request ts
    auto deadline = std::chrono::steady_clock::now() + kTimerAccuracy;

    while (true) {
      SyncCommand cmd;
      auto status = inner->recv_until(&cmd, deadline);
      if (status == RecvStatus::kClosed) return;
      if (status == RecvStatus::kTimeout) {
        // 'Perfect point-to-point link': rebroadcast stale requests to all
        // (synchronizer.rs:84-105).
        uint64_t now = now_ms();
        for (const auto& [digest, ts] : requests) {
          if (ts + sync_retry_delay < now) {
            LOG_DEBUG("consensus::synchronizer")
                << "Requesting sync for block " << digest.to_base64()
                << " (retry)";
            std::vector<Address> addresses;
            for (const auto& [_, addr] : committee.broadcast_addresses(name)) {
              addresses.push_back(addr);
            }
            network.broadcast(addresses,
                              ConsensusMessage::sync_request(digest, name));
          }
        }
        deadline = std::chrono::steady_clock::now() + kTimerAccuracy;
        continue;
      }

      if (cmd.kind == SyncCommand::Kind::kDelivered) {
        pending.erase(cmd.block.digest());
        requests.erase(cmd.block.parent());
        tx_loopback->send(CoreEvent::loopback(std::move(cmd.block)));
        continue;
      }

      const Block& block = cmd.block;
      if (!pending.insert(block.digest()).second) continue;
      Digest parent = block.parent();
      // Waiter: when the parent appears in storage, the store-thread
      // callback loops the suspended block back through this channel
      // (synchronizer.rs:110-118 analogue).
      store.notify_read(parent.to_bytes())
          .on_ready([inner, block](const Bytes&) {
            SyncCommand done;
            done.kind = SyncCommand::Kind::kDelivered;
            done.block = block;
            inner->send(std::move(done));  // unbounded: never blocks
          });
      if (!requests.count(parent)) {
        LOG_DEBUG("consensus::synchronizer")
            << "Requesting sync for block " << parent.to_base64();
        requests[parent] = now_ms();
        auto address = committee.address(block.author);
        if (address) {
          network.send(*address,
                       ConsensusMessage::sync_request(parent, name));
        }
      }
    }
  });
}

Synchronizer::~Synchronizer() {
  inner_->close();
  if (thread_.joinable()) thread_.join();
}

std::optional<Block> Synchronizer::get_parent_block(const Block& block) {
  if (block.qc.is_genesis()) return Block::genesis();
  auto bytes = store_.read(block.parent().to_bytes());
  if (bytes) return Block::from_bytes(*bytes);
  SyncCommand cmd;
  cmd.block = block;
  inner_->send(std::move(cmd));
  return std::nullopt;
}

std::optional<std::pair<Block, Block>> Synchronizer::get_ancestors(
    const Block& block) {
  auto b1 = get_parent_block(block);
  if (!b1) return std::nullopt;
  auto b0 = get_parent_block(*b1);
  if (!b0) {
    // Invariant from the reference (synchronizer.rs:136-149): delivered
    // blocks have all ancestors; a miss here means the store lost data.
    LOG_ERROR("consensus::synchronizer")
        << "missing grandparent of delivered block";
    return std::nullopt;
  }
  return std::make_pair(std::move(*b0), std::move(*b1));
}

}  // namespace consensus
}  // namespace hotstuff

#include "consensus/aggregator.hpp"

#include <algorithm>

namespace hotstuff {
namespace consensus {

Aggregator::AddResult Aggregator::add_vote(const Vote& vote) {
  QCMaker& maker = votes_aggregators_[vote.round][vote.digest()];
  AddResult result;
  if (!maker.used.insert(vote.author).second) {
    result.error = "authority reuse: " + vote.author.to_base64();
    return result;
  }
  maker.votes.emplace_back(vote.author, vote.signature);
  maker.weight += committee_.stake(vote.author);
  if (maker.weight >= committee_.quorum_threshold()) {
    maker.weight = 0;  // ensures the QC is only made once
    QC qc;
    qc.hash = vote.hash;
    qc.round = vote.round;
    qc.votes = maker.votes;
    result.qc = std::move(qc);
  }
  return result;
}

Digest Aggregator::signature_id(const PublicKey& author,
                                const Signature& sig) {
  return DigestBuilder().update(author.data).update(sig.data).finalize();
}

Aggregator::AddTimeoutResult Aggregator::add_timeout(const Timeout& timeout,
                                                     bool pre_verified) {
  AddTimeoutResult result;
  // Stake check at admission: with verification deferred to the batch,
  // this is what bounds a round's aggregation state to the committee —
  // fabricated authorities must not be able to grow `used`/`entries`.
  Stake stake = committee_.stake(timeout.author);
  if (stake == 0) {
    result.error = "unknown timeout author: " + timeout.author.to_base64();
    return result;
  }
  TCMaker& maker = timeouts_aggregators_[timeout.round];
  if (maker.rejected.count(signature_id(timeout.author, timeout.signature))) {
    result.error = "previously ejected timeout signature from " +
                   timeout.author.to_base64();
    return result;
  }
  if (!maker.used.insert(timeout.author).second) {
    result.error = "authority reuse: " + timeout.author.to_base64();
    return result;
  }
  maker.entries.push_back({timeout.author, timeout.signature,
                           timeout.high_qc.round, pre_verified});
  maker.weight += stake;
  if (pre_verified) maker.verified_weight += stake;
  maybe_complete(timeout.round, maker, &result);
  return result;
}

Aggregator::AddTimeoutResult Aggregator::resolve_timeouts(
    Round round, const std::vector<PublicKey>& verified,
    const std::vector<PublicKey>& ejected) {
  AddTimeoutResult result;
  auto it = timeouts_aggregators_.find(round);
  if (it == timeouts_aggregators_.end()) return result;  // round moved on
  TCMaker& maker = it->second;
  maker.batch_inflight = false;
  for (const PublicKey& name : verified) {
    for (TimeoutEntry& e : maker.entries) {
      if (e.author == name && !e.verified) {
        e.verified = true;
        maker.verified_weight += committee_.stake(name);
      }
    }
  }
  size_t rejected_cap =
      kRejectedCapPerAuthority * std::max<size_t>(1, committee_.size());
  // Blacklist rejected bytes only on a MIXED outcome: at least one
  // candidate verifying proves the verifier itself worked, so the
  // failures are genuinely bad signatures.  An all-fail batch is more
  // consistent with a verifier outage (scheme=bls with the sidecar
  // down has no host pairing: every honest signature reads false) —
  // ejecting drops the quorum either way, but remembering the bytes
  // would refuse the DETERMINISTIC honest re-broadcasts forever and
  // wedge the round past the outage.
  bool blacklist = !verified.empty();
  for (const PublicKey& name : ejected) {
    auto entry = std::find_if(
        maker.entries.begin(), maker.entries.end(),
        [&](const TimeoutEntry& e) { return e.author == name; });
    if (entry == maker.entries.end()) continue;
    if (blacklist && maker.rejected.size() < rejected_cap) {
      maker.rejected.insert(signature_id(entry->author, entry->signature));
    }
    maker.weight -= committee_.stake(name);
    // Reopen the authority slot: the bad bytes may be a THIRD party's
    // spoof, and the genuine author's honest timeout must still count.
    maker.used.erase(name);
    maker.entries.erase(entry);
    ejected_total_++;
  }
  maybe_complete(round, maker, &result);
  return result;
}

void Aggregator::maybe_complete(Round round, TCMaker& maker,
                                AddTimeoutResult* out) {
  if (maker.batch_inflight) return;  // one verdict at a time per round
  Stake quorum = committee_.quorum_threshold();
  if (maker.verified_weight >= quorum) {
    // Seal from verified entries only, in admission order, stopping at
    // the quorum: under equal stakes this emits the MINIMAL certificate
    // the structural over-quorum guard (messages.cpp) demands.
    TC tc;
    tc.round = round;
    Stake weight = 0;
    for (const TimeoutEntry& e : maker.entries) {
      if (!e.verified) continue;
      tc.votes.emplace_back(e.author, e.signature, e.high_qc_round);
      weight += committee_.stake(e.author);
      if (weight >= quorum) break;
    }
    maker.verified_weight = 0;  // ensures the TC is only made once
    maker.weight = 0;
    out->tc = std::move(tc);
    return;
  }
  if (maker.weight >= quorum) {
    for (const TimeoutEntry& e : maker.entries) {
      if (e.verified) continue;
      out->candidates.push_back({e.author, e.signature, e.high_qc_round});
    }
    if (!out->candidates.empty()) maker.batch_inflight = true;
  }
}

void Aggregator::cleanup(Round round) {
  votes_aggregators_.erase(votes_aggregators_.begin(),
                           votes_aggregators_.lower_bound(round));
  timeouts_aggregators_.erase(timeouts_aggregators_.begin(),
                              timeouts_aggregators_.lower_bound(round));
}

size_t Aggregator::gc_committed(Round last_committed) {
  // upper_bound: state AT the committed round is dead too (its QC/TC, if
  // any, already exists — that is what committed it or its descendant).
  auto ve = votes_aggregators_.upper_bound(last_committed);
  auto te = timeouts_aggregators_.upper_bound(last_committed);
  size_t dropped = size_t(std::distance(votes_aggregators_.begin(), ve)) +
                   size_t(std::distance(timeouts_aggregators_.begin(), te));
  votes_aggregators_.erase(votes_aggregators_.begin(), ve);
  timeouts_aggregators_.erase(timeouts_aggregators_.begin(), te);
  return dropped;
}

}  // namespace consensus
}  // namespace hotstuff

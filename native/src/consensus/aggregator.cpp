#include "consensus/aggregator.hpp"

namespace hotstuff {
namespace consensus {

Aggregator::AddResult Aggregator::add_vote(const Vote& vote) {
  QCMaker& maker = votes_aggregators_[vote.round][vote.digest()];
  AddResult result;
  if (!maker.used.insert(vote.author).second) {
    result.error = "authority reuse: " + vote.author.to_base64();
    return result;
  }
  maker.votes.emplace_back(vote.author, vote.signature);
  maker.weight += committee_.stake(vote.author);
  if (maker.weight >= committee_.quorum_threshold()) {
    maker.weight = 0;  // ensures the QC is only made once
    QC qc;
    qc.hash = vote.hash;
    qc.round = vote.round;
    qc.votes = maker.votes;
    result.qc = std::move(qc);
  }
  return result;
}

Aggregator::AddTimeoutResult Aggregator::add_timeout(const Timeout& timeout) {
  TCMaker& maker = timeouts_aggregators_[timeout.round];
  AddTimeoutResult result;
  if (!maker.used.insert(timeout.author).second) {
    result.error = "authority reuse: " + timeout.author.to_base64();
    return result;
  }
  maker.votes.emplace_back(timeout.author, timeout.signature,
                           timeout.high_qc.round);
  maker.weight += committee_.stake(timeout.author);
  if (maker.weight >= committee_.quorum_threshold()) {
    maker.weight = 0;  // ensures the TC is only made once
    TC tc;
    tc.round = timeout.round;
    tc.votes = maker.votes;
    result.tc = std::move(tc);
  }
  return result;
}

void Aggregator::cleanup(Round round) {
  votes_aggregators_.erase(votes_aggregators_.begin(),
                           votes_aggregators_.lower_bound(round));
  timeouts_aggregators_.erase(timeouts_aggregators_.begin(),
                              timeouts_aggregators_.lower_bound(round));
}

}  // namespace consensus
}  // namespace hotstuff

// Consensus message types: Block, Vote, QC, Timeout, TC and the network
// envelope ConsensusMessage (consensus/src/messages.rs:16-326 and
// consensus/src/consensus.rs:32-39 in the reference). QC verification is
// the TPU hot path: it stake-checks the vote set then calls
// Signature::verify_batch, which dispatches to the verify sidecar.
#pragma once

#include <optional>
#include <string>
#include <vector>

#include "common/serde.hpp"
#include "consensus/config.hpp"
#include "crypto/crypto.hpp"
#include "mempool/messages.hpp"

namespace hotstuff {
namespace consensus {

// Verification outcome; `ok()` false carries a reason (the reference's
// ConsensusError variants, consensus/src/error.rs:22-65).
struct VerifyResult {
  std::string error;  // empty = ok
  bool ok() const { return error.empty(); }
  static VerifyResult good() { return {}; }
  static VerifyResult bad(std::string why) { return {std::move(why)}; }
};

struct QC {
  Digest hash;  // digest of the certified block
  Round round = 0;
  std::vector<std::pair<PublicKey, Signature>> votes;

  static const QC& genesis();
  bool is_genesis() const { return *this == genesis(); }

  // Equality on (hash, round) as in the reference (messages.rs:219-222).
  bool operator==(const QC& o) const {
    return hash == o.hash && round == o.round;
  }
  bool operator!=(const QC& o) const { return !(*this == o); }

  Digest digest() const;  // what each vote signed
  VerifyResult verify(const Committee& committee) const;
  // Stake/reuse/quorum checks only — everything but the signature batch.
  // Lets the Core run the cheap host checks synchronously and dispatch the
  // signature batch to the device asynchronously.
  VerifyResult verify_structure(const Committee& committee) const;
  // The (digest, pk, sig) records the signature batch must verify (all
  // votes share this QC's digest()).
  std::vector<std::tuple<Digest, PublicKey, Signature>> vote_items() const;
  // Hash over the full serialized QC — the verified-certificate cache
  // key.  Deliberately NOT digest(): that covers only (hash, round), and
  // a byte-tampered vote set with the same (hash, round) must MISS the
  // cache so it is re-verified (and rejected) rather than persisted and
  // served to syncing peers.
  Digest content_digest() const;

  void serialize(Writer* w) const;
  static QC deserialize(Reader* r);
};

struct TC {
  Round round = 0;
  std::vector<std::tuple<PublicKey, Signature, Round>> votes;

  std::vector<Round> high_qc_rounds() const;
  VerifyResult verify(const Committee& committee) const;
  // Stake/reuse/quorum checks only (see QC::verify_structure).
  VerifyResult verify_structure(const Committee& committee) const;
  // The (digest, pk, sig) records the signature batch must verify — each
  // timeout vote signed its own (round, high_qc_round) digest.
  std::vector<std::tuple<Digest, PublicKey, Signature>> vote_items() const;
  // Hash over the full serialized TC (round + complete vote set) — the
  // verified-TC cache key.  Unlike QC::digest(), which covers only the
  // semantic content (hash, round), a TC's high_qc_rounds feed the voting
  // safety rule, so the cache must key on everything.
  Digest content_digest() const;

  void serialize(Writer* w) const;
  static TC deserialize(Reader* r);
};

struct Block {
  QC qc;
  std::optional<TC> tc;
  PublicKey author;
  Round round = 0;
  std::vector<Digest> payload;
  // graftdag: availability certificates for the payload digests.  Either
  // empty (legacy payload-sync blocks) or EXACTLY parallel to `payload`
  // (certs[i].digest == payload[i]) — check_certs enforces the shape.  A
  // cert-carrying proposal is constant-size evidence that every ordered
  // batch is retrievable from f+1 honest replicas, so replicas can vote
  // without possessing the bytes.  NOT covered by digest(): the payload
  // digests are, and the shape invariant ties each cert to its digest, so
  // two blocks differing only in cert vote sets order the same batches.
  std::vector<mempool::BatchCertificate> certs;
  Signature signature;

  static const Block& genesis();

  Digest digest() const;
  const Digest& parent() const { return qc.hash; }
  VerifyResult verify(const Committee& committee) const;
  // Structural certificate checks only — shape invariant plus per-cert
  // stake/reuse/quorum/minimality — everything but the signature batches,
  // which the Core dispatches to the verify sidecar asynchronously.
  VerifyResult check_certs(const Committee& committee) const;

  void serialize(Writer* w) const;
  static Block deserialize(Reader* r);
  Bytes to_bytes() const {
    Writer w;
    serialize(&w);
    return std::move(w.out);
  }
  static Block from_bytes(const Bytes& b) {
    Reader r(b);
    return deserialize(&r);
  }
};

struct Vote {
  Digest hash;  // block digest
  Round round = 0;
  PublicKey author;
  Signature signature;

  static Vote make(const Block& block, const PublicKey& author,
                   const SignatureService& service);

  Digest digest() const;
  VerifyResult verify(const Committee& committee) const;

  void serialize(Writer* w) const;
  static Vote deserialize(Reader* r);
};

struct Timeout {
  QC high_qc;
  Round round = 0;
  PublicKey author;
  Signature signature;

  static Timeout make(QC high_qc, Round round, const PublicKey& author,
                      const SignatureService& service);

  // The digest a timeout vote signs: round LE || high_qc_round LE
  // (messages.rs:267-273).  Exposed statically because THREE layers must
  // agree byte-for-byte on it: Timeout::digest() at signing time,
  // TC::vote_items() when a formed TC's batch re-verifies, and the
  // Core's per-signature eject loop when a batched TC verify fails
  // (graftview) — a divergence would make the eject path accept/reject
  // different sets than per-signature verification.
  static Digest vote_digest(Round round, Round high_qc_round);
  Digest digest() const;
  VerifyResult verify(const Committee& committee) const;
  // Author + signature checks only — without the embedded high_qc, which
  // the Core verifies through its verified-QC cache (during a view change
  // all 2f+1 timeouts typically carry the SAME high QC; re-verifying it
  // per timeout is O(n^2) signature work at committee scale).
  VerifyResult verify_own(const Committee& committee) const;

  void serialize(Writer* w) const;
  static Timeout deserialize(Reader* r);
};

// Network envelope (consensus/src/consensus.rs:32-39).
struct ConsensusMessage {
  enum class Kind : uint32_t {
    kPropose = 0,
    kVote = 1,
    kTimeout = 2,
    kTC = 3,
    kSyncRequest = 4,
  };

  Kind kind;
  Block block;          // kPropose
  Vote vote;            // kVote
  Timeout timeout;      // kTimeout
  TC tc;                // kTC
  Digest sync_digest;   // kSyncRequest
  PublicKey sync_from;  // kSyncRequest

  Bytes serialize() const;
  static ConsensusMessage deserialize(const Bytes& data);

  static Bytes propose(const Block& b);
  static Bytes vote_msg(const Vote& v);
  static Bytes timeout_msg(const Timeout& t);
  static Bytes tc_msg(const TC& tc);
  static Bytes sync_request(const Digest& digest, const PublicKey& from);
};

}  // namespace consensus
}  // namespace hotstuff

// Consensus message types: Block, Vote, QC, Timeout, TC and the network
// envelope ConsensusMessage (consensus/src/messages.rs:16-326 and
// consensus/src/consensus.rs:32-39 in the reference). QC verification is
// the TPU hot path: it stake-checks the vote set then calls
// Signature::verify_batch, which dispatches to the verify sidecar.
#pragma once

#include <optional>
#include <string>
#include <vector>

#include "common/serde.hpp"
#include "consensus/config.hpp"
#include "crypto/crypto.hpp"

namespace hotstuff {
namespace consensus {

// Verification outcome; `ok()` false carries a reason (the reference's
// ConsensusError variants, consensus/src/error.rs:22-65).
struct VerifyResult {
  std::string error;  // empty = ok
  bool ok() const { return error.empty(); }
  static VerifyResult good() { return {}; }
  static VerifyResult bad(std::string why) { return {std::move(why)}; }
};

struct QC {
  Digest hash;  // digest of the certified block
  Round round = 0;
  std::vector<std::pair<PublicKey, Signature>> votes;

  static const QC& genesis();
  bool is_genesis() const { return *this == genesis(); }

  // Equality on (hash, round) as in the reference (messages.rs:219-222).
  bool operator==(const QC& o) const {
    return hash == o.hash && round == o.round;
  }
  bool operator!=(const QC& o) const { return !(*this == o); }

  Digest digest() const;  // what each vote signed
  VerifyResult verify(const Committee& committee) const;

  void serialize(Writer* w) const;
  static QC deserialize(Reader* r);
};

struct TC {
  Round round = 0;
  std::vector<std::tuple<PublicKey, Signature, Round>> votes;

  std::vector<Round> high_qc_rounds() const;
  VerifyResult verify(const Committee& committee) const;

  void serialize(Writer* w) const;
  static TC deserialize(Reader* r);
};

struct Block {
  QC qc;
  std::optional<TC> tc;
  PublicKey author;
  Round round = 0;
  std::vector<Digest> payload;
  Signature signature;

  static const Block& genesis();

  Digest digest() const;
  const Digest& parent() const { return qc.hash; }
  VerifyResult verify(const Committee& committee) const;

  void serialize(Writer* w) const;
  static Block deserialize(Reader* r);
  Bytes to_bytes() const {
    Writer w;
    serialize(&w);
    return std::move(w.out);
  }
  static Block from_bytes(const Bytes& b) {
    Reader r(b);
    return deserialize(&r);
  }
};

struct Vote {
  Digest hash;  // block digest
  Round round = 0;
  PublicKey author;
  Signature signature;

  static Vote make(const Block& block, const PublicKey& author,
                   const SignatureService& service);

  Digest digest() const;
  VerifyResult verify(const Committee& committee) const;

  void serialize(Writer* w) const;
  static Vote deserialize(Reader* r);
};

struct Timeout {
  QC high_qc;
  Round round = 0;
  PublicKey author;
  Signature signature;

  static Timeout make(QC high_qc, Round round, const PublicKey& author,
                      const SignatureService& service);

  Digest digest() const;
  VerifyResult verify(const Committee& committee) const;

  void serialize(Writer* w) const;
  static Timeout deserialize(Reader* r);
};

// Network envelope (consensus/src/consensus.rs:32-39).
struct ConsensusMessage {
  enum class Kind : uint32_t {
    kPropose = 0,
    kVote = 1,
    kTimeout = 2,
    kTC = 3,
    kSyncRequest = 4,
  };

  Kind kind;
  Block block;          // kPropose
  Vote vote;            // kVote
  Timeout timeout;      // kTimeout
  TC tc;                // kTC
  Digest sync_digest;   // kSyncRequest
  PublicKey sync_from;  // kSyncRequest

  Bytes serialize() const;
  static ConsensusMessage deserialize(const Bytes& data);

  static Bytes propose(const Block& b);
  static Bytes vote_msg(const Vote& v);
  static Bytes timeout_msg(const Timeout& t);
  static Bytes tc_msg(const TC& tc);
  static Bytes sync_request(const Digest& digest, const PublicKey& from);
};

}  // namespace consensus
}  // namespace hotstuff

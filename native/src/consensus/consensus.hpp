// Consensus facade: wires the channels and spawns Receiver / Core /
// Proposer / Helper plus the synchronizer and mempool driver
// (consensus/src/consensus.rs:41-162 in the reference).
#pragma once

#include <memory>

#include "common/channel.hpp"
#include "consensus/core.hpp"
#include "consensus/proposer.hpp"
#include "mempool/messages.hpp"
#include "network/receiver.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace consensus {

class Consensus {
 public:
  // rx_mempool: batch digests from the mempool processors;
  // tx_mempool: Synchronize/Cleanup commands to the mempool;
  // tx_commit: committed blocks out to the application layer.
  static std::unique_ptr<Consensus> spawn(
      PublicKey name, Committee committee, Parameters parameters,
      SignatureService signature_service, Store store,
      ChannelPtr<Digest> rx_mempool,
      ChannelPtr<mempool::ConsensusMempoolMessage> tx_mempool,
      ChannelPtr<Block> tx_commit);

  ~Consensus();

 private:
  Consensus() = default;

  NetworkReceiver receiver_;
};

}  // namespace consensus
}  // namespace hotstuff

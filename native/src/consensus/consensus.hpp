// Consensus facade: wires the channels and spawns Receiver / Core /
// Proposer / Helper plus the synchronizer and mempool driver
// (consensus/src/consensus.rs:41-162 in the reference).
#pragma once

#include <atomic>
#include <functional>
#include <memory>
#include <thread>
#include <vector>

#include "common/channel.hpp"
#include "consensus/core.hpp"
#include "consensus/proposer.hpp"
#include "mempool/messages.hpp"
#include "network/receiver.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace consensus {

class Consensus {
 public:
  // rx_mempool: payload refs (batch digest + optional availability
  // certificate, graftdag) from the mempool processors;
  // tx_mempool: Synchronize/Cleanup commands to the mempool;
  // tx_commit: committed blocks out to the application layer.
  // store holds consensus metadata (blocks, last-vote state); batch_store
  // holds mempool batch payloads.  They are separate actors so a commit
  // walk or state flush never queues behind ~500 KB batch writes
  // (graftdag: the payload store is the write-heavy one by 2-3 orders of
  // magnitude, and sharing one single-threaded store actor let batch
  // traffic wedge the core's blocking metadata round trips).
  static std::unique_ptr<Consensus> spawn(
      PublicKey name, Committee committee, Parameters parameters,
      SignatureService signature_service, Store store, Store batch_store,
      ChannelPtr<mempool::PayloadRef> rx_mempool,
      ChannelPtr<mempool::ConsensusMempoolMessage> tx_mempool,
      ChannelPtr<Block> tx_commit);

  // Orderly teardown: set the stop flag, close every channel (including
  // tx_commit, which releases the application's commit drain), stop the
  // receiver, join Core/Proposer/Helper. Idempotent; destructor calls it.
  void stop();
  ~Consensus();

 private:
  Consensus() = default;

  NetworkReceiver receiver_;
  std::shared_ptr<std::atomic<bool>> stop_flag_ =
      std::make_shared<std::atomic<bool>>(false);
  std::vector<std::function<void()>> closers_;
  std::vector<std::thread> threads_;
  bool stopped_ = false;
};

}  // namespace consensus
}  // namespace hotstuff

// Compiled off-chain signature benchmark — the C++ counterpart of the
// reference's `production` crate (off-chain-benchmarking/production/src/
// main.rs:15-108), driving the SAME crypto stack the consensus node uses
// (crypto.cpp host path; TpuVerifier device batch path when a sidecar is
// reachable) instead of a separate library.
//
// Axes mirror the reference:
//   multi:  N = 1, 65, 129, ... <= 2048 signatures over distinct 64-byte
//           messages; per-N average of (a) sequential single verifies and
//           (b) one batched verification — the reference compares
//           sequential ed25519 against BLS *aggregate* verify; in this
//           framework the batched fast path is the device batch verify,
//           and the BLS aggregate axis lives in the Python sweep
//           (hotstuff_tpu/offchain/bench.py) where BLS keygen exists.
//   length: one signature over messages of 64..6400 bytes (hash included
//           in the timed region, since this stack signs digests).
//
// Usage: offchain_bench [--sidecar host:port] [--iters-budget-ms N]
// Output: one "axis n seq_us batch_us" line per point (microseconds per
// full verification of the whole set), suitable for results/offchain-cpp.

#include <chrono>
#include <cstdio>
#include <cstring>
#include <functional>
#include <random>
#include <string>
#include <vector>

#include "crypto/crypto.hpp"
#include "crypto/sidecar_client.hpp"

using namespace hotstuff;

namespace {

using Clock = std::chrono::steady_clock;

double time_us(const std::function<void()>& fn, double budget_ms) {
  // Time-boxed averaging: repeat until the budget is spent (>= 3 reps),
  // return mean microseconds per rep.  The reference uses a fixed 100
  // iterations; a budget keeps the 2048-point affordable on small hosts.
  fn();  // warm
  int reps = 0;
  auto t0 = Clock::now();
  do {
    fn();
    reps++;
  } while (std::chrono::duration<double, std::milli>(Clock::now() - t0)
                   .count() < budget_ms ||
           reps < 3);
  auto dt = std::chrono::duration<double, std::micro>(Clock::now() - t0);
  return dt.count() / reps;
}

struct Record {
  Digest digest;
  PublicKey pk;
  Signature sig;
};

std::vector<Record> make_records(size_t n, std::mt19937_64* rng) {
  std::vector<Record> out;
  out.reserve(n);
  for (size_t i = 0; i < n; i++) {
    std::array<uint8_t, 32> seed;
    for (auto& b : seed) b = uint8_t((*rng)());
    KeyPair kp = keypair_from_seed(seed);
    Bytes msg(64);
    for (auto& b : msg) b = uint8_t((*rng)());
    Record r;
    r.digest = DigestBuilder().update(msg).finalize();
    r.pk = kp.name;
    r.sig = Signature::sign(r.digest, kp.secret);
    out.push_back(std::move(r));
  }
  return out;
}

void multi_sweep(double budget_ms) {
  std::mt19937_64 rng(7);
  bool device = TpuVerifier::instance() && TpuVerifier::instance()->connected();
  std::printf("# multi: N seq_host_us batch_%s_us\n",
              device ? "device" : "host");
  // N = 1, 65, 129, ... <= 2048: the reference's stride (main.rs:21-61).
  for (int n = 1; n <= 2048; n += 64) {
    // DISTINCT record sets per timed repetition: the sidecar caches
    // verdicts by record bytes, so re-verifying one set would time the
    // cache, not the device.  Generation happens outside the timed
    // region.
    constexpr int kSets = 3;
    std::vector<std::vector<Record>> sets;
    std::vector<std::vector<std::tuple<Digest, PublicKey, Signature>>>
        item_sets;
    for (int s = 0; s < kSets; s++) {
      sets.push_back(make_records(size_t(n), &rng));
      std::vector<std::tuple<Digest, PublicKey, Signature>> items;
      items.reserve(sets.back().size());
      for (const auto& r : sets.back()) {
        items.emplace_back(r.digest, r.pk, r.sig);
      }
      item_sets.push_back(std::move(items));
    }
    double seq = time_us(
        [&] {
          for (const auto& r : sets[0]) {
            if (!r.sig.verify(r.digest, r.pk)) std::abort();
          }
        },
        budget_ms);
    // Warm the dispatch path (shape compile on device) untimed, then one
    // timed pass over each fresh set.  These are throughput batches, not
    // consensus certificates: tag them bulk-class so a live sidecar
    // schedules them behind (and into the pad slots of) QC verifies.
    if (!Signature::verify_batch_multi(item_sets[0], /*bulk=*/true)) {
      std::abort();
    }
    auto t0 = Clock::now();
    for (int s = 1; s < kSets; s++) {
      if (!Signature::verify_batch_multi(item_sets[s], /*bulk=*/true)) {
        std::abort();
      }
    }
    double batch = std::chrono::duration<double, std::micro>(
                       Clock::now() - t0).count() / (kSets - 1);
    std::printf("multi %d %.1f %.1f\n", n, seq, batch);
    std::fflush(stdout);
  }
}

void length_sweep(double budget_ms) {
  std::mt19937_64 rng(11);
  std::array<uint8_t, 32> seed;
  for (auto& b : seed) b = uint8_t(rng());
  KeyPair kp = keypair_from_seed(seed);
  std::printf("# length: bytes verify_us (digest+verify, host)\n");
  for (int i = 1; i <= 100; i++) {
    size_t len = size_t(64) * size_t(i);
    Bytes msg(len);
    for (auto& b : msg) b = uint8_t(rng());
    Digest d = DigestBuilder().update(msg).finalize();
    Signature sig = Signature::sign(d, kp.secret);
    double t = time_us(
        [&] {
          Digest d2 = DigestBuilder().update(msg).finalize();
          if (!sig.verify(d2, kp.name)) std::abort();
        },
        budget_ms);
    std::printf("length %zu %.1f\n", len, t);
  }
}

}  // namespace

int main(int argc, char** argv) {
  double budget_ms = 50.0;
  for (int i = 1; i < argc; i++) {
    if (std::strcmp(argv[i], "--sidecar") == 0 && i + 1 < argc) {
      auto addr = Address::parse(argv[++i]);
      if (!addr) {
        std::fprintf(stderr, "bad sidecar address\n");
        return 1;
      }
      TpuVerifier::install(std::make_unique<TpuVerifier>(*addr));
    } else if (std::strcmp(argv[i], "--iters-budget-ms") == 0 &&
               i + 1 < argc) {
      try {
        budget_ms = std::stod(argv[++i]);
      } catch (const std::exception&) {
        std::fprintf(stderr, "bad --iters-budget-ms value\n");
        return 1;
      }
    } else {
      std::fprintf(stderr,
                   "usage: offchain_bench [--sidecar host:port] "
                   "[--iters-budget-ms N]\n");
      return 1;
    }
  }
  multi_sweep(budget_ms);
  length_sweep(budget_ms);
  return 0;
}

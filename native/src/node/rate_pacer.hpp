// Open-loop client load models (graftsurge).
//
// RatePacer: exact constant-rate pacing.  The old scheme sent
// floor(rate / precision) transactions per tick, which under-delivers
// every rate that truncates — worst in [precision, 2*precision), where
// e.g. --rate 39 at precision 20 sent 20 tx/s, half the run label
// (round-5 ADVICE.md).  The pacer carries the remainder across ticks so
// the offered load over any whole second equals `rate` exactly, for
// every rate >= 1 (sub-precision rates emit empty ticks in between).
//
// UserLoadModel: the multi-user open-loop generator behind `client
// --users N`.  Thousands of simulated users per client process, each
// with heavy-tailed (lognormal or Pareto, seeded) inter-arrival times —
// real traffic is bursty: a p99 burst is many times the mean, which a
// constant-rate stream never exercises — plus an optional diurnal ramp,
// with the AGGREGATE mean rate still equal to `--rate` (every
// inter-arrival multiplier is sampled mean-1, and the diurnal profile
// averages to 1 over its period).  On a node BUSY reply the model backs
// off PER USER with jittered exponential retry: arrivals due inside the
// busy window are deferred, not dropped — an open-loop load the node
// can actually shed.  All time is caller-supplied seconds, so tests and
// the bench probe drive it on a virtual clock.
#pragma once

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <queue>
#include <random>
#include <vector>

namespace hotstuff {

struct RatePacer {
  uint64_t rate;       // offered load, tx/s
  uint64_t precision;  // ticks per second
  uint64_t acc = 0;    // carried remainder, always < precision

  // Number of transactions to send on this tick.  Summed over any
  // precision consecutive ticks (one second) this is exactly `rate`.
  uint64_t next_burst() {
    acc += rate;
    uint64_t burst = acc / precision;
    acc -= burst * precision;
    return burst;
  }
};

enum class ArrivalDist { kLognormal, kPareto };

class UserLoadModel {
 public:
  struct Options {
    uint64_t rate = 1000;   // aggregate mean tx/s across all users
    size_t users = 1000;
    uint64_t seed = 1;      // generator is deterministic in the seed
    ArrivalDist dist = ArrivalDist::kLognormal;
    double sigma = 1.5;     // lognormal shape: CV = sqrt(e^sigma^2 - 1)
    double alpha = 2.5;     // pareto shape (> 1 for a finite mean)
    double diurnal_amp = 0.0;       // 0 = flat; 0.5 = rate swings +-50%
    double diurnal_period_s = 600;  // compressed "day" for bench windows
    double busy_base_s = 0.05;      // backoff base when BUSY has no hint
  };

  explicit UserLoadModel(const Options& opt) : opt_(opt), rng_(opt.seed) {
    size_t users = std::max<size_t>(1, opt_.users);
    mean_gap_s_ = double(users) / std::max<uint64_t>(1, opt_.rate);
    users_.resize(users);
    std::uniform_real_distribution<double> phase(0.0, mean_gap_s_);
    for (size_t u = 0; u < users; u++) {
      // Random start phase: the aggregate is at its mean rate from t=0
      // instead of every user firing at once.
      heap_.push({phase(rng_), u});
    }
  }

  // Diurnal multiplier at time t (mean exactly 1 over a period).
  double profile(double t) const {
    if (opt_.diurnal_amp <= 0.0) return 1.0;
    constexpr double kTau = 6.283185307179586;
    return 1.0 + opt_.diurnal_amp *
                     std::sin(kTau * t / opt_.diurnal_period_s);
  }

  // Number of transactions to send at `now` (all user arrivals due up
  // to now).  Call with a monotonically non-decreasing clock.
  // graftingress: `out_users` (optional) receives the user index of
  // each due arrival, in order — the signing client derives the
  // per-user keypair from it.
  uint64_t arrivals(double now, std::vector<size_t>* out_users = nullptr) {
    uint64_t due = 0;
    while (!heap_.empty() && heap_.top().t <= now) {
      Arrival a = heap_.top();
      heap_.pop();
      User& u = users_[a.user];
      if (a.t < busy_until_) {
        // The node said BUSY: this user's arrival defers with jittered
        // exponential backoff — deferred, never dropped (open loop).
        u.attempt = std::min<uint32_t>(u.attempt + 1, 6);
        double base = std::max(busy_hint_s_, opt_.busy_base_s);
        double jitter = jitter_(rng_);
        heap_.push({busy_until_ + base * double(1u << u.attempt) * jitter,
                    a.user});
        deferred_++;
        continue;
      }
      u.attempt = 0;
      due++;
      sent_++;
      if (out_users != nullptr) out_users->push_back(a.user);
      heap_.push({a.t + next_gap_(a.t), a.user});
    }
    return due;
  }

  // A node BUSY reply observed at `now` with a retry-after hint.
  void busy(double now, double hint_s) {
    busy_hint_s_ = std::max(0.0, hint_s);
    busy_until_ =
        std::max(busy_until_, now + std::max(busy_hint_s_, opt_.busy_base_s));
    busy_events_++;
  }

  uint64_t sent() const { return sent_; }
  uint64_t deferred() const { return deferred_; }
  uint64_t busy_events() const { return busy_events_; }

  // Test hook: one inter-arrival gap sample at time t, drawn from the
  // same rng stream the generator uses (distribution sanity checks).
  double sample_gap_for_test(double t) { return next_gap_(t); }

 private:
  struct Arrival {
    double t;
    size_t user;
    bool operator>(const Arrival& o) const { return t > o.t; }
  };
  struct User {
    uint32_t attempt = 0;
  };

  // One inter-arrival gap for a user at time t: the user's mean gap
  // (users / rate) times a mean-1 heavy-tailed multiplier, compressed
  // by the diurnal profile.
  double next_gap_(double t) {
    double x;
    if (opt_.dist == ArrivalDist::kPareto) {
      // X = xm * U^(-1/alpha) with xm = (alpha-1)/alpha has mean 1.
      double a = std::max(1.05, opt_.alpha);
      double u = std::max(1e-12, uniform_(rng_));
      x = (a - 1.0) / a * std::pow(u, -1.0 / a);
    } else {
      // X = exp(sigma Z - sigma^2/2) has mean 1.
      double z = normal_(rng_);
      x = std::exp(opt_.sigma * z - 0.5 * opt_.sigma * opt_.sigma);
    }
    double gap = mean_gap_s_ * x / profile(t);
    return std::max(gap, 1e-9);
  }

  Options opt_;
  double mean_gap_s_ = 1.0;
  std::mt19937_64 rng_;
  std::normal_distribution<double> normal_{0.0, 1.0};
  std::uniform_real_distribution<double> uniform_{0.0, 1.0};
  std::uniform_real_distribution<double> jitter_{0.5, 1.5};
  std::vector<User> users_;
  std::priority_queue<Arrival, std::vector<Arrival>, std::greater<Arrival>>
      heap_;
  double busy_until_ = -1.0;
  double busy_hint_s_ = 0.0;
  uint64_t sent_ = 0;
  uint64_t deferred_ = 0;
  uint64_t busy_events_ = 0;
};

}  // namespace hotstuff

// Exact open-loop pacing for the benchmark client.  The old scheme sent
// floor(rate / precision) transactions per tick, which under-delivers
// every rate that truncates — worst in [precision, 2*precision), where
// e.g. --rate 39 at precision 20 sent 20 tx/s, half the run label
// (round-5 ADVICE.md).  The pacer carries the remainder across ticks so
// the offered load over any whole second equals `rate` exactly, for
// every rate >= 1 (sub-precision rates emit empty ticks in between).
#pragma once

#include <cstdint>

namespace hotstuff {

struct RatePacer {
  uint64_t rate;       // offered load, tx/s
  uint64_t precision;  // ticks per second
  uint64_t acc = 0;    // carried remainder, always < precision

  // Number of transactions to send on this tick.  Summed over any
  // precision consecutive ticks (one second) this is exactly `rate`.
  uint64_t next_burst() {
    acc += rate;
    uint64_t burst = acc / precision;
    acc -= burst * precision;
    return burst;
  }
};

}  // namespace hotstuff

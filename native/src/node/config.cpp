#include "node/config.hpp"

namespace hotstuff {
namespace node {

Secret Secret::generate() {
  KeyPair kp = generate_keypair();
  Secret s;
  s.name = kp.name;
  s.secret = kp.secret;
  return s;
}

Secret Secret::read(const std::string& path) {
  Json j = Json::read_file(path);
  Secret s;
  if (!PublicKey::from_base64(j.at("name").as_string(), &s.name) ||
      !SecretKey::from_base64(j.at("secret").as_string(), &s.secret)) {
    throw JsonError("bad key file " + path);
  }
  if (auto* v = j.find("bls_secret")) {
    if (!base64_decode(v->as_string(), &s.bls_secret) ||
        s.bls_secret.size() != 48) {
      throw JsonError("bad bls_secret in " + path);
    }
  }
  return s;
}

void Secret::write(const std::string& path) const {
  Json j = Json::object();
  j.set("name", Json(name.to_base64()));
  j.set("secret", Json(secret.to_base64()));
  if (!bls_secret.empty()) {
    j.set("bls_secret", Json(base64_encode(bls_secret)));
  }
  j.write_file(path);
}

Committee Committee::read(const std::string& path) {
  Json j = Json::read_file(path);
  Committee c;
  c.consensus = consensus::Committee::from_json(j.at("consensus"));
  c.mempool = mempool::Committee::from_json(j.at("mempool"));
  return c;
}

void Committee::write(const std::string& path) const {
  Json j = Json::object();
  j.set("consensus", consensus.to_json());
  j.set("mempool", mempool.to_json());
  j.write_file(path);
}

Parameters Parameters::from_json(const Json& j) {
  Parameters p;
  if (auto* v = j.find("consensus")) {
    p.consensus = consensus::Parameters::from_json(*v);
  }
  if (auto* v = j.find("mempool")) {
    p.mempool = mempool::Parameters::from_json(*v);
  }
  if (auto* v = j.find("tpu_sidecar")) {
    if (v->type() == Json::Type::kString) {
      p.tpu_sidecar = Address::parse(v->as_string());
      if (p.tpu_sidecar) p.tpu_sidecars.push_back(*p.tpu_sidecar);
    } else if (v->type() == Json::Type::kArray) {
      // graftfleet: ordered endpoint list; a malformed entry is a config
      // error (silently skipping one would re-order the failover ladder).
      for (const auto& e : v->items()) {
        auto a = Address::parse(e.as_string());
        if (!a) throw JsonError("bad tpu_sidecar address: " + e.as_string());
        p.tpu_sidecars.push_back(*a);
      }
      if (!p.tpu_sidecars.empty()) p.tpu_sidecar = p.tpu_sidecars.front();
    }
  }
  if (auto* v = j.find("tpu_tenant")) {
    p.tpu_tenant = v->as_string();
  }
  if (auto* v = j.find("scheme")) {
    p.scheme = v->as_string();
    if (p.scheme != "ed25519" && p.scheme != "bls") {
      throw JsonError("unknown scheme: " + p.scheme);
    }
  }
  if (auto* v = j.find("trace")) {
    p.trace = v->as_bool();
  }
  return p;
}

Parameters Parameters::read(const std::string& path) {
  return from_json(Json::read_file(path));
}

}  // namespace node
}  // namespace hotstuff

// Node assembly: reads configs, opens the store, starts the signature
// service and (optionally) the TPU verifier, spawns mempool + consensus,
// and exposes the commit channel (node/src/node.rs:13-81 in the reference).
#pragma once

#include <memory>
#include <string>

#include "consensus/consensus.hpp"
#include "mempool/mempool.hpp"
#include "node/config.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace node {

class Node {
 public:
  static std::unique_ptr<Node> create(const std::string& committee_file,
                                      const std::string& key_file,
                                      const std::string& store_path,
                                      const std::string& parameters_file);

  // Drains the commit channel (node.rs:76-81). Returns once stop() closes
  // the channel.
  void analyze_block();

  // Orderly shutdown: stops consensus then mempool (joining every actor
  // thread), which also closes the commit channel. Idempotent.
  void stop();

  ChannelPtr<consensus::Block> commit_channel() { return commit_; }
  const PublicKey& name() const { return name_; }

 private:
  Node() = default;

  PublicKey name_;
  Store store_;        // consensus metadata (blocks, vote state)
  Store batch_store_;  // mempool batch payloads (write-heavy)
  ChannelPtr<consensus::Block> commit_;
  std::unique_ptr<mempool::Mempool> mempool_;
  std::unique_ptr<consensus::Consensus> consensus_;
};

}  // namespace node
}  // namespace hotstuff

// Benchmark client: open-loop transaction load generator
// (node/src/client.rs:15-168 in the reference). Sends `rate` tx/s in
// PRECISION bursts per second over one framed TCP connection to a node's
// transactions address. Sample txs ([0u8][u64 BE counter][padding]) are
// logged for end-to-end latency measurement; filler txs are
// [1u8][u64 BE r][padding].
//   client ADDR --size BYTES --rate TXS [--timeout MS] [--nodes A1 A2 ...]
#include <algorithm>
#include <chrono>
#include <iostream>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "common/log.hpp"
#include "network/socket.hpp"
#include "node/rate_pacer.hpp"

using namespace hotstuff;

namespace {
constexpr uint64_t kPrecision = 20;  // sample precision: bursts per second
constexpr uint64_t kBurstDurationMs = 1000 / kPrecision;
}  // namespace

int main(int argc, char** argv) {
  std::string target_str;
  size_t size = 512;
  uint64_t rate = 1000;
  uint64_t timeout_ms = 0;
  std::vector<std::string> nodes;
  for (int i = 1; i < argc; i++) {
    std::string arg = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) {
        std::cerr << "missing value for " << arg << "\n";
        std::exit(2);
      }
      return argv[++i];
    };
    if (arg == "--size") size = std::stoul(next());
    else if (arg == "--rate") rate = std::stoull(next());
    else if (arg == "--timeout") timeout_ms = std::stoull(next());
    else if (arg == "--nodes") {
      while (i + 1 < argc && argv[i + 1][0] != '-') nodes.push_back(argv[++i]);
    } else if (arg[0] != '-') target_str = arg;
  }
  log_set_level(LogLevel::kInfo);

  auto target = Address::parse(target_str);
  if (!target) {
    std::cerr << "client ADDR --size BYTES --rate TXS [--timeout MS] "
                 "[--nodes ...]\n";
    return 2;
  }
  if (size < 9) {
    LOG_ERROR("client") << "Transaction size must be at least 9 bytes";
    return 1;
  }
  if (rate < 1) {
    LOG_ERROR("client") << "rate must be at least 1 tx/s";
    return 1;
  }

  LOG_INFO("client") << "Node address: " << target->str();
  // NOTE: These log entries are used to compute performance
  // (hotstuff_tpu/harness/logs.py client regexes).
  LOG_INFO("client") << "Transactions size: " << size << " B";
  LOG_INFO("client") << "Transactions rate: " << rate << " tx/s";

  // Wait for all nodes to be online, then for synchronization
  // (client.rs:152-167).
  LOG_INFO("client") << "Waiting for all nodes to be online...";
  for (const auto& n : nodes) {
    auto addr = Address::parse(n);
    if (!addr) continue;
    while (!Socket::connect(*addr)) {
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
  }
  LOG_INFO("client") << "Waiting for all nodes to be synchronized...";
  std::this_thread::sleep_for(std::chrono::milliseconds(2 * timeout_ms));

  auto sock = Socket::connect(*target);
  if (!sock) {
    LOG_WARN("client") << "failed to connect to " << target->str();
    return 1;
  }

  // One tick every 1/kPrecision s; the pacer carries the rate/kPrecision
  // remainder across ticks so the offered load matches --rate exactly at
  // EVERY rate >= 1 (truncation used to under-deliver [kPrecision,
  // 2*kPrecision) by up to 2x, and the harness divides the total rate by
  // committee size, so per-client rates land in that band at scale).
  // Sub-kPrecision rates emit empty ticks in between 1-tx bursts.
  RatePacer pacer{rate, kPrecision};
  std::mt19937_64 rng(std::random_device{}());
  uint64_t r = rng();
  uint64_t counter = 0;
  Bytes tx(size, 0);

  // NOTE: This log entry is used to compute performance.
  LOG_INFO("client") << "Start sending transactions";

  auto interval = std::chrono::milliseconds(kBurstDurationMs);
  auto next_tick = std::chrono::steady_clock::now() + interval;
  while (true) {
    std::this_thread::sleep_until(next_tick);
    next_tick += interval;
    const uint64_t burst = pacer.next_burst();
    if (burst == 0) continue;  // sub-kPrecision rate: skip this tick
    auto burst_start = std::chrono::steady_clock::now();
    for (uint64_t x = 0; x < burst; x++) {
      uint64_t id;
      if (x == counter % burst) {
        // NOTE: This log entry is used to compute performance.
        LOG_INFO("client") << "Sending sample transaction " << counter;
        tx[0] = 0;  // sample txs start with 0
        id = counter;
      } else {
        tx[0] = 1;  // standard txs start with 1
        id = ++r;
      }
      for (int b = 0; b < 8; b++) tx[1 + b] = (id >> (8 * (7 - b))) & 0xFF;
      if (!sock->write_frame(tx)) {
        LOG_WARN("client") << "Failed to send transaction";
        return 1;
      }
    }
    auto elapsed = std::chrono::duration_cast<std::chrono::milliseconds>(
        std::chrono::steady_clock::now() - burst_start);
    if (elapsed.count() > int64_t(kBurstDurationMs)) {
      // NOTE: This log entry is used to compute performance.
      LOG_WARN("client") << "Transaction rate too high for this client";
    }
    counter++;
  }
}

// Benchmark client: open-loop transaction load generator
// (node/src/client.rs:15-168 in the reference), generalized by graftsurge
// into a multi-user open-loop generator.  Default (--users 1) is the
// legacy constant-rate stream: `rate` tx/s in PRECISION bursts per second
// over one framed TCP connection to a node's transactions address.  With
// --users N it simulates N independent users, each with heavy-tailed
// (lognormal or Pareto, seeded) inter-arrival times and an optional
// diurnal ramp, the AGGREGATE mean still honoring --rate (see
// node/rate_pacer.hpp UserLoadModel).  The node's bounded ingress can
// reply "BUSY <retry_ms>" on this connection; a reader thread parses it
// and the generator backs off — per user with jittered exponential
// retry in model mode, a whole-stream pause in legacy mode.
// Sample txs ([0u8][u64 BE counter][padding]) are logged for end-to-end
// latency measurement; filler txs are [1u8][u64 BE r][padding].
// graftingress (--sign): every tx rides the signed-transaction frame
// (mempool/tx_frame.hpp) instead — the legacy bytes become the PAYLOAD,
// wrapped in (pubkey ‖ nonce ‖ len ‖ payload ‖ sig) and signed with the
// per-user Ed25519 key derived from --seed + user index.  --forge-pct
// flips one signature bit on that fraction of filler txs (marker 2):
// structurally valid frames the node's admission verify must reject.
// --user-offset / --sample-offset shard the user-id and sample-id
// spaces so multi-process client shards never collide.
//   client ADDR --size BYTES --rate TXS [--timeout MS] [--nodes A1 A2 ...]
//          [--users N] [--seed S] [--dist lognormal|pareto] [--sigma X]
//          [--alpha X] [--diurnal AMP] [--diurnal-period SEC]
//          [--sign] [--forge-pct P] [--user-offset K] [--sample-offset K]
#include <algorithm>
#include <atomic>
#include <chrono>
#include <iostream>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "common/log.hpp"
#include "mempool/tx_frame.hpp"
#include "network/socket.hpp"
#include "node/rate_pacer.hpp"

using namespace hotstuff;
using hotstuff::mempool::build_signed_tx;
using hotstuff::mempool::kTxFrameOverhead;
using hotstuff::mempool::kTxMarkerFiller;
using hotstuff::mempool::kTxMarkerForged;
using hotstuff::mempool::kTxMarkerSample;
using hotstuff::mempool::TxKeyring;

namespace {
constexpr uint64_t kPrecision = 20;  // sample precision: bursts per second
constexpr uint64_t kBurstDurationMs = 1000 / kPrecision;
// BUSY replies are per-shed; log the first and every Nth so a surge
// leaves evidence without drowning the log.
constexpr uint64_t kBusyLogEvery = 50;
// Forged sends carry a cumulative total, so sparse logging still lets
// the parser recover the count to within one log interval.
constexpr uint64_t kForgeLogEvery = 25;

// "BUSY <retry_ms>" -> retry_ms, or -1 when the frame is something else.
int64_t parse_busy(const Bytes& frame) {
  static const std::string kTag = "BUSY ";
  if (frame.size() < kTag.size() + 1) return -1;
  if (!std::equal(kTag.begin(), kTag.end(), frame.begin())) return -1;
  int64_t ms = 0;
  for (size_t i = kTag.size(); i < frame.size(); i++) {
    if (frame[i] < '0' || frame[i] > '9') return -1;
    ms = ms * 10 + (frame[i] - '0');
    // Clamp but KEEP validating: a corrupt frame with a long digit
    // prefix and junk after it must be rejected, not read as a 60 s
    // backoff order.
    if (ms > 60'000) ms = 60'000;
  }
  return ms;
}
}  // namespace

int main(int argc, char** argv) {
  std::string target_str;
  size_t size = 512;
  uint64_t rate = 1000;
  uint64_t timeout_ms = 0;
  size_t users = 1;
  uint64_t seed = std::random_device{}();
  ArrivalDist dist = ArrivalDist::kLognormal;
  double sigma = 1.5;
  double alpha = 2.5;
  double diurnal_amp = 0.0;
  double diurnal_period_s = 600.0;
  bool sign = false;
  double forge_pct = 0.0;
  uint64_t user_offset = 0;
  uint64_t sample_offset = 0;
  std::vector<std::string> nodes;
  for (int i = 1; i < argc; i++) {
    std::string arg = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) {
        std::cerr << "missing value for " << arg << "\n";
        std::exit(2);
      }
      return argv[++i];
    };
    if (arg == "--size") size = std::stoul(next());
    else if (arg == "--rate") rate = std::stoull(next());
    else if (arg == "--timeout") timeout_ms = std::stoull(next());
    else if (arg == "--users") users = std::stoul(next());
    else if (arg == "--seed") seed = std::stoull(next());
    else if (arg == "--sigma") sigma = std::stod(next());
    else if (arg == "--alpha") alpha = std::stod(next());
    else if (arg == "--diurnal") diurnal_amp = std::stod(next());
    else if (arg == "--diurnal-period") diurnal_period_s = std::stod(next());
    else if (arg == "--sign") sign = true;
    else if (arg == "--forge-pct") forge_pct = std::stod(next());
    else if (arg == "--user-offset") user_offset = std::stoull(next());
    else if (arg == "--sample-offset") sample_offset = std::stoull(next());
    else if (arg == "--dist") {
      std::string d = next();
      if (d == "pareto") dist = ArrivalDist::kPareto;
      else if (d == "lognormal") dist = ArrivalDist::kLognormal;
      else {
        std::cerr << "unknown --dist " << d << "\n";
        return 2;
      }
    } else if (arg == "--nodes") {
      while (i + 1 < argc && argv[i + 1][0] != '-') nodes.push_back(argv[++i]);
    } else if (arg[0] != '-') target_str = arg;
  }
  log_set_level(LogLevel::kInfo);

  auto target = Address::parse(target_str);
  if (!target) {
    std::cerr << "client ADDR --size BYTES --rate TXS [--timeout MS] "
                 "[--users N] [--seed S] [--dist lognormal|pareto] "
                 "[--sigma X] [--alpha X] [--diurnal AMP] "
                 "[--diurnal-period SEC] [--sign] [--forge-pct P] "
                 "[--user-offset K] [--sample-offset K] [--nodes ...]\n";
    return 2;
  }
  if (size < 9) {
    LOG_ERROR("client") << "Transaction size must be at least 9 bytes";
    return 1;
  }
  if (rate < 1) {
    LOG_ERROR("client") << "rate must be at least 1 tx/s";
    return 1;
  }
  if (users < 1) users = 1;

  LOG_INFO("client") << "Node address: " << target->str();
  // NOTE: These log entries are used to compute performance
  // (hotstuff_tpu/harness/logs.py client regexes).  Signed frames put
  // kTxFrameOverhead extra bytes on the wire per tx; the size logged is
  // the ON-WIRE size so the parser's bytes→tx arithmetic stays exact.
  LOG_INFO("client") << "Transactions size: "
                     << (sign ? size + kTxFrameOverhead : size) << " B";
  LOG_INFO("client") << "Transactions rate: " << rate << " tx/s";
  if (sign) {
    // NOTE: This log entry switches the log parser into signed-ingress
    // accounting (and marks shard identity via the offsets).
    LOG_INFO("client") << "Signed ingress enabled (seed " << seed
                       << ", forge " << forge_pct << "%, user offset "
                       << user_offset << ", sample offset "
                       << sample_offset << ")";
  }
  if (users > 1) {
    LOG_INFO("client") << "Simulating " << users << " users ("
                       << (dist == ArrivalDist::kPareto ? "pareto alpha="
                                                        : "lognormal sigma=")
                       << (dist == ArrivalDist::kPareto ? alpha : sigma)
                       << ", seed " << seed << ", diurnal "
                       << diurnal_amp * 100 << "% over " << diurnal_period_s
                       << " s)";
  }

  // Wait for all nodes to be online, then for synchronization
  // (client.rs:152-167).
  LOG_INFO("client") << "Waiting for all nodes to be online...";
  for (const auto& n : nodes) {
    auto addr = Address::parse(n);
    if (!addr) continue;
    while (!Socket::connect(*addr)) {
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
  }
  LOG_INFO("client") << "Waiting for all nodes to be synchronized...";
  std::this_thread::sleep_for(std::chrono::milliseconds(2 * timeout_ms));

  auto sock_opt = Socket::connect(*target);
  if (!sock_opt) {
    LOG_WARN("client") << "failed to connect to " << target->str();
    return 1;
  }
  // Shared ownership: the detached BUSY reader below may still be
  // blocked in read_frame when main returns on a send failure; the
  // shared_ptr keeps the fd alive until both sides are done.
  auto sock = std::make_shared<Socket>(std::move(*sock_opt));

  // BUSY reader: the node's bounded ingress replies "BUSY <retry_ms>"
  // when it sheds (mempool/ingress.hpp).  A dedicated thread drains the
  // connection — the send loop never blocks on reads — and publishes
  // the freshest hint for the generator to consume at its next tick.
  // static: the detached reader must never touch a dead stack frame if
  // main returns on a send failure while it is still parsing a reply.
  static std::atomic<int64_t> busy_hint_ms{-1};   // -1 = none pending
  static std::atomic<uint64_t> busy_total{0};
  std::thread busy_reader([sock] {
    Bytes frame;
    while (sock->read_frame(&frame)) {
      int64_t ms = parse_busy(frame);
      if (ms < 0) continue;  // unknown reply kind: ignore
      busy_hint_ms.store(ms, std::memory_order_release);
      uint64_t n = busy_total.fetch_add(1, std::memory_order_relaxed) + 1;
      if (n == 1 || n % kBusyLogEvery == 0) {
        // NOTE: The log parser mines these lines for overload notes.
        LOG_INFO("client") << "Node busy (retry-after " << ms
                           << " ms); backing off (" << n << " total)";
      }
    }
  });
  busy_reader.detach();  // exits when the socket closes with the process

  UserLoadModel::Options opt;
  opt.rate = rate;
  opt.users = users;
  opt.seed = seed;
  opt.dist = dist;
  opt.sigma = sigma;
  opt.alpha = alpha;
  opt.diurnal_amp = diurnal_amp;
  opt.diurnal_period_s = diurnal_period_s;
  UserLoadModel model(opt);

  // Legacy single-user pacing: one tick every 1/kPrecision s; the pacer
  // carries the rate/kPrecision remainder across ticks so the offered
  // load matches --rate exactly at EVERY rate >= 1 (truncation used to
  // under-deliver; see rate_pacer.hpp).  Sub-kPrecision rates emit
  // empty ticks in between 1-tx bursts.
  RatePacer pacer{rate, kPrecision};
  std::mt19937_64 rng(seed);
  uint64_t r = rng();
  uint64_t counter = 0;
  Bytes tx(size, 0);
  // graftingress signing state: the keyring derives (and LRU-caches)
  // per-user keypairs from --seed; forgery is a seeded coin flip on
  // FILLER txs only — sample txs must commit for the latency join.
  TxKeyring keyring(seed);
  std::bernoulli_distribution forge(
      std::min(1.0, std::max(0.0, forge_pct / 100.0)));
  uint64_t nonce = 0;
  uint64_t forged_total = 0;
  uint64_t total_sent = 0;
  uint64_t ticks = 0;
  std::vector<size_t> burst_users;

  // NOTE: This log entry is used to compute performance.
  LOG_INFO("client") << "Start sending transactions";

  auto interval = std::chrono::milliseconds(kBurstDurationMs);
  auto start = std::chrono::steady_clock::now();
  auto next_tick = start + interval;
  auto legacy_busy_until = start;
  while (true) {
    std::this_thread::sleep_until(next_tick);
    next_tick += interval;
    auto now = std::chrono::steady_clock::now();
    double now_s = std::chrono::duration<double>(now - start).count();
    int64_t hint = busy_hint_ms.exchange(-1, std::memory_order_acquire);
    uint64_t burst;
    burst_users.clear();
    if (users > 1) {
      if (hint >= 0) model.busy(now_s, double(hint) / 1e3);
      burst = model.arrivals(now_s, sign ? &burst_users : nullptr);
    } else {
      if (hint >= 0) {
        legacy_busy_until =
            now + std::chrono::milliseconds(std::max<int64_t>(hint, 20));
      }
      if (now < legacy_busy_until) continue;  // whole-stream pause
      burst = pacer.next_burst();
    }
    if (++ticks % (5 * kPrecision) == 0) {
      // NOTE: This log entry is used to compute performance (per-shard
      // fairness accounting; cumulative, ~every 5 s).
      LOG_INFO("client") << "Sent " << total_sent << " transactions";
    }
    if (burst == 0) continue;  // no arrivals due on this tick
    auto burst_start = std::chrono::steady_clock::now();
    for (uint64_t x = 0; x < burst; x++) {
      uint64_t id;
      uint8_t marker;
      if (x == counter % burst) {
        id = sample_offset + counter;
        // NOTE: This log entry is used to compute performance.
        LOG_INFO("client") << "Sending sample transaction " << id;
        marker = kTxMarkerSample;  // sample txs start with 0
      } else {
        marker = kTxMarkerFiller;  // standard txs start with 1
        id = ++r;
      }
      bool forged = false;
      if (sign && marker == kTxMarkerFiller && forge_pct > 0.0 &&
          forge(rng)) {
        marker = kTxMarkerForged;
        forged = true;
      }
      tx[0] = marker;
      for (int b = 0; b < 8; b++) tx[1 + b] = (id >> (8 * (7 - b))) & 0xFF;
      bool ok;
      if (sign) {
        size_t user = size_t(user_offset) +
                      (x < burst_users.size() ? burst_users[x] : 0);
        Bytes frame = build_signed_tx(keyring.get(user), nonce++,
                                      tx.data(), tx.size(),
                                      /*flip_sig_bit=*/forged);
        if (forged) {
          forged_total++;
          // NOTE: This log entry is used to compute performance
          // (cumulative; first + every kForgeLogEvery-th).
          if (forged_total == 1 || forged_total % kForgeLogEvery == 0) {
            LOG_INFO("client") << "Forged transaction sent ("
                               << forged_total << " total)";
          }
        }
        ok = sock->write_frame(frame);
      } else {
        ok = sock->write_frame(tx);
      }
      if (!ok) {
        LOG_WARN("client") << "Failed to send transaction";
        return 1;
      }
      total_sent++;
    }
    auto elapsed = std::chrono::duration_cast<std::chrono::milliseconds>(
        std::chrono::steady_clock::now() - burst_start);
    if (elapsed.count() > int64_t(kBurstDurationMs)) {
      // NOTE: This log entry is used to compute performance.
      LOG_WARN("client") << "Transaction rate too high for this client";
    }
    counter++;
  }
}

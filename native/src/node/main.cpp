// node binary: keys / run / deploy subcommands (node/src/main.rs:16-154 in
// the reference).
//   node keys --filename FILE
//   node run --keys FILE --committee FILE --store PATH [--parameters FILE] [-v...]
//   node deploy NODES  (local in-process testbed on ports 25000+)
#include <cctype>
#include <chrono>
#include <csignal>
#include <cstring>
#include <iostream>
#include <string>
#include <thread>
#include <vector>

#include "common/log.hpp"
#include "node/config.hpp"
#include "node/node.hpp"

using namespace hotstuff;

namespace {

volatile std::sig_atomic_t g_shutdown = 0;

void on_signal(int) { g_shutdown = 1; }

void install_signal_handlers() {
  std::signal(SIGTERM, on_signal);
  std::signal(SIGINT, on_signal);
}

// Drain the commit channel until the node's channels close or a signal
// arrives (polling the async-signal-safe flag every 200 ms).
void drain_commits(node::Node& node) {
  auto ch = node.commit_channel();
  while (!g_shutdown) {
    consensus::Block block;
    auto status = ch->recv_until(&block,
                                 std::chrono::steady_clock::now() +
                                     std::chrono::milliseconds(200));
    if (status == RecvStatus::kClosed) return;
  }
}

struct Args {
  std::vector<std::string> positional;
  std::string keys, committee, store, parameters, filename, nodes;
  int verbosity = 0;

  static Args parse(int argc, char** argv) {
    Args a;
    for (int i = 1; i < argc; i++) {
      std::string arg = argv[i];
      auto next = [&]() -> std::string {
        if (i + 1 >= argc) {
          std::cerr << "missing value for " << arg << "\n";
          std::exit(2);
        }
        return argv[++i];
      };
      if (arg == "--keys") a.keys = next();
      else if (arg == "--committee") a.committee = next();
      else if (arg == "--store") a.store = next();
      else if (arg == "--parameters") a.parameters = next();
      else if (arg == "--filename") a.filename = next();
      else if (arg == "--nodes") a.nodes = next();
      else if (arg[0] == '-' && arg.find_first_not_of('v', 1) ==
               std::string::npos && arg.size() > 1) {
        a.verbosity += int(arg.size()) - 1;
      } else if (arg.size() > 1 && arg[0] == '-' &&
                 !std::isdigit(static_cast<unsigned char>(arg[1]))) {
        std::cerr << "unknown flag " << arg << "\n";
        std::exit(2);
      } else a.positional.push_back(arg);
    }
    return a;
  }
};

void apply_verbosity(int v) {
  // -v: info (default), -vv: debug (main.rs:43-53 analogue; benchmark logs
  // need info level).
  log_set_level(v >= 2 ? LogLevel::kDebug : LogLevel::kInfo);
}

int cmd_keys(const Args& args) {
  if (args.filename.empty()) {
    std::cerr << "node keys --filename FILE\n";
    return 2;
  }
  node::Secret::generate().write(args.filename);
  return 0;
}

int cmd_run(const Args& args) {
  if (args.keys.empty() || args.committee.empty() || args.store.empty()) {
    std::cerr << "node run --keys FILE --committee FILE --store PATH "
                 "[--parameters FILE]\n";
    return 2;
  }
  install_signal_handlers();
  auto node = node::Node::create(args.committee, args.keys, args.store,
                                 args.parameters);
  drain_commits(*node);
  LOG_INFO("node::main") << "shutting down";
  node->stop();
  LOG_INFO("node::main") << "shutdown complete";
  return 0;
}

int cmd_deploy(const Args& args) {
  std::string count = args.nodes;
  if (count.empty() && args.positional.size() >= 2) {
    count = args.positional[1];
  }
  size_t nodes = 0;
  try {
    size_t pos = 0;
    nodes = std::stoul(count, &pos);
    if (pos != count.size()) nodes = 0;  // trailing garbage: reject
  } catch (const std::exception&) {
    nodes = 0;
  }
  if (nodes < 1 || nodes > 128) {
    std::cerr << "usage: node deploy NODES | node deploy --nodes N "
                 "(1 <= N <= 128)\n";
    return 2;
  }
  uint16_t base_port = 25000;

  // Generate keys + committee (main.rs:94-154 analogue).
  std::vector<node::Secret> secrets;
  for (size_t i = 0; i < nodes; i++) secrets.push_back(node::Secret::generate());

  std::map<PublicKey, consensus::Authority> cons_auth;
  std::map<PublicKey, mempool::Authority> memp_auth;
  uint16_t port = base_port;
  for (const auto& s : secrets) {
    consensus::Authority ca;
    ca.stake = 1;
    ca.address = Address{"127.0.0.1", port++};
    cons_auth.emplace(s.name, ca);
    mempool::Authority ma;
    ma.stake = 1;
    ma.transactions_address = Address{"127.0.0.1", port++};
    ma.mempool_address = Address{"127.0.0.1", port++};
    memp_auth.emplace(s.name, ma);
  }
  node::Committee committee;
  committee.consensus = consensus::Committee(std::move(cons_auth), 1);
  committee.mempool = mempool::Committee(std::move(memp_auth), 1);
  committee.write(".committee.json");

  std::vector<std::unique_ptr<node::Node>> instances;
  for (size_t i = 0; i < nodes; i++) {
    std::string key_file = ".node-" + std::to_string(i) + ".json";
    secrets[i].write(key_file);
    std::string store_path = ".db-" + std::to_string(i);
    instances.push_back(node::Node::create(".committee.json", key_file,
                                           store_path, ""));
  }
  install_signal_handlers();
  std::vector<std::thread> sinks;
  for (auto& n : instances) {
    sinks.emplace_back([&n] { n->analyze_block(); });
  }
  while (!g_shutdown) {
    std::this_thread::sleep_for(std::chrono::milliseconds(200));
  }
  for (auto& n : instances) n->stop();
  for (auto& t : sinks) t.join();
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  Args args = Args::parse(argc, argv);
  apply_verbosity(args.verbosity);
  if (args.positional.empty()) {
    std::cerr << "usage: node {keys|run|deploy} ...\n";
    return 2;
  }
  const std::string& cmd = args.positional[0];
  try {
    if (cmd == "keys") return cmd_keys(args);
    if (cmd == "run") return cmd_run(args);
    if (cmd == "deploy") return cmd_deploy(args);
  } catch (const std::exception& e) {
    LOG_ERROR("node::main") << e.what();
    return 1;
  }
  std::cerr << "unknown command: " << cmd << "\n";
  return 2;
}

// Node-level config files (JSON, harness-generated): keypair, combined
// committee (consensus + mempool address books), combined parameters
// (node/src/config.rs:22-87 in the reference). The TPU addition: an
// optional "tpu_sidecar" address in parameters routes QC batch verification
// to the JAX verify sidecar.
#pragma once

#include <optional>
#include <string>
#include <vector>

#include "consensus/config.hpp"
#include "crypto/crypto.hpp"
#include "mempool/config.hpp"

namespace hotstuff {
namespace node {

struct Secret {
  PublicKey name;
  SecretKey secret;
  Bytes bls_secret;  // optional 48-byte scalar (scheme=bls deployments)

  static Secret generate();
  static Secret read(const std::string& path);
  void write(const std::string& path) const;
};

struct Committee {
  consensus::Committee consensus;
  mempool::Committee mempool;

  static Committee read(const std::string& path);
  void write(const std::string& path) const;
};

struct Parameters {
  consensus::Parameters consensus;
  mempool::Parameters mempool;
  std::optional<Address> tpu_sidecar;
  // graftfleet: ordered sidecar endpoint list (first = primary).  The
  // JSON "tpu_sidecar" key accepts a single address string (legacy) or
  // a list of them; tpu_sidecar above always mirrors the first entry so
  // pre-fleet call sites keep working.
  std::vector<Address> tpu_sidecars;
  // graftfleet: tenant id announced on each sidecar connection via the
  // protocol-v6 HELLO (empty = the sidecar's default tenant).
  std::string tpu_tenant;
  // "ed25519" (default) or "bls" — the reference's branch-level scheme
  // choice as a runtime knob (README.md:1-3).
  std::string scheme = "ed25519";
  // grafttrace: emit machine-parseable TRACE span lines at the
  // consensus hot-path stages (hotstuff_tpu/obs/trace.py mines them).
  bool trace = false;

  static Parameters read(const std::string& path);
  static Parameters from_json(const Json& j);
};

}  // namespace node
}  // namespace hotstuff

#include "node/node.hpp"

#include "common/log.hpp"
#include "common/metrics.hpp"
#include "crypto/sidecar_client.hpp"

namespace hotstuff {
namespace node {

std::unique_ptr<Node> Node::create(const std::string& committee_file,
                                   const std::string& key_file,
                                   const std::string& store_path,
                                   const std::string& parameters_file) {
  Committee committee = Committee::read(committee_file);
  Secret secret = Secret::read(key_file);
  Parameters parameters = parameters_file.empty()
                              ? Parameters{}
                              : Parameters::read(parameters_file);

  auto node = std::unique_ptr<Node>(new Node());
  node->name_ = secret.name;
  node->store_ = Store::open(store_path);
  // Batches get their own store actor (graftdag).  A store is a single
  // worker thread behind a bounded command queue, and Store::read is a
  // blocking round trip through that queue: with one shared store, the
  // core's small metadata reads (parent blocks on the commit walk, state
  // flushes) sat behind a firehose of ~500 KB batch writes and stretched
  // to seconds under load, cascading into consensus timeouts.  Splitting
  // the WALs keeps the consensus critical path off the bulk-data queue.
  node->batch_store_ = Store::open(
      store_path.empty() ? store_path : store_path + "-batches");
  node->commit_ = make_channel<consensus::Block>();

  // grafttrace: span lines are opt-in per deployment; the harness turns
  // them on for benched runs so commit latency is attributable per
  // stage (obs/trace.py stitches them into per-block critical paths).
  // graftscope rides the same flag: the 1 Hz METRICS sampler (commit
  // rate, ingress fill, BUSY sheds, breaker state) starts with tracing
  // so a benched run's node side lands next to the sidecar series in
  // logs/metrics.jsonl.
  if (parameters.trace) {
    log_set_trace(true);
    LOG_INFO("node::node") << "Consensus tracing enabled (TRACE spans)";
    NodeMetrics::instance().start();
  }

  // Device dispatch for QC batch verification (process-wide; the crypto
  // layer falls back to host verify when absent/unreachable).
  if (!parameters.tpu_sidecars.empty()) {
    // graftfleet: ordered endpoint list (first = primary); the verifier
    // fails over down the list and keeps host verify as the last rung.
    TpuVerifier::install(std::make_unique<TpuVerifier>(
        parameters.tpu_sidecars, parameters.tpu_tenant));
  }

  // Scheme knob (the reference's EdDSA-vs-BLS branch choice as runtime
  // config). BLS has no C++ pairing or signer: the sidecar is mandatory.
  if (parameters.scheme == "bls") {
    if (!parameters.tpu_sidecar) {
      throw std::runtime_error("scheme=bls requires a tpu_sidecar address");
    }
    if (secret.bls_secret.size() != 48) {
      throw std::runtime_error("scheme=bls requires bls_secret in the key "
                               "file");
    }
    auto ctx = std::make_unique<BlsContext>();
    ctx->secret = secret.bls_secret;
    for (const auto& [auth_name, auth] : committee.consensus.authorities()) {
      if (auth.bls_pubkey.size() != 96) {
        throw std::runtime_error(
            "scheme=bls requires bls_pubkey for every authority");
      }
      ctx->public_keys.emplace(auth_name, auth.bls_pubkey);
    }
    BlsContext::install(std::move(ctx));
    set_scheme(Scheme::kBls);
    LOG_INFO("node::node") << "Signature scheme: bls (sidecar-backed)";
  } else {
    set_scheme(Scheme::kEd25519);
  }

  SignatureService signature_service(secret.secret);

  // Effectively unbounded (like the mempool synchronizer's payload-waiter
  // channel): a payload ref is small (digest + cert handle), and the
  // mempool's inlined peer-batch path try_sends here AFTER the batch is
  // stored and ACKed — a bounded channel would drop the ref under a
  // consensus backlog and the stored batch could never be proposed by
  // this node (round-5 ADVICE.md).
  auto tx_mempool_to_consensus = make_channel<mempool::PayloadRef>(SIZE_MAX);
  auto tx_consensus_to_mempool =
      make_channel<mempool::ConsensusMempoolMessage>();

  node->mempool_ = mempool::Mempool::spawn(
      secret.name, secret.secret, committee.mempool, parameters.mempool,
      node->batch_store_, tx_consensus_to_mempool, tx_mempool_to_consensus);

  node->consensus_ = consensus::Consensus::spawn(
      secret.name, committee.consensus, parameters.consensus,
      signature_service, node->store_, node->batch_store_,
      tx_mempool_to_consensus, tx_consensus_to_mempool, node->commit_);

  LOG_INFO("node::node")
      << "Node " << secret.name.to_base64() << " successfully booted";
  return node;
}

void Node::analyze_block() {
  while (auto block = commit_->recv()) {
    // Sink committed blocks (the application layer goes here).
    (void)block;
  }
}

void Node::stop() {
  // Consensus first (it closes tx_commit and stops proposing), then the
  // mempool; the store and signature service wind down with their last
  // handles. The reference gets the equivalent ordering from tokio runtime
  // drop; here it is explicit so `node` exits cleanly on SIGTERM and the
  // in-process e2e test tears down without leaking threads.  The METRICS
  // sampler goes first — its gauges read the mempool's ingress gate.
  NodeMetrics::instance().stop();
  if (consensus_) consensus_->stop();
  if (mempool_) mempool_->stop();
}

}  // namespace node
}  // namespace hotstuff

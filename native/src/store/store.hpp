// Storage actor: a single thread owning the key-value state, commanded over
// a channel — the same shape as the reference's Store task wrapping RocksDB
// (store/src/lib.rs:15-93), including the notify_read obligation contract
// (register a waiter for a key; fulfilled by a later write).  Backing medium
// is an in-memory map with an append-only write-ahead log replayed on open
// (this image has no RocksDB; durability semantics — every batch/block
// persisted before use — are preserved).
#pragma once

#include <memory>
#include <optional>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common/bytes.hpp"
#include "common/channel.hpp"

namespace hotstuff {

class Store {
 public:
  // Opens (creating if needed) the store at `path` (a directory; the WAL
  // lives at path + "/wal"). Empty path = purely in-memory (tests).
  // The WAL compacts once appended bytes exceed `compact_bytes` AND 4x the
  // live map size (compact_bytes <= 0 disables compaction).
  static Store open(const std::string& path,
                    int64_t compact_bytes = 64 * 1024 * 1024);

  Store() = default;  // null handle; open() returns the real one

  void write(const Bytes& key, const Bytes& value);
  std::optional<Bytes> read(const Bytes& key);

  // Returns a oneshot fulfilled with the value as soon as the key exists
  // (immediately if it already does).
  Oneshot<Bytes> notify_read(const Bytes& key);

  bool valid() const { return static_cast<bool>(ch_); }

 private:
  struct Command {
    enum class Kind { kWrite, kRead, kNotifyRead } kind;
    Bytes key;
    Bytes value;                          // write
    Oneshot<std::optional<Bytes>> read_reply;  // read
    Oneshot<Bytes> notify_reply;          // notify_read
  };

  ChannelPtr<Command> ch_;
  std::shared_ptr<std::thread> worker_;
};

}  // namespace hotstuff

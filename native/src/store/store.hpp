// Storage actor: a single thread owning the key-value state, commanded over
// a channel — the same shape as the reference's Store task wrapping RocksDB
// (store/src/lib.rs:15-93), including the notify_read obligation contract
// (register a waiter for a key; fulfilled by a later write).  Backing medium
// is an append-only write-ahead log with an in-memory OFFSET INDEX and an
// LRU-bounded resident value cache: state larger than RAM stays readable
// (values spill to the WAL and are pread back on demand), preserving the
// RocksDB role the reference relies on (this image has no RocksDB;
// durability semantics — every batch/block persisted before use — are
// preserved).
#pragma once

#include <memory>
#include <optional>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common/bytes.hpp"
#include "common/channel.hpp"

namespace hotstuff {

class Store {
 public:
  // Resident-cache and compaction telemetry (testing/observability).
  struct Stats {
    size_t keys = 0;            // total keys (index size)
    size_t resident_bytes = 0;  // bytes of values held in memory
    size_t wal_bytes = 0;       // current WAL file size
  };

  // Opens (creating if needed) the store at `path` (a directory; the WAL
  // lives at path + "/wal"). Empty path = purely in-memory (tests).
  // The WAL compacts once appended bytes exceed `compact_bytes` AND 4x the
  // live map size (compact_bytes <= 0 disables compaction).
  // `resident_bytes` caps the in-memory value cache when disk-backed:
  // least-recently-used values are dropped from memory (NOT from disk)
  // past the cap, so a long benchmark's RSS stays bounded while every
  // key remains readable.  <= 0 disables the cap.
  static Store open(const std::string& path,
                    int64_t compact_bytes = 64 * 1024 * 1024,
                    int64_t resident_bytes = 128 * 1024 * 1024);

  Store() = default;  // null handle; open() returns the real one

  void write(const Bytes& key, const Bytes& value);
  // Non-blocking write for reactor-thread callers: false = store actor
  // backlogged (command channel full), nothing enqueued and *value is
  // left INTACT so the caller can divert it to an overflow lane.  A
  // reactor must never block on the store; on success the value is moved,
  // not copied (it can be ~500 KB of batch).
  bool try_write(const Bytes& key, Bytes* value);
  std::optional<Bytes> read(const Bytes& key);

  // Returns a oneshot fulfilled with the value as soon as the key exists
  // (immediately if it already does).
  Oneshot<Bytes> notify_read(const Bytes& key);

  Stats stats();

  bool valid() const { return static_cast<bool>(ch_); }

 private:
  struct Command {
    enum class Kind { kWrite, kRead, kNotifyRead, kStats } kind;
    Bytes key;
    Bytes value;                          // write
    Oneshot<std::optional<Bytes>> read_reply;  // read
    Oneshot<Bytes> notify_reply;          // notify_read
    Oneshot<Stats> stats_reply;           // stats
  };

  // graftsync: the handle is freely copyable across threads — all
  // storage state (index, resident cache, WAL handle) is OWNED_BY the
  // worker thread inside the .cpp lambda; these two members are the
  // only shared surface and both synchronize themselves.
  ChannelPtr<Command> ch_;  // SHARED_OK(Channel is internally locked)
  std::shared_ptr<std::thread> worker_;  // SHARED_OK(set in open(),
                                         // then read-only)
};

}  // namespace hotstuff

#include "store/store.hpp"

#include <sys/stat.h>

#include <cstdio>
#include <stdexcept>

#include "common/log.hpp"

namespace hotstuff {

namespace {

// WAL record: u32 LE key len | key | u32 LE value len | value.
void wal_append(std::FILE* f, const Bytes& key, const Bytes& value) {
  auto put_u32 = [&](uint32_t v) {
    uint8_t b[4] = {uint8_t(v), uint8_t(v >> 8), uint8_t(v >> 16),
                    uint8_t(v >> 24)};
    std::fwrite(b, 1, 4, f);
  };
  put_u32(static_cast<uint32_t>(key.size()));
  std::fwrite(key.data(), 1, key.size(), f);
  put_u32(static_cast<uint32_t>(value.size()));
  std::fwrite(value.data(), 1, value.size(), f);
  std::fflush(f);
}

void wal_replay(const std::string& path,
                std::unordered_map<Bytes, Bytes, BytesHash>* map) {
  std::FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return;
  auto get_u32 = [&](uint32_t* v) {
    uint8_t b[4];
    if (std::fread(b, 1, 4, f) != 4) return false;
    *v = uint32_t(b[0]) | (uint32_t(b[1]) << 8) | (uint32_t(b[2]) << 16) |
         (uint32_t(b[3]) << 24);
    return true;
  };
  while (true) {
    uint32_t klen, vlen;
    if (!get_u32(&klen)) break;
    Bytes key(klen);
    if (std::fread(key.data(), 1, klen, f) != klen) break;
    if (!get_u32(&vlen)) break;
    Bytes value(vlen);
    if (std::fread(value.data(), 1, vlen, f) != vlen) break;
    (*map)[std::move(key)] = std::move(value);
  }
  std::fclose(f);
}

}  // namespace

Store Store::open(const std::string& path) {
  auto ch = make_channel<Command>();

  std::FILE* wal = nullptr;
  auto map = std::make_shared<std::unordered_map<Bytes, Bytes, BytesHash>>();
  if (!path.empty()) {
    ::mkdir(path.c_str(), 0755);
    std::string wal_path = path + "/wal";
    wal_replay(wal_path, map.get());
    wal = std::fopen(wal_path.c_str(), "ab");
    if (!wal) throw std::runtime_error("cannot open WAL at " + wal_path);
  }

  Store s;
  s.ch_ = ch;
  s.worker_ = std::shared_ptr<std::thread>(
      new std::thread([ch, map, wal] {
        // Obligations: key -> oneshots fulfilled by a future write
        // (store/src/lib.rs:36-57 semantics).
        std::unordered_map<Bytes, std::vector<Oneshot<Bytes>>, BytesHash>
            obligations;
        while (auto cmd = ch->recv()) {
          switch (cmd->kind) {
            case Command::Kind::kWrite: {
              if (wal) wal_append(wal, cmd->key, cmd->value);
              (*map)[cmd->key] = cmd->value;
              auto it = obligations.find(cmd->key);
              if (it != obligations.end()) {
                for (auto& waiter : it->second) waiter.set(cmd->value);
                obligations.erase(it);
              }
              break;
            }
            case Command::Kind::kRead: {
              auto it = map->find(cmd->key);
              cmd->read_reply.set(it == map->end()
                                      ? std::nullopt
                                      : std::optional<Bytes>(it->second));
              break;
            }
            case Command::Kind::kNotifyRead: {
              auto it = map->find(cmd->key);
              if (it != map->end()) {
                cmd->notify_reply.set(it->second);
              } else {
                obligations[cmd->key].push_back(cmd->notify_reply);
              }
              break;
            }
          }
        }
        if (wal) std::fclose(wal);
      }),
      [ch](std::thread* t) {
        ch->close();
        t->join();
        delete t;
      });
  return s;
}

void Store::write(const Bytes& key, const Bytes& value) {
  Command cmd;
  cmd.kind = Command::Kind::kWrite;
  cmd.key = key;
  cmd.value = value;
  ch_->send(std::move(cmd));
}

std::optional<Bytes> Store::read(const Bytes& key) {
  Command cmd;
  cmd.kind = Command::Kind::kRead;
  cmd.key = key;
  auto reply = cmd.read_reply;
  if (!ch_->send(std::move(cmd))) return std::nullopt;
  return reply.wait();
}

Oneshot<Bytes> Store::notify_read(const Bytes& key) {
  Command cmd;
  cmd.kind = Command::Kind::kNotifyRead;
  cmd.key = key;
  auto reply = cmd.notify_reply;
  ch_->send(std::move(cmd));
  return reply;
}

}  // namespace hotstuff

#include "store/store.hpp"

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <stdexcept>

#include "common/log.hpp"

namespace hotstuff {

namespace {

// WAL record: u32 LE key len | key | u32 LE value len | value.
// Returns the appended byte count.  `flush` pushes the record to the
// kernel (process-crash durability; power-loss durability would need
// fdatasync per record, which the consensus workload cannot afford —
// matching the reference, whose RocksDB default WAL is also not fsync'd
// per write).
size_t wal_append(std::FILE* f, const Bytes& key, const Bytes& value,
                  bool flush = true) {
  auto put_u32 = [&](uint32_t v) {
    uint8_t b[4] = {uint8_t(v), uint8_t(v >> 8), uint8_t(v >> 16),
                    uint8_t(v >> 24)};
    std::fwrite(b, 1, 4, f);
  };
  put_u32(static_cast<uint32_t>(key.size()));
  std::fwrite(key.data(), 1, key.size(), f);
  put_u32(static_cast<uint32_t>(value.size()));
  std::fwrite(value.data(), 1, value.size(), f);
  if (flush) std::fflush(f);
  return 8 + key.size() + value.size();
}

// Rewrite the WAL as a snapshot of the live map: write wal.tmp, sync,
// open the fresh append handle on the snapshot, atomically rename it over
// the old file, sync the directory.  Every fallible step happens BEFORE
// the rename (the append fd follows the inode through it), so failure can
// only skip the compaction and keep the old handle — never strand the
// store memory-only, which would let the consensus core's vote-watermark
// persistence "succeed" against the in-memory map and double-vote after a
// crash.
struct CompactResult {
  std::FILE* wal;
  size_t snapshot_bytes = 0;
  bool ok = false;
};

CompactResult wal_compact(
    std::FILE* old_wal, const std::string& wal_path,
    const std::string& dir_path,
    const std::unordered_map<Bytes, Bytes, BytesHash>& map) {
  const std::string tmp = wal_path + ".tmp";
  std::FILE* f = std::fopen(tmp.c_str(), "wb");
  if (!f) {
    LOG_WARN("store") << "compaction skipped: cannot open " << tmp;
    return {old_wal};
  }
  size_t bytes = 0;
  for (const auto& [k, v] : map)
    bytes += wal_append(f, k, v, /*flush=*/false);
  std::fflush(f);
  ::fsync(::fileno(f));  // snapshot on disk before it replaces the WAL
  std::fclose(f);
  std::FILE* fresh = std::fopen(tmp.c_str(), "ab");
  if (!fresh) {
    LOG_WARN("store") << "compaction skipped: cannot reopen snapshot";
    std::remove(tmp.c_str());
    return {old_wal};
  }
  if (std::rename(tmp.c_str(), wal_path.c_str()) != 0) {
    LOG_WARN("store") << "compaction skipped: rename failed";
    std::fclose(fresh);
    std::remove(tmp.c_str());
    return {old_wal};
  }
  int dfd = ::open(dir_path.c_str(), O_RDONLY | O_DIRECTORY);
  if (dfd >= 0) {
    ::fsync(dfd);  // persist the rename itself
    ::close(dfd);
  }
  std::fclose(old_wal);
  LOG_INFO("store") << "WAL compacted to " << bytes << " bytes";
  return {fresh, bytes, true};
}

void wal_replay(const std::string& path,
                std::unordered_map<Bytes, Bytes, BytesHash>* map) {
  std::FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return;
  auto get_u32 = [&](uint32_t* v) {
    uint8_t b[4];
    if (std::fread(b, 1, 4, f) != 4) return false;
    *v = uint32_t(b[0]) | (uint32_t(b[1]) << 8) | (uint32_t(b[2]) << 16) |
         (uint32_t(b[3]) << 24);
    return true;
  };
  while (true) {
    uint32_t klen, vlen;
    if (!get_u32(&klen)) break;
    Bytes key(klen);
    if (std::fread(key.data(), 1, klen, f) != klen) break;
    if (!get_u32(&vlen)) break;
    Bytes value(vlen);
    if (std::fread(value.data(), 1, vlen, f) != vlen) break;
    (*map)[std::move(key)] = std::move(value);
  }
  std::fclose(f);
}

}  // namespace

Store Store::open(const std::string& path, int64_t compact_bytes) {
  auto ch = make_channel<Command>();

  std::FILE* wal = nullptr;
  std::string wal_path;
  auto map = std::make_shared<std::unordered_map<Bytes, Bytes, BytesHash>>();
  if (!path.empty()) {
    ::mkdir(path.c_str(), 0755);
    wal_path = path + "/wal";
    wal_replay(wal_path, map.get());
    wal = std::fopen(wal_path.c_str(), "ab");
    if (!wal) throw std::runtime_error("cannot open WAL at " + wal_path);
  }

  Store s;
  s.ch_ = ch;
  s.worker_ = std::shared_ptr<std::thread>(
      new std::thread([ch, map, wal, wal_path, path_dir = path,
                       compact_bytes]() mutable {
        // Obligations: key -> oneshots fulfilled by a future write
        // (store/src/lib.rs:36-57 semantics).
        std::unordered_map<Bytes, std::vector<Oneshot<Bytes>>, BytesHash>
            obligations;
        // Compaction accounting: bytes appended since the last rewrite,
        // and the approximate live (retained) byte footprint.
        size_t appended = 0, live = 0;
        for (const auto& [k, v] : *map) live += 8 + k.size() + v.size();
        if (wal) {
          // "ab" streams report position 0 until the first write; seek to
          // find the real replayed-file size (dead bytes included).
          std::fseek(wal, 0, SEEK_END);
          long pos = std::ftell(wal);
          appended = pos > 0 ? size_t(pos) : live;
        }
        while (auto cmd = ch->recv()) {
          switch (cmd->kind) {
            case Command::Kind::kWrite: {
              if (wal) {
                appended += wal_append(wal, cmd->key, cmd->value);
                auto it0 = map->find(cmd->key);
                if (it0 != map->end())
                  live -= 8 + it0->first.size() + it0->second.size();
                live += 8 + cmd->key.size() + cmd->value.size();
              }
              // Map update BEFORE any compaction: the snapshot must
              // include the record just appended, or the rename drops it.
              (*map)[cmd->key] = cmd->value;
              if (wal && compact_bytes > 0 &&
                  appended > size_t(compact_bytes) && appended > 4 * live) {
                auto res = wal_compact(wal, wal_path, path_dir, *map);
                wal = res.wal;
                if (res.ok) {  // failure keeps counters; retry later
                  appended = res.snapshot_bytes;
                  live = res.snapshot_bytes;
                }
              }
              auto it = obligations.find(cmd->key);
              if (it != obligations.end()) {
                for (auto& waiter : it->second) waiter.set(cmd->value);
                obligations.erase(it);
              }
              break;
            }
            case Command::Kind::kRead: {
              auto it = map->find(cmd->key);
              cmd->read_reply.set(it == map->end()
                                      ? std::nullopt
                                      : std::optional<Bytes>(it->second));
              break;
            }
            case Command::Kind::kNotifyRead: {
              auto it = map->find(cmd->key);
              if (it != map->end()) {
                cmd->notify_reply.set(it->second);
              } else {
                obligations[cmd->key].push_back(cmd->notify_reply);
              }
              break;
            }
          }
        }
        if (wal) std::fclose(wal);
      }),
      [ch](std::thread* t) {
        ch->close();
        t->join();
        delete t;
      });
  return s;
}

void Store::write(const Bytes& key, const Bytes& value) {
  Command cmd;
  cmd.kind = Command::Kind::kWrite;
  cmd.key = key;
  cmd.value = value;
  ch_->send(std::move(cmd));
}

std::optional<Bytes> Store::read(const Bytes& key) {
  Command cmd;
  cmd.kind = Command::Kind::kRead;
  cmd.key = key;
  auto reply = cmd.read_reply;
  if (!ch_->send(std::move(cmd))) return std::nullopt;
  return reply.wait();
}

Oneshot<Bytes> Store::notify_read(const Bytes& key) {
  Command cmd;
  cmd.kind = Command::Kind::kNotifyRead;
  cmd.key = key;
  auto reply = cmd.notify_reply;
  ch_->send(std::move(cmd));
  return reply;
}

}  // namespace hotstuff

#include "store/store.hpp"

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <array>
#include <chrono>
#include <cstdio>
#include <list>
#include <stdexcept>

#include "common/log.hpp"

namespace hotstuff {

namespace {

// CRC-32 (IEEE 802.3, reflected poly 0xEDB88320), incremental: feed the
// previous return value back in as `crc` (seed 0).  Table-built once.
uint32_t crc32_update(uint32_t crc, const uint8_t* data, size_t len) {
  static const std::array<uint32_t, 256> table = [] {
    std::array<uint32_t, 256> t{};
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) {
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      }
      t[i] = c;
    }
    return t;
  }();
  crc = ~crc;
  for (size_t i = 0; i < len; ++i) {
    crc = table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  }
  return ~crc;
}

// Per-record checksum over key bytes then value bytes (the length
// prefixes are implicitly covered: a flipped length misframes the next
// read and fails this CRC or the tail check).
uint32_t record_crc(const Bytes& key, const Bytes& value) {
  return crc32_update(crc32_update(0, key.data(), key.size()), value.data(),
                      value.size());
}

// WAL record: u32 LE key len | key | u32 LE value len | value |
// u32 LE CRC-32 of key+value.  The checksum sits at the TAIL so value
// offsets stay record_start + 8 + klen — bit rot inside a record is
// caught at replay, not silently served to the consensus core.
// Returns the appended byte count, or nullopt if any write failed
// (ENOSPC/EIO): the offset index must never point at a record that is
// not provably on disk.  `flush` pushes the record to the kernel
// (process-crash durability; power-loss durability would need fdatasync
// per record, which the consensus workload cannot afford — matching the
// reference, whose RocksDB default WAL is also not fsync'd per write).
// Flushing is also what makes spilled values pread-able: evicted reads
// go through the page cache, never through this stream's user-space
// buffer.
std::optional<size_t> wal_append(std::FILE* f, const Bytes& key,
                                 const Bytes& value, bool flush = true) {
  bool ok = true;
  auto put_u32 = [&](uint32_t v) {
    uint8_t b[4] = {uint8_t(v), uint8_t(v >> 8), uint8_t(v >> 16),
                    uint8_t(v >> 24)};
    ok &= std::fwrite(b, 1, 4, f) == 4;
  };
  put_u32(static_cast<uint32_t>(key.size()));
  ok &= std::fwrite(key.data(), 1, key.size(), f) == key.size();
  put_u32(static_cast<uint32_t>(value.size()));
  ok &= std::fwrite(value.data(), 1, value.size(), f) == value.size();
  put_u32(record_crc(key, value));
  if (flush) ok &= std::fflush(f) == 0;
  if (!ok) return std::nullopt;
  return 12 + key.size() + value.size();
}

// All storage state, owned by the worker thread after open().
//
// Memory model (the RocksDB-role requirement, store/src/lib.rs:28): the
// INDEX (key -> WAL offset of the value) is the only per-key state that
// must stay in memory; VALUES live in an LRU cache bounded by
// `resident_cap` and spill to the WAL — a read of an evicted value is one
// pread.  A state larger than RAM therefore stays fully readable with
// bounded RSS.
class Backing {
 public:
  Backing(const std::string& path, int64_t compact_bytes,
          int64_t resident_cap)
      : compact_bytes_(compact_bytes),
        resident_cap_(resident_cap > 0 ? size_t(resident_cap) : 0) {
    if (path.empty()) return;  // purely in-memory (tests)
    ::mkdir(path.c_str(), 0755);
    dir_path_ = path;
    wal_path_ = path + "/wal";
    replay_();
    wal_ = std::fopen(wal_path_.c_str(), "ab");
    if (!wal_) throw std::runtime_error("cannot open WAL at " + wal_path_);
    read_fd_ = ::open(wal_path_.c_str(), O_RDONLY);
    if (read_fd_ < 0) {
      std::fclose(wal_);
      throw std::runtime_error("cannot open WAL for reads at " + wal_path_);
    }
  }

  ~Backing() {
    if (wal_) std::fclose(wal_);
    if (read_fd_ >= 0) ::close(read_fd_);
  }

  Backing(const Backing&) = delete;
  Backing& operator=(const Backing&) = delete;

  bool disk_backed() const { return wal_ != nullptr; }

  void put(const Bytes& key, const Bytes& value) {
    if (disk_backed() && !wal_failed_) {
      uint64_t value_off = appended_ + 8 + key.size();
      auto appended = wal_append(wal_, key, value);
      if (!appended) {
        // Disk full / IO error: a partial record may be on disk, so any
        // further append would land at an unknowable offset.  Degrade to
        // memory-only — eviction and compaction stop, reads stay correct
        // (pre-failure offsets are still valid; post-failure values pin
        // in the resident cache) — and say so LOUDLY: durability of new
        // writes is gone until restart.
        LOG_ERROR("store")
            << "WAL append failed (disk full?); degrading to memory-only "
               "writes — new records are NOT crash-durable";
        wal_failed_ = true;
      } else {
        appended_ += *appended;
        auto it = index_.find(key);
        if (it != index_.end()) {
          live_ -= 12 + key.size() + it->second.len;
          it->second = {value_off, uint32_t(value.size())};
        } else {
          index_.emplace(key,
                         IndexEntry{value_off, uint32_t(value.size())});
        }
        live_ += 12 + key.size() + value.size();
      }
    }
    cache_put_(key, value);
    maybe_compact_();
  }

  std::optional<Bytes> get(const Bytes& key) {
    auto it = resident_.find(key);
    if (it != resident_.end()) {
      lru_.splice(lru_.begin(), lru_, it->second.pos);  // touch
      return it->second.value;
    }
    if (!disk_backed()) return std::nullopt;
    auto iit = index_.find(key);
    if (iit == index_.end()) return std::nullopt;
    Bytes value(iit->second.len);
    if (!pread_all_(read_fd_, value.data(), value.size(), iit->second.off)) {
      LOG_ERROR("store") << "WAL pread failed for spilled value";
      return std::nullopt;
    }
    cache_put_(key, value);  // hot again: re-admit
    return value;
  }

  Store::Stats stats() const {
    Store::Stats s;
    s.keys = disk_backed() ? index_.size() : resident_.size();
    s.resident_bytes = resident_bytes_;
    s.wal_bytes = appended_;
    return s;
  }

 private:
  struct IndexEntry {
    uint64_t off;  // byte offset of the VALUE within the WAL
    uint32_t len;
  };
  struct Resident {
    Bytes value;
    std::list<Bytes>::iterator pos;  // position in lru_
  };

  static bool pread_all_(int fd, uint8_t* buf, size_t len, uint64_t off) {
    size_t done = 0;
    while (done < len) {
      ssize_t n = ::pread(fd, buf + done, len - done, off + done);
      if (n <= 0) return false;
      done += size_t(n);
    }
    return true;
  }

  void cache_put_(const Bytes& key, const Bytes& value) {
    auto it = resident_.find(key);
    if (it != resident_.end()) {
      resident_bytes_ -= it->second.value.size();
      resident_bytes_ += value.size();
      it->second.value = value;
      lru_.splice(lru_.begin(), lru_, it->second.pos);
    } else {
      lru_.push_front(key);
      resident_.emplace(key, Resident{value, lru_.begin()});
      resident_bytes_ += value.size();
    }
    // Evict only when the WAL holds the bytes; the in-memory store keeps
    // everything (dropping would lose data), and a failed WAL pins every
    // post-failure value (its index offset may be stale or absent).
    if (disk_backed() && !wal_failed_ && resident_cap_ > 0) {
      while (resident_bytes_ > resident_cap_ && resident_.size() > 1) {
        const Bytes& victim = lru_.back();
        auto vit = resident_.find(victim);
        resident_bytes_ -= vit->second.value.size();
        resident_.erase(vit);
        lru_.pop_back();
      }
    }
  }

  // Sequential replay building the offset index (and warming the resident
  // cache, newest wins).  Truncates a torn tail — a crash mid-append —
  // back to the last complete record, so post-restart appends extend a
  // clean log instead of burying themselves behind garbage.  A record
  // whose CRC does not match is treated the same way: everything from
  // the first corrupt record on is cut (later records' offsets are only
  // trustworthy if every earlier length field is).
  void replay_() {
    std::FILE* f = std::fopen(wal_path_.c_str(), "rb");
    if (!f) return;
    auto get_u32 = [&](uint32_t* v) {
      uint8_t b[4];
      if (std::fread(b, 1, 4, f) != 4) return false;
      *v = uint32_t(b[0]) | (uint32_t(b[1]) << 8) | (uint32_t(b[2]) << 16) |
           (uint32_t(b[3]) << 24);
      return true;
    };
    uint64_t cursor = 0;
    while (true) {
      uint32_t klen, vlen, crc;
      if (!get_u32(&klen)) break;
      Bytes key(klen);
      if (std::fread(key.data(), 1, klen, f) != klen) break;
      if (!get_u32(&vlen)) break;
      Bytes value(vlen);
      if (std::fread(value.data(), 1, vlen, f) != vlen) break;
      if (!get_u32(&crc)) break;
      if (crc != record_crc(key, value)) {
        LOG_WARN("store") << "WAL checksum mismatch at offset " << cursor
                          << "; truncating from the corrupt record";
        break;
      }
      uint64_t value_off = cursor + 8 + klen;
      cursor += 12 + klen + vlen;
      auto it = index_.find(key);
      if (it != index_.end()) {
        live_ -= 12 + key.size() + it->second.len;
        it->second = {value_off, vlen};
      } else {
        index_.emplace(std::move(key), IndexEntry{value_off, vlen});
      }
      live_ += 12 + klen + vlen;
    }
    std::fseek(f, 0, SEEK_END);  // a corrupt record stops replay mid-file
    long end = std::ftell(f);
    std::fclose(f);
    if (end > 0 && uint64_t(end) != cursor) {
      LOG_WARN("store") << "truncating torn WAL tail ("
                        << (uint64_t(end) - cursor) << " bytes)";
      if (::truncate(wal_path_.c_str(), off_t(cursor)) != 0) {
        // Appending after un-removed garbage would shift every future
        // offset by the tail length — an unusable-but-undetected store.
        // Refuse to open instead.
        throw std::runtime_error("cannot truncate torn WAL tail at " +
                                 wal_path_);
      }
    }
    appended_ = cursor;
    // Warm the cache with the most recent values (bounded): replaying
    // values again via get() is fine, so just leave the cache cold —
    // consensus touches recent keys, which re-admit on first read.
  }

  // Rewrite the WAL as a snapshot of live state: write wal.tmp (values
  // from the resident cache or pread from the old WAL), sync, open the
  // fresh append handle, atomically rename, sync the directory, reopen
  // the read fd, swap the index.  Every fallible step happens BEFORE the
  // rename, so failure can only skip the compaction and keep the old
  // handle — never strand the store memory-only, which would let the
  // consensus core's vote-watermark persistence "succeed" against the
  // cache and double-vote after a crash.
  void maybe_compact_() {
    if (!disk_backed() || wal_failed_ || compact_bytes_ <= 0) return;
    if (appended_ <= size_t(compact_bytes_) || appended_ <= 4 * live_) {
      return;
    }
    const std::string tmp = wal_path_ + ".tmp";
    std::FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) {
      LOG_WARN("store") << "compaction skipped: cannot open " << tmp;
      return;
    }
    std::unordered_map<Bytes, IndexEntry, BytesHash> new_index;
    new_index.reserve(index_.size());
    size_t bytes = 0;
    for (const auto& [key, entry] : index_) {
      const Bytes* value;
      Bytes spilled;
      auto rit = resident_.find(key);
      if (rit != resident_.end()) {
        value = &rit->second.value;
      } else {
        spilled.resize(entry.len);
        if (!pread_all_(read_fd_, spilled.data(), spilled.size(),
                        entry.off)) {
          LOG_WARN("store") << "compaction skipped: spilled value unreadable";
          std::fclose(f);
          std::remove(tmp.c_str());
          return;
        }
        value = &spilled;
      }
      new_index.emplace(key, IndexEntry{bytes + 8 + key.size(),
                                        uint32_t(value->size())});
      auto appended = wal_append(f, key, *value, /*flush=*/false);
      if (!appended) {
        LOG_WARN("store") << "compaction skipped: snapshot write failed";
        std::fclose(f);
        std::remove(tmp.c_str());
        return;
      }
      bytes += *appended;
    }
    // Buffered writes surface ENOSPC/EIO only at flush time; an unchecked
    // failure here would rename a TRUNCATED snapshot over the live WAL
    // while new_index's offsets assume every byte landed — live reads of
    // evicted values would then pread past EOF on durably-acked data.
    bool flushed = std::fflush(f) == 0 && ::fsync(::fileno(f)) == 0;
    flushed = (std::fclose(f) == 0) && flushed;
    if (!flushed) {
      LOG_WARN("store") << "compaction skipped: snapshot flush failed";
      std::remove(tmp.c_str());
      return;
    }
    std::FILE* fresh = std::fopen(tmp.c_str(), "ab");
    if (!fresh) {
      LOG_WARN("store") << "compaction skipped: cannot reopen snapshot";
      std::remove(tmp.c_str());
      return;
    }
    int fresh_read = ::open(tmp.c_str(), O_RDONLY);
    if (fresh_read < 0) {
      LOG_WARN("store") << "compaction skipped: cannot reopen for reads";
      std::fclose(fresh);
      std::remove(tmp.c_str());
      return;
    }
    if (std::rename(tmp.c_str(), wal_path_.c_str()) != 0) {
      LOG_WARN("store") << "compaction skipped: rename failed";
      std::fclose(fresh);
      ::close(fresh_read);
      std::remove(tmp.c_str());
      return;
    }
    int dfd = ::open(dir_path_.c_str(), O_RDONLY | O_DIRECTORY);
    if (dfd >= 0) {
      ::fsync(dfd);  // persist the rename itself
      ::close(dfd);
    }
    std::fclose(wal_);
    ::close(read_fd_);
    wal_ = fresh;
    read_fd_ = fresh_read;  // fd follows the inode through the rename
    index_ = std::move(new_index);
    appended_ = bytes;
    live_ = bytes;
    LOG_INFO("store") << "WAL compacted to " << bytes << " bytes";
  }

  std::string wal_path_, dir_path_;
  std::FILE* wal_ = nullptr;
  int read_fd_ = -1;
  int64_t compact_bytes_;
  size_t resident_cap_;
  size_t appended_ = 0;  // WAL file size
  size_t live_ = 0;      // bytes of live (latest-version) records
  bool wal_failed_ = false;  // see put(): degrade-to-memory-only latch
  size_t resident_bytes_ = 0;
  std::unordered_map<Bytes, IndexEntry, BytesHash> index_;
  std::unordered_map<Bytes, Resident, BytesHash> resident_;
  std::list<Bytes> lru_;  // front = most recently used
};

}  // namespace

Store Store::open(const std::string& path, int64_t compact_bytes,
                  int64_t resident_bytes) {
  auto ch = make_channel<Command>();
  auto backing =
      std::make_shared<Backing>(path, compact_bytes, resident_bytes);

  Store s;
  s.ch_ = ch;
  s.worker_ = std::shared_ptr<std::thread>(
      new std::thread([ch, backing] {
        set_thread_name("store");
        // Obligations: key -> oneshots fulfilled by a future write
        // (store/src/lib.rs:36-57 semantics).
        std::unordered_map<Bytes, std::vector<Oneshot<Bytes>>, BytesHash>
            obligations;
        while (auto cmd = ch->recv()) {
          switch (cmd->kind) {
            case Command::Kind::kWrite: {
              backing->put(cmd->key, cmd->value);
              auto it = obligations.find(cmd->key);
              if (it != obligations.end()) {
                for (auto& waiter : it->second) waiter.set(cmd->value);
                obligations.erase(it);
              }
              break;
            }
            case Command::Kind::kRead: {
              cmd->read_reply.set(backing->get(cmd->key));
              break;
            }
            case Command::Kind::kNotifyRead: {
              auto value = backing->get(cmd->key);
              if (value) {
                cmd->notify_reply.set(std::move(*value));
              } else {
                obligations[cmd->key].push_back(cmd->notify_reply);
              }
              break;
            }
            case Command::Kind::kStats: {
              cmd->stats_reply.set(backing->stats());
              break;
            }
          }
        }
      }),
      [ch](std::thread* t) {
        ch->close();
        t->join();
        delete t;
      });
  return s;
}

void Store::write(const Bytes& key, const Bytes& value) {
  Command cmd;
  cmd.kind = Command::Kind::kWrite;
  cmd.key = key;
  cmd.value = value;
  auto start = std::chrono::steady_clock::now();
  ch_->send(std::move(cmd));
  auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                std::chrono::steady_clock::now() - start)
                .count();
  if (ms > 200) {
    LOG_WARN("store") << "SLOW write enqueue blocked " << ms << " ms";
  }
}

bool Store::try_write(const Bytes& key, Bytes* value) {
  Command cmd;
  cmd.kind = Command::Kind::kWrite;
  cmd.key = key;
  cmd.value = std::move(*value);
  if (ch_->send_until(&cmd, std::chrono::steady_clock::now()) ==
      RecvStatus::kOk) {
    return true;
  }
  *value = std::move(cmd.value);  // send_until does not consume on timeout
  return false;
}

std::optional<Bytes> Store::read(const Bytes& key) {
  Command cmd;
  cmd.kind = Command::Kind::kRead;
  cmd.key = key;
  auto reply = cmd.read_reply;
  auto start = std::chrono::steady_clock::now();
  if (!ch_->send(std::move(cmd))) return std::nullopt;
  auto result = reply.wait();
  auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                std::chrono::steady_clock::now() - start)
                .count();
  if (ms > 200) {
    LOG_WARN("store") << "SLOW read round-trip " << ms << " ms";
  }
  return result;
}

Oneshot<Bytes> Store::notify_read(const Bytes& key) {
  Command cmd;
  cmd.kind = Command::Kind::kNotifyRead;
  cmd.key = key;
  auto reply = cmd.notify_reply;
  ch_->send(std::move(cmd));
  return reply;
}

Store::Stats Store::stats() {
  Command cmd;
  cmd.kind = Command::Kind::kStats;
  auto reply = cmd.stats_reply;
  if (!ch_->send(std::move(cmd))) return {};
  return reply.wait();
}

}  // namespace hotstuff

#include "crypto/crypto.hpp"

#include <atomic>
#include <chrono>
#include <cstring>
#include <stdexcept>

#include "common/log.hpp"
#include "crypto/openssl_shim.hpp"
#include "crypto/sidecar_client.hpp"

namespace hotstuff {

namespace {
// Atomic: in-process multi-node deployments (test_e2e, `node deploy`)
// re-assert the scheme from each Node::create while earlier nodes' actor
// threads are already signing. The BLS context itself is process-wide and
// single-keyed, so scheme=bls supports one node per process (the harness
// always runs one process per node).
std::atomic<Scheme> g_scheme{Scheme::kEd25519};
std::unique_ptr<BlsContext> g_bls;
}  // namespace

Scheme current_scheme() { return g_scheme.load(std::memory_order_relaxed); }
void set_scheme(Scheme s) { g_scheme.store(s, std::memory_order_relaxed); }

BlsContext* BlsContext::instance() { return g_bls.get(); }
void BlsContext::install(std::unique_ptr<BlsContext> ctx) {
  g_bls = std::move(ctx);
}

Digest sha512_digest(const uint8_t* data, size_t len) {
  unsigned char md[64];
  unsigned int mdlen = 0;
  if (EVP_Digest(data, len, md, &mdlen, EVP_sha512(), nullptr) != 1 ||
      mdlen != 64) {
    throw std::runtime_error("sha512 failed");
  }
  Digest d;
  std::memcpy(d.data.data(), md, 32);
  return d;
}

DigestBuilder::DigestBuilder() : ctx_(EVP_MD_CTX_new()) {
  if (!ctx_ || EVP_DigestInit_ex(static_cast<EVP_MD_CTX*>(ctx_), EVP_sha512(),
                                 nullptr) != 1) {
    throw std::runtime_error("sha512 init failed");
  }
}

DigestBuilder::~DigestBuilder() {
  EVP_MD_CTX_free(static_cast<EVP_MD_CTX*>(ctx_));
}

DigestBuilder& DigestBuilder::update(const uint8_t* data, size_t len) {
  if (EVP_DigestUpdate(static_cast<EVP_MD_CTX*>(ctx_), data, len) != 1) {
    throw std::runtime_error("sha512 update failed");
  }
  return *this;
}

DigestBuilder& DigestBuilder::update_u64_le(uint64_t v) {
  uint8_t buf[8];
  for (int i = 0; i < 8; i++) buf[i] = (v >> (8 * i)) & 0xFF;
  return update(buf, 8);
}

Digest DigestBuilder::finalize() {
  unsigned char md[64];
  unsigned int mdlen = 0;
  if (EVP_DigestFinal_ex(static_cast<EVP_MD_CTX*>(ctx_), md, &mdlen) != 1 ||
      mdlen != 64) {
    throw std::runtime_error("sha512 final failed");
  }
  Digest d;
  std::memcpy(d.data.data(), md, 32);
  return d;
}

bool PublicKey::from_base64(const std::string& s, PublicKey* out) {
  Bytes b;
  if (!base64_decode(s, &b) || b.size() != 32) return false;
  std::memcpy(out->data.data(), b.data(), 32);
  return true;
}

bool SecretKey::from_base64(const std::string& s, SecretKey* out) {
  Bytes b;
  if (!base64_decode(s, &b) || b.size() != 64) return false;
  std::memcpy(out->data.data(), b.data(), 64);
  return true;
}

namespace {

struct PkeyGuard {
  EVP_PKEY* p;
  ~PkeyGuard() { EVP_PKEY_free(p); }
};

struct CtxGuard {
  EVP_MD_CTX* c;
  ~CtxGuard() { EVP_MD_CTX_free(c); }
};

}  // namespace

Signature Signature::sign_host(const Digest& digest, const SecretKey& sk) {
  PkeyGuard key{EVP_PKEY_new_raw_private_key(kEvpPkeyEd25519, nullptr,
                                             sk.seed(), 32)};
  if (!key.p) throw std::runtime_error("bad secret key");
  CtxGuard ctx{EVP_MD_CTX_new()};
  Signature sig;
  size_t siglen = sig.data.size();
  if (EVP_DigestSignInit(ctx.c, nullptr, nullptr, nullptr, key.p) != 1 ||
      EVP_DigestSign(ctx.c, sig.data.data(), &siglen, digest.data.data(),
                     digest.data.size()) != 1 ||
      siglen != 64) {
    throw std::runtime_error("ed25519 sign failed");
  }
  return sig;
}

Signature Signature::sign(const Digest& digest, const SecretKey& sk) {
  if (current_scheme() == Scheme::kBls) {
    TpuVerifier* tpu = TpuVerifier::instance();
    BlsContext* bls = BlsContext::instance();
    if (!tpu || !bls) {
      throw std::runtime_error("scheme=bls requires sidecar + BLS keys");
    }
    // Bounded retries over transient sidecar failures.  This runs on the
    // SignatureService worker thread, which has no exception handler — a
    // throw here would std::terminate the whole node on one sidecar
    // hiccup.  When the sidecar is already unreachable (breaker open /
    // never connected) skip the retry dance: bls_sign fails fast and
    // every vote/timeout queued behind this one would otherwise eat the
    // full backoff.
    const int attempts = tpu->connected() ? 3 : 1;
    for (int attempt = 0; attempt < attempts; attempt++) {
      auto sig = tpu->bls_sign(digest, bls->secret);
      if (sig) {
        Signature s;
        s.data = std::move(*sig);
        return s;
      }
      LOG_WARN("crypto") << "BLS sign attempt " << attempt + 1 << "/"
                         << attempts << " failed";
      if (attempt + 1 < attempts) {
        std::this_thread::sleep_for(std::chrono::milliseconds(500));
      }
    }
    // Sidecar-down fallback: sign with the host Ed25519 identity key
    // (every committee entry carries it under both schemes).  Verifiers
    // dispatch on signature length, so timeouts and votes signed during
    // an outage still verify on the HOST path — the node keeps
    // participating in view changes instead of emitting invalid bytes
    // and stalling TC assembly until the sidecar returns.
    LOG_ERROR("crypto") << "BLS signing unavailable; falling back to the "
                           "host Ed25519 identity key";
  }
  return sign_host(digest, sk);
}

namespace {

// Small-order (8-torsion) rejection, mirroring the device path's
// verify_strict parity (hotstuff_tpu/crypto/eddsa.py _SMALL_ORDER_Y) so a
// node whose sidecar is down reaches the same verdict as one using the
// device path: OpenSSL's EVP_DigestVerify accepts small-order A/R per
// RFC 8032, under which the identity pk plus sig = ([S]B || S) verifies
// ANY message — a universal forgery that breaks vote attribution.
//
// The eight 8-torsion points have five distinct y values and the set is
// closed under negation, so reducing the sign-cleared 255-bit y mod p and
// comparing against the five values is an exact test over ALL encodings
// (canonical and non-canonical alike — the closure dalek's checked list of
// excluded point encodings enumerates explicitly).
bool is_small_order_encoding(const uint8_t* enc32) {
  // y = little-endian value of the encoding with the sign bit cleared.
  std::array<uint8_t, 32> y;
  std::memcpy(y.data(), enc32, 32);
  y[31] &= 0x7f;
  // Reduce mod p = 2^255 - 19: y < 2^255 < 2p, so at most one subtract.
  static constexpr std::array<uint8_t, 32> kP = {
      0xed, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f};
  auto ge = [](const std::array<uint8_t, 32>& a,
               const std::array<uint8_t, 32>& b) {
    for (int i = 31; i >= 0; i--) {
      if (a[i] != b[i]) return a[i] > b[i];
    }
    return true;
  };
  if (ge(y, kP)) {
    int borrow = 0;
    for (int i = 0; i < 32; i++) {
      int d = int(y[i]) - int(kP[i]) - borrow;
      borrow = d < 0;
      y[i] = uint8_t(d & 0xff);
    }
  }
  // The five 8-torsion y values: 0, 1, p-1, y8, p-y8 (eddsa.py:76-85).
  static constexpr std::array<std::array<uint8_t, 32>, 5> kTorsionY = {{
      {0},
      {1},
      {0xec, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
       0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
       0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
       0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f},
      // 0x7A03AC9277FDC74EC6CC392CFA53202A0F67100D760B3CBA4FD84D3D706A17C7
      {0xc7, 0x17, 0x6a, 0x70, 0x3d, 0x4d, 0xd8, 0x4f,
       0xba, 0x3c, 0x0b, 0x76, 0x0d, 0x10, 0x67, 0x0f,
       0x2a, 0x20, 0x53, 0xfa, 0x2c, 0x39, 0xcc, 0xc6,
       0x4e, 0xc7, 0xfd, 0x77, 0x92, 0xac, 0x03, 0x7a},
      // 0x05FC536D880238B13933C6D305ACDFD5F098EFF289F4C345B027B2C28F95E826
      {0x26, 0xe8, 0x95, 0x8f, 0xc2, 0xb2, 0x27, 0xb0,
       0x45, 0xc3, 0xf4, 0x89, 0xf2, 0xef, 0x98, 0xf0,
       0xd5, 0xdf, 0xac, 0x05, 0xd3, 0xc6, 0x33, 0x39,
       0xb1, 0x38, 0x02, 0x88, 0x6d, 0x53, 0xfc, 0x05},
  }};
  for (const auto& t : kTorsionY) {
    if (y == t) return true;
  }
  return false;
}

}  // namespace

// VERIFIES(sig)
bool Signature::verify(const Digest& digest, const PublicKey& pk) const {
  // 192-byte signatures are BLS G2 and verify through the sidecar.
  // 64-byte signatures take the host Ed25519 path EVEN under scheme=bls:
  // they are the sidecar-down fallback (see Signature::sign), verified
  // against the signer's Ed25519 identity key.
  if (current_scheme() == Scheme::kBls && data.size() != 64) {
    return verify_batch(digest, {{pk, *this}});
  }
  if (data.size() != 64) return false;
  // verify_strict parity with the device path (and dalek's verify_strict,
  // crypto/src/lib.rs:204-208): reject small-order A and R before OpenSSL,
  // which would otherwise accept them per plain RFC 8032.
  if (is_small_order_encoding(pk.data.data()) ||
      is_small_order_encoding(data.data())) {
    return false;
  }
  PkeyGuard key{EVP_PKEY_new_raw_public_key(kEvpPkeyEd25519, nullptr,
                                            pk.data.data(), 32)};
  if (!key.p) return false;
  CtxGuard ctx{EVP_MD_CTX_new()};
  if (EVP_DigestVerifyInit(ctx.c, nullptr, nullptr, nullptr, key.p) != 1) {
    return false;
  }
  return EVP_DigestVerify(ctx.c, data.data(), data.size(),
                          digest.data.data(), digest.data.size()) == 1;
}

// VERIFIES(sig)
bool Signature::verify_batch(
    const Digest& digest,
    const std::vector<std::pair<PublicKey, Signature>>& votes) {
  if (current_scheme() == Scheme::kBls) {
    // Partition by signature length: 64-byte entries are host Ed25519
    // fallback signatures (signed while their author's sidecar was
    // down — see Signature::sign) and verify right here; only genuine
    // 192-byte G2 signatures ride the sidecar pairing op (whose records
    // are fixed-size and would reject the mix).  No host pairing exists
    // in the C++ plane, so a transport failure on the BLS remainder
    // rejects.
    std::vector<std::pair<PublicKey, Signature>> bls_votes;
    bls_votes.reserve(votes.size());
    for (const auto& [pk, sig] : votes) {
      if (sig.data.size() == 64) {
        if (!sig.verify(digest, pk)) return false;
      } else {
        bls_votes.emplace_back(pk, sig);
      }
    }
    if (bls_votes.empty()) return true;
    TpuVerifier* tpu = TpuVerifier::instance();
    if (!tpu) return false;
    auto ok = tpu->bls_verify_votes(digest, bls_votes);
    return ok.value_or(false);
  }
  std::vector<std::tuple<Digest, PublicKey, Signature>> items;
  items.reserve(votes.size());
  for (const auto& [pk, sig] : votes) items.emplace_back(digest, pk, sig);
  return verify_batch_multi(items);
}

// VERIFIES(sig)
bool Signature::verify_batch_multi(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    bool bulk) {
  // Callers without a retry path: a transport failure on the BLS
  // remainder (nullopt) maps to reject here.
  return verify_batch_multi_checked(items, bulk).value_or(false);
}

// VERIFIES(sig)
std::optional<bool> Signature::verify_batch_multi_checked(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    bool bulk) {
  // BLS TCs carry per-vote BLS signatures over distinct digests: ONE
  // multi-digest sidecar round-trip, verified device-side as a single
  // product of pairings (TC verify parity: consensus/src/messages.rs:
  // 307-313).  Same partition as verify_batch: 64-byte Ed25519 fallback
  // signatures verify on host first (a forged one rejects definitively),
  // then the 192-byte remainder goes to the sidecar.  nullopt = that
  // remainder is UNKNOWN (no transport), never forged — TC assembly
  // re-arms on it instead of ejecting honest signers for the outage.
  if (current_scheme() == Scheme::kBls) {
    std::vector<std::tuple<Digest, PublicKey, Signature>> bls_items;
    bls_items.reserve(items.size());
    for (const auto& [d, pk, sig] : items) {
      if (sig.data.size() == 64) {
        if (!sig.verify(d, pk)) return false;
      } else {
        bls_items.emplace_back(d, pk, sig);
      }
    }
    if (bls_items.empty()) return true;
    TpuVerifier* tpu = TpuVerifier::instance();
    if (!tpu) return std::nullopt;
    return tpu->bls_verify_multi(bls_items);
  }
  TpuVerifier* tpu = TpuVerifier::instance();
  if (tpu && tpu->connected()) {
    auto mask = tpu->verify_batch_multi(items, bulk);
    if (mask) {
      for (bool ok : *mask) {
        if (!ok) return false;
      }
      return true;
    }
    // fall through to host loop on sidecar failure
  }
  for (const auto& [d, pk, sig] : items) {
    if (!sig.verify(d, pk)) return false;
  }
  return true;
}

bool Signature::async_available() {
  TpuVerifier* tpu = TpuVerifier::instance();
  if (!tpu) return false;
  // Bound the pipeline depth: past this, backpressure to the synchronous
  // path beats queueing more work behind a busy engine.  The bound is
  // adaptive — the client shrinks it when the sidecar's OP_STATS report
  // a rising latency-class queue-wait p99 (TpuVerifier::adapt_budget) —
  // so congestion sheds pipelining pressure before the engine has to
  // shed requests.
  if (tpu->inflight() >= static_cast<size_t>(tpu->inflight_budget())) {
    return false;
  }
  if (current_scheme() == Scheme::kBls && !BlsContext::instance()) {
    return false;
  }
  // Both schemes require a live connection: for BLS a transport failure is
  // a definitive reject, so dispatching async while the sidecar is down
  // would turn an outage into spurious "invalid certificate" verdicts.
  return tpu->connected();
}

// VERIFIES(sig)
void Signature::verify_batch_multi_async(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    AsyncCallback cb, bool bulk, const Digest* ctx) {
  TpuVerifier* tpu = TpuVerifier::instance();
  if (!tpu) {
    cb(std::nullopt);
    return;
  }
  if (current_scheme() == Scheme::kBls) {
    // Same partition as the synchronous path: 64-byte Ed25519 fallback
    // signatures verify on host inline (microseconds), only genuine G2
    // signatures ship to the sidecar.  Transport failure propagates as
    // nullopt so the caller's synchronous retry — which can host-verify
    // or re-arm — decides, instead of turning a mid-flight outage into
    // a definitive "invalid certificate" verdict.  The ctx tag rides
    // the BLS frame exactly as it does the Ed25519 one (v5 parity).
    std::vector<std::tuple<Digest, PublicKey, Signature>> bls_items;
    bls_items.reserve(items.size());
    for (const auto& [d, pk, sig] : items) {
      if (sig.data.size() == 64) {
        if (!sig.verify(d, pk)) {
          cb(false);
          return;
        }
      } else {
        bls_items.emplace_back(d, pk, sig);
      }
    }
    if (bls_items.empty()) {
      cb(true);
      return;
    }
    tpu->bls_verify_multi_async(bls_items, std::move(cb), ctx);
    return;
  }
  tpu->verify_batch_multi_async(
      items, [cb = std::move(cb)](std::optional<std::vector<bool>> mask) {
        if (!mask) {
          cb(std::nullopt);  // transport failure: caller re-verifies sync
          return;
        }
        for (bool ok : *mask) {
          if (!ok) {
            cb(false);
            return;
          }
        }
        cb(true);
      },
      bulk, ctx);
}

// VERIFIES(sig)
void Signature::verify_batch_multi_async_masked(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    MaskedCallback cb, bool bulk, const Digest* ctx) {
  // Ed25519-only lane: client tx signatures are Ed25519 under either
  // scheme knob, so there is no BLS partition here — a non-64-byte
  // signature is a caller bug and surfaces as the transport-shaped
  // nullopt (the admission worker then host-verifies, which rejects it).
  TpuVerifier* tpu = TpuVerifier::instance();
  if (tpu == nullptr) {
    cb(std::nullopt, -1);
    return;
  }
  tpu->verify_batch_multi_async_ex(items, std::move(cb), bulk, ctx);
}

KeyPair generate_keypair() {
  std::array<uint8_t, 32> seed;
  if (RAND_bytes(seed.data(), seed.size()) != 1) {
    throw std::runtime_error("RAND_bytes failed");
  }
  return keypair_from_seed(seed);
}

KeyPair keypair_from_seed(const std::array<uint8_t, 32>& seed) {
  PkeyGuard key{EVP_PKEY_new_raw_private_key(kEvpPkeyEd25519, nullptr,
                                             seed.data(), 32)};
  if (!key.p) throw std::runtime_error("bad seed");
  KeyPair kp;
  size_t publen = 32;
  if (EVP_PKEY_get_raw_public_key(key.p, kp.name.data.data(), &publen) != 1 ||
      publen != 32) {
    throw std::runtime_error("pubkey derivation failed");
  }
  std::memcpy(kp.secret.data.data(), seed.data(), 32);
  std::memcpy(kp.secret.data.data() + 32, kp.name.data.data(), 32);
  return kp;
}

SignatureService::SignatureService(const SecretKey& sk)
    : ch_(make_channel<Request>()) {
  auto ch = ch_;
  SecretKey key = sk;
  worker_ = std::shared_ptr<std::thread>(
      new std::thread([ch, key] {
        set_thread_name("sig-service");
        while (auto req = ch->recv()) {
          req->reply.set(Signature::sign(req->digest, key));
        }
      }),
      [ch](std::thread* t) {
        ch->close();
        t->join();
        delete t;
      });
}

Signature SignatureService::request_signature(const Digest& digest) const {
  Request req;
  req.digest = digest;
  Oneshot<Signature> reply = req.reply;
  if (!ch_->send(std::move(req))) {
    throw std::runtime_error("signature service stopped");
  }
  return reply.wait();
}

}  // namespace hotstuff

// C++ client for the TPU verify sidecar (hotstuff_tpu/sidecar/service.py).
// This is the device-dispatch half of the crypto boundary: QC batch
// verification ships (digest, pk, sig) records to the JAX process over
// localhost TCP and gets back a validity mask — replacing the in-process
// dalek::verify_batch call of the reference (crypto/src/lib.rs:210-223).
#pragma once

#include <chrono>
#include <memory>
#include <mutex>
#include <optional>
#include <tuple>
#include <utility>
#include <vector>

#include "network/socket.hpp"

namespace hotstuff {

struct Digest;
struct PublicKey;
struct Signature;
struct BlsContext;
class Writer;

class TpuVerifier {
 public:
  explicit TpuVerifier(const Address& addr);

  // Process-wide instance used by Signature::verify_batch. Install once at
  // node startup (Node::new does when parameters carry a sidecar address).
  static TpuVerifier* instance();
  static void install(std::unique_ptr<TpuVerifier> v);

  bool connected();

  // One coalesced launch, one digest PER record (QC votes share a digest;
  // TC votes sign distinct (round, high_qc_round) digests — the wire
  // format carries a message per record either way). Returns nullopt on
  // transport failure (caller falls back to host verify).
  std::optional<std::vector<bool>> verify_batch_multi(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items);

  // scheme=bls operations (pairing lives only in the sidecar; signing is
  // its host G2 scalar mult). These use a longer receive deadline than
  // Ed25519 batches — a pairing is milliseconds-to-seconds, not micro.
  std::optional<Bytes> bls_sign(const Digest& digest, const Bytes& sk48);
  std::optional<bool> bls_verify_votes(
      const Digest& digest,
      const std::vector<std::pair<PublicKey, Signature>>& votes);
  // Distinct digest per vote (the TC shape): ONE round-trip, verified
  // device-side as a single product of pairings.
  std::optional<bool> bls_verify_multi(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items);

 private:
  bool append_bls_record_(BlsContext* bls, Writer* w, const PublicKey& pk,
                          const Signature& sig);
  std::optional<bool> bls_bool_exchange_locked_(const Writer& w,
                                                uint8_t opcode,
                                                uint32_t rid);

 public:

  // Deadlines (ms). Every sidecar interaction is bounded: a slow or wedged
  // device process makes verify_batch return nullopt (host fallback), never
  // stalls the consensus Core thread (SURVEY.md §7 latency discipline).
  static constexpr int kConnectTimeoutMs = 250;
  static constexpr int kRecvTimeoutMs = 1000;
  static constexpr int kBlsRecvTimeoutMs = 60'000;
  // After a transport failure, skip the sidecar entirely for this long so a
  // dead device costs one timeout, not one per QC.
  static constexpr int kBackoffMs = 2000;

 private:
  bool ensure_connected_locked();
  std::optional<Bytes> bls_roundtrip_locked_(const Bytes& frame);

  Address addr_;
  std::mutex m_;
  Socket sock_;
  uint32_t next_id_ = 0;
  bool ever_connected_ = false;
  std::chrono::steady_clock::time_point backoff_until_{};
};

}  // namespace hotstuff

// C++ client for the TPU verify sidecar (hotstuff_tpu/sidecar/service.py).
// This is the device-dispatch half of the crypto boundary: QC batch
// verification ships (digest, pk, sig) records to the JAX process over
// localhost TCP and gets back a validity mask — replacing the in-process
// dalek::verify_batch call of the reference (crypto/src/lib.rs:210-223).
//
// The client PIPELINES: requests carry an id the sidecar echoes back
// (sidecar/protocol.py frame layout), so any number of verifications can be
// in flight at once.  A dedicated reader thread matches replies to pending
// callbacks; submitting never waits for earlier replies.  This is what lets
// the consensus Core suspend a proposal on a pending device verify and keep
// processing votes (the async analogue of the reference's synchronous
// QC::verify at consensus/src/messages.rs:180-198).
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <memory>
#include <mutex>
#include <optional>
#include <string>
#include <tuple>
#include <unordered_map>
#include <utility>
#include <vector>

#include "network/socket.hpp"

namespace hotstuff {

struct Digest;
struct PublicKey;
struct Signature;
struct BlsContext;
class Writer;

class TpuVerifier {
 public:
  explicit TpuVerifier(const Address& addr);
  // graftfleet: an ORDERED sidecar endpoint list (first = primary) plus
  // an optional tenant id.  Every endpoint keeps its own circuit
  // breaker/backoff/probe state; requests ride the active endpoint
  // (sticky until unhealthy) and fail over to the first healthy
  // alternative — scanning from index 0, so a recovered primary is
  // preferred as soon as the current endpoint falters — before the
  // host path is ever used.  A non-empty tenant is announced with a
  // protocol v6 HELLO frame on every (re)connect, keying the sidecar's
  // per-tenant fair scheduling.
  TpuVerifier(std::vector<Address> addrs, std::string tenant);
  ~TpuVerifier();

  // Process-wide instance used by Signature::verify_batch. Install once at
  // node startup (Node::new does when parameters carry a sidecar address).
  static TpuVerifier* instance();
  static void install(std::unique_ptr<TpuVerifier> v);

  bool connected();
  // Number of requests currently awaiting a sidecar reply.
  size_t inflight() const;

  // Degradation ladder (graftchaos): after kBreakerThreshold consecutive
  // transport failures the breaker OPENs — every verify goes straight to
  // the host path with zero connect cost while a background probe thread
  // re-dials the sidecar on an exponential backoff (half-open).  A probe
  // that connects CLOSEs the breaker and re-attaches the reader.  State
  // transitions are logged ("circuit breaker OPEN/CLOSED"), which the
  // harness LogParser folds into the run summary.
  enum class BreakerState { kClosed, kOpen, kHalfOpen };
  BreakerState breaker_state() const;
  // graftfleet: per-endpoint breaker view + the active (sticky)
  // endpoint requests currently ride.
  BreakerState breaker_state(size_t ix) const;
  size_t endpoint_count() const;
  size_t active_endpoint() const;

  // Adaptive async pipeline bound: the reader polls the sidecar's
  // OP_STATS latency-class queue-wait p99 every kStatsIntervalMs and
  // AIMD-adapts how many requests may be pending at once (replacing the
  // old fixed 64 in Signature::async_available) — a congested engine
  // sheds pipelining pressure before its queue-full backpressure has to.
  int inflight_budget() const;
  // The pure adaptation step (multiplicative decrease past
  // kQueueWaitShrinkMs, additive increase below kQueueWaitGrowMs,
  // hysteresis between): factored out for unit tests.
  static int adapt_budget(int current, double p99_ms);

  // Test hook: shrink the breaker timings so unit tests can watch a full
  // open -> probe -> re-attach cycle without multi-second sleeps.
  void set_backoff_for_test(int base_ms, int max_ms);

  // One coalesced launch, one digest PER record (QC votes share a digest;
  // TC votes sign distinct (round, high_qc_round) digests — the wire
  // format carries a message per record either way). Returns nullopt on
  // transport failure OR an explicit queue-full shed by the sidecar's
  // scheduler (caller falls back to host verify either way).
  //
  // `bulk` tags the request's scheduling class on the wire (protocol v2):
  // false = latency class (consensus QC/TC verification — launched ahead
  // of any bulk backlog), true = bulk class (mempool/offchain batches —
  // coalesced behind latency work).  Consensus paths must NOT pass true.
  //
  // `ctx` (protocol v5, graftscope) is the 32-byte block-digest context
  // tag: the consensus core passes the digest of the block whose
  // certificates this batch verifies, and the sidecar tags its stage
  // spans with it so obs/trace.py can nest device time inside that
  // block's verify segment.  nullptr emits the legacy tag-less frame —
  // byte-identical to v4, so a node upgraded before its sidecar keeps
  // its no-context verifies working.
  std::optional<std::vector<bool>> verify_batch_multi(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
      bool bulk = false, const Digest* ctx = nullptr);

  // Asynchronous form: the callback is invoked EXACTLY once — with the
  // validity mask on a reply, or nullopt on transport failure/timeout —
  // from either this call (immediate failure) or the reader thread.  Keep
  // callbacks tiny (a channel push): they run on the reply path.
  using MaskCallback =
      std::function<void(std::optional<std::vector<bool>>)>;
  void verify_batch_multi_async(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
      MaskCallback cb, bool bulk = false, const Digest* ctx = nullptr);

  // graftingress: backpressure-aware form.  `busy_retry_ms` is -1 except
  // when the sidecar explicitly shed the request with OP_BUSY, in which
  // case it carries the (clamped, advisory) retry-after hint and the
  // mask is nullopt.  Consensus callers keep the plain form (an overload
  // and an outage both mean "host fallback now"); the mempool
  // admission-verify lane distinguishes them — BUSY is worth a bounded
  // paced retry on the device, a dead transport is not.
  using MaskBusyCallback =
      std::function<void(std::optional<std::vector<bool>>, int busy_retry_ms)>;
  void verify_batch_multi_async_ex(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
      MaskBusyCallback cb, bool bulk = false, const Digest* ctx = nullptr);

  // scheme=bls operations (pairing lives only in the sidecar; signing is
  // its host G2 scalar mult). These use a longer deadline than Ed25519
  // batches — a pairing is milliseconds-to-seconds, not micro.  `ctx` is
  // the same optional v5 context tag as verify_batch_multi: BLS verifies
  // carrying the block digest join that block's trace spans exactly like
  // EdDSA ones (ROADMAP item-2 parity); nullptr emits the legacy frame.
  using BoolCallback = std::function<void(std::optional<bool>)>;
  std::optional<Bytes> bls_sign(const Digest& digest, const Bytes& sk48);
  std::optional<bool> bls_verify_votes(
      const Digest& digest,
      const std::vector<std::pair<PublicKey, Signature>>& votes,
      const Digest* ctx = nullptr);
  void bls_verify_votes_async(
      const Digest& digest,
      const std::vector<std::pair<PublicKey, Signature>>& votes,
      BoolCallback cb, const Digest* ctx = nullptr);
  // Distinct digest per vote (the TC shape): ONE round-trip, verified
  // device-side as a single product of pairings.
  std::optional<bool> bls_verify_multi(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
      const Digest* ctx = nullptr);
  void bls_verify_multi_async(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
      BoolCallback cb, const Digest* ctx = nullptr);

  // Deadlines (ms). Every sidecar interaction is bounded: a slow or wedged
  // device process fails the pending request (host fallback), never stalls
  // a consensus thread indefinitely (SURVEY.md §7 latency discipline).
  static constexpr int kConnectTimeoutMs = 250;
  static constexpr int kRecvTimeoutMs = 1000;
  static constexpr int kBlsRecvTimeoutMs = 60'000;
  // After a transport failure, skip the sidecar entirely for this long so a
  // dead device costs one timeout, not one per QC.  Once the breaker is
  // open this is also the INITIAL probe interval, doubled per failed
  // probe up to kBackoffMaxMs — steady-state cost of a dead sidecar is
  // one background connect attempt per backoff, zero per verify.
  static constexpr int kBackoffMs = 2000;
  static constexpr int kBackoffMaxMs = 30'000;
  // Consecutive transport failures (failed connects, lost/wedged
  // connections) before the breaker opens.  One flaky reply should not
  // abandon the device path; three in a row is an outage.
  static constexpr int kBreakerThreshold = 3;
  // OP_STATS polling cadence and the adaptive in-flight budget's bounds
  // + thresholds (queue-wait p99, ms).
  static constexpr int kStatsIntervalMs = 1000;
  static constexpr int kInflightBudgetMax = 64;
  static constexpr int kInflightBudgetMin = 8;
  static constexpr double kQueueWaitShrinkMs = 50.0;
  static constexpr double kQueueWaitGrowMs = 10.0;

 private:
  // Reply callback: full reply frame bytes, or nullopt on failure.
  using FrameCallback = std::function<void(std::optional<Bytes>)>;

  struct PendingReq {
    uint8_t opcode = 0;
    std::chrono::steady_clock::time_point deadline;
    FrameCallback cb;
  };

  // Per-ENDPOINT connection state (graftfleet: one Inner per fleet
  // member), shared with (detached) reader/probe threads, so a thread
  // draining a dead socket can never touch a destroyed client.
  // Every member below is guarded by `m` (analysis/cxxsync.py enforces
  // the annotations; *_locked_ helpers document caller-held locking).
  struct Inner {
    mutable std::mutex m;
    Socket sock;       // GUARDED_BY(m) — reader's read_frame carries the
                       // one worked suppression (it is the sole reader)
    Address addr;      // GUARDED_BY(m) — dial target; written pre-thread
                       // in the ctor, re-read by the probe under m
    size_t ix = 0;     // GUARDED_BY(m) — endpoint index (log labels);
                       // written once pre-thread in the ctor
    std::string tenant;  // GUARDED_BY(m) — HELLO id; written pre-thread
                         // in the ctor, read on (re)connect under m
    uint64_t gen = 0;  // GUARDED_BY(m) — bumped per socket lifetime;
                       // stale readers exit
    std::unordered_map<uint32_t, PendingReq> pending;  // GUARDED_BY(m)
    bool ever_connected = false;                       // GUARDED_BY(m)
    std::chrono::steady_clock::time_point backoff_until{};  // GUARDED_BY(m)
    std::chrono::steady_clock::time_point last_rx{};        // GUARDED_BY(m)
    // Circuit breaker + probe state (constants on TpuVerifier).
    BreakerState breaker = BreakerState::kClosed;  // GUARDED_BY(m)
    int consecutive_failures = 0;                  // GUARDED_BY(m)
    int backoff_ms = kBackoffMs;       // GUARDED_BY(m) — probe interval
    int backoff_base_ms = kBackoffMs;  // GUARDED_BY(m) — reset target
    int backoff_max_ms = kBackoffMaxMs;  // GUARDED_BY(m)
    bool probe_running = false;          // GUARDED_BY(m)
    bool closing = false;  // GUARDED_BY(m) — destructor: probes must exit
    std::condition_variable cv;  // SHARED_OK(cv is self-synchronizing;
                                 // waited on under m)
    // Adaptive async budget (OP_STATS-driven).
    int inflight_budget = kInflightBudgetMax;  // GUARDED_BY(m)
    std::chrono::steady_clock::time_point last_stats_tx{};  // GUARDED_BY(m)
  };

  static void reader_loop_(std::shared_ptr<Inner> inner, uint64_t gen,
                           int fd);
  static void fail_all_(const std::shared_ptr<Inner>& inner, uint64_t gen,
                        const char* why);
  // Count one transport failure; opens the breaker (and starts the probe
  // thread) at the threshold.  Lock held by the caller.
  static void note_failure_locked_(const std::shared_ptr<Inner>& inner,
                                   const char* why);
  static void start_probe_locked_(const std::shared_ptr<Inner>& inner);
  static void probe_loop_(std::shared_ptr<Inner> inner);
  // Send an OP_STATS request at most once per kStatsIntervalMs (called
  // from the reader loop; the reply adapts inflight_budget).
  static void maybe_poll_stats_(const std::shared_ptr<Inner>& inner,
                                uint64_t gen);
  static void handle_stats_reply_(const std::weak_ptr<Inner>& weak,
                                  uint32_t rid, std::optional<Bytes> reply);
  static bool ensure_connected_locked_(const std::shared_ptr<Inner>& inner);
  // graftfleet HELLO: announce the endpoint's tenant id on a fresh
  // connection (protocol v6); the reply echoes the server version.
  // Called with the endpoint lock held, right after the reader starts.
  static void send_hello_locked_(const std::shared_ptr<Inner>& inner);
  // The sticky endpoint selector: the active endpoint while its breaker
  // is closed, else the first healthy endpoint scanning from 0 (the
  // re-home is logged for the harness); falls back to the active one
  // when no endpoint is healthy (its failure routes to the host path).
  std::shared_ptr<Inner> pick_inner_(size_t* ix_out);
  // Registers cb and writes the frame to ONE endpoint; on any failure
  // invokes cb(nullopt) before returning. Thread-safe; never blocks on
  // the sidecar's reply.
  static void submit_on_(const std::shared_ptr<Inner>& inner,
                         uint8_t opcode, const Bytes& frame, uint32_t rid,
                         int deadline_ms, FrameCallback cb);
  // Failover form: submits to the chosen endpoint and, on a TERMINAL
  // transport failure (never on OP_BUSY — overload is not an outage),
  // resubmits the identical frame to the next untried healthy endpoint
  // before ever failing the caller to the host path.
  void submit_(uint8_t opcode, const Bytes& frame, uint32_t rid,
               int deadline_ms, FrameCallback cb);
  static void submit_failover_(
      std::vector<std::shared_ptr<Inner>> endpoints, uint8_t opcode,
      Bytes frame, uint32_t rid, int deadline_ms, FrameCallback cb,
      uint32_t tried, size_t ix);
  bool append_bls_record_(BlsContext* bls, Writer* w, const PublicKey& pk,
                          const Signature& sig);

  Address addr_;                  // SHARED_OK(immutable after ctor)
  std::vector<std::shared_ptr<Inner>> inners_;  // SHARED_OK(immutable
                                                // after ctor; pointees
                                                // lock their own m)
  std::shared_ptr<Inner> inner_;  // SHARED_OK(immutable after ctor:
                                  // alias of inners_[0], the primary)
  std::atomic<size_t> active_ix_{0};  // SHARED_OK(atomic)
};

}  // namespace hotstuff

#include "crypto/sidecar_client.hpp"

#include <poll.h>

#include <thread>

#include "common/channel.hpp"
#include "common/log.hpp"
#include "common/serde.hpp"
#include "crypto/crypto.hpp"

namespace hotstuff {

namespace {
constexpr uint8_t kOpVerifyBatch = 1;
constexpr uint8_t kOpBlsVerifyAgg = 3;  // NOLINT (wire constant, unused here)
constexpr uint8_t kOpBlsSign = 4;
constexpr uint8_t kOpBlsVerifyVotes = 5;
constexpr uint8_t kOpBlsVerifyMulti = 6;
// Protocol v2 (verifysched): bulk-class verifies ride a distinct opcode;
// kOpVerifyBatch stays the latency class (consensus QC/TC verifies), so
// the scheduler can launch them ahead of any bulk backlog.
constexpr uint8_t kOpVerifyBulk = 7;
constexpr uint8_t kOpStats = 8;  // NOLINT (wire constant, unused here)
constexpr uint8_t kProtocolVersion = 2;  // NOLINT (lint anchor; no handshake)
constexpr size_t kBlsPkLen = 96;
constexpr size_t kBlsSigLen = 192;
constexpr size_t kBlsSkLen = 48;
// Every message this client ships is a 32-byte digest (protocol.py
// DIGEST_LEN; graftlint cross-checks the two).
constexpr size_t kDigestLen = 32;
std::unique_ptr<TpuVerifier> g_instance;

void write_header(Writer* w, uint8_t opcode, uint32_t rid, uint32_t count) {
  w->u8(opcode);
  w->u32(rid);
  w->u32(count);
  w->u8(kDigestLen & 0xFF);  // msg_len lo (u16 LE)
  w->u8(kDigestLen >> 8);    // msg_len hi
}
}  // namespace

TpuVerifier::TpuVerifier(const Address& addr)
    : addr_(addr), inner_(std::make_shared<Inner>()) {}

TpuVerifier::~TpuVerifier() {
  std::vector<FrameCallback> cbs;
  {
    std::lock_guard<std::mutex> lk(inner_->m);
    inner_->gen++;  // stale readers exit without touching the socket
    for (auto& [rid, p] : inner_->pending) cbs.push_back(std::move(p.cb));
    inner_->pending.clear();
    // Wakes a reader blocked in poll/read; the Socket fd itself is closed
    // by ~Inner once the last reader drops its shared_ptr.
    inner_->sock.shutdown();
  }
  for (auto& cb : cbs) cb(std::nullopt);
}

TpuVerifier* TpuVerifier::instance() { return g_instance.get(); }

void TpuVerifier::install(std::unique_ptr<TpuVerifier> v) {
  g_instance = std::move(v);
}

bool TpuVerifier::connected() {
  std::lock_guard<std::mutex> lk(inner_->m);
  return ensure_connected_locked_();
}

size_t TpuVerifier::inflight() const {
  std::lock_guard<std::mutex> lk(inner_->m);
  return inner_->pending.size();
}

bool TpuVerifier::ensure_connected_locked_() {
  Inner& in = *inner_;
  if (in.sock.valid()) return true;
  if (std::chrono::steady_clock::now() < in.backoff_until) return false;
  auto s = Socket::connect(addr_, kConnectTimeoutMs);
  if (!s) {
    in.backoff_until = std::chrono::steady_clock::now() +
                       std::chrono::milliseconds(kBackoffMs);
    if (!in.ever_connected) return false;
    LOG_WARN("crypto::sidecar") << "lost connection to verify sidecar "
                                << addr_.str();
    in.ever_connected = false;
    return false;
  }
  in.sock = std::move(*s);
  // Backstop only: the reader polls with its own timeout; this bounds a
  // pathological partial frame.
  in.sock.set_recv_timeout(kRecvTimeoutMs);
  in.gen++;
  in.last_rx = std::chrono::steady_clock::now();
  if (!in.ever_connected) {
    LOG_INFO("crypto::sidecar") << "connected to verify sidecar "
                                << addr_.str();
  }
  in.ever_connected = true;
  std::thread(reader_loop_, inner_, in.gen, in.sock.fd()).detach();
  return true;
}

// Fails every pending request and closes the socket. The reader of `gen`
// is the only caller while its socket lives, so close here cannot race a
// concurrent read; writers write under the same lock.
void TpuVerifier::fail_all_(const std::shared_ptr<Inner>& inner,
                            uint64_t gen, const char* why) {
  std::vector<FrameCallback> cbs;
  {
    std::lock_guard<std::mutex> lk(inner->m);
    if (inner->gen != gen) return;  // a newer connection took over
    if (!inner->pending.empty()) {
      LOG_WARN("crypto::sidecar")
          << "failing " << inner->pending.size()
          << " in-flight sidecar request(s): " << why;
    }
    for (auto& [rid, p] : inner->pending) cbs.push_back(std::move(p.cb));
    inner->pending.clear();
    inner->sock.close();
    inner->backoff_until = std::chrono::steady_clock::now() +
                           std::chrono::milliseconds(kBackoffMs);
  }
  for (auto& cb : cbs) cb(std::nullopt);
}

void TpuVerifier::reader_loop_(std::shared_ptr<Inner> inner, uint64_t gen,
                               int fd) {
  for (;;) {
    {
      std::lock_guard<std::mutex> lk(inner->m);
      if (inner->gen != gen || !inner->sock.valid()) return;
    }
    pollfd p{fd, POLLIN, 0};
    int rc = ::poll(&p, 1, 200);
    if (rc < 0) {
      if (errno == EINTR) continue;
      fail_all_(inner, gen, "poll error");
      return;
    }
    // Deadline sweep EVERY iteration (not only on poll timeout): under
    // continuous reply traffic an orphaned request — one the sidecar
    // never answers — must still expire, or a sync wrapper blocked on it
    // waits forever.  Expire overdue requests individually; if nothing at
    // all has arrived for a full receive window while requests are
    // overdue, the connection (or the engine behind it) is wedged.
    auto now = std::chrono::steady_clock::now();
    {
      std::vector<FrameCallback> expired;
      bool wedged = false;
      {
        std::lock_guard<std::mutex> lk(inner->m);
        if (inner->gen != gen) return;
        for (auto it = inner->pending.begin(); it != inner->pending.end();) {
          if (now > it->second.deadline) {
            expired.push_back(std::move(it->second.cb));
            it = inner->pending.erase(it);
          } else {
            ++it;
          }
        }
        wedged = !expired.empty() &&
                 now - inner->last_rx >
                     std::chrono::milliseconds(kRecvTimeoutMs);
      }
      for (auto& cb : expired) cb(std::nullopt);
      if (wedged) {
        fail_all_(inner, gen, "no replies within deadline");
        return;
      }
    }
    if (rc == 0) continue;
    Bytes reply;
    // Safe without the lock: this reader is the only thread reading, and
    // only this reader closes the gen's socket (writers only shutdown()).
    if (!inner->sock.read_frame(&reply)) {
      fail_all_(inner, gen, "connection closed by sidecar");
      return;
    }
    FrameCallback cb;
    {
      std::lock_guard<std::mutex> lk(inner->m);
      if (inner->gen != gen) return;
      inner->last_rx = now;
      if (reply.size() >= 5) {
        uint32_t rid = static_cast<uint32_t>(reply[1]) |
                       static_cast<uint32_t>(reply[2]) << 8 |
                       static_cast<uint32_t>(reply[3]) << 16 |
                       static_cast<uint32_t>(reply[4]) << 24;
        auto it = inner->pending.find(rid);
        if (it != inner->pending.end()) {
          cb = std::move(it->second.cb);
          inner->pending.erase(it);
        }
      }
    }
    if (cb) {
      cb(std::move(reply));
    } else {
      LOG_DEBUG("crypto::sidecar") << "dropping late/unknown sidecar reply";
    }
  }
}

void TpuVerifier::submit_(uint8_t opcode, const Bytes& frame, uint32_t rid,
                          int deadline_ms, FrameCallback cb) {
  bool fail = false;
  {
    std::lock_guard<std::mutex> lk(inner_->m);
    if (!ensure_connected_locked_()) {
      fail = true;
    } else {
      PendingReq req;
      req.opcode = opcode;
      req.deadline = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(deadline_ms);
      req.cb = std::move(cb);
      inner_->pending.emplace(rid, std::move(req));
      if (!inner_->sock.write_frame(frame)) {
        // The reader owns teardown: wake it and let fail_all_ invoke the
        // callback we just registered (along with any other pendings).
        inner_->sock.shutdown();
      }
    }
  }
  if (fail) cb(std::nullopt);
}

// -- Ed25519 ---------------------------------------------------------------

void TpuVerifier::verify_batch_multi_async(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    MaskCallback cb, bool bulk) {
  // Class tag rides the opcode: consensus QC/TC verifies stay latency
  // class (the sidecar launches them ahead of any bulk backlog); bulk
  // callers (offchain sweeps, mempool-style batches) must say so.
  const uint8_t opcode = bulk ? kOpVerifyBulk : kOpVerifyBatch;
  Writer w;
  uint32_t rid;
  {
    std::lock_guard<std::mutex> lk(inner_->m);
    rid = inner_->next_id++;
  }
  write_header(&w, opcode, rid, static_cast<uint32_t>(items.size()));
  for (const auto& [digest, pk, sig] : items) {
    if (sig.data.size() != 64) {  // not an Ed25519 sig
      cb(std::nullopt);
      return;
    }
    w.fixed(digest.data);
    w.fixed(pk.data);
    w.out.insert(w.out.end(), sig.data.begin(), sig.data.end());
  }
  size_t n_items = items.size();
  submit_(opcode, w.out, rid, kRecvTimeoutMs,
          [cb = std::move(cb), rid, n_items,
           opcode](std::optional<Bytes> reply) {
            if (!reply) {
              cb(std::nullopt);
              return;
            }
            try {
              Reader r(*reply);
              uint8_t got_op = r.u8();
              uint32_t got_rid = r.u32();
              uint32_t n = r.u32();
              if (got_op == opcode && got_rid == rid && n == 0 &&
                  n_items != 0) {
                // Explicit backpressure: the sidecar shed this request
                // (class queue full).  nullopt -> caller's host fallback.
                LOG_DEBUG("crypto::sidecar") << "sidecar queue full; "
                                                "falling back to host";
                cb(std::nullopt);
                return;
              }
              if (got_op != opcode || got_rid != rid || n != n_items) {
                LOG_WARN("crypto::sidecar") << "protocol mismatch from sidecar";
                cb(std::nullopt);
                return;
              }
              std::vector<bool> mask(n);
              for (uint32_t i = 0; i < n; i++) mask[i] = r.u8() != 0;
              cb(std::move(mask));
            } catch (const SerdeError&) {
              cb(std::nullopt);
            }
          });
}

std::optional<std::vector<bool>> TpuVerifier::verify_batch_multi(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    bool bulk) {
  Oneshot<std::optional<std::vector<bool>>> done;
  verify_batch_multi_async(
      items,
      [done](std::optional<std::vector<bool>> mask) {
        done.set(std::move(mask));
      },
      bulk);
  return done.wait();  // bounded: every submitted callback fires by deadline
}

// -- BLS operations ---------------------------------------------------------

bool TpuVerifier::append_bls_record_(BlsContext* bls, Writer* w,
                                     const PublicKey& pk,
                                     const Signature& sig) {
  auto it = bls->public_keys.find(pk);
  if (it == bls->public_keys.end() || it->second.size() != kBlsPkLen ||
      sig.data.size() != kBlsSigLen) {
    return false;
  }
  w->out.insert(w->out.end(), it->second.begin(), it->second.end());
  w->out.insert(w->out.end(), sig.data.begin(), sig.data.end());
  return true;
}

namespace {
// Parses the single 0/1-byte reply of the BLS verify opcodes.
void parse_bool_reply(uint8_t opcode, uint32_t rid,
                      const TpuVerifier::BoolCallback& cb,
                      std::optional<Bytes> reply) {
  if (!reply) {
    cb(std::nullopt);
    return;
  }
  try {
    Reader r(*reply);
    uint8_t got_op = r.u8();
    uint32_t got_rid = r.u32();
    uint32_t n = r.u32();
    if (got_op != opcode || got_rid != rid || n != 1) {
      cb(std::nullopt);
      return;
    }
    cb(r.u8() != 0);
  } catch (const SerdeError&) {
    cb(std::nullopt);
  }
}
}  // namespace

std::optional<Bytes> TpuVerifier::bls_sign(const Digest& digest,
                                           const Bytes& sk48) {
  if (sk48.size() != kBlsSkLen) return std::nullopt;
  Writer w;
  uint32_t rid;
  {
    std::lock_guard<std::mutex> lk(inner_->m);
    rid = inner_->next_id++;
  }
  write_header(&w, kOpBlsSign, rid, 1);
  w.fixed(digest.data);
  w.out.insert(w.out.end(), sk48.begin(), sk48.end());
  Oneshot<std::optional<Bytes>> done;
  submit_(kOpBlsSign, w.out, rid, kBlsRecvTimeoutMs,
          [done, rid](std::optional<Bytes> reply) {
            if (!reply) {
              done.set(std::nullopt);
              return;
            }
            try {
              Reader r(*reply);
              uint8_t opcode = r.u8();
              uint32_t got_rid = r.u32();
              uint32_t n = r.u32();
              if (opcode != kOpBlsSign || got_rid != rid || n != kBlsSigLen) {
                done.set(std::nullopt);
                return;
              }
              Bytes sig(kBlsSigLen);
              for (auto& b : sig) b = r.u8();
              done.set(std::move(sig));
            } catch (const SerdeError&) {
              done.set(std::nullopt);
            }
          });
  return done.wait();
}

void TpuVerifier::bls_verify_votes_async(
    const Digest& digest,
    const std::vector<std::pair<PublicKey, Signature>>& votes,
    BoolCallback cb) {
  BlsContext* bls = BlsContext::instance();
  if (!bls) {
    cb(std::nullopt);
    return;
  }
  Writer w;
  uint32_t rid;
  {
    std::lock_guard<std::mutex> lk(inner_->m);
    rid = inner_->next_id++;
  }
  write_header(&w, kOpBlsVerifyVotes, rid,
               static_cast<uint32_t>(votes.size()));
  w.fixed(digest.data);  // one shared digest for the whole QC
  for (const auto& [pk, sig] : votes) {
    if (!append_bls_record_(bls, &w, pk, sig)) {
      cb(false);  // unknown authority / malformed sig: definitively invalid
      return;
    }
  }
  submit_(kOpBlsVerifyVotes, w.out, rid, kBlsRecvTimeoutMs,
          [cb = std::move(cb), rid](std::optional<Bytes> reply) {
            parse_bool_reply(kOpBlsVerifyVotes, rid, cb, std::move(reply));
          });
}

std::optional<bool> TpuVerifier::bls_verify_votes(
    const Digest& digest,
    const std::vector<std::pair<PublicKey, Signature>>& votes) {
  Oneshot<std::optional<bool>> done;
  bls_verify_votes_async(digest, votes, [done](std::optional<bool> ok) {
    done.set(std::move(ok));
  });
  return done.wait();
}

void TpuVerifier::bls_verify_multi_async(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    BoolCallback cb) {
  BlsContext* bls = BlsContext::instance();
  if (!bls) {
    cb(std::nullopt);
    return;
  }
  Writer w;
  uint32_t rid;
  {
    std::lock_guard<std::mutex> lk(inner_->m);
    rid = inner_->next_id++;
  }
  write_header(&w, kOpBlsVerifyMulti, rid,
               static_cast<uint32_t>(items.size()));
  for (const auto& [digest, pk, sig] : items) {
    w.fixed(digest.data);  // one digest PER record (the TC shape)
    if (!append_bls_record_(bls, &w, pk, sig)) {
      cb(false);
      return;
    }
  }
  submit_(kOpBlsVerifyMulti, w.out, rid, kBlsRecvTimeoutMs,
          [cb = std::move(cb), rid](std::optional<Bytes> reply) {
            parse_bool_reply(kOpBlsVerifyMulti, rid, cb, std::move(reply));
          });
}

std::optional<bool> TpuVerifier::bls_verify_multi(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items) {
  Oneshot<std::optional<bool>> done;
  bls_verify_multi_async(items, [done](std::optional<bool> ok) {
    done.set(std::move(ok));
  });
  return done.wait();
}

}  // namespace hotstuff

#include "crypto/sidecar_client.hpp"

#include <poll.h>

#include <algorithm>
#include <thread>

#include "common/channel.hpp"
#include "common/json.hpp"
#include "common/log.hpp"
#include "common/serde.hpp"
#include "crypto/crypto.hpp"

namespace hotstuff {

namespace {
constexpr uint8_t kOpVerifyBatch = 1;
constexpr uint8_t kOpBlsVerifyAgg = 3;  // NOLINT (wire constant, unused here)
constexpr uint8_t kOpBlsSign = 4;
constexpr uint8_t kOpBlsVerifyVotes = 5;
constexpr uint8_t kOpBlsVerifyMulti = 6;
// Protocol v2 (verifysched): bulk-class verifies ride a distinct opcode;
// kOpVerifyBatch stays the latency class (consensus QC/TC verifies), so
// the scheduler can launch them ahead of any bulk backlog.
constexpr uint8_t kOpVerifyBulk = 7;
// Telemetry snapshot: the reader polls this to adapt the async in-flight
// budget off the sidecar's latency queue-wait p99.
constexpr uint8_t kOpStats = 8;
// Protocol v3 (graftchaos): sidecar fault-injection hook. The node never
// sends it (the chaos harness does, via the python client).
constexpr uint8_t kOpChaos = 9;  // NOLINT (wire constant, unused here)
// Protocol v4 (graftsurge): reply-only BUSY opcode — a queue-full shed
// answers OP_BUSY with a u16 LE retry-after hint instead of the old
// empty-count echo.  This client treats it exactly like the legacy shed
// (host fallback now); the in-flight AIMD already paces resubmission,
// so the hint is logged, not slept on.
constexpr uint8_t kOpBusy = 10;
// Protocol v5 (graftscope): verify requests carry a 32-byte block-digest
// context tag between the header and the records (all-zero = none), so
// the sidecar's stage spans can be joined to the block's node-side
// trace.  Frame length discriminates tagged from legacy frames.
constexpr size_t kCtxLen = 32;
// Protocol v6 (graftfleet): HELLO tenant registration.  The request
// rides the standard header — the count field carries the CLIENT
// protocol version and msg_len carries the tenant byte length, with the
// tenant id as the body; the reply echoes the server version (1 byte) +
// the accepted tenant.  Connections that never HELLO schedule under the
// sidecar's default tenant, so the frame is strictly additive.
constexpr uint8_t kOpHello = 11;
constexpr uint8_t kProtocolVersion = 6;  // NOLINT (lint anchor; HELLO echo)
constexpr size_t kBlsPkLen = 96;
constexpr size_t kBlsSigLen = 192;
constexpr size_t kBlsSkLen = 48;
// Every message this client ships is a 32-byte digest (protocol.py
// DIGEST_LEN; graftlint cross-checks the two).
constexpr size_t kDigestLen = 32;
std::unique_ptr<TpuVerifier> g_instance;
// Request ids are allocated process-wide (graftfleet): a failover
// resubmits the identical frame bytes to another endpoint, so rids must
// be unique across every endpoint's pending map, not per-connection.
std::atomic<uint32_t> g_next_rid{0};

uint32_t next_rid() {
  // relaxed: only uniqueness is needed; frame bytes publish via the
  // per-endpoint socket write under the inner mutex.
  return g_next_rid.fetch_add(1, std::memory_order_relaxed);
}

void write_header(Writer* w, uint8_t opcode, uint32_t rid, uint32_t count) {
  w->u8(opcode);
  w->u32(rid);
  w->u32(count);
  w->u8(kDigestLen & 0xFF);  // msg_len lo (u16 LE)
  w->u8(kDigestLen >> 8);    // msg_len hi
}
}  // namespace

TpuVerifier::TpuVerifier(const Address& addr)
    : TpuVerifier(std::vector<Address>{addr}, std::string()) {}

TpuVerifier::TpuVerifier(std::vector<Address> addrs, std::string tenant)
    : addr_(addrs.empty() ? Address{} : addrs.front()) {
  if (addrs.empty()) addrs.push_back(Address{});
  inners_.reserve(addrs.size());
  for (size_t i = 0; i < addrs.size(); i++) {
    auto inner = std::make_shared<Inner>();
    // Construction precedes every reader/probe thread (ensure_connected_
    // locked_ spawns the first one later); the thread-start edge is the
    // happens-before, so these pre-publication writes need no lock.
    // graftlint: disable=guarded-member-unlocked (pre-publication write; thread-start edge below is the happens-before)
    inner->addr = addrs[i];
    // graftlint: disable=guarded-member-unlocked (pre-publication write; thread-start edge below is the happens-before)
    inner->ix = i;
    // graftlint: disable=guarded-member-unlocked (pre-publication write; thread-start edge below is the happens-before)
    inner->tenant = tenant;
    inners_.push_back(std::move(inner));
  }
  inner_ = inners_.front();
}

TpuVerifier::~TpuVerifier() {
  std::vector<FrameCallback> cbs;
  for (const auto& inner : inners_) {
    {
      std::lock_guard<std::mutex> lk(inner->m);
      inner->closing = true;  // probes exit; no new probe may start
      inner->gen++;  // stale readers exit without touching the socket
      for (auto& [rid, p] : inner->pending) cbs.push_back(std::move(p.cb));
      inner->pending.clear();
      // Wakes a reader blocked in poll/read; the Socket fd itself is
      // closed by ~Inner once the last reader drops its shared_ptr.
      inner->sock.shutdown();
    }
    inner->cv.notify_all();  // wakes a probe sleeping out its backoff
  }
  for (auto& cb : cbs) cb(std::nullopt);
}

TpuVerifier* TpuVerifier::instance() { return g_instance.get(); }

void TpuVerifier::install(std::unique_ptr<TpuVerifier> v) {
  g_instance = std::move(v);
}

bool TpuVerifier::connected() {
  size_t ix = 0;
  auto inner = pick_inner_(&ix);
  std::lock_guard<std::mutex> lk(inner->m);
  return ensure_connected_locked_(inner);
}

size_t TpuVerifier::inflight() const {
  size_t total = 0;
  for (const auto& inner : inners_) {
    std::lock_guard<std::mutex> lk(inner->m);
    total += inner->pending.size();
  }
  return total;
}

TpuVerifier::BreakerState TpuVerifier::breaker_state() const {
  std::lock_guard<std::mutex> lk(inner_->m);
  return inner_->breaker;
}

TpuVerifier::BreakerState TpuVerifier::breaker_state(size_t ix) const {
  const auto& inner = inners_.at(ix);
  std::lock_guard<std::mutex> lk(inner->m);
  return inner->breaker;
}

size_t TpuVerifier::endpoint_count() const { return inners_.size(); }

size_t TpuVerifier::active_endpoint() const {
  // relaxed: an advisory index; endpoint state is read under its mutex.
  return active_ix_.load(std::memory_order_relaxed);
}

int TpuVerifier::inflight_budget() const {
  // relaxed: any endpoint's budget is an acceptable answer mid-failover;
  // the budget itself is read under that inner's mutex.
  const auto& inner = inners_[active_ix_.load(std::memory_order_relaxed)];
  std::lock_guard<std::mutex> lk(inner->m);
  return inner->inflight_budget;
}

int TpuVerifier::adapt_budget(int current, double p99_ms) {
  // AIMD: a congested engine (queue-wait p99 past the shrink threshold)
  // halves the pipeline fast — every queued request is already paying
  // that wait, so piling more on only lengthens it — while a quiet one
  // creeps back up additively.  The hysteresis band between the two
  // thresholds keeps the budget from oscillating on a borderline load.
  if (p99_ms > kQueueWaitShrinkMs) {
    return std::max(kInflightBudgetMin, current / 2);
  }
  if (p99_ms < kQueueWaitGrowMs) {
    return std::min(kInflightBudgetMax, current + 8);
  }
  return current;
}

void TpuVerifier::set_backoff_for_test(int base_ms, int max_ms) {
  for (const auto& inner : inners_) {
    std::lock_guard<std::mutex> lk(inner->m);
    inner->backoff_base_ms = base_ms;
    inner->backoff_ms = base_ms;
    inner->backoff_max_ms = max_ms;
    inner->backoff_until = {};
  }
}

std::shared_ptr<TpuVerifier::Inner> TpuVerifier::pick_inner_(
    size_t* ix_out) {
  // relaxed: a stale index only costs one extra breaker check below —
  // every Inner field is read under its own mutex.
  size_t active = active_ix_.load(std::memory_order_relaxed);
  {
    const auto& inner = inners_[active];
    std::lock_guard<std::mutex> lk(inner->m);
    if (inner->breaker == BreakerState::kClosed) {
      *ix_out = active;
      return inner;
    }
  }
  // Active endpoint's breaker is open: re-home to the first healthy
  // endpoint scanning from 0 — a recovered PRIMARY (its probe closed
  // the breaker) is preferred over a later fallback, so the fleet
  // drifts back to its configured order after an outage.
  for (size_t i = 0; i < inners_.size(); i++) {
    if (i == active) continue;
    const auto& inner = inners_[i];
    std::lock_guard<std::mutex> lk(inner->m);
    if (inner->breaker == BreakerState::kClosed) {
      active_ix_.store(i, std::memory_order_relaxed);  // advisory index
      LOG_WARN("crypto::sidecar")
          << "sidecar failover: endpoint " << active
          << " unhealthy, re-homed to endpoint " << i << " ("
          << inner->addr.str() << ")";
      *ix_out = i;
      return inner;
    }
  }
  // No healthy endpoint: stay with the active one — its terminal
  // failure routes the caller to the host path, the LAST rung.
  *ix_out = active;
  return inners_[active];
}

bool TpuVerifier::ensure_connected_locked_(
    const std::shared_ptr<Inner>& inner) {
  Inner& in = *inner;
  if (in.closing) return false;
  if (in.sock.valid()) return true;
  if (in.breaker != BreakerState::kClosed) {
    // Open (or probing): the host path answers immediately; reconnection
    // is the probe thread's job, never a verify's.
    start_probe_locked_(inner);
    return false;
  }
  if (std::chrono::steady_clock::now() < in.backoff_until) return false;
  auto s = Socket::connect(in.addr, kConnectTimeoutMs);
  if (!s) {
    if (in.ever_connected) {
      LOG_WARN("crypto::sidecar") << "lost connection to verify sidecar "
                                  << in.addr.str();
      in.ever_connected = false;
    }
    note_failure_locked_(inner, "connect failed");
    return false;
  }
  in.sock = std::move(*s);
  // Backstop only: the reader polls with its own timeout; this bounds a
  // pathological partial frame.
  in.sock.set_recv_timeout(kRecvTimeoutMs);
  in.gen++;
  in.last_rx = std::chrono::steady_clock::now();
  in.consecutive_failures = 0;
  in.backoff_ms = in.backoff_base_ms;
  if (!in.ever_connected) {
    LOG_INFO("crypto::sidecar") << "connected to verify sidecar "
                                << in.addr.str();
  }
  in.ever_connected = true;
  std::thread(reader_loop_, inner, in.gen, in.sock.fd()).detach();
  send_hello_locked_(inner);
  return true;
}

void TpuVerifier::send_hello_locked_(const std::shared_ptr<Inner>& inner) {
  Inner& in = *inner;
  if (in.tenant.empty()) return;
  uint32_t rid = next_rid();
  Writer w;
  w.u8(kOpHello);
  w.u32(rid);
  w.u32(kProtocolVersion);  // count field carries the client version
  w.u8(in.tenant.size() & 0xFF);  // msg_len = tenant byte length
  w.u8((in.tenant.size() >> 8) & 0xFF);
  for (char c : in.tenant) w.u8(static_cast<uint8_t>(c));
  PendingReq req;
  req.opcode = kOpHello;
  req.deadline = std::chrono::steady_clock::now() +
                 std::chrono::milliseconds(kRecvTimeoutMs);
  std::string tenant = in.tenant;
  size_t ix = in.ix;
  req.cb = [tenant, ix](std::optional<Bytes> reply) {
    if (!reply) return;  // transport failure: the reader handled it
    try {
      Reader r(*reply);
      uint8_t op = r.u8();
      r.u32();  // rid (already matched by the reader)
      uint32_t n = r.u32();
      if (op != kOpHello || n < 1) {
        LOG_WARN("crypto::sidecar")
            << "HELLO rejected by sidecar endpoint " << ix << " (tenant "
            << tenant << ")";
        return;
      }
      uint8_t version = r.u8();
      if (version != kProtocolVersion) {
        LOG_WARN("crypto::sidecar")
            << "sidecar protocol version skew on endpoint " << ix
            << ": server v" << int(version) << ", client v"
            << int(kProtocolVersion);
      } else {
        LOG_INFO("crypto::sidecar")
            << "HELLO accepted by endpoint " << ix << ": tenant "
            << tenant << " (protocol v" << int(version) << ")";
      }
    } catch (const SerdeError&) {
      LOG_WARN("crypto::sidecar") << "malformed HELLO reply";
    }
  };
  in.pending.emplace(rid, std::move(req));
  if (!in.sock.write_frame(w.out)) in.sock.shutdown();
}

void TpuVerifier::note_failure_locked_(const std::shared_ptr<Inner>& inner,
                                       const char* why) {
  Inner& in = *inner;
  in.backoff_until = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(in.backoff_ms);
  in.consecutive_failures++;
  if (in.breaker == BreakerState::kClosed &&
      in.consecutive_failures >= kBreakerThreshold) {
    in.breaker = BreakerState::kOpen;
    LOG_WARN("crypto::sidecar")
        << "circuit breaker OPEN after " << in.consecutive_failures
        << " consecutive transport failures (" << why
        << "): verifying on host, probing " << in.addr.str() << " every "
        << in.backoff_ms << "+ ms";
    start_probe_locked_(inner);
  }
}

void TpuVerifier::start_probe_locked_(const std::shared_ptr<Inner>& inner) {
  if (inner->probe_running || inner->closing ||
      inner->breaker == BreakerState::kClosed) {
    return;
  }
  inner->probe_running = true;
  std::thread(probe_loop_, inner).detach();
}

// Half-open reconnect loop: sleep out the current backoff, try one
// connect, double the backoff on failure (capped).  Owns breaker state
// transitions while the breaker is open; exits as soon as it re-attaches,
// the client is destroyed, or something else closed the breaker.
void TpuVerifier::probe_loop_(std::shared_ptr<Inner> inner) {
  std::unique_lock<std::mutex> lk(inner->m);
  while (!inner->closing && inner->breaker != BreakerState::kClosed) {
    inner->breaker = BreakerState::kOpen;
    inner->cv.wait_for(lk, std::chrono::milliseconds(inner->backoff_ms),
                       [&] { return inner->closing; });
    if (inner->closing) break;
    inner->breaker = BreakerState::kHalfOpen;
    Address addr = inner->addr;
    lk.unlock();
    auto s = Socket::connect(addr, kConnectTimeoutMs);
    lk.lock();
    if (inner->closing) break;
    if (s) {
      inner->sock = std::move(*s);
      inner->sock.set_recv_timeout(kRecvTimeoutMs);
      inner->gen++;
      inner->last_rx = std::chrono::steady_clock::now();
      inner->breaker = BreakerState::kClosed;
      inner->consecutive_failures = 0;
      inner->backoff_ms = inner->backoff_base_ms;
      inner->backoff_until = {};
      inner->ever_connected = true;
      LOG_INFO("crypto::sidecar")
          << "circuit breaker CLOSED: re-attached to verify sidecar "
          << addr.str();
      std::thread(reader_loop_, inner, inner->gen, inner->sock.fd())
          .detach();
      send_hello_locked_(inner);
      break;
    }
    inner->backoff_ms =
        std::min(inner->backoff_ms * 2, inner->backoff_max_ms);
    LOG_DEBUG("crypto::sidecar")
        << "breaker probe failed; next probe in " << inner->backoff_ms
        << " ms";
  }
  inner->probe_running = false;
}

// Fails every pending request and closes the socket. The reader of `gen`
// is the only caller while its socket lives, so close here cannot race a
// concurrent read; writers write under the same lock.
void TpuVerifier::fail_all_(const std::shared_ptr<Inner>& inner,
                            uint64_t gen, const char* why) {
  std::vector<FrameCallback> cbs;
  {
    std::lock_guard<std::mutex> lk(inner->m);
    if (inner->gen != gen) return;  // a newer connection took over
    if (!inner->pending.empty()) {
      LOG_WARN("crypto::sidecar")
          << "failing " << inner->pending.size()
          << " in-flight sidecar request(s): " << why;
    }
    for (auto& [rid, p] : inner->pending) cbs.push_back(std::move(p.cb));
    inner->pending.clear();
    inner->sock.close();
    note_failure_locked_(inner, why);
  }
  for (auto& cb : cbs) cb(std::nullopt);
}

void TpuVerifier::reader_loop_(std::shared_ptr<Inner> inner, uint64_t gen,
                               int fd) {
  for (;;) {
    {
      std::lock_guard<std::mutex> lk(inner->m);
      if (inner->gen != gen || !inner->sock.valid()) return;
    }
    pollfd p{fd, POLLIN, 0};
    int rc = ::poll(&p, 1, 200);
    if (rc < 0) {
      if (errno == EINTR) continue;
      fail_all_(inner, gen, "poll error");
      return;
    }
    // Deadline sweep EVERY iteration (not only on poll timeout): under
    // continuous reply traffic an orphaned request — one the sidecar
    // never answers — must still expire, or a sync wrapper blocked on it
    // waits forever.  Expire overdue requests individually; if nothing at
    // all has arrived for a full receive window while requests are
    // overdue, the connection (or the engine behind it) is wedged.
    auto now = std::chrono::steady_clock::now();
    {
      std::vector<FrameCallback> expired;
      bool wedged = false;
      {
        std::lock_guard<std::mutex> lk(inner->m);
        if (inner->gen != gen) return;
        for (auto it = inner->pending.begin(); it != inner->pending.end();) {
          if (now > it->second.deadline) {
            expired.push_back(std::move(it->second.cb));
            it = inner->pending.erase(it);
          } else {
            ++it;
          }
        }
        wedged = !expired.empty() &&
                 now - inner->last_rx >
                     std::chrono::milliseconds(kRecvTimeoutMs);
      }
      for (auto& cb : expired) cb(std::nullopt);
      if (wedged) {
        fail_all_(inner, gen, "no replies within deadline");
        return;
      }
    }
    // Telemetry heartbeat rides the same pipelined connection: at most
    // one OP_STATS request per kStatsIntervalMs, whose reply adapts the
    // async in-flight budget off the engine's queue-wait p99.
    maybe_poll_stats_(inner, gen);
    if (rc == 0) continue;
    Bytes reply;
    // Safe without the lock: this reader is the only thread reading, and
    // only this reader closes the gen's socket (writers only shutdown(),
    // which is async-signal-safe against a concurrent read); holding m
    // across a blocking read_frame would wedge every submitter.
    // graftlint: disable=guarded-member-unlocked
    if (!inner->sock.read_frame(&reply)) {
      fail_all_(inner, gen, "connection closed by sidecar");
      return;
    }
    FrameCallback cb;
    {
      std::lock_guard<std::mutex> lk(inner->m);
      if (inner->gen != gen) return;
      inner->last_rx = now;
      if (reply.size() >= 5) {
        // graftguard: an OP_BUSY reply is a LIVE sidecar shedding
        // honestly — its engine may be mid crash-only reboot, during
        // which bulk gets BUSY and latency is host-answered, never
        // silence.  That is liveness evidence: clear any accumulated
        // transport-failure count so the breaker cannot open off a
        // stale tally while the sidecar re-warms (the breaker exists
        // for a sidecar that stops ANSWERING, not one that sheds).
        if (reply[0] == kOpBusy) inner->consecutive_failures = 0;
        uint32_t rid = static_cast<uint32_t>(reply[1]) |
                       static_cast<uint32_t>(reply[2]) << 8 |
                       static_cast<uint32_t>(reply[3]) << 16 |
                       static_cast<uint32_t>(reply[4]) << 24;
        auto it = inner->pending.find(rid);
        if (it != inner->pending.end()) {
          cb = std::move(it->second.cb);
          inner->pending.erase(it);
        }
      }
    }
    if (cb) {
      cb(std::move(reply));
    } else {
      LOG_DEBUG("crypto::sidecar") << "dropping late/unknown sidecar reply";
    }
  }
}

void TpuVerifier::maybe_poll_stats_(const std::shared_ptr<Inner>& inner,
                                    uint64_t gen) {
  std::lock_guard<std::mutex> lk(inner->m);
  if (inner->gen != gen || !inner->sock.valid()) return;
  auto now = std::chrono::steady_clock::now();
  if (now - inner->last_stats_tx <
      std::chrono::milliseconds(kStatsIntervalMs)) {
    return;
  }
  inner->last_stats_tx = now;
  uint32_t rid = next_rid();
  Writer w;
  write_header(&w, kOpStats, rid, 0);
  PendingReq req;
  req.opcode = kOpStats;
  req.deadline = now + std::chrono::milliseconds(kRecvTimeoutMs);
  std::weak_ptr<Inner> weak = inner;
  req.cb = [weak, rid](std::optional<Bytes> reply) {
    handle_stats_reply_(weak, rid, std::move(reply));
  };
  inner->pending.emplace(rid, std::move(req));
  if (!inner->sock.write_frame(w.out)) inner->sock.shutdown();
}

void TpuVerifier::handle_stats_reply_(const std::weak_ptr<Inner>& weak,
                                      uint32_t rid,
                                      std::optional<Bytes> reply) {
  if (!reply) return;  // transport failure: budget stays as it was
  double p99 = -1.0;
  try {
    Reader r(*reply);
    uint8_t op = r.u8();
    uint32_t got_rid = r.u32();
    uint32_t n = r.u32();
    if (op != kOpStats || got_rid != rid) return;
    std::string body;
    body.reserve(n);
    for (uint32_t i = 0; i < n; i++) body.push_back(char(r.u8()));
    Json snap = Json::parse(body);
    const Json* waits = snap.find("queue_wait");
    if (!waits || !waits->is_object()) return;
    const Json* lat = waits->find("latency");
    if (!lat || !lat->is_object()) return;
    const Json* p99j = lat->find("p99_ms");
    const Json* count = lat->find("n");
    // No samples yet means no evidence of congestion either way.
    if (!p99j || !count || count->as_u64() == 0) return;
    p99 = p99j->as_number();
  } catch (const SerdeError&) {
    return;
  } catch (const JsonError&) {
    return;
  }
  auto inner = weak.lock();
  if (!inner) return;
  int before;
  int after;
  {
    std::lock_guard<std::mutex> lk(inner->m);
    before = inner->inflight_budget;
    inner->inflight_budget = adapt_budget(before, p99);
    after = inner->inflight_budget;
  }
  if (after != before) {
    LOG_INFO("crypto::sidecar")
        << "async in-flight budget " << before << " -> " << after
        << " (sidecar latency queue-wait p99 " << p99 << " ms)";
  }
}

void TpuVerifier::submit_on_(const std::shared_ptr<Inner>& inner,
                             uint8_t opcode, const Bytes& frame,
                             uint32_t rid, int deadline_ms,
                             FrameCallback cb) {
  bool fail = false;
  {
    std::lock_guard<std::mutex> lk(inner->m);
    if (!ensure_connected_locked_(inner)) {
      fail = true;
    } else {
      PendingReq req;
      req.opcode = opcode;
      req.deadline = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(deadline_ms);
      req.cb = std::move(cb);
      inner->pending.emplace(rid, std::move(req));
      if (!inner->sock.write_frame(frame)) {
        // The reader owns teardown: wake it and let fail_all_ invoke the
        // callback we just registered (along with any other pendings).
        inner->sock.shutdown();
      }
    }
  }
  if (fail) cb(std::nullopt);
}

// graftfleet failover: on a terminal transport failure the identical
// frame bytes are resubmitted to the next untried healthy endpoint (rids
// are process-unique, so the frame needs no rewrite).  An OP_BUSY shed
// arrives as a real reply and never lands here — overload means the
// endpoint is ALIVE, and re-submitting elsewhere would just migrate the
// flood.  Only when every endpoint has been tried (or is breaker-open)
// does the caller see nullopt and take the host path — the last rung of
// the ladder, behind every healthy fleet member.
void TpuVerifier::submit_failover_(
    std::vector<std::shared_ptr<Inner>> endpoints, uint8_t opcode,
    Bytes frame, uint32_t rid, int deadline_ms, FrameCallback cb,
    uint32_t tried, size_t ix) {
  auto inner = endpoints[ix];
  FrameCallback wrapped =
      [endpoints = std::move(endpoints), opcode, frame, rid, deadline_ms,
       cb = std::move(cb), tried, ix](std::optional<Bytes> reply) mutable {
        if (reply) {
          cb(std::move(reply));
          return;
        }
        for (size_t j = 0; j < endpoints.size(); j++) {
          if (tried & (1u << (j & 31))) continue;
          {
            std::lock_guard<std::mutex> lk(endpoints[j]->m);
            if (endpoints[j]->closing ||
                endpoints[j]->breaker != BreakerState::kClosed) {
              continue;
            }
          }
          LOG_WARN("crypto::sidecar")
              << "sidecar failover: endpoint " << ix
              << " failed in flight, resubmitting to endpoint " << j;
          submit_failover_(std::move(endpoints), opcode, std::move(frame),
                           rid, deadline_ms, std::move(cb),
                           tried | (1u << (j & 31)), j);
          return;
        }
        cb(std::nullopt);
      };
  submit_on_(inner, opcode, frame, rid, deadline_ms, std::move(wrapped));
}

void TpuVerifier::submit_(uint8_t opcode, const Bytes& frame, uint32_t rid,
                          int deadline_ms, FrameCallback cb) {
  if (inners_.size() == 1) {
    // Single-endpoint topology: no failover ladder to walk — the
    // pre-fleet behavior, byte for byte.
    submit_on_(inner_, opcode, frame, rid, deadline_ms, std::move(cb));
    return;
  }
  size_t ix = 0;
  pick_inner_(&ix);
  submit_failover_(inners_, opcode, frame, rid, deadline_ms, std::move(cb),
                   1u << (ix & 31), ix);
}

// -- Ed25519 ---------------------------------------------------------------

void TpuVerifier::verify_batch_multi_async(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    MaskCallback cb, bool bulk, const Digest* ctx) {
  verify_batch_multi_async_ex(
      items,
      [cb = std::move(cb)](std::optional<std::vector<bool>> mask,
                           int /*busy_retry_ms*/) { cb(std::move(mask)); },
      bulk, ctx);
}

void TpuVerifier::verify_batch_multi_async_ex(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    MaskBusyCallback cb, bool bulk, const Digest* ctx) {
  // Class tag rides the opcode: consensus QC/TC verifies stay latency
  // class (the sidecar launches them ahead of any bulk backlog); bulk
  // callers (offchain sweeps, mempool-style batches) must say so.
  const uint8_t opcode = bulk ? kOpVerifyBulk : kOpVerifyBatch;
  Writer w;
  uint32_t rid = next_rid();
  write_header(&w, opcode, rid, static_cast<uint32_t>(items.size()));
  // Protocol v5 context tag, written ONLY when a block context exists:
  // the tag rides between header and records and the sidecar
  // discriminates by frame length, so an untagged frame is byte-for-
  // byte the legacy v4 form — a node upgraded before its sidecar keeps
  // verifying (no-ctx callers emit frames a v4 decoder still accepts,
  // and tagged frames only flow once tracing-relevant traffic exists).
  // An all-zero tag is also legal on the wire and decodes as "none".
  if (ctx != nullptr) {
    static_assert(sizeof(ctx->data) == kCtxLen, "ctx tag is a digest");
    w.fixed(ctx->data);
  }
  for (const auto& [digest, pk, sig] : items) {
    if (sig.data.size() != 64) {  // not an Ed25519 sig
      cb(std::nullopt, -1);
      return;
    }
    w.fixed(digest.data);
    w.fixed(pk.data);
    w.out.insert(w.out.end(), sig.data.begin(), sig.data.end());
  }
  size_t n_items = items.size();
  submit_(opcode, w.out, rid, kRecvTimeoutMs,
          [cb = std::move(cb), rid, n_items,
           opcode](std::optional<Bytes> reply) {
            if (!reply) {
              cb(std::nullopt, -1);
              return;
            }
            try {
              Reader r(*reply);
              uint8_t got_op = r.u8();
              uint32_t got_rid = r.u32();
              uint32_t n = r.u32();
              if (got_op == kOpBusy && got_rid == rid) {
                // Explicit backpressure (v4): the sidecar shed this
                // request; the body's u16 retry-after hint is advisory
                // — latency callers host-fallback now (the async budget
                // AIMD paces resubmission), the ingress bulk lane paces
                // a bounded retry off the surfaced hint.
                uint32_t hint_ms = 0;
                if (n == 2) {
                  // Sequenced reads: the | operands are unsequenced in
                  // C++17 and u8() advances the reader.
                  uint32_t lo = r.u8();
                  hint_ms = lo | uint32_t(r.u8()) << 8;
                }
                LOG_DEBUG("crypto::sidecar")
                    << "sidecar busy (retry-after " << hint_ms
                    << " ms); falling back to host";
                cb(std::nullopt, int(hint_ms));
                return;
              }
              if (got_op == opcode && got_rid == rid && n == 0 &&
                  n_items != 0) {
                // Legacy (v2/v3) shed form: empty-count echo, no hint.
                LOG_DEBUG("crypto::sidecar") << "sidecar queue full; "
                                                "falling back to host";
                cb(std::nullopt, 0);
                return;
              }
              if (got_op != opcode || got_rid != rid || n != n_items) {
                LOG_WARN("crypto::sidecar") << "protocol mismatch from sidecar";
                cb(std::nullopt, -1);
                return;
              }
              std::vector<bool> mask(n);
              for (uint32_t i = 0; i < n; i++) mask[i] = r.u8() != 0;
              cb(std::move(mask), -1);
            } catch (const SerdeError&) {
              cb(std::nullopt, -1);
            }
          });
}

std::optional<std::vector<bool>> TpuVerifier::verify_batch_multi(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    bool bulk, const Digest* ctx) {
  Oneshot<std::optional<std::vector<bool>>> done;
  verify_batch_multi_async(
      items,
      [done](std::optional<std::vector<bool>> mask) {
        done.set(std::move(mask));
      },
      bulk, ctx);
  return done.wait();  // bounded: every submitted callback fires by deadline
}

// -- BLS operations ---------------------------------------------------------

bool TpuVerifier::append_bls_record_(BlsContext* bls, Writer* w,
                                     const PublicKey& pk,
                                     const Signature& sig) {
  auto it = bls->public_keys.find(pk);
  if (it == bls->public_keys.end() || it->second.size() != kBlsPkLen ||
      sig.data.size() != kBlsSigLen) {
    return false;
  }
  w->out.insert(w->out.end(), it->second.begin(), it->second.end());
  w->out.insert(w->out.end(), sig.data.begin(), sig.data.end());
  return true;
}

namespace {
// Parses the single 0/1-byte reply of the BLS verify opcodes.
void parse_bool_reply(uint8_t opcode, uint32_t rid,
                      const TpuVerifier::BoolCallback& cb,
                      std::optional<Bytes> reply) {
  if (!reply) {
    cb(std::nullopt);
    return;
  }
  try {
    Reader r(*reply);
    uint8_t got_op = r.u8();
    uint32_t got_rid = r.u32();
    uint32_t n = r.u32();
    if (got_op == kOpBusy && got_rid == rid) {
      // v4 shed: overload is nullopt (caller's host fallback), never a
      // 'false' verdict — an overload must not read as forged.
      cb(std::nullopt);
      return;
    }
    if (got_op != opcode || got_rid != rid || n != 1) {
      cb(std::nullopt);
      return;
    }
    cb(r.u8() != 0);
  } catch (const SerdeError&) {
    cb(std::nullopt);
  }
}
}  // namespace

std::optional<Bytes> TpuVerifier::bls_sign(const Digest& digest,
                                           const Bytes& sk48) {
  if (sk48.size() != kBlsSkLen) return std::nullopt;
  Writer w;
  uint32_t rid = next_rid();
  write_header(&w, kOpBlsSign, rid, 1);
  w.fixed(digest.data);
  w.out.insert(w.out.end(), sk48.begin(), sk48.end());
  Oneshot<std::optional<Bytes>> done;
  submit_(kOpBlsSign, w.out, rid, kBlsRecvTimeoutMs,
          [done, rid](std::optional<Bytes> reply) {
            if (!reply) {
              done.set(std::nullopt);
              return;
            }
            try {
              Reader r(*reply);
              uint8_t opcode = r.u8();
              uint32_t got_rid = r.u32();
              uint32_t n = r.u32();
              if (opcode != kOpBlsSign || got_rid != rid || n != kBlsSigLen) {
                done.set(std::nullopt);
                return;
              }
              Bytes sig(kBlsSigLen);
              for (auto& b : sig) b = r.u8();
              done.set(std::move(sig));
            } catch (const SerdeError&) {
              done.set(std::nullopt);
            }
          });
  return done.wait();
}

void TpuVerifier::bls_verify_votes_async(
    const Digest& digest,
    const std::vector<std::pair<PublicKey, Signature>>& votes,
    BoolCallback cb, const Digest* ctx) {
  BlsContext* bls = BlsContext::instance();
  if (!bls) {
    cb(std::nullopt);
    return;
  }
  Writer w;
  uint32_t rid = next_rid();
  write_header(&w, kOpBlsVerifyVotes, rid,
               static_cast<uint32_t>(votes.size()));
  // v5 context tag: same slot (between header and body) and same
  // length-discriminated optionality as the Ed25519 frames — a BLS
  // record is 288 bytes, so the 32 tag bytes can never alias one.
  if (ctx != nullptr) {
    static_assert(sizeof(ctx->data) == kCtxLen, "ctx tag is a digest");
    w.fixed(ctx->data);
  }
  w.fixed(digest.data);  // one shared digest for the whole QC
  for (const auto& [pk, sig] : votes) {
    if (!append_bls_record_(bls, &w, pk, sig)) {
      cb(false);  // unknown authority / malformed sig: definitively invalid
      return;
    }
  }
  submit_(kOpBlsVerifyVotes, w.out, rid, kBlsRecvTimeoutMs,
          [cb = std::move(cb), rid](std::optional<Bytes> reply) {
            parse_bool_reply(kOpBlsVerifyVotes, rid, cb, std::move(reply));
          });
}

std::optional<bool> TpuVerifier::bls_verify_votes(
    const Digest& digest,
    const std::vector<std::pair<PublicKey, Signature>>& votes,
    const Digest* ctx) {
  Oneshot<std::optional<bool>> done;
  bls_verify_votes_async(
      digest, votes,
      [done](std::optional<bool> ok) { done.set(std::move(ok)); }, ctx);
  return done.wait();
}

void TpuVerifier::bls_verify_multi_async(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    BoolCallback cb, const Digest* ctx) {
  BlsContext* bls = BlsContext::instance();
  if (!bls) {
    cb(std::nullopt);
    return;
  }
  Writer w;
  uint32_t rid = next_rid();
  write_header(&w, kOpBlsVerifyMulti, rid,
               static_cast<uint32_t>(items.size()));
  if (ctx != nullptr) {
    static_assert(sizeof(ctx->data) == kCtxLen, "ctx tag is a digest");
    w.fixed(ctx->data);
  }
  for (const auto& [digest, pk, sig] : items) {
    w.fixed(digest.data);  // one digest PER record (the TC shape)
    if (!append_bls_record_(bls, &w, pk, sig)) {
      cb(false);
      return;
    }
  }
  submit_(kOpBlsVerifyMulti, w.out, rid, kBlsRecvTimeoutMs,
          [cb = std::move(cb), rid](std::optional<Bytes> reply) {
            parse_bool_reply(kOpBlsVerifyMulti, rid, cb, std::move(reply));
          });
}

std::optional<bool> TpuVerifier::bls_verify_multi(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
    const Digest* ctx) {
  Oneshot<std::optional<bool>> done;
  bls_verify_multi_async(
      items,
      [done](std::optional<bool> ok) { done.set(std::move(ok)); }, ctx);
  return done.wait();
}

}  // namespace hotstuff

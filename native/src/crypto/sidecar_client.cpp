#include "crypto/sidecar_client.hpp"

#include "common/log.hpp"
#include "common/serde.hpp"
#include "crypto/crypto.hpp"

namespace hotstuff {

namespace {
constexpr uint8_t kOpVerifyBatch = 1;
constexpr uint8_t kOpBlsVerifyAgg = 3;
constexpr uint8_t kOpBlsSign = 4;
constexpr uint8_t kOpBlsVerifyVotes = 5;
constexpr uint8_t kOpBlsVerifyMulti = 6;
constexpr size_t kBlsPkLen = 96;
constexpr size_t kBlsSigLen = 192;
constexpr size_t kBlsSkLen = 48;
std::unique_ptr<TpuVerifier> g_instance;
}  // namespace

TpuVerifier::TpuVerifier(const Address& addr) : addr_(addr) {}

TpuVerifier* TpuVerifier::instance() { return g_instance.get(); }

void TpuVerifier::install(std::unique_ptr<TpuVerifier> v) {
  g_instance = std::move(v);
}

bool TpuVerifier::connected() {
  std::lock_guard<std::mutex> lk(m_);
  return ensure_connected_locked();
}

bool TpuVerifier::ensure_connected_locked() {
  if (sock_.valid()) return true;
  if (std::chrono::steady_clock::now() < backoff_until_) return false;
  auto s = Socket::connect(addr_, kConnectTimeoutMs);
  if (!s) {
    backoff_until_ = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(kBackoffMs);
    if (!ever_connected_) return false;
    LOG_WARN("crypto::sidecar") << "lost connection to verify sidecar "
                                << addr_.str();
    ever_connected_ = false;
    return false;
  }
  sock_ = std::move(*s);
  sock_.set_recv_timeout(kRecvTimeoutMs);
  if (!ever_connected_) {
    LOG_INFO("crypto::sidecar") << "connected to verify sidecar "
                                << addr_.str();
  }
  ever_connected_ = true;
  return true;
}

std::optional<std::vector<bool>> TpuVerifier::verify_batch_multi(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items) {
  std::lock_guard<std::mutex> lk(m_);
  if (!ensure_connected_locked()) return std::nullopt;

  // Request: u8 opcode | u32 rid | u32 count | u16 msg_len | records.
  Writer w;
  uint32_t rid = next_id_++;
  w.u8(kOpVerifyBatch);
  w.u32(rid);
  w.u32(static_cast<uint32_t>(items.size()));
  w.u8(32);  // msg_len lo (u16 LE)
  w.u8(0);   // msg_len hi
  for (const auto& [digest, pk, sig] : items) {
    if (sig.data.size() != 64) return std::nullopt;  // not an Ed25519 sig
    w.fixed(digest.data);
    w.fixed(pk.data);
    w.out.insert(w.out.end(), sig.data.begin(), sig.data.end());
  }
  if (!sock_.write_frame(w.out)) {
    sock_.close();
    backoff_until_ = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(kBackoffMs);
    return std::nullopt;
  }

  // Bounded wait (SO_RCVTIMEO set at connect): a wedged sidecar costs at
  // most kRecvTimeoutMs once per backoff window, then the caller's host
  // fallback takes over. Closing the socket also discards any late reply,
  // so request/reply framing can never desynchronize.
  Bytes reply;
  if (!sock_.read_frame(&reply)) {
    LOG_WARN("crypto::sidecar")
        << "sidecar read failed/timed out; falling back to host verify";
    sock_.close();
    backoff_until_ = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(kBackoffMs);
    return std::nullopt;
  }
  try {
    Reader r(reply);
    uint8_t opcode = r.u8();
    uint32_t got_rid = r.u32();
    uint32_t n = r.u32();
    if (opcode != kOpVerifyBatch || got_rid != rid || n != items.size()) {
      LOG_WARN("crypto::sidecar") << "protocol mismatch from sidecar";
      sock_.close();
      return std::nullopt;
    }
    std::vector<bool> mask(n);
    for (uint32_t i = 0; i < n; i++) mask[i] = r.u8() != 0;
    return mask;
  } catch (const SerdeError&) {
    sock_.close();
    return std::nullopt;
  }
}

// -- BLS operations ---------------------------------------------------------

// One request/reply exchange under the (longer) BLS deadline; resets the
// socket on any failure so framing can't desynchronize.
std::optional<Bytes> TpuVerifier::bls_roundtrip_locked_(const Bytes& frame) {
  if (!ensure_connected_locked()) return std::nullopt;
  sock_.set_recv_timeout(kBlsRecvTimeoutMs);
  bool ok = sock_.write_frame(frame);
  Bytes reply;
  if (ok) ok = sock_.read_frame(&reply);
  sock_.set_recv_timeout(kRecvTimeoutMs);
  if (!ok) {
    LOG_WARN("crypto::sidecar") << "BLS sidecar exchange failed";
    sock_.close();
    backoff_until_ = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(kBackoffMs);
    return std::nullopt;
  }
  return reply;
}

std::optional<Bytes> TpuVerifier::bls_sign(const Digest& digest,
                                           const Bytes& sk48) {
  if (sk48.size() != kBlsSkLen) return std::nullopt;
  std::lock_guard<std::mutex> lk(m_);
  Writer w;
  uint32_t rid = next_id_++;
  w.u8(kOpBlsSign);
  w.u32(rid);
  w.u32(1);
  w.u8(32);  // msg_len lo (u16 LE)
  w.u8(0);
  w.fixed(digest.data);
  w.out.insert(w.out.end(), sk48.begin(), sk48.end());
  auto reply = bls_roundtrip_locked_(w.out);
  if (!reply) return std::nullopt;
  try {
    Reader r(*reply);
    uint8_t opcode = r.u8();
    uint32_t got_rid = r.u32();
    uint32_t n = r.u32();
    if (opcode != kOpBlsSign || got_rid != rid || n != kBlsSigLen) {
      return std::nullopt;
    }
    Bytes sig(kBlsSigLen);
    for (auto& b : sig) b = r.u8();
    return sig;
  } catch (const SerdeError&) {
    sock_.close();
    return std::nullopt;
  }
}

// Append one committee vote record (pk looked up in BlsContext, then
// signature) to `w`; false = unknown authority or malformed signature.
bool TpuVerifier::append_bls_record_(BlsContext* bls, Writer* w,
                                     const PublicKey& pk,
                                     const Signature& sig) {
  auto it = bls->public_keys.find(pk);
  if (it == bls->public_keys.end() || it->second.size() != kBlsPkLen ||
      sig.data.size() != kBlsSigLen) {
    return false;
  }
  w->out.insert(w->out.end(), it->second.begin(), it->second.end());
  w->out.insert(w->out.end(), sig.data.begin(), sig.data.end());
  return true;
}

// Exchange `w` under the BLS deadline and parse the single 0/1-byte reply.
std::optional<bool> TpuVerifier::bls_bool_exchange_locked_(
    const Writer& w, uint8_t opcode, uint32_t rid) {
  auto reply = bls_roundtrip_locked_(w.out);
  if (!reply) return std::nullopt;
  try {
    Reader r(*reply);
    uint8_t got_op = r.u8();
    uint32_t got_rid = r.u32();
    uint32_t n = r.u32();
    if (got_op != opcode || got_rid != rid || n != 1) return std::nullopt;
    return r.u8() != 0;
  } catch (const SerdeError&) {
    sock_.close();
    return std::nullopt;
  }
}

std::optional<bool> TpuVerifier::bls_verify_votes(
    const Digest& digest,
    const std::vector<std::pair<PublicKey, Signature>>& votes) {
  BlsContext* bls = BlsContext::instance();
  if (!bls) return std::nullopt;
  std::lock_guard<std::mutex> lk(m_);
  Writer w;
  uint32_t rid = next_id_++;
  w.u8(kOpBlsVerifyVotes);
  w.u32(rid);
  w.u32(static_cast<uint32_t>(votes.size()));
  w.u8(32);  // msg_len lo (u16 LE)
  w.u8(0);
  w.fixed(digest.data);  // one shared digest for the whole QC
  for (const auto& [pk, sig] : votes) {
    if (!append_bls_record_(bls, &w, pk, sig)) return false;
  }
  return bls_bool_exchange_locked_(w, kOpBlsVerifyVotes, rid);
}

std::optional<bool> TpuVerifier::bls_verify_multi(
    const std::vector<std::tuple<Digest, PublicKey, Signature>>& items) {
  BlsContext* bls = BlsContext::instance();
  if (!bls) return std::nullopt;
  std::lock_guard<std::mutex> lk(m_);
  Writer w;
  uint32_t rid = next_id_++;
  w.u8(kOpBlsVerifyMulti);
  w.u32(rid);
  w.u32(static_cast<uint32_t>(items.size()));
  w.u8(32);  // msg_len lo (u16 LE)
  w.u8(0);
  for (const auto& [digest, pk, sig] : items) {
    w.fixed(digest.data);  // one digest PER record (the TC shape)
    if (!append_bls_record_(bls, &w, pk, sig)) return false;
  }
  return bls_bool_exchange_locked_(w, kOpBlsVerifyMulti, rid);
}

}  // namespace hotstuff

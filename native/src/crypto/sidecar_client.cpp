#include "crypto/sidecar_client.hpp"

#include "common/log.hpp"
#include "common/serde.hpp"
#include "crypto/crypto.hpp"

namespace hotstuff {

namespace {
constexpr uint8_t kOpVerifyBatch = 1;
std::unique_ptr<TpuVerifier> g_instance;
}  // namespace

TpuVerifier::TpuVerifier(const Address& addr) : addr_(addr) {}

TpuVerifier* TpuVerifier::instance() { return g_instance.get(); }

void TpuVerifier::install(std::unique_ptr<TpuVerifier> v) {
  g_instance = std::move(v);
}

bool TpuVerifier::connected() {
  std::lock_guard<std::mutex> lk(m_);
  return ensure_connected_locked();
}

bool TpuVerifier::ensure_connected_locked() {
  if (sock_.valid()) return true;
  if (std::chrono::steady_clock::now() < backoff_until_) return false;
  auto s = Socket::connect(addr_, kConnectTimeoutMs);
  if (!s) {
    backoff_until_ = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(kBackoffMs);
    if (!ever_connected_) return false;
    LOG_WARN("crypto::sidecar") << "lost connection to verify sidecar "
                                << addr_.str();
    ever_connected_ = false;
    return false;
  }
  sock_ = std::move(*s);
  sock_.set_recv_timeout(kRecvTimeoutMs);
  if (!ever_connected_) {
    LOG_INFO("crypto::sidecar") << "connected to verify sidecar "
                                << addr_.str();
  }
  ever_connected_ = true;
  return true;
}

std::optional<std::vector<bool>> TpuVerifier::verify_batch(
    const Digest& digest,
    const std::vector<std::pair<PublicKey, Signature>>& votes) {
  std::lock_guard<std::mutex> lk(m_);
  if (!ensure_connected_locked()) return std::nullopt;

  // Request: u8 opcode | u32 rid | u32 count | u16 msg_len | records.
  Writer w;
  uint32_t rid = next_id_++;
  w.u8(kOpVerifyBatch);
  w.u32(rid);
  w.u32(static_cast<uint32_t>(votes.size()));
  w.u8(32);  // msg_len lo (u16 LE)
  w.u8(0);   // msg_len hi
  for (const auto& [pk, sig] : votes) {
    w.fixed(digest.data);
    w.fixed(pk.data);
    w.fixed(sig.data);
  }
  if (!sock_.write_frame(w.out)) {
    sock_.close();
    backoff_until_ = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(kBackoffMs);
    return std::nullopt;
  }

  // Bounded wait (SO_RCVTIMEO set at connect): a wedged sidecar costs at
  // most kRecvTimeoutMs once per backoff window, then the caller's host
  // fallback takes over. Closing the socket also discards any late reply,
  // so request/reply framing can never desynchronize.
  Bytes reply;
  if (!sock_.read_frame(&reply)) {
    LOG_WARN("crypto::sidecar")
        << "sidecar read failed/timed out; falling back to host verify";
    sock_.close();
    backoff_until_ = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(kBackoffMs);
    return std::nullopt;
  }
  try {
    Reader r(reply);
    uint8_t opcode = r.u8();
    uint32_t got_rid = r.u32();
    uint32_t n = r.u32();
    if (opcode != kOpVerifyBatch || got_rid != rid || n != votes.size()) {
      LOG_WARN("crypto::sidecar") << "protocol mismatch from sidecar";
      sock_.close();
      return std::nullopt;
    }
    std::vector<bool> mask(n);
    for (uint32_t i = 0; i < n; i++) mask[i] = r.u8() != 0;
    return mask;
  } catch (const SerdeError&) {
    sock_.close();
    return std::nullopt;
  }
}

}  // namespace hotstuff

// Crypto layer: Digest / PublicKey / SecretKey / Signature / KeyPair /
// SignatureService — the same narrow surface as the reference's crypto crate
// (crypto/src/lib.rs:21-254).  Host signing + single verification run on
// OpenSSL's Ed25519; quorum batch verification routes to the TPU sidecar
// through TpuVerifier (sidecar_client.hpp) with a host fallback, which is
// exactly where the reference calls dalek's verify_batch
// (crypto/src/lib.rs:210-223).
#pragma once

#include <array>
#include <memory>
#include <thread>
#include <utility>
#include <vector>

#include "common/bytes.hpp"
#include "common/channel.hpp"
#include "common/serde.hpp"

namespace hotstuff {

struct Digest {
  std::array<uint8_t, 32> data{};

  bool operator==(const Digest& o) const { return data == o.data; }
  bool operator!=(const Digest& o) const { return data != o.data; }
  bool operator<(const Digest& o) const { return data < o.data; }

  std::string to_base64() const { return base64_encode(data); }
  Bytes to_bytes() const { return Bytes(data.begin(), data.end()); }

  void serialize(Writer* w) const { w->fixed(data); }
  static Digest deserialize(Reader* r) {
    Digest d;
    r->fixed(&d.data);
    return d;
  }
};

// SHA-512 truncated to 32 bytes — the digest function used for every hash in
// the reference (e.g. consensus/src/messages.rs:80-89).
Digest sha512_digest(const uint8_t* data, size_t len);
inline Digest sha512_digest(const Bytes& b) {
  return sha512_digest(b.data(), b.size());
}

// Incremental SHA-512/32 for multi-part message digests.
class DigestBuilder {
 public:
  DigestBuilder();
  ~DigestBuilder();
  DigestBuilder(const DigestBuilder&) = delete;
  DigestBuilder& operator=(const DigestBuilder&) = delete;

  DigestBuilder& update(const uint8_t* data, size_t len);
  DigestBuilder& update(const Bytes& b) { return update(b.data(), b.size()); }
  template <size_t N>
  DigestBuilder& update(const std::array<uint8_t, N>& a) {
    return update(a.data(), N);
  }
  DigestBuilder& update_u64_le(uint64_t v);
  Digest finalize();

 private:
  void* ctx_;
};

struct PublicKey {
  std::array<uint8_t, 32> data{};

  bool operator==(const PublicKey& o) const { return data == o.data; }
  bool operator!=(const PublicKey& o) const { return data != o.data; }
  bool operator<(const PublicKey& o) const { return data < o.data; }

  std::string to_base64() const { return base64_encode(data); }
  static bool from_base64(const std::string& s, PublicKey* out);

  void serialize(Writer* w) const { w->fixed(data); }
  static PublicKey deserialize(Reader* r) {
    PublicKey p;
    r->fixed(&p.data);
    return p;
  }
};

// 64 bytes = 32-byte seed || 32-byte public key (the layout the reference
// serializes for its dalek keypair, crypto/src/lib.rs:120-155).
struct SecretKey {
  std::array<uint8_t, 64> data{};

  const uint8_t* seed() const { return data.data(); }
  std::string to_base64() const { return base64_encode(data); }
  static bool from_base64(const std::string& s, SecretKey* out);
};

struct Signature {
  std::array<uint8_t, 64> data{};

  bool operator==(const Signature& o) const { return data == o.data; }

  void serialize(Writer* w) const { w->fixed(data); }
  static Signature deserialize(Reader* r) {
    Signature s;
    r->fixed(&s.data);
    return s;
  }

  // Sign a 32-byte digest (the message is always a Digest in this protocol).
  static Signature sign(const Digest& digest, const SecretKey& sk);

  bool verify(const Digest& digest, const PublicKey& pk) const;

  // Batch verification over a QC's votes. Uses the process-wide TpuVerifier
  // if one is installed (see sidecar_client.hpp), else a host loop.
  static bool verify_batch(
      const Digest& digest,
      const std::vector<std::pair<PublicKey, Signature>>& votes);
};

struct KeyPair {
  PublicKey name;
  SecretKey secret;
};

// Fresh keypair from the system RNG; deterministic variant from a seed for
// test fixtures (mirrors the reference's seeded-RNG test keys,
// consensus/src/tests/common.rs:17-20).
KeyPair generate_keypair();
KeyPair keypair_from_seed(const std::array<uint8_t, 32>& seed);

// ---------------------------------------------------------------------------
// SignatureService: dedicated signing actor (crypto/src/lib.rs:226-254).
// ---------------------------------------------------------------------------

class SignatureService {
 public:
  explicit SignatureService(const SecretKey& sk);

  // Clonable handle; the background thread lives as long as any copy.
  Signature request_signature(const Digest& digest) const;

 private:
  struct Request {
    Digest digest;
    Oneshot<Signature> reply;
  };
  ChannelPtr<Request> ch_;
  std::shared_ptr<std::thread> worker_;
};

}  // namespace hotstuff

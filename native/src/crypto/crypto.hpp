// Crypto layer: Digest / PublicKey / SecretKey / Signature / KeyPair /
// SignatureService — the same narrow surface as the reference's crypto crate
// (crypto/src/lib.rs:21-254).  Host signing + single verification run on
// OpenSSL's Ed25519; quorum batch verification routes to the TPU sidecar
// through TpuVerifier (sidecar_client.hpp) with a host fallback, which is
// exactly where the reference calls dalek's verify_batch
// (crypto/src/lib.rs:210-223).
#pragma once

#include <array>
#include <functional>
#include <map>
#include <memory>
#include <optional>
#include <thread>
#include <utility>
#include <vector>

#include "common/bytes.hpp"
#include "common/channel.hpp"
#include "common/serde.hpp"

namespace hotstuff {

struct Digest {
  std::array<uint8_t, 32> data{};

  bool operator==(const Digest& o) const { return data == o.data; }
  bool operator!=(const Digest& o) const { return data != o.data; }
  bool operator<(const Digest& o) const { return data < o.data; }

  std::string to_base64() const { return base64_encode(data); }
  Bytes to_bytes() const { return Bytes(data.begin(), data.end()); }

  void serialize(Writer* w) const { w->fixed(data); }
  static Digest deserialize(Reader* r) {
    Digest d;
    r->fixed(&d.data);
    return d;
  }
};

// Field moduli of the curves whose signatures cross the sidecar wire,
// as big-endian hex.  The C++ node never computes in these fields (all
// field math lives in OpenSSL or the JAX sidecar); the literals document
// the crypto contract, and graftlint's wire cross-checker asserts they
// match the Python sources (ops/field25519.py, utils/intmath.py,
// ops/field381.py, offchain/bls12381.py) — edit BOTH sides or the gate
// fails.
constexpr char kEd25519FieldPrimeHex[] =  // 2^255 - 19
    "7fffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffed";
constexpr char kBls381FieldPrimeHex[] =
    "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf"
    "6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaaab";
static_assert(sizeof(kEd25519FieldPrimeHex) == 65,
              "ed25519 field prime must be 32 bytes of hex");
static_assert(sizeof(kBls381FieldPrimeHex) == 97,
              "bls12-381 field prime must be 48 bytes of hex");

// SHA-512 truncated to 32 bytes — the digest function used for every hash in
// the reference (e.g. consensus/src/messages.rs:80-89).
Digest sha512_digest(const uint8_t* data, size_t len);
inline Digest sha512_digest(const Bytes& b) {
  return sha512_digest(b.data(), b.size());
}

// Incremental SHA-512/32 for multi-part message digests.
class DigestBuilder {
 public:
  DigestBuilder();
  ~DigestBuilder();
  DigestBuilder(const DigestBuilder&) = delete;
  DigestBuilder& operator=(const DigestBuilder&) = delete;

  DigestBuilder& update(const uint8_t* data, size_t len);
  DigestBuilder& update(const Bytes& b) { return update(b.data(), b.size()); }
  template <size_t N>
  DigestBuilder& update(const std::array<uint8_t, N>& a) {
    return update(a.data(), N);
  }
  DigestBuilder& update_u64_le(uint64_t v);
  Digest finalize();

 private:
  void* ctx_;
};

struct PublicKey {
  std::array<uint8_t, 32> data{};

  bool operator==(const PublicKey& o) const { return data == o.data; }
  bool operator!=(const PublicKey& o) const { return data != o.data; }
  bool operator<(const PublicKey& o) const { return data < o.data; }

  std::string to_base64() const { return base64_encode(data); }
  static bool from_base64(const std::string& s, PublicKey* out);

  void serialize(Writer* w) const { w->fixed(data); }
  static PublicKey deserialize(Reader* r) {
    PublicKey p;
    r->fixed(&p.data);
    return p;
  }
};

// 64 bytes = 32-byte seed || 32-byte public key (the layout the reference
// serializes for its dalek keypair, crypto/src/lib.rs:120-155).
struct SecretKey {
  std::array<uint8_t, 64> data{};

  const uint8_t* seed() const { return data.data(); }
  std::string to_base64() const { return base64_encode(data); }
  static bool from_base64(const std::string& s, SecretKey* out);
};

// Signature scheme knob (the reference's EdDSA main branch vs BLS sibling
// branch, README.md:1-3, selected per-deployment in node parameters).
enum class Scheme { kEd25519, kBls };

Scheme current_scheme();
void set_scheme(Scheme s);

// Process-wide BLS context, installed at node boot when scheme=bls: the
// node's signing scalar plus the committee's 96-byte uncompressed G1
// public keys (the 32-byte PublicKey stays the node identity everywhere;
// BLS material rides alongside it in the config files).
struct BlsContext {
  Bytes secret;                              // 48-byte big-endian scalar
  std::map<PublicKey, Bytes> public_keys;    // name -> 96-byte G1

  static BlsContext* instance();
  static void install(std::unique_ptr<BlsContext> ctx);
};

struct Signature {
  // 64 bytes (Ed25519) or 192 bytes (uncompressed BLS G2); variable so the
  // scheme knob doesn't triple the wire cost of the default scheme.
  Bytes data = Bytes(64, 0);

  bool operator==(const Signature& o) const { return data == o.data; }

  void serialize(Writer* w) const { w->bytes(data); }
  static Signature deserialize(Reader* r) {
    Signature s;
    s.data = r->bytes();
    if (s.data.size() != 64 && s.data.size() != 192) {
      throw SerdeError("bad signature length");
    }
    return s;
  }

  // Sign a 32-byte digest (the message is always a Digest in this
  // protocol). scheme=bls routes to the sidecar's host signer; when the
  // sidecar is unreachable it falls back to the host Ed25519 identity
  // key (the 64-byte signature verifiers dispatch on by length), so a
  // node with a dead sidecar keeps signing votes/timeouts and view
  // changes stay live instead of stalling on invalid BLS bytes.
  static Signature sign(const Digest& digest, const SecretKey& sk);

  // Host-forced Ed25519 signing, regardless of the scheme knob.  The dag
  // mempool's batch ACKs go through here: availability certificates are
  // Ed25519 under BOTH schemes (every committee entry carries the Ed25519
  // identity key, and the verify path dispatches on signature length), so
  // cert assembly never blocks on a sidecar round-trip per ACK.
  static Signature sign_host(const Digest& digest, const SecretKey& sk);

  // Under scheme=bls, 64-byte signatures take the HOST Ed25519 path —
  // they are the sidecar-down fallback above, verified against the
  // signer's Ed25519 identity key; only 192-byte G2 signatures ride the
  // sidecar pairing ops.
  bool verify(const Digest& digest, const PublicKey& pk) const;

  // Batch verification over a QC's votes. Uses the process-wide TpuVerifier
  // if one is installed (see sidecar_client.hpp), else a host loop
  // (scheme=bls requires the sidecar: there is no host pairing in C++;
  // mixed batches are partitioned — 64-byte fallback entries verify on
  // host, the BLS remainder in one sidecar op).
  static bool verify_batch(
      const Digest& digest,
      const std::vector<std::pair<PublicKey, Signature>>& votes);

  // Batch verification where every vote signed its own digest (a TC's
  // timeout votes). The reference verifies these one-by-one
  // (messages.rs:307-313); here they share a single device launch when the
  // TpuVerifier is installed.  `bulk` tags the sidecar scheduling class
  // (protocol v2): consensus certificate verification keeps the default
  // latency class; only throughput-bound batch workloads (the offchain
  // sweep, mempool-style verification) pass true.
  static bool verify_batch_multi(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
      bool bulk = false);

  // Transport-aware form of verify_batch_multi: nullopt means the BLS
  // remainder of the batch could not be checked at all (sidecar
  // unreachable / timed out) — UNKNOWN, not forged.  Callers that can
  // retry later (TC assembly) must not eject signers on nullopt; callers
  // without a retry path use verify_batch_multi, which maps it to
  // reject.  Ed25519 batches never return nullopt (the host loop always
  // exists).
  static std::optional<bool> verify_batch_multi_checked(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
      bool bulk = false);

  // True when a device verifier is installed, connected, and has spare
  // in-flight budget — i.e. verify_batch_multi_async will actually
  // pipeline to the device rather than fail over.
  static bool async_available();

  // Asynchronous batch verification: the callback fires exactly once from
  // the sidecar reply path — with the overall verdict, or nullopt on
  // transport failure (caller should then re-verify synchronously, which
  // falls back to the host path).  This is what lets the consensus Core
  // suspend a proposal on a pending device verify instead of eating the
  // device round-trip on its own thread (SURVEY.md §7; the reference's
  // QC::verify is synchronous, consensus/src/messages.rs:180-198).
  //
  // `ctx` (graftscope, protocol v5): digest of the block whose
  // certificates this batch verifies — rides the verify RPC as the
  // context tag so the sidecar's stage spans join the block's trace.
  // nullptr sends the legacy tag-less frame (v4-compatible).
  //
  // `bulk` (graftingress) picks the sidecar scheduling class exactly as
  // in verify_batch_multi: consensus certificate paths pass false (the
  // default); only throughput-bound admission batches pass true.
  using AsyncCallback = std::function<void(std::optional<bool>)>;
  static void verify_batch_multi_async(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
      AsyncCallback cb, bool bulk = false, const Digest* ctx = nullptr);

  // graftingress admission-verify form: per-item verdict mask (one
  // forged client tx must reject that tx, not the whole batch) plus the
  // sidecar's OP_BUSY retry-after hint.  `busy_retry_ms` is -1 unless
  // the sidecar explicitly shed the request with OP_BUSY (mask is then
  // nullopt): overload is worth a bounded paced retry on the device;
  // any other nullopt is a transport failure the caller host-verifies
  // through.  Ed25519 records only (client tx keys are Ed25519 under
  // either scheme knob — BLS is a committee-signature concern).
  using MaskedCallback =
      std::function<void(std::optional<std::vector<bool>>, int busy_retry_ms)>;
  static void verify_batch_multi_async_masked(
      const std::vector<std::tuple<Digest, PublicKey, Signature>>& items,
      MaskedCallback cb, bool bulk = false, const Digest* ctx = nullptr);
};

struct KeyPair {
  PublicKey name;
  SecretKey secret;
};

// Fresh keypair from the system RNG; deterministic variant from a seed for
// test fixtures (mirrors the reference's seeded-RNG test keys,
// consensus/src/tests/common.rs:17-20).
KeyPair generate_keypair();
KeyPair keypair_from_seed(const std::array<uint8_t, 32>& seed);

// ---------------------------------------------------------------------------
// SignatureService: dedicated signing actor (crypto/src/lib.rs:226-254).
// ---------------------------------------------------------------------------

class SignatureService {
 public:
  explicit SignatureService(const SecretKey& sk);

  // Clonable handle; the background thread lives as long as any copy.
  Signature request_signature(const Digest& digest) const;

 private:
  struct Request {
    Digest digest;
    Oneshot<Signature> reply;
  };
  ChannelPtr<Request> ch_;
  std::shared_ptr<std::thread> worker_;
};

}  // namespace hotstuff

// Hand-declared subset of the stable libcrypto 3.x C ABI (this image ships
// /lib/x86_64-linux-gnu/libcrypto.so.3 but no dev headers). Only the
// documented, ABI-stable EVP entry points for SHA-512 and Ed25519 raw-key
// sign/verify plus RAND_bytes are declared; CMake links the versioned .so
// directly.
#pragma once

#include <cstddef>

extern "C" {

typedef struct evp_md_ctx_st EVP_MD_CTX;
typedef struct evp_md_st EVP_MD;
typedef struct evp_pkey_st EVP_PKEY;
typedef struct evp_pkey_ctx_st EVP_PKEY_CTX;
typedef struct engine_st ENGINE;

const EVP_MD* EVP_sha512(void);
int EVP_Digest(const void* data, size_t count, unsigned char* md,
               unsigned int* size, const EVP_MD* type, ENGINE* impl);

EVP_MD_CTX* EVP_MD_CTX_new(void);
void EVP_MD_CTX_free(EVP_MD_CTX* ctx);
int EVP_DigestInit_ex(EVP_MD_CTX* ctx, const EVP_MD* type, ENGINE* impl);
int EVP_DigestUpdate(EVP_MD_CTX* ctx, const void* d, size_t cnt);
int EVP_DigestFinal_ex(EVP_MD_CTX* ctx, unsigned char* md, unsigned int* s);

EVP_PKEY* EVP_PKEY_new_raw_private_key(int type, ENGINE* e,
                                       const unsigned char* priv, size_t len);
EVP_PKEY* EVP_PKEY_new_raw_public_key(int type, ENGINE* e,
                                      const unsigned char* pub, size_t len);
int EVP_PKEY_get_raw_public_key(const EVP_PKEY* pkey, unsigned char* pub,
                                size_t* len);
void EVP_PKEY_free(EVP_PKEY* pkey);

int EVP_DigestSignInit(EVP_MD_CTX* ctx, EVP_PKEY_CTX** pctx,
                       const EVP_MD* type, ENGINE* e, EVP_PKEY* pkey);
int EVP_DigestSign(EVP_MD_CTX* ctx, unsigned char* sigret, size_t* siglen,
                   const unsigned char* tbs, size_t tbslen);
int EVP_DigestVerifyInit(EVP_MD_CTX* ctx, EVP_PKEY_CTX** pctx,
                         const EVP_MD* type, ENGINE* e, EVP_PKEY* pkey);
int EVP_DigestVerify(EVP_MD_CTX* ctx, const unsigned char* sigret,
                     size_t siglen, const unsigned char* tbs, size_t tbslen);

int RAND_bytes(unsigned char* buf, int num);

}  // extern "C"

inline constexpr int kEvpPkeyEd25519 = 1087;  // NID_ED25519

// Low-level sockets + length-delimited framing (4-byte big-endian prefix),
// the same frame format the reference gets from LengthDelimitedCodec
// (network/src/receiver.rs:70) and the verify sidecar speaks
// (hotstuff_tpu/sidecar/protocol.py).
#pragma once

#include <optional>
#include <string>

#include "common/bytes.hpp"

namespace hotstuff {

// "ip:port" address; resolution is numeric-only (the harness always writes
// numeric addresses, benchmark config.py analogue).
struct Address {
  std::string host;
  uint16_t port = 0;

  static std::optional<Address> parse(const std::string& s);
  std::string str() const { return host + ":" + std::to_string(port); }
  bool operator==(const Address& o) const {
    return host == o.host && port == o.port;
  }
  bool operator<(const Address& o) const {
    return host != o.host ? host < o.host : port < o.port;
  }
};

struct AddressHash {
  size_t operator()(const Address& a) const {
    return std::hash<std::string>()(a.host) * 31 + a.port;
  }
};

// Thin owning wrapper over a connected TCP socket fd.
class Socket {
 public:
  Socket() = default;
  explicit Socket(int fd) : fd_(fd) {}
  ~Socket() { close(); }
  Socket(Socket&& o) noexcept : fd_(o.fd_) { o.fd_ = -1; }
  Socket& operator=(Socket&& o) noexcept;
  Socket(const Socket&) = delete;
  Socket& operator=(const Socket&) = delete;

  static std::optional<Socket> connect(const Address& addr);
  // Non-blocking connect bounded by `timeout_ms` (poll-based); used for
  // dispatch paths that must never stall a state-machine thread, e.g. the
  // TPU sidecar client.
  static std::optional<Socket> connect(const Address& addr, int timeout_ms);

  // Bound every subsequent recv: read_frame/read_exact fail (returning
  // false) instead of blocking past the deadline. 0 disables.
  bool set_recv_timeout(int timeout_ms);

  bool valid() const { return fd_ >= 0; }
  int fd() const { return fd_; }
  void close();
  // Shut down both directions (wakes a thread blocked in read_frame).
  void shutdown();

  // Framed IO. Returns false on EOF/error. The default frame cap matches
  // the reference's LengthDelimitedCodec limit (8 MiB) — large enough for a
  // 500 KB batch or a big QC, small enough that a hostile length prefix
  // can't trigger a giant allocation.
  bool write_frame(const Bytes& payload);
  bool write_frame(const uint8_t* data, size_t len);
  bool read_frame(Bytes* out, size_t max_len = 8u << 20);

 private:
  bool read_exact(uint8_t* buf, size_t len);
  bool write_all(const uint8_t* buf, size_t len);

  int fd_ = -1;
};

// Listening socket (SO_REUSEADDR). port 0 picks an ephemeral port.
class Listener {
 public:
  Listener() = default;
  ~Listener() { close(); }
  Listener(Listener&& o) noexcept : fd_(o.fd_), port_(o.port_) {
    o.fd_ = -1;
  }
  Listener& operator=(Listener&& o) noexcept {
    if (this != &o) {
      close();
      fd_ = o.fd_;
      port_ = o.port_;
      o.fd_ = -1;
    }
    return *this;
  }
  Listener(const Listener&) = delete;
  Listener& operator=(const Listener&) = delete;

  static std::optional<Listener> bind(const Address& addr);

  std::optional<Socket> accept();
  // Hand the listening fd to another owner (the EventLoop); this object
  // forgets it.
  int release() {
    int fd = fd_;
    fd_ = -1;
    return fd;
  }
  uint16_t port() const { return port_; }
  bool valid() const { return fd_ >= 0; }
  void close();
  void shutdown();  // unblocks accept()

 private:
  int fd_ = -1;
  uint16_t port_ = 0;
};

}  // namespace hotstuff

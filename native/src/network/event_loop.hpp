// Single-threaded epoll reactor: every data-plane socket of a node — all
// inbound connections, outbound peer connections and listeners — is
// multiplexed on ONE thread, the way the reference multiplexes its
// per-connection tasks on the tokio runtime (network/src/receiver.rs:31-89,
// simple_sender.rs:105-143).  This replaces the thread-per-connection
// design, which collapsed on single-host committees (≈5 threads/peer ×
// 20 nodes ≈ 2000 runnable threads on one vCPU).
//
// Threading contract: `post/post_wait/run_after` are thread-safe; every
// other method must be called ON the loop thread (from a posted task or
// a callback).  Callbacks run on the loop thread and must never block:
// channel pushes must be try_send (a blocking send on a full channel
// would stall every connection in the process), blocking IO is out.
#pragma once

#include <chrono>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common/bytes.hpp"
#include "network/socket.hpp"

namespace hotstuff {

class EventLoop {
 public:
  // Frame cap, matching the reference's LengthDelimitedCodec limit
  // (8 MiB); oversized inbound frames drop the connection, oversized
  // sends are refused.
  static constexpr size_t kMaxFrame = 8u << 20;

  using Task = std::function<void()>;
  // A connection's frame/closed callbacks.  on_frame receives whole
  // de-framed payloads (4-byte big-endian length prefix stripped).
  using FrameCb = std::function<void(uint64_t conn_id, Bytes frame)>;
  using ClosedCb = std::function<void(uint64_t conn_id)>;
  using AcceptCb = std::function<void(int fd)>;          // takes ownership
  using ConnectCb = std::function<void(int fd)>;         // -1 on failure

  EventLoop();
  ~EventLoop();
  EventLoop(const EventLoop&) = delete;

  // Process-wide reactor (lazily started).  One loop serves every node in
  // the process — the in-process deploy testbed runs several — so it is
  // never stopped; component teardown closes its own ids instead.
  static EventLoop& instance();

  // -- thread-safe -----------------------------------------------------
  void post(Task fn);
  // Schedule `fn` on the loop thread after `delay`.
  void run_after(std::chrono::milliseconds delay, Task fn);
  // Post `fn` and block until the loop ran it (teardown barrier).
  void post_wait(Task fn);

  // -- loop-thread only ------------------------------------------------
  // Adopt a connected (or in-progress) fd as a framed connection.
  uint64_t adopt(int fd, FrameCb on_frame, ClosedCb on_closed);
  // Register a listening fd; on_accept receives each accepted fd.
  uint64_t add_listener(int fd, AcceptCb on_accept);
  // Begin a non-blocking connect; `done` runs on the loop thread with a
  // connected fd, or -1 on refusal/timeout.
  void connect(const Address& addr, int timeout_ms, ConnectCb done);
  // Queue a frame (length prefix added here).  False if the id is gone or
  // `max_queue` (> 0) frames are already backlogged on the connection.
  bool send(uint64_t conn_id, std::shared_ptr<const Bytes> payload,
            size_t max_queue = 0);
  // Suspend/resume EPOLLIN on a connection (graftsurge ingress
  // watermarks): while paused the kernel receive buffer fills and TCP
  // flow control pushes back on the peer — the reactor stops reading,
  // writes still flush.  A pause set from inside the connection's own
  // on_frame callback also stops the current read loop after that
  // callback returns (at most the already-buffered chunk is parsed).
  void set_read_paused(uint64_t conn_id, bool paused);
  // Close an id (connection or listener); runs no ClosedCb (explicit
  // close means the owner already knows).
  void close(uint64_t id);

 private:
  struct OutFrame {
    uint8_t hdr[4];
    std::shared_ptr<const Bytes> payload;
    size_t off = 0;  // 0..4+payload->size()
  };
  struct Conn {
    int fd = -1;
    Bytes in;
    std::deque<OutFrame> out;
    FrameCb on_frame;
    ClosedCb on_closed;
    bool want_write = false;
    bool read_paused = false;
  };
  struct Listener_ {
    int fd = -1;
    AcceptCb on_accept;
  };
  struct Connecting {
    int fd = -1;
    ConnectCb done;
    uint64_t timer_seq = 0;
  };
  struct Timer {
    std::chrono::steady_clock::time_point when;
    uint64_t seq;
    Task fn;
    bool operator>(const Timer& o) const {
      return when != o.when ? when > o.when : seq > o.seq;
    }
  };

  void run();
  void handle_event(uint64_t id, uint32_t events);
  void handle_readable(uint64_t id, Conn* c);
  void flush(uint64_t id, Conn* c);
  void update_interest(uint64_t id, Conn* c);
  void apply_interest_(uint64_t id, Conn* c);
  void destroy(uint64_t id, bool run_closed_cb);
  void cancel_timer(uint64_t seq);
  int next_timeout_ms() const;
  void fire_due_timers();

  // graftsync annotations (analysis/cxxsync.py enforces GUARDED_BY;
  // OWNED_BY documents single-thread confinement — here, the loop
  // thread per the threading contract above).
  int epfd_ = -1;           // SHARED_OK(set in ctor, then read-only)
  int wakeup_fd_ = -1;      // SHARED_OK(set in ctor; eventfd writes are
                            // thread-safe by contract)
  std::thread thread_;      // SHARED_OK(set in ctor, joined in dtor)
  bool stopping_ = false;   // OWNED_BY(loop thread — set via posted task)

  uint64_t next_id_ = 1;          // OWNED_BY(loop thread)
  uint64_t next_timer_seq_ = 1;   // OWNED_BY(loop thread)
  // Id of the connection whose on_frame callback is currently executing
  // (0 = none; ids start at 1): destroy() of that id is deferred until
  // the callback returns (see destroy()).
  uint64_t in_callback_id_ = 0;   // OWNED_BY(loop thread)
  bool defer_destroy_ = false;    // OWNED_BY(loop thread)
  bool defer_run_closed_ = false;  // OWNED_BY(loop thread)
  std::unordered_map<uint64_t, Conn> conns_;  // OWNED_BY(loop thread)
  std::unordered_map<uint64_t, Listener_> listeners_;  // OWNED_BY(loop thread)
  std::unordered_map<uint64_t, Connecting> connecting_;  // OWNED_BY(loop thread)
  std::priority_queue<Timer, std::vector<Timer>, std::greater<Timer>>
      timers_;  // OWNED_BY(loop thread)
  std::vector<uint64_t> cancelled_timers_;  // OWNED_BY(loop thread)

  // The ONE cross-thread ingress: post/post_wait/run_after enqueue
  // under tasks_m_ from any thread; run() swaps the deque out under it.
  std::mutex tasks_m_;
  std::deque<Task> tasks_;  // GUARDED_BY(tasks_m_)
};

}  // namespace hotstuff

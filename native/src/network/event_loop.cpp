#include "network/event_loop.hpp"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <condition_variable>
#include <cerrno>
#include <cstring>

#include "common/log.hpp"

namespace hotstuff {

namespace {

constexpr size_t kMaxFrame = EventLoop::kMaxFrame;
constexpr size_t kReadChunk = 64 * 1024;

void set_nonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

}  // namespace

EventLoop::EventLoop() {
  epfd_ = epoll_create1(EPOLL_CLOEXEC);
  wakeup_fd_ = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
  if (epfd_ < 0 || wakeup_fd_ < 0) {
    // A reactor that silently failed to set up would hang every
    // post_wait in the process; fail loudly at first network use.
    throw std::runtime_error("EventLoop: epoll/eventfd setup failed");
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = 0;  // reserved id for the wakeup eventfd
  epoll_ctl(epfd_, EPOLL_CTL_ADD, wakeup_fd_, &ev);
  thread_ = std::thread([this] { set_thread_name("reactor"); run(); });
}

EventLoop::~EventLoop() {
  post([this] { stopping_ = true; });
  if (thread_.joinable()) thread_.join();
  for (auto& [_, c] : conns_) ::close(c.fd);
  for (auto& [_, l] : listeners_) ::close(l.fd);
  for (auto& [_, p] : connecting_) ::close(p.fd);
  ::close(wakeup_fd_);
  ::close(epfd_);
}

EventLoop& EventLoop::instance() {
  // Intentionally leaked: the reactor must outlive every component that
  // might still post teardown work during static destruction.
  static EventLoop* loop = new EventLoop();
  return *loop;
}

void EventLoop::post(Task fn) {
  {
    std::lock_guard<std::mutex> lk(tasks_m_);
    tasks_.push_back(std::move(fn));
  }
  uint64_t one = 1;
  [[maybe_unused]] ssize_t n = ::write(wakeup_fd_, &one, sizeof(one));
}

void EventLoop::post_wait(Task fn) {
  if (std::this_thread::get_id() == thread_.get_id()) {
    fn();  // already on the loop; waiting would deadlock
    return;
  }
  std::mutex m;
  std::condition_variable cv;
  bool done = false;
  post([&] {
    fn();
    std::lock_guard<std::mutex> lk(m);
    done = true;
    cv.notify_one();
  });
  std::unique_lock<std::mutex> lk(m);
  cv.wait(lk, [&] { return done; });
}

void EventLoop::run_after(std::chrono::milliseconds delay, Task fn) {
  post([this, delay, fn = std::move(fn)]() mutable {
    timers_.push(Timer{std::chrono::steady_clock::now() + delay,
                       next_timer_seq_++, std::move(fn)});
  });
}

uint64_t EventLoop::adopt(int fd, FrameCb on_frame, ClosedCb on_closed) {
  set_nonblocking(fd);
  set_nodelay(fd);
  uint64_t id = next_id_++;
  Conn c;
  c.fd = fd;
  c.on_frame = std::move(on_frame);
  c.on_closed = std::move(on_closed);
  conns_.emplace(id, std::move(c));
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = id;
  epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
  return id;
}

uint64_t EventLoop::add_listener(int fd, AcceptCb on_accept) {
  set_nonblocking(fd);
  uint64_t id = next_id_++;
  listeners_.emplace(id, Listener_{fd, std::move(on_accept)});
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = id;
  epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
  return id;
}

void EventLoop::connect(const Address& addr, int timeout_ms, ConnectCb done) {
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(addr.port);
  if (inet_pton(AF_INET, addr.host.c_str(), &sa.sin_addr) != 1) {
    done(-1);
    return;
  }
  int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC | SOCK_NONBLOCK, 0);
  if (fd < 0) {
    done(-1);
    return;
  }
  int rc = ::connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa));
  if (rc == 0) {
    done(fd);
    return;
  }
  if (errno != EINPROGRESS) {
    ::close(fd);
    done(-1);
    return;
  }
  uint64_t id = next_id_++;
  uint64_t seq = next_timer_seq_++;
  connecting_.emplace(id, Connecting{fd, std::move(done), seq});
  epoll_event ev{};
  ev.events = EPOLLOUT;
  ev.data.u64 = id;
  epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
  timers_.push(Timer{
      std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms),
      seq, [this, id] {
        auto it = connecting_.find(id);
        if (it == connecting_.end()) return;
        ConnectCb cb = std::move(it->second.done);
        epoll_ctl(epfd_, EPOLL_CTL_DEL, it->second.fd, nullptr);
        ::close(it->second.fd);
        connecting_.erase(it);
        cb(-1);
      }});
}

bool EventLoop::send(uint64_t conn_id, std::shared_ptr<const Bytes> payload,
                     size_t max_queue) {
  auto it = conns_.find(conn_id);
  if (it == conns_.end()) return false;
  Conn& c = it->second;
  if (max_queue > 0 && c.out.size() >= max_queue) return false;
  size_t len = payload->size();
  if (len > kMaxFrame) return false;
  OutFrame f;
  f.hdr[0] = uint8_t(len >> 24);
  f.hdr[1] = uint8_t(len >> 16);
  f.hdr[2] = uint8_t(len >> 8);
  f.hdr[3] = uint8_t(len);
  f.payload = std::move(payload);
  c.out.push_back(std::move(f));
  flush(conn_id, &c);
  // flush may have destroyed the connection on a hard error; the frame
  // was accepted either way (best-effort boundary, like a kernel buffer).
  return true;
}

void EventLoop::close(uint64_t id) { destroy(id, /*run_closed_cb=*/false); }

void EventLoop::destroy(uint64_t id, bool run_closed_cb) {
  // A connection's on_frame callback may itself trigger destruction of
  // its own connection (e.g. the handler's Ack reply hits a dead peer and
  // flush takes the hard-error path).  Destroying NOW would free the
  // std::function currently executing on this stack — a use-after-free
  // on its captures (caught by ASan under mass-teardown load).  Defer to
  // the callback's caller instead.
  if (id == in_callback_id_) {
    defer_destroy_ = true;
    defer_run_closed_ |= run_closed_cb;
    return;
  }
  if (auto it = conns_.find(id); it != conns_.end()) {
    epoll_ctl(epfd_, EPOLL_CTL_DEL, it->second.fd, nullptr);
    ::close(it->second.fd);
    ClosedCb cb = std::move(it->second.on_closed);
    conns_.erase(it);
    if (run_closed_cb && cb) cb(id);
    return;
  }
  if (auto it = listeners_.find(id); it != listeners_.end()) {
    epoll_ctl(epfd_, EPOLL_CTL_DEL, it->second.fd, nullptr);
    ::close(it->second.fd);
    listeners_.erase(it);
    return;
  }
  if (auto it = connecting_.find(id); it != connecting_.end()) {
    epoll_ctl(epfd_, EPOLL_CTL_DEL, it->second.fd, nullptr);
    ::close(it->second.fd);
    connecting_.erase(it);
  }
}

void EventLoop::apply_interest_(uint64_t id, Conn* c) {
  epoll_event ev{};
  ev.events = (c->read_paused ? 0u : uint32_t(EPOLLIN)) |
              (c->want_write ? uint32_t(EPOLLOUT) : 0u);
  ev.data.u64 = id;
  epoll_ctl(epfd_, EPOLL_CTL_MOD, c->fd, &ev);
}

void EventLoop::update_interest(uint64_t id, Conn* c) {
  bool want = !c->out.empty();
  if (want == c->want_write) return;
  c->want_write = want;
  apply_interest_(id, c);
}

void EventLoop::set_read_paused(uint64_t conn_id, bool paused) {
  auto it = conns_.find(conn_id);
  if (it == conns_.end()) return;
  if (it->second.read_paused == paused) return;
  it->second.read_paused = paused;
  apply_interest_(conn_id, &it->second);
}

void EventLoop::flush(uint64_t id, Conn* c) {
  // Gather header+payload pairs across queued frames into one writev:
  // the consensus workload sends many small frames (votes, ACKs) per
  // wakeup, and one syscall per fragment was the dominant per-message
  // cost at the 60k tx/s single-host ceiling.
  constexpr int kMaxIov = 64;
  while (!c->out.empty()) {
    iovec iov[kMaxIov];
    int iovs = 0;
    size_t want = 0;
    for (auto it = c->out.begin();
         it != c->out.end() && iovs + 2 <= kMaxIov; ++it) {
      size_t total = 4 + it->payload->size();
      if (it->off < 4) {
        iov[iovs].iov_base = const_cast<uint8_t*>(it->hdr + it->off);
        iov[iovs].iov_len = 4 - it->off;
        want += iov[iovs].iov_len;
        iovs++;
        iov[iovs].iov_base = const_cast<uint8_t*>(it->payload->data());
        iov[iovs].iov_len = it->payload->size();
      } else {
        iov[iovs].iov_base =
            const_cast<uint8_t*>(it->payload->data() + (it->off - 4));
        iov[iovs].iov_len = total - it->off;
      }
      want += iov[iovs].iov_len;
      iovs++;
    }
    msghdr mh{};
    mh.msg_iov = iov;
    mh.msg_iovlen = size_t(iovs);
    ssize_t n = ::sendmsg(c->fd, &mh, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      destroy(id, /*run_closed_cb=*/true);
      return;
    }
    // Consume n bytes across the queued frames.
    size_t left = size_t(n);
    while (left > 0 && !c->out.empty()) {
      OutFrame& f = c->out.front();
      size_t total = 4 + f.payload->size();
      size_t take = std::min(left, total - f.off);
      f.off += take;
      left -= take;
      if (f.off == total) c->out.pop_front();
    }
    // Short write: the kernel buffer is full — wait for EPOLLOUT.
    if (size_t(n) < want) break;
  }
  update_interest(id, c);
}

void EventLoop::handle_readable(uint64_t id, Conn* c) {
  uint8_t buf[kReadChunk];
  while (true) {
    ssize_t n = ::recv(c->fd, buf, sizeof(buf), 0);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      destroy(id, /*run_closed_cb=*/true);
      return;
    }
    if (n == 0) {
      destroy(id, /*run_closed_cb=*/true);
      return;
    }
    c->in.insert(c->in.end(), buf, buf + n);
    // Parse every complete frame in the buffer.
    size_t pos = 0;
    while (c->in.size() - pos >= 4) {
      size_t len = (size_t(c->in[pos]) << 24) | (size_t(c->in[pos + 1]) << 16) |
                   (size_t(c->in[pos + 2]) << 8) | size_t(c->in[pos + 3]);
      if (len > kMaxFrame) {
        destroy(id, /*run_closed_cb=*/true);
        return;
      }
      if (c->in.size() - pos - 4 < len) break;
      Bytes frame(c->in.begin() + pos + 4, c->in.begin() + pos + 4 + len);
      pos += 4 + len;
      // Guard the callback's own closure: any destroy(id) triggered from
      // inside it (its Ack reply failing, a handler-initiated close) is
      // deferred until the callback has returned.
      in_callback_id_ = id;
      defer_destroy_ = false;
      defer_run_closed_ = false;
      c->on_frame(id, std::move(frame));
      in_callback_id_ = 0;
      if (defer_destroy_) {
        destroy(id, defer_run_closed_);
        return;
      }
      // The callback may have closed this connection (handler returned
      // false); stop touching freed state if so.
      auto it = conns_.find(id);
      if (it == conns_.end() || &it->second != c) return;
      // A pause set from inside the callback (ingress watermark) stops
      // this read pass too: parse no further buffered frames and stop
      // recv'ing — the partial remainder waits for the resume.
      if (c->read_paused) break;
    }
    if (pos) c->in.erase(c->in.begin(), c->in.begin() + pos);
    if (c->read_paused) break;
    if (size_t(n) < sizeof(buf)) break;  // drained the socket
  }
}

void EventLoop::handle_event(uint64_t id, uint32_t events) {
  if (auto it = connecting_.find(id); it != connecting_.end()) {
    int fd = it->second.fd;
    ConnectCb cb = std::move(it->second.done);
    uint64_t seq = it->second.timer_seq;
    epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
    connecting_.erase(it);
    cancel_timer(seq);
    int err = 0;
    socklen_t len = sizeof(err);
    getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
    if ((events & (EPOLLERR | EPOLLHUP)) || err != 0) {
      ::close(fd);
      cb(-1);
    } else {
      cb(fd);
    }
    return;
  }
  if (auto it = listeners_.find(id); it != listeners_.end()) {
    while (true) {
      int fd = ::accept4(it->second.fd, nullptr, nullptr, SOCK_CLOEXEC);
      if (fd < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
          break;
        }
        // Persistent accept failure (EMFILE/ENFILE): the level-triggered
        // readiness would spin the reactor at 100% CPU, so disarm the
        // listener and re-arm after a short backoff.
        int lfd = it->second.fd;
        epoll_event ev{};
        ev.events = 0;
        ev.data.u64 = id;
        epoll_ctl(epfd_, EPOLL_CTL_MOD, lfd, &ev);
        timers_.push(Timer{
            std::chrono::steady_clock::now() + std::chrono::milliseconds(50),
            next_timer_seq_++, [this, id] {
              auto again = listeners_.find(id);
              if (again == listeners_.end()) return;
              epoll_event rev{};
              rev.events = EPOLLIN;
              rev.data.u64 = id;
              epoll_ctl(epfd_, EPOLL_CTL_MOD, again->second.fd, &rev);
            }});
        break;
      }
      it->second.on_accept(fd);
      if (listeners_.find(id) == listeners_.end()) return;  // cb closed us
    }
    return;
  }
  if (auto it = conns_.find(id); it != conns_.end()) {
    Conn* c = &it->second;
    if (events & (EPOLLERR | EPOLLHUP)) {
      // Drain what the kernel still has for us before tearing down.
      handle_readable(id, c);
      auto again = conns_.find(id);
      if (again != conns_.end()) destroy(id, /*run_closed_cb=*/true);
      return;
    }
    if (events & EPOLLIN) {
      handle_readable(id, c);
      auto again = conns_.find(id);
      if (again == conns_.end()) return;
      c = &again->second;
    }
    if (events & EPOLLOUT) flush(id, c);
  }
}

void EventLoop::cancel_timer(uint64_t seq) {
  cancelled_timers_.push_back(seq);
}

int EventLoop::next_timeout_ms() const {
  if (timers_.empty()) return 500;
  auto now = std::chrono::steady_clock::now();
  auto when = timers_.top().when;
  if (when <= now) return 0;
  auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(when - now);
  return int(std::min<long long>(ms.count() + 1, 500));
}

void EventLoop::fire_due_timers() {
  auto now = std::chrono::steady_clock::now();
  while (!timers_.empty() && timers_.top().when <= now) {
    Timer t = timers_.top();
    timers_.pop();
    auto c = std::find(cancelled_timers_.begin(), cancelled_timers_.end(),
                       t.seq);
    if (c != cancelled_timers_.end()) {
      cancelled_timers_.erase(c);
      continue;
    }
    t.fn();
  }
}

void EventLoop::run() {
  constexpr int kMaxEvents = 128;
  epoll_event events[kMaxEvents];
  while (!stopping_) {
    int n = epoll_wait(epfd_, events, kMaxEvents, next_timeout_ms());
    if (n < 0 && errno != EINTR) break;
    for (int i = 0; i < n; i++) {
      uint64_t id = events[i].data.u64;
      if (id == 0) {
        uint64_t drain;
        while (::read(wakeup_fd_, &drain, sizeof(drain)) > 0) {
        }
        continue;
      }
      handle_event(id, events[i].events);
    }
    // Run posted tasks (after events so sends see fresh conn state).
    std::deque<Task> tasks;
    {
      std::lock_guard<std::mutex> lk(tasks_m_);
      tasks.swap(tasks_);
    }
    for (auto& t : tasks) t();
    fire_due_timers();
  }
}

}  // namespace hotstuff

// Network receiver: a listener plus all of its inbound connections
// multiplexed on the process-wide epoll EventLoop, each message dispatched
// through a MessageHandler that may write reply frames (ACKs) back on the
// same connection — the reference's Receiver<Handler>
// (network/src/receiver.rs:31-89) as reactor callbacks instead of
// thread-per-connection.
#pragma once

#include <functional>
#include <memory>
#include <string>
#include <unordered_set>

#include "common/bytes.hpp"
#include "network/event_loop.hpp"
#include "network/socket.hpp"

namespace hotstuff {

// Reply-capable view of a connection handed to handlers (the Writer half of
// the reference's split framed transport).  Copyable value: handlers may
// retain a copy past the handler call (the mempool admission-verify stage
// keeps one per queued tx for the deferred BUSY shed) — EventLoop::send
// looks the connection id up and returns false if it has since closed, so
// a stale copy is safe, its sends just drop.
class ConnectionWriter {
 public:
  // Reply backlog cap: a peer that sends but never reads would otherwise
  // grow the connection's out-queue without bound.  Dropped ACKs are
  // recovered by the sender's retransmission.
  static constexpr size_t kMaxReplyQueue = 1000;

  ConnectionWriter(EventLoop* loop, uint64_t conn_id)
      : loop_(loop), conn_id_(conn_id) {}

  bool send(const Bytes& frame) {
    return loop_->send(conn_id_, std::make_shared<const Bytes>(frame),
                       kMaxReplyQueue);
  }
  bool send(const std::string& s) {
    return loop_->send(conn_id_,
                       std::make_shared<const Bytes>(s.begin(), s.end()),
                       kMaxReplyQueue);
  }

 private:
  EventLoop* loop_;
  uint64_t conn_id_;
};

// dispatch(writer, message): return false to drop the connection.
using MessageHandler = std::function<bool(ConnectionWriter&, Bytes)>;

class NetworkReceiver {
 public:
  NetworkReceiver() = default;
  ~NetworkReceiver() { stop(); }
  NetworkReceiver(const NetworkReceiver&) = delete;

  // Binds and registers the accept callback on the EventLoop. Returns
  // false if bind fails.
  bool spawn(const Address& address, MessageHandler handler,
             const std::string& log_module = "network::receiver");

  uint16_t port() const { return port_; }
  void stop();

  // graftsurge ingress watermarks: suspend/resume reading on every
  // current AND future connection of this receiver (the listener keeps
  // accepting — a paused receiver is slow, not dead; accepted sockets
  // simply start paused).  Thread-safe (posts to the loop); idempotent.
  void set_read_paused(bool paused);

 private:
  // Loop-thread-only connection registry; shared so late callbacks after
  // stop() hit a flagged state instead of a dangling receiver.
  struct State {
    std::unordered_set<uint64_t> conns;
    bool stopped = false;
    bool paused = false;
  };

  uint16_t port_ = 0;
  uint64_t listener_id_ = 0;
  bool spawned_ = false;
  std::shared_ptr<State> state_ = std::make_shared<State>();
};

}  // namespace hotstuff

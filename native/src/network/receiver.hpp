// Network receiver: accept loop + one reader thread per connection, each
// message dispatched through a MessageHandler that may write reply frames
// (ACKs) back on the same connection — the reference's Receiver<Handler>
// (network/src/receiver.rs:31-89) in thread form.
#pragma once

#include <atomic>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common/bytes.hpp"
#include "network/socket.hpp"

namespace hotstuff {

// Reply-capable view of a connection handed to handlers (the Writer half of
// the reference's split framed transport).
class ConnectionWriter {
 public:
  explicit ConnectionWriter(Socket* sock) : sock_(sock) {}

  bool send(const Bytes& frame) {
    std::lock_guard<std::mutex> lk(m_);
    return sock_->write_frame(frame);
  }
  bool send(const std::string& s) {
    std::lock_guard<std::mutex> lk(m_);
    return sock_->write_frame(reinterpret_cast<const uint8_t*>(s.data()),
                              s.size());
  }

 private:
  std::mutex m_;
  Socket* sock_;
};

// dispatch(writer, message): return false to drop the connection.
using MessageHandler =
    std::function<bool(ConnectionWriter&, Bytes)>;

class NetworkReceiver {
 public:
  NetworkReceiver() = default;
  ~NetworkReceiver() { stop(); }
  NetworkReceiver(const NetworkReceiver&) = delete;

  // Binds and spawns the accept loop. Returns false if bind fails.
  bool spawn(const Address& address, MessageHandler handler,
             const std::string& log_module = "network::receiver");

  uint16_t port() const { return listener_.port(); }
  void stop();

 private:
  // Live connection sockets + their (joinable) threads. A connection thread
  // that finishes moves its own thread handle to the graveyard, which the
  // accept loop reaps opportunistically and stop() drains; stop() therefore
  // joins every connection thread ever spawned — no detached thread can
  // outlive the receiver (the round-1/2 shutdown segfault family).
  struct ConnRegistry {
    std::mutex m;
    uint64_t next_id = 0;
    std::unordered_map<uint64_t, std::shared_ptr<Socket>> conns;
    std::unordered_map<uint64_t, std::thread> threads;
    std::vector<std::thread> graveyard;
  };

  Listener listener_;
  std::thread accept_thread_;
  std::atomic<bool> stopping_{false};
  std::shared_ptr<ConnRegistry> registry_ =
      std::make_shared<ConnRegistry>();
};

}  // namespace hotstuff

// Reliable sender: every message returns a CancelHandler (oneshot fulfilled
// with the peer's ACK bytes); per-peer connection state machines live on
// the process-wide EventLoop, retry with exponential backoff (200 ms
// doubling to 60 s) and retransmit un-ACKed messages on reconnection —
// the reference's ReliableSender (network/src/reliable_sender.rs:31-248)
// as reactor callbacks instead of two threads per peer.
#pragma once

#include <atomic>
#include <memory>
#include <vector>

#include "common/bytes.hpp"
#include "common/channel.hpp"
#include "network/socket.hpp"

namespace hotstuff {

using CancelHandler = Oneshot<Bytes>;

class ReliableSender {
 public:
  // `stop` (optional): once set, new sends cancel (empty ACK) immediately
  // instead of queueing, so an actor mid-send always reaches teardown.
  explicit ReliableSender(
      std::shared_ptr<std::atomic<bool>> stop = nullptr);
  // Cancels every outstanding CancelHandler with empty bytes so quorum
  // waiters can never block on an ACK that will not come (the reference
  // gets the same from dropped oneshot senders, reliable_sender.rs:25).
  ~ReliableSender();
  ReliableSender(const ReliableSender&) = delete;
  ReliableSender& operator=(const ReliableSender&) = delete;

  CancelHandler send(const Address& address, Bytes data);
  CancelHandler send_shared(const Address& address,
                            std::shared_ptr<const Bytes> data);
  std::vector<CancelHandler> broadcast(const std::vector<Address>& addresses,
                                       const Bytes& data);

 private:
  struct State;

  // graftsync: no mutex here by design — State lives its whole life on
  // the EventLoop thread (submit/teardown reach it only via post), the
  // reference's task-confinement model.  See the OWNED_BY annotations
  // on State's members in the .cpp.
  std::shared_ptr<std::atomic<bool>> stop_;  // SHARED_OK(atomic flag)
  std::shared_ptr<State> state_;  // SHARED_OK(pointer immutable after
                                  // ctor; pointee loop-thread-only)
};

}  // namespace hotstuff

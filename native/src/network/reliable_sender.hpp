// Reliable sender: every message returns a CancelHandler (oneshot fulfilled
// with the peer's ACK bytes); per-peer connections retry with exponential
// backoff (200 ms doubling to 60 s) and retransmit un-ACKed messages on
// reconnection — the reference's ReliableSender state machine
// (network/src/reliable_sender.rs:31-248).
#pragma once

#include <atomic>
#include <memory>
#include <random>
#include <unordered_map>
#include <vector>

#include "common/bytes.hpp"
#include "common/channel.hpp"
#include "network/socket.hpp"

namespace hotstuff {

using CancelHandler = Oneshot<Bytes>;

class ReliableSender {
 public:
  // `stop` (optional) makes send() interruptible: a send blocked on a full
  // per-peer queue re-checks it every 100 ms and cancels (empty-ACK) once
  // set, so an actor mid-send can always reach its own teardown.
  explicit ReliableSender(
      std::shared_ptr<std::atomic<bool>> stop = nullptr);
  // Closes every per-peer queue and joins the connection threads; any
  // outstanding CancelHandler is fulfilled with empty bytes so quorum
  // waiters can never block on an ACK that will not come (the reference
  // gets the same from dropped oneshot senders, reliable_sender.rs:25).
  ~ReliableSender();
  ReliableSender(const ReliableSender&) = delete;
  ReliableSender& operator=(const ReliableSender&) = delete;

  CancelHandler send(const Address& address, Bytes data);
  CancelHandler send_shared(const Address& address,
                            std::shared_ptr<const Bytes> data);
  std::vector<CancelHandler> broadcast(const std::vector<Address>& addresses,
                                       const Bytes& data);

 private:
  struct Connection;
  std::shared_ptr<Connection> get_or_spawn(const Address& address);

  std::unordered_map<Address, std::shared_ptr<Connection>, AddressHash>
      connections_;
  std::shared_ptr<std::atomic<bool>> stop_;
};

}  // namespace hotstuff

// Reliable sender: every message returns a CancelHandler (oneshot fulfilled
// with the peer's ACK bytes); per-peer connections retry with exponential
// backoff (200 ms doubling to 60 s) and retransmit un-ACKed messages on
// reconnection — the reference's ReliableSender state machine
// (network/src/reliable_sender.rs:31-248).
#pragma once

#include <memory>
#include <random>
#include <unordered_map>
#include <vector>

#include "common/bytes.hpp"
#include "common/channel.hpp"
#include "network/socket.hpp"

namespace hotstuff {

using CancelHandler = Oneshot<Bytes>;

class ReliableSender {
 public:
  ReliableSender();

  CancelHandler send(const Address& address, Bytes data);
  CancelHandler send_shared(const Address& address,
                            std::shared_ptr<const Bytes> data);
  std::vector<CancelHandler> broadcast(const std::vector<Address>& addresses,
                                       const Bytes& data);

 private:
  struct Connection;
  std::shared_ptr<Connection> get_or_spawn(const Address& address);

  std::unordered_map<Address, std::shared_ptr<Connection>, AddressHash>
      connections_;
};

}  // namespace hotstuff

#include "network/simple_sender.hpp"

#include <algorithm>
#include <atomic>
#include <thread>

#include "common/log.hpp"

namespace hotstuff {

namespace {
// Bound the connect syscall so a vanished peer can't pin a connection
// thread (and its joiner) for the kernel's multi-minute TCP timeout.
constexpr int kConnectTimeoutMs = 5000;
}  // namespace

// A connection drains its queue into one socket. On any socket error the
// connection marks itself dead and drops remaining queued messages; the
// next send() to that address spawns a fresh connection (reference
// Connection::run returns on error, simple_sender.rs:105-143).
struct SimpleSender::Connection {
  explicit Connection(const Address& addr)
      : address(addr), queue(kChannelCapacity) {}

  ~Connection() { stop_and_join(); }

  void start() {
    writer_thread = std::thread([this] { run(); });
  }

  void run() {
    auto sock_opt = Socket::connect(address, kConnectTimeoutMs);
    if (!sock_opt) {
      LOG_WARN("network::simple_sender")
          << "failed to connect to " << address.str();
      dead.store(true);
      queue.close();
      return;
    }
    {
      // Serialize the fd hand-off against a concurrent stop_and_join()
      // shutdown (the owner may reap this connection while we connect).
      std::lock_guard<std::mutex> lk(sock_m);
      sock = std::move(*sock_opt);
    }
    // Close the teardown/connect race: stop_and_join()'s shutdown may have
    // hit the pre-connect placeholder fd while we were inside connect().
    // dead is set before that shutdown, so checking it after the hand-off
    // covers both interleavings — without this, the writer would drain
    // already-queued frames into a socket nobody can cut.
    if (dead.load()) {
      std::lock_guard<std::mutex> lk(sock_m);
      sock.shutdown();
      return;
    }
    LOG_DEBUG("network::simple_sender")
        << "Outgoing connection established with " << address.str();

    // Sink replies so the peer's ACK writes never fill the TCP buffer.
    reader_thread = std::thread([this] {
      Bytes frame;
      while (sock.read_frame(&frame)) {
      }
      dead.store(true);
      queue.close();  // wake the writer
    });

    while (auto data = queue.recv()) {
      if (dead.load() || !sock.write_frame(*data)) {
        LOG_WARN("network::simple_sender")
            << "failed to send message to " << address.str();
        break;
      }
    }
    dead.store(true);
    queue.close();
    std::lock_guard<std::mutex> lk(sock_m);
    sock.shutdown();  // wake the reader
  }

  // Idempotent; joining the writer first guarantees reader_thread is fully
  // constructed (the writer creates it) before we join it.
  void stop_and_join() {
    dead.store(true);  // before the shutdown: see the post-connect check
    queue.close();
    {
      std::lock_guard<std::mutex> lk(sock_m);
      sock.shutdown();
    }
    if (writer_thread.joinable()) writer_thread.join();
    if (reader_thread.joinable()) reader_thread.join();
  }

  Address address;
  Channel<Bytes> queue;
  std::mutex sock_m;  // guards fd hand-off/shutdown, not steady-state IO
  Socket sock;
  std::atomic<bool> dead{false};
  std::thread writer_thread;
  std::thread reader_thread;
};

SimpleSender::SimpleSender() : rng_(std::random_device{}()) {}

SimpleSender::~SimpleSender() {
  for (auto& [_, conn] : connections_) conn->stop_and_join();
}

std::shared_ptr<SimpleSender::Connection> SimpleSender::get_or_spawn(
    const Address& address) {
  auto it = connections_.find(address);
  if (it != connections_.end() && !it->second->dead.load()) {
    return it->second;
  }
  if (it != connections_.end()) it->second->stop_and_join();
  auto conn = std::make_shared<Connection>(address);
  conn->start();
  connections_[address] = conn;  // old entry (if any) joined above
  return conn;
}

void SimpleSender::send(const Address& address, Bytes data) {
  auto conn = get_or_spawn(address);
  if (!conn->queue.try_send(std::move(data))) {
    // Queue full or connection died — best-effort: drop.
    LOG_DEBUG("network::simple_sender")
        << "dropping message to " << address.str();
  }
}

void SimpleSender::broadcast(const std::vector<Address>& addresses,
                             const Bytes& data) {
  for (const auto& a : addresses) send(a, data);
}

void SimpleSender::lucky_broadcast(std::vector<Address> addresses,
                                   const Bytes& data, size_t nodes) {
  std::shuffle(addresses.begin(), addresses.end(), rng_);
  if (addresses.size() > nodes) addresses.resize(nodes);
  broadcast(addresses, data);
}

}  // namespace hotstuff

#include "network/simple_sender.hpp"

#include <algorithm>
#include <atomic>
#include <thread>

#include "common/log.hpp"

namespace hotstuff {

// A connection drains its queue into one socket. On any socket error the
// connection marks itself dead and drops remaining queued messages; the
// next send() to that address spawns a fresh connection (reference
// Connection::run returns on error, simple_sender.rs:105-143).
struct SimpleSender::Connection {
  explicit Connection(const Address& addr)
      : address(addr), queue(kChannelCapacity) {}

  void start() {
    auto self = shared;
    writer_thread = std::thread([self] { self->run(); });
    writer_thread.detach();
  }

  void run() {
    auto sock_opt = Socket::connect(address);
    if (!sock_opt) {
      LOG_WARN("network::simple_sender")
          << "failed to connect to " << address.str();
      dead.store(true);
      queue.close();
      shared.reset();
      return;
    }
    sock = std::move(*sock_opt);
    LOG_DEBUG("network::simple_sender")
        << "Outgoing connection established with " << address.str();

    // Sink replies so the peer's ACK writes never fill the TCP buffer.
    auto self = shared;
    std::thread([self] {
      Bytes frame;
      while (self->sock.read_frame(&frame)) {
      }
      self->dead.store(true);
      self->queue.close();  // wake the writer
    }).detach();

    while (auto data = queue.recv()) {
      if (dead.load() || !sock.write_frame(*data)) {
        LOG_WARN("network::simple_sender")
            << "failed to send message to " << address.str();
        break;
      }
    }
    dead.store(true);
    queue.close();
    sock.shutdown();
    shared.reset();  // break the self-cycle so dead connections free
  }

  Address address;
  Channel<Bytes> queue;
  Socket sock;
  std::atomic<bool> dead{false};
  std::thread writer_thread;
  std::shared_ptr<Connection> shared;  // set by get_or_spawn before start()
};

SimpleSender::SimpleSender() : rng_(std::random_device{}()) {}

std::shared_ptr<SimpleSender::Connection> SimpleSender::get_or_spawn(
    const Address& address) {
  auto it = connections_.find(address);
  if (it != connections_.end() && !it->second->dead.load()) {
    return it->second;
  }
  auto conn = std::make_shared<Connection>(address);
  conn->shared = conn;
  conn->start();
  connections_[address] = conn;
  return conn;
}

void SimpleSender::send(const Address& address, Bytes data) {
  auto conn = get_or_spawn(address);
  if (!conn->queue.try_send(std::move(data))) {
    // Queue full or connection died — best-effort: drop.
    LOG_DEBUG("network::simple_sender")
        << "dropping message to " << address.str();
  }
}

void SimpleSender::broadcast(const std::vector<Address>& addresses,
                             const Bytes& data) {
  for (const auto& a : addresses) send(a, data);
}

void SimpleSender::lucky_broadcast(std::vector<Address> addresses,
                                   const Bytes& data, size_t nodes) {
  std::shuffle(addresses.begin(), addresses.end(), rng_);
  if (addresses.size() > nodes) addresses.resize(nodes);
  broadcast(addresses, data);
}

}  // namespace hotstuff

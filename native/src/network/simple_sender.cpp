#include "network/simple_sender.hpp"

#include <unistd.h>

#include <algorithm>
#include <deque>

#include "common/log.hpp"
#include "network/event_loop.hpp"

namespace hotstuff {

namespace {
// Bound the connect attempt so a vanished peer can't pin reconnect state
// past the point anyone cares.
constexpr int kConnectTimeoutMs = 5000;
// Per-peer outbound backlog cap, matching the bounded channel of the
// thread-based design: beyond it messages drop (best-effort semantics,
// simple_sender.rs:105-143).
constexpr size_t kMaxQueue = kChannelCapacity;
// A failed connect retries (with capped backoff) while queued messages
// exist, instead of dropping them.  At 100-node single-host scale the
// boot is a connect storm: listeners come up over many seconds, and a
// once-per-round message (a vote) dropped on one early failed connect
// costs the whole committee a view change.  Bounded so a genuinely dead
// peer still converges to kDead/drop (best-effort semantics preserved).
constexpr int kMaxConnectRetries = 40;
constexpr auto kConnectRetryBase = std::chrono::milliseconds(250);
constexpr auto kConnectRetryCap = std::chrono::milliseconds(2000);
}  // namespace

// Loop-thread-only state. A peer is (re)connected lazily on send; failure
// drops everything queued and the next send retries — matching the
// reference's Connection::run returning on error.
struct SimpleSender::State {
  struct Peer {
    enum class St { kConnecting, kLive, kDead };
    St st = St::kDead;
    uint64_t conn_id = 0;
    int connect_fails = 0;
    std::deque<std::shared_ptr<const Bytes>> pending;  // while connecting
  };

  EventLoop* loop = &EventLoop::instance();
  std::unordered_map<Address, Peer, AddressHash> peers;
  bool stopped = false;

  void send(const std::shared_ptr<State>& self, const Address& addr,
            std::shared_ptr<const Bytes> data) {
    if (stopped) return;
    Peer& p = peers[addr];
    switch (p.st) {
      case Peer::St::kLive:
        if (!loop->send(p.conn_id, std::move(data), kMaxQueue)) {
          LOG_DEBUG("network::simple_sender")
              << "dropping message to " << addr.str();
        }
        return;
      case Peer::St::kConnecting:
        if (p.pending.size() >= kMaxQueue) {
          LOG_DEBUG("network::simple_sender")
              << "dropping message to " << addr.str();
          return;
        }
        p.pending.push_back(std::move(data));
        return;
      case Peer::St::kDead:
        p.st = Peer::St::kConnecting;
        p.pending.clear();
        p.pending.push_back(std::move(data));
        connect(self, addr);
        return;
    }
  }

  void connect(const std::shared_ptr<State>& self, Address addr) {
    loop->connect(addr, kConnectTimeoutMs, [self, addr](int fd) {
      Peer& p = self->peers[addr];
      if (self->stopped) {
        if (fd >= 0) ::close(fd);
        return;
      }
      if (fd < 0) {
        if (!p.pending.empty() && p.connect_fails < kMaxConnectRetries) {
          auto delay = std::min(kConnectRetryBase * (1 + p.connect_fails),
                                kConnectRetryCap);
          ++p.connect_fails;
          self->loop->run_after(delay, [self, addr] {
            if (self->stopped) return;
            // Invariant: while a retry timer is pending the peer stays
            // kConnecting with a non-empty queue (sends only enqueue,
            // on_closed requires a live conn, and give-up/success run
            // only in the connect callback below).
            self->connect(self, addr);
          });
          return;  // stays kConnecting; sends keep queueing (capped)
        }
        LOG_WARN("network::simple_sender")
            << "failed to connect to " << addr.str();
        p.st = Peer::St::kDead;
        p.connect_fails = 0;
        p.pending.clear();
        return;
      }
      LOG_DEBUG("network::simple_sender")
          << "Outgoing connection established with " << addr.str();
      p.st = Peer::St::kLive;
      p.connect_fails = 0;
      uint64_t cid = self->loop->adopt(
          fd,
          // Sink replies so the peer's ACK writes never fill its buffer.
          [](uint64_t, Bytes) {},
          [self, addr](uint64_t) {
            // Peer closed (EOF at teardown is the common case; a failed
            // in-flight write lands here too). Best-effort semantics:
            // drop state, reconnect lazily on the next send.
            Peer& q = self->peers[addr];
            LOG_DEBUG("network::simple_sender")
                << "connection to " << addr.str() << " closed";
            q.st = Peer::St::kDead;
            q.pending.clear();
          });
      p.conn_id = cid;
      // Drain a MOVED backlog: a hard send error runs on_closed
      // reentrantly, and that callback clears p.pending — clearing the
      // deque being iterated would be UB.
      auto backlog = std::move(p.pending);
      p.pending.clear();
      for (auto& d : backlog) {
        if (!self->loop->send(cid, std::move(d))) break;  // died mid-drain
      }
    });
  }
};

SimpleSender::SimpleSender()
    : rng_(std::random_device{}()), state_(std::make_shared<State>()) {}

SimpleSender::~SimpleSender() {
  auto state = state_;
  state->loop->post_wait([state] {
    state->stopped = true;
    for (auto& [_, p] : state->peers) {
      if (p.st == State::Peer::St::kLive) state->loop->close(p.conn_id);
      p.pending.clear();
    }
    state->peers.clear();
  });
}

void SimpleSender::send(const Address& address, Bytes data) {
  auto state = state_;
  auto shared = std::make_shared<const Bytes>(std::move(data));
  state->loop->post([state, address, shared] {
    state->send(state, address, shared);
  });
}

void SimpleSender::broadcast(const std::vector<Address>& addresses,
                             const Bytes& data) {
  auto shared = std::make_shared<const Bytes>(data);
  auto state = state_;
  for (const auto& a : addresses) {
    state->loop->post([state, a, shared] { state->send(state, a, shared); });
  }
}

void SimpleSender::lucky_broadcast(std::vector<Address> addresses,
                                   const Bytes& data, size_t nodes) {
  std::shuffle(addresses.begin(), addresses.end(), rng_);
  if (addresses.size() > nodes) addresses.resize(nodes);
  broadcast(addresses, data);
}

}  // namespace hotstuff

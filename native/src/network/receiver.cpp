#include "network/receiver.hpp"

#include <unistd.h>

#include "common/log.hpp"

namespace hotstuff {

bool NetworkReceiver::spawn(const Address& address, MessageHandler handler,
                            const std::string& log_module) {
  auto l = Listener::bind(address);
  if (!l) {
    LOG_ERROR(log_module) << "failed to bind " << address.str();
    return false;
  }
  port_ = l->port();
  int listen_fd = l->release();
  LOG_DEBUG(log_module) << "Listening on " << address.str();

  EventLoop* loop = &EventLoop::instance();
  auto state = state_;
  loop->post_wait([this, loop, state, listen_fd, handler, log_module] {
    listener_id_ = loop->add_listener(listen_fd, [loop, state, handler,
                                                  log_module](int fd) {
      if (state->stopped) {
        ::close(fd);
        return;
      }
      uint64_t id = loop->adopt(
          fd,
          // on_frame: dispatch through the handler; false drops the conn.
          [loop, state, handler](uint64_t cid, Bytes frame) {
            ConnectionWriter writer(loop, cid);
            bool keep = true;
            try {
              keep = handler(writer, std::move(frame));
            } catch (const std::exception& e) {
              // Handlers guard their own parse paths; this is the
              // last-resort belt so attacker bytes can't take the
              // reactor down.
              keep = false;
            }
            if (!keep) {
              state->conns.erase(cid);
              loop->close(cid);
            }
          },
          // on_closed (peer EOF / error)
          [state](uint64_t cid) { state->conns.erase(cid); });
      state->conns.insert(id);
      // A connection accepted while the receiver is paused (ingress
      // watermark) starts paused: the backlog that triggered the pause
      // is shared, so a fresh socket must not bypass it.
      if (state->paused) loop->set_read_paused(id, true);
    });
  });
  spawned_ = true;
  return true;
}

void NetworkReceiver::set_read_paused(bool paused) {
  if (!spawned_) return;
  EventLoop* loop = &EventLoop::instance();
  auto state = state_;
  loop->post([loop, state, paused] {
    if (state->stopped || state->paused == paused) return;
    state->paused = paused;
    for (uint64_t id : state->conns) loop->set_read_paused(id, paused);
  });
}

void NetworkReceiver::stop() {
  if (!spawned_) return;
  spawned_ = false;
  EventLoop* loop = &EventLoop::instance();
  auto state = state_;
  uint64_t listener_id = listener_id_;
  loop->post_wait([loop, state, listener_id] {
    state->stopped = true;
    loop->close(listener_id);
    for (uint64_t id : state->conns) loop->close(id);
    state->conns.clear();
  });
}

}  // namespace hotstuff

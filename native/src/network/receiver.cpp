#include "network/receiver.hpp"

#include "common/log.hpp"

namespace hotstuff {

bool NetworkReceiver::spawn(const Address& address, MessageHandler handler,
                            const std::string& log_module) {
  auto l = Listener::bind(address);
  if (!l) {
    LOG_ERROR(log_module) << "failed to bind " << address.str();
    return false;
  }
  listener_ = std::move(*l);
  LOG_DEBUG(log_module) << "Listening on " << address.str();

  auto registry = registry_;
  accept_thread_ = std::thread([this, registry, handler, log_module] {
    while (!stopping_.load()) {
      auto sock = listener_.accept();
      if (!sock) {
        if (stopping_.load()) return;
        // Persistent accept failures (e.g. EMFILE) must not busy-spin.
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
        continue;
      }
      auto sp = std::make_shared<Socket>(std::move(*sock));
      uint64_t id;
      {
        std::lock_guard<std::mutex> lk(registry->m);
        id = registry->next_id++;
        registry->conns.emplace(id, sp);
      }
      // Joinable: the thread parks its own handle in the graveyard when it
      // exits (reaped below / in stop()), so long-running nodes don't
      // accumulate per-connection state yet every thread gets joined.
      std::thread conn_thread([registry, id, sp, handler] {
        ConnectionWriter writer(sp.get());
        Bytes frame;
        while (sp->read_frame(&frame)) {
          if (!handler(writer, std::move(frame))) break;
          frame.clear();
        }
        std::lock_guard<std::mutex> lk(registry->m);
        registry->conns.erase(id);
        auto it = registry->threads.find(id);
        if (it != registry->threads.end()) {
          registry->graveyard.push_back(std::move(it->second));
          registry->threads.erase(it);
        }
      });
      {
        std::lock_guard<std::mutex> lk(registry->m);
        // The thread may have already finished and found no handle to
        // park; only register it if its connection is still live — else
        // straight to the graveyard.
        if (registry->conns.count(id)) {
          registry->threads.emplace(id, std::move(conn_thread));
        } else {
          registry->graveyard.push_back(std::move(conn_thread));
        }
        // Reap finished threads (join returns immediately for them).
        for (auto& t : registry->graveyard) t.join();
        registry->graveyard.clear();
      }
    }
  });
  return true;
}

void NetworkReceiver::stop() {
  if (stopping_.exchange(true)) return;
  listener_.shutdown();
  if (accept_thread_.joinable()) accept_thread_.join();
  listener_.close();
  // Shut down live connections and join every connection thread. Callers
  // must close the channels the handler feeds BEFORE stopping the receiver,
  // or a handler blocked in a full channel send would stall the join.
  std::vector<std::thread> to_join;
  {
    std::lock_guard<std::mutex> lk(registry_->m);
    for (auto& [_, s] : registry_->conns) s->shutdown();
    for (auto& [_, t] : registry_->threads) to_join.push_back(std::move(t));
    registry_->threads.clear();
    for (auto& t : registry_->graveyard) to_join.push_back(std::move(t));
    registry_->graveyard.clear();
  }
  for (auto& t : to_join) t.join();
}

}  // namespace hotstuff

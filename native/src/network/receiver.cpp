#include "network/receiver.hpp"

#include "common/log.hpp"

namespace hotstuff {

bool NetworkReceiver::spawn(const Address& address, MessageHandler handler,
                            const std::string& log_module) {
  auto l = Listener::bind(address);
  if (!l) {
    LOG_ERROR(log_module) << "failed to bind " << address.str();
    return false;
  }
  listener_ = std::move(*l);
  LOG_DEBUG(log_module) << "Listening on " << address.str();

  auto registry = registry_;
  accept_thread_ = std::thread([this, registry, handler, log_module] {
    while (!stopping_.load()) {
      auto sock = listener_.accept();
      if (!sock) {
        if (stopping_.load()) return;
        // Persistent accept failures (e.g. EMFILE) must not busy-spin.
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
        continue;
      }
      auto sp = std::make_shared<Socket>(std::move(*sock));
      uint64_t id;
      {
        std::lock_guard<std::mutex> lk(registry->m);
        id = registry->next_id++;
        registry->conns.emplace(id, sp);
      }
      // Detached; self-removes from the registry on exit so long-running
      // nodes don't accumulate per-connection state.
      std::thread([registry, id, sp, handler] {
        ConnectionWriter writer(sp.get());
        Bytes frame;
        while (sp->read_frame(&frame)) {
          if (!handler(writer, std::move(frame))) break;
          frame.clear();
        }
        std::lock_guard<std::mutex> lk(registry->m);
        registry->conns.erase(id);
      }).detach();
    }
  });
  return true;
}

void NetworkReceiver::stop() {
  if (stopping_.exchange(true)) return;
  listener_.shutdown();
  if (accept_thread_.joinable()) accept_thread_.join();
  listener_.close();
  // Shut down live connections; their detached threads hold the socket and
  // registry shared_ptrs and unregister themselves as they exit.
  std::lock_guard<std::mutex> lk(registry_->m);
  for (auto& [_, s] : registry_->conns) s->shutdown();
}

}  // namespace hotstuff

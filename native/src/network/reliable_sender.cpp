#include "network/reliable_sender.hpp"

#include <unistd.h>

#include <chrono>
#include <deque>

#include "common/log.hpp"
#include "network/event_loop.hpp"

namespace hotstuff {

namespace {
constexpr auto kInitialBackoff = std::chrono::milliseconds(200);
// Reconnect probes are one SYN each: capping the backoff at 5 s (not the
// reference's effectively-unbounded doubling) costs a dead peer ~0.2
// connect attempts/s, and recovers a 100-node single-host boot storm —
// with a 60 s cap, a sender that failed a handful of early connects
// sleeps through entire view-change cycles after its peer is up.
constexpr auto kMaxBackoff = std::chrono::milliseconds(5'000);
constexpr int kConnectTimeoutMs = 5000;
// Cap on un-ACKed + queued messages per peer (the thread-based design's
// bounded channel): beyond it new sends cancel immediately (empty ACK) —
// a peer 1000 messages behind is as good as gone, and quorum waiters
// count the OTHER replicas' ACKs.
constexpr size_t kMaxOutstanding = kChannelCapacity;
}  // namespace

// Loop-thread-only per-peer state machine, the reference's ReliableSender
// Connection task (network/src/reliable_sender.rs:31-248) as reactor
// callbacks: FIFO ACK matching, exponential reconnect backoff, un-ACKed
// retransmission on reconnect, and cancellation (empty ACK) of everything
// outstanding at teardown.
struct ReliableSender::State {
  struct Msg {
    std::shared_ptr<const Bytes> data;
    CancelHandler ack;
  };
  struct Peer {
    enum class St { kIdle, kConnecting, kLive, kBackoff };
    St st = St::kIdle;
    uint64_t conn_id = 0;
    std::deque<Msg> queue;    // waiting to be written (incl. retransmit)
    std::deque<Msg> pending;  // written, awaiting ACK (FIFO)
    std::chrono::milliseconds backoff = kInitialBackoff;
  };

  EventLoop* loop = &EventLoop::instance();  // SHARED_OK(immutable)
  std::unordered_map<Address, Peer, AddressHash> peers;  // OWNED_BY(loop thread)
  bool stopped = false;                                  // OWNED_BY(loop thread)

  void submit(const std::shared_ptr<State>& self, const Address& addr,
              Msg msg) {
    if (stopped) {
      msg.ack.set(Bytes{});
      return;
    }
    if (msg.data->size() > EventLoop::kMaxFrame) {
      // An unframeable payload would sit in pending forever and shift
      // the FIFO ACK matching; cancel it up front.
      msg.ack.set(Bytes{});
      return;
    }
    Peer& p = peers[addr];
    if (p.queue.size() + p.pending.size() >= kMaxOutstanding) {
      LOG_DEBUG("network::reliable_sender")
          << "backlog full for " << addr.str() << "; cancelling send";
      msg.ack.set(Bytes{});
      return;
    }
    switch (p.st) {
      case Peer::St::kLive:
        write(p, std::move(msg));
        return;
      case Peer::St::kConnecting:
      case Peer::St::kBackoff:
        p.queue.push_back(std::move(msg));
        return;
      case Peer::St::kIdle:
        p.queue.push_back(std::move(msg));
        start_connect(self, addr);
        return;
    }
  }

  // Pushes to pending BEFORE the send: a hard send error destroys the
  // connection and runs on_disconnected reentrantly, which recovers
  // pending (including this message) into the queue — so nothing is
  // stranded and FIFO order is preserved.  False = the connection died.
  bool write(Peer& p, Msg msg) {
    auto data = msg.data;
    p.pending.push_back(std::move(msg));
    return loop->send(p.conn_id, std::move(data)) &&
           p.st == Peer::St::kLive;
  }

  void start_connect(const std::shared_ptr<State>& self, Address addr) {
    Peer& p = peers[addr];
    p.st = Peer::St::kConnecting;
    loop->connect(addr, kConnectTimeoutMs, [self, addr](int fd) {
      self->on_connected(self, addr, fd);
    });
  }

  void on_connected(const std::shared_ptr<State>& self, const Address& addr,
                    int fd) {
    Peer& p = peers[addr];
    if (stopped) {
      if (fd >= 0) ::close(fd);
      return;
    }
    if (fd < 0) {
      LOG_DEBUG("network::reliable_sender")
          << "failed to connect to " << addr.str() << "; retrying in "
          << p.backoff.count() << " ms";
      schedule_reconnect(self, addr);
      return;
    }
    LOG_DEBUG("network::reliable_sender")
        << "Outgoing connection established with " << addr.str();
    p.st = Peer::St::kLive;
    p.backoff = kInitialBackoff;
    p.conn_id = loop->adopt(
        fd,
        // ACK frames match the oldest in-flight message (FIFO, the
        // reference's pending_replies deque, reliable_sender.rs:214-238).
        [self, addr](uint64_t, Bytes frame) {
          Peer& q = self->peers[addr];
          if (!q.pending.empty()) {
            q.pending.front().ack.set(std::move(frame));
            q.pending.pop_front();
          }
        },
        [self, addr](uint64_t) { self->on_disconnected(self, addr); });
    // Drain the backlog (retransmits first — submit appends to the back).
    // Stop the moment the connection dies mid-drain: on_disconnected has
    // already recovered pending into the queue, and continuing would
    // re-pend messages against a stale conn id.
    while (p.st == Peer::St::kLive && !p.queue.empty()) {
      Msg m = std::move(p.queue.front());
      p.queue.pop_front();
      if (!write(p, std::move(m))) break;
    }
  }

  void on_disconnected(const std::shared_ptr<State>& self,
                       const Address& addr) {
    Peer& p = peers[addr];
    // Un-ACKed messages go back to the FRONT of the queue, before anything
    // submitted while we were live, preserving send order on reconnect.
    while (!p.pending.empty()) {
      p.queue.push_front(std::move(p.pending.back()));
      p.pending.pop_back();
    }
    LOG_DEBUG("network::reliable_sender")
        << "connection to " << addr.str() << " dropped; " << p.queue.size()
        << " message(s) to retransmit";
    schedule_reconnect(self, addr);
  }

  void schedule_reconnect(const std::shared_ptr<State>& self, Address addr) {
    Peer& p = peers[addr];
    p.st = Peer::St::kBackoff;
    auto delay = p.backoff;
    p.backoff = std::min(p.backoff * 2, kMaxBackoff);
    loop->run_after(delay, [self, addr] {
      if (self->stopped) return;
      Peer& q = self->peers[addr];
      if (q.st == Peer::St::kBackoff) self->start_connect(self, addr);
    });
  }

  void teardown() {
    stopped = true;
    for (auto& [_, p] : peers) {
      if (p.st == Peer::St::kLive) loop->close(p.conn_id);
      // Cancel every outstanding send (empty ACK) so QuorumWaiter/Proposer
      // stake-waits can't hang on messages that will never be delivered.
      for (auto& m : p.pending) m.ack.set(Bytes{});
      for (auto& m : p.queue) m.ack.set(Bytes{});
      p.pending.clear();
      p.queue.clear();
    }
    peers.clear();
  }
};

ReliableSender::ReliableSender(std::shared_ptr<std::atomic<bool>> stop)
    : stop_(std::move(stop)), state_(std::make_shared<State>()) {}

ReliableSender::~ReliableSender() {
  auto state = state_;
  state->loop->post_wait([state] { state->teardown(); });
}

CancelHandler ReliableSender::send(const Address& address, Bytes data) {
  return send_shared(address,
                     std::make_shared<const Bytes>(std::move(data)));
}

CancelHandler ReliableSender::send_shared(
    const Address& address, std::shared_ptr<const Bytes> data) {
  State::Msg m;
  m.data = std::move(data);
  CancelHandler handler = m.ack;
  if (stop_ && stop_->load(std::memory_order_relaxed)) {
    handler.set(Bytes{});  // stopping: cancelled, waiters must not hang
    return handler;
  }
  auto state = state_;
  state->loop->post([state, address, m = std::move(m)]() mutable {
    state->submit(state, address, std::move(m));
  });
  return handler;
}

std::vector<CancelHandler> ReliableSender::broadcast(
    const std::vector<Address>& addresses, const Bytes& data) {
  auto shared = std::make_shared<const Bytes>(data);
  std::vector<CancelHandler> handlers;
  handlers.reserve(addresses.size());
  for (const auto& a : addresses) handlers.push_back(send_shared(a, shared));
  return handlers;
}

}  // namespace hotstuff

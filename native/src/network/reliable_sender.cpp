#include "network/reliable_sender.hpp"

#include <atomic>
#include <chrono>
#include <deque>
#include <mutex>
#include <thread>

#include "common/log.hpp"

namespace hotstuff {

namespace {
constexpr auto kInitialBackoff = std::chrono::milliseconds(200);
constexpr auto kMaxBackoff = std::chrono::milliseconds(60'000);
constexpr int kConnectTimeoutMs = 5000;
}  // namespace

// One long-lived connection task per peer. The writer loop pulls from the
// queue and sends; a per-socket reader matches incoming ACK frames to the
// oldest in-flight message (FIFO, as the reference's pending_replies deque,
// reliable_sender.rs:214-238). On any socket error both halves tear down,
// un-ACKed messages are queued for retransmission, and the connect loop
// backs off exponentially.
struct ReliableSender::Connection {
  struct Msg {
    // Shared so broadcast fan-out and the pending/retransmit queues never
    // deep-copy the payload (the reference's refcounted bytes::Bytes).
    std::shared_ptr<const Bytes> data;
    CancelHandler ack;
  };

  explicit Connection(const Address& addr)
      : address(addr), queue(kChannelCapacity) {}

  void start() {
    thread = std::thread([this] { run(); });
  }

  void run() {
    auto backoff = kInitialBackoff;
    std::deque<Msg> retransmit;
    bool closed = false;
    while (!closed) {
      // -- connect (with backoff) ----------------------------------------
      auto sock_opt = Socket::connect(address, kConnectTimeoutMs);
      if (!sock_opt) {
        LOG_DEBUG("network::reliable_sender")
            << "failed to connect to " << address.str() << "; retrying in "
            << backoff.count() << " ms";
        // Interruptible backoff: new messages arriving while disconnected
        // are stashed for the retransmit pass, and a closed queue
        // (teardown) ends the loop instead of sleeping out the backoff.
        Msg stash;
        auto status = queue.recv_until(
            &stash, std::chrono::steady_clock::now() + backoff);
        if (status == RecvStatus::kOk) {
          retransmit.push_back(std::move(stash));
        } else if (status == RecvStatus::kClosed) {
          closed = true;
        }
        backoff = std::min(backoff * 2, kMaxBackoff);
        continue;
      }
      backoff = kInitialBackoff;
      LOG_DEBUG("network::reliable_sender")
          << "Outgoing connection established with " << address.str();

      auto sock = std::make_shared<Socket>(std::move(*sock_opt));
      {
        // Publish the live socket so ~ReliableSender can shutdown() it and
        // unblock a writer stuck in write_frame against a wedged peer.
        std::lock_guard<std::mutex> lk(live_sock_m);
        live_sock = sock;
      }
      // Close the teardown/connect race: if ~ReliableSender ran its
      // shutdown pass while we were inside connect() (live_sock was null,
      // nothing to cut), we must not start writing on a socket nobody can
      // shut down. stopping is set before that pass, so checking it after
      // publishing covers both interleavings.
      if (stopping.load()) {
        sock->shutdown();
        break;
      }
      auto pending = std::make_shared<std::deque<Msg>>();
      auto pending_m = std::make_shared<std::mutex>();
      auto broken = std::make_shared<std::atomic<bool>>(false);

      // -- reader: match ACK frames to in-flight messages ----------------
      std::thread reader([sock, pending, pending_m, broken] {
        Bytes frame;
        while (sock->read_frame(&frame)) {
          std::lock_guard<std::mutex> lk(*pending_m);
          if (!pending->empty()) {
            pending->front().ack.set(std::move(frame));
            pending->pop_front();
          }
          frame.clear();
        }
        broken->store(true);
        sock->shutdown();
      });

      // -- retransmit backlog from the previous socket -------------------
      bool ok = true;
      while (ok && !retransmit.empty()) {
        Msg m = std::move(retransmit.front());
        retransmit.pop_front();
        auto data = m.data;
        {
          std::lock_guard<std::mutex> lk(*pending_m);
          pending->push_back(std::move(m));
        }
        ok = sock->write_frame(*data);
      }

      // -- writer loop ---------------------------------------------------
      while (ok && !broken->load()) {
        Msg m;
        auto status = queue.recv_until(
            &m, std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(100));
        if (status == RecvStatus::kClosed) {
          closed = true;
          break;
        }
        if (status == RecvStatus::kTimeout) continue;
        auto data = m.data;
        {
          std::lock_guard<std::mutex> lk(*pending_m);
          pending->push_back(std::move(m));
        }
        ok = sock->write_frame(*data);
      }

      // -- teardown: recover un-ACKed messages ---------------------------
      {
        std::lock_guard<std::mutex> lk(live_sock_m);
        live_sock.reset();
      }
      sock->shutdown();
      reader.join();
      {
        std::lock_guard<std::mutex> lk(*pending_m);
        for (auto& m : *pending) retransmit.push_back(std::move(m));
        pending->clear();
      }
      LOG_DEBUG("network::reliable_sender")
          << "connection to " << address.str() << " dropped; "
          << retransmit.size() << " message(s) to retransmit";
    }
    // Teardown: cancel every outstanding send by fulfilling its ack with
    // empty bytes, so QuorumWaiter/Proposer stake-waits can't hang on
    // messages that will never be delivered.
    for (auto& m : retransmit) m.ack.set(Bytes{});
    Msg leftover;
    while (queue.try_recv(&leftover)) leftover.ack.set(Bytes{});
  }

  void shutdown_live_socket() {
    std::lock_guard<std::mutex> lk(live_sock_m);
    if (live_sock) live_sock->shutdown();
  }

  Address address;
  Channel<Msg> queue;
  std::thread thread;
  std::atomic<bool> stopping{false};
  std::mutex live_sock_m;
  std::shared_ptr<Socket> live_sock;
};

ReliableSender::ReliableSender(std::shared_ptr<std::atomic<bool>> stop)
    : stop_(std::move(stop)) {}

ReliableSender::~ReliableSender() {
  for (auto& [_, conn] : connections_) {
    conn->stopping.store(true);
    conn->queue.close();
  }
  // A writer blocked inside write_frame (peer TCP-connected but not
  // reading) cannot observe the closed queue; cut the socket under it.
  for (auto& [_, conn] : connections_) conn->shutdown_live_socket();
  for (auto& [_, conn] : connections_) {
    if (conn->thread.joinable()) conn->thread.join();
  }
}

std::shared_ptr<ReliableSender::Connection> ReliableSender::get_or_spawn(
    const Address& address) {
  auto it = connections_.find(address);
  if (it != connections_.end()) return it->second;
  auto conn = std::make_shared<Connection>(address);
  conn->start();
  connections_[address] = conn;
  return conn;
}

CancelHandler ReliableSender::send(const Address& address, Bytes data) {
  return send_shared(address,
                     std::make_shared<const Bytes>(std::move(data)));
}

CancelHandler ReliableSender::send_shared(
    const Address& address, std::shared_ptr<const Bytes> data) {
  auto conn = get_or_spawn(address);
  Connection::Msg m;
  m.data = std::move(data);
  CancelHandler handler = m.ack;
  // Bounded, stop-aware send: a full queue (peer long gone, 1000-message
  // backlog) must not wedge the calling actor past teardown.
  while (true) {
    auto status = conn->queue.send_until(
        &m, std::chrono::steady_clock::now() +
                std::chrono::milliseconds(100));
    if (status == RecvStatus::kOk) return handler;
    if (status == RecvStatus::kClosed || (stop_ && stop_->load())) {
      handler.set(Bytes{});  // cancelled — waiters must not hang on this
      return handler;
    }
  }
}

std::vector<CancelHandler> ReliableSender::broadcast(
    const std::vector<Address>& addresses, const Bytes& data) {
  auto shared = std::make_shared<const Bytes>(data);
  std::vector<CancelHandler> handlers;
  handlers.reserve(addresses.size());
  for (const auto& a : addresses) handlers.push_back(send_shared(a, shared));
  return handlers;
}

}  // namespace hotstuff

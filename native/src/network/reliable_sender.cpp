#include "network/reliable_sender.hpp"

#include <atomic>
#include <chrono>
#include <deque>
#include <mutex>
#include <thread>

#include "common/log.hpp"

namespace hotstuff {

namespace {
constexpr auto kInitialBackoff = std::chrono::milliseconds(200);
constexpr auto kMaxBackoff = std::chrono::milliseconds(60'000);
}  // namespace

// One long-lived connection task per peer. The writer loop pulls from the
// queue and sends; a per-socket reader matches incoming ACK frames to the
// oldest in-flight message (FIFO, as the reference's pending_replies deque,
// reliable_sender.rs:214-238). On any socket error both halves tear down,
// un-ACKed messages are queued for retransmission, and the connect loop
// backs off exponentially.
struct ReliableSender::Connection {
  struct Msg {
    // Shared so broadcast fan-out and the pending/retransmit queues never
    // deep-copy the payload (the reference's refcounted bytes::Bytes).
    std::shared_ptr<const Bytes> data;
    CancelHandler ack;
  };

  explicit Connection(const Address& addr)
      : address(addr), queue(kChannelCapacity) {}

  void start(std::shared_ptr<Connection> self) {
    std::thread([self] { self->run(); }).detach();
  }

  void run() {
    auto backoff = kInitialBackoff;
    std::deque<Msg> retransmit;
    while (true) {
      // -- connect (with backoff) ----------------------------------------
      auto sock_opt = Socket::connect(address);
      if (!sock_opt) {
        LOG_DEBUG("network::reliable_sender")
            << "failed to connect to " << address.str() << "; retrying in "
            << backoff.count() << " ms";
        std::this_thread::sleep_for(backoff);
        backoff = std::min(backoff * 2, kMaxBackoff);
        continue;
      }
      backoff = kInitialBackoff;
      LOG_DEBUG("network::reliable_sender")
          << "Outgoing connection established with " << address.str();

      auto sock = std::make_shared<Socket>(std::move(*sock_opt));
      auto pending = std::make_shared<std::deque<Msg>>();
      auto pending_m = std::make_shared<std::mutex>();
      auto broken = std::make_shared<std::atomic<bool>>(false);

      // -- reader: match ACK frames to in-flight messages ----------------
      std::thread reader([sock, pending, pending_m, broken] {
        Bytes frame;
        while (sock->read_frame(&frame)) {
          std::lock_guard<std::mutex> lk(*pending_m);
          if (!pending->empty()) {
            pending->front().ack.set(std::move(frame));
            pending->pop_front();
          }
          frame.clear();
        }
        broken->store(true);
        sock->shutdown();
      });

      // -- retransmit backlog from the previous socket -------------------
      bool ok = true;
      while (ok && !retransmit.empty()) {
        Msg m = std::move(retransmit.front());
        retransmit.pop_front();
        auto data = m.data;
        {
          std::lock_guard<std::mutex> lk(*pending_m);
          pending->push_back(std::move(m));
        }
        ok = sock->write_frame(*data);
      }

      // -- writer loop ---------------------------------------------------
      while (ok && !broken->load()) {
        Msg m;
        auto status = queue.recv_until(
            &m, std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(100));
        if (status == RecvStatus::kClosed) return;
        if (status == RecvStatus::kTimeout) continue;
        auto data = m.data;
        {
          std::lock_guard<std::mutex> lk(*pending_m);
          pending->push_back(std::move(m));
        }
        ok = sock->write_frame(*data);
      }

      // -- teardown: recover un-ACKed messages ---------------------------
      sock->shutdown();
      reader.join();
      {
        std::lock_guard<std::mutex> lk(*pending_m);
        for (auto& m : *pending) retransmit.push_back(std::move(m));
        pending->clear();
      }
      LOG_DEBUG("network::reliable_sender")
          << "connection to " << address.str() << " dropped; "
          << retransmit.size() << " message(s) to retransmit";
    }
  }

  Address address;
  Channel<Msg> queue;
};

ReliableSender::ReliableSender() = default;

std::shared_ptr<ReliableSender::Connection> ReliableSender::get_or_spawn(
    const Address& address) {
  auto it = connections_.find(address);
  if (it != connections_.end()) return it->second;
  auto conn = std::make_shared<Connection>(address);
  conn->start(conn);
  connections_[address] = conn;
  return conn;
}

CancelHandler ReliableSender::send(const Address& address, Bytes data) {
  return send_shared(address,
                     std::make_shared<const Bytes>(std::move(data)));
}

CancelHandler ReliableSender::send_shared(
    const Address& address, std::shared_ptr<const Bytes> data) {
  auto conn = get_or_spawn(address);
  Connection::Msg m;
  m.data = std::move(data);
  CancelHandler handler = m.ack;
  conn->queue.send(std::move(m));
  return handler;
}

std::vector<CancelHandler> ReliableSender::broadcast(
    const std::vector<Address>& addresses, const Bytes& data) {
  auto shared = std::make_shared<const Bytes>(data);
  std::vector<CancelHandler> handlers;
  handlers.reserve(addresses.size());
  for (const auto& a : addresses) handlers.push_back(send_shared(a, shared));
  return handlers;
}

}  // namespace hotstuff

#include "network/socket.hpp"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>

namespace hotstuff {

std::optional<Address> Address::parse(const std::string& s) {
  auto colon = s.rfind(':');
  if (colon == std::string::npos) return std::nullopt;
  Address a;
  a.host = s.substr(0, colon);
  try {
    int p = std::stoi(s.substr(colon + 1));
    if (p < 0 || p > 65535) return std::nullopt;
    a.port = static_cast<uint16_t>(p);
  } catch (const std::exception&) {
    return std::nullopt;
  }
  if (a.host == "localhost") a.host = "127.0.0.1";
  return a;
}

namespace {

bool fill_sockaddr(const Address& addr, sockaddr_in* sa) {
  std::memset(sa, 0, sizeof(*sa));
  sa->sin_family = AF_INET;
  sa->sin_port = htons(addr.port);
  return inet_pton(AF_INET, addr.host.c_str(), &sa->sin_addr) == 1;
}

void set_common_opts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

}  // namespace

Socket& Socket::operator=(Socket&& o) noexcept {
  if (this != &o) {
    close();
    fd_ = o.fd_;
    o.fd_ = -1;
  }
  return *this;
}

std::optional<Socket> Socket::connect(const Address& addr) {
  sockaddr_in sa;
  if (!fill_sockaddr(addr, &sa)) return std::nullopt;
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return std::nullopt;
  if (::connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0) {
    ::close(fd);
    return std::nullopt;
  }
  set_common_opts(fd);
  return Socket(fd);
}

std::optional<Socket> Socket::connect(const Address& addr, int timeout_ms) {
  sockaddr_in sa;
  if (!fill_sockaddr(addr, &sa)) return std::nullopt;
  int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return std::nullopt;
  int rc = ::connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa));
  if (rc != 0) {
    if (errno != EINPROGRESS) {
      ::close(fd);
      return std::nullopt;
    }
    pollfd pfd{fd, POLLOUT, 0};
    if (::poll(&pfd, 1, timeout_ms) != 1) {
      ::close(fd);
      return std::nullopt;
    }
    int err = 0;
    socklen_t len = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 || err != 0) {
      ::close(fd);
      return std::nullopt;
    }
  }
  // Back to blocking mode; per-read deadlines come from set_recv_timeout.
  int flags = fcntl(fd, F_GETFL, 0);
  if (flags < 0 || fcntl(fd, F_SETFL, flags & ~O_NONBLOCK) != 0) {
    ::close(fd);
    return std::nullopt;
  }
  set_common_opts(fd);
  return Socket(fd);
}

bool Socket::set_recv_timeout(int timeout_ms) {
  timeval tv;
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  return setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv)) == 0;
}

void Socket::close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Socket::shutdown() {
  if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
}

bool Socket::read_exact(uint8_t* buf, size_t len) {
  size_t got = 0;
  while (got < len) {
    ssize_t n = ::recv(fd_, buf + got, len - got, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    got += static_cast<size_t>(n);
  }
  return true;
}

bool Socket::write_all(const uint8_t* buf, size_t len) {
  size_t sent = 0;
  while (sent < len) {
    ssize_t n = ::send(fd_, buf + sent, len - sent, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    sent += static_cast<size_t>(n);
  }
  return true;
}

bool Socket::write_frame(const uint8_t* data, size_t len) {
  uint8_t hdr[4] = {static_cast<uint8_t>(len >> 24),
                    static_cast<uint8_t>(len >> 16),
                    static_cast<uint8_t>(len >> 8),
                    static_cast<uint8_t>(len)};
  // Single writev-style send: header + payload back to back. Two sends are
  // fine under TCP_NODELAY for large frames; coalesce small ones.
  if (len <= 8192) {
    Bytes buf;
    buf.reserve(4 + len);
    buf.insert(buf.end(), hdr, hdr + 4);
    buf.insert(buf.end(), data, data + len);
    return write_all(buf.data(), buf.size());
  }
  return write_all(hdr, 4) && write_all(data, len);
}

bool Socket::write_frame(const Bytes& payload) {
  return write_frame(payload.data(), payload.size());
}

bool Socket::read_frame(Bytes* out, size_t max_len) {
  uint8_t hdr[4];
  if (!read_exact(hdr, 4)) return false;
  size_t len = (size_t(hdr[0]) << 24) | (size_t(hdr[1]) << 16) |
               (size_t(hdr[2]) << 8) | size_t(hdr[3]);
  if (len > max_len) return false;
  out->resize(len);
  return read_exact(out->data(), len);
}

std::optional<Listener> Listener::bind(const Address& addr) {
  sockaddr_in sa;
  if (!fill_sockaddr(addr, &sa)) return std::nullopt;
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return std::nullopt;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0 ||
      ::listen(fd, 1024) != 0) {
    ::close(fd);
    return std::nullopt;
  }
  Listener l;
  l.fd_ = fd;
  sockaddr_in bound;
  socklen_t blen = sizeof(bound);
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen) == 0) {
    l.port_ = ntohs(bound.sin_port);
  }
  return l;
}

std::optional<Socket> Listener::accept() {
  int fd = ::accept(fd_, nullptr, nullptr);
  if (fd < 0) return std::nullopt;
  set_common_opts(fd);
  return Socket(fd);
}

void Listener::close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Listener::shutdown() {
  if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
}

}  // namespace hotstuff

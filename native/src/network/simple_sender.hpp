// Best-effort sender: one multiplexed connection per peer on the
// process-wide EventLoop, bounded per-peer backlog, incoming frames (ACKs)
// sunk on arrival; failed peers drop queued messages and reconnect lazily
// on the next send — matching the reference's SimpleSender/Connection
// semantics (network/src/simple_sender.rs:22-143) without its
// two-threads-per-peer cost.
#pragma once

#include <memory>
#include <random>
#include <vector>

#include "common/bytes.hpp"
#include "common/channel.hpp"
#include "network/socket.hpp"

namespace hotstuff {

class SimpleSender {
 public:
  SimpleSender();
  ~SimpleSender();
  SimpleSender(const SimpleSender&) = delete;
  SimpleSender& operator=(const SimpleSender&) = delete;

  void send(const Address& address, Bytes data);
  void broadcast(const std::vector<Address>& addresses, const Bytes& data);
  // Random subset of `nodes` addresses (mempool sync retries,
  // mempool/src/synchronizer.rs:196-204 analogue).
  void lucky_broadcast(std::vector<Address> addresses, const Bytes& data,
                       size_t nodes);

 private:
  struct State;

  std::mt19937 rng_;
  std::shared_ptr<State> state_;
};

}  // namespace hotstuff

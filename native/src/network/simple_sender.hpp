// Best-effort sender: one connection task per peer fed by a bounded queue,
// incoming frames (ACKs) sunk by a reader thread; failed peers drop queued
// messages and reconnect lazily on the next send — matching the reference's
// SimpleSender/Connection semantics (network/src/simple_sender.rs:22-143).
// All connection threads are joinable: the destructor closes every queue,
// shuts the sockets, and joins, so a SimpleSender never leaks a thread past
// its owner (tokio gives the reference this for free on runtime drop).
#pragma once

#include <memory>
#include <random>
#include <unordered_map>
#include <vector>

#include "common/bytes.hpp"
#include "common/channel.hpp"
#include "network/socket.hpp"

namespace hotstuff {

class SimpleSender {
 public:
  SimpleSender();
  ~SimpleSender();
  SimpleSender(const SimpleSender&) = delete;
  SimpleSender& operator=(const SimpleSender&) = delete;

  void send(const Address& address, Bytes data);
  void broadcast(const std::vector<Address>& addresses, const Bytes& data);
  // Random subset of `nodes` addresses (mempool sync retries,
  // mempool/src/synchronizer.rs:196-204 analogue).
  void lucky_broadcast(std::vector<Address> addresses, const Bytes& data,
                       size_t nodes);

 private:
  struct Connection;
  std::shared_ptr<Connection> get_or_spawn(const Address& address);

  std::unordered_map<Address, std::shared_ptr<Connection>, AddressHash>
      connections_;
  std::mt19937 rng_;
};

}  // namespace hotstuff

// Binary serialization for wire messages: little-endian fixed ints, u64
// length-prefixed sequences, u32 enum tags, u8 option flags — the same data
// model the reference gets from bincode (consensus/src/core.rs:222 etc.),
// reimplemented as explicit Writer/Reader so the C++ node controls its own
// wire format.
#pragma once

#include <cstdint>
#include <optional>
#include <stdexcept>

#include "common/bytes.hpp"

namespace hotstuff {

struct SerdeError : std::runtime_error {
  explicit SerdeError(const std::string& what) : std::runtime_error(what) {}
};

class Writer {
 public:
  Bytes out;

  void u8(uint8_t v) { out.push_back(v); }

  void u32(uint32_t v) {
    for (int i = 0; i < 4; i++) out.push_back((v >> (8 * i)) & 0xFF);
  }

  void u64(uint64_t v) {
    for (int i = 0; i < 8; i++) out.push_back((v >> (8 * i)) & 0xFF);
  }

  void raw(const uint8_t* data, size_t len) {
    out.insert(out.end(), data, data + len);
  }

  template <size_t N>
  void fixed(const std::array<uint8_t, N>& a) {
    raw(a.data(), N);
  }

  void bytes(const Bytes& b) {
    u64(b.size());
    raw(b.data(), b.size());
  }

  void tag(uint32_t variant) { u32(variant); }
};

class Reader {
 public:
  Reader(const uint8_t* data, size_t len) : data_(data), len_(len) {}
  explicit Reader(const Bytes& b) : Reader(b.data(), b.size()) {}

  uint8_t u8() {
    need(1);
    return data_[pos_++];
  }

  uint32_t u32() {
    need(4);
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) v |= uint32_t(data_[pos_++]) << (8 * i);
    return v;
  }

  uint64_t u64() {
    need(8);
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v |= uint64_t(data_[pos_++]) << (8 * i);
    return v;
  }

  template <size_t N>
  void fixed(std::array<uint8_t, N>* a) {
    need(N);
    std::memcpy(a->data(), data_ + pos_, N);
    pos_ += N;
  }

  Bytes bytes() {
    uint64_t n = u64();
    need(n);
    Bytes b(data_ + pos_, data_ + pos_ + n);
    pos_ += n;
    return b;
  }

  // Sequence length guarded by the minimum wire size of one element, so a
  // hostile length prefix can't amplify into a huge reserve/allocation.
  uint64_t seq_len(size_t min_element_bytes = 1) {
    uint64_t n = u64();
    if (min_element_bytes == 0) min_element_bytes = 1;
    if (n > remaining() / min_element_bytes) {
      throw SerdeError("sequence length exceeds buffer");
    }
    return n;
  }

  uint32_t tag() { return u32(); }

  bool done() const { return pos_ == len_; }
  size_t remaining() const { return len_ - pos_; }

 private:
  void need(size_t n) {
    if (len_ - pos_ < n) throw SerdeError("unexpected end of buffer");
  }

  const uint8_t* data_;
  size_t len_;
  size_t pos_ = 0;
};

}  // namespace hotstuff

// Actor plumbing: bounded MPSC channel + oneshot, the C++ equivalents of the
// tokio primitives that carry all inter-component traffic in the reference
// (bounded mpsc of capacity 1000, consensus/src/consensus.rs:27; oneshot
// CancelHandler, network/src/reliable_sender.rs:25).  Oneshot additionally
// supports on_ready callbacks, which is how quorum waiting and notify_read
// obligations compose without a thread per pending future.
#pragma once

#include <chrono>
#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <optional>

namespace hotstuff {

inline constexpr size_t kChannelCapacity = 1000;

enum class RecvStatus { kOk, kTimeout, kClosed };

template <typename T>
class Channel {
 public:
  explicit Channel(size_t capacity = kChannelCapacity)
      : capacity_(capacity) {}

  // Blocks while full. Returns false if the channel is closed.
  bool send(T value) {
    std::unique_lock<std::mutex> lk(m_);
    cv_send_.wait(lk, [&] { return q_.size() < capacity_ || closed_; });
    if (closed_) return false;
    q_.push_back(std::move(value));
    cv_recv_.notify_one();
    return true;
  }

  bool try_send(T value) {
    std::lock_guard<std::mutex> lk(m_);
    if (closed_ || q_.size() >= capacity_) return false;
    q_.push_back(std::move(value));
    cv_recv_.notify_one();
    return true;
  }

  // Bounded send: blocks until capacity frees, the deadline passes, or the
  // channel closes. On kTimeout the value is NOT consumed (still valid in
  // *value) so callers can retry or cancel — the escape hatch that lets a
  // producer observe a stop signal instead of wedging on a full queue.
  RecvStatus send_until(T* value,
                        std::chrono::steady_clock::time_point deadline) {
    std::unique_lock<std::mutex> lk(m_);
    if (!cv_send_.wait_until(lk, deadline, [&] {
          return q_.size() < capacity_ || closed_;
        })) {
      return RecvStatus::kTimeout;
    }
    if (closed_) return RecvStatus::kClosed;
    q_.push_back(std::move(*value));
    cv_recv_.notify_one();
    return RecvStatus::kOk;
  }

  bool try_recv(T* out) {
    std::lock_guard<std::mutex> lk(m_);
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    cv_send_.notify_one();
    return true;
  }

  // Blocks while empty. nullopt once closed and drained.
  std::optional<T> recv() {
    std::unique_lock<std::mutex> lk(m_);
    cv_recv_.wait(lk, [&] { return !q_.empty() || closed_; });
    return pop_locked();
  }

  RecvStatus recv_until(T* out, std::chrono::steady_clock::time_point deadline) {
    std::unique_lock<std::mutex> lk(m_);
    if (!cv_recv_.wait_until(lk, deadline,
                             [&] { return !q_.empty() || closed_; })) {
      return RecvStatus::kTimeout;
    }
    auto v = pop_locked();
    if (!v) return RecvStatus::kClosed;
    *out = std::move(*v);
    return RecvStatus::kOk;
  }

  void close() {
    std::lock_guard<std::mutex> lk(m_);
    closed_ = true;
    cv_recv_.notify_all();
    cv_send_.notify_all();
  }

 private:
  std::optional<T> pop_locked() {
    if (q_.empty()) return std::nullopt;  // closed
    T v = std::move(q_.front());
    q_.pop_front();
    cv_send_.notify_one();
    return v;
  }

  std::mutex m_;
  std::condition_variable cv_recv_, cv_send_;
  std::deque<T> q_;
  size_t capacity_;
  bool closed_ = false;
};

// Clonable handle pair around a shared channel (actors hold SenderHandle
// copies the way reference components clone tokio Senders).
template <typename T>
using ChannelPtr = std::shared_ptr<Channel<T>>;

template <typename T>
ChannelPtr<T> make_channel(size_t capacity = kChannelCapacity) {
  return std::make_shared<Channel<T>>(capacity);
}

// ---------------------------------------------------------------------------
// Oneshot: single value, many-waiter, optional callback on fulfilment.
// ---------------------------------------------------------------------------

template <typename T>
class Oneshot {
 public:
  Oneshot() : s_(std::make_shared<State>()) {}

  void set(T value) const {
    std::function<void(const T&)> cb;
    {
      std::lock_guard<std::mutex> lk(s_->m);
      if (s_->value) return;  // first write wins
      s_->value = std::move(value);
      cb = std::move(s_->cb);
      s_->cb = nullptr;
      s_->cv.notify_all();
    }
    if (cb) cb(*value_ref());
  }

  // Blocks until set. (No cancellation path: senders in this codebase always
  // fulfil or the process is going down.)
  const T& wait() const {
    std::unique_lock<std::mutex> lk(s_->m);
    s_->cv.wait(lk, [&] { return s_->value.has_value(); });
    return *s_->value;
  }

  bool wait_for(std::chrono::milliseconds timeout) const {
    std::unique_lock<std::mutex> lk(s_->m);
    return s_->cv.wait_for(lk, timeout,
                           [&] { return s_->value.has_value(); });
  }

  bool ready() const {
    std::lock_guard<std::mutex> lk(s_->m);
    return s_->value.has_value();
  }

  // Runs f(value) when set; immediately if already set. At most one callback.
  // Callbacks execute on the setter's thread — keep them tiny (channel push,
  // counter decrement).
  void on_ready(std::function<void(const T&)> f) const {
    {
      std::lock_guard<std::mutex> lk(s_->m);
      if (!s_->value) {
        s_->cb = std::move(f);
        return;
      }
    }
    f(*s_->value);
  }

 private:
  struct State {
    std::mutex m;
    std::condition_variable cv;
    std::optional<T> value;
    std::function<void(const T&)> cb;
  };

  const T* value_ref() const { return &*s_->value; }

  std::shared_ptr<State> s_;
};

}  // namespace hotstuff

#include "common/json.hpp"

#include <cctype>
#include <cmath>
#include <cstdio>
#include <fstream>
#include <sstream>

namespace hotstuff {

const Json& Json::at(const std::string& key) const {
  const Json* j = find(key);
  if (!j) throw JsonError("missing key: " + key);
  return *j;
}

const Json* Json::find(const std::string& key) const {
  expect(Type::kObject);
  for (const auto& [k, v] : obj_) {
    if (k == key) return &v;
  }
  return nullptr;
}

void Json::set(const std::string& key, Json value) {
  expect(Type::kObject);
  for (auto& [k, v] : obj_) {
    if (k == key) {
      v = std::move(value);
      return;
    }
  }
  obj_.emplace_back(key, std::move(value));
}

namespace {

class Parser {
 public:
  explicit Parser(const std::string& text) : s_(text) {}

  Json parse() {
    Json j = value();
    skip_ws();
    if (pos_ != s_.size()) throw JsonError("trailing characters");
    return j;
  }

 private:
  Json value() {
    skip_ws();
    if (pos_ >= s_.size()) throw JsonError("unexpected end");
    char c = s_[pos_];
    switch (c) {
      case '{': return object();
      case '[': return array();
      case '"': return Json(string());
      case 't': literal("true"); return Json(true);
      case 'f': literal("false"); return Json(false);
      case 'n': literal("null"); return Json();
      default: return number();
    }
  }

  Json object() {
    Json j = Json::object();
    pos_++;  // {
    skip_ws();
    if (peek() == '}') { pos_++; return j; }
    while (true) {
      skip_ws();
      std::string key = string();
      skip_ws();
      require(':');
      j.set(key, value());
      skip_ws();
      char c = next();
      if (c == '}') return j;
      if (c != ',') throw JsonError("expected , or }");
    }
  }

  Json array() {
    Json j = Json::array();
    pos_++;  // [
    skip_ws();
    if (peek() == ']') { pos_++; return j; }
    while (true) {
      j.push_back(value());
      skip_ws();
      char c = next();
      if (c == ']') return j;
      if (c != ',') throw JsonError("expected , or ]");
    }
  }

  std::string string() {
    require('"');
    std::string out;
    while (true) {
      if (pos_ >= s_.size()) throw JsonError("unterminated string");
      char c = s_[pos_++];
      if (c == '"') return out;
      if (c == '\\') {
        char e = next();
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            unsigned code = 0;
            for (int i = 0; i < 4; i++) {
              char h = next();
              code <<= 4;
              if (h >= '0' && h <= '9') code |= h - '0';
              else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
              else throw JsonError("bad \\u escape");
            }
            // UTF-8 encode (BMP only — config files are ASCII in practice)
            if (code < 0x80) {
              out += char(code);
            } else if (code < 0x800) {
              out += char(0xC0 | (code >> 6));
              out += char(0x80 | (code & 0x3F));
            } else {
              out += char(0xE0 | (code >> 12));
              out += char(0x80 | ((code >> 6) & 0x3F));
              out += char(0x80 | (code & 0x3F));
            }
            break;
          }
          default: throw JsonError("bad escape");
        }
      } else {
        out += c;
      }
    }
  }

  Json number() {
    size_t start = pos_;
    if (peek() == '-') pos_++;
    while (pos_ < s_.size() &&
           (std::isdigit(static_cast<unsigned char>(s_[pos_])) ||
            s_[pos_] == '.' || s_[pos_] == 'e' || s_[pos_] == 'E' ||
            s_[pos_] == '+' || s_[pos_] == '-')) {
      pos_++;
    }
    try {
      return Json(std::stod(s_.substr(start, pos_ - start)));
    } catch (const std::exception&) {
      throw JsonError("bad number");
    }
  }

  void literal(const char* lit) {
    for (const char* p = lit; *p; ++p) {
      if (pos_ >= s_.size() || s_[pos_++] != *p) throw JsonError("bad literal");
    }
  }

  void skip_ws() {
    while (pos_ < s_.size() &&
           std::isspace(static_cast<unsigned char>(s_[pos_]))) {
      pos_++;
    }
  }

  char peek() {
    if (pos_ >= s_.size()) throw JsonError("unexpected end");
    return s_[pos_];
  }

  char next() {
    if (pos_ >= s_.size()) throw JsonError("unexpected end");
    return s_[pos_++];
  }

  void require(char c) {
    if (next() != c) throw JsonError(std::string("expected ") + c);
  }

  const std::string& s_;
  size_t pos_ = 0;
};

void escape_string(const std::string& in, std::string* out) {
  out->push_back('"');
  for (char c : in) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(c);
        }
    }
  }
  out->push_back('"');
}

void format_number(double n, std::string* out) {
  if (n == std::floor(n) && std::fabs(n) < 9.2e18) {
    char buf[32];
    std::snprintf(buf, sizeof(buf), "%lld", static_cast<long long>(n));
    *out += buf;
  } else {
    char buf[32];
    std::snprintf(buf, sizeof(buf), "%.17g", n);
    *out += buf;
  }
}

}  // namespace

void Json::dump_to(std::string* out, int indent, int depth) const {
  auto newline = [&](int d) {
    if (indent > 0) {
      out->push_back('\n');
      out->append(size_t(indent) * d, ' ');
    }
  };
  switch (type_) {
    case Type::kNull: *out += "null"; break;
    case Type::kBool: *out += bool_ ? "true" : "false"; break;
    case Type::kNumber: format_number(num_, out); break;
    case Type::kString: escape_string(str_, out); break;
    case Type::kArray: {
      out->push_back('[');
      bool first = true;
      for (const auto& v : arr_) {
        if (!first) out->push_back(',');
        first = false;
        newline(depth + 1);
        v.dump_to(out, indent, depth + 1);
      }
      if (!arr_.empty()) newline(depth);
      out->push_back(']');
      break;
    }
    case Type::kObject: {
      out->push_back('{');
      bool first = true;
      for (const auto& [k, v] : obj_) {
        if (!first) out->push_back(',');
        first = false;
        newline(depth + 1);
        escape_string(k, out);
        out->push_back(':');
        if (indent > 0) out->push_back(' ');
        v.dump_to(out, indent, depth + 1);
      }
      if (!obj_.empty()) newline(depth);
      out->push_back('}');
      break;
    }
  }
}

std::string Json::dump(int indent) const {
  std::string out;
  dump_to(&out, indent, 0);
  return out;
}

Json Json::parse(const std::string& text) { return Parser(text).parse(); }

Json Json::read_file(const std::string& path) {
  std::ifstream f(path);
  if (!f) throw JsonError("cannot open " + path);
  std::stringstream ss;
  ss << f.rdbuf();
  return parse(ss.str());
}

void Json::write_file(const std::string& path) const {
  std::ofstream f(path);
  if (!f) throw JsonError("cannot write " + path);
  f << dump(2) << "\n";
}

}  // namespace hotstuff

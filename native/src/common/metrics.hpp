// graftscope node-side metrics: 1 Hz machine-parseable METRICS lines.
//
// The sidecar has had a live OP_STATS time series since grafttrace; the
// C++ node had nothing — a straggling replica, a paused ingress, or a
// breaker stuck open was invisible until the post-run log mining.  This
// sampler emits one line per second into the node's own log, in the
// frozen log grammar, so hotstuff_tpu/obs/sampler.py can read the node
// side NEXT TO the sidecar series in logs/metrics.jsonl:
//
//   [<ts>Z INFO node::metrics] METRICS commits=<u64> commit_rate=<f.1>
//       ingress_tx=<u64> ingress_bytes=<u64> busy=<u64>
//       breaker=<closed|open|half_open|none>
//
// The line grammar is FROZEN (mined by obs/sampler.py; graftlint's
// obsgrammar checker cross-checks the two sides) — extend by appending
// key=value fields only.
//
// Cost discipline (the trace_stage contract): everything here is behind
// the parameters-file `trace` flag.  The one hot-path instrumentation
// site, note_commit(), pays exactly one relaxed atomic load when
// tracing is off (log_trace_enabled()) and one relaxed fetch_add when
// on; gauges (ingress fill, breaker state) are read by the 1 Hz sampler
// thread only, never on a hot path.
//
// Process scope: one singleton per process, like TpuVerifier — the
// harness runs one node per process.  In-process multi-node tests
// (test_e2e) share the counter; the sampler is only started by
// Node::create under the trace flag, which those tests leave off.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <memory>
#include <mutex>
#include <thread>

namespace hotstuff {

namespace mempool {
class IngressGate;
class TxVerifier;
}  // namespace mempool

class NodeMetrics {
 public:
  static NodeMetrics& instance();

  // Consensus core thread, once per committed block.  One relaxed load
  // when tracing is off; one relaxed add when on (same discipline as
  // trace_stage in consensus/core.cpp).
  void note_commit();
  uint64_t commits() const {
    return commits_.load(std::memory_order_relaxed);
  }

  // Mempool boot registers its ingress gate so the sampler can report
  // fill + BUSY sheds; weak so the gate's lifetime stays the mempool's.
  void set_ingress_gate(std::weak_ptr<const mempool::IngressGate> gate);

  // graftingress: the admission-verify stage registers itself the same
  // way so the sampler can report verified/forged totals + queue depth
  // (absent — legacy unsigned ingress — the gauges stay zero and the
  // METRICS suffix still emits, keeping the grammar unconditional).
  void set_tx_verifier(std::weak_ptr<const mempool::TxVerifier> verifier);

  // Start/stop the 1 Hz sampler thread (Node::create under the `trace`
  // parameter; idempotent — a second start is a no-op).
  void start(uint64_t interval_ms = 1000);
  void stop();

  // One METRICS line from the current counters (the sampler's tick body,
  // exposed for tests); `dt_s` scales the commit-rate delta.
  void emit_sample(double dt_s);

 private:
  NodeMetrics() = default;

  std::atomic<uint64_t> commits_{0};

  std::mutex m_;
  std::condition_variable cv_;  // SHARED_OK(waited on under m_)
  std::weak_ptr<const mempool::IngressGate> gate_;  // GUARDED_BY(m_)
  std::weak_ptr<const mempool::TxVerifier> tx_verifier_;  // GUARDED_BY(m_)
  bool running_ = false;                            // GUARDED_BY(m_)
  bool stopping_ = false;                           // GUARDED_BY(m_)
  std::thread thread_;                              // GUARDED_BY(m_)
  uint64_t last_commits_ = 0;  // OWNED_BY(sampler thread)
};

}  // namespace hotstuff

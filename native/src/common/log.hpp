// Logger with the exact line grammar the benchmark harness mines
// (SURVEY.md section 5.1): "[<RFC3339 ms>Z <LEVEL> <module>] <message>".
// The reference gets this from env_logger under the benchmark feature
// (node/src/main.rs:43-53); the TPS/latency parser regexes over it, so the
// format is frozen — see hotstuff_tpu/harness/logs.py.
#pragma once

#include <sstream>
#include <string>

namespace hotstuff {

enum class LogLevel { kError = 1, kWarn, kInfo, kDebug };

// Global verbosity (default Info). Thread-safe writes to the sink.
void log_set_level(LogLevel level);
LogLevel log_level();

// grafttrace span emission (default off; the parameters-file "trace"
// flag turns it on).  Disabled cost is one relaxed atomic load per
// instrumented site — the hot path pays nothing measurable, and the
// TRACE line grammar ("TRACE stage=<s> block=<digest> round=<r>") is
// mined by hotstuff_tpu/obs/trace.py, so it is frozen like the rest of
// the log grammar.
void log_set_trace(bool on);
bool log_trace_enabled();

// Sink is stderr by default (the harness redirects per-process to
// logs/node-i.log, matching benchmark/local.py:25-28).
void log_write(LogLevel level, const std::string& module,
               const std::string& message);

// Label the calling thread (<= 15 chars) so per-subsystem CPU can be
// attributed from /proc/<pid>/task/*/stat at benchmark scale.
void set_thread_name(const char* name);

struct LogLine {
  LogLevel level;
  std::string module;
  std::ostringstream os;

  LogLine(LogLevel l, std::string m) : level(l), module(std::move(m)) {}
  ~LogLine() { log_write(level, module, os.str()); }
};

}  // namespace hotstuff

#define HS_LOG(lvl, module)                           \
  if (static_cast<int>(lvl) <= static_cast<int>(::hotstuff::log_level())) \
  ::hotstuff::LogLine(lvl, module).os

#define LOG_ERROR(module) HS_LOG(::hotstuff::LogLevel::kError, module)
#define LOG_WARN(module) HS_LOG(::hotstuff::LogLevel::kWarn, module)
#define LOG_INFO(module) HS_LOG(::hotstuff::LogLevel::kInfo, module)
#define LOG_DEBUG(module) HS_LOG(::hotstuff::LogLevel::kDebug, module)

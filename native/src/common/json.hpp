// Minimal JSON for config files only (committee / parameters / keys, the
// three files the harness generates — node/src/config.rs:22-87 in the
// reference). Objects preserve insertion order so round-trips are stable.
#pragma once

#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace hotstuff {

struct JsonError : std::runtime_error {
  explicit JsonError(const std::string& what) : std::runtime_error(what) {}
};

class Json {
 public:
  enum class Type { kNull, kBool, kNumber, kString, kArray, kObject };

  Json() : type_(Type::kNull) {}
  explicit Json(bool b) : type_(Type::kBool), bool_(b) {}
  explicit Json(double n) : type_(Type::kNumber), num_(n) {}
  explicit Json(int64_t n) : type_(Type::kNumber), num_(double(n)) {}
  explicit Json(std::string s) : type_(Type::kString), str_(std::move(s)) {}

  static Json array() {
    Json j;
    j.type_ = Type::kArray;
    return j;
  }
  static Json object() {
    Json j;
    j.type_ = Type::kObject;
    return j;
  }

  Type type() const { return type_; }
  bool is_object() const { return type_ == Type::kObject; }

  bool as_bool() const { expect(Type::kBool); return bool_; }
  double as_number() const { expect(Type::kNumber); return num_; }
  uint64_t as_u64() const { expect(Type::kNumber); return uint64_t(num_); }
  const std::string& as_string() const { expect(Type::kString); return str_; }
  const std::vector<Json>& items() const { expect(Type::kArray); return arr_; }

  // object access
  const Json& at(const std::string& key) const;
  const Json* find(const std::string& key) const;
  const std::vector<std::pair<std::string, Json>>& members() const {
    expect(Type::kObject);
    return obj_;
  }
  void set(const std::string& key, Json value);
  void push_back(Json value) { expect(Type::kArray); arr_.push_back(std::move(value)); }

  std::string dump(int indent = 0) const;

  static Json parse(const std::string& text);
  static Json read_file(const std::string& path);
  void write_file(const std::string& path) const;

 private:
  void expect(Type t) const {
    if (type_ != t) throw JsonError("wrong JSON type access");
  }
  void dump_to(std::string* out, int indent, int depth) const;

  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  std::vector<Json> arr_;
  std::vector<std::pair<std::string, Json>> obj_;
};

}  // namespace hotstuff

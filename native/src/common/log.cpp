#include "common/log.hpp"

#include <pthread.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <mutex>

namespace hotstuff {

namespace {
std::atomic<int> g_level{static_cast<int>(LogLevel::kInfo)};
std::atomic<bool> g_trace{false};
std::mutex g_sink_mutex;

const char* level_name(LogLevel l) {
  switch (l) {
    case LogLevel::kError: return "ERROR";
    case LogLevel::kWarn: return "WARN";
    case LogLevel::kInfo: return "INFO";
    case LogLevel::kDebug: return "DEBUG";
  }
  return "?";
}
}  // namespace

void log_set_level(LogLevel level) {
  g_level.store(static_cast<int>(level), std::memory_order_relaxed);
}

LogLevel log_level() {
  return static_cast<LogLevel>(g_level.load(std::memory_order_relaxed));
}

void log_set_trace(bool on) { g_trace.store(on, std::memory_order_relaxed); }

bool log_trace_enabled() {
  return g_trace.load(std::memory_order_relaxed);
}

void log_write(LogLevel level, const std::string& module,
               const std::string& message) {
  using namespace std::chrono;
  auto now = system_clock::now();
  auto ms = duration_cast<milliseconds>(now.time_since_epoch()) % 1000;
  std::time_t t = system_clock::to_time_t(now);
  std::tm tm{};
  gmtime_r(&t, &tm);
  char ts[40];
  std::snprintf(ts, sizeof(ts), "%04d-%02d-%02dT%02d:%02d:%02d.%03dZ",
                tm.tm_year + 1900, tm.tm_mon + 1, tm.tm_mday, tm.tm_hour,
                tm.tm_min, tm.tm_sec, static_cast<int>(ms.count()));
  std::lock_guard<std::mutex> lk(g_sink_mutex);
  std::fprintf(stderr, "[%s %s %s] %s\n", ts, level_name(level),
               module.c_str(), message.c_str());
  std::fflush(stderr);
}

void set_thread_name(const char* name) {
  pthread_setname_np(pthread_self(), name);
}

}  // namespace hotstuff

#include "common/metrics.hpp"

#include <chrono>
#include <cstdio>

#include "common/log.hpp"
#include "crypto/sidecar_client.hpp"
#include "mempool/ingress.hpp"
#include "mempool/tx_verify.hpp"

namespace hotstuff {

NodeMetrics& NodeMetrics::instance() {
  static NodeMetrics g;
  return g;
}

void NodeMetrics::note_commit() {
  if (!log_trace_enabled()) return;
  commits_.fetch_add(1, std::memory_order_relaxed);
}

void NodeMetrics::set_ingress_gate(
    std::weak_ptr<const mempool::IngressGate> gate) {
  std::lock_guard<std::mutex> lk(m_);
  gate_ = std::move(gate);
}

void NodeMetrics::set_tx_verifier(
    std::weak_ptr<const mempool::TxVerifier> verifier) {
  std::lock_guard<std::mutex> lk(m_);
  tx_verifier_ = std::move(verifier);
}

namespace {
const char* breaker_name(TpuVerifier* tpu) {
  if (tpu == nullptr) return "none";
  switch (tpu->breaker_state()) {
    case TpuVerifier::BreakerState::kOpen:
      return "open";
    case TpuVerifier::BreakerState::kHalfOpen:
      return "half_open";
    case TpuVerifier::BreakerState::kClosed:
    default:
      return "closed";
  }
}
}  // namespace

void NodeMetrics::emit_sample(double dt_s) {
  uint64_t commits = commits_.load(std::memory_order_relaxed);
  uint64_t delta = commits - last_commits_;
  last_commits_ = commits;
  double rate = dt_s > 0 ? double(delta) / dt_s : 0.0;
  // Fixed one-decimal rate: the python miner's grammar expects a plain
  // [0-9.]+ token, never scientific notation.
  char rate_buf[32];
  std::snprintf(rate_buf, sizeof(rate_buf), "%.1f", rate);
  uint64_t ingress_tx = 0;
  uint64_t ingress_bytes = 0;
  uint64_t busy = 0;
  uint64_t verified = 0;
  uint64_t forged = 0;
  uint64_t vq = 0;
  {
    std::shared_ptr<const mempool::IngressGate> gate;
    std::shared_ptr<const mempool::TxVerifier> verifier;
    {
      std::lock_guard<std::mutex> lk(m_);
      gate = gate_.lock();
      verifier = tx_verifier_.lock();
    }
    if (gate) {
      ingress_tx = gate->queued_txs();
      ingress_bytes = gate->queued_bytes();
      busy = gate->sheds();
    }
    if (verifier) {
      verified = verifier->verified();
      forged = verifier->forged();
      vq = verifier->queue_depth();
    }
  }
  // FROZEN grammar (obs/sampler.py _NODE_METRICS_RE; graftlint
  // obsgrammar cross-checks): append-only.  The graftingress suffix
  // (verified/forged/vq) emits unconditionally — zeros on legacy
  // unsigned-ingress runs — so the grammar has exactly one shape.
  LOG_INFO("node::metrics")
      << "METRICS commits=" << commits << " commit_rate=" << rate_buf
      << " ingress_tx=" << ingress_tx << " ingress_bytes=" << ingress_bytes
      << " busy=" << busy << " breaker=" << breaker_name(
          TpuVerifier::instance())
      << " verified=" << verified << " forged=" << forged << " vq=" << vq;
}

void NodeMetrics::start(uint64_t interval_ms) {
  std::lock_guard<std::mutex> lk(m_);
  if (running_) return;
  running_ = true;
  stopping_ = false;
  last_commits_ = commits_.load(std::memory_order_relaxed);
  thread_ = std::thread([this, interval_ms] {
    set_thread_name("node-metrics");
    auto last = std::chrono::steady_clock::now();
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(m_);
        cv_.wait_for(lk, std::chrono::milliseconds(interval_ms),
                     [this] { return stopping_; });
        if (stopping_) return;
      }
      auto now = std::chrono::steady_clock::now();
      emit_sample(std::chrono::duration<double>(now - last).count());
      last = now;
    }
  });
}

void NodeMetrics::stop() {
  std::thread t;
  {
    std::lock_guard<std::mutex> lk(m_);
    if (!running_) return;
    stopping_ = true;
    running_ = false;
    t = std::move(thread_);
  }
  cv_.notify_all();
  if (t.joinable()) t.join();
}

}  // namespace hotstuff

// Byte-buffer primitives shared across the node: hex/base64 codecs and a
// hash functor so Bytes and fixed arrays key unordered containers.
// (Capability parity: the reference's Digest/keys serialize as base64 via
// serde, crypto/src/lib.rs:33-56.)
#pragma once

#include <array>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace hotstuff {

using Bytes = std::vector<uint8_t>;

inline Bytes to_bytes(const std::string& s) {
  return Bytes(s.begin(), s.end());
}

inline std::string to_string(const Bytes& b) {
  return std::string(b.begin(), b.end());
}

// ---------------------------------------------------------------------------
// base64 (standard alphabet, padded) — matches the reference's serde encoding
// ---------------------------------------------------------------------------

inline std::string base64_encode(const uint8_t* data, size_t len) {
  static const char tab[] =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  out.reserve((len + 2) / 3 * 4);
  size_t i = 0;
  for (; i + 3 <= len; i += 3) {
    uint32_t v = (data[i] << 16) | (data[i + 1] << 8) | data[i + 2];
    out.push_back(tab[(v >> 18) & 63]);
    out.push_back(tab[(v >> 12) & 63]);
    out.push_back(tab[(v >> 6) & 63]);
    out.push_back(tab[v & 63]);
  }
  if (i + 1 == len) {
    uint32_t v = data[i] << 16;
    out.push_back(tab[(v >> 18) & 63]);
    out.push_back(tab[(v >> 12) & 63]);
    out += "==";
  } else if (i + 2 == len) {
    uint32_t v = (data[i] << 16) | (data[i + 1] << 8);
    out.push_back(tab[(v >> 18) & 63]);
    out.push_back(tab[(v >> 12) & 63]);
    out.push_back(tab[(v >> 6) & 63]);
    out.push_back('=');
  }
  return out;
}

template <size_t N>
std::string base64_encode(const std::array<uint8_t, N>& a) {
  return base64_encode(a.data(), N);
}

inline std::string base64_encode(const Bytes& b) {
  return base64_encode(b.data(), b.size());
}

inline bool base64_decode(const std::string& in, Bytes* out) {
  auto val = [](char c) -> int {
    if (c >= 'A' && c <= 'Z') return c - 'A';
    if (c >= 'a' && c <= 'z') return c - 'a' + 26;
    if (c >= '0' && c <= '9') return c - '0' + 52;
    if (c == '+') return 62;
    if (c == '/') return 63;
    return -1;
  };
  out->clear();
  uint32_t acc = 0;
  int bits = 0;
  for (char c : in) {
    if (c == '=') break;
    int v = val(c);
    if (v < 0) return false;
    acc = (acc << 6) | static_cast<uint32_t>(v);
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out->push_back(static_cast<uint8_t>((acc >> bits) & 0xFF));
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// hashing for container keys
// ---------------------------------------------------------------------------

struct BytesHash {
  size_t operator()(const Bytes& b) const {
    // FNV-1a
    size_t h = 1469598103934665603ull;
    for (uint8_t c : b) {
      h ^= c;
      h *= 1099511628211ull;
    }
    return h;
  }
};

}  // namespace hotstuff

// graftsurge bounded ingress: the admission gate between the client-tx
// receiver and the BatchMaker pipeline.
//
// The tx receiver used to try_send into a fixed 1000-deep channel and
// silently drop the overflow — under a 3-5x offered overload the client
// learned nothing and kept flooding, and nothing bounded the BYTES
// buffered (1000 x 8 MiB frames is the frame cap, not a budget).  The
// gate enforces an explicit byte + tx budget and tells the client:
//
//   * backlog within budget      -> admit into the channel;
//   * backlog at budget          -> shed, reply "BUSY <retry_ms>" on the
//     tx connection (clients back off per-user with jittered
//     exponential retry — node/rate_pacer.hpp UserLoadModel);
//   * a client that ignores BUSY (pause_after_sheds consecutive sheds
//     with the backlog still at the high-water mark) -> PAUSE the tx
//     receiver entirely: the reactor stops reading, the kernel socket
//     buffers fill, and TCP flow control pushes back — the one
//     backpressure no client can ignore.  The BatchMaker side resumes
//     the receiver once it has drained the backlog to the low-water
//     mark (budget / low_water_div).
//
// Threading: admit() runs on the reactor thread (the tx receiver's
// on_frame callback — it must never block; the gate is a few counter
// updates under an uncontended mutex); on_consumed() runs on the
// BatchMaker thread, once per transaction drained.  The pause callback
// (NetworkReceiver::set_read_paused) posts to the event loop and is
// safe from either thread; it is invoked OUTSIDE the gate lock.
#pragma once

#include <algorithm>
#include <cstdint>
#include <functional>
#include <mutex>

#include "common/log.hpp"

namespace hotstuff {
namespace mempool {

class IngressGate {
 public:
  struct Config {
    size_t tx_budget = 20'000;          // txs buffered ahead of sealing
    size_t byte_budget = 16u << 20;     // bytes buffered (16 MiB)
    size_t low_water_div = 2;           // resume at budget / div
    size_t pause_after_sheds = 256;     // consecutive BUSYs before pause
    uint64_t max_batch_delay_ms = 100;  // scales the retry-after hint
  };
  using PauseFn = std::function<void(bool paused)>;

  IngressGate(Config cfg, PauseFn pause)
      : cfg_(cfg), pause_(std::move(pause)) {}

  // Reactor thread: admit one client tx of `tx_bytes` into the pipeline
  // (true), or shed it (false; *retry_ms carries the BUSY hint).
  // VERIFIES(ingress-budget)
  bool admit(size_t tx_bytes, uint32_t* retry_ms) {
    bool pause_now = false;
    bool admitted;
    size_t txs;
    size_t bytes;
    {
      std::lock_guard<std::mutex> lk(m_);
      admitted = txs_ < cfg_.tx_budget && bytes_ + tx_bytes <= cfg_.byte_budget;
      if (admitted) {
        txs_++;
        bytes_ += tx_bytes;
        consecutive_sheds_ = 0;
      } else {
        sheds_++;
        consecutive_sheds_++;
        if (retry_ms != nullptr) *retry_ms = retry_hint_locked_();
        if (!paused_ && consecutive_sheds_ >= cfg_.pause_after_sheds) {
          paused_ = true;
          pause_crossings_++;
          pause_now = true;
        }
      }
      txs = txs_;
      bytes = bytes_;
    }
    if (pause_now) {
      LOG_WARN("mempool::ingress")
          << "Ingress paused: " << txs << " txs / " << bytes
          << " B queued after " << cfg_.pause_after_sheds
          << " consecutive busy sheds (crossing " << pause_crossings()
          << "); resuming at " << cfg_.tx_budget / cfg_.low_water_div
          << " txs";
      if (pause_) pause_(true);
    }
    return admitted;
  }

  // BatchMaker thread: one tx drained from the channel.
  void on_consumed(size_t tx_bytes) {
    bool resume_now = false;
    size_t txs;
    {
      std::lock_guard<std::mutex> lk(m_);
      txs_ = txs_ > 0 ? txs_ - 1 : 0;
      bytes_ = bytes_ > tx_bytes ? bytes_ - tx_bytes : 0;
      if (paused_ && txs_ <= cfg_.tx_budget / cfg_.low_water_div &&
          bytes_ <= cfg_.byte_budget / cfg_.low_water_div) {
        paused_ = false;
        consecutive_sheds_ = 0;
        resume_now = true;
      }
      txs = txs_;
    }
    if (resume_now) {
      LOG_INFO("mempool::ingress")
          << "Ingress resumed at " << txs << " queued txs (low-water mark)";
      if (pause_) pause_(false);
    }
  }

  // -- telemetry (any thread) ----------------------------------------------

  size_t queued_txs() const {
    std::lock_guard<std::mutex> lk(m_);
    return txs_;
  }
  size_t queued_bytes() const {
    std::lock_guard<std::mutex> lk(m_);
    return bytes_;
  }
  uint64_t sheds() const {
    std::lock_guard<std::mutex> lk(m_);
    return sheds_;
  }
  uint64_t pause_crossings() const {
    std::lock_guard<std::mutex> lk(m_);
    return pause_crossings_;
  }
  bool paused() const {
    std::lock_guard<std::mutex> lk(m_);
    return paused_;
  }

 private:
  // Retry-after heuristic: one max_batch_delay is the sealing cadence
  // both sides already reason in; persistent shedding (a client that
  // keeps arriving hot) doubles the hint per pause_after_sheds/4 run of
  // consecutive sheds, capped so a blip never parks a client for more
  // than ~2 s.
  uint32_t retry_hint_locked_() const {
    uint64_t base = std::max<uint64_t>(50, 2 * cfg_.max_batch_delay_ms);
    size_t quarter = std::max<size_t>(1, cfg_.pause_after_sheds / 4);
    uint64_t doublings = std::min<uint64_t>(consecutive_sheds_ / quarter, 5);
    return uint32_t(std::min<uint64_t>(base << doublings, 2'000));
  }

  const Config cfg_;      // SHARED_OK(immutable after construction)
  const PauseFn pause_;   // SHARED_OK(immutable after construction;
                          // posts to the event loop, called unlocked)
  mutable std::mutex m_;
  size_t txs_ = 0;                  // GUARDED_BY(m_)
  size_t bytes_ = 0;                // GUARDED_BY(m_)
  size_t consecutive_sheds_ = 0;    // GUARDED_BY(m_)
  uint64_t sheds_ = 0;              // GUARDED_BY(m_)
  uint64_t pause_crossings_ = 0;    // GUARDED_BY(m_)
  bool paused_ = false;             // GUARDED_BY(m_)
};

}  // namespace mempool
}  // namespace hotstuff

// Mempool helper: serves BatchRequest messages by reading the requested
// batches from storage and sending them back to the requestor
// (mempool/src/helper.rs:14-68 in the reference).
#pragma once

#include "common/channel.hpp"
#include "mempool/config.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace mempool {

class Helper {
 public:
  static void spawn(
      Committee committee, Store store,
      ChannelPtr<std::pair<std::vector<Digest>, PublicKey>> rx_request);
};

}  // namespace mempool
}  // namespace hotstuff

// Mempool helper: serves BatchRequest messages by reading the requested
// batches from storage and sending them back to the requestor
// (mempool/src/helper.rs:14-68 in the reference).
#pragma once

#include <thread>

#include "common/channel.hpp"
#include "mempool/config.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace mempool {

class Helper {
 public:
  // Returns the actor thread; exits when rx_request is closed and drained.
  static std::thread spawn(
      Committee committee, Store store,
      ChannelPtr<std::pair<std::vector<Digest>, PublicKey>> rx_request);
};

}  // namespace mempool
}  // namespace hotstuff

// Processor: hashes each quorum-acked (or peer-received) serialized batch,
// persists it, and forwards the digest to consensus
// (mempool/src/processor.rs:16-39 in the reference).
#pragma once

#include "common/channel.hpp"
#include "crypto/crypto.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace mempool {

class Processor {
 public:
  static void spawn(Store store, ChannelPtr<Bytes> rx_batch,
                    ChannelPtr<Digest> tx_digest);
};

}  // namespace mempool
}  // namespace hotstuff

// Processor: hashes each quorum-acked (or peer-received) serialized batch,
// persists it, and forwards the digest to consensus
// (mempool/src/processor.rs:16-39 in the reference).
#pragma once

#include <thread>

#include "common/channel.hpp"
#include "crypto/crypto.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace mempool {

class Processor {
 public:
  // Returns the actor thread; exits when rx_batch is closed and drained.
  static std::thread spawn(Store store, ChannelPtr<Bytes> rx_batch,
                    ChannelPtr<Digest> tx_digest);
};

}  // namespace mempool
}  // namespace hotstuff

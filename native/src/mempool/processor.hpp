// Processor: hashes each quorum-acked (or peer-received) serialized batch,
// persists it, and forwards the digest to consensus
// (mempool/src/processor.rs:16-39 in the reference).
#pragma once

#include <thread>

#include "common/channel.hpp"
#include "crypto/crypto.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace mempool {

class Processor {
 public:
  // Returns the actor thread; exits when rx_batch is closed and drained.
  static std::thread spawn(Store store, ChannelPtr<Bytes> rx_batch,
                    ChannelPtr<Digest> tx_digest);

  // ONE source of truth for batch identity, shared by this actor and the
  // reactor-inlined peer path (mempool.cpp): the digest of the FULL
  // serialized message is both the store key and the payload handle
  // consensus carries in block payloads — if these ever diverged between
  // the own-batch and peer-batch paths, synchronizers would request
  // batches under keys peers never stored.
  // VERIFIES(batch-digest)
  static Digest digest_of(const Bytes& serialized_batch) {
    return sha512_digest(serialized_batch);
  }
};

}  // namespace mempool
}  // namespace hotstuff

// Processor: hashes each quorum-acked (or peer-received) serialized batch,
// persists it, and forwards the digest to consensus
// (mempool/src/processor.rs:16-39 in the reference).
#pragma once

#include <optional>
#include <thread>

#include "common/channel.hpp"
#include "crypto/crypto.hpp"
#include "mempool/messages.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace mempool {

// One batch headed for the store: our own quorum-acked batches (with the
// assembled availability certificate in dag mode), or a peer batch off
// the receiver's overflow lane.  `forward` false stores WITHOUT feeding
// the proposer — dag mode's peer batches, where only the producer
// proposes its own certified batch.
struct ProcessorMessage {
  Bytes batch;
  std::optional<BatchCertificate> cert;
  bool forward = true;
};

class Processor {
 public:
  // Returns the actor thread; exits when rx_batch is closed and drained.
  static std::thread spawn(Store store, ChannelPtr<ProcessorMessage> rx_batch,
                    ChannelPtr<PayloadRef> tx_digest);

  // ONE source of truth for batch identity, shared by this actor and the
  // reactor-inlined peer path (mempool.cpp): the digest of the FULL
  // serialized message is both the store key and the payload handle
  // consensus carries in block payloads — if these ever diverged between
  // the own-batch and peer-batch paths, synchronizers would request
  // batches under keys peers never stored.
  // VERIFIES(batch-digest)
  static Digest digest_of(const Bytes& serialized_batch) {
    return sha512_digest(serialized_batch);
  }
};

}  // namespace mempool
}  // namespace hotstuff

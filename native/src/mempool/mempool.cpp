#include "mempool/mempool.hpp"

#include "common/log.hpp"
#include "common/metrics.hpp"
#include "mempool/batch_maker.hpp"
#include "mempool/helper.hpp"
#include "mempool/processor.hpp"
#include "mempool/quorum_waiter.hpp"
#include "mempool/synchronizer.hpp"
#include "mempool/tx_frame.hpp"

namespace hotstuff {
namespace mempool {

std::unique_ptr<Mempool> Mempool::spawn(
    PublicKey name, SecretKey secret, Committee committee,
    Parameters parameters, Store store,
    ChannelPtr<ConsensusMempoolMessage> rx_consensus,
    ChannelPtr<PayloadRef> tx_consensus) {
  parameters.log();
  const bool dag = parameters.dag;

  auto mp = std::unique_ptr<Mempool>(new Mempool());

  // graftsurge: the tx channel is sized to the ingress budget so the
  // GATE, not the channel, is the admission authority (the +64 slack
  // absorbs the reactor-vs-consumer accounting race; the gate's budget
  // is what clients experience).
  auto tx_batch_maker =
      make_channel<Transaction>(parameters.ingress_tx_budget + 64);
  auto tx_quorum_waiter = make_channel<QuorumWaiterMessage>();
  auto tx_processor = make_channel<ProcessorMessage>();  // own acked batches
  auto tx_helper =
      make_channel<std::pair<std::vector<Digest>, PublicKey>>();

  // Everything the facade created gets a closer; stop() runs them all
  // before joining so no actor can stay blocked in a channel op.
  mp->closers_.push_back([tx_batch_maker] { tx_batch_maker->close(); });
  mp->closers_.push_back([tx_quorum_waiter] { tx_quorum_waiter->close(); });
  mp->closers_.push_back([tx_processor] { tx_processor->close(); });
  mp->closers_.push_back([tx_helper] { tx_helper->close(); });
  mp->closers_.push_back([rx_consensus] { rx_consensus->close(); });
  // tx_consensus is caller-owned but the peer-receiver's reactor BLOCKS
  // in send() on it (digest delivery must not drop); closing it here is
  // what guarantees stop() can always unwedge that send, even if a
  // caller wired the channel bounded.
  mp->closers_.push_back([tx_consensus] { tx_consensus->close(); });

  mp->threads_.push_back(
      Synchronizer::spawn(name, committee, store, parameters.gc_depth,
                          parameters.sync_retry_delay,
                          parameters.sync_retry_nodes, rx_consensus));

  // Client transaction ingress (:front), behind the graftsurge bounded
  // admission gate: within budget txs are admitted; at budget the
  // client gets an explicit "BUSY <retry_ms>" reply (it backs off
  // per-user); a client that ignores BUSY gets the receiver PAUSED —
  // kernel-buffer TCP backpressure — until the BatchMaker drains the
  // backlog to the low-water mark.  The pause callback posts to the
  // event loop, so calling it from either thread is safe; the receiver
  // member outlives every actor thread (stop() joins them first).
  IngressGate::Config gate_cfg;
  gate_cfg.tx_budget = parameters.ingress_tx_budget;
  gate_cfg.byte_budget = parameters.ingress_byte_budget;
  gate_cfg.max_batch_delay_ms = parameters.max_batch_delay;
  NetworkReceiver* tx_rx = &mp->tx_receiver_;
  mp->ingress_gate_ = std::make_shared<IngressGate>(
      gate_cfg, [tx_rx](bool paused) { tx_rx->set_read_paused(paused); });
  // graftscope: the node METRICS sampler reports ingress fill + BUSY
  // sheds from this gate (weak ref — the gate's lifetime stays ours).
  NodeMetrics::instance().set_ingress_gate(mp->ingress_gate_);
  auto gate = mp->ingress_gate_;
  // graftingress admission verify: between the gate and the BatchMaker,
  // admitted signed txs batch-verify through the sidecar bulk lane; the
  // legacy unsigned path stays wired when the knob is off (A/B).
  if (parameters.verify_ingress) {
    TxVerifier::Config vc;
    vc.batch = parameters.verify_batch;
    vc.max_delay_ms = parameters.verify_max_delay;
    vc.queue_budget = parameters.verify_queue_budget;
    mp->tx_verifier_ = TxVerifier::spawn(vc, tx_batch_maker,
                                         mp->ingress_gate_);
    NodeMetrics::instance().set_tx_verifier(mp->tx_verifier_);
  }
  auto verifier = mp->tx_verifier_;
  auto tx_address = committee.transactions_address(name);
  if (!tx_address) throw std::runtime_error("our key is not in the committee");
  if (!mp->tx_receiver_.spawn(
          *tx_address,
          [tx_batch_maker, gate, verifier](ConnectionWriter& writer,
                                           Bytes msg) {
            // Reactor-thread handler: parse + gate check + try_send only
            // (see peer handler) — never a blocking channel op.
            size_t tx_bytes = msg.size();
            if (verifier) {
              // Structural parse BEFORE any accounting: a malformed or
              // legacy-unsigned frame under verify-ingress is dropped
              // here (error, never a crash, never an admitted forgery —
              // a forged-but-well-formed frame parses cleanly and dies
              // at the verify stage instead).
              TxParse pr = parse_signed_tx(msg.data(), msg.size(), nullptr);
              if (pr != TxParse::kOk) {
                LOG_DEBUG("mempool::tx_verify")
                    << "dropping malformed client frame ("
                    << (pr == TxParse::kNotSigned ? "unsigned"
                        : pr == TxParse::kTruncated ? "truncated"
                                                    : "bad payload length")
                    << ", " << tx_bytes << " B)";
                return true;
              }
            }
            uint32_t retry_ms = 0;
            if (!gate->admit(tx_bytes, &retry_ms)) {
              writer.send("BUSY " + std::to_string(retry_ms));
              return true;
            }
            if (verifier) {
              // The writer copy is retained for the verify stage's shed
              // path (EventLoop::send is stale-id safe); the gate is
              // unwound by TxVerifier for any tx that never reaches the
              // BatchMaker.
              if (!verifier->enqueue(std::move(msg), writer, &retry_ms)) {
                gate->on_consumed(tx_bytes);
                writer.send("BUSY " +
                            std::to_string(retry_ms ? retry_ms : 100));
                LOG_DEBUG("mempool::tx_verify")
                    << "admission verify queue full; shedding transaction";
              }
              return true;
            }
            if (!tx_batch_maker->try_send(std::move(msg))) {
              // The slack between gate budget and channel capacity makes
              // this unreachable in practice; unwind the accounting and
              // tell the client anyway rather than silently dropping.
              gate->on_consumed(tx_bytes);
              writer.send("BUSY " + std::to_string(retry_ms ? retry_ms : 100));
              LOG_DEBUG("mempool::mempool")
                  << "batch maker overloaded; shedding transaction";
            }
            return true;
          },
          "mempool::tx_receiver")) {
    throw std::runtime_error("failed to bind " + tx_address->str());
  }
  LOG_INFO("mempool::mempool")
      << "Mempool listening to client transactions on " << tx_address->str();

  mp->threads_.push_back(
      BatchMaker::spawn(parameters.batch_size, parameters.max_batch_delay,
                        tx_batch_maker, tx_quorum_waiter,
                        committee.broadcast_addresses(name),
                        mp->stop_flag_, mp->ingress_gate_));

  mp->threads_.push_back(QuorumWaiter::spawn(committee, name, secret, dag,
                                             tx_quorum_waiter, tx_processor,
                                             mp->stop_flag_));

  // Our quorum-acked batches keep a processor thread (fed off-reactor by
  // the QuorumWaiter; mempool.rs:147-151).  The PEER-batch processor
  // (mempool.rs:185-189) is inlined into the receiver callback below:
  // at committee size N every sealed batch is processed N-1 times across
  // the host, and the extra channel hop per reception (enqueue + worker
  // wakeup) was a measured ~20% of the core at the 50..100-node scale
  // (scripts/PROFILE.md round-5b) for ~25 us of actual work (SHA-512 of
  // one batch).
  mp->threads_.push_back(Processor::spawn(store, tx_processor, tx_consensus));

  // Peer ingress (:mempool). ACK every message, then route by type
  // (mempool.rs:225-243).  graftdag: batches are acked with a SIGNED
  // kAck over the batch's ack digest — the availability vote the
  // producer's QuorumWaiter assembles into a BatchCertificate — and
  // their digests do NOT feed our proposer (only the producer proposes
  // its own certified batch).
  auto peer_address = committee.mempool_address(name);
  if (!mp->peer_receiver_.spawn(
          *peer_address,
          [store, tx_consensus, tx_processor, tx_helper, dag, name,
           secret](ConnectionWriter& writer, Bytes msg) mutable {
            // Reactor-thread handler: blocking channel sends would stall
            // the whole process's data plane; drop under overload (the
            // sender's ReliableSender retransmits un-ACKed batches, the
            // payload synchronizer re-fetches missing batches, and sync
            // requests are re-issued on a timer).
            if (!dag) writer.send(std::string("Ack"));
            try {
              MempoolMessage m = MempoolMessage::deserialize(msg);
              if (m.kind == MempoolMessage::Kind::kBatch) {
                // Inline peer-batch processing (store + digest to
                // consensus); ~25 us of SHA-512 on the reactor thread.
                Digest digest = Processor::digest_of(msg);
                bool accepted;
                if (store.try_write(digest.to_bytes(), &msg)) {
                  accepted = true;
                  if (!dag) {
                    // Once stored, the batch bytes are consumed and the
                    // sender saw an ACK — the digest MUST reach consensus
                    // or this node can never propose the batch.  The node
                    // wires this channel unbounded (node.cpp; refs are
                    // small), so this send never blocks there; a caller
                    // that mis-wires a bounded channel gets reactor
                    // backpressure instead of silent digest loss, and a
                    // false return means the channel closed at shutdown.
                    if (!tx_consensus->send(
                            PayloadRef{digest, std::nullopt})) {
                      LOG_WARN("mempool::mempool")
                          << "consensus digest channel closed; dropping "
                             "digest during shutdown";
                    }
                  }
                } else {
                  // Overflow lane: a stalled store worker (WAL compaction
                  // rewrites the whole log synchronously) must not cost
                  // every peer's batches for the stall duration — the
                  // processor actor absorbs up to a channel of them and
                  // BLOCKS in store.write off-reactor, the pre-inline
                  // behavior.  Only both-full drops (recovered via batch
                  // sync).  Dag mode stores WITHOUT forwarding: the
                  // producer, not us, proposes this batch.
                  ProcessorMessage overflow;
                  overflow.batch = std::move(msg);
                  overflow.forward = !dag;
                  accepted = tx_processor->try_send(std::move(overflow));
                  if (!accepted) {
                    LOG_WARN("mempool::mempool")
                        << "processor overloaded; dropping batch";
                  }
                }
                // graftdag: the availability vote is signed only for
                // bytes that are stored (or queued for the store
                // worker) — a signed ack over dropped bytes would let a
                // certificate form for a batch we cannot serve to
                // syncing peers.  Legacy mode already transport-acked
                // above, before the store.
                if (dag) {
                  if (accepted) {
                    writer.send(
                        MempoolMessage::make_ack(
                            digest, name,
                            Signature::sign_host(
                                BatchCertificate::ack_digest_of(digest),
                                secret))
                            .serialize());
                  } else {
                    // The sender's ReliableSender pairs replies to sends
                    // FIFO per connection, so even a dropped batch must
                    // be answered — a transport-only "Ack" that carries
                    // no availability vote (the QuorumWaiter skips it).
                    writer.send(std::string("Ack"));
                  }
                }
              } else if (m.kind == MempoolMessage::Kind::kBatchRequest) {
                if (dag) writer.send(std::string("Ack"));
                if (!tx_helper->try_send({std::move(m.missing), m.origin})) {
                  LOG_WARN("mempool::mempool")
                      << "helper overloaded; dropping sync request";
                }
              } else if (dag) {
                writer.send(std::string("Ack"));
              }
            } catch (const std::exception& e) {
              // Parse errors on peer bytes must not escape the connection
              // thread (std::terminate would take the node down).
              LOG_WARN("mempool::mempool")
                  << "Serialization failure: " << e.what();
            }
            return true;
          },
          "mempool::peer_receiver")) {
    throw std::runtime_error("failed to bind " + peer_address->str());
  }
  LOG_INFO("mempool::mempool")
      << "Mempool listening to mempool messages on " << peer_address->str();

  mp->threads_.push_back(Helper::spawn(committee, store, tx_helper));

  LOG_INFO("mempool::mempool")
      << "Mempool successfully booted on " << peer_address->host;
  return mp;
}

void Mempool::stop() {
  if (stopped_) return;
  stopped_ = true;
  stop_flag_->store(true, std::memory_order_relaxed);
  for (auto& close : closers_) close();
  // The closers already closed tx_batch_maker, so the verify worker can
  // never wedge in forward_admitted's blocking send; its own queue is
  // closed (and the worker joined) here.
  if (tx_verifier_) tx_verifier_->stop();
  tx_receiver_.stop();
  peer_receiver_.stop();
  for (auto& t : threads_) {
    if (t.joinable()) t.join();
  }
}

Mempool::~Mempool() { stop(); }

}  // namespace mempool
}  // namespace hotstuff

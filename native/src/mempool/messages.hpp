// Wire messages between mempools + the consensus-to-mempool command type
// (mempool/src/mempool.rs:29-42 in the reference).
#pragma once

#include <vector>

#include "common/serde.hpp"
#include "crypto/crypto.hpp"

namespace hotstuff {
namespace mempool {

using Transaction = Bytes;
using Batch = std::vector<Transaction>;

struct MempoolMessage {
  enum class Kind : uint32_t { kBatch = 0, kBatchRequest = 1 };

  Kind kind;
  Batch batch;                   // kBatch
  std::vector<Digest> missing;   // kBatchRequest
  PublicKey origin;              // kBatchRequest

  static MempoolMessage make_batch(Batch b) {
    MempoolMessage m;
    m.kind = Kind::kBatch;
    m.batch = std::move(b);
    return m;
  }

  static MempoolMessage make_batch_request(std::vector<Digest> missing,
                                           const PublicKey& origin) {
    MempoolMessage m;
    m.kind = Kind::kBatchRequest;
    m.missing = std::move(missing);
    m.origin = origin;
    return m;
  }

  Bytes serialize() const;
  static MempoolMessage deserialize(const Bytes& data);
};

// Commands the consensus sends to its mempool (Synchronize / Cleanup).
struct ConsensusMempoolMessage {
  enum class Kind { kSynchronize, kCleanup };

  Kind kind;
  std::vector<Digest> digests;  // kSynchronize
  PublicKey target;             // kSynchronize
  uint64_t round = 0;           // kCleanup
};

}  // namespace mempool
}  // namespace hotstuff

// Wire messages between mempools + the consensus-to-mempool command type
// (mempool/src/mempool.rs:29-42 in the reference).
#pragma once

#include <optional>
#include <set>
#include <string>
#include <vector>

#include "common/serde.hpp"
#include "crypto/crypto.hpp"

namespace hotstuff {
namespace mempool {

using Transaction = Bytes;
using Batch = std::vector<Transaction>;

// graftdag wire constants, pinned against hotstuff_tpu/analysis/dagwire.py
// by the graftlint wire cross-checker (wirecheck.py certframe rule) — edit
// BOTH sides or the gate fails.
//
// kBatchAckTag: the MempoolMessage tag value of a signed batch ACK.
// kBatchAckDomain: domain-separation constant folded into the digest an
// ACK signs (ack digest = SHA-512/32 of batch digest || kBatchAckDomain
// LE) so a batch-availability signature can never be replayed as a vote,
// timeout, or tx-frame signature (all of which sign other derivations of
// 32-byte digests).
// kCertVoteLen: minimum serialized bytes per certificate vote record
// (32-byte public key + 64-byte Ed25519 signature, the same per-element
// bound QC::deserialize uses) — the deserializer's guard against hostile
// length fields.
constexpr uint32_t kBatchAckTag = 2;
constexpr uint64_t kBatchAckDomain = 0x6b6361676164;  // "dagack" LE
constexpr size_t kCertVoteLen = 96;

struct MempoolMessage {
  enum class Kind : uint32_t { kBatch = 0, kBatchRequest = 1, kAck = 2 };

  Kind kind;
  Batch batch;                   // kBatch
  std::vector<Digest> missing;   // kBatchRequest
  PublicKey origin;              // kBatchRequest
  Digest ack_digest;             // kAck: the batch digest being certified
  PublicKey ack_author;          // kAck
  Signature ack_signature;       // kAck: Ed25519 over the ack digest

  static MempoolMessage make_batch(Batch b) {
    MempoolMessage m;
    m.kind = Kind::kBatch;
    m.batch = std::move(b);
    return m;
  }

  static MempoolMessage make_batch_request(std::vector<Digest> missing,
                                           const PublicKey& origin) {
    MempoolMessage m;
    m.kind = Kind::kBatchRequest;
    m.missing = std::move(missing);
    m.origin = origin;
    return m;
  }

  static MempoolMessage make_ack(const Digest& batch_digest,
                                 const PublicKey& author,
                                 Signature signature) {
    MempoolMessage m;
    m.kind = Kind::kAck;
    m.ack_digest = batch_digest;
    m.ack_author = author;
    m.ack_signature = std::move(signature);
    return m;
  }

  Bytes serialize() const;
  static MempoolMessage deserialize(const Bytes& data);
};

// graftdag availability certificate: a batch digest plus 2f+1 stake of
// Ed25519 ACK signatures over its ack digest.  Possession of a valid
// certificate proves the batch is retrievable from at least f+1 honest
// replicas, so consensus can order the digest WITHOUT the payload bytes —
// the Narwhal separation of availability from ordering.  QC-shaped by
// construction (a vote quorum over ONE common digest), so its signature
// batch rides the warmed sidecar RLC verify path.
struct BatchCertificate {
  Digest digest;  // the certified batch's digest (store key)
  std::vector<std::pair<PublicKey, Signature>> votes;

  // The digest every ACK signs: batch digest || kBatchAckDomain LE,
  // SHA-512/32.  Exposed statically because the signer (peer receiver),
  // the assembler (QuorumWaiter) and the verifier (consensus Core) must
  // agree byte-for-byte.
  static Digest ack_digest_of(const Digest& batch_digest) {
    return DigestBuilder()
        .update(batch_digest.data)
        .update_u64_le(kBatchAckDomain)
        .finalize();
  }
  Digest ack_digest() const { return ack_digest_of(digest); }

  // The (digest, pk, sig) records a signature batch must verify — all
  // votes share this certificate's ack digest (QC shape).
  std::vector<std::tuple<Digest, PublicKey, Signature>> vote_items() const {
    Digest d = ack_digest();
    std::vector<std::tuple<Digest, PublicKey, Signature>> items;
    items.reserve(votes.size());
    for (const auto& [pk, sig] : votes) items.emplace_back(d, pk, sig);
    return items;
  }

  // Hash over the full serialized certificate — the consensus Core's
  // verified-certificate cache key (any tampered byte misses the cache
  // and re-verifies; see QC::content_digest for the rationale).
  Digest content_digest() const {
    Writer w;
    serialize(&w);
    return DigestBuilder().update(w.out).finalize();
  }

  // Structural (stake/reuse/quorum/minimality) checks — everything but
  // the signature batch; returns an error string, empty = ok.  Templated
  // on the committee so both the mempool's and consensus's address books
  // (same names, stakes and quorum rule) can gate a certificate.
  // Mirrors check_vote_stakes in consensus/messages.cpp, including the
  // equal-stakes minimality guard: a padded certificate is a shape the
  // verify sidecar never warmed, so it is refused outright.
  template <typename CommitteeT>
  std::string check(const CommitteeT& committee) const {
    using StakeT = decltype(committee.stake(PublicKey{}));
    StakeT weight = 0;
    StakeT min_stake = 0;
    bool equal_stakes = true;
    std::set<PublicKey> used;
    for (const auto& [name, sig] : votes) {
      (void)sig;
      if (used.count(name)) {
        return "authority reuse in batch certificate: " + name.to_base64();
      }
      StakeT stake = committee.stake(name);
      if (stake == 0) {
        return "unknown authority in batch certificate: " + name.to_base64();
      }
      used.insert(name);
      weight += stake;
      if (min_stake == 0) {
        min_stake = stake;
      } else if (stake != min_stake) {
        equal_stakes = false;
      }
    }
    if (weight < committee.quorum_threshold()) {
      return "batch certificate requires a quorum";
    }
    if (equal_stakes && min_stake > 0 &&
        weight - min_stake >= committee.quorum_threshold()) {
      return "batch certificate carries more votes than a quorum";
    }
    return std::string();
  }

  void serialize(Writer* w) const;
  static BatchCertificate deserialize(Reader* r);
  Bytes to_bytes() const {
    Writer w;
    serialize(&w);
    return std::move(w.out);
  }
};

// What the mempool hands the consensus proposer per proposable batch: the
// digest, plus (dag mode) the availability certificate the block will
// carry in place of the payload bytes.
struct PayloadRef {
  Digest digest;
  std::optional<BatchCertificate> cert;
};

// Commands the consensus sends to its mempool (Synchronize / Cleanup).
struct ConsensusMempoolMessage {
  enum class Kind { kSynchronize, kCleanup };

  Kind kind;
  std::vector<Digest> digests;  // kSynchronize
  PublicKey target;             // kSynchronize
  // kSynchronize, graftdag: certificate signers known to HOLD the batch
  // (they signed its availability ACK).  When non-empty the synchronizer
  // fans the request across them instead of betting on the block author
  // alone — cert-driven fetch.
  std::vector<PublicKey> holders;
  uint64_t round = 0;           // kCleanup
};

}  // namespace mempool
}  // namespace hotstuff

#include "mempool/processor.hpp"

#include "common/log.hpp"

#include <thread>

namespace hotstuff {
namespace mempool {

std::thread Processor::spawn(Store store, ChannelPtr<ProcessorMessage> rx_batch,
                      ChannelPtr<PayloadRef> tx_digest) {
  return std::thread([store, rx_batch, tx_digest]() mutable {
    set_thread_name("mp-processor");
    while (auto msg = rx_batch->recv()) {
      Digest digest = Processor::digest_of(msg->batch);
      store.write(digest.to_bytes(), msg->batch);
      if (msg->forward) {
        tx_digest->send(PayloadRef{digest, std::move(msg->cert)});
      }
    }
  });
}

}  // namespace mempool
}  // namespace hotstuff

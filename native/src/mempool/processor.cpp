#include "mempool/processor.hpp"

#include "common/log.hpp"

#include <thread>

namespace hotstuff {
namespace mempool {

std::thread Processor::spawn(Store store, ChannelPtr<Bytes> rx_batch,
                      ChannelPtr<Digest> tx_digest) {
  return std::thread([store, rx_batch, tx_digest]() mutable {
    set_thread_name("mp-processor");
    while (auto batch = rx_batch->recv()) {
      Digest digest = Processor::digest_of(*batch);
      store.write(digest.to_bytes(), *batch);
      tx_digest->send(digest);
    }
  });
}

}  // namespace mempool
}  // namespace hotstuff

// Mempool facade: spawns the full actor pipeline — client-tx receiver →
// BatchMaker → QuorumWaiter → Processor → consensus, peer receiver →
// Processor/Helper, and the Synchronizer servicing consensus commands
// (mempool/src/mempool.rs:44-193 in the reference).
#pragma once

#include <atomic>
#include <functional>
#include <memory>
#include <thread>
#include <vector>

#include "common/channel.hpp"
#include "mempool/config.hpp"
#include "mempool/ingress.hpp"
#include "mempool/messages.hpp"
#include "mempool/tx_verify.hpp"
#include "network/receiver.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace mempool {

class Mempool {
 public:
  // tx_consensus carries proposable payload refs (batch digest, plus the
  // availability certificate in dag mode) into the consensus proposer;
  // rx_consensus carries Synchronize/Cleanup commands back.  `secret`
  // signs batch ACKs and our own certificate votes in dag mode (host
  // Ed25519 under either scheme knob).
  static std::unique_ptr<Mempool> spawn(
      PublicKey name, SecretKey secret, Committee committee,
      Parameters parameters, Store store,
      ChannelPtr<ConsensusMempoolMessage> rx_consensus,
      ChannelPtr<PayloadRef> tx_consensus);

  // Orderly teardown: set the stop flag, close every channel (waking any
  // actor blocked in send/recv), stop the receivers, join all actor
  // threads. Idempotent; the destructor calls it.
  void stop();
  ~Mempool();

  NetworkReceiver& tx_receiver() { return tx_receiver_; }
  NetworkReceiver& peer_receiver() { return peer_receiver_; }
  // graftsurge: the bounded-ingress admission gate (telemetry access).
  const IngressGate& ingress_gate() const { return *ingress_gate_; }
  // graftingress: the admission-verify stage (null when verify_ingress
  // is off — the legacy unsigned A/B path).
  std::shared_ptr<const TxVerifier> tx_verifier() const {
    return tx_verifier_;
  }

 private:
  Mempool() = default;

  NetworkReceiver tx_receiver_;
  NetworkReceiver peer_receiver_;
  std::shared_ptr<IngressGate> ingress_gate_;
  std::shared_ptr<TxVerifier> tx_verifier_;
  std::shared_ptr<std::atomic<bool>> stop_flag_ =
      std::make_shared<std::atomic<bool>>(false);
  std::vector<std::function<void()>> closers_;
  std::vector<std::thread> threads_;
  bool stopped_ = false;
};

}  // namespace mempool
}  // namespace hotstuff

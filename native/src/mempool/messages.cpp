#include "mempool/messages.hpp"

#include "mempool/config.hpp"

namespace hotstuff {
namespace mempool {

Bytes MempoolMessage::serialize() const {
  Writer w;
  w.tag(static_cast<uint32_t>(kind));
  switch (kind) {
    case Kind::kBatch:
      w.u64(batch.size());
      for (const auto& tx : batch) w.bytes(tx);
      break;
    case Kind::kBatchRequest:
      w.u64(missing.size());
      for (const auto& d : missing) d.serialize(&w);
      origin.serialize(&w);
      break;
    case Kind::kAck:
      ack_digest.serialize(&w);
      ack_author.serialize(&w);
      ack_signature.serialize(&w);
      break;
  }
  return std::move(w.out);
}

MempoolMessage MempoolMessage::deserialize(const Bytes& data) {
  Reader r(data);
  MempoolMessage m;
  uint32_t tag = r.tag();
  switch (tag) {
    case 0: {
      m.kind = Kind::kBatch;
      uint64_t n = r.seq_len(8);
      m.batch.reserve(n);
      for (uint64_t i = 0; i < n; i++) m.batch.push_back(r.bytes());
      break;
    }
    case 1: {
      m.kind = Kind::kBatchRequest;
      uint64_t n = r.seq_len(32);
      m.missing.reserve(n);
      for (uint64_t i = 0; i < n; i++) {
        m.missing.push_back(Digest::deserialize(&r));
      }
      m.origin = PublicKey::deserialize(&r);
      break;
    }
    case kBatchAckTag: {
      m.kind = Kind::kAck;
      m.ack_digest = Digest::deserialize(&r);
      m.ack_author = PublicKey::deserialize(&r);
      m.ack_signature = Signature::deserialize(&r);
      break;
    }
    default:
      throw SerdeError("bad MempoolMessage tag");
  }
  return m;
}

void BatchCertificate::serialize(Writer* w) const {
  digest.serialize(w);
  w->u64(votes.size());
  for (const auto& [pk, sig] : votes) {
    pk.serialize(w);
    sig.serialize(w);
  }
}

BatchCertificate BatchCertificate::deserialize(Reader* r) {
  BatchCertificate cert;
  cert.digest = Digest::deserialize(r);
  uint64_t n = r->seq_len(kCertVoteLen);
  cert.votes.reserve(n);
  for (uint64_t i = 0; i < n; i++) {
    PublicKey pk = PublicKey::deserialize(r);
    Signature sig = Signature::deserialize(r);
    cert.votes.emplace_back(pk, std::move(sig));
  }
  return cert;
}

Json Committee::to_json() const {
  Json auths = Json::object();
  for (const auto& [name, a] : authorities_) {
    Json entry = Json::object();
    entry.set("stake", Json(int64_t(a.stake)));
    entry.set("transactions_address", Json(a.transactions_address.str()));
    entry.set("mempool_address", Json(a.mempool_address.str()));
    auths.set(name.to_base64(), std::move(entry));
  }
  Json j = Json::object();
  j.set("authorities", std::move(auths));
  j.set("epoch", Json(int64_t(epoch_)));
  return j;
}

Committee Committee::from_json(const Json& j) {
  std::map<PublicKey, Authority> authorities;
  for (const auto& [name_b64, entry] : j.at("authorities").members()) {
    PublicKey name;
    if (!PublicKey::from_base64(name_b64, &name)) {
      throw JsonError("bad public key in mempool committee: " + name_b64);
    }
    Authority a;
    a.stake = static_cast<Stake>(entry.at("stake").as_u64());
    auto ta = Address::parse(entry.at("transactions_address").as_string());
    auto ma = Address::parse(entry.at("mempool_address").as_string());
    if (!ta || !ma) throw JsonError("bad address in mempool committee");
    a.transactions_address = *ta;
    a.mempool_address = *ma;
    authorities.emplace(name, std::move(a));
  }
  uint64_t epoch = j.find("epoch") ? j.at("epoch").as_u64() : 1;
  return Committee(std::move(authorities), epoch);
}

}  // namespace mempool
}  // namespace hotstuff

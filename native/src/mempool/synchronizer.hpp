// Mempool synchronizer: on Synchronize(digests, target) it registers
// notify_read waiters for the missing batches and sends a BatchRequest to
// the block author; a 1 s timer rebroadcasts stale requests to a few random
// peers; Cleanup garbage-collects by round depth
// (mempool/src/synchronizer.rs:23-210 in the reference).
#pragma once

#include <thread>

#include "common/channel.hpp"
#include "mempool/config.hpp"
#include "mempool/messages.hpp"
#include "store/store.hpp"

namespace hotstuff {
namespace mempool {

class Synchronizer {
 public:
  // Returns the actor thread; exits when rx_message is closed and drained.
  static std::thread spawn(PublicKey name, Committee committee, Store store,
                    Round gc_depth, uint64_t sync_retry_delay,
                    size_t sync_retry_nodes,
                    ChannelPtr<ConsensusMempoolMessage> rx_message);
};

}  // namespace mempool
}  // namespace hotstuff

#include "mempool/quorum_waiter.hpp"

#include "common/log.hpp"

#include <chrono>
#include <condition_variable>
#include <mutex>
#include <set>
#include <thread>
#include <utility>
#include <vector>

namespace hotstuff {
namespace mempool {

namespace {

// Legacy (eventloop) wait: bare transport ACKs, stake counted per reply.
bool wait_transport_acks(const Committee& committee, Stake my_stake,
                         QuorumWaiterMessage* msg,
                         const std::atomic<bool>& stop) {
  // Stake accumulates as ACKs arrive in any order (the reference's
  // FuturesUnordered wait, quorum_waiter.rs:60-86): each handler's
  // on_ready callback bumps a shared counter; we sleep until quorum.
  auto m = std::make_shared<std::mutex>();
  auto cv = std::make_shared<std::condition_variable>();
  auto total = std::make_shared<Stake>(my_stake);
  for (const auto& [name, handler] : msg->handlers) {
    Stake stake = committee.stake(name);
    handler.on_ready([m, cv, total, stake](const Bytes& reply) {
      // Empty bytes mean CANCELLED (teardown or full backlog), not a
      // peer ACK — counting those would certify batch availability
      // for peers that never received it.
      if (reply.empty()) return;
      std::lock_guard<std::mutex> lk(*m);
      *total += stake;
      cv->notify_one();
    });
  }
  Stake quorum = committee.quorum_threshold();
  std::unique_lock<std::mutex> lk(*m);
  // Bounded waits so a teardown (stop set, peers gone) can't wedge the
  // actor; in steady state the notify wakes us immediately.
  while (*total < quorum && !stop.load(std::memory_order_relaxed)) {
    cv->wait_for(lk, std::chrono::milliseconds(50));
  }
  return *total >= quorum;
}

// graftdag wait: each reply must be a well-formed kAck whose Ed25519
// signature covers THIS batch's ack digest.  Replies are collected on
// the sender's reply thread but parsed and verified HERE, so signature
// work never stalls the network reactor.  Returns the assembled minimal
// certificate, or nullopt when stopped before quorum.
std::optional<BatchCertificate> wait_signed_acks(
    const Committee& committee, const PublicKey& name,
    const SecretKey& secret, QuorumWaiterMessage* msg,
    const std::atomic<bool>& stop) {
  Digest batch_digest = sha512_digest(msg->batch);
  Digest ack_digest = BatchCertificate::ack_digest_of(batch_digest);

  auto m = std::make_shared<std::mutex>();
  auto cv = std::make_shared<std::condition_variable>();
  auto replies = std::make_shared<std::vector<Bytes>>();
  for (const auto& [peer, handler] : msg->handlers) {
    (void)peer;  // attribution comes from the SIGNED author field
    handler.on_ready([m, cv, replies](const Bytes& reply) {
      if (reply.empty()) return;  // cancelled, not an ACK
      std::lock_guard<std::mutex> lk(*m);
      replies->push_back(reply);
      cv->notify_one();
    });
  }

  // Our own vote first: the producer trivially holds its own batch.
  BatchCertificate cert;
  cert.digest = batch_digest;
  cert.votes.emplace_back(name, Signature::sign_host(ack_digest, secret));
  Stake verified = committee.stake(name);
  std::set<PublicKey> used{name};

  Stake quorum = committee.quorum_threshold();
  size_t consumed = 0;
  while (verified < quorum && !stop.load(std::memory_order_relaxed)) {
    Bytes reply;
    {
      std::unique_lock<std::mutex> lk(*m);
      if (consumed == replies->size()) {
        cv->wait_for(lk, std::chrono::milliseconds(50));
        if (consumed == replies->size()) continue;
      }
      reply = std::move((*replies)[consumed++]);
    }
    // A bare transport "Ack" is a peer that received but could not store
    // the batch (overloaded) — it keeps the sender's FIFO reply pairing
    // intact but carries no availability vote.
    if (reply.size() == 3 && reply[0] == 'A' && reply[1] == 'c' &&
        reply[2] == 'k') {
      continue;
    }
    // Parse + verify with the lock RELEASED (the reply thread only needs
    // it to append).  Any malformed or mis-signed reply is dropped — the
    // slot reopens for the honest retransmit.
    try {
      MempoolMessage ack = MempoolMessage::deserialize(reply);
      if (ack.kind != MempoolMessage::Kind::kAck) continue;
      if (ack.ack_digest != batch_digest) continue;  // stale/foreign ack
      if (committee.stake(ack.ack_author) == 0) continue;
      if (used.count(ack.ack_author)) continue;  // duplicate signer
      if (!ack.ack_signature.verify(ack_digest, ack.ack_author)) {
        LOG_WARN("mempool::quorum_waiter")
            << "invalid batch-ack signature from "
            << ack.ack_author.to_base64();
        continue;
      }
      used.insert(ack.ack_author);
      cert.votes.emplace_back(ack.ack_author, std::move(ack.ack_signature));
      verified += committee.stake(ack.ack_author);
    } catch (const std::exception& e) {
      LOG_WARN("mempool::quorum_waiter")
          << "Serialization failure on batch ack: " << e.what();
    }
  }
  if (verified < quorum) return std::nullopt;  // stopped mid-wait
  LOG_DEBUG("mempool::quorum_waiter")
      << "Certified batch " << batch_digest.to_base64() << " with "
      << cert.votes.size() << " signed acks";
  return cert;
}

}  // namespace

std::thread QuorumWaiter::spawn(Committee committee, PublicKey name,
                                SecretKey secret, bool dag,
                                ChannelPtr<QuorumWaiterMessage> rx_message,
                                ChannelPtr<ProcessorMessage> tx_batch,
                                std::shared_ptr<std::atomic<bool>> stop) {
  return std::thread([committee = std::move(committee), name, secret, dag,
                      rx_message, tx_batch, stop] {
    set_thread_name("quorum-wait");
    while (auto msg = rx_message->recv()) {
      ProcessorMessage out;
      if (dag) {
        auto cert = wait_signed_acks(committee, name, secret, &*msg, *stop);
        if (!cert) break;  // stopped mid-wait
        out.cert = std::move(*cert);
      } else {
        if (!wait_transport_acks(committee, committee.stake(name), &*msg,
                                 *stop)) {
          break;  // stopped mid-wait
        }
      }
      out.batch = std::move(msg->batch);
      tx_batch->send(std::move(out));
    }
  });
}

}  // namespace mempool
}  // namespace hotstuff

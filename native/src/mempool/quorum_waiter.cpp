#include "mempool/quorum_waiter.hpp"

#include "common/log.hpp"

#include <chrono>
#include <condition_variable>
#include <mutex>
#include <thread>

namespace hotstuff {
namespace mempool {

std::thread QuorumWaiter::spawn(Committee committee, Stake my_stake,
                                ChannelPtr<QuorumWaiterMessage> rx_message,
                                ChannelPtr<Bytes> tx_batch,
                                std::shared_ptr<std::atomic<bool>> stop) {
  return std::thread([committee = std::move(committee), my_stake, rx_message,
                      tx_batch, stop] {
    set_thread_name("quorum-wait");
    while (auto msg = rx_message->recv()) {
      // Stake accumulates as ACKs arrive in any order (the reference's
      // FuturesUnordered wait, quorum_waiter.rs:60-86): each handler's
      // on_ready callback bumps a shared counter; we sleep until quorum.
      auto m = std::make_shared<std::mutex>();
      auto cv = std::make_shared<std::condition_variable>();
      auto total = std::make_shared<Stake>(my_stake);
      for (const auto& [name, handler] : msg->handlers) {
        Stake stake = committee.stake(name);
        handler.on_ready([m, cv, total, stake](const Bytes& reply) {
          // Empty bytes mean CANCELLED (teardown or full backlog), not a
          // peer ACK — counting those would certify batch availability
          // for peers that never received it.
          if (reply.empty()) return;
          std::lock_guard<std::mutex> lk(*m);
          *total += stake;
          cv->notify_one();
        });
      }
      Stake quorum = committee.quorum_threshold();
      std::unique_lock<std::mutex> lk(*m);
      // Bounded waits so a teardown (stop set, peers gone) can't wedge the
      // actor; in steady state the notify wakes us immediately.
      while (*total < quorum &&
             !stop->load(std::memory_order_relaxed)) {
        cv->wait_for(lk, std::chrono::milliseconds(50));
      }
      if (*total < quorum) break;  // stopped mid-wait
      lk.unlock();
      tx_batch->send(std::move(msg->batch));
    }
  });
}

}  // namespace mempool
}  // namespace hotstuff

// Mempool configuration: tunables + committee address book with stake
// accounting (mempool/src/config.rs:8-115 in the reference). JSON schemas
// match the harness writers (hotstuff_tpu/harness/config.py).
#pragma once

#include <map>
#include <string>
#include <vector>

#include "common/json.hpp"
#include "common/log.hpp"
#include "crypto/crypto.hpp"
#include "network/socket.hpp"

namespace hotstuff {
namespace mempool {

using Stake = uint32_t;
using Round = uint64_t;

struct Parameters {
  Round gc_depth = 50;
  uint64_t sync_retry_delay = 5'000;  // ms
  size_t sync_retry_nodes = 3;
  size_t batch_size = 500'000;  // bytes
  uint64_t max_batch_delay = 100;  // ms
  // graftsurge bounded ingress (mempool/ingress.hpp): client txs
  // buffered ahead of the BatchMaker before the gate sheds with BUSY
  // (tx count AND byte budget; the receiver pauses entirely when BUSY
  // is ignored).
  size_t ingress_tx_budget = 20'000;
  size_t ingress_byte_budget = 16u << 20;  // 16 MiB
  // graftingress admission verify (mempool/tx_verify.hpp): when true,
  // client txs must be signed frames (mempool/tx_frame.hpp) and verify
  // through the sidecar bulk lane before reaching the BatchMaker; false
  // keeps the legacy unsigned path for A/B measurement.
  bool verify_ingress = false;
  size_t verify_batch = 64;           // records per admission launch
  uint64_t verify_max_delay = 20;     // ms; seal a partial verify batch
  size_t verify_queue_budget = 4096;  // txs queued ahead of verify
  // graftdag certified-batch mempool (Narwhal-style availability
  // separation): peers reply to each broadcast batch with an Ed25519
  // SIGNED ack, the QuorumWaiter assembles 2f+1 of them into a
  // BatchCertificate, and only the PRODUCER proposes its batch (as
  // digest + certificate) — peers store payload bytes without feeding
  // their own proposer, so dissemination scales with committee size
  // instead of funneling every digest through every leader.  false
  // keeps the legacy transport-ACK eventloop path for A/B measurement.
  bool dag = false;

  static Parameters from_json(const Json& j) {
    Parameters p;
    if (auto* v = j.find("gc_depth")) p.gc_depth = v->as_u64();
    if (auto* v = j.find("sync_retry_delay")) p.sync_retry_delay = v->as_u64();
    if (auto* v = j.find("sync_retry_nodes")) {
      p.sync_retry_nodes = size_t(v->as_u64());
    }
    if (auto* v = j.find("batch_size")) p.batch_size = size_t(v->as_u64());
    if (auto* v = j.find("max_batch_delay")) p.max_batch_delay = v->as_u64();
    if (auto* v = j.find("ingress_tx_budget")) {
      p.ingress_tx_budget = size_t(v->as_u64());
    }
    if (auto* v = j.find("ingress_byte_budget")) {
      p.ingress_byte_budget = size_t(v->as_u64());
    }
    if (auto* v = j.find("verify_ingress")) p.verify_ingress = v->as_bool();
    if (auto* v = j.find("verify_batch")) {
      p.verify_batch = size_t(v->as_u64());
    }
    if (auto* v = j.find("verify_max_delay")) {
      p.verify_max_delay = v->as_u64();
    }
    if (auto* v = j.find("verify_queue_budget")) {
      p.verify_queue_budget = size_t(v->as_u64());
    }
    if (auto* v = j.find("dag")) p.dag = v->as_bool();
    return p;
  }

  void log() const {
    // NOTE: These log entries are used to compute performance
    // (hotstuff_tpu/harness/logs.py config regexes).
    LOG_INFO("mempool::config")
        << "Garbage collection depth set to " << gc_depth << " rounds";
    LOG_INFO("mempool::config")
        << "Sync retry delay set to " << sync_retry_delay << " ms";
    LOG_INFO("mempool::config")
        << "Sync retry nodes set to " << sync_retry_nodes << " nodes";
    LOG_INFO("mempool::config") << "Batch size set to " << batch_size << " B";
    LOG_INFO("mempool::config")
        << "Max batch delay set to " << max_batch_delay << " ms";
    LOG_INFO("mempool::config")
        << "Ingress tx budget set to " << ingress_tx_budget << " txs";
    LOG_INFO("mempool::config")
        << "Ingress byte budget set to " << ingress_byte_budget << " B";
    // Optional line (logs.py mines it with a plain `search`): absent on
    // legacy unsigned-ingress runs, so old logs keep parsing.
    if (verify_ingress) {
      LOG_INFO("mempool::config")
          << "Ingress signature verification enabled with batch "
          << verify_batch << " txs";
    }
    // Optional line (same contract): absent on legacy eventloop runs.
    if (dag) {
      LOG_INFO("mempool::config") << "Dag certified batches enabled";
    }
  }
};

struct Authority {
  Stake stake = 1;
  Address transactions_address;  // client-facing (:front)
  Address mempool_address;       // peer-facing
};

class Committee {
 public:
  Committee() = default;
  Committee(std::map<PublicKey, Authority> authorities, uint64_t epoch)
      : authorities_(std::move(authorities)), epoch_(epoch) {}

  static Committee from_json(const Json& j);
  Json to_json() const;

  size_t size() const { return authorities_.size(); }
  Stake stake(const PublicKey& name) const {
    auto it = authorities_.find(name);
    return it == authorities_.end() ? 0 : it->second.stake;
  }

  Stake total_stake() const {
    Stake total = 0;
    for (const auto& [_, a] : authorities_) total += a.stake;
    return total;
  }

  // 2f+1 equivalent: 2N/3 + 1 (mempool/src/config.rs:90-95).
  Stake quorum_threshold() const { return 2 * total_stake() / 3 + 1; }

  std::optional<Address> transactions_address(const PublicKey& name) const {
    auto it = authorities_.find(name);
    if (it == authorities_.end()) return std::nullopt;
    return it->second.transactions_address;
  }

  std::optional<Address> mempool_address(const PublicKey& name) const {
    auto it = authorities_.find(name);
    if (it == authorities_.end()) return std::nullopt;
    return it->second.mempool_address;
  }

  // All peers' mempool addresses except ours.
  std::vector<std::pair<PublicKey, Address>> broadcast_addresses(
      const PublicKey& myself) const {
    std::vector<std::pair<PublicKey, Address>> out;
    for (const auto& [name, a] : authorities_) {
      if (name != myself) out.emplace_back(name, a.mempool_address);
    }
    return out;
  }

  const std::map<PublicKey, Authority>& authorities() const {
    return authorities_;
  }

 private:
  std::map<PublicKey, Authority> authorities_;
  uint64_t epoch_ = 1;
};

}  // namespace mempool
}  // namespace hotstuff

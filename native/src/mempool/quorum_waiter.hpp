// QuorumWaiter: holds each sealed batch until peers with 2f+1 cumulative
// stake (including our own) have ACKed the broadcast, then releases it for
// processing (mempool/src/quorum_waiter.rs:22-88 in the reference).
#pragma once

#include "common/channel.hpp"
#include "mempool/batch_maker.hpp"
#include "mempool/config.hpp"

namespace hotstuff {
namespace mempool {

class QuorumWaiter {
 public:
  static void spawn(Committee committee, Stake my_stake,
                    ChannelPtr<QuorumWaiterMessage> rx_message,
                    ChannelPtr<Bytes> tx_batch);
};

}  // namespace mempool
}  // namespace hotstuff

// QuorumWaiter: holds each sealed batch until peers with 2f+1 cumulative
// stake (including our own) have ACKed the broadcast, then releases it for
// processing (mempool/src/quorum_waiter.rs:22-88 in the reference).
#pragma once

#include <atomic>
#include <memory>
#include <thread>

#include "common/channel.hpp"
#include "mempool/batch_maker.hpp"
#include "mempool/config.hpp"

namespace hotstuff {
namespace mempool {

class QuorumWaiter {
 public:
  // Returns the actor thread; exits when rx_message is closed and drained.
  // `stop` breaks an in-progress stake wait at teardown (the ACKs it is
  // waiting for may never arrive once peers shut down).
  static std::thread spawn(Committee committee, Stake my_stake,
                           ChannelPtr<QuorumWaiterMessage> rx_message,
                           ChannelPtr<Bytes> tx_batch,
                           std::shared_ptr<std::atomic<bool>> stop);
};

}  // namespace mempool
}  // namespace hotstuff

// QuorumWaiter: holds each sealed batch until peers with 2f+1 cumulative
// stake (including our own) have ACKed the broadcast, then releases it for
// processing (mempool/src/quorum_waiter.rs:22-88 in the reference).
//
// graftdag: in dag mode the ACKs are Ed25519 SIGNATURES over the batch's
// ack digest (see BatchCertificate) rather than bare transport ACKs.  The
// waiter parses each signed reply, verifies it on THIS thread (never the
// sender's reactor), accumulates verified stake, and releases the batch
// together with the assembled availability certificate — minimal (exactly
// a quorum under equal stakes), so it passes the structural over-quorum
// guard every verifier applies.
#pragma once

#include <atomic>
#include <memory>
#include <thread>

#include "common/channel.hpp"
#include "mempool/batch_maker.hpp"
#include "mempool/config.hpp"
#include "mempool/processor.hpp"

namespace hotstuff {
namespace mempool {

class QuorumWaiter {
 public:
  // Returns the actor thread; exits when rx_message is closed and drained.
  // `stop` breaks an in-progress stake wait at teardown (the ACKs it is
  // waiting for may never arrive once peers shut down).  `secret` signs
  // our own certificate vote in dag mode (host Ed25519, scheme-agnostic);
  // legacy mode ignores it.
  static std::thread spawn(Committee committee, PublicKey name,
                           SecretKey secret, bool dag,
                           ChannelPtr<QuorumWaiterMessage> rx_message,
                           ChannelPtr<ProcessorMessage> tx_batch,
                           std::shared_ptr<std::atomic<bool>> stop);
};

}  // namespace mempool
}  // namespace hotstuff

#include "mempool/tx_verify.hpp"

#include <chrono>
#include <cstring>
#include <tuple>
#include <utility>
#include <vector>

#include "common/log.hpp"
#include "mempool/tx_frame.hpp"

namespace hotstuff {
namespace mempool {

std::shared_ptr<TxVerifier> TxVerifier::spawn(
    Config cfg, ChannelPtr<Transaction> tx_batch_maker,
    std::shared_ptr<IngressGate> gate) {
  auto v = std::shared_ptr<TxVerifier>(
      new TxVerifier(cfg, std::move(tx_batch_maker), std::move(gate)));
  LOG_INFO("mempool::tx_verify")
      << "Admission verify enabled: batch " << cfg.batch << " txs, max delay "
      << cfg.max_delay_ms << " ms, queue budget " << cfg.queue_budget
      << " txs";
  return v;
}

TxVerifier::TxVerifier(Config cfg, ChannelPtr<Transaction> tx_batch_maker,
                       std::shared_ptr<IngressGate> gate)
    : cfg_(cfg),
      queue_(make_channel<PendingTx>(cfg.queue_budget + 64)),
      tx_batch_maker_(std::move(tx_batch_maker)),
      gate_(std::move(gate)) {
  worker_ = std::thread([this] { run_(); });
}

bool TxVerifier::enqueue(Bytes frame,
                         std::optional<ConnectionWriter> writer,
                         uint32_t* retry_ms) {
  // Budget first: the channel has slack above the budget (like the
  // gate/channel split in Mempool::spawn), so the counter is the
  // admission authority and try_send only fails at teardown.
  if (depth_.load(std::memory_order_relaxed) >= cfg_.queue_budget) {
    if (retry_ms != nullptr) {
      *retry_ms = uint32_t(std::max<uint64_t>(50, 2 * cfg_.max_delay_ms));
    }
    return false;
  }
  PendingTx tx;
  tx.frame = std::move(frame);
  tx.writer = std::move(writer);
  if (!queue_->try_send(std::move(tx))) {
    if (retry_ms != nullptr) *retry_ms = 100;
    return false;
  }
  depth_.fetch_add(1, std::memory_order_relaxed);
  return true;
}

void TxVerifier::run_() {
  set_thread_name("tx-verify");
  std::vector<PendingTx> batch;
  auto delay = std::chrono::milliseconds(cfg_.max_delay_ms);
  auto deadline = std::chrono::steady_clock::now() + delay;
  while (true) {
    PendingTx tx;
    auto status = queue_->recv_until(&tx, deadline);
    if (status == RecvStatus::kClosed) {
      // Teardown: unwind the gate for anything still pending so a
      // restart never inherits phantom backlog accounting.
      for (auto& p : batch) {
        if (gate_) gate_->on_consumed(p.frame.size());
        depth_.fetch_sub(1, std::memory_order_relaxed);
      }
      return;
    }
    if (status == RecvStatus::kTimeout) {
      settle_batch_(&batch);
      deadline = std::chrono::steady_clock::now() + delay;
      continue;
    }
    batch.push_back(std::move(tx));
    if (batch.size() >= cfg_.batch) {
      settle_batch_(&batch);
      deadline = std::chrono::steady_clock::now() + delay;
    }
  }
}

void TxVerifier::settle_batch_(std::vector<PendingTx>* batch) {
  if (batch->empty()) return;
  // QC-shaped records: (preimage digest, user pubkey, signature) — the
  // exact triple every consensus verify path ships, so the batch rides
  // OP_VERIFY_BULK unchanged.  Frames were structurally validated at
  // enqueue; the re-parse here is offset arithmetic, not trust.
  std::vector<std::tuple<Digest, PublicKey, Signature>> items;
  items.reserve(batch->size());
  for (const auto& tx : *batch) {
    SignedTxView v;
    parse_signed_tx(tx.frame.data(), tx.frame.size(), &v);
    Digest d = tx_sign_digest(tx.frame.data(),
                              kTxFrameHeaderLen + v.payload_len);
    PublicKey pk;
    std::memcpy(pk.data.data(), v.pk, kTxPkLen);
    Signature sig;
    sig.data.assign(v.sig, v.sig + kTxSigLen);
    items.emplace_back(d, pk, sig);
  }

  static const Digest kIngressCtx = tx_ingress_ctx();
  std::optional<std::vector<bool>> mask;
  int attempts = 0;
  while (true) {
    if (!Signature::async_available()) break;  // breaker open / no budget
    Oneshot<std::pair<std::optional<std::vector<bool>>, int>> done;
    Signature::verify_batch_multi_async_masked(
        items,
        [done](std::optional<std::vector<bool>> m, int busy_ms) {
          done.set({std::move(m), busy_ms});
        },
        /*bulk=*/true, &kIngressCtx);
    auto result = done.wait();  // bounded: callbacks fire by deadline
    if (result.first) {
      mask = std::move(result.first);
      break;
    }
    int busy_ms = result.second;
    if (busy_ms < 0) break;  // transport failure -> host path
    // Explicit OP_BUSY backpressure: a bounded paced retry keeps the
    // batch on the device through a transient surge; past the budget
    // the whole batch sheds with a client-visible BUSY (honest load
    // backs off per-user, the same contract as the ingress gate).
    uint32_t pace = std::min<uint32_t>(
        std::max(1, busy_ms), cfg_.busy_retry_cap_ms);
    if (attempts >= cfg_.busy_retries) {
      shed_busy_(batch, pace);
      return;
    }
    attempts++;
    busy_retries_.fetch_add(1, std::memory_order_relaxed);
    std::this_thread::sleep_for(std::chrono::milliseconds(pace));
  }

  if (!mask) {
    // Host path: breaker-open or mid-flight transport failure.  Same
    // per-tx verdicts, pure OpenSSL — degraded goodput, never an
    // unverified admission.
    host_fallbacks_.fetch_add(1, std::memory_order_relaxed);
    std::vector<bool> m(items.size());
    for (size_t i = 0; i < items.size(); i++) {
      m[i] = std::get<2>(items[i]).verify(std::get<0>(items[i]),
                                          std::get<1>(items[i]));
    }
    mask = std::move(m);
  }

  size_t rejected = 0;
  for (size_t i = 0; i < batch->size(); i++) {
    if ((*mask)[i]) {
      verified_.fetch_add(1, std::memory_order_relaxed);
      // VERIFIES(tx-signature)
      forward_admitted(std::move((*batch)[i].frame));
    } else {
      reject_forged_(&(*batch)[i]);
      rejected++;
    }
    depth_.fetch_sub(1, std::memory_order_relaxed);
  }
  if (rejected > 0) {
    // NOTE: mined by hotstuff_tpu/harness/logs.py (format frozen).
    LOG_WARN("mempool::tx_verify")
        << "Rejected " << rejected
        << " forged transaction(s) at ingress admission ("
        << forged_.load(std::memory_order_relaxed) << " total)";
  }
  batch->clear();
}

void TxVerifier::forward_admitted(Bytes frame) {
  size_t tx_bytes = frame.size();
  // Blocking send is safe on the worker: capacity tracks the ingress
  // budget, which bounds how many admitted txs can be outstanding.  A
  // false return means teardown — unwind the gate ourselves since the
  // BatchMaker will never drain this tx.
  if (!tx_batch_maker_->send(std::move(frame))) {
    if (gate_) gate_->on_consumed(tx_bytes);
  }
}

void TxVerifier::reject_forged_(PendingTx* tx) {
  forged_.fetch_add(1, std::memory_order_relaxed);
  if (gate_) gate_->on_consumed(tx->frame.size());
}

void TxVerifier::shed_busy_(std::vector<PendingTx>* batch,
                            uint32_t retry_ms) {
  for (auto& tx : *batch) {
    if (tx.writer) tx.writer->send("BUSY " + std::to_string(retry_ms));
    if (gate_) gate_->on_consumed(tx.frame.size());
    shed_.fetch_add(1, std::memory_order_relaxed);
    depth_.fetch_sub(1, std::memory_order_relaxed);
  }
  LOG_WARN("mempool::tx_verify")
      << "Admission verify busy; shed " << batch->size()
      << " tx(s) with retry-after " << retry_ms << " ms ("
      << shed_.load(std::memory_order_relaxed) << " total)";
  batch->clear();
}

void TxVerifier::stop() {
  // acq_rel: the winning stop() publishes everything before the close +
  // join below; a losing racer must observe that teardown as complete.
  if (stopped_.exchange(true, std::memory_order_acq_rel)) return;
  queue_->close();
  if (worker_.joinable()) worker_.join();
}

TxVerifier::~TxVerifier() { stop(); }

}  // namespace mempool
}  // namespace hotstuff

// BatchMaker: accumulates client transactions into batches sealed at
// batch_size bytes or max_batch_delay ms, broadcasts each sealed batch to
// all peers via the reliable sender, and hands the serialized batch plus the
// broadcast ACK handlers to the QuorumWaiter
// (mempool/src/batch_maker.rs:27-168 in the reference).
#pragma once

#include <memory>
#include <thread>

#include "common/channel.hpp"
#include "mempool/config.hpp"
#include "mempool/messages.hpp"
#include "network/reliable_sender.hpp"

namespace hotstuff {
namespace mempool {

struct QuorumWaiterMessage {
  Bytes batch;  // serialized MempoolMessage::Batch
  std::vector<std::pair<PublicKey, CancelHandler>> handlers;
};

class BatchMaker {
 public:
  static void spawn(size_t batch_size, uint64_t max_batch_delay,
                    ChannelPtr<Transaction> rx_transaction,
                    ChannelPtr<QuorumWaiterMessage> tx_message,
                    std::vector<std::pair<PublicKey, Address>>
                        mempool_addresses);
};

}  // namespace mempool
}  // namespace hotstuff

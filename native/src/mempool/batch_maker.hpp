// BatchMaker: accumulates client transactions into batches sealed at
// batch_size bytes or max_batch_delay ms, broadcasts each sealed batch to
// all peers via the reliable sender, and hands the serialized batch plus the
// broadcast ACK handlers to the QuorumWaiter
// (mempool/src/batch_maker.rs:27-168 in the reference).
#pragma once

#include <atomic>
#include <memory>
#include <thread>

#include "common/channel.hpp"
#include "mempool/config.hpp"
#include "mempool/ingress.hpp"
#include "mempool/messages.hpp"
#include "network/reliable_sender.hpp"

namespace hotstuff {
namespace mempool {

struct QuorumWaiterMessage {
  Bytes batch;  // serialized MempoolMessage::Batch
  std::vector<std::pair<PublicKey, CancelHandler>> handlers;
};

class BatchMaker {
 public:
  // Returns the actor thread; it exits when rx_transaction is closed and
  // drained. The caller owns the join. `stop` makes the broadcast sends
  // interruptible at teardown (see ReliableSender).  `gate` (optional)
  // is the graftsurge ingress gate: every drained transaction unwinds
  // its backlog accounting, which is what resumes a paused receiver at
  // the low-water mark.
  static std::thread spawn(size_t batch_size, uint64_t max_batch_delay,
                           ChannelPtr<Transaction> rx_transaction,
                           ChannelPtr<QuorumWaiterMessage> tx_message,
                           std::vector<std::pair<PublicKey, Address>>
                               mempool_addresses,
                           std::shared_ptr<std::atomic<bool>> stop,
                           std::shared_ptr<IngressGate> gate = nullptr);
};

}  // namespace mempool
}  // namespace hotstuff

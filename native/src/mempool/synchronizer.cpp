#include "mempool/synchronizer.hpp"

#include <chrono>
#include <map>
#include <thread>

#include "common/log.hpp"
#include "network/simple_sender.hpp"

namespace hotstuff {
namespace mempool {

namespace {
constexpr auto kTimerResolution = std::chrono::milliseconds(1000);

uint64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}
}  // namespace

std::thread Synchronizer::spawn(PublicKey name, Committee committee, Store store,
                         Round gc_depth, uint64_t sync_retry_delay,
                         size_t sync_retry_nodes,
                         ChannelPtr<ConsensusMempoolMessage> rx_message) {
  return std::thread([name, committee = std::move(committee), store, gc_depth,
               sync_retry_delay, sync_retry_nodes, rx_message]() mutable {
    set_thread_name("mp-sync");
    SimpleSender network;
    // Internal completion channel: notify_read callbacks push the digest
    // that arrived (replacing the reference's FuturesUnordered stream).
    // Unbounded so store-thread callbacks never block and no arrival is
    // dropped (a lost arrival would leave a stale pending entry retried
    // via lucky_broadcast forever).
    auto arrived = make_channel<Digest>(SIZE_MAX);
    // digest -> (round it was requested at, request timestamp ms)
    std::map<Digest, std::pair<Round, uint64_t>> pending;
    Round round = 0;
    auto deadline = std::chrono::steady_clock::now() + kTimerResolution;

    while (true) {
      // Drain arrivals without blocking.
      Digest done;
      while (arrived->recv_until(
                 &done, std::chrono::steady_clock::now()) ==
             RecvStatus::kOk) {
        pending.erase(done);
      }

      ConsensusMempoolMessage msg;
      auto status = rx_message->recv_until(&msg, deadline);
      if (status == RecvStatus::kClosed) return;
      if (status == RecvStatus::kTimeout) {
        // Retry stale requests via lucky broadcast
        // (mempool/src/synchronizer.rs:175-206).
        std::vector<Digest> retry;
        uint64_t now = now_ms();
        for (const auto& [digest, info] : pending) {
          if (info.second + sync_retry_delay < now) {
            LOG_DEBUG("mempool::synchronizer")
                << "Requesting sync for batch " << digest.to_base64()
                << " (retry)";
            retry.push_back(digest);
          }
        }
        if (!retry.empty()) {
          std::vector<Address> addresses;
          for (const auto& [_, addr] : committee.broadcast_addresses(name)) {
            addresses.push_back(addr);
          }
          Bytes serialized =
              MempoolMessage::make_batch_request(retry, name).serialize();
          network.lucky_broadcast(addresses, serialized, sync_retry_nodes);
        }
        deadline = std::chrono::steady_clock::now() + kTimerResolution;
        continue;
      }

      switch (msg.kind) {
        case ConsensusMempoolMessage::Kind::kSynchronize: {
          uint64_t now = now_ms();
          std::vector<Digest> missing;
          for (const auto& digest : msg.digests) {
            if (pending.count(digest)) continue;
            // graftdag: consensus prefetch no longer reads the store on
            // the core thread — the possession check lives here instead.
            // A blocking read on this thread only delays background sync,
            // never block processing.  Skipping present digests entirely
            // (no pending entry, no network request) keeps already-held
            // certified batches free: any waiter's own notify_read fires
            // immediately for existing keys.
            if (store.read(digest.to_bytes())) continue;
            missing.push_back(digest);
            LOG_DEBUG("mempool::synchronizer")
                << "Requesting sync for batch " << digest.to_base64();
            pending.emplace(digest, std::make_pair(round, now));
            store.notify_read(digest.to_bytes())
                .on_ready([arrived, digest](const Bytes&) {
                  arrived->send(digest);  // unbounded: never blocks
                });
          }
          if (missing.empty()) break;
          Bytes serialized =
              MempoolMessage::make_batch_request(missing, name).serialize();
          // graftdag: when consensus knows WHO certified the batch (the
          // certificate's signers), fan the first request across up to
          // sync_retry_nodes of them — every holder signed for stored
          // bytes, so any one honest signer can serve us, and we no
          // longer depend on the (possibly crashed) block author alone.
          if (!msg.holders.empty()) {
            size_t fan = sync_retry_nodes ? sync_retry_nodes : 1;
            size_t sent = 0;
            for (const auto& holder : msg.holders) {
              if (sent >= fan) break;
              if (holder == name) continue;  // we already know it's missing
              auto addr = committee.mempool_address(holder);
              if (!addr) continue;
              network.send(*addr, Bytes(serialized));
              ++sent;
            }
            if (sent > 0) break;
            // Every holder unknown/self: fall through to the author.
          }
          auto address = committee.mempool_address(msg.target);
          if (!address) {
            LOG_ERROR("mempool::synchronizer")
                << "consensus asked us to sync with an unknown node: "
                << msg.target.to_base64();
            break;
          }
          network.send(*address, std::move(serialized));
          break;
        }
        case ConsensusMempoolMessage::Kind::kCleanup: {
          round = msg.round;
          if (round < gc_depth) break;
          Round gc_round = round - gc_depth;
          for (auto it = pending.begin(); it != pending.end();) {
            if (it->second.first <= gc_round) {
              it = pending.erase(it);
            } else {
              ++it;
            }
          }
          break;
        }
      }
    }
  });
}

}  // namespace mempool
}  // namespace hotstuff

#include "mempool/helper.hpp"

#include <thread>

#include "common/log.hpp"
#include "network/simple_sender.hpp"

namespace hotstuff {
namespace mempool {

std::thread Helper::spawn(
    Committee committee, Store store,
    ChannelPtr<std::pair<std::vector<Digest>, PublicKey>> rx_request) {
  return std::thread([committee = std::move(committee), store, rx_request]() mutable {
    set_thread_name("mp-helper");
    SimpleSender network;
    while (auto req = rx_request->recv()) {
      const auto& [digests, origin] = *req;
      auto address = committee.mempool_address(origin);
      if (!address) {
        LOG_WARN("mempool::helper")
            << "Received batch request from unknown authority: "
            << origin.to_base64();
        continue;
      }
      for (const auto& digest : digests) {
        auto value = store.read(digest.to_bytes());
        if (value) {
          // Stored value is already a serialized MempoolMessage::Batch.
          network.send(*address, std::move(*value));
        }
      }
    }
  });
}

}  // namespace mempool
}  // namespace hotstuff

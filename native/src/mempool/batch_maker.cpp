#include "mempool/batch_maker.hpp"

#include "common/log.hpp"
#include "mempool/tx_frame.hpp"

namespace hotstuff {
namespace mempool {

namespace {

void seal_and_send(Batch* current, size_t* current_size,
                   ReliableSender* network,
                   const std::vector<std::pair<PublicKey, Address>>& peers,
                   Channel<QuorumWaiterMessage>* tx_message) {
  size_t size = *current_size;

  // Sample txs start with 0; their id is the next 8 bytes big-endian
  // (node/src/client.rs:126-133 convention, kept for the log parser).
  // Signed frames (graftingress, first byte kTxFrameVersion=2) carry the
  // same inner format at the payload offset: marker 0 keeps the sample
  // id accounting, marker 2 is the forged-marker — a forged tx reaching
  // a sealed batch is the failure the admission-verify stage exists to
  // prevent, and the log parser treats the line as a hard error on
  // verify-ingress runs.
  std::vector<uint64_t> tx_ids;
  std::vector<uint64_t> forged_ids;
  for (const auto& tx : *current) {
    if (!tx.empty() && tx[0] == 0 && tx.size() > 8) {
      uint64_t id = 0;
      for (int i = 0; i < 8; i++) id = (id << 8) | tx[1 + i];
      tx_ids.push_back(id);
    } else if (!tx.empty() && tx[0] == kTxFrameVersion &&
               tx.size() > kTxFrameHeaderLen + 8) {
      uint8_t marker = tx[kTxFrameHeaderLen];
      if (marker != kTxMarkerSample && marker != kTxMarkerForged) continue;
      uint64_t id = 0;
      for (int i = 0; i < 8; i++) {
        id = (id << 8) | tx[kTxFrameHeaderLen + 1 + i];
      }
      (marker == kTxMarkerSample ? tx_ids : forged_ids).push_back(id);
    }
  }

  Batch batch;
  batch.swap(*current);
  *current_size = 0;
  Bytes serialized = MempoolMessage::make_batch(std::move(batch)).serialize();

  // NOTE: These log entries are used to compute performance
  // (hotstuff_tpu/harness/logs.py mines them; format frozen).
  Digest digest = sha512_digest(serialized);
  for (uint64_t id : tx_ids) {
    LOG_INFO("mempool::batch_maker")
        << "Batch " << digest.to_base64() << " contains sample tx " << id;
  }
  for (uint64_t id : forged_ids) {
    LOG_WARN("mempool::batch_maker")
        << "Batch " << digest.to_base64() << " contains forged tx " << id;
  }
  LOG_INFO("mempool::batch_maker")
      << "Batch " << digest.to_base64() << " contains " << size << " B";

  std::vector<Address> addresses;
  addresses.reserve(peers.size());
  for (const auto& [_, addr] : peers) addresses.push_back(addr);
  auto handlers = network->broadcast(addresses, serialized);

  QuorumWaiterMessage msg;
  msg.batch = std::move(serialized);
  for (size_t i = 0; i < peers.size(); i++) {
    msg.handlers.emplace_back(peers[i].first, std::move(handlers[i]));
  }
  tx_message->send(std::move(msg));
}

}  // namespace

std::thread BatchMaker::spawn(
    size_t batch_size, uint64_t max_batch_delay,
    ChannelPtr<Transaction> rx_transaction,
    ChannelPtr<QuorumWaiterMessage> tx_message,
    std::vector<std::pair<PublicKey, Address>> mempool_addresses,
    std::shared_ptr<std::atomic<bool>> stop,
    std::shared_ptr<IngressGate> gate) {
  return std::thread([batch_size, max_batch_delay, rx_transaction, tx_message,
               peers = std::move(mempool_addresses),
               stop = std::move(stop), gate = std::move(gate)] {
    set_thread_name("batch-maker");
    ReliableSender network(stop);
    Batch current;
    size_t current_size = 0;
    auto delay = std::chrono::milliseconds(max_batch_delay);
    auto deadline = std::chrono::steady_clock::now() + delay;

    while (true) {
      Transaction tx;
      auto status = rx_transaction->recv_until(&tx, deadline);
      if (status == RecvStatus::kClosed) return;
      if (status == RecvStatus::kTimeout) {
        if (!current.empty()) {
          seal_and_send(&current, &current_size, &network, peers,
                        tx_message.get());
        }
        deadline = std::chrono::steady_clock::now() + delay;
        continue;
      }
      // Unwind the ingress gate's backlog accounting the moment the tx
      // leaves the channel: a paused tx receiver resumes off this edge
      // (low-water mark), so it must track actual drain, not sealing.
      if (gate) gate->on_consumed(tx.size());
      current_size += tx.size();
      current.push_back(std::move(tx));
      if (current_size >= batch_size) {
        seal_and_send(&current, &current_size, &network, peers,
                      tx_message.get());
        deadline = std::chrono::steady_clock::now() + delay;
      }
    }
  });
}

}  // namespace mempool
}  // namespace hotstuff

// graftingress admission-verify stage: the signed-transaction verifier
// between IngressGate::admit and the BatchMaker.
//
// Admitted signed txs (tx_frame.hpp) accumulate into QC-shaped batches
// on a dedicated worker thread and verify through the sidecar BULK lane
// (Signature::verify_batch_multi_async_masked → OP_VERIFY_BULK, tagged
// with the pinned graftingress context so the sidecar's OP_STATS can
// tell ingress-fed bulk records from offchain bench filler).  The
// degradation ladder mirrors the consensus paths:
//
//   * device mask        -> per-tx verdicts: valid txs forward to the
//     BatchMaker (the ONLY way client bytes reach a sealed batch when
//     --verify-ingress is on; the forward carries the
//     `// VERIFIES(tx-signature)` taint gate), forged txs are counted
//     and dropped before they can reach a block;
//   * OP_BUSY            -> bounded paced retry off the sidecar's
//     retry-after hint, then shed the whole batch with a client-visible
//     "BUSY <ms>" reply (the same backoff contract as the ingress gate);
//   * breaker open / no async budget / transport failure -> host verify
//     loop (OpenSSL), same per-tx verdicts — overload degrades goodput,
//     never admits an unverified tx.
//
// Threading: enqueue() runs on the reactor thread (counter + try_send,
// never blocks); everything else runs on the single worker thread.  The
// retained ConnectionWriter copies are safe off-thread: EventLoop::send
// looks up the connection id under the loop and is a no-op for stale
// ids (see receiver.hpp).
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <optional>
#include <thread>

#include "common/channel.hpp"
#include "mempool/ingress.hpp"
#include "mempool/messages.hpp"
#include "network/receiver.hpp"

namespace hotstuff {
namespace mempool {

class TxVerifier {
 public:
  struct Config {
    size_t batch = 64;             // records per admission-verify launch
    uint64_t max_delay_ms = 20;    // seal a partial batch after this
    size_t queue_budget = 4096;    // txs queued ahead of verify
    int busy_retries = 2;          // bounded OP_BUSY paced retries
    uint32_t busy_retry_cap_ms = 500;  // clamp on the sidecar's hint
  };

  // One admitted signed tx awaiting verification.  The writer is a
  // retained ConnectionWriter copy used only for the client-visible
  // BUSY shed (absent in tests that drive frames without a connection).
  struct PendingTx {
    Bytes frame;
    std::optional<ConnectionWriter> writer;
  };

  // `tx_batch_maker` receives verified frames; `gate` is unwound for
  // every tx that does NOT reach the BatchMaker (forged / shed /
  // dropped-at-teardown) — forwarded txs keep the existing drain-side
  // accounting in BatchMaker.
  static std::shared_ptr<TxVerifier> spawn(
      Config cfg, ChannelPtr<Transaction> tx_batch_maker,
      std::shared_ptr<IngressGate> gate);

  // Reactor thread: queue one structurally valid signed frame for
  // verification.  Returns false when the verify queue is over budget —
  // the caller replies BUSY with *retry_ms and unwinds the gate.
  bool enqueue(Bytes frame, std::optional<ConnectionWriter> writer,
               uint32_t* retry_ms);

  // Close the queue and join the worker; pending txs are dropped with
  // their gate accounting unwound.  Idempotent; the destructor calls it.
  void stop();
  ~TxVerifier();

  // -- telemetry (any thread; the node METRICS sampler reads these) -------
  uint64_t verified() const { return verified_.load(std::memory_order_relaxed); }
  uint64_t forged() const { return forged_.load(std::memory_order_relaxed); }
  uint64_t shed() const { return shed_.load(std::memory_order_relaxed); }
  uint64_t busy_retries() const {
    return busy_retries_.load(std::memory_order_relaxed);
  }
  uint64_t host_fallbacks() const {
    return host_fallbacks_.load(std::memory_order_relaxed);
  }
  size_t queue_depth() const { return depth_.load(std::memory_order_relaxed); }

 private:
  TxVerifier(Config cfg, ChannelPtr<Transaction> tx_batch_maker,
             std::shared_ptr<IngressGate> gate);

  void run_();
  void settle_batch_(std::vector<PendingTx>* batch);
  void forward_admitted(Bytes frame);
  void reject_forged_(PendingTx* tx);
  void shed_busy_(std::vector<PendingTx>* batch, uint32_t retry_ms);

  const Config cfg_;  // SHARED_OK(immutable after construction)
  ChannelPtr<PendingTx> queue_;          // SHARED_OK(Channel self-syncs)
  ChannelPtr<Transaction> tx_batch_maker_;  // SHARED_OK(Channel self-syncs)
  std::shared_ptr<IngressGate> gate_;    // SHARED_OK(IngressGate self-syncs)
  std::atomic<uint64_t> verified_{0};
  std::atomic<uint64_t> forged_{0};
  std::atomic<uint64_t> shed_{0};
  std::atomic<uint64_t> busy_retries_{0};
  std::atomic<uint64_t> host_fallbacks_{0};
  std::atomic<size_t> depth_{0};
  std::atomic<bool> stopped_{false};
  std::thread worker_;
};

}  // namespace mempool
}  // namespace hotstuff

// graftingress signed-transaction frame: the client→mempool wire format
// for per-user Ed25519-authenticated transactions, pinned here as the
// single C++ source of truth.  The Python twin is
// hotstuff_tpu/crypto/txsign.py and graftlint's wire cross-checker
// (analysis/wirecheck.py, rule `txframe-mismatch`) asserts the constant
// sets match — edit BOTH sides or the gate fails.
//
// Frame layout (version 2, all integers big-endian):
//
//   offset  len  field
//   ------  ---  -----------------------------------------------------
//        0    1  version        (kTxFrameVersion = 2; legacy unsigned
//                                txs start with 0=sample / 1=filler, so
//                                the first byte discriminates)
//        1   32  user pubkey    (Ed25519, derived from --seed + user id)
//       33    8  nonce          (client-local monotonic counter)
//       41    4  payload_len    (must equal frame_len - kTxFrameOverhead)
//       45    n  payload        (legacy inner tx format: marker u8 +
//                                id u64 BE + padding; marker 0=sample,
//                                1=filler, 2=forged-marker for the A/B
//                                forgery drill)
//     45+n   64  signature      (Ed25519 over the signing preimage)
//
// Signing preimage: SHA-512/32 over (kTxSignDomain ‖ frame[0 .. 45+n)),
// i.e. the domain-separated frame with the signature stripped.  The
// 32-byte digest is the message handed to Ed25519 — the same
// (digest, pk, sig) record shape every other verify path in this repo
// ships to the sidecar, so admission batches ride OP_VERIFY_BULK
// unchanged.
//
// Per-user keys: seed32 = SHA-512(kTxKeyDomain ‖ seed u64 BE ‖
// user u64 BE)[:32] → Ed25519 keypair.  Deterministic on both sides, so
// a verifier fixture can recompute any user's pubkey without key
// distribution, and a 1e6-user client derives on first arrival behind a
// bounded LRU (TxKeyring below) instead of materializing 1e6 keypairs.
#pragma once

#include <cstdint>
#include <cstring>
#include <list>
#include <unordered_map>
#include <utility>

#include "common/bytes.hpp"
#include "crypto/crypto.hpp"

namespace hotstuff {
namespace mempool {

constexpr uint8_t kTxFrameVersion = 2;
constexpr size_t kTxPkLen = 32;
constexpr size_t kTxNonceLen = 8;
constexpr size_t kTxLenLen = 4;
constexpr size_t kTxSigLen = 64;
// version + pubkey + nonce + payload_len header ahead of the payload.
constexpr size_t kTxFrameHeaderLen = 1 + kTxPkLen + kTxNonceLen + kTxLenLen;
// Total non-payload bytes in a signed frame.
constexpr size_t kTxFrameOverhead = kTxFrameHeaderLen + kTxSigLen;
static_assert(kTxFrameHeaderLen == 45, "signed-tx header drifted");
static_assert(kTxFrameOverhead == 109, "signed-tx overhead drifted");
// Payload bounds: the legacy inner format needs marker + u64 id; the
// upper bound keeps one admission batch's memory footprint sane (and is
// far under the 8 MiB network frame cap).
constexpr size_t kTxMinPayload = 9;
constexpr size_t kTxMaxPayload = 1u << 20;
constexpr uint8_t kTxMarkerSample = 0;
constexpr uint8_t kTxMarkerFiller = 1;
constexpr uint8_t kTxMarkerForged = 2;

// Domain separators (preimage + key derivation) and the sidecar context
// tag for admission-verify batches.  The ctx tag is exactly kCtxLen(32)
// chars and deliberately NON-zero: protocol.py decodes an all-zero ctx
// as "no tag", so a zero sentinel would be invisible to the sidecar's
// ingress-vs-offchain bulk class mix accounting.
constexpr char kTxSignDomain[] = "graftingress-tx-v1";
constexpr char kTxKeyDomain[] = "graftingress-key-v1";
constexpr char kTxIngressCtxTag[] = "graftingress-tx-admission-ctx-v1";
static_assert(sizeof(kTxIngressCtxTag) == 33,
              "ingress ctx tag must be exactly 32 bytes");

inline Digest tx_ingress_ctx() {
  Digest d;
  std::memcpy(d.data.data(), kTxIngressCtxTag, 32);
  return d;
}

// Zero-copy view over a structurally valid signed frame.  Pointers alias
// the caller's buffer.
struct SignedTxView {
  const uint8_t* pk = nullptr;       // kTxPkLen bytes
  uint64_t nonce = 0;
  const uint8_t* payload = nullptr;  // payload_len bytes
  size_t payload_len = 0;
  const uint8_t* sig = nullptr;      // kTxSigLen bytes
};

enum class TxParse {
  kOk,
  kNotSigned,       // first byte is not kTxFrameVersion (legacy tx)
  kTruncated,       // shorter than overhead + min payload
  kBadPayloadLen,   // declared length out of bounds or ≠ frame remainder
};

// Structural parse of one client frame.  Never throws, never reads past
// `len`; the admission path feeds it raw client bytes (fuzz target).
inline TxParse parse_signed_tx(const uint8_t* data, size_t len,
                               SignedTxView* out) {
  if (len == 0 || data[0] != kTxFrameVersion) return TxParse::kNotSigned;
  if (len < kTxFrameOverhead + kTxMinPayload) return TxParse::kTruncated;
  uint64_t nonce = 0;
  for (size_t i = 0; i < kTxNonceLen; i++) {
    nonce = (nonce << 8) | data[1 + kTxPkLen + i];
  }
  uint32_t plen = 0;
  for (size_t i = 0; i < kTxLenLen; i++) {
    plen = (plen << 8) | data[1 + kTxPkLen + kTxNonceLen + i];
  }
  if (plen < kTxMinPayload || plen > kTxMaxPayload) {
    return TxParse::kBadPayloadLen;
  }
  // The declared payload length must exactly account for the frame: a
  // lying length (short or long) is malformed, not silently truncated.
  if (size_t(plen) + kTxFrameOverhead != len) return TxParse::kBadPayloadLen;
  if (out != nullptr) {
    out->pk = data + 1;
    out->nonce = nonce;
    out->payload = data + kTxFrameHeaderLen;
    out->payload_len = plen;
    out->sig = data + kTxFrameHeaderLen + plen;
  }
  return TxParse::kOk;
}

// Signing preimage digest over frame[0 .. signed_len) where signed_len =
// kTxFrameHeaderLen + payload_len (everything but the signature).
inline Digest tx_sign_digest(const uint8_t* frame, size_t signed_len) {
  DigestBuilder b;
  b.update(reinterpret_cast<const uint8_t*>(kTxSignDomain),
           sizeof(kTxSignDomain) - 1);
  b.update(frame, signed_len);
  return b.finalize();
}

// Deterministic per-user key seed: SHA-512/32(domain ‖ seed ‖ user),
// integers big-endian.
inline std::array<uint8_t, 32> tx_user_seed(uint64_t seed, uint64_t user) {
  uint8_t buf[16];
  for (int i = 0; i < 8; i++) buf[i] = uint8_t(seed >> (56 - 8 * i));
  for (int i = 0; i < 8; i++) buf[8 + i] = uint8_t(user >> (56 - 8 * i));
  DigestBuilder b;
  b.update(reinterpret_cast<const uint8_t*>(kTxKeyDomain),
           sizeof(kTxKeyDomain) - 1);
  b.update(buf, sizeof(buf));
  return b.finalize().data;
}

inline KeyPair tx_user_keypair(uint64_t seed, uint64_t user) {
  return keypair_from_seed(tx_user_seed(seed, user));
}

// Bounded LRU of expanded per-user keypairs: derive-on-first-arrival so
// a 1e6-user client only ever holds `capacity` expanded keys.  Single
// threaded (the client's send loop / the verifier fixture own one each).
class TxKeyring {
 public:
  explicit TxKeyring(uint64_t seed, size_t capacity = 4096)
      : seed_(seed), capacity_(capacity == 0 ? 1 : capacity) {}

  const KeyPair& get(uint64_t user) {
    auto it = map_.find(user);
    if (it != map_.end()) {
      lru_.splice(lru_.begin(), lru_, it->second.second);
      return it->second.first;
    }
    if (map_.size() >= capacity_) {
      map_.erase(lru_.back());
      lru_.pop_back();
    }
    lru_.push_front(user);
    auto [ins, _] =
        map_.emplace(user, std::make_pair(tx_user_keypair(seed_, user),
                                          lru_.begin()));
    derivations_++;
    return ins->second.first;
  }

  size_t size() const { return map_.size(); }
  uint64_t derivations() const { return derivations_; }

 private:
  uint64_t seed_;
  size_t capacity_;
  uint64_t derivations_ = 0;
  std::list<uint64_t> lru_;
  std::unordered_map<uint64_t,
                     std::pair<KeyPair, std::list<uint64_t>::iterator>>
      map_;
};

// Build one signed frame: header + payload + signature over the
// preimage digest.  `flip_sig_bit` forges the signature (the seeded
// forgery mix in the A/B drill) while keeping the structure valid — a
// forged frame must parse cleanly and die at verify, not at parse.
inline Bytes build_signed_tx(const KeyPair& kp, uint64_t nonce,
                             const uint8_t* payload, size_t payload_len,
                             bool flip_sig_bit = false) {
  Bytes frame(kTxFrameHeaderLen + payload_len + kTxSigLen);
  frame[0] = kTxFrameVersion;
  std::memcpy(frame.data() + 1, kp.name.data.data(), kTxPkLen);
  for (size_t i = 0; i < kTxNonceLen; i++) {
    frame[1 + kTxPkLen + i] = uint8_t(nonce >> (56 - 8 * i));
  }
  for (size_t i = 0; i < kTxLenLen; i++) {
    frame[1 + kTxPkLen + kTxNonceLen + i] =
        uint8_t(uint32_t(payload_len) >> (24 - 8 * i));
  }
  std::memcpy(frame.data() + kTxFrameHeaderLen, payload, payload_len);
  Digest d = tx_sign_digest(frame.data(), kTxFrameHeaderLen + payload_len);
  Signature sig = Signature::sign(d, kp.secret);
  std::memcpy(frame.data() + kTxFrameHeaderLen + payload_len, sig.data.data(),
              kTxSigLen);
  if (flip_sig_bit) frame[kTxFrameHeaderLen + payload_len] ^= 0x01;
  return frame;
}

}  // namespace mempool
}  // namespace hotstuff

// Client load-model tests (graftsurge): the heavy-tailed multi-user
// open-loop generator (node/rate_pacer.hpp UserLoadModel) driven on a
// virtual clock — seeded determinism, aggregate rate honoring --rate,
// heavy-tailed inter-arrival shape, per-user BUSY backoff, the diurnal
// profile's mean-1 invariant — plus the legacy RatePacer exactness.
#include <cmath>
#include <vector>

#include "node/rate_pacer.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;

namespace {

// Step a model through `seconds` of virtual time in `tick_s` ticks,
// returning total arrivals.
uint64_t drive(UserLoadModel* m, double from_s, double to_s,
               double tick_s = 0.05) {
  uint64_t total = 0;
  for (double t = from_s + tick_s; t <= to_s + 1e-9; t += tick_s) {
    total += m->arrivals(t);
  }
  return total;
}

UserLoadModel::Options base_opts() {
  UserLoadModel::Options opt;
  opt.rate = 2000;
  opt.users = 400;
  opt.seed = 42;
  opt.sigma = 1.5;
  return opt;
}

}  // namespace

TEST(rate_pacer_is_exact_at_truncating_rates) {
  RatePacer pacer{39, 20};
  uint64_t total = 0;
  for (int i = 0; i < 20; i++) total += pacer.next_burst();
  CHECK(total == 39);
}

TEST(load_model_is_deterministic_in_the_seed) {
  UserLoadModel a(base_opts());
  UserLoadModel b(base_opts());
  for (double t = 0.05; t <= 5.0; t += 0.05) {
    CHECK(a.arrivals(t) == b.arrivals(t));
  }
  UserLoadModel::Options other = base_opts();
  other.seed = 43;
  UserLoadModel c(base_opts());
  UserLoadModel d(other);
  drive(&c, 0.0, 5.0);
  drive(&d, 0.0, 5.0);
  CHECK(c.sent() != d.sent());  // a different world, not a constant
}

TEST(load_model_aggregate_honors_rate_on_virtual_clock) {
  // 400 heavy-tailed users at aggregate 2000 tx/s over 30 virtual
  // seconds: the mean-1 multiplier construction must keep the total
  // within a few percent of rate * seconds despite per-user burstiness.
  UserLoadModel m(base_opts());
  uint64_t total = drive(&m, 0.0, 30.0);
  CHECK(total > 54'000);   // -10%
  CHECK(total < 66'000);   // +10%
}

TEST(load_model_pareto_aggregate_honors_rate) {
  UserLoadModel::Options opt = base_opts();
  opt.dist = ArrivalDist::kPareto;
  opt.alpha = 2.5;
  UserLoadModel m(opt);
  uint64_t total = drive(&m, 0.0, 30.0);
  CHECK(total > 54'000);
  CHECK(total < 66'000);
}

TEST(load_model_gaps_are_heavy_tailed) {
  // Sample the inter-arrival multiplier stream directly: the lognormal
  // shape at sigma=1.5 has CV ~ 2.9 — far above the CV=1 of the
  // exponential arrivals a Poisson (let alone constant-rate) client
  // would produce.  Mean must still track 1/user-rate.
  UserLoadModel::Options opt;
  opt.rate = 100;
  opt.users = 1;
  opt.seed = 7;
  opt.sigma = 1.5;
  UserLoadModel m(opt);
  const int n = 20'000;
  double sum = 0.0;
  double sum2 = 0.0;
  for (int i = 0; i < n; i++) {
    double g = m.sample_gap_for_test(0.0);
    sum += g;
    sum2 += g * g;
  }
  double mean = sum / n;
  double var = sum2 / n - mean * mean;
  double cv = std::sqrt(var) / mean;
  CHECK(mean > 0.0085);  // user mean gap 10 ms +-15%
  CHECK(mean < 0.0115);
  CHECK(cv > 1.2);       // heavy tail (true CV ~ 2.9)
}

TEST(load_model_busy_backoff_defers_then_recovers) {
  UserLoadModel::Options opt;
  opt.rate = 1000;
  opt.users = 20;
  opt.seed = 3;
  UserLoadModel m(opt);
  uint64_t before = drive(&m, 0.0, 1.0, 0.01);
  CHECK(before > 0);
  m.busy(1.0, 0.5);
  // Inside the busy window every due arrival defers (jittered
  // exponential per-user retry) — nothing is sent, nothing is dropped.
  uint64_t during = drive(&m, 1.0, 1.5, 0.01);
  CHECK(during == 0);
  CHECK(m.deferred() > 0);
  CHECK(m.busy_events() == 1);
  // Users come back after their backoff; the open loop recovers.
  uint64_t after = drive(&m, 1.5, 6.0, 0.01);
  CHECK(after > 0);
}

TEST(load_model_diurnal_profile_means_one) {
  UserLoadModel::Options opt = base_opts();
  opt.diurnal_amp = 0.5;
  opt.diurnal_period_s = 100.0;
  UserLoadModel m(opt);
  double acc = 0.0;
  const int steps = 1000;
  for (int i = 0; i < steps; i++) {
    acc += m.profile(100.0 * i / steps);
  }
  CHECK(std::fabs(acc / steps - 1.0) < 0.01);  // mean 1 over a period
  CHECK(m.profile(25.0) > 1.4);                // peak ~ 1 + amp
  CHECK(m.profile(75.0) < 0.6);                // trough ~ 1 - amp
  // The ramp bends the aggregate but not its mean: 2 whole periods of
  // diurnal load still deliver ~rate * seconds.
  uint64_t total = drive(&m, 0.0, 200.0, 0.05);
  CHECK(total > 360'000);  // 2000 tx/s * 200 s -10%
  CHECK(total < 440'000);
}

int main() { return run_all(); }

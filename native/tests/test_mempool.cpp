// Mempool tests (mempool/src/tests/ analogue): batch sealing by size and by
// timeout, quorum waiting with fake ACKing peers, processor hash+store,
// synchronizer request emission, helper batch reply, and the full pipeline
// client-tx -> digest.
#include <chrono>
#include <thread>

#include "crypto/sidecar_client.hpp"
#include "mempool/batch_maker.hpp"
#include "mempool/helper.hpp"
#include "mempool/mempool.hpp"
#include "mempool/processor.hpp"
#include "mempool/quorum_waiter.hpp"
#include "mempool/synchronizer.hpp"
#include "mempool/tx_frame.hpp"
#include "mempool/tx_verify.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;
using namespace hotstuff::mempool;

namespace {

// Listeners for all 3 peer mempool addresses that ACK one batch each.
std::vector<std::thread> peer_listeners(const Committee& committee,
                                        const PublicKey& myself,
                                        ChannelPtr<Bytes> delivered) {
  std::vector<std::thread> threads;
  for (const auto& [name, addr] : committee.broadcast_addresses(myself)) {
    auto l = Listener::bind(addr);
    if (!l) throw std::runtime_error("bind failed: " + addr.str());
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  return threads;
}

}  // namespace

TEST(batch_maker_seals_by_size) {
  auto committee = mempool_committee(7100);
  auto myself = keys()[0].name;
  auto delivered = make_channel<Bytes>();
  auto threads = peer_listeners(committee, myself, delivered);

  auto rx_tx = make_channel<Transaction>();
  auto tx_msg = make_channel<QuorumWaiterMessage>();
  auto actor = BatchMaker::spawn(
      /*batch_size=*/100, /*max_batch_delay=*/60'000, rx_tx, tx_msg,
      committee.broadcast_addresses(myself),
      std::make_shared<std::atomic<bool>>(false));
  Transaction tx(60, 5);  // 60 bytes: two txs cross the 100-byte seal point
  rx_tx->send(tx);
  rx_tx->send(tx);
  auto msg = tx_msg->recv();
  CHECK(msg.has_value());
  auto m = MempoolMessage::deserialize(msg->batch);
  CHECK(m.kind == MempoolMessage::Kind::kBatch);
  CHECK(m.batch.size() == 2);
  CHECK(m.batch[0] == tx);
  CHECK(msg->handlers.size() == 3);
  for (auto& t : threads) t.join();
  rx_tx->close();
  tx_msg->close();
  actor.join();
}

TEST(batch_maker_seals_by_timeout) {
  auto committee = mempool_committee(7200);
  auto myself = keys()[0].name;
  auto delivered = make_channel<Bytes>();
  auto threads = peer_listeners(committee, myself, delivered);

  auto rx_tx = make_channel<Transaction>();
  auto tx_msg = make_channel<QuorumWaiterMessage>();
  auto actor = BatchMaker::spawn(
      /*batch_size=*/1'000'000, /*max_batch_delay=*/50, rx_tx, tx_msg,
      committee.broadcast_addresses(myself),
      std::make_shared<std::atomic<bool>>(false));
  rx_tx->send(Transaction(10, 1));
  auto msg = tx_msg->recv();
  CHECK(msg.has_value());
  auto m = MempoolMessage::deserialize(msg->batch);
  CHECK(m.batch.size() == 1);
  for (auto& t : threads) t.join();
  rx_tx->close();
  tx_msg->close();
  actor.join();
}

TEST(quorum_waiter_waits_for_stake) {
  auto committee = mempool_committee(7300);
  auto myself = keys()[0].name;
  auto rx_msg = make_channel<QuorumWaiterMessage>();
  auto tx_batch = make_channel<ProcessorMessage>();
  auto stop = std::make_shared<std::atomic<bool>>(false);
  auto actor = QuorumWaiter::spawn(committee, myself, keys()[0].secret,
                                   /*dag=*/false, rx_msg, tx_batch, stop);

  QuorumWaiterMessage msg;
  msg.batch = Bytes{1, 2, 3};
  std::vector<CancelHandler> handlers;
  for (const auto& [name, _] : committee.broadcast_addresses(myself)) {
    CancelHandler h;
    handlers.push_back(h);
    msg.handlers.emplace_back(name, h);
  }
  rx_msg->send(std::move(msg));

  // With only our stake (1) nothing is delivered yet; two ACKs reach 2f+1=3.
  ProcessorMessage out;
  CHECK(tx_batch->recv_until(&out, std::chrono::steady_clock::now() +
                                       std::chrono::milliseconds(100)) ==
        RecvStatus::kTimeout);
  handlers[0].set(to_bytes("Ack"));
  handlers[1].set(to_bytes("Ack"));
  auto got = tx_batch->recv();
  CHECK(got.has_value());
  CHECK(got->batch == (Bytes{1, 2, 3}));
  CHECK(!got->cert.has_value());  // legacy mode: no certificate
  CHECK(got->forward);
  rx_msg->close();
  tx_batch->close();
  actor.join();
}

TEST(quorum_waiter_ignores_cancelled_acks) {
  // Empty-byte fulfilment means CANCELLED (sender teardown / full
  // backlog), not a peer ACK: counting it would certify batch
  // availability for peers that never received the batch.
  auto committee = mempool_committee(7320);
  auto myself = keys()[0].name;
  auto rx_msg = make_channel<QuorumWaiterMessage>();
  auto tx_batch = make_channel<ProcessorMessage>();
  auto stop = std::make_shared<std::atomic<bool>>(false);
  auto actor = QuorumWaiter::spawn(committee, myself, keys()[0].secret,
                                   /*dag=*/false, rx_msg, tx_batch, stop);

  QuorumWaiterMessage msg;
  msg.batch = Bytes{9, 9};
  std::vector<CancelHandler> handlers;
  for (const auto& [name, _] : committee.broadcast_addresses(myself)) {
    CancelHandler h;
    handlers.push_back(h);
    msg.handlers.emplace_back(name, h);
  }
  rx_msg->send(std::move(msg));

  // One CANCELLED send (empty bytes) plus one real ACK is stake 2 — the
  // pre-fix bug would count the cancel and hit quorum (3) here.
  CHECK(handlers.size() == 3);  // 4-node committee: 3 peers
  handlers[0].set(Bytes{});
  handlers[1].set(to_bytes("Ack"));
  ProcessorMessage out;
  CHECK(tx_batch->recv_until(&out, std::chrono::steady_clock::now() +
                                       std::chrono::milliseconds(200)) ==
        RecvStatus::kTimeout);
  // A second real ACK reaches quorum (our stake 1 + 2 = 2f+1 = 3).
  handlers[2].set(to_bytes("Ack"));
  auto got = tx_batch->recv();
  CHECK(got.has_value());
  CHECK(got->batch == (Bytes{9, 9}));
  rx_msg->close();
  tx_batch->close();
  actor.join();
}

TEST(processor_hashes_and_stores) {
  Store store = Store::open("");
  auto rx_batch = make_channel<ProcessorMessage>();
  auto tx_digest = make_channel<PayloadRef>();
  auto actor = Processor::spawn(store, rx_batch, tx_digest);
  Bytes batch{7, 7, 7, 7};
  ProcessorMessage pm;
  pm.batch = batch;
  rx_batch->send(std::move(pm));
  auto ref = tx_digest->recv();
  CHECK(ref.has_value());
  CHECK(ref->digest == sha512_digest(batch));
  CHECK(!ref->cert.has_value());
  auto stored = store.read(ref->digest.to_bytes());
  CHECK(stored.has_value());
  CHECK(*stored == batch);
  rx_batch->close();
  tx_digest->close();
  actor.join();
}

TEST(processor_forward_false_stores_without_digest) {
  // graftdag peer lane: a cert-mode peer batch is stored for availability
  // but must NOT feed this node's proposer (only the producer proposes
  // its own certified batches).
  Store store = Store::open("");
  auto rx_batch = make_channel<ProcessorMessage>();
  auto tx_digest = make_channel<PayloadRef>();
  auto actor = Processor::spawn(store, rx_batch, tx_digest);
  Bytes peer_batch{5, 5, 5};
  ProcessorMessage pm;
  pm.batch = peer_batch;
  pm.forward = false;
  rx_batch->send(std::move(pm));
  // A forwarded batch after it proves the first was processed (FIFO).
  Bytes own_batch{6, 6};
  ProcessorMessage own;
  own.batch = own_batch;
  rx_batch->send(std::move(own));
  auto ref = tx_digest->recv();
  CHECK(ref.has_value());
  CHECK(ref->digest == sha512_digest(own_batch));  // peer digest skipped
  CHECK(store.read(sha512_digest(peer_batch).to_bytes()).has_value());
  rx_batch->close();
  tx_digest->close();
  actor.join();
}

TEST(synchronizer_sends_batch_request) {
  auto committee = mempool_committee(7400);
  auto myself = keys()[0].name;
  auto target = keys()[1].name;
  auto l = Listener::bind(*committee.mempool_address(target));
  CHECK(l.has_value());
  auto delivered = make_channel<Bytes>();
  auto t = listener(std::move(*l),
                    [delivered](Bytes b) { delivered->send(std::move(b)); });

  Store store = Store::open("");
  auto rx_msg = make_channel<ConsensusMempoolMessage>();
  auto actor = Synchronizer::spawn(myself, committee, store,
                                   /*gc_depth=*/50,
                                   /*sync_retry_delay=*/60'000,
                                   /*sync_retry_nodes=*/3, rx_msg);
  ConsensusMempoolMessage msg;
  msg.kind = ConsensusMempoolMessage::Kind::kSynchronize;
  msg.digests = {sha512_digest(Bytes{1})};
  msg.target = target;
  rx_msg->send(std::move(msg));

  auto got = delivered->recv();
  CHECK(got.has_value());
  auto m = MempoolMessage::deserialize(*got);
  CHECK(m.kind == MempoolMessage::Kind::kBatchRequest);
  CHECK(m.missing.size() == 1);
  CHECK(m.origin == myself);
  t.join();
  rx_msg->close();
  actor.join();
}

TEST(helper_serves_batches) {
  auto committee = mempool_committee(7500);
  auto myself = keys()[0].name;
  auto requestor = keys()[1].name;
  auto l = Listener::bind(*committee.mempool_address(requestor));
  CHECK(l.has_value());
  auto delivered = make_channel<Bytes>();
  auto t = listener(std::move(*l),
                    [delivered](Bytes b) { delivered->send(std::move(b)); });

  Store store = Store::open("");
  Bytes batch = MempoolMessage::make_batch({{1, 2}}).serialize();
  Digest digest = sha512_digest(batch);
  store.write(digest.to_bytes(), batch);

  auto rx_req = make_channel<std::pair<std::vector<Digest>, PublicKey>>();
  auto actor = Helper::spawn(committee, store, rx_req);
  rx_req->send({{digest}, requestor});

  auto got = delivered->recv();
  CHECK(got.has_value());
  CHECK(*got == batch);
  t.join();
  rx_req->close();
  actor.join();
}

TEST(mempool_pipeline_end_to_end) {
  // Client tx in -> quorum-acked batch digest out (mempool_tests.rs:7-46).
  auto committee = mempool_committee(7600);
  auto myself = keys()[0].name;
  auto delivered = make_channel<Bytes>();
  auto threads = peer_listeners(committee, myself, delivered);

  Store store = Store::open("");
  Parameters params;
  params.batch_size = 20;  // tiny: one tx seals a batch
  params.max_batch_delay = 10'000;
  auto rx_consensus = make_channel<ConsensusMempoolMessage>();
  auto tx_consensus = make_channel<PayloadRef>();
  auto mp = Mempool::spawn(myself, keys()[0].secret, committee, params, store,
                           rx_consensus, tx_consensus);

  // Send a client transaction to the :front address.
  auto sock = Socket::connect(*committee.transactions_address(myself));
  CHECK(sock.has_value());
  Bytes tx(32, 9);
  CHECK(sock->write_frame(tx));

  auto ref = tx_consensus->recv();
  CHECK(ref.has_value());
  CHECK(!ref->cert.has_value());  // legacy mode: digest only
  auto stored = store.read(ref->digest.to_bytes());
  CHECK(stored.has_value());
  auto m = MempoolMessage::deserialize(*stored);
  CHECK(m.batch.size() == 1);
  CHECK(m.batch[0] == tx);
  for (auto& t : threads) t.join();
}

TEST(ingress_gate_budget_watermarks_and_retry_hints) {
  // Unit drive of the graftsurge admission gate: budget admits, the
  // overflow sheds with a retry hint, persistent shedding crosses the
  // pause watermark exactly once, and the consumer side resumes at the
  // low-water mark.
  IngressGate::Config cfg;
  cfg.tx_budget = 10;
  cfg.byte_budget = 10'000;
  cfg.pause_after_sheds = 3;
  cfg.low_water_div = 2;
  cfg.max_batch_delay_ms = 100;
  std::vector<bool> pauses;
  IngressGate gate(cfg, [&pauses](bool p) { pauses.push_back(p); });

  uint32_t retry = 0;
  for (int i = 0; i < 10; i++) CHECK(gate.admit(100, &retry));
  CHECK(gate.queued_txs() == 10);
  CHECK(gate.queued_bytes() == 1000);
  // 11th: over the tx budget -> BUSY with a hint, no pause yet.
  CHECK(!gate.admit(100, &retry));
  CHECK(retry >= 50);
  CHECK(gate.sheds() == 1);
  CHECK(pauses.empty());
  // Two more consecutive sheds cross the pause watermark exactly once.
  CHECK(!gate.admit(100, &retry));
  CHECK(!gate.admit(100, &retry));
  CHECK(gate.paused());
  CHECK(gate.pause_crossings() == 1);
  CHECK(pauses.size() == 1 && pauses[0] == true);
  CHECK(!gate.admit(100, &retry));  // still shedding, still one crossing
  CHECK(gate.pause_crossings() == 1);
  // Draining to the low-water mark (10/2 = 5 txs) resumes.
  for (int i = 0; i < 4; i++) gate.on_consumed(100);
  CHECK(gate.paused());  // 6 queued: still above low water
  gate.on_consumed(100);
  CHECK(!gate.paused());
  CHECK(pauses.size() == 2 && pauses[1] == false);
  // Admission works again after the resume.
  CHECK(gate.admit(100, &retry));
}

TEST(ingress_gate_byte_budget_sheds_too) {
  IngressGate::Config cfg;
  cfg.tx_budget = 1000;
  cfg.byte_budget = 250;
  IngressGate gate(cfg, nullptr);
  uint32_t retry = 0;
  CHECK(gate.admit(100, &retry));
  CHECK(gate.admit(100, &retry));
  CHECK(!gate.admit(100, &retry));  // 300 > 250
  CHECK(gate.sheds() == 1);
  gate.on_consumed(100);
  CHECK(gate.admit(100, &retry));
}

TEST(mempool_bounded_ingress_replies_busy) {
  // End-to-end through the real pipeline: with no ACKing peers the
  // QuorumWaiter wedges on its first sealed batch, the BatchMaker
  // backs up behind it, the tx channel fills to the (tiny) ingress
  // budget — and the client's own connection receives an explicit
  // "BUSY <retry_ms>" frame instead of a silent drop.
  auto committee = mempool_committee(7800);
  auto myself = keys()[0].name;

  Store store = Store::open("");
  Parameters params;
  params.batch_size = 20;        // one tx seals a batch
  params.max_batch_delay = 60'000;
  params.ingress_tx_budget = 16;
  auto rx_consensus = make_channel<ConsensusMempoolMessage>();
  auto tx_consensus = make_channel<PayloadRef>();
  auto mp = Mempool::spawn(myself, keys()[0].secret, committee, params, store,
                           rx_consensus, tx_consensus);

  auto sock = Socket::connect(*committee.transactions_address(myself));
  CHECK(sock.has_value());
  sock->set_recv_timeout(30'000);
  Bytes tx(32, 9);
  // The QuorumWaiter holds batch 1; the tx_quorum_waiter channel holds
  // the next 1000; the BatchMaker's in-flight tx is one more; past
  // that the gate's 16-tx budget fills and sheds begin.
  const size_t kSends = 1'100;
  for (size_t i = 0; i < kSends; i++) CHECK(sock->write_frame(tx));
  Bytes reply;
  CHECK(sock->read_frame(&reply));
  std::string text(reply.begin(), reply.end());
  CHECK(text.rfind("BUSY ", 0) == 0);
  uint64_t hint = std::stoull(text.substr(5));
  CHECK(hint >= 50);
  CHECK(hint <= 2'000);
  CHECK(mp->ingress_gate().sheds() > 0);
  mp->stop();
}

TEST(peer_batch_digest_survives_consensus_backlog) {
  // A stored+ACKed peer batch must remain proposable even when consensus
  // has a deep backlog: the inlined peer-batch path try_sends the digest
  // AFTER the batch bytes are consumed, so the node wires the digest
  // channel unbounded (node.cpp).  Replicate that wiring, never drain,
  // and push well past the default channel capacity — every digest must
  // survive (a bounded channel silently dropped them, round-5 ADVICE.md).
  auto committee = mempool_committee(7700);
  auto myself = keys()[0].name;

  Store store = Store::open("");
  Parameters params;
  params.batch_size = 1'000'000;  // nothing seals: only peer batches flow
  params.max_batch_delay = 60'000;
  auto rx_consensus = make_channel<ConsensusMempoolMessage>();
  auto tx_consensus = make_channel<PayloadRef>(SIZE_MAX);  // the node wiring
  auto mp = Mempool::spawn(myself, keys()[0].secret, committee, params, store,
                           rx_consensus, tx_consensus);

  auto sock = Socket::connect(*committee.mempool_address(myself));
  CHECK(sock.has_value());
  sock->set_recv_timeout(10000);
  const size_t kBatches = kChannelCapacity + 64;
  for (size_t i = 0; i < kBatches; i++) {
    Bytes tx(16, 0);
    for (int b = 0; b < 8; b++) tx[b] = (i >> (8 * b)) & 0xFF;
    auto frame = MempoolMessage::make_batch({tx}).serialize();
    CHECK(sock->write_frame(frame));
    Bytes ack;  // every peer message is ACKed before processing
    CHECK(sock->read_frame(&ack));
  }
  // All digests arrived (nothing was dropped) and every batch is stored.
  for (size_t i = 0; i < kBatches; i++) {
    auto ref = tx_consensus->recv();
    CHECK(ref.has_value());
    CHECK(store.read(ref->digest.to_bytes()).has_value());
  }
  mp->stop();
}

// -- graftdag: signed batch ACKs + availability certificates ----------------

TEST(batch_ack_message_roundtrip) {
  auto kp = keys()[1];
  Digest batch_digest = sha512_digest(Bytes{1, 2, 3});
  Digest ack = BatchCertificate::ack_digest_of(batch_digest);
  // Domain separation: an availability ACK never signs the raw batch
  // digest, so it can't be replayed as any other signature kind.
  CHECK(!(ack == batch_digest));
  auto msg = MempoolMessage::make_ack(batch_digest, kp.name,
                                      Signature::sign_host(ack, kp.secret));
  auto rt = MempoolMessage::deserialize(msg.serialize());
  CHECK(rt.kind == MempoolMessage::Kind::kAck);
  CHECK(rt.ack_digest == batch_digest);
  CHECK(rt.ack_author == kp.name);
  CHECK(rt.ack_signature.verify(ack, kp.name));
}

TEST(batch_certificate_roundtrip_and_structural_checks) {
  auto committee = mempool_committee(8000);  // address book only, no net
  auto ks = keys();
  BatchCertificate cert;
  cert.digest = sha512_digest(Bytes{9, 9, 9});
  Digest ack = cert.ack_digest();
  for (size_t i = 0; i < 3; i++) {
    cert.votes.emplace_back(ks[i].name,
                            Signature::sign_host(ack, ks[i].secret));
  }
  CHECK(cert.check(committee).empty());
  CHECK(Signature::verify_batch(ack, cert.votes));

  // Serde round trip preserves every byte (content digest is the
  // consensus Core's verified-cert cache key).
  Bytes wire = cert.to_bytes();
  Reader r(wire);
  auto rt = BatchCertificate::deserialize(&r);
  CHECK(rt.digest == cert.digest);
  CHECK(rt.votes.size() == 3);
  CHECK(rt.content_digest() == cert.content_digest());
  CHECK(rt.check(committee).empty());

  // Below 2f+1 refused.
  BatchCertificate small = cert;
  small.votes.pop_back();
  CHECK(!small.check(committee).empty());
  // A duplicate signer must not count twice toward the quorum.
  BatchCertificate dup = cert;
  dup.votes[2] = dup.votes[0];
  CHECK(!dup.check(committee).empty());
  // Padded past the quorum (equal stakes) refused: a shape the verify
  // sidecar never warmed.
  BatchCertificate padded = cert;
  padded.votes.emplace_back(ks[3].name,
                            Signature::sign_host(ack, ks[3].secret));
  CHECK(!padded.check(committee).empty());
  // A signer outside the committee refused.
  std::array<uint8_t, 32> seed{};
  seed[0] = 200;
  auto stranger = keypair_from_seed(seed);
  BatchCertificate foreign = cert;
  foreign.votes[2] = {stranger.name,
                      Signature::sign_host(ack, stranger.secret)};
  CHECK(!foreign.check(committee).empty());
}

TEST(quorum_waiter_dag_assembles_minimal_certificate) {
  // Signed-ACK collection: transport "Ack"s and forged votes carry no
  // stake; two honest signed peer votes plus our own reach 2f+1 = 3 and
  // the released batch carries a minimal, structurally valid certificate.
  auto committee = mempool_committee(8020);
  auto ks = keys();
  auto myself = ks[0].name;
  auto rx_msg = make_channel<QuorumWaiterMessage>();
  auto tx_batch = make_channel<ProcessorMessage>();
  auto stop = std::make_shared<std::atomic<bool>>(false);
  auto actor = QuorumWaiter::spawn(committee, myself, ks[0].secret,
                                   /*dag=*/true, rx_msg, tx_batch, stop);

  QuorumWaiterMessage msg;
  msg.batch = MempoolMessage::make_batch({{1, 2, 3}}).serialize();
  Digest digest = Processor::digest_of(msg.batch);
  Digest ack = BatchCertificate::ack_digest_of(digest);
  std::vector<CancelHandler> handlers;
  std::vector<PublicKey> peers;
  for (const auto& [name, _] : committee.broadcast_addresses(myself)) {
    CancelHandler h;
    handlers.push_back(h);
    peers.push_back(name);
    msg.handlers.emplace_back(name, h);
  }
  rx_msg->send(std::move(msg));
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown peer");
  };

  // Slot 0: a FORGED vote claiming peer 1 — signed over the raw batch
  // digest instead of the domain-separated ack digest.  Dropped; the
  // author slot stays open (attribution comes from the signed field,
  // never the reply slot).
  handlers[0].set(MempoolMessage::make_ack(
                      digest, peers[1],
                      Signature::sign_host(digest, key_for(peers[1]).secret))
                      .serialize());
  // Slot 1: peer 1's honest vote — verifies and counts (own + 1 = 2).
  handlers[1].set(MempoolMessage::make_ack(
                      digest, peers[1],
                      Signature::sign_host(ack, key_for(peers[1]).secret))
                      .serialize());
  ProcessorMessage out;
  CHECK(tx_batch->recv_until(&out, std::chrono::steady_clock::now() +
                                       std::chrono::milliseconds(200)) ==
        RecvStatus::kTimeout);
  // Slot 2: peer 2's honest vote reaches 2f+1 = 3 and releases the batch.
  handlers[2].set(MempoolMessage::make_ack(
                      digest, peers[2],
                      Signature::sign_host(ack, key_for(peers[2]).secret))
                      .serialize());
  auto got = tx_batch->recv();
  CHECK(got.has_value());
  CHECK(got->forward);
  CHECK(got->cert.has_value());
  const BatchCertificate& cert = *got->cert;
  CHECK(cert.digest == digest);
  CHECK(cert.votes.size() == 3);  // minimal: stops exactly at the quorum
  CHECK(cert.votes[0].first == myself);  // own vote first (we hold it)
  CHECK(cert.check(committee).empty());
  CHECK(Signature::verify_batch(cert.ack_digest(), cert.votes));
  rx_msg->close();
  tx_batch->close();
  stop->store(true);
  actor.join();
}

TEST(quorum_waiter_dag_skips_transport_acks) {
  // A dag peer that received but could not store a batch replies a bare
  // transport "Ack" (FIFO pairing filler).  It must be skipped silently —
  // counting it would certify availability the peer does not have.
  auto committee = mempool_committee(8040);
  auto ks = keys();
  auto myself = ks[0].name;
  auto rx_msg = make_channel<QuorumWaiterMessage>();
  auto tx_batch = make_channel<ProcessorMessage>();
  auto stop = std::make_shared<std::atomic<bool>>(false);
  auto actor = QuorumWaiter::spawn(committee, myself, ks[0].secret,
                                   /*dag=*/true, rx_msg, tx_batch, stop);

  QuorumWaiterMessage msg;
  msg.batch = MempoolMessage::make_batch({{4, 4}}).serialize();
  Digest digest = Processor::digest_of(msg.batch);
  Digest ack = BatchCertificate::ack_digest_of(digest);
  std::vector<CancelHandler> handlers;
  std::vector<PublicKey> peers;
  for (const auto& [name, _] : committee.broadcast_addresses(myself)) {
    CancelHandler h;
    handlers.push_back(h);
    peers.push_back(name);
    msg.handlers.emplace_back(name, h);
  }
  rx_msg->send(std::move(msg));
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown peer");
  };

  handlers[0].set(to_bytes("Ack"));  // overloaded peer: no vote
  handlers[1].set(MempoolMessage::make_ack(
                      digest, peers[1],
                      Signature::sign_host(ack, key_for(peers[1]).secret))
                      .serialize());
  ProcessorMessage out;
  CHECK(tx_batch->recv_until(&out, std::chrono::steady_clock::now() +
                                       std::chrono::milliseconds(200)) ==
        RecvStatus::kTimeout);  // own + 1 vote: the "Ack" added nothing
  handlers[2].set(MempoolMessage::make_ack(
                      digest, peers[2],
                      Signature::sign_host(ack, key_for(peers[2]).secret))
                      .serialize());
  auto got = tx_batch->recv();
  CHECK(got.has_value());
  CHECK(got->cert.has_value());
  CHECK(got->cert->votes.size() == 3);
  rx_msg->close();
  tx_batch->close();
  stop->store(true);
  actor.join();
}

TEST(mempool_dag_peer_replies_signed_ack) {
  // Peer-receiver dag lane end to end: a peer batch is stored FIRST, then
  // answered with a signed availability ACK — and it never feeds this
  // node's proposer (only the producer proposes its own certified batch).
  auto committee = mempool_committee(8060);
  auto ks = keys();
  auto myself = ks[0].name;
  Store store = Store::open("");
  Parameters params;
  params.batch_size = 1'000'000;  // nothing seals: only peer batches flow
  params.max_batch_delay = 60'000;
  params.dag = true;
  auto rx_consensus = make_channel<ConsensusMempoolMessage>();
  auto tx_consensus = make_channel<PayloadRef>(SIZE_MAX);
  auto mp = Mempool::spawn(myself, ks[0].secret, committee, params, store,
                           rx_consensus, tx_consensus);

  auto sock = Socket::connect(*committee.mempool_address(myself));
  CHECK(sock.has_value());
  sock->set_recv_timeout(10'000);
  Bytes frame = MempoolMessage::make_batch({{8, 8, 8}}).serialize();
  Digest digest = sha512_digest(frame);
  CHECK(sock->write_frame(frame));
  Bytes reply;
  CHECK(sock->read_frame(&reply));
  auto ackmsg = MempoolMessage::deserialize(reply);
  CHECK(ackmsg.kind == MempoolMessage::Kind::kAck);
  CHECK(ackmsg.ack_digest == digest);
  CHECK(ackmsg.ack_author == myself);
  CHECK(ackmsg.ack_signature.verify(BatchCertificate::ack_digest_of(digest),
                                    myself));
  // Sign-only-after-store: the ACK implies the batch is durably held.
  CHECK(store.read(digest.to_bytes()).has_value());
  PayloadRef leak;
  CHECK(tx_consensus->recv_until(&leak, std::chrono::steady_clock::now() +
                                            std::chrono::milliseconds(200)) ==
        RecvStatus::kTimeout);
  mp->stop();
}

// -- graftingress: signed-tx admission verify -------------------------------

namespace {

// One signed frame in the legacy inner-payload shape (marker + id + pad).
Bytes signed_tx_frame(const KeyPair& kp, uint64_t nonce, uint64_t id,
                      size_t payload_len = 32, bool forge = false) {
  Bytes payload(payload_len, 0);
  payload[0] = forge ? kTxMarkerForged : kTxMarkerSample;
  for (int i = 0; i < 8; i++) payload[1 + i] = uint8_t(id >> (56 - 8 * i));
  return build_signed_tx(kp, nonce, payload.data(), payload.size(), forge);
}

// Uninstalls the process-global sidecar client even when a failing CHECK
// returns early (test_crypto.cpp's SidecarGuard, Ed25519-only flavour).
struct SidecarGuard {
  ~SidecarGuard() { TpuVerifier::install(nullptr); }
};

// Poll a telemetry counter until it reaches `want` or the deadline hits;
// the verify worker settles batches asynchronously off a max-delay timer.
template <typename Fn>
bool wait_counter(Fn&& read, uint64_t want, int timeout_ms = 5000) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (std::chrono::steady_clock::now() < deadline) {
    if (read() >= want) return true;
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  return read() >= want;
}

}  // namespace

TEST(tx_frame_parse_fuzz_never_crashes_or_misparses) {
  // C++ twin of tests/test_fuzz.py's tx_corpus: every malformed mutation
  // of a valid signed frame must classify cleanly — no crash, no read
  // past len, and never a kOk verdict for a frame whose declared length
  // lies about its body.
  auto kp = tx_user_keypair(5, 0);
  Bytes frame = signed_tx_frame(kp, 7, 42);
  SignedTxView v;
  CHECK(parse_signed_tx(frame.data(), frame.size(), &v) == TxParse::kOk);
  CHECK(v.payload_len == 32);
  CHECK(v.nonce == 7);
  CHECK(std::memcmp(v.pk, kp.name.data.data(), kTxPkLen) == 0);
  CHECK(v.sig == frame.data() + kTxFrameHeaderLen + 32);
  // The out param is nullable (the reactor's structural pre-check).
  CHECK(parse_signed_tx(frame.data(), frame.size(), nullptr) == TxParse::kOk);

  // A forged frame (flipped sig bit) must parse kOk: forgeries die at
  // the verify stage, never at parse.
  Bytes forged = signed_tx_frame(kp, 7, 42, 32, /*forge=*/true);
  CHECK(parse_signed_tx(forged.data(), forged.size(), &v) == TxParse::kOk);

  // Empty / wrong leading version byte -> legacy-unsigned verdict.
  CHECK(parse_signed_tx(frame.data(), 0, nullptr) == TxParse::kNotSigned);
  for (uint8_t ver : {uint8_t(0), uint8_t(1), uint8_t(3), uint8_t(255)}) {
    Bytes m = frame;
    m[0] = ver;
    CHECK(parse_signed_tx(m.data(), m.size(), nullptr) == TxParse::kNotSigned);
  }
  // Every truncation below overhead+min-payload is kTruncated.
  for (size_t k = 1; k < kTxFrameOverhead + kTxMinPayload; k++) {
    CHECK(parse_signed_tx(frame.data(), k, nullptr) == TxParse::kTruncated);
  }
  // Lying declared payload lengths: short, long, zero, max+1, absurd.
  auto with_plen = [&frame](uint32_t plen) {
    Bytes m = frame;
    for (size_t i = 0; i < kTxLenLen; i++) {
      m[1 + kTxPkLen + kTxNonceLen + i] = uint8_t(plen >> (24 - 8 * i));
    }
    return m;
  };
  for (uint32_t plen : {31u, 33u, 0u, uint32_t(kTxMaxPayload + 1),
                        0xFFFFFFFFu}) {
    Bytes m = with_plen(plen);
    CHECK(parse_signed_tx(m.data(), m.size(), nullptr) ==
          TxParse::kBadPayloadLen);
  }
  // Honest header, dishonest body: cut or pad the frame itself.
  CHECK(parse_signed_tx(frame.data(), frame.size() - 1, nullptr) ==
        TxParse::kBadPayloadLen);
  Bytes padded = frame;
  padded.push_back(0);
  CHECK(parse_signed_tx(padded.data(), padded.size(), nullptr) ==
        TxParse::kBadPayloadLen);
}

TEST(tx_keyring_bounded_lru_derives_on_demand) {
  // Deterministic derivation (the python twin recomputes the same keys)
  // and bounded residency: a 1e6-user load never holds more than
  // `capacity` expanded keypairs.
  CHECK(tx_user_keypair(5, 9).name == tx_user_keypair(5, 9).name);
  CHECK(!(tx_user_keypair(5, 9).name == tx_user_keypair(5, 10).name));
  CHECK(!(tx_user_keypair(6, 9).name == tx_user_keypair(5, 9).name));
  TxKeyring ring(5, /*capacity=*/2);
  PublicKey pk1 = ring.get(1).name;
  ring.get(2);
  CHECK(ring.size() == 2);
  CHECK(ring.derivations() == 2);
  ring.get(2);  // hit: no new derivation
  CHECK(ring.derivations() == 2);
  ring.get(3);  // evicts user 1 (LRU)
  CHECK(ring.size() == 2);
  CHECK(ring.derivations() == 3);
  CHECK(ring.get(1).name == pk1);  // re-derived, same key
  CHECK(ring.derivations() == 4);
}

TEST(admission_verify_host_path_admits_valid_rejects_forged) {
  // Unit drive of TxVerifier with NO sidecar installed: the worker falls
  // back to the host verify loop, valid txs forward to the batch-maker
  // channel in order, and forged txs are counted + gate-unwound before
  // they can ever reach a batch.
  SidecarGuard guard;  // ensure no verifier leaks in from another test
  TpuVerifier::install(nullptr);
  IngressGate::Config gc;
  gc.tx_budget = 100;
  auto gate = std::make_shared<IngressGate>(gc, nullptr);
  auto out = make_channel<Transaction>();
  TxVerifier::Config vc;
  vc.batch = 4;
  vc.max_delay_ms = 10;
  auto verifier = TxVerifier::spawn(vc, out, gate);

  TxKeyring ring(5);
  std::vector<Bytes> valid;
  uint32_t retry = 0;
  for (uint64_t u = 0; u < 3; u++) {
    Bytes f = signed_tx_frame(ring.get(u), /*nonce=*/u, /*id=*/u);
    CHECK(gate->admit(f.size(), &retry));
    valid.push_back(f);
    CHECK(verifier->enqueue(std::move(f), std::nullopt, &retry));
  }
  Bytes forged = signed_tx_frame(ring.get(9), 9, 9, 32, /*forge=*/true);
  CHECK(gate->admit(forged.size(), &retry));
  CHECK(verifier->enqueue(std::move(forged), std::nullopt, &retry));

  // Batch of 4 seals by size; only the 3 valid frames come through.
  for (const auto& f : valid) {
    auto got = out->recv();
    CHECK(got.has_value());
    CHECK(*got == f);
  }
  CHECK(wait_counter([&] { return verifier->forged(); }, 1));
  CHECK(verifier->verified() == 3);
  CHECK(verifier->forged() == 1);
  CHECK(verifier->host_fallbacks() == 1);
  CHECK(verifier->shed() == 0);
  // The forged tx's gate slot was unwound; the 3 forwarded ones keep
  // their accounting until a BatchMaker would drain them.
  CHECK(wait_counter([&] { return uint64_t(4 - gate->queued_txs()); }, 1));
  CHECK(gate->queued_txs() == 3);
  verifier->stop();
  out->close();
}

TEST(admission_verify_dead_sidecar_falls_back_to_host) {
  // A sidecar that is installed but unreachable must degrade to the host
  // path (async_available() is false while disconnected) — overload or
  // outage degrades goodput, never admits an unverified tx.
  uint16_t port;
  {
    auto l = Listener::bind({"127.0.0.1", 0});
    CHECK(l.has_value());
    port = l->port();
  }
  SidecarGuard guard;
  TpuVerifier::install(
      std::make_unique<TpuVerifier>(Address{"127.0.0.1", port}));

  auto gate = std::make_shared<IngressGate>(IngressGate::Config{}, nullptr);
  auto out = make_channel<Transaction>();
  TxVerifier::Config vc;
  vc.batch = 2;
  vc.max_delay_ms = 10;
  auto verifier = TxVerifier::spawn(vc, out, gate);

  TxKeyring ring(5);
  uint32_t retry = 0;
  Bytes ok_frame = signed_tx_frame(ring.get(1), 1, 1);
  Bytes bad_frame = signed_tx_frame(ring.get(2), 2, 2, 32, /*forge=*/true);
  CHECK(gate->admit(ok_frame.size(), &retry));
  CHECK(gate->admit(bad_frame.size(), &retry));
  Bytes expect = ok_frame;
  CHECK(verifier->enqueue(std::move(ok_frame), std::nullopt, &retry));
  CHECK(verifier->enqueue(std::move(bad_frame), std::nullopt, &retry));

  auto got = out->recv();
  CHECK(got.has_value());
  CHECK(*got == expect);
  CHECK(wait_counter([&] { return verifier->forged(); }, 1));
  CHECK(verifier->verified() == 1);
  CHECK(verifier->forged() == 1);
  CHECK(verifier->host_fallbacks() >= 1);
  verifier->stop();
  out->close();
}

TEST(admission_verify_busy_retries_then_sheds) {
  // Explicit OP_BUSY backpressure from a live sidecar: the worker paces
  // a bounded retry off the surfaced hint, then sheds the whole batch
  // (client-visible BUSY handled by the writer in the node wiring) with
  // the gate fully unwound — nothing reaches the batch maker.
  auto l = Listener::bind({"127.0.0.1", 0});
  CHECK(l.has_value());
  uint16_t port = l->port();
  std::thread server([&l] {
    auto sock = l->accept();
    if (!sock) return;
    Bytes frame;
    while (sock->read_frame(&frame)) {
      Reader r(frame);
      r.u8();  // opcode (ignored: everything gets shed)
      uint32_t rid = r.u32();
      Writer w;
      w.u8(10);  // OP_BUSY
      w.u32(rid);
      w.u32(2);
      w.u8(7);  // retry-after hint: 7 ms, little-endian u16
      w.u8(0);
      if (!sock->write_frame(w.out)) return;
    }
  });

  SidecarGuard guard;
  TpuVerifier::install(
      std::make_unique<TpuVerifier>(Address{"127.0.0.1", port}));
  // Prime the connection: the sync path dials, eats the BUSY, and host-
  // verifies — after which async_available() sees a live transport.
  auto kp = keys()[0];
  Digest d = sha512_digest(Bytes{1});
  CHECK(Signature::verify_batch_multi(
      {{d, kp.name, Signature::sign(d, kp.secret)}}));

  auto gate = std::make_shared<IngressGate>(IngressGate::Config{}, nullptr);
  auto out = make_channel<Transaction>();
  TxVerifier::Config vc;
  vc.batch = 2;
  vc.max_delay_ms = 10;
  vc.busy_retries = 1;
  vc.busy_retry_cap_ms = 20;
  auto verifier = TxVerifier::spawn(vc, out, gate);

  TxKeyring ring(5);
  uint32_t retry = 0;
  for (uint64_t u = 0; u < 2; u++) {
    Bytes f = signed_tx_frame(ring.get(u), u, u);
    CHECK(gate->admit(f.size(), &retry));
    CHECK(verifier->enqueue(std::move(f), std::nullopt, &retry));
  }
  CHECK(wait_counter([&] { return verifier->shed(); }, 2));
  CHECK(verifier->shed() == 2);
  CHECK(verifier->busy_retries() == 1);
  CHECK(verifier->verified() == 0);
  CHECK(verifier->forged() == 0);
  CHECK(gate->queued_txs() == 0);  // fully unwound
  Transaction leak;
  CHECK(out->recv_until(&leak, std::chrono::steady_clock::now() +
                                   std::chrono::milliseconds(100)) ==
        RecvStatus::kTimeout);
  verifier->stop();
  out->close();
  TpuVerifier::install(nullptr);  // closes the socket -> server sees EOF
  l->shutdown();
  server.join();
}

TEST(mempool_signed_ingress_end_to_end) {
  // Full pipeline with --verify-ingress on: a malformed frame is dropped
  // at parse, a forged-but-well-formed frame dies at the verify stage,
  // and only the honestly signed frame seals a batch and reaches a
  // quorum-acked digest.
  SidecarGuard guard;  // host verify path: no sidecar in this test
  TpuVerifier::install(nullptr);
  auto committee = mempool_committee(7900);
  auto myself = keys()[0].name;
  auto delivered = make_channel<Bytes>();
  auto threads = peer_listeners(committee, myself, delivered);

  Store store = Store::open("");
  Parameters params;
  params.batch_size = 100;  // one signed frame (141 B) seals a batch
  params.max_batch_delay = 10'000;
  params.verify_ingress = true;
  params.verify_batch = 1;  // settle every frame immediately
  params.verify_max_delay = 10;
  auto rx_consensus = make_channel<ConsensusMempoolMessage>();
  auto tx_consensus = make_channel<PayloadRef>();
  auto mp = Mempool::spawn(myself, keys()[0].secret, committee, params, store,
                           rx_consensus, tx_consensus);
  CHECK(mp->tx_verifier() != nullptr);

  auto sock = Socket::connect(*committee.transactions_address(myself));
  CHECK(sock.has_value());
  TxKeyring ring(5);
  // 1. Malformed: signed version byte but truncated body -> parse drop.
  Bytes malformed = signed_tx_frame(ring.get(0), 0, 0);
  malformed.resize(40);
  CHECK(sock->write_frame(malformed));
  // 2. Forged: parses cleanly, rejected + counted at the verify stage.
  Bytes forged = signed_tx_frame(ring.get(1), 1, 1, 32, /*forge=*/true);
  CHECK(sock->write_frame(forged));
  CHECK(wait_counter([&] { return mp->tx_verifier()->forged(); }, 1));
  // 3. Honest: verifies, seals, broadcasts, quorum-ACKs, commits.
  Bytes honest = signed_tx_frame(ring.get(2), 2, 2);
  CHECK(sock->write_frame(honest));
  auto ref = tx_consensus->recv();
  CHECK(ref.has_value());
  auto stored = store.read(ref->digest.to_bytes());
  CHECK(stored.has_value());
  auto m = MempoolMessage::deserialize(*stored);
  CHECK(m.batch.size() == 1);
  CHECK(m.batch[0] == honest);
  CHECK(mp->tx_verifier()->verified() == 1);
  CHECK(mp->tx_verifier()->forged() == 1);
  for (auto& t : threads) t.join();
  mp->stop();
}

int main() { return run_all(); }

// Mempool tests (mempool/src/tests/ analogue): batch sealing by size and by
// timeout, quorum waiting with fake ACKing peers, processor hash+store,
// synchronizer request emission, helper batch reply, and the full pipeline
// client-tx -> digest.
#include <thread>

#include "mempool/batch_maker.hpp"
#include "mempool/helper.hpp"
#include "mempool/mempool.hpp"
#include "mempool/processor.hpp"
#include "mempool/quorum_waiter.hpp"
#include "mempool/synchronizer.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;
using namespace hotstuff::mempool;

namespace {

// Listeners for all 3 peer mempool addresses that ACK one batch each.
std::vector<std::thread> peer_listeners(const Committee& committee,
                                        const PublicKey& myself,
                                        ChannelPtr<Bytes> delivered) {
  std::vector<std::thread> threads;
  for (const auto& [name, addr] : committee.broadcast_addresses(myself)) {
    auto l = Listener::bind(addr);
    if (!l) throw std::runtime_error("bind failed: " + addr.str());
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  return threads;
}

}  // namespace

TEST(batch_maker_seals_by_size) {
  auto committee = mempool_committee(7100);
  auto myself = keys()[0].name;
  auto delivered = make_channel<Bytes>();
  auto threads = peer_listeners(committee, myself, delivered);

  auto rx_tx = make_channel<Transaction>();
  auto tx_msg = make_channel<QuorumWaiterMessage>();
  auto actor = BatchMaker::spawn(
      /*batch_size=*/100, /*max_batch_delay=*/60'000, rx_tx, tx_msg,
      committee.broadcast_addresses(myself),
      std::make_shared<std::atomic<bool>>(false));
  Transaction tx(60, 5);  // 60 bytes: two txs cross the 100-byte seal point
  rx_tx->send(tx);
  rx_tx->send(tx);
  auto msg = tx_msg->recv();
  CHECK(msg.has_value());
  auto m = MempoolMessage::deserialize(msg->batch);
  CHECK(m.kind == MempoolMessage::Kind::kBatch);
  CHECK(m.batch.size() == 2);
  CHECK(m.batch[0] == tx);
  CHECK(msg->handlers.size() == 3);
  for (auto& t : threads) t.join();
  rx_tx->close();
  tx_msg->close();
  actor.join();
}

TEST(batch_maker_seals_by_timeout) {
  auto committee = mempool_committee(7200);
  auto myself = keys()[0].name;
  auto delivered = make_channel<Bytes>();
  auto threads = peer_listeners(committee, myself, delivered);

  auto rx_tx = make_channel<Transaction>();
  auto tx_msg = make_channel<QuorumWaiterMessage>();
  auto actor = BatchMaker::spawn(
      /*batch_size=*/1'000'000, /*max_batch_delay=*/50, rx_tx, tx_msg,
      committee.broadcast_addresses(myself),
      std::make_shared<std::atomic<bool>>(false));
  rx_tx->send(Transaction(10, 1));
  auto msg = tx_msg->recv();
  CHECK(msg.has_value());
  auto m = MempoolMessage::deserialize(msg->batch);
  CHECK(m.batch.size() == 1);
  for (auto& t : threads) t.join();
  rx_tx->close();
  tx_msg->close();
  actor.join();
}

TEST(quorum_waiter_waits_for_stake) {
  auto committee = mempool_committee(7300);
  auto myself = keys()[0].name;
  auto rx_msg = make_channel<QuorumWaiterMessage>();
  auto tx_batch = make_channel<Bytes>();
  auto stop = std::make_shared<std::atomic<bool>>(false);
  auto actor = QuorumWaiter::spawn(committee, committee.stake(myself), rx_msg,
                                   tx_batch, stop);

  QuorumWaiterMessage msg;
  msg.batch = Bytes{1, 2, 3};
  std::vector<CancelHandler> handlers;
  for (const auto& [name, _] : committee.broadcast_addresses(myself)) {
    CancelHandler h;
    handlers.push_back(h);
    msg.handlers.emplace_back(name, h);
  }
  rx_msg->send(std::move(msg));

  // With only our stake (1) nothing is delivered yet; two ACKs reach 2f+1=3.
  Bytes out;
  CHECK(tx_batch->recv_until(&out, std::chrono::steady_clock::now() +
                                       std::chrono::milliseconds(100)) ==
        RecvStatus::kTimeout);
  handlers[0].set(to_bytes("Ack"));
  handlers[1].set(to_bytes("Ack"));
  auto got = tx_batch->recv();
  CHECK(got.has_value());
  CHECK(*got == (Bytes{1, 2, 3}));
  rx_msg->close();
  tx_batch->close();
  actor.join();
}

TEST(quorum_waiter_ignores_cancelled_acks) {
  // Empty-byte fulfilment means CANCELLED (sender teardown / full
  // backlog), not a peer ACK: counting it would certify batch
  // availability for peers that never received the batch.
  auto committee = mempool_committee(7320);
  auto myself = keys()[0].name;
  auto rx_msg = make_channel<QuorumWaiterMessage>();
  auto tx_batch = make_channel<Bytes>();
  auto stop = std::make_shared<std::atomic<bool>>(false);
  auto actor = QuorumWaiter::spawn(committee, committee.stake(myself), rx_msg,
                                   tx_batch, stop);

  QuorumWaiterMessage msg;
  msg.batch = Bytes{9, 9};
  std::vector<CancelHandler> handlers;
  for (const auto& [name, _] : committee.broadcast_addresses(myself)) {
    CancelHandler h;
    handlers.push_back(h);
    msg.handlers.emplace_back(name, h);
  }
  rx_msg->send(std::move(msg));

  // One CANCELLED send (empty bytes) plus one real ACK is stake 2 — the
  // pre-fix bug would count the cancel and hit quorum (3) here.
  CHECK(handlers.size() == 3);  // 4-node committee: 3 peers
  handlers[0].set(Bytes{});
  handlers[1].set(to_bytes("Ack"));
  Bytes out;
  CHECK(tx_batch->recv_until(&out, std::chrono::steady_clock::now() +
                                       std::chrono::milliseconds(200)) ==
        RecvStatus::kTimeout);
  // A second real ACK reaches quorum (our stake 1 + 2 = 2f+1 = 3).
  handlers[2].set(to_bytes("Ack"));
  auto got = tx_batch->recv();
  CHECK(got.has_value());
  CHECK(*got == (Bytes{9, 9}));
  rx_msg->close();
  tx_batch->close();
  actor.join();
}

TEST(processor_hashes_and_stores) {
  Store store = Store::open("");
  auto rx_batch = make_channel<Bytes>();
  auto tx_digest = make_channel<Digest>();
  auto actor = Processor::spawn(store, rx_batch, tx_digest);
  Bytes batch{7, 7, 7, 7};
  rx_batch->send(batch);
  auto digest = tx_digest->recv();
  CHECK(digest.has_value());
  CHECK(*digest == sha512_digest(batch));
  auto stored = store.read(digest->to_bytes());
  CHECK(stored.has_value());
  CHECK(*stored == batch);
  rx_batch->close();
  tx_digest->close();
  actor.join();
}

TEST(synchronizer_sends_batch_request) {
  auto committee = mempool_committee(7400);
  auto myself = keys()[0].name;
  auto target = keys()[1].name;
  auto l = Listener::bind(*committee.mempool_address(target));
  CHECK(l.has_value());
  auto delivered = make_channel<Bytes>();
  auto t = listener(std::move(*l),
                    [delivered](Bytes b) { delivered->send(std::move(b)); });

  Store store = Store::open("");
  auto rx_msg = make_channel<ConsensusMempoolMessage>();
  auto actor = Synchronizer::spawn(myself, committee, store,
                                   /*gc_depth=*/50,
                                   /*sync_retry_delay=*/60'000,
                                   /*sync_retry_nodes=*/3, rx_msg);
  ConsensusMempoolMessage msg;
  msg.kind = ConsensusMempoolMessage::Kind::kSynchronize;
  msg.digests = {sha512_digest(Bytes{1})};
  msg.target = target;
  rx_msg->send(std::move(msg));

  auto got = delivered->recv();
  CHECK(got.has_value());
  auto m = MempoolMessage::deserialize(*got);
  CHECK(m.kind == MempoolMessage::Kind::kBatchRequest);
  CHECK(m.missing.size() == 1);
  CHECK(m.origin == myself);
  t.join();
  rx_msg->close();
  actor.join();
}

TEST(helper_serves_batches) {
  auto committee = mempool_committee(7500);
  auto myself = keys()[0].name;
  auto requestor = keys()[1].name;
  auto l = Listener::bind(*committee.mempool_address(requestor));
  CHECK(l.has_value());
  auto delivered = make_channel<Bytes>();
  auto t = listener(std::move(*l),
                    [delivered](Bytes b) { delivered->send(std::move(b)); });

  Store store = Store::open("");
  Bytes batch = MempoolMessage::make_batch({{1, 2}}).serialize();
  Digest digest = sha512_digest(batch);
  store.write(digest.to_bytes(), batch);

  auto rx_req = make_channel<std::pair<std::vector<Digest>, PublicKey>>();
  auto actor = Helper::spawn(committee, store, rx_req);
  rx_req->send({{digest}, requestor});

  auto got = delivered->recv();
  CHECK(got.has_value());
  CHECK(*got == batch);
  t.join();
  rx_req->close();
  actor.join();
}

TEST(mempool_pipeline_end_to_end) {
  // Client tx in -> quorum-acked batch digest out (mempool_tests.rs:7-46).
  auto committee = mempool_committee(7600);
  auto myself = keys()[0].name;
  auto delivered = make_channel<Bytes>();
  auto threads = peer_listeners(committee, myself, delivered);

  Store store = Store::open("");
  Parameters params;
  params.batch_size = 20;  // tiny: one tx seals a batch
  params.max_batch_delay = 10'000;
  auto rx_consensus = make_channel<ConsensusMempoolMessage>();
  auto tx_consensus = make_channel<Digest>();
  auto mp = Mempool::spawn(myself, committee, params, store, rx_consensus,
                           tx_consensus);

  // Send a client transaction to the :front address.
  auto sock = Socket::connect(*committee.transactions_address(myself));
  CHECK(sock.has_value());
  Bytes tx(32, 9);
  CHECK(sock->write_frame(tx));

  auto digest = tx_consensus->recv();
  CHECK(digest.has_value());
  auto stored = store.read(digest->to_bytes());
  CHECK(stored.has_value());
  auto m = MempoolMessage::deserialize(*stored);
  CHECK(m.batch.size() == 1);
  CHECK(m.batch[0] == tx);
  for (auto& t : threads) t.join();
}

TEST(ingress_gate_budget_watermarks_and_retry_hints) {
  // Unit drive of the graftsurge admission gate: budget admits, the
  // overflow sheds with a retry hint, persistent shedding crosses the
  // pause watermark exactly once, and the consumer side resumes at the
  // low-water mark.
  IngressGate::Config cfg;
  cfg.tx_budget = 10;
  cfg.byte_budget = 10'000;
  cfg.pause_after_sheds = 3;
  cfg.low_water_div = 2;
  cfg.max_batch_delay_ms = 100;
  std::vector<bool> pauses;
  IngressGate gate(cfg, [&pauses](bool p) { pauses.push_back(p); });

  uint32_t retry = 0;
  for (int i = 0; i < 10; i++) CHECK(gate.admit(100, &retry));
  CHECK(gate.queued_txs() == 10);
  CHECK(gate.queued_bytes() == 1000);
  // 11th: over the tx budget -> BUSY with a hint, no pause yet.
  CHECK(!gate.admit(100, &retry));
  CHECK(retry >= 50);
  CHECK(gate.sheds() == 1);
  CHECK(pauses.empty());
  // Two more consecutive sheds cross the pause watermark exactly once.
  CHECK(!gate.admit(100, &retry));
  CHECK(!gate.admit(100, &retry));
  CHECK(gate.paused());
  CHECK(gate.pause_crossings() == 1);
  CHECK(pauses.size() == 1 && pauses[0] == true);
  CHECK(!gate.admit(100, &retry));  // still shedding, still one crossing
  CHECK(gate.pause_crossings() == 1);
  // Draining to the low-water mark (10/2 = 5 txs) resumes.
  for (int i = 0; i < 4; i++) gate.on_consumed(100);
  CHECK(gate.paused());  // 6 queued: still above low water
  gate.on_consumed(100);
  CHECK(!gate.paused());
  CHECK(pauses.size() == 2 && pauses[1] == false);
  // Admission works again after the resume.
  CHECK(gate.admit(100, &retry));
}

TEST(ingress_gate_byte_budget_sheds_too) {
  IngressGate::Config cfg;
  cfg.tx_budget = 1000;
  cfg.byte_budget = 250;
  IngressGate gate(cfg, nullptr);
  uint32_t retry = 0;
  CHECK(gate.admit(100, &retry));
  CHECK(gate.admit(100, &retry));
  CHECK(!gate.admit(100, &retry));  // 300 > 250
  CHECK(gate.sheds() == 1);
  gate.on_consumed(100);
  CHECK(gate.admit(100, &retry));
}

TEST(mempool_bounded_ingress_replies_busy) {
  // End-to-end through the real pipeline: with no ACKing peers the
  // QuorumWaiter wedges on its first sealed batch, the BatchMaker
  // backs up behind it, the tx channel fills to the (tiny) ingress
  // budget — and the client's own connection receives an explicit
  // "BUSY <retry_ms>" frame instead of a silent drop.
  auto committee = mempool_committee(7800);
  auto myself = keys()[0].name;

  Store store = Store::open("");
  Parameters params;
  params.batch_size = 20;        // one tx seals a batch
  params.max_batch_delay = 60'000;
  params.ingress_tx_budget = 16;
  auto rx_consensus = make_channel<ConsensusMempoolMessage>();
  auto tx_consensus = make_channel<Digest>();
  auto mp = Mempool::spawn(myself, committee, params, store, rx_consensus,
                           tx_consensus);

  auto sock = Socket::connect(*committee.transactions_address(myself));
  CHECK(sock.has_value());
  sock->set_recv_timeout(30'000);
  Bytes tx(32, 9);
  // The QuorumWaiter holds batch 1; the tx_quorum_waiter channel holds
  // the next 1000; the BatchMaker's in-flight tx is one more; past
  // that the gate's 16-tx budget fills and sheds begin.
  const size_t kSends = 1'100;
  for (size_t i = 0; i < kSends; i++) CHECK(sock->write_frame(tx));
  Bytes reply;
  CHECK(sock->read_frame(&reply));
  std::string text(reply.begin(), reply.end());
  CHECK(text.rfind("BUSY ", 0) == 0);
  uint64_t hint = std::stoull(text.substr(5));
  CHECK(hint >= 50);
  CHECK(hint <= 2'000);
  CHECK(mp->ingress_gate().sheds() > 0);
  mp->stop();
}

TEST(peer_batch_digest_survives_consensus_backlog) {
  // A stored+ACKed peer batch must remain proposable even when consensus
  // has a deep backlog: the inlined peer-batch path try_sends the digest
  // AFTER the batch bytes are consumed, so the node wires the digest
  // channel unbounded (node.cpp).  Replicate that wiring, never drain,
  // and push well past the default channel capacity — every digest must
  // survive (a bounded channel silently dropped them, round-5 ADVICE.md).
  auto committee = mempool_committee(7700);
  auto myself = keys()[0].name;

  Store store = Store::open("");
  Parameters params;
  params.batch_size = 1'000'000;  // nothing seals: only peer batches flow
  params.max_batch_delay = 60'000;
  auto rx_consensus = make_channel<ConsensusMempoolMessage>();
  auto tx_consensus = make_channel<Digest>(SIZE_MAX);  // the node wiring
  auto mp = Mempool::spawn(myself, committee, params, store, rx_consensus,
                           tx_consensus);

  auto sock = Socket::connect(*committee.mempool_address(myself));
  CHECK(sock.has_value());
  sock->set_recv_timeout(10000);
  const size_t kBatches = kChannelCapacity + 64;
  for (size_t i = 0; i < kBatches; i++) {
    Bytes tx(16, 0);
    for (int b = 0; b < 8; b++) tx[b] = (i >> (8 * b)) & 0xFF;
    auto frame = MempoolMessage::make_batch({tx}).serialize();
    CHECK(sock->write_frame(frame));
    Bytes ack;  // every peer message is ACKed before processing
    CHECK(sock->read_frame(&ack));
  }
  // All digests arrived (nothing was dropped) and every batch is stored.
  for (size_t i = 0; i < kBatches; i++) {
    auto digest = tx_consensus->recv();
    CHECK(digest.has_value());
    CHECK(store.read(digest->to_bytes()).has_value());
  }
  mp->stop();
}

int main() { return run_all(); }

// Consensus tests (consensus/src/tests/ analogue): QC verification and its
// rejection paths, aggregator quorum formation + cleanup, core
// proposal->vote flow, votes->QC->proposal flow, chain commit, and timeout
// broadcast.
#include <memory>
#include <thread>

#include "consensus/consensus.hpp"
#include "crypto/sidecar_client.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;
using namespace hotstuff::consensus;

TEST(qc_verify_ok) {
  auto committee = consensus_committee(8100);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);
  CHECK(qc.verify(committee).ok());
}

TEST(qc_verify_rejects_authority_reuse) {
  auto committee = consensus_committee(8110);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);
  qc.votes.push_back(qc.votes[0]);  // duplicate voter
  CHECK(!qc.verify(committee).ok());
}

TEST(qc_verify_rejects_unknown_authority) {
  auto committee = consensus_committee(8120);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);
  std::array<uint8_t, 32> seed{};
  seed[0] = 99;
  auto unknown = keypair_from_seed(seed);
  qc.votes[0].first = unknown.name;
  CHECK(!qc.verify(committee).ok());
}

TEST(qc_verify_rejects_insufficient_stake) {
  auto committee = consensus_committee(8130);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);
  qc.votes.pop_back();  // 2 < quorum of 3
  CHECK(!qc.verify(committee).ok());
}

TEST(qc_verify_rejects_bad_signature) {
  auto committee = consensus_committee(8140);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);
  qc.votes[1].second.data[0] ^= 1;
  CHECK(!qc.verify(committee).ok());
}

TEST(aggregator_forms_qc_at_quorum) {
  auto committee = consensus_committee(8200);
  Aggregator aggregator(committee);
  auto chain = make_chain(1, committee);
  const Block& block = chain[0];
  auto ks = keys();
  // First two votes: no QC. Third: QC (2f+1 = 3).
  CHECK(!aggregator.add_vote(make_vote(block, ks[0])).qc.has_value());
  CHECK(!aggregator.add_vote(make_vote(block, ks[1])).qc.has_value());
  auto result = aggregator.add_vote(make_vote(block, ks[2]));
  CHECK(result.qc.has_value());
  CHECK(result.qc->hash == block.digest());
  CHECK(result.qc->verify(committee).ok());
  // Duplicate vote rejected.
  CHECK(!aggregator.add_vote(make_vote(block, ks[0])).error.empty());
  // Cleanup drops the round.
  aggregator.cleanup(10);
  auto after = aggregator.add_vote(make_vote(block, ks[0]));
  CHECK(after.error.empty());
}

namespace {

struct CoreFixture {
  ChannelPtr<CoreEvent> tx_core = make_channel<CoreEvent>();
  ChannelPtr<ProposerMessage> tx_proposer = make_channel<ProposerMessage>();
  ChannelPtr<Block> tx_commit = make_channel<Block>();
  ChannelPtr<mempool::ConsensusMempoolMessage> tx_mempool =
      make_channel<mempool::ConsensusMempoolMessage>();
  Store store = Store::open("");
  std::thread core_thread;

  // Spawns a core for fixture key `idx` with the given committee.
  void spawn_core(size_t idx, const Committee& committee,
                  uint64_t timeout_delay = 60'000, uint32_t chain_depth = 2) {
    Parameters params;
    params.timeout_delay = timeout_delay;
    params.chain_depth = chain_depth;
    spawn_core_params(idx, committee, params);
  }

  void spawn_core_params(size_t idx, const Committee& committee,
                         const Parameters& params) {
    auto kp = keys()[idx];
    SignatureService service(kp.secret);
    auto leader_elector = std::make_shared<LeaderElector>(committee);
    auto mempool_driver =
        std::make_shared<MempoolDriver>(store, tx_mempool, tx_core);
    auto synchronizer = std::make_shared<Synchronizer>(
        kp.name, committee, store, tx_core, /*sync_retry_delay=*/60'000);
    core_thread = Core::spawn(kp.name, committee, service, store,
                              leader_elector, mempool_driver, synchronizer,
                              params, tx_core, tx_proposer, tx_commit);
  }

  ~CoreFixture() {
    tx_core->close();
    tx_proposer->close();
    tx_commit->close();
    tx_mempool->close();
    if (core_thread.joinable()) core_thread.join();
  }
};

}  // namespace

TEST(core_votes_on_valid_proposal) {
  // Replica receives a proposal for round 1 and sends a vote to the next
  // leader (core_tests.rs:70-101 analogue).
  auto committee = consensus_committee(8300);
  auto chain = make_chain(1, committee);
  const Block& block = chain[0];

  // We are node idx such that leader(2) != us; vote goes over the network
  // to leader(2)'s consensus address.
  auto sorted = committee.sorted_keys();
  PublicKey next_leader = sorted[2 % sorted.size()];
  size_t us = 0;
  while (keys()[us].name == next_leader) us++;

  auto l = Listener::bind(*committee.address(next_leader));
  CHECK(l.has_value());
  auto delivered = make_channel<Bytes>();
  auto t = listener(std::move(*l),
                    [delivered](Bytes b) { delivered->send(std::move(b)); });

  CoreFixture fx;
  fx.spawn_core(us, committee);
  fx.tx_core->send(CoreEvent::msg(
      ConsensusMessage::deserialize(ConsensusMessage::propose(block))));

  auto got = delivered->recv();
  CHECK(got.has_value());
  auto msg = ConsensusMessage::deserialize(*got);
  CHECK(msg.kind == ConsensusMessage::Kind::kVote);
  CHECK(msg.vote.hash == block.digest());
  CHECK(msg.vote.verify(committee).ok());
  t.join();
}

TEST(core_makes_proposal_on_qc) {
  // Leader of round 2 collects 2f+1 votes for a round-1 block and asks the
  // proposer to make a block (core_tests.rs:103-130 analogue).
  auto committee = consensus_committee(8400);
  auto chain = make_chain(1, committee);
  const Block& block = chain[0];
  auto sorted = committee.sorted_keys();
  PublicKey leader2 = sorted[2 % sorted.size()];
  size_t us = 0;
  while (keys()[us].name != leader2) us++;

  CoreFixture fx;
  fx.spawn_core(us, committee);
  for (size_t i = 0; i < 3; i++) {
    fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
        ConsensusMessage::vote_msg(make_vote(block, keys()[i])))));
  }
  auto msg = fx.tx_proposer->recv();
  CHECK(msg.has_value());
  CHECK(msg->kind == ProposerMessage::Kind::kMake);
  CHECK(msg->round == 2);
  CHECK(msg->qc.hash == block.digest());
}

TEST(core_commits_two_chain) {
  // Processing blocks 1..3 of a chain commits block 1 (2-chain rule;
  // core_tests.rs:132-160 analogue). Payloads make commits observable.
  auto committee = consensus_committee(8500);
  CoreFixture fx;

  // Build a chain whose blocks carry payload digests already in the store
  // so MempoolDriver::verify passes.
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  std::vector<Block> chain;
  QC qc;
  for (uint64_t round = 1; round <= 3; round++) {
    Bytes payload_bytes{uint8_t(round)};
    Digest payload = sha512_digest(payload_bytes);
    fx.store.write(payload.to_bytes(), payload_bytes);
    Block b = make_block(qc, key_for(sorted[round % sorted.size()]), round,
                         {payload});
    qc = make_qc(b.digest(), b.round);
    chain.push_back(std::move(b));
  }

  // We are a replica that never leads rounds 1..4 if possible; any node
  // works since votes to other leaders go to dead addresses (SimpleSender
  // drops them silently).
  fx.spawn_core(0, committee);
  for (const Block& b : chain) {
    fx.tx_core->send(CoreEvent::msg(
        ConsensusMessage::deserialize(ConsensusMessage::propose(b))));
  }
  auto committed = fx.tx_commit->recv();
  CHECK(committed.has_value());
  CHECK(committed->round == 1);
  CHECK(committed->digest() == chain[0].digest());
}

TEST(core_commits_three_chain_one_round_later) {
  // Under chain_depth=3 the commit rule needs THREE consecutive certified
  // rounds: processing blocks 1..3 (which under 2-chain already commits
  // block 1) must commit nothing, and block 4 then commits block 1 — the
  // "+1 round of commit latency" the 3-chain variant exists to measure.
  auto committee = consensus_committee(8700);
  CoreFixture fx;
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  std::vector<Block> chain;
  QC qc;
  for (uint64_t round = 1; round <= 4; round++) {
    Bytes payload_bytes{uint8_t(round)};
    Digest payload = sha512_digest(payload_bytes);
    fx.store.write(payload.to_bytes(), payload_bytes);
    Block b = make_block(qc, key_for(sorted[round % sorted.size()]), round,
                         {payload});
    qc = make_qc(b.digest(), b.round);
    chain.push_back(std::move(b));
  }
  fx.spawn_core(0, committee, /*timeout_delay=*/60'000, /*chain_depth=*/3);
  for (size_t i = 0; i < 3; i++) {
    fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
        ConsensusMessage::propose(chain[i]))));
  }
  Block none;
  auto status = fx.tx_commit->recv_until(
      &none, std::chrono::steady_clock::now() + std::chrono::milliseconds(500));
  CHECK(status == RecvStatus::kTimeout);  // 2-chain would have committed B1

  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::propose(chain[3]))));
  auto committed = fx.tx_commit->recv();
  CHECK(committed.has_value());
  CHECK(committed->round == 1);
  CHECK(committed->digest() == chain[0].digest());
}

TEST(core_commits_four_chain_two_rounds_later) {
  // The generalized k-chain walk at chain_depth=4: FOUR consecutive
  // certified rounds are needed, so blocks 1..4 commit nothing (3-chain
  // would have committed B1 at block 4) and block 5 commits block 1.
  auto committee = consensus_committee(8710);
  CoreFixture fx;
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  std::vector<Block> chain;
  QC qc;
  for (uint64_t round = 1; round <= 5; round++) {
    Bytes payload_bytes{uint8_t(round)};
    Digest payload = sha512_digest(payload_bytes);
    fx.store.write(payload.to_bytes(), payload_bytes);
    Block b = make_block(qc, key_for(sorted[round % sorted.size()]), round,
                         {payload});
    qc = make_qc(b.digest(), b.round);
    chain.push_back(std::move(b));
  }
  fx.spawn_core(0, committee, /*timeout_delay=*/60'000, /*chain_depth=*/4);
  for (size_t i = 0; i < 4; i++) {
    fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
        ConsensusMessage::propose(chain[i]))));
  }
  Block none;
  auto status = fx.tx_commit->recv_until(
      &none, std::chrono::steady_clock::now() + std::chrono::milliseconds(500));
  CHECK(status == RecvStatus::kTimeout);
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::propose(chain[4]))));
  auto committed = fx.tx_commit->recv();
  CHECK(committed.has_value());
  CHECK(committed->round == 1);
  CHECK(committed->digest() == chain[0].digest());
}

// -- graftdag: certificate-carrying blocks ----------------------------------

namespace {

// 2f+1 signed availability ACKs over `batch_digest` from the first 3
// fixture keys (the mempool QuorumWaiter's output, rebuilt by hand).
mempool::BatchCertificate make_cert(const Digest& batch_digest) {
  mempool::BatchCertificate cert;
  cert.digest = batch_digest;
  Digest ack = cert.ack_digest();
  auto ks = keys();
  for (size_t i = 0; i < 3; i++) {
    cert.votes.emplace_back(ks[i].name,
                            Signature::sign_host(ack, ks[i].secret));
  }
  return cert;
}

}  // namespace

TEST(block_with_certs_serde_and_shape_checks) {
  auto committee = consensus_committee(8730);
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  Digest payload = sha512_digest(Bytes{1, 2, 3});
  Block b = make_block(QC{}, key_for(sorted[1 % sorted.size()]), 1, {payload});
  b.certs.push_back(make_cert(payload));
  // Certs are NOT covered by digest(): attaching one after signing must
  // not invalidate the author signature (two blocks differing only in
  // cert vote sets order the same batches).
  CHECK(b.signature.verify(b.digest(), b.author));
  CHECK(b.check_certs(committee).ok());
  CHECK(b.verify(committee).ok());

  // Serde round trip carries the certificate byte-for-byte.
  Block rt = Block::from_bytes(b.to_bytes());
  CHECK(rt.digest() == b.digest());
  CHECK(rt.certs.size() == 1);
  CHECK(rt.certs[0].digest == payload);
  CHECK(rt.certs[0].content_digest() == b.certs[0].content_digest());
  CHECK(rt.verify(committee).ok());

  // Shape violations: cert over the WRONG digest, and a cert count that
  // does not match the payload list.
  Block wrong = b;
  wrong.certs[0] = make_cert(sha512_digest(Bytes{4, 5, 6}));
  CHECK(!wrong.check_certs(committee).ok());
  Block extra = b;
  extra.certs.push_back(make_cert(payload));
  CHECK(!extra.check_certs(committee).ok());
  // A padded (over-quorum) certificate fails the structural check too.
  Block padded = b;
  padded.certs[0].votes.emplace_back(
      ks[3].name,
      Signature::sign_host(padded.certs[0].ack_digest(), ks[3].secret));
  CHECK(!padded.check_certs(committee).ok());
}

TEST(core_votes_on_certified_proposal_without_payload) {
  // Vote-without-possession: the payload bytes are NOT in our store, but
  // the block carries an availability certificate — the core must vote
  // anyway (the cert proves retrievability) and fire a cert-driven
  // prefetch naming the signers as holders, never suspending the round.
  auto committee = consensus_committee(8740);
  auto chain = make_chain(1, committee);
  Block block = chain[0];
  Digest payload = sha512_digest(Bytes{7, 7, 7});
  block.payload = {payload};
  block.certs = {make_cert(payload)};
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  block.signature =
      Signature::sign(block.digest(), key_for(block.author).secret);

  PublicKey next_leader = sorted[2 % sorted.size()];
  size_t us = 0;
  while (keys()[us].name == next_leader) us++;
  auto l = Listener::bind(*committee.address(next_leader));
  CHECK(l.has_value());
  auto delivered = make_channel<Bytes>();
  auto t = listener(std::move(*l),
                    [delivered](Bytes b) { delivered->send(std::move(b)); });

  CoreFixture fx;
  fx.spawn_core(us, committee);
  fx.tx_core->send(CoreEvent::msg(
      ConsensusMessage::deserialize(ConsensusMessage::propose(block))));

  // The prefetch goes out BEFORE the block is processed: one Synchronize
  // per missing certified digest, holders = the cert's signers.
  auto sync = fx.tx_mempool->recv();
  CHECK(sync.has_value());
  CHECK(sync->kind == mempool::ConsensusMempoolMessage::Kind::kSynchronize);
  CHECK(sync->digests.size() == 1);
  CHECK(sync->digests[0] == payload);
  CHECK(sync->target == block.author);
  CHECK(sync->holders.size() == 3);
  CHECK(sync->holders[0] == block.certs[0].votes[0].first);

  auto got = delivered->recv();
  CHECK(got.has_value());
  auto msg = ConsensusMessage::deserialize(*got);
  CHECK(msg.kind == ConsensusMessage::Kind::kVote);
  CHECK(msg.vote.hash == block.digest());
  CHECK(msg.vote.verify(committee).ok());
  t.join();
}

TEST(aggregator_gc_committed_drops_dead_rounds) {
  // Commit-keyed GC: everything at or below the committed round dies
  // (its QC already exists), later rounds keep aggregating.
  auto committee = consensus_committee(8750);  // address book only
  Aggregator aggregator(committee);
  auto chain = make_chain(3, committee);
  auto ks = keys();
  aggregator.add_vote(make_vote(chain[0], ks[0]));  // round 1
  aggregator.add_vote(make_vote(chain[1], ks[0]));  // round 2
  aggregator.add_vote(make_vote(chain[2], ks[0]));  // round 3
  CHECK(aggregator.gc_committed(2) == 2);  // rounds 1 and 2 dropped
  CHECK(aggregator.gc_committed(2) == 0);  // idempotent
  // Round 1's state is gone: the same vote admits cleanly again.
  CHECK(aggregator.add_vote(make_vote(chain[0], ks[0])).error.empty());
  // Round 3 survived: its duplicate-author guard still remembers ks[0].
  CHECK(!aggregator.add_vote(make_vote(chain[2], ks[0])).error.empty());
}

TEST(core_broadcasts_timeout_on_timer) {
  // Timer fires -> Timeout broadcast to all peers (core_tests.rs:162-192).
  auto committee = consensus_committee(8600);
  size_t us = 0;
  auto delivered = make_channel<Bytes>();
  std::vector<std::thread> threads;
  for (const auto& [name, addr] : committee.broadcast_addresses(
           keys()[us].name)) {
    auto l = Listener::bind(addr);
    CHECK(l.has_value());
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  CoreFixture fx;
  fx.spawn_core(us, committee, /*timeout_delay=*/100);
  for (size_t i = 0; i < 3; i++) {
    auto got = delivered->recv();
    CHECK(got.has_value());
    auto msg = ConsensusMessage::deserialize(*got);
    CHECK(msg.kind == ConsensusMessage::Kind::kTimeout);
    CHECK(msg.timeout.round == 1);
    CHECK(msg.timeout.verify(committee).ok());
  }
  for (auto& t : threads) t.join();
}

TEST(core_restores_persisted_state_after_restart) {
  // Crash recovery (EXCEEDS the reference, which leaves this state
  // volatile — core.rs:112 TODO): drive a core through rounds 1..3 on a
  // shared store, tear it down, restart a fresh core on the SAME store,
  // and observe via its first timeout broadcast that it resumed at the
  // persisted round (and voting watermark) instead of round 1.
  auto committee = consensus_committee(8800);
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  Store store = Store::open("");
  std::vector<Block> chain;
  QC qc;
  for (uint64_t round = 1; round <= 3; round++) {
    Bytes payload_bytes{uint8_t(round)};
    Digest payload = sha512_digest(payload_bytes);
    store.write(payload.to_bytes(), payload_bytes);
    Block b = make_block(qc, key_for(sorted[round % sorted.size()]), round,
                         {payload});
    qc = make_qc(b.digest(), b.round);
    chain.push_back(std::move(b));
  }

  {
    CoreFixture fx;
    fx.store = store;
    fx.spawn_core(0, committee);
    for (const Block& b : chain) {
      fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
          ConsensusMessage::propose(b))));
    }
    // Wait until the chain is fully processed (block 1 commits under the
    // 2-chain rule), so round_/high_qc_ were persisted before teardown.
    auto committed = fx.tx_commit->recv();
    CHECK(committed.has_value());
    CHECK(committed->round == 1);
  }  // fixture teardown = crash

  // Restart on the same store; listeners catch its timeout broadcast.
  auto delivered = make_channel<Bytes>();
  std::vector<std::thread> threads;
  for (const auto& [name, addr] :
       committee.broadcast_addresses(keys()[0].name)) {
    auto l = Listener::bind(addr);
    CHECK(l.has_value());
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  CoreFixture fx2;
  fx2.store = store;
  fx2.spawn_core(0, committee, /*timeout_delay=*/100);
  auto got = delivered->recv();
  CHECK(got.has_value());
  auto msg = ConsensusMessage::deserialize(*got);
  CHECK(msg.kind == ConsensusMessage::Kind::kTimeout);
  // Blocks 1..3 certify rounds 1..2 in QCs; processing block 3 (qc for
  // round 2) advanced the core to round 3. An amnesiac core would time
  // out at round 1.
  CHECK(msg.timeout.round == 3);
  CHECK(msg.timeout.verify(committee).ok());
  for (auto& t : threads) t.join();
}

// ---------------------------------------------------------------------------
// graftview: optimistic batched TC assembly + pacemaker hardening
// ---------------------------------------------------------------------------

namespace {

// A timeout for `round` from fixture key `i`; valid=false forges the
// author with a garbage signature (the spoof a Byzantine peer can send
// now that admission defers signature verification to the batch).
consensus::Timeout make_timeout(size_t i, uint64_t round,
                                bool valid = true) {
  consensus::Timeout t;
  t.round = round;
  t.author = keys()[i].name;
  if (valid) {
    t.signature = Signature::sign(
        consensus::Timeout::vote_digest(round, t.high_qc.round),
        keys()[i].secret);
  }
  return t;  // default signature = 64 zero bytes, never verifies
}

}  // namespace

TEST(aggregator_batched_timeout_eject_matches_per_sig) {
  // The eject path must accept/reject EXACTLY the sets per-signature
  // verification would: a spoofed entry is ejected when the batch
  // verdict (realized here as the per-sig loop the Core runs on batch
  // failure) rejects it, the authority slot reopens for the genuine
  // author, the same bad bytes are refused at admission, and the sealed
  // TC re-verifies per-signature.
  auto committee = consensus_committee(8650);
  Aggregator agg(committee);
  auto ks = keys();
  const uint64_t round = 7;

  CHECK(agg.add_timeout(make_timeout(2, round, false)).candidates.empty());
  CHECK(agg.add_timeout(make_timeout(0, round)).candidates.empty());
  auto res = agg.add_timeout(make_timeout(1, round));
  CHECK(!res.tc.has_value());
  CHECK(res.candidates.size() == 3);  // quorum stake present, unverified

  // The batch verdict: per-signature host verification (what the Core
  // does when the one-launch verdict comes back false).
  std::vector<PublicKey> good, bad;
  for (const auto& c : res.candidates) {
    if (c.signature.verify(
            consensus::Timeout::vote_digest(round, c.high_qc_round),
            c.author)) {
      good.push_back(c.author);
    } else {
      bad.push_back(c.author);
    }
  }
  CHECK(good.size() == 2);
  CHECK(bad.size() == 1 && bad[0] == ks[2].name);

  auto after = agg.resolve_timeouts(round, good, bad);
  CHECK(!after.tc.has_value());        // quorum lost: delay, not a TC
  CHECK(after.candidates.empty());
  CHECK(agg.ejected_total() == 1);

  // The identical bad bytes re-sent are refused without another batch.
  CHECK(!agg.add_timeout(make_timeout(2, round, false)).error.empty());

  // ... but the GENUINE author's honest timeout re-completes the quorum:
  // one Byzantine spoof delayed TC formation, it could not prevent it.
  auto res2 = agg.add_timeout(make_timeout(2, round));
  CHECK(res2.candidates.size() == 1);
  auto sealed = agg.resolve_timeouts(round, {ks[2].name}, {});
  CHECK(sealed.tc.has_value());
  CHECK(sealed.tc->votes.size() == 3);
  CHECK(sealed.tc->verify(committee).ok());  // per-signature re-verify
}

TEST(aggregator_all_fail_batch_does_not_blacklist) {
  // An ALL-fail batch reads as a verifier outage (scheme=bls with a
  // dead sidecar has no host pairing: every honest signature fails), so
  // the bytes are NOT blacklisted — the deterministic honest
  // re-broadcasts re-enter once the verifier is back, and the round can
  // still form its TC.  Only a MIXED outcome (some candidate verified)
  // proves the failures are genuinely bad signatures worth remembering.
  auto committee = consensus_committee(8655);
  Aggregator agg(committee);
  auto ks = keys();
  const uint64_t round = 9;
  agg.add_timeout(make_timeout(0, round));
  agg.add_timeout(make_timeout(1, round));
  auto res = agg.add_timeout(make_timeout(2, round));
  CHECK(res.candidates.size() == 3);
  // Outage: everyone "failed" — eject all, blacklist none.
  auto after = agg.resolve_timeouts(
      round, {}, {ks[0].name, ks[1].name, ks[2].name});
  CHECK(!after.tc.has_value());
  // The SAME bytes re-sent are re-admitted (not "previously ejected")
  // and complete the quorum once the verifier answers honestly.
  CHECK(agg.add_timeout(make_timeout(0, round)).error.empty());
  CHECK(agg.add_timeout(make_timeout(1, round)).error.empty());
  auto res2 = agg.add_timeout(make_timeout(2, round));
  CHECK(res2.candidates.size() == 3);
  auto sealed = agg.resolve_timeouts(
      round, {ks[0].name, ks[1].name, ks[2].name}, {});
  CHECK(sealed.tc.has_value());
  CHECK(sealed.tc->verify(committee).ok());
}

TEST(aggregator_pre_verified_timeouts_seal_without_batch) {
  // The synchronous path (no sidecar pipeline room) verifies inline and
  // admits pre-verified entries: the third one seals directly, no
  // candidate round-trip.
  auto committee = consensus_committee(8660);
  Aggregator agg(committee);
  CHECK(agg.add_timeout(make_timeout(0, 3), true).candidates.empty());
  CHECK(agg.add_timeout(make_timeout(1, 3), true).candidates.empty());
  auto res = agg.add_timeout(make_timeout(2, 3), true);
  CHECK(res.candidates.empty());
  CHECK(res.tc.has_value());
  CHECK(res.tc->verify(committee).ok());
}

TEST(aggregator_rejects_unknown_timeout_author) {
  // Stake check moved to admission: with signatures unverified until the
  // batch, this is what bounds aggregation state to the committee.
  auto committee = consensus_committee(8670);
  Aggregator agg(committee);
  std::array<uint8_t, 32> seed{};
  seed[0] = 77;
  auto unknown = keypair_from_seed(seed);
  consensus::Timeout t;
  t.round = 2;
  t.author = unknown.name;
  t.signature = Signature::sign(t.digest(), unknown.secret);
  auto res = agg.add_timeout(t);
  CHECK(!res.error.empty());
  CHECK(res.error.find("unknown timeout author") != std::string::npos);
}

TEST(core_forms_tc_batched_with_spoofed_signer_ejected) {
  // End to end through the Core's event loop: a spoofed timeout is
  // admitted optimistically, the quorum-triggered batch verify ejects
  // it (per-sig host fallback), and the genuine author's later timeout
  // completes the TC — which every peer receives and verifies.
  auto committee = consensus_committee(8850);
  auto ks = keys();
  auto delivered = make_channel<Bytes>();
  std::vector<std::thread> threads;
  for (const auto& [name, addr] :
       committee.broadcast_addresses(ks[0].name)) {
    auto l = Listener::bind(addr);
    CHECK(l.has_value());
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  CoreFixture fx;
  fx.spawn_core(0, committee);  // timer far away (60 s)
  // Spoof first so it occupies k1's authority slot before the genuine
  // timeout could; then two honest timeouts complete the quorum stake.
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(1, 1, false)))));
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(2, 1)))));
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(3, 1)))));
  // Quorum reached -> batch verify (host loop, no sidecar) -> spoof
  // ejected -> no TC yet.  The genuine k1 timeout re-completes it.
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(1, 1)))));
  auto got = delivered->recv();
  CHECK(got.has_value());
  auto msg = ConsensusMessage::deserialize(*got);
  CHECK(msg.kind == ConsensusMessage::Kind::kTC);
  CHECK(msg.tc.round == 1);
  CHECK(msg.tc.votes.size() == 3);
  CHECK(msg.tc.verify(committee).ok());
  // The spoofed signature is NOT in the sealed set: the accepted set is
  // exactly what per-signature admission would have built.
  for (const auto& [author, sig, hq] : msg.tc.votes) {
    CHECK(sig.verify(consensus::Timeout::vote_digest(1, hq), author));
  }
  for (auto& t : threads) t.join();
}

TEST(core_forms_tc_from_fallback_sigs_with_sidecar_stopped) {
  // Sidecar stopped mid-round under scheme=bls (the PR 15 view-change
  // note): the committee keeps signing timeouts with the 64-byte host
  // Ed25519 fallback (Signature::sign with a dead sidecar), and the
  // quorum-triggered batch verify takes the HOST path — no sidecar
  // round-trip, no stall — so TC assembly stays live through the outage.
  uint16_t dead_port;
  {
    // Reserve a port with nothing listening by binding and releasing it.
    auto l = Listener::bind({"127.0.0.1", 0});
    CHECK(l.has_value());
    dead_port = l->port();
  }
  // Uninstalls the globals and restores the scheme even on early CHECK
  // failure; declared before the fixture so the core thread joins first.
  struct BlsGuard {
    ~BlsGuard() {
      TpuVerifier::install(nullptr);
      BlsContext::install(nullptr);
      set_scheme(Scheme::kEd25519);
    }
  } guard;
  TpuVerifier::install(
      std::make_unique<TpuVerifier>(Address{"127.0.0.1", dead_port}));
  auto bls = std::make_unique<BlsContext>();
  bls->secret = Bytes(48, 1);
  BlsContext::install(std::move(bls));
  set_scheme(Scheme::kBls);

  auto committee = consensus_committee(8880);
  auto ks = keys();
  auto delivered = make_channel<Bytes>();
  std::vector<std::thread> threads;
  for (const auto& [name, addr] :
       committee.broadcast_addresses(ks[0].name)) {
    auto l = Listener::bind(addr);
    CHECK(l.has_value());
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  CoreFixture fx;
  fx.spawn_core(0, committee);  // timer far away (60 s)
  // make_timeout signs through Signature::sign, which with the dead
  // sidecar produces exactly what outage peers emit: 64-byte fallback.
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(1, 1)))));
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(2, 1)))));
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(3, 1)))));
  auto got = delivered->recv();
  CHECK(got.has_value());
  auto msg = ConsensusMessage::deserialize(*got);
  CHECK(msg.kind == ConsensusMessage::Kind::kTC);
  CHECK(msg.tc.round == 1);
  CHECK(msg.tc.votes.size() == 3);
  // The sealed TC is all host-fallback signatures and verifies under
  // scheme=bls via length dispatch — receivers do not need the sidecar.
  for (const auto& [author, sig, hq] : msg.tc.votes) {
    CHECK(sig.data.size() == 64);
  }
  CHECK(msg.tc.verify(committee).ok());
  for (auto& t : threads) t.join();
}

TEST(core_spoof_flood_cannot_starve_tc_formation) {
  // One-strike optimism: after a batch ejects a spoof, the round falls
  // back to inline per-signature admission — a spoofer re-occupying the
  // reopened slot with FRESH garbage bytes is now rejected at arrival
  // (it cannot waste a second batch or block the genuine author), and
  // the honest re-broadcasts still complete the TC.
  auto committee = consensus_committee(8870);
  auto ks = keys();
  auto delivered = make_channel<Bytes>();
  std::vector<std::thread> threads;
  for (const auto& [name, addr] :
       committee.broadcast_addresses(ks[0].name)) {
    auto l = Listener::bind(addr);
    CHECK(l.has_value());
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  CoreFixture fx;
  fx.spawn_core(0, committee);
  // Spoofs for TWO authors + one honest timeout reach quorum stake;
  // the batch ejects both spoofs (round goes inline).
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(1, 1, false)))));
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(2, 1, false)))));
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(3, 1)))));
  // The attacker races the reopened slots with FRESH garbage (distinct
  // bytes, so the blacklist alone would not catch them): inline
  // admission rejects each without a batch.
  for (int wave = 0; wave < 3; wave++) {
    consensus::Timeout spoof = make_timeout(1, 1, false);
    spoof.signature.data[0] = uint8_t(7 + wave);  // fresh bytes per wave
    fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
        ConsensusMessage::timeout_msg(spoof))));
  }
  // The genuine authors' honest re-broadcasts complete the quorum.
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(1, 1)))));
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::timeout_msg(make_timeout(2, 1)))));
  auto got = delivered->recv();
  CHECK(got.has_value());
  auto msg = ConsensusMessage::deserialize(*got);
  CHECK(msg.kind == ConsensusMessage::Kind::kTC);
  CHECK(msg.tc.round == 1);
  CHECK(msg.tc.verify(committee).ok());
  for (auto& t : threads) t.join();
}

TEST(core_drops_future_round_timeout_flood) {
  // Bounded timeout aggregation: a flood of timeouts for rounds far past
  // the horizon is dropped without consuming authority slots or
  // aggregation state — afterwards a legitimate in-horizon view change
  // still completes from the same authors.
  auto committee = consensus_committee(8860);
  auto ks = keys();
  auto delivered = make_channel<Bytes>();
  std::vector<std::thread> threads;
  for (const auto& [name, addr] :
       committee.broadcast_addresses(ks[0].name)) {
    auto l = Listener::bind(addr);
    CHECK(l.has_value());
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  CoreFixture fx;
  Parameters params;
  params.timeout_delay = 60'000;
  params.timeout_future_horizon = 5;
  fx.spawn_core_params(0, committee, params);
  // 100 far-future rounds from every authority: all dropped (the
  // aggregator map must not grow a TCMaker per attacker-chosen round).
  for (uint64_t r = 1'000'000'000; r < 1'000'000'100; r++) {
    fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
        ConsensusMessage::timeout_msg(make_timeout(1, r, false)))));
  }
  // An in-horizon view change for round 6 (= round_ 1 + horizon 5) from
  // the same authors completes: nothing was consumed by the flood.
  for (size_t i = 1; i <= 3; i++) {
    fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
        ConsensusMessage::timeout_msg(make_timeout(i, 6)))));
  }
  auto got = delivered->recv();
  CHECK(got.has_value());
  auto msg = ConsensusMessage::deserialize(*got);
  CHECK(msg.kind == ConsensusMessage::Kind::kTC);
  CHECK(msg.tc.round == 6);
  CHECK(msg.tc.verify(committee).ok());
  for (auto& t : threads) t.join();
}

TEST(backoff_schedule_exponential_capped) {
  Parameters p;
  p.timeout_delay = 1'000;
  p.timeout_backoff_factor_pct = 200;
  p.timeout_backoff_cap = 7'000;
  CHECK(backoff_delay_ms(p, 0) == 1'000);  // today's behavior at depth 1
  CHECK(backoff_delay_ms(p, 1) == 2'000);
  CHECK(backoff_delay_ms(p, 2) == 4'000);
  CHECK(backoff_delay_ms(p, 3) == 7'000);  // capped
  CHECK(backoff_delay_ms(p, 50) == 7'000);  // deep storms cannot overflow
  p.timeout_backoff_factor_pct = 100;  // flat schedule = legacy pacemaker
  CHECK(backoff_delay_ms(p, 9) == 1'000);
  p.timeout_backoff_factor_pct = 150;
  CHECK(backoff_delay_ms(p, 1) == 1'500);
  p.timeout_backoff_cap = 10;  // a cap below the base never undercuts it
  CHECK(backoff_delay_ms(p, 0) == 1'000);
  CHECK(backoff_delay_ms(p, 5) == 1'000);
}

TEST(parameters_reject_bad_pacemaker_values) {
  bool threw = false;
  try {
    Parameters::from_json(Json::parse("{\"timeout_backoff_factor_pct\": 50}"));
  } catch (const std::exception&) {
    threw = true;
  }
  CHECK(threw);
  threw = false;
  try {
    Parameters::from_json(Json::parse("{\"timeout_future_horizon\": 0}"));
  } catch (const std::exception&) {
    threw = true;
  }
  CHECK(threw);
  // defaults parse clean and preserve the documented schedule knobs
  Parameters p = Parameters::from_json(Json::parse("{}"));
  CHECK(p.timeout_backoff_factor_pct == 200);
  CHECK(p.timeout_backoff_cap == 60'000);
  CHECK(p.timeout_jitter_pct == 10);
  CHECK(p.timeout_future_horizon == 1'000);
}

TEST(qc_verify_rejects_overweight_certificate) {
  // Equal-stake committees reject certificates padded beyond the quorum
  // (a Byzantine leader's all-n certificate would otherwise force every
  // verifier onto an unwarmed sidecar shape at once — ADVICE r4).
  auto committee = consensus_committee(8900);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);  // exactly the quorum (3)
  qc.votes.emplace_back(keys()[3].name,
                        Signature::sign(qc.digest(), keys()[3].secret));
  auto r = qc.verify(committee);
  CHECK(!r.ok());
  CHECK(r.error.find("more votes than a quorum") != std::string::npos);
}

TEST(small_order_pk_and_r_rejected) {
  // verify_strict parity on the HOST path (ADVICE r4): the identity-point
  // public key admits a universal forgery under plain RFC 8032, which
  // OpenSSL accepts; the C++ path must reject it like the device path
  // does, or a node with a dead sidecar diverges from its peers.
  Digest msg = sha512_digest(Bytes{42});
  // pk = identity encoding (y=1), sig = ([S]B || S) with S=0:
  // R = [0]B = identity, S = 0. Equation: [0]B == R + [k]A holds for ANY
  // message since R and A are both the identity.
  PublicKey identity_pk;
  identity_pk.data.fill(0);
  identity_pk.data[0] = 1;
  Signature forged;
  forged.data.assign(64, 0);
  forged.data[0] = 1;  // R = identity encoding too
  CHECK(!forged.verify(msg, identity_pk));

  // A genuine signature still verifies after the guard.
  auto kp = keys()[0];
  Signature good = Signature::sign(msg, kp.secret);
  CHECK(good.verify(msg, kp.name));
}

namespace {

// Minimal in-process stand-in for the verify sidecar: accepts ONE
// connection, parses Ed25519 verify-batch requests
// (sidecar/protocol.py framing), and answers all-valid — but only after
// `release` is signalled, so tests can observe the Core doing other work
// while a verification is in flight.
struct FakeSidecar {
  Listener listener;
  ChannelPtr<uint32_t> request_seen = make_channel<uint32_t>();
  ChannelPtr<bool> release = make_channel<bool>();
  std::thread thread;
  Address addr;

  explicit FakeSidecar(uint16_t port) {
    auto l = Listener::bind({"127.0.0.1", port});
    if (!l) throw std::runtime_error("fake sidecar bind failed");
    addr = {"127.0.0.1", l->port()};
    listener = std::move(*l);
    thread = std::thread([this] {
      auto sock = listener.accept();
      if (!sock) return;
      Bytes frame;
      while (sock->read_frame(&frame)) {
        Reader r(frame);
        uint8_t op = r.u8();
        uint32_t rid = r.u32();
        uint32_t count = r.u32();
        request_seen->send(count);
        if (!release->recv()) return;  // hold the reply until told
        Writer w;
        w.u8(op);
        w.u32(rid);
        w.u32(count);
        for (uint32_t i = 0; i < count; i++) w.u8(1);
        if (!sock->write_frame(w.out)) return;
      }
    });
  }

  ~FakeSidecar() {
    request_seen->close();
    release->close();
    // Destroying the client closes its socket, which unblocks the fake's
    // read_frame (EOF); only then can the thread be joined.
    TpuVerifier::install(nullptr);
    listener.shutdown();
    if (thread.joinable()) thread.join();
  }
};

}  // namespace

TEST(core_processes_votes_while_verify_in_flight) {
  // The async-dispatch contract (SURVEY.md §7 latency discipline): a
  // proposal whose QC is being verified on the device suspends, and the
  // Core keeps handling votes meanwhile — forming a QC and asking the
  // proposer for a block BEFORE the device verdict arrives.  When the
  // verdict lands, the suspended proposal resumes and commits.
  auto committee = consensus_committee(9100);
  FakeSidecar sidecar(0);
  TpuVerifier::install(std::make_unique<TpuVerifier>(sidecar.addr));

  CoreFixture fx;
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  // Chain of 3: b2/b3 carry non-genesis QCs (the device-verified part);
  // processing b3 commits b1 under the 2-chain rule.
  std::vector<Block> chain;
  QC qc;
  for (uint64_t round = 1; round <= 3; round++) {
    Bytes payload_bytes{uint8_t(round)};
    Digest payload = sha512_digest(payload_bytes);
    fx.store.write(payload.to_bytes(), payload_bytes);
    Block b = make_block(qc, key_for(sorted[round % sorted.size()]), round,
                         {payload});
    qc = make_qc(b.digest(), b.round);
    chain.push_back(std::move(b));
  }

  // Run as the leader of round 3, so a vote quorum for b2 visibly turns
  // into a ProposerMessage::kMake.
  PublicKey leader3 = sorted[3 % sorted.size()];
  size_t us = 0;
  while (ks[us].name != leader3) us++;
  fx.spawn_core(us, committee);

  // b1 (genesis QC: nothing to dispatch) processes synchronously.
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::propose(chain[0]))));
  // Propose b2: its QC dispatches to the (stalling) sidecar and suspends.
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::propose(chain[1]))));
  auto seen = sidecar.request_seen->recv();
  CHECK(seen.has_value());
  CHECK(*seen == 3);  // the QC's 2f+1 votes

  // While the verdict is pending, feed 2f+1 votes for b2; the Core must
  // process them NOW and ask the proposer for a round-3 block.
  for (size_t i = 0; i < 3; i++) {
    fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
        ConsensusMessage::vote_msg(make_vote(chain[1], ks[i])))));
  }
  // Skip kCleanup traffic from block processing; the QC-completion signal
  // is the kMake.
  std::optional<ProposerMessage> msg;
  while ((msg = fx.tx_proposer->recv()) &&
         msg->kind == ProposerMessage::Kind::kCleanup) {
  }
  CHECK(msg.has_value());
  CHECK(msg->kind == ProposerMessage::Kind::kMake);
  CHECK(msg->round == 3);
  CHECK(msg->qc.hash == chain[1].digest());

  // Release the device verdict; the suspended b2 resumes.  b3's QC was
  // formed by OUR aggregator from the votes above, so it is already in
  // the verified-certificate cache: proposing b3 must process without
  // another sidecar round-trip and commit b1 (2-chain rule).
  sidecar.release->send(true);
  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::propose(chain[2]))));
  auto committed = fx.tx_commit->recv();
  CHECK(committed.has_value());
  CHECK(committed->round == 1);
  CHECK(committed->digest() == chain[0].digest());
}

TEST(core_rejects_proposal_on_device_verdict_false) {
  // An all-invalid device verdict must reject the suspended proposal: no
  // vote is produced and nothing commits.
  auto committee = consensus_committee(9200);
  FakeSidecar sidecar(0);
  TpuVerifier::install(std::make_unique<TpuVerifier>(sidecar.addr));
  auto committee_keys = keys();
  CoreFixture fx;
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : committee_keys) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  std::vector<Block> chain;
  QC qc;
  for (uint64_t round = 1; round <= 2; round++) {
    Bytes payload_bytes{uint8_t(round)};
    Digest payload = sha512_digest(payload_bytes);
    fx.store.write(payload.to_bytes(), payload_bytes);
    Block b = make_block(qc, key_for(sorted[round % sorted.size()]), round,
                         {payload});
    qc = make_qc(b.digest(), b.round);
    chain.push_back(std::move(b));
  }
  fx.store.write(chain[0].digest().to_bytes(), chain[0].to_bytes());
  fx.spawn_core(0, committee);

  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::propose(chain[1]))));
  auto seen = sidecar.request_seen->recv();
  CHECK(seen.has_value());

  // Sidecar replies all-valid, but meanwhile deliver a FALSE verdict the
  // way the reply path would: inject the verdict event directly.  (The
  // real false-verdict wire path is covered by the fake above returning
  // 1s; the Core-side rejection logic is what this test pins.)
  fx.tx_core->send(CoreEvent::verdict_of(chain[1], false));
  Block none;
  auto status = fx.tx_commit->recv_until(
      &none,
      std::chrono::steady_clock::now() + std::chrono::milliseconds(400));
  CHECK(status == RecvStatus::kTimeout);  // nothing commits
  sidecar.release->send(true);  // unblock the fake's held reply
}

int main() { return run_all(); }

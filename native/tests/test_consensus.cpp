// Consensus tests (consensus/src/tests/ analogue): QC verification and its
// rejection paths, aggregator quorum formation + cleanup, core
// proposal->vote flow, votes->QC->proposal flow, chain commit, and timeout
// broadcast.
#include <thread>

#include "consensus/consensus.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;
using namespace hotstuff::consensus;

TEST(qc_verify_ok) {
  auto committee = consensus_committee(8100);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);
  CHECK(qc.verify(committee).ok());
}

TEST(qc_verify_rejects_authority_reuse) {
  auto committee = consensus_committee(8110);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);
  qc.votes.push_back(qc.votes[0]);  // duplicate voter
  CHECK(!qc.verify(committee).ok());
}

TEST(qc_verify_rejects_unknown_authority) {
  auto committee = consensus_committee(8120);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);
  std::array<uint8_t, 32> seed{};
  seed[0] = 99;
  auto unknown = keypair_from_seed(seed);
  qc.votes[0].first = unknown.name;
  CHECK(!qc.verify(committee).ok());
}

TEST(qc_verify_rejects_insufficient_stake) {
  auto committee = consensus_committee(8130);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);
  qc.votes.pop_back();  // 2 < quorum of 3
  CHECK(!qc.verify(committee).ok());
}

TEST(qc_verify_rejects_bad_signature) {
  auto committee = consensus_committee(8140);
  QC qc = make_qc(sha512_digest(Bytes{1}), 3);
  qc.votes[1].second.data[0] ^= 1;
  CHECK(!qc.verify(committee).ok());
}

TEST(aggregator_forms_qc_at_quorum) {
  auto committee = consensus_committee(8200);
  Aggregator aggregator(committee);
  auto chain = make_chain(1, committee);
  const Block& block = chain[0];
  auto ks = keys();
  // First two votes: no QC. Third: QC (2f+1 = 3).
  CHECK(!aggregator.add_vote(make_vote(block, ks[0])).qc.has_value());
  CHECK(!aggregator.add_vote(make_vote(block, ks[1])).qc.has_value());
  auto result = aggregator.add_vote(make_vote(block, ks[2]));
  CHECK(result.qc.has_value());
  CHECK(result.qc->hash == block.digest());
  CHECK(result.qc->verify(committee).ok());
  // Duplicate vote rejected.
  CHECK(!aggregator.add_vote(make_vote(block, ks[0])).error.empty());
  // Cleanup drops the round.
  aggregator.cleanup(10);
  auto after = aggregator.add_vote(make_vote(block, ks[0]));
  CHECK(after.error.empty());
}

namespace {

struct CoreFixture {
  ChannelPtr<CoreEvent> tx_core = make_channel<CoreEvent>();
  ChannelPtr<ProposerMessage> tx_proposer = make_channel<ProposerMessage>();
  ChannelPtr<Block> tx_commit = make_channel<Block>();
  ChannelPtr<mempool::ConsensusMempoolMessage> tx_mempool =
      make_channel<mempool::ConsensusMempoolMessage>();
  Store store = Store::open("");
  std::thread core_thread;

  // Spawns a core for fixture key `idx` with the given committee.
  void spawn_core(size_t idx, const Committee& committee,
                  uint64_t timeout_delay = 60'000, uint32_t chain_depth = 2) {
    auto kp = keys()[idx];
    SignatureService service(kp.secret);
    auto leader_elector = std::make_shared<LeaderElector>(committee);
    auto mempool_driver =
        std::make_shared<MempoolDriver>(store, tx_mempool, tx_core);
    auto synchronizer = std::make_shared<Synchronizer>(
        kp.name, committee, store, tx_core, /*sync_retry_delay=*/60'000);
    core_thread = Core::spawn(kp.name, committee, service, store,
                              leader_elector, mempool_driver, synchronizer,
                              timeout_delay, chain_depth, tx_core,
                              tx_proposer, tx_commit);
  }

  ~CoreFixture() {
    tx_core->close();
    tx_proposer->close();
    tx_commit->close();
    tx_mempool->close();
    if (core_thread.joinable()) core_thread.join();
  }
};

}  // namespace

TEST(core_votes_on_valid_proposal) {
  // Replica receives a proposal for round 1 and sends a vote to the next
  // leader (core_tests.rs:70-101 analogue).
  auto committee = consensus_committee(8300);
  auto chain = make_chain(1, committee);
  const Block& block = chain[0];

  // We are node idx such that leader(2) != us; vote goes over the network
  // to leader(2)'s consensus address.
  auto sorted = committee.sorted_keys();
  PublicKey next_leader = sorted[2 % sorted.size()];
  size_t us = 0;
  while (keys()[us].name == next_leader) us++;

  auto l = Listener::bind(*committee.address(next_leader));
  CHECK(l.has_value());
  auto delivered = make_channel<Bytes>();
  auto t = listener(std::move(*l),
                    [delivered](Bytes b) { delivered->send(std::move(b)); });

  CoreFixture fx;
  fx.spawn_core(us, committee);
  fx.tx_core->send(CoreEvent::msg(
      ConsensusMessage::deserialize(ConsensusMessage::propose(block))));

  auto got = delivered->recv();
  CHECK(got.has_value());
  auto msg = ConsensusMessage::deserialize(*got);
  CHECK(msg.kind == ConsensusMessage::Kind::kVote);
  CHECK(msg.vote.hash == block.digest());
  CHECK(msg.vote.verify(committee).ok());
  t.join();
}

TEST(core_makes_proposal_on_qc) {
  // Leader of round 2 collects 2f+1 votes for a round-1 block and asks the
  // proposer to make a block (core_tests.rs:103-130 analogue).
  auto committee = consensus_committee(8400);
  auto chain = make_chain(1, committee);
  const Block& block = chain[0];
  auto sorted = committee.sorted_keys();
  PublicKey leader2 = sorted[2 % sorted.size()];
  size_t us = 0;
  while (keys()[us].name != leader2) us++;

  CoreFixture fx;
  fx.spawn_core(us, committee);
  for (size_t i = 0; i < 3; i++) {
    fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
        ConsensusMessage::vote_msg(make_vote(block, keys()[i])))));
  }
  auto msg = fx.tx_proposer->recv();
  CHECK(msg.has_value());
  CHECK(msg->kind == ProposerMessage::Kind::kMake);
  CHECK(msg->round == 2);
  CHECK(msg->qc.hash == block.digest());
}

TEST(core_commits_two_chain) {
  // Processing blocks 1..3 of a chain commits block 1 (2-chain rule;
  // core_tests.rs:132-160 analogue). Payloads make commits observable.
  auto committee = consensus_committee(8500);
  CoreFixture fx;

  // Build a chain whose blocks carry payload digests already in the store
  // so MempoolDriver::verify passes.
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  std::vector<Block> chain;
  QC qc;
  for (uint64_t round = 1; round <= 3; round++) {
    Bytes payload_bytes{uint8_t(round)};
    Digest payload = sha512_digest(payload_bytes);
    fx.store.write(payload.to_bytes(), payload_bytes);
    Block b = make_block(qc, key_for(sorted[round % sorted.size()]), round,
                         {payload});
    qc = make_qc(b.digest(), b.round);
    chain.push_back(std::move(b));
  }

  // We are a replica that never leads rounds 1..4 if possible; any node
  // works since votes to other leaders go to dead addresses (SimpleSender
  // drops them silently).
  fx.spawn_core(0, committee);
  for (const Block& b : chain) {
    fx.tx_core->send(CoreEvent::msg(
        ConsensusMessage::deserialize(ConsensusMessage::propose(b))));
  }
  auto committed = fx.tx_commit->recv();
  CHECK(committed.has_value());
  CHECK(committed->round == 1);
  CHECK(committed->digest() == chain[0].digest());
}

TEST(core_commits_three_chain_one_round_later) {
  // Under chain_depth=3 the commit rule needs THREE consecutive certified
  // rounds: processing blocks 1..3 (which under 2-chain already commits
  // block 1) must commit nothing, and block 4 then commits block 1 — the
  // "+1 round of commit latency" the 3-chain variant exists to measure.
  auto committee = consensus_committee(8700);
  CoreFixture fx;
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  std::vector<Block> chain;
  QC qc;
  for (uint64_t round = 1; round <= 4; round++) {
    Bytes payload_bytes{uint8_t(round)};
    Digest payload = sha512_digest(payload_bytes);
    fx.store.write(payload.to_bytes(), payload_bytes);
    Block b = make_block(qc, key_for(sorted[round % sorted.size()]), round,
                         {payload});
    qc = make_qc(b.digest(), b.round);
    chain.push_back(std::move(b));
  }
  fx.spawn_core(0, committee, /*timeout_delay=*/60'000, /*chain_depth=*/3);
  for (size_t i = 0; i < 3; i++) {
    fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
        ConsensusMessage::propose(chain[i]))));
  }
  Block none;
  auto status = fx.tx_commit->recv_until(
      &none, std::chrono::steady_clock::now() + std::chrono::milliseconds(500));
  CHECK(status == RecvStatus::kTimeout);  // 2-chain would have committed B1

  fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
      ConsensusMessage::propose(chain[3]))));
  auto committed = fx.tx_commit->recv();
  CHECK(committed.has_value());
  CHECK(committed->round == 1);
  CHECK(committed->digest() == chain[0].digest());
}

TEST(core_broadcasts_timeout_on_timer) {
  // Timer fires -> Timeout broadcast to all peers (core_tests.rs:162-192).
  auto committee = consensus_committee(8600);
  size_t us = 0;
  auto delivered = make_channel<Bytes>();
  std::vector<std::thread> threads;
  for (const auto& [name, addr] : committee.broadcast_addresses(
           keys()[us].name)) {
    auto l = Listener::bind(addr);
    CHECK(l.has_value());
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  CoreFixture fx;
  fx.spawn_core(us, committee, /*timeout_delay=*/100);
  for (size_t i = 0; i < 3; i++) {
    auto got = delivered->recv();
    CHECK(got.has_value());
    auto msg = ConsensusMessage::deserialize(*got);
    CHECK(msg.kind == ConsensusMessage::Kind::kTimeout);
    CHECK(msg.timeout.round == 1);
    CHECK(msg.timeout.verify(committee).ok());
  }
  for (auto& t : threads) t.join();
}

TEST(core_restores_persisted_state_after_restart) {
  // Crash recovery (EXCEEDS the reference, which leaves this state
  // volatile — core.rs:112 TODO): drive a core through rounds 1..3 on a
  // shared store, tear it down, restart a fresh core on the SAME store,
  // and observe via its first timeout broadcast that it resumed at the
  // persisted round (and voting watermark) instead of round 1.
  auto committee = consensus_committee(8800);
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  Store store = Store::open("");
  std::vector<Block> chain;
  QC qc;
  for (uint64_t round = 1; round <= 3; round++) {
    Bytes payload_bytes{uint8_t(round)};
    Digest payload = sha512_digest(payload_bytes);
    store.write(payload.to_bytes(), payload_bytes);
    Block b = make_block(qc, key_for(sorted[round % sorted.size()]), round,
                         {payload});
    qc = make_qc(b.digest(), b.round);
    chain.push_back(std::move(b));
  }

  {
    CoreFixture fx;
    fx.store = store;
    fx.spawn_core(0, committee);
    for (const Block& b : chain) {
      fx.tx_core->send(CoreEvent::msg(ConsensusMessage::deserialize(
          ConsensusMessage::propose(b))));
    }
    // Wait until the chain is fully processed (block 1 commits under the
    // 2-chain rule), so round_/high_qc_ were persisted before teardown.
    auto committed = fx.tx_commit->recv();
    CHECK(committed.has_value());
    CHECK(committed->round == 1);
  }  // fixture teardown = crash

  // Restart on the same store; listeners catch its timeout broadcast.
  auto delivered = make_channel<Bytes>();
  std::vector<std::thread> threads;
  for (const auto& [name, addr] :
       committee.broadcast_addresses(keys()[0].name)) {
    auto l = Listener::bind(addr);
    CHECK(l.has_value());
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  CoreFixture fx2;
  fx2.store = store;
  fx2.spawn_core(0, committee, /*timeout_delay=*/100);
  auto got = delivered->recv();
  CHECK(got.has_value());
  auto msg = ConsensusMessage::deserialize(*got);
  CHECK(msg.kind == ConsensusMessage::Kind::kTimeout);
  // Blocks 1..3 certify rounds 1..2 in QCs; processing block 3 (qc for
  // round 2) advanced the core to round 3. An amnesiac core would time
  // out at round 1.
  CHECK(msg.timeout.round == 3);
  CHECK(msg.timeout.verify(committee).ok());
  for (auto& t : threads) t.join();
}

int main() { return run_all(); }

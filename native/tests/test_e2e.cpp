// End-to-end: 4 full node stacks in one process on localhost ports commit
// the same block from a client transaction
// (consensus/src/tests/consensus_tests.rs:56-68 analogue, widened to the
// full node: mempool batching + quorum dissemination + consensus).
#include <cstdlib>
#include <thread>

#include "node/node.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;

TEST(four_nodes_commit_same_block) {
  std::system("rm -rf /tmp/.hs_e2e && mkdir -p /tmp/.hs_e2e");
  const std::string dir = "/tmp/.hs_e2e/";

  // Configs: committee on ports 9500+, small batches, fast timeout off the
  // happy path (10 s so it never fires).
  node::Committee committee;
  committee.consensus = consensus_committee(9500);
  committee.mempool = mempool_committee(9510);
  committee.write(dir + "committee.json");
  {
    Json params = Json::object();
    Json cons = Json::object();
    cons.set("timeout_delay", Json(int64_t(10'000)));
    cons.set("sync_retry_delay", Json(int64_t(10'000)));
    Json memp = Json::object();
    memp.set("batch_size", Json(int64_t(64)));
    memp.set("max_batch_delay", Json(int64_t(50)));
    params.set("consensus", std::move(cons));
    params.set("mempool", std::move(memp));
    params.write_file(dir + "parameters.json");
  }
  auto ks = keys();
  std::vector<std::unique_ptr<node::Node>> nodes;
  for (size_t i = 0; i < 4; i++) {
    node::Secret s;
    s.name = ks[i].name;
    s.secret = ks[i].secret;
    std::string key_file = dir + "node-" + std::to_string(i) + ".json";
    s.write(key_file);
    nodes.push_back(node::Node::create(dir + "committee.json", key_file,
                                       dir + "db-" + std::to_string(i),
                                       dir + "parameters.json"));
  }

  // Feed one transaction to every node's transactions address (so whoever
  // leads has a payload to propose).
  for (size_t i = 0; i < 4; i++) {
    auto addr = committee.mempool.transactions_address(ks[i].name);
    auto sock = Socket::connect(*addr);
    CHECK(sock.has_value());
    Bytes tx(32, uint8_t(i + 1));
    CHECK(sock->write_frame(tx));
  }

  // Every node commits a block with a payload, and the first such block
  // matches across all nodes.
  std::vector<Digest> first_committed(4);
  std::vector<std::thread> waiters;
  std::atomic<int> failures{0};
  for (size_t i = 0; i < 4; i++) {
    waiters.emplace_back([&, i] {
      auto ch = nodes[i]->commit_channel();
      while (true) {
        consensus::Block b;
        auto status = ch->recv_until(
            &b, std::chrono::steady_clock::now() + std::chrono::seconds(30));
        if (status != RecvStatus::kOk) {
          failures++;
          return;
        }
        if (!b.payload.empty()) {
          first_committed[i] = b.digest();
          return;
        }
      }
    });
  }
  for (auto& t : waiters) t.join();
  CHECK(failures.load() == 0);
  CHECK(first_committed[0] == first_committed[1]);
  CHECK(first_committed[0] == first_committed[2]);
  CHECK(first_committed[0] == first_committed[3]);

  // Orderly teardown: every actor thread joins; the old std::exit escape
  // hatch raced detached threads against static destruction (the round-1/2
  // flaky segfault).
  for (auto& n : nodes) n->stop();
}

TEST(four_nodes_commit_under_3chain_rule) {
  // Same quartet under chain_depth=3 (the reference's 3-chain data variant,
  // benchmark/data/3-chain/): commits require a third consecutive round, so
  // a committed block proves the deeper rule fires end to end.
  std::system("rm -rf /tmp/.hs_e2e3 && mkdir -p /tmp/.hs_e2e3");
  const std::string dir = "/tmp/.hs_e2e3/";

  node::Committee committee;
  committee.consensus = consensus_committee(9700);
  committee.mempool = mempool_committee(9710);
  committee.write(dir + "committee.json");
  {
    Json params = Json::object();
    Json cons = Json::object();
    cons.set("timeout_delay", Json(int64_t(10'000)));
    cons.set("sync_retry_delay", Json(int64_t(10'000)));
    cons.set("chain_depth", Json(int64_t(3)));
    Json memp = Json::object();
    memp.set("batch_size", Json(int64_t(64)));
    memp.set("max_batch_delay", Json(int64_t(50)));
    params.set("consensus", std::move(cons));
    params.set("mempool", std::move(memp));
    params.write_file(dir + "parameters.json");
  }
  auto ks = keys();
  std::vector<std::unique_ptr<node::Node>> nodes;
  for (size_t i = 0; i < 4; i++) {
    node::Secret s;
    s.name = ks[i].name;
    s.secret = ks[i].secret;
    std::string key_file = dir + "node-" + std::to_string(i) + ".json";
    s.write(key_file);
    nodes.push_back(node::Node::create(dir + "committee.json", key_file,
                                       dir + "db-" + std::to_string(i),
                                       dir + "parameters.json"));
  }
  for (size_t i = 0; i < 4; i++) {
    auto addr = committee.mempool.transactions_address(ks[i].name);
    auto sock = Socket::connect(*addr);
    CHECK(sock.has_value());
    Bytes tx(32, uint8_t(i + 1));
    CHECK(sock->write_frame(tx));
  }
  std::vector<Digest> first_committed(4);
  std::vector<std::thread> waiters;
  std::atomic<int> failures{0};
  for (size_t i = 0; i < 4; i++) {
    waiters.emplace_back([&, i] {
      auto ch = nodes[i]->commit_channel();
      while (true) {
        consensus::Block b;
        auto status = ch->recv_until(
            &b, std::chrono::steady_clock::now() + std::chrono::seconds(30));
        if (status != RecvStatus::kOk) {
          failures++;
          return;
        }
        if (!b.payload.empty()) {
          first_committed[i] = b.digest();
          return;
        }
      }
    });
  }
  for (auto& t : waiters) t.join();
  CHECK(failures.load() == 0);
  CHECK(first_committed[0] == first_committed[1]);
  CHECK(first_committed[0] == first_committed[2]);
  CHECK(first_committed[0] == first_committed[3]);
  for (auto& n : nodes) n->stop();
}

int main() { return run_all(); }

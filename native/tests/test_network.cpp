// Network tests (network/src/tests/ analogue): receiver dispatch,
// simple send + broadcast, reliable send with ACK, the retry path
// (send before any listener exists, then start it, assert eventual ACK),
// hostile-frame handling at the reactor's parser, and many-connection
// multiplexing on the single event-loop thread.
#include <sys/socket.h>

#include <atomic>
#include <thread>

#include "network/receiver.hpp"
#include "network/reliable_sender.hpp"
#include "network/simple_sender.hpp"
#include "node/rate_pacer.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;

TEST(receiver_dispatch) {
  NetworkReceiver receiver;
  auto received = make_channel<Bytes>();
  CHECK(receiver.spawn(Address{"127.0.0.1", 0},
                       [received](ConnectionWriter& w, Bytes msg) {
                         w.send(std::string("Ack"));
                         received->send(std::move(msg));
                         return true;
                       }));
  Address addr{"127.0.0.1", receiver.port()};
  auto sock = Socket::connect(addr);
  CHECK(sock.has_value());
  Bytes msg{1, 2, 3, 4};
  CHECK(sock->write_frame(msg));
  Bytes ack;
  CHECK(sock->read_frame(&ack));
  CHECK(to_string(ack) == "Ack");
  auto got = received->recv();
  CHECK(got.has_value());
  CHECK(*got == msg);
  receiver.stop();
}

TEST(simple_send) {
  auto l = Listener::bind(Address{"127.0.0.1", 0});
  CHECK(l.has_value());
  Address addr{"127.0.0.1", l->port()};
  auto delivered = make_channel<Bytes>();
  auto t = listener(std::move(*l),
                    [delivered](Bytes b) { delivered->send(std::move(b)); });
  SimpleSender sender;
  sender.send(addr, Bytes{5, 6, 7});
  auto got = delivered->recv();
  CHECK(got.has_value());
  CHECK(*got == (Bytes{5, 6, 7}));
  t.join();
}

TEST(simple_broadcast) {
  std::vector<Address> addrs;
  std::vector<std::thread> threads;
  auto delivered = make_channel<Bytes>();
  for (int i = 0; i < 3; i++) {
    auto l = Listener::bind(Address{"127.0.0.1", 0});
    CHECK(l.has_value());
    addrs.push_back(Address{"127.0.0.1", l->port()});
    threads.push_back(listener(std::move(*l), [delivered](Bytes b) {
      delivered->send(std::move(b));
    }));
  }
  SimpleSender sender;
  sender.broadcast(addrs, Bytes{9});
  for (int i = 0; i < 3; i++) {
    auto got = delivered->recv();
    CHECK(got.has_value());
    CHECK(*got == (Bytes{9}));
  }
  for (auto& t : threads) t.join();
}

TEST(reliable_send_acks) {
  auto l = Listener::bind(Address{"127.0.0.1", 0});
  CHECK(l.has_value());
  Address addr{"127.0.0.1", l->port()};
  auto t = listener(std::move(*l), nullptr);
  ReliableSender sender;
  auto handler = sender.send(addr, Bytes{1});
  CHECK(handler.wait_for(std::chrono::milliseconds(5000)));
  CHECK(to_string(handler.wait()) == "Ack");
  t.join();
}

TEST(reliable_send_retries_until_listener_appears) {
  // Reserve a port, close it, send (connection fails), then start the
  // listener and expect the retransmission to get through
  // (reliable_sender_tests.rs:49-67 analogue).
  uint16_t port;
  {
    auto probe = Listener::bind(Address{"127.0.0.1", 0});
    CHECK(probe.has_value());
    port = probe->port();
  }
  Address addr{"127.0.0.1", port};
  ReliableSender sender;
  auto handler = sender.send(addr, Bytes{42});
  std::this_thread::sleep_for(std::chrono::milliseconds(300));
  CHECK(!handler.ready());
  auto l = Listener::bind(addr);
  CHECK(l.has_value());
  auto t = listener(std::move(*l), nullptr);
  CHECK(handler.wait_for(std::chrono::milliseconds(10000)));
  CHECK(to_string(handler.wait()) == "Ack");
  t.join();
}

TEST(simple_send_retries_connect_while_queued) {
  // Boot-storm shape: the message is sent BEFORE the listener exists.
  // The bounded connect-retry (simple_sender.cpp) must keep the queued
  // message alive and deliver it once the listener appears — a vote is
  // sent exactly once, and dropping it on one failed connect used to
  // cost a 100-node committee its round 1-3 view changes.
  uint16_t port;
  {
    auto probe = Listener::bind(Address{"127.0.0.1", 0});
    CHECK(probe.has_value());
    port = probe->port();
  }
  Address addr{"127.0.0.1", port};
  SimpleSender sender;
  sender.send(addr, Bytes{7, 7, 7});  // no listener yet: connect fails
  std::this_thread::sleep_for(std::chrono::milliseconds(400));
  auto delivered = make_channel<Bytes>();
  auto l = Listener::bind(addr);
  CHECK(l.has_value());
  auto t = listener(std::move(*l),
                    [delivered](Bytes b) { delivered->send(std::move(b)); });
  Bytes got_msg;
  auto status = delivered->recv_until(
      &got_msg, std::chrono::steady_clock::now() +
                    std::chrono::seconds(15));
  CHECK(status == RecvStatus::kOk);
  CHECK(got_msg == (Bytes{7, 7, 7}));
  t.join();
}

TEST(reliable_send_replays_across_listener_crashes) {
  // Reconnect/replay stress (the state machine SURVEY.md calls out as a
  // hard part): a flaky peer accepts ONE message per connection lifetime
  // and dies without ACKing every third one, so each dropped message must
  // be re-queued and retransmitted on a fresh connection. One message is
  // outstanding at a time — a peer that closes with unread inbound data
  // sends TCP RST, which can lawfully destroy an already-sent ACK (the
  // production Receiver never closes with data pending, so that failure
  // mode is out of scope here).
  auto l0 = Listener::bind(Address{"127.0.0.1", 0});
  CHECK(l0.has_value());
  Address addr{"127.0.0.1", l0->port()};

  constexpr int kMessages = 6;
  std::atomic<int> acked{0};
  std::atomic<int> dropped{0};
  std::atomic<bool> stop{false};

  std::thread server([&, l = std::make_shared<Listener>(std::move(*l0))] {
    int round = 0;
    while (!stop.load()) {
      auto sock = l->accept();
      if (!sock) return;
      Bytes frame;
      if (sock->read_frame(&frame)) {
        if (round++ % 3 == 0) {
          dropped++;   // die without ACK: forces reconnect + replay
          continue;
        }
        acked++;  // before the write: the sender can observe the ACK (and
                  // the test finish) before a post-write increment runs
        sock->write_frame(reinterpret_cast<const uint8_t*>("Ack"), 3);
      }
    }
  });

  {
    ReliableSender sender;
    for (int i = 0; i < kMessages; i++) {
      auto h = sender.send(addr, Bytes{uint8_t(i)});
      CHECK(h.wait_for(std::chrono::milliseconds(30000)));
      CHECK(to_string(h.wait()) == "Ack");
    }
    CHECK(acked.load() >= kMessages);
    CHECK(dropped.load() >= 1);  // the replay path actually ran
  }  // sender teardown closes its idle reconnection; the server's
     // read_frame returns and the accept loop can observe `stop`
  stop.store(true);
  // Unblock the accept loop with one last (immediately closed) connection.
  { auto poke = Socket::connect(addr); }
  server.join();
}

TEST(receiver_survives_hostile_frames) {
  // The reactor's frame parser (event_loop.cpp) faces raw peer bytes:
  // a hostile length prefix must drop that connection only, and the
  // receiver must keep serving others (serde-fuzz discipline at the
  // framing layer).
  NetworkReceiver receiver;
  auto received = make_channel<Bytes>();
  CHECK(receiver.spawn(Address{"127.0.0.1", 0},
                       [received](ConnectionWriter&, Bytes msg) {
                         received->send(std::move(msg));
                         return true;
                       }));
  Address addr{"127.0.0.1", receiver.port()};

  {  // frame length far over the 8 MiB cap -> connection dropped
    auto sock = Socket::connect(addr);
    CHECK(sock.has_value());
    // Bounded read: if the frame-cap guard ever regresses, this test
    // must FAIL, not hang the suite waiting for a 4 GB frame.
    sock->set_recv_timeout(5000);
    const uint8_t hostile[8] = {0xFF, 0xFF, 0xFF, 0xFF, 1, 2, 3, 4};
    CHECK(::send(sock->fd(), hostile, sizeof(hostile), 0) == 8);
    Bytes reply;  // peer closes: read fails rather than hanging
    CHECK(!sock->read_frame(&reply));
  }

  {  // fragmented-but-honest frames on a fresh connection still dispatch
    auto sock = Socket::connect(addr);
    CHECK(sock.has_value());
    Bytes msg{7, 7, 7, 7, 7};
    const uint8_t hdr[4] = {0, 0, 0, 5};
    for (int i = 0; i < 4; i++) {
      CHECK(::send(sock->fd(), hdr + i, 1, 0) == 1);
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
    for (size_t i = 0; i < msg.size(); i++) {
      CHECK(::send(sock->fd(), msg.data() + i, 1, 0) == 1);
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
    auto got = received->recv();
    CHECK(got.has_value());
    CHECK(*got == msg);
  }
  receiver.stop();
}

TEST(reactor_multiplexes_many_connections) {
  // One reactor thread must serve many concurrent inbound connections —
  // the property the 20-node single-host bench depends on.
  NetworkReceiver receiver;
  auto received = make_channel<Bytes>();
  CHECK(receiver.spawn(Address{"127.0.0.1", 0},
                       [received](ConnectionWriter& w, Bytes msg) {
                         w.send(std::string("Ack"));
                         received->send(std::move(msg));
                         return true;
                       }));
  Address addr{"127.0.0.1", receiver.port()};
  constexpr int kConns = 40;
  std::vector<Socket> socks;
  for (int i = 0; i < kConns; i++) {
    auto s = Socket::connect(addr);
    CHECK(s.has_value());
    // Bounded reads: a multiplexing regression must FAIL, not hang.
    s->set_recv_timeout(10000);
    socks.push_back(std::move(*s));
  }
  for (int i = 0; i < kConns; i++) {
    Bytes msg{uint8_t(i), uint8_t(i + 1)};
    CHECK(socks[i].write_frame(msg));
  }
  for (int i = 0; i < kConns; i++) {
    Bytes ack;
    CHECK(socks[i].read_frame(&ack));
    CHECK(to_string(ack) == "Ack");
    auto got = received->recv();
    CHECK(got.has_value());
  }
  receiver.stop();
}

TEST(rate_pacer_delivers_exact_rate) {
  // The load generator's pacing (node/client.cpp): over any whole number
  // of seconds the sum of bursts must equal rate * seconds EXACTLY —
  // truncation used to under-deliver [kPrecision, 2*kPrecision) by up to
  // 2x, misstating the offered load in the run label.
  constexpr uint64_t kPrecision = 20;
  for (uint64_t rate : {uint64_t(1), uint64_t(7), uint64_t(19),
                        uint64_t(20), uint64_t(21), uint64_t(39),
                        uint64_t(40), uint64_t(1000), uint64_t(12345)}) {
    RatePacer pacer{rate, kPrecision};
    uint64_t sent = 0;
    constexpr uint64_t kSeconds = 10;
    for (uint64_t tick = 0; tick < kPrecision * kSeconds; tick++) {
      sent += pacer.next_burst();
    }
    CHECK(sent == rate * kSeconds);
    CHECK(pacer.acc == 0);  // whole seconds leave no remainder
  }
}

TEST(rate_pacer_truncation_band) {
  // The ADVICE.md example: --rate 39 must send 39 tx in 20 ticks (the
  // old code sent 20), and no single tick may burst more than the exact
  // rational rate rounds up to.
  RatePacer pacer{39, 20};
  uint64_t sent = 0;
  for (int tick = 0; tick < 20; tick++) {
    uint64_t burst = pacer.next_burst();
    CHECK(burst <= 2);
    sent += burst;
  }
  CHECK(sent == 39);
  // Sub-precision rates average out exactly too: 5 tx/s = one 1-tx burst
  // every 4th tick.
  RatePacer slow{5, 20};
  uint64_t slow_sent = 0;
  for (int tick = 0; tick < 40; tick++) {
    uint64_t burst = slow.next_burst();
    CHECK(burst <= 1);
    slow_sent += burst;
  }
  CHECK(slow_sent == 10);
}

int main() { return run_all(); }

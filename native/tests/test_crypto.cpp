// Crypto layer tests (crypto/src/tests/crypto_tests.rs:31-132 analogue):
// key round-trips, valid/invalid single + batch verification,
// SignatureService, RFC 8032 test vector cross-check, and the sidecar
// client's circuit breaker / adaptive in-flight budget.
#include <chrono>
#include <thread>

#include "crypto/sidecar_client.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;

TEST(import_export_public_key) {
  auto kp = keys()[0];
  std::string b64 = kp.name.to_base64();
  PublicKey back;
  CHECK(PublicKey::from_base64(b64, &back));
  CHECK(back == kp.name);
}

TEST(import_export_secret_key) {
  auto kp = keys()[0];
  std::string b64 = kp.secret.to_base64();
  SecretKey back;
  CHECK(SecretKey::from_base64(b64, &back));
  CHECK(back.data == kp.secret.data);
}

TEST(rfc8032_vector) {
  // RFC 8032 section 7.1 TEST 1: empty message. We sign 32-byte digests in
  // the protocol, but the primitive must match the RFC on raw messages —
  // cross-check key derivation: secret 9d61...  -> public d75a...
  std::array<uint8_t, 32> seed = {
      0x9d, 0x61, 0xb1, 0x9d, 0xef, 0xfd, 0x5a, 0x60, 0xba, 0x84, 0x4a,
      0xf4, 0x92, 0xec, 0x2c, 0xc4, 0x44, 0x49, 0xc5, 0x69, 0x7b, 0x32,
      0x69, 0x19, 0x70, 0x3b, 0xac, 0x03, 0x1c, 0xae, 0x7f, 0x60};
  std::array<uint8_t, 32> expect_pub = {
      0xd7, 0x5a, 0x98, 0x01, 0x82, 0xb1, 0x0a, 0xb7, 0xd5, 0x4b, 0xfe,
      0xd3, 0xc9, 0x64, 0x07, 0x3a, 0x0e, 0xe1, 0x72, 0xf3, 0xda, 0xa6,
      0x23, 0x25, 0xaf, 0x02, 0x1a, 0x68, 0xf7, 0x07, 0x51, 0x1a};
  KeyPair kp = keypair_from_seed(seed);
  CHECK(kp.name.data == expect_pub);
}

TEST(sign_verify) {
  auto kp = keys()[0];
  Digest d = sha512_digest(Bytes{1, 2, 3});
  Signature sig = Signature::sign(d, kp.secret);
  CHECK(sig.verify(d, kp.name));
  // wrong digest
  Digest d2 = sha512_digest(Bytes{9});
  CHECK(!sig.verify(d2, kp.name));
  // wrong key
  CHECK(!sig.verify(d, keys()[1].name));
  // corrupted signature
  Signature bad = sig;
  bad.data[5] ^= 1;
  CHECK(!bad.verify(d, kp.name));
}

TEST(verify_batch) {
  Digest d = sha512_digest(Bytes{42});
  std::vector<std::pair<PublicKey, Signature>> votes;
  for (const auto& kp : keys()) {
    votes.emplace_back(kp.name, Signature::sign(d, kp.secret));
  }
  CHECK(Signature::verify_batch(d, votes));
  votes[2].second.data[0] ^= 1;
  CHECK(!Signature::verify_batch(d, votes));
}

TEST(digest_builder_matches_oneshot) {
  Bytes msg{1, 2, 3, 4, 5};
  Digest a = sha512_digest(msg);
  Digest b = DigestBuilder()
                 .update(msg.data(), 2)
                 .update(msg.data() + 2, 3)
                 .finalize();
  CHECK(a == b);
}

TEST(signature_service) {
  auto kp = keys()[0];
  SignatureService service(kp.secret);
  Digest d = sha512_digest(Bytes{7, 7, 7});
  Signature sig = service.request_signature(d);
  CHECK(sig.verify(d, kp.name));
}

TEST(signature_serde_variable_length) {
  // 64-byte (Ed25519) and 192-byte (BLS G2) signatures round-trip; any
  // other length is rejected at deserialization (scheme=bls support).
  for (size_t len : {size_t(64), size_t(192)}) {
    Signature s;
    s.data = Bytes(len);
    for (size_t i = 0; i < len; i++) s.data[i] = uint8_t(i * 7);
    Writer w;
    s.serialize(&w);
    Reader r(w.out);
    Signature back = Signature::deserialize(&r);
    CHECK(back == s);
  }
  Signature bad;
  bad.data = Bytes(128, 3);
  Writer w;
  bad.serialize(&w);
  Reader r(w.out);
  bool threw = false;
  try {
    Signature::deserialize(&r);
  } catch (const SerdeError&) {
    threw = true;
  }
  CHECK(threw);
}

namespace {
// Restores the process-global scheme even when a failing CHECK returns
// early (a leaked kBls would poison every later test in the binary).
struct SchemeGuard {
  ~SchemeGuard() { set_scheme(Scheme::kEd25519); }
};
}  // namespace

TEST(bls_length_dispatch_without_sidecar) {
  // Under scheme=bls, 64-byte signatures are the sidecar-down host
  // Ed25519 fallback (see Signature::sign) and verify on the HOST path;
  // only 192-byte G2 bytes need the sidecar.  With no sidecar installed
  // the BLS remainder is UNKNOWN (nullopt), never silently accepted.
  auto kp = keys()[0];
  Digest d = sha512_digest(Bytes{9});
  Signature sig = Signature::sign(d, kp.secret);  // ed25519-signed, 64 B
  SchemeGuard guard;
  set_scheme(Scheme::kBls);
  // Length dispatch: the fallback signature verifies against the
  // signer's Ed25519 identity key even under scheme=bls ...
  CHECK(sig.verify(d, kp.name));
  CHECK(Signature::verify_batch(d, {{kp.name, sig}}));
  // ... and a corrupted one still rejects — the host check is real.
  Signature bad = sig;
  bad.data[5] ^= 1;
  CHECK(!bad.verify(d, kp.name));
  CHECK(!Signature::verify_batch(d, {{kp.name, bad}}));
  // 192-byte BLS bytes cannot be checked without a sidecar: the plain
  // forms reject, and the transport-aware form reports UNKNOWN so TC
  // assembly can defer/retry instead of ejecting an honest signer.
  Signature g2;
  g2.data = Bytes(192, 7);
  CHECK(!g2.verify(d, kp.name));
  CHECK(!Signature::verify_batch(d, {{kp.name, g2}}));
  CHECK(!Signature::verify_batch_multi({{d, kp.name, g2}}));
  auto unknown = Signature::verify_batch_multi_checked({{d, kp.name, g2}});
  CHECK(!unknown.has_value());
  // A forged 64-byte entry in a mixed batch is DEFINITIVELY false even
  // though the BLS remainder is unknowable.
  auto mixed = Signature::verify_batch_multi_checked(
      {{d, kp.name, bad}, {d, kp.name, g2}});
  CHECK(mixed.has_value());
  CHECK(!*mixed);
  // An all-fallback batch needs no sidecar at all.
  auto host = Signature::verify_batch_multi_checked({{d, kp.name, sig}});
  CHECK(host.has_value());
  CHECK(*host);
  set_scheme(Scheme::kEd25519);
  CHECK(sig.verify(d, kp.name));
}

namespace {
// Uninstalls the process-global sidecar client + BLS context and
// restores scheme=ed25519 even when a failing CHECK returns early.
struct SidecarGuard {
  ~SidecarGuard() {
    TpuVerifier::install(nullptr);
    BlsContext::install(nullptr);
    set_scheme(Scheme::kEd25519);
  }
};
}  // namespace

TEST(bls_sign_falls_back_to_host_key_when_sidecar_dead) {
  // scheme=bls with a sidecar that is installed but unreachable (stopped
  // mid-run): Signature::sign must fall back to the host Ed25519
  // identity key — a VALID 64-byte signature — instead of emitting
  // invalid BLS bytes that would stall TC assembly at every verifier.
  uint16_t port;
  {
    // Reserve a port with nothing listening by binding and releasing it.
    auto l = Listener::bind({"127.0.0.1", 0});
    CHECK(l.has_value());
    port = l->port();
  }
  SidecarGuard guard;
  TpuVerifier::install(
      std::make_unique<TpuVerifier>(Address{"127.0.0.1", port}));
  auto kp = keys()[0];
  auto bls = std::make_unique<BlsContext>();
  bls->secret = Bytes(48, 1);
  // Register a (garbage) G1 key for the signer so a 192-byte check is a
  // TRANSPORT question, not an unknown-authority reject.
  bls->public_keys[kp.name] = Bytes(96, 9);
  BlsContext::install(std::move(bls));
  set_scheme(Scheme::kBls);

  Digest d = sha512_digest(Bytes{4, 2});
  Signature sig = Signature::sign(d, kp.secret);
  CHECK(sig.data.size() == 64);
  // Verifies under scheme=bls (length dispatch) and under ed25519.
  CHECK(sig.verify(d, kp.name));
  CHECK(Signature::verify_batch(d, {{kp.name, sig}}));
  // The dead transport still reports UNKNOWN for genuine BLS bytes.
  Signature g2;
  g2.data = Bytes(192, 7);
  CHECK(!Signature::verify_batch_multi_checked({{d, kp.name, g2}})
             .has_value());
  set_scheme(Scheme::kEd25519);
  CHECK(sig.verify(d, kp.name));
}

TEST(verify_batch_multi_distinct_digests) {
  // The TC path: every signature over its own digest, one batch call.
  auto kp1 = keypair_from_seed({{1}});
  auto kp2 = keypair_from_seed({{2}});
  Digest d1 = DigestBuilder().update_u64_le(7).update_u64_le(3).finalize();
  Digest d2 = DigestBuilder().update_u64_le(7).update_u64_le(5).finalize();
  Signature s1 = Signature::sign(d1, kp1.secret);
  Signature s2 = Signature::sign(d2, kp2.secret);
  CHECK(Signature::verify_batch_multi({{d1, kp1.name, s1},
                                       {d2, kp2.name, s2}}));
  // Swapped digests must fail.
  CHECK(!Signature::verify_batch_multi({{d2, kp1.name, s1},
                                        {d1, kp2.name, s2}}));
  // One corrupted signature fails the whole batch.
  Signature bad = s2;
  bad.data[5] ^= 1;
  CHECK(!Signature::verify_batch_multi({{d1, kp1.name, s1},
                                        {d2, kp2.name, bad}}));
}

TEST(sidecar_inflight_budget_adapts_aimd) {
  // Multiplicative decrease past the shrink threshold, bounded below.
  CHECK(TpuVerifier::adapt_budget(64, 100.0) == 32);
  CHECK(TpuVerifier::adapt_budget(9, 100.0) == 8);
  CHECK(TpuVerifier::adapt_budget(8, 10000.0) == 8);
  // Additive increase below the grow threshold, bounded above.
  CHECK(TpuVerifier::adapt_budget(32, 5.0) == 40);
  CHECK(TpuVerifier::adapt_budget(64, 0.0) == 64);
  // Hysteresis band: no change.
  CHECK(TpuVerifier::adapt_budget(32, 25.0) == 32);
}

TEST(sidecar_circuit_breaker_opens_then_reattaches) {
  // Reserve a port with nothing listening by binding and releasing it.
  uint16_t port;
  {
    auto l = Listener::bind({"127.0.0.1", 0});
    CHECK(l.has_value());
    port = l->port();
  }
  auto v = std::make_unique<TpuVerifier>(Address{"127.0.0.1", port});
  v->set_backoff_for_test(50, 200);
  CHECK(v->breaker_state() == TpuVerifier::BreakerState::kClosed);
  CHECK(v->inflight_budget() == TpuVerifier::kInflightBudgetMax);

  auto kp = keys()[0];
  Digest d = sha512_digest(Bytes{5});
  Signature sig = Signature::sign(d, kp.secret);
  std::vector<std::tuple<Digest, PublicKey, Signature>> items{
      {d, kp.name, sig}};

  // Each failed connect is one consecutive transport failure; the short
  // backoff gate between attempts must elapse or later calls return
  // without dialing (and without counting).
  for (int i = 0; i < TpuVerifier::kBreakerThreshold; i++) {
    CHECK(!v->verify_batch_multi(items).has_value());
    std::this_thread::sleep_for(std::chrono::milliseconds(60));
  }
  CHECK(v->breaker_state() != TpuVerifier::BreakerState::kClosed);

  // Open breaker: verifies fail over to the caller instantly — no
  // connect timeout is paid on the verify path.
  auto t0 = std::chrono::steady_clock::now();
  CHECK(!v->verify_batch_multi(items).has_value());
  auto dt = std::chrono::duration_cast<std::chrono::milliseconds>(
      std::chrono::steady_clock::now() - t0);
  CHECK(dt.count() < TpuVerifier::kConnectTimeoutMs);

  // ... and the crypto-layer entry point still answers via host verify.
  CHECK(Signature::verify_batch_multi(items));

  // Boot a minimal sidecar stand-in on the reserved port: the probe
  // must re-attach within a few (capped 200 ms) backoff periods.
  auto l2 = Listener::bind({"127.0.0.1", port});
  CHECK(l2.has_value());
  std::thread server([&l2] {
    auto sock = l2->accept();
    if (!sock) return;
    Bytes frame;
    while (sock->read_frame(&frame)) {
      Reader r(frame);
      uint8_t op = r.u8();
      uint32_t rid = r.u32();
      uint32_t count = r.u32();
      Writer w;
      w.u8(op);
      w.u32(rid);
      if (op == 8) {  // OP_STATS: reply an empty JSON object
        w.u32(2);
        w.out.push_back('{');
        w.out.push_back('}');
      } else {
        w.u32(count);
        for (uint32_t i = 0; i < count; i++) w.u8(1);
      }
      if (!sock->write_frame(w.out)) return;
    }
  });
  auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(5);
  while (v->breaker_state() != TpuVerifier::BreakerState::kClosed &&
         std::chrono::steady_clock::now() < deadline) {
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  CHECK(v->breaker_state() == TpuVerifier::BreakerState::kClosed);
  auto mask = v->verify_batch_multi(items);
  CHECK(mask.has_value());
  CHECK(mask->size() == 1 && (*mask)[0]);

  v.reset();  // closes the socket -> server's read_frame sees EOF
  l2->shutdown();
  server.join();
}

namespace {
// Minimal protocol-v6 sidecar stand-in: all-valid verify masks, an
// empty OP_STATS JSON object, and the HELLO version echo, until the
// peer closes (or the test shuts the socket down under it).
void standin_loop(Socket& sock) {
  Bytes frame;
  while (sock.read_frame(&frame)) {
    Reader r(frame);
    uint8_t op = r.u8();
    uint32_t rid = r.u32();
    uint32_t count = r.u32();
    Writer w;
    w.u8(op);
    w.u32(rid);
    if (op == 8) {  // OP_STATS: an empty JSON object
      w.u32(2);
      w.out.push_back('{');
      w.out.push_back('}');
    } else if (op == 11) {  // OP_HELLO: [server version][tenant echo]
      w.u32(1);
      w.u8(6);
    } else {
      w.u32(count);
      for (uint32_t i = 0; i < count; i++) w.u8(1);
    }
    if (!sock.write_frame(w.out)) return;
  }
}
}  // namespace

TEST(sidecar_fleet_failover_rehomes_to_secondary) {
  // The graftfleet ladder: a two-endpoint TpuVerifier serves on the
  // primary, and killing the primary's connection re-homes verify
  // traffic to the healthy secondary — the caller NEVER sees a
  // transport failure (host fallback is the last rung, not the next).
  auto la = Listener::bind({"127.0.0.1", 0});
  auto lb = Listener::bind({"127.0.0.1", 0});
  CHECK(la.has_value() && lb.has_value());
  std::optional<Socket> sa, sb;
  std::thread ta([&] {
    sa = la->accept();
    if (sa) standin_loop(*sa);
  });
  std::thread tb([&] {
    sb = lb->accept();
    if (sb) standin_loop(*sb);
  });

  auto v = std::make_unique<TpuVerifier>(
      std::vector<Address>{{"127.0.0.1", la->port()},
                           {"127.0.0.1", lb->port()}},
      std::string("node"));
  v->set_backoff_for_test(50, 200);

  auto kp = keys()[0];
  Digest d = sha512_digest(Bytes{6});
  Signature sig = Signature::sign(d, kp.secret);
  std::vector<std::tuple<Digest, PublicKey, Signature>> items{
      {d, kp.name, sig}};

  auto mask = v->verify_batch_multi(items);
  CHECK(mask.has_value());
  CHECK(mask->size() == 1 && (*mask)[0]);
  CHECK(v->active_endpoint() == 0);

  // Kill the primary: shut its accepted socket down (the stand-in's
  // read_frame sees EOF and the loop exits) and stop the listener so a
  // re-probe cannot reconnect.
  la->shutdown();
  if (sa) sa->shutdown();
  ta.join();

  // Verifies must re-home to the secondary within a few breaker
  // backoff periods — and once re-homed, answer from the sidecar leg.
  auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(5);
  std::optional<std::vector<bool>> rehomed;
  while (std::chrono::steady_clock::now() < deadline) {
    rehomed = v->verify_batch_multi(items);
    if (rehomed.has_value() && v->active_endpoint() == 1) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  CHECK(rehomed.has_value());
  CHECK(rehomed->size() == 1 && (*rehomed)[0]);
  CHECK(v->active_endpoint() == 1);
  CHECK(v->breaker_state(1) == TpuVerifier::BreakerState::kClosed);

  v.reset();
  lb->shutdown();
  if (sb) sb->shutdown();
  tb.join();
}

int main() { return run_all(); }

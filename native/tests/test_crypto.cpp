// Crypto layer tests (crypto/src/tests/crypto_tests.rs:31-132 analogue):
// key round-trips, valid/invalid single + batch verification,
// SignatureService, and RFC 8032 test vector cross-check.
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;

TEST(import_export_public_key) {
  auto kp = keys()[0];
  std::string b64 = kp.name.to_base64();
  PublicKey back;
  CHECK(PublicKey::from_base64(b64, &back));
  CHECK(back == kp.name);
}

TEST(import_export_secret_key) {
  auto kp = keys()[0];
  std::string b64 = kp.secret.to_base64();
  SecretKey back;
  CHECK(SecretKey::from_base64(b64, &back));
  CHECK(back.data == kp.secret.data);
}

TEST(rfc8032_vector) {
  // RFC 8032 section 7.1 TEST 1: empty message. We sign 32-byte digests in
  // the protocol, but the primitive must match the RFC on raw messages —
  // cross-check key derivation: secret 9d61...  -> public d75a...
  std::array<uint8_t, 32> seed = {
      0x9d, 0x61, 0xb1, 0x9d, 0xef, 0xfd, 0x5a, 0x60, 0xba, 0x84, 0x4a,
      0xf4, 0x92, 0xec, 0x2c, 0xc4, 0x44, 0x49, 0xc5, 0x69, 0x7b, 0x32,
      0x69, 0x19, 0x70, 0x3b, 0xac, 0x03, 0x1c, 0xae, 0x7f, 0x60};
  std::array<uint8_t, 32> expect_pub = {
      0xd7, 0x5a, 0x98, 0x01, 0x82, 0xb1, 0x0a, 0xb7, 0xd5, 0x4b, 0xfe,
      0xd3, 0xc9, 0x64, 0x07, 0x3a, 0x0e, 0xe1, 0x72, 0xf3, 0xda, 0xa6,
      0x23, 0x25, 0xaf, 0x02, 0x1a, 0x68, 0xf7, 0x07, 0x51, 0x1a};
  KeyPair kp = keypair_from_seed(seed);
  CHECK(kp.name.data == expect_pub);
}

TEST(sign_verify) {
  auto kp = keys()[0];
  Digest d = sha512_digest(Bytes{1, 2, 3});
  Signature sig = Signature::sign(d, kp.secret);
  CHECK(sig.verify(d, kp.name));
  // wrong digest
  Digest d2 = sha512_digest(Bytes{9});
  CHECK(!sig.verify(d2, kp.name));
  // wrong key
  CHECK(!sig.verify(d, keys()[1].name));
  // corrupted signature
  Signature bad = sig;
  bad.data[5] ^= 1;
  CHECK(!bad.verify(d, kp.name));
}

TEST(verify_batch) {
  Digest d = sha512_digest(Bytes{42});
  std::vector<std::pair<PublicKey, Signature>> votes;
  for (const auto& kp : keys()) {
    votes.emplace_back(kp.name, Signature::sign(d, kp.secret));
  }
  CHECK(Signature::verify_batch(d, votes));
  votes[2].second.data[0] ^= 1;
  CHECK(!Signature::verify_batch(d, votes));
}

TEST(digest_builder_matches_oneshot) {
  Bytes msg{1, 2, 3, 4, 5};
  Digest a = sha512_digest(msg);
  Digest b = DigestBuilder()
                 .update(msg.data(), 2)
                 .update(msg.data() + 2, 3)
                 .finalize();
  CHECK(a == b);
}

TEST(signature_service) {
  auto kp = keys()[0];
  SignatureService service(kp.secret);
  Digest d = sha512_digest(Bytes{7, 7, 7});
  Signature sig = service.request_signature(d);
  CHECK(sig.verify(d, kp.name));
}

TEST(signature_serde_variable_length) {
  // 64-byte (Ed25519) and 192-byte (BLS G2) signatures round-trip; any
  // other length is rejected at deserialization (scheme=bls support).
  for (size_t len : {size_t(64), size_t(192)}) {
    Signature s;
    s.data = Bytes(len);
    for (size_t i = 0; i < len; i++) s.data[i] = uint8_t(i * 7);
    Writer w;
    s.serialize(&w);
    Reader r(w.out);
    Signature back = Signature::deserialize(&r);
    CHECK(back == s);
  }
  Signature bad;
  bad.data = Bytes(128, 3);
  Writer w;
  bad.serialize(&w);
  Reader r(w.out);
  bool threw = false;
  try {
    Signature::deserialize(&r);
  } catch (const SerdeError&) {
    threw = true;
  }
  CHECK(threw);
}

namespace {
// Restores the process-global scheme even when a failing CHECK returns
// early (a leaked kBls would poison every later test in the binary).
struct SchemeGuard {
  ~SchemeGuard() { set_scheme(Scheme::kEd25519); }
};
}  // namespace

TEST(bls_signature_paths_reject_without_sidecar) {
  // Under scheme=bls with no sidecar installed, verification rejects
  // (it must never fall through to the Ed25519 host loop).
  auto kp = keys()[0];
  Digest d = sha512_digest(Bytes{9});
  Signature sig = Signature::sign(d, kp.secret);  // ed25519-signed
  SchemeGuard guard;
  set_scheme(Scheme::kBls);
  CHECK(!sig.verify(d, kp.name));
  CHECK(!Signature::verify_batch(d, {{kp.name, sig}}));
  set_scheme(Scheme::kEd25519);
  CHECK(sig.verify(d, kp.name));
}

TEST(verify_batch_multi_distinct_digests) {
  // The TC path: every signature over its own digest, one batch call.
  auto kp1 = keypair_from_seed({{1}});
  auto kp2 = keypair_from_seed({{2}});
  Digest d1 = DigestBuilder().update_u64_le(7).update_u64_le(3).finalize();
  Digest d2 = DigestBuilder().update_u64_le(7).update_u64_le(5).finalize();
  Signature s1 = Signature::sign(d1, kp1.secret);
  Signature s2 = Signature::sign(d2, kp2.secret);
  CHECK(Signature::verify_batch_multi({{d1, kp1.name, s1},
                                       {d2, kp2.name, s2}}));
  // Swapped digests must fail.
  CHECK(!Signature::verify_batch_multi({{d2, kp1.name, s1},
                                        {d1, kp2.name, s2}}));
  // One corrupted signature fails the whole batch.
  Signature bad = s2;
  bad.data[5] ^= 1;
  CHECK(!Signature::verify_batch_multi({{d1, kp1.name, s1},
                                        {d2, kp2.name, bad}}));
}

int main() { return run_all(); }

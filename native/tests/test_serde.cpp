// Serialization + JSON + base64 round-trips.
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;

TEST(base64_roundtrip) {
  for (size_t len : {0u, 1u, 2u, 3u, 31u, 32u, 33u, 64u}) {
    Bytes b(len);
    for (size_t i = 0; i < len; i++) b[i] = uint8_t(i * 7 + 1);
    Bytes back;
    CHECK(base64_decode(base64_encode(b), &back));
    CHECK(back == b);
  }
  // 32-byte digests end with '=' (the log parser depends on this).
  Bytes d(32, 0xAB);
  std::string enc = base64_encode(d);
  CHECK(enc.size() == 44);
  CHECK(enc.back() == '=');
}

TEST(writer_reader_roundtrip) {
  Writer w;
  w.u8(7);
  w.u32(0xDEADBEEF);
  w.u64(0x0123456789ABCDEFull);
  w.bytes(Bytes{1, 2, 3});
  Reader r(w.out);
  CHECK(r.u8() == 7);
  CHECK(r.u32() == 0xDEADBEEF);
  CHECK(r.u64() == 0x0123456789ABCDEFull);
  CHECK(r.bytes() == (Bytes{1, 2, 3}));
  CHECK(r.done());
}

TEST(reader_rejects_truncation) {
  Writer w;
  w.u64(1000);  // claims 1000-element sequence in a tiny buffer
  Reader r(w.out);
  bool threw = false;
  try {
    r.seq_len();
  } catch (const SerdeError&) {
    threw = true;
  }
  CHECK(threw);
}

TEST(json_roundtrip) {
  std::string text = R"({"a": 1, "b": [true, null, "x\n"], "c": {"d": 2.5}})";
  Json j = Json::parse(text);
  CHECK(j.at("a").as_u64() == 1);
  CHECK(j.at("b").items().size() == 3);
  CHECK(j.at("b").items()[0].as_bool());
  CHECK(j.at("b").items()[2].as_string() == "x\n");
  CHECK(j.at("c").at("d").as_number() == 2.5);
  Json j2 = Json::parse(j.dump(2));
  CHECK(j2.at("c").at("d").as_number() == 2.5);
}

TEST(consensus_message_roundtrip) {
  auto committee = consensus_committee(6100);
  auto chain = make_chain(3, committee);
  consensus::Block& block = chain[2];
  block.payload.push_back(sha512_digest(Bytes{1, 2, 3}));

  Bytes ser = consensus::ConsensusMessage::propose(block);
  auto msg = consensus::ConsensusMessage::deserialize(ser);
  CHECK(msg.kind == consensus::ConsensusMessage::Kind::kPropose);
  CHECK(msg.block.digest() == block.digest());
  CHECK(msg.block.qc.votes.size() == 3);

  auto vote = make_vote(block, keys()[0]);
  auto vmsg = consensus::ConsensusMessage::deserialize(
      consensus::ConsensusMessage::vote_msg(vote));
  CHECK(vmsg.vote.digest() == vote.digest());
  CHECK(vmsg.vote.signature == vote.signature);
}

TEST(mempool_message_roundtrip) {
  mempool::Batch batch{{1, 2, 3}, {4, 5}};
  Bytes ser = mempool::MempoolMessage::make_batch(batch).serialize();
  auto m = mempool::MempoolMessage::deserialize(ser);
  CHECK(m.kind == mempool::MempoolMessage::Kind::kBatch);
  CHECK(m.batch == batch);

  auto req = mempool::MempoolMessage::make_batch_request(
      {sha512_digest(Bytes{9})}, keys()[1].name);
  auto m2 = mempool::MempoolMessage::deserialize(req.serialize());
  CHECK(m2.kind == mempool::MempoolMessage::Kind::kBatchRequest);
  CHECK(m2.missing.size() == 1);
  CHECK(m2.origin == keys()[1].name);
}

TEST(committee_json_roundtrip) {
  node::Committee c;
  c.consensus = consensus_committee(6200);
  c.mempool = mempool_committee(6300);
  c.write("/tmp/.hs_test_committee.json");
  node::Committee back = node::Committee::read("/tmp/.hs_test_committee.json");
  CHECK(back.consensus.size() == 4);
  CHECK(back.mempool.size() == 4);
  auto name = keys()[2].name;
  CHECK(back.consensus.address(name) == c.consensus.address(name));
  CHECK(back.mempool.mempool_address(name) == c.mempool.mempool_address(name));
  CHECK(back.consensus.quorum_threshold() == 3);
}

TEST(bls_config_roundtrip) {
  // scheme=bls material: per-authority bls_pubkey in the committee and
  // bls_secret in the key file survive the JSON round-trip.
  auto auths = consensus_committee(6400).authorities();
  std::map<PublicKey, consensus::Authority> with_bls;
  uint8_t fill = 1;
  for (auto [name, a] : auths) {
    a.bls_pubkey = Bytes(96, fill++);
    with_bls.emplace(name, std::move(a));
  }
  node::Committee c;
  c.consensus = consensus::Committee(std::move(with_bls), 1);
  c.mempool = mempool_committee(6500);
  c.write("/tmp/.hs_test_committee_bls.json");
  node::Committee back =
      node::Committee::read("/tmp/.hs_test_committee_bls.json");
  const auto& orig = c.consensus.authorities();
  for (const auto& [name, a] : back.consensus.authorities()) {
    CHECK(a.bls_pubkey == orig.at(name).bls_pubkey);  // exact per-authority
  }

  node::Secret s = node::Secret::generate();
  s.bls_secret = Bytes(48, 0x5A);
  s.write("/tmp/.hs_test_secret_bls.json");
  node::Secret back_s = node::Secret::read("/tmp/.hs_test_secret_bls.json");
  CHECK(back_s.bls_secret == s.bls_secret);
  CHECK(back_s.name == s.name);
}

TEST(deserializers_survive_hostile_bytes) {
  // The consensus/mempool receivers feed attacker-controlled bytes into
  // these deserializers and rely on exceptions (never UB) for rejection.
  // Deterministic xorshift fuzz: random buffers, truncations of valid
  // messages, and bit-flipped valid messages. Run under ASan in CI.
  uint64_t state = 0x9E3779B97F4A7C15ull;
  auto next = [&]() {
    state ^= state << 13;
    state ^= state >> 7;
    state ^= state << 17;
    return state;
  };
  auto fuzz_one = [](const Bytes& b) {
    try {
      (void)consensus::ConsensusMessage::deserialize(b);
    } catch (const std::exception&) {
    }
    try {
      (void)consensus::Block::from_bytes(b);
    } catch (const std::exception&) {
    }
    try {
      (void)mempool::MempoolMessage::deserialize(b);
    } catch (const std::exception&) {
    }
  };

  // 1. Pure random buffers (lengths 0..512).
  for (int i = 0; i < 2000; i++) {
    Bytes b(next() % 513);
    for (auto& c : b) c = uint8_t(next());
    fuzz_one(b);
  }

  // 2. Truncations and single-bit flips of a real message.
  auto chain = make_chain(1, consensus_committee(9900));
  Bytes valid = consensus::ConsensusMessage::propose(chain[0]);
  for (size_t cut = 0; cut < valid.size(); cut += 7) {
    fuzz_one(Bytes(valid.begin(), valid.begin() + cut));
  }
  for (int i = 0; i < 800; i++) {
    Bytes b = valid;
    b[next() % b.size()] ^= uint8_t(1 << (next() % 8));
    fuzz_one(b);
  }
  CHECK(true);  // reaching here without crash/sanitizer report is the pass
}

int main() { return run_all(); }

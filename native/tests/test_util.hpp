// Minimal test harness + deterministic fixtures, mirroring the reference's
// tests/common.rs pattern (seeded keys, 4-node localhost committees with
// per-file base ports, canned blocks/votes/QCs, chain builder, one-shot
// listener fakes — consensus/src/tests/common.rs:17-198).
#pragma once

#include <cstdio>
#include <functional>
#include <string>
#include <vector>

#include "consensus/messages.hpp"
#include "mempool/config.hpp"
#include "mempool/messages.hpp"
#include "node/config.hpp"

namespace hotstuff {
namespace test {

// -- harness ----------------------------------------------------------------

struct Registry {
  static Registry& get() {
    static Registry r;
    return r;
  }
  std::vector<std::pair<std::string, std::function<void()>>> tests;
  int failures = 0;
  std::string current;
};

struct Register {
  Register(const std::string& name, std::function<void()> fn) {
    Registry::get().tests.emplace_back(name, std::move(fn));
  }
};

#define TEST(name)                                                      \
  static void test_##name();                                            \
  static ::hotstuff::test::Register reg_##name(#name, test_##name);     \
  static void test_##name()

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::printf("FAIL %s: %s (%s:%d)\n",                              \
                  ::hotstuff::test::Registry::get().current.c_str(),    \
                  #cond, __FILE__, __LINE__);                           \
      ::hotstuff::test::Registry::get().failures++;                     \
      return;                                                           \
    }                                                                   \
  } while (0)

inline int run_all() {
  auto& reg = Registry::get();
  for (auto& [name, fn] : reg.tests) {
    reg.current = name;
    std::printf("RUN  %s\n", name.c_str());
    std::fflush(stdout);
    fn();
  }
  if (reg.failures) {
    std::printf("%d FAILURE(S)\n", reg.failures);
    return 1;
  }
  std::printf("OK (%zu tests)\n", reg.tests.size());
  return 0;
}

// -- fixtures ---------------------------------------------------------------

// Deterministic 4-node keys (seeds 100..103).
inline std::vector<KeyPair> keys() {
  std::vector<KeyPair> out;
  for (uint8_t i = 0; i < 4; i++) {
    std::array<uint8_t, 32> seed{};
    seed[0] = 100 + i;
    out.push_back(keypair_from_seed(seed));
  }
  return out;
}

inline consensus::Committee consensus_committee(uint16_t base_port) {
  std::map<PublicKey, consensus::Authority> auth;
  uint16_t port = base_port;
  for (const auto& kp : keys()) {
    consensus::Authority a;
    a.stake = 1;
    a.address = Address{"127.0.0.1", port++};
    auth.emplace(kp.name, a);
  }
  return consensus::Committee(std::move(auth), 1);
}

inline mempool::Committee mempool_committee(uint16_t base_port) {
  std::map<PublicKey, mempool::Authority> auth;
  uint16_t port = base_port;
  for (const auto& kp : keys()) {
    mempool::Authority a;
    a.stake = 1;
    a.transactions_address = Address{"127.0.0.1", port++};
    a.mempool_address = Address{"127.0.0.1", port++};
    auth.emplace(kp.name, a);
  }
  return mempool::Committee(std::move(auth), 1);
}

// Signed block from a specific key (Block::new_from_key analogue).
inline consensus::Block make_block(const consensus::QC& qc,
                                   const KeyPair& author, uint64_t round,
                                   std::vector<Digest> payload) {
  consensus::Block b;
  b.qc = qc;
  b.author = author.name;
  b.round = round;
  b.payload = std::move(payload);
  b.signature = Signature::sign(b.digest(), author.secret);
  return b;
}

inline consensus::Vote make_vote(const consensus::Block& block,
                                 const KeyPair& author) {
  consensus::Vote v;
  v.hash = block.digest();
  v.round = block.round;
  v.author = author.name;
  v.signature = Signature::sign(v.digest(), author.secret);
  return v;
}

// QC over a block hash/round signed by the first 3 fixture keys (quorum).
inline consensus::QC make_qc(const Digest& hash, uint64_t round) {
  consensus::QC qc;
  qc.hash = hash;
  qc.round = round;
  consensus::QC unsigned_qc = qc;
  Digest digest = unsigned_qc.digest();
  auto ks = keys();
  for (size_t i = 0; i < 3; i++) {
    qc.votes.emplace_back(ks[i].name, Signature::sign(digest, ks[i].secret));
  }
  return qc;
}

// Valid chain of n blocks rooted at genesis, each certified by a QC
// (chain() builder, common.rs:147-179). Leader keys cycle round-robin over
// the sorted committee so handle_proposal's leader check passes.
inline std::vector<consensus::Block> make_chain(
    size_t n, const consensus::Committee& committee) {
  auto ks = keys();
  auto sorted = committee.sorted_keys();
  auto key_for = [&](const PublicKey& name) -> const KeyPair& {
    for (const auto& kp : ks) {
      if (kp.name == name) return kp;
    }
    throw std::runtime_error("unknown leader");
  };
  std::vector<consensus::Block> chain;
  consensus::QC qc;  // genesis
  for (size_t i = 0; i < n; i++) {
    uint64_t round = i + 1;
    PublicKey leader = sorted[round % sorted.size()];
    consensus::Block b = make_block(qc, key_for(leader), round, {});
    qc = make_qc(b.digest(), b.round);
    chain.push_back(std::move(b));
  }
  return chain;
}

// One-shot fake peer: accepts a connection, receives one frame, replies
// "Ack", delivers the frame (listener() fixture, common.rs:182-198).
inline std::thread listener(Listener l, std::function<void(Bytes)> deliver,
                            bool ack = true) {
  return std::thread([l = std::make_shared<Listener>(std::move(l)), deliver,
                      ack]() mutable {
    auto sock = l->accept();
    if (!sock) return;
    Bytes frame;
    if (sock->read_frame(&frame)) {
      if (ack) {
        sock->write_frame(reinterpret_cast<const uint8_t*>("Ack"), 3);
      }
      if (deliver) deliver(std::move(frame));
    }
    // Closing here is fine: the ACK is already in the TCP buffer, and
    // senders treat the drop as a peer failure (best-effort / reconnect).
  });
}

}  // namespace test
}  // namespace hotstuff

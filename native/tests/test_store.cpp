// Store tests (store/src/tests/store_tests.rs:4-73 analogue): create,
// read/write, unknown key, notify_read wake-on-write, WAL persistence.
#include <cstdlib>
#include <thread>

#include <sys/stat.h>

#include "store/store.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;

TEST(create_store) {
  Store s = Store::open("");
  CHECK(s.valid());
}

TEST(read_write_value) {
  Store s = Store::open("");
  Bytes key{0, 1, 2}, value{3, 4, 5};
  s.write(key, value);
  auto got = s.read(key);
  CHECK(got.has_value());
  CHECK(*got == value);
}

TEST(read_unknown_key) {
  Store s = Store::open("");
  CHECK(!s.read(Bytes{9, 9, 9}).has_value());
}

TEST(read_notify) {
  Store s = Store::open("");
  Bytes key{0, 1, 2}, value{3, 4, 5};
  auto waiter = s.notify_read(key);
  CHECK(!waiter.ready());
  std::thread writer([&] {
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    s.write(key, value);
  });
  CHECK(waiter.wait() == value);
  writer.join();
  // already-present key resolves immediately
  auto instant = s.notify_read(key);
  CHECK(instant.wait_for(std::chrono::milliseconds(500)));
}

TEST(wal_persistence) {
  std::string path = "/tmp/.hs_test_store";
  std::system(("rm -rf " + path).c_str());
  Bytes key{1, 1}, value{2, 2, 2};
  {
    Store s = Store::open(path);
    s.write(key, value);
    // read-back forces the write to have been applied before scope exit
    CHECK(s.read(key).has_value());
  }
  Store s2 = Store::open(path);
  auto got = s2.read(key);
  CHECK(got.has_value());
  CHECK(*got == value);
  std::system(("rm -rf " + path).c_str());
}

TEST(wal_checksum_truncates_corrupt_record) {
  // Bit rot drill: flip one byte inside the SECOND record's value on
  // disk.  Replay must cut the WAL at the corrupt record — the first
  // record survives, the corrupted one and everything after it are gone
  // (never served back), and the store appends cleanly from the cut.
  const std::string path = "/tmp/.hs_store_crc";
  std::system(("rm -rf " + path).c_str());
  auto value_of = [](uint8_t i) { return Bytes(16, i); };
  {
    Store s = Store::open(path);
    s.write(Bytes{0}, value_of(10));
    s.write(Bytes{1}, value_of(11));
    s.write(Bytes{2}, value_of(12));
    CHECK(s.read(Bytes{2}).has_value());  // barrier: all writes applied
  }
  // Record layout: 4 klen | 1 key | 4 vlen | 16 value | 4 crc = 29 B.
  // Second record starts at 29; its value starts 9 bytes in.
  {
    std::FILE* f = std::fopen((path + "/wal").c_str(), "r+b");
    CHECK(f != nullptr);
    CHECK(std::fseek(f, 29 + 9 + 3, SEEK_SET) == 0);
    int c = std::fgetc(f);
    CHECK(c != EOF);
    CHECK(std::fseek(f, -1, SEEK_CUR) == 0);
    std::fputc(c ^ 0x40, f);
    std::fclose(f);
  }
  {
    Store s = Store::open(path);
    auto ok = s.read(Bytes{0});
    CHECK(ok.has_value());
    CHECK(*ok == value_of(10));
    CHECK(!s.read(Bytes{1}).has_value());  // corrupt: dropped, not served
    CHECK(!s.read(Bytes{2}).has_value());  // after the cut: dropped too
    s.write(Bytes{3}, value_of(13));       // append onto the clean cut
    CHECK(s.read(Bytes{3}).has_value());
  }
  Store s2 = Store::open(path);
  CHECK(s2.read(Bytes{0}).has_value());
  auto got = s2.read(Bytes{3});
  CHECK(got.has_value());
  CHECK(*got == value_of(13));
  std::system(("rm -rf " + path).c_str());
}

TEST(wal_compaction_bounds_overwrites) {
  // 10k overwrites of one key with a tiny compaction threshold: the WAL
  // must stay near the live size (one record), not 10k records, and the
  // data must survive a reopen (RocksDB-compaction analogue).
  const std::string path = "/tmp/.hs_store_compact";
  std::system(("rm -rf " + path).c_str());
  Bytes key{9, 9};
  Bytes final_value;
  {
    Store s = Store::open(path, /*compact_bytes=*/4096);
    for (int i = 0; i < 10'000; i++) {
      Bytes value(64, uint8_t(i & 0xFF));
      final_value = value;
      s.write(key, value);
      if (i % 37 == 0) {
        // Unique never-rewritten keys sprinkled across compaction
        // boundaries: each must survive the snapshot+rename (a snapshot
        // taken before the triggering write is applied would drop one).
        Bytes ukey{8, uint8_t(i >> 8), uint8_t(i & 0xFF)};
        s.write(ukey, Bytes{uint8_t(i & 0xFF)});
      }
    }
    CHECK(s.read(key).has_value());  // barrier: all writes applied
  }
  struct ::stat st;
  CHECK(::stat((path + "/wal").c_str(), &st) == 0);
  // 10k uncompacted records would be ~780 KB; compacted stays within a
  // few threshold units (live size + the tail since the last rewrite).
  CHECK(st.st_size < 6 * 4096);
  Store s2 = Store::open(path);
  auto got = s2.read(key);
  CHECK(got.has_value());
  CHECK(*got == final_value);
  for (int i = 0; i < 10'000; i += 37) {
    Bytes ukey{8, uint8_t(i >> 8), uint8_t(i & 0xFF)};
    auto gu = s2.read(ukey);
    CHECK(gu.has_value());
    CHECK(*gu == (Bytes{uint8_t(i & 0xFF)}));
  }
  std::system(("rm -rf " + path).c_str());
}

TEST(store_bounded_memory_spills_to_disk) {
  // The RocksDB-role requirement (store/src/lib.rs:28): state far larger
  // than the resident cap stays fully readable while the in-memory value
  // footprint remains bounded — values spill to the WAL and come back via
  // pread.
  const std::string path = "/tmp/.hs_store_bounded";
  std::system(("rm -rf " + path).c_str());
  constexpr size_t kCap = 64 * 1024;        // 64 KB resident cap
  constexpr int kKeys = 1000;               // 1 MB of 1 KB values >> cap
  auto key_of = [](int i) {
    return Bytes{7, uint8_t(i >> 8), uint8_t(i & 0xFF)};
  };
  auto value_of = [](int i) {
    Bytes v(1024, uint8_t(i & 0xFF));
    v[0] = uint8_t(i >> 8);  // make every value distinct
    return v;
  };
  {
    Store s = Store::open(path, /*compact_bytes=*/-1,
                          /*resident_bytes=*/kCap);
    for (int i = 0; i < kKeys; i++) s.write(key_of(i), value_of(i));
    auto st = s.stats();
    CHECK(st.keys == kKeys);
    CHECK(st.resident_bytes <= kCap);       // the bound held under load
    CHECK(st.wal_bytes > kKeys * 1024);     // ... because values spilled
    // Every value — including long-evicted ones — reads back correctly.
    for (int i = 0; i < kKeys; i++) {
      auto got = s.read(key_of(i));
      CHECK(got.has_value());
      CHECK(*got == value_of(i));
    }
    CHECK(s.stats().resident_bytes <= kCap);  // reads didn't unbound it
  }
  // Restart: the offset index rebuilds from the WAL; spilled reads work.
  Store s2 = Store::open(path, /*compact_bytes=*/-1,
                         /*resident_bytes=*/kCap);
  for (int i = 0; i < kKeys; i += 97) {
    auto got = s2.read(key_of(i));
    CHECK(got.has_value());
    CHECK(*got == value_of(i));
  }
  CHECK(s2.stats().resident_bytes <= kCap);
  std::system(("rm -rf " + path).c_str());
}

TEST(store_bounded_memory_survives_compaction) {
  // Compaction must carry EVICTED values into the snapshot (it reads them
  // back from the old WAL) and remap every offset to the new file.
  const std::string path = "/tmp/.hs_store_bounded_compact";
  std::system(("rm -rf " + path).c_str());
  auto key_of = [](int i) {
    return Bytes{6, uint8_t(i >> 8), uint8_t(i & 0xFF)};
  };
  Store s = Store::open(path, /*compact_bytes=*/8192,
                        /*resident_bytes=*/4096);
  // Unique cold keys (evicted early), then hot-key churn to trigger
  // compaction (appended > 4x live).
  for (int i = 0; i < 32; i++) s.write(key_of(i), Bytes(256, uint8_t(i)));
  for (int i = 0; i < 2000; i++) {
    s.write(Bytes{1, 2, 3}, Bytes(64, uint8_t(i & 0xFF)));
  }
  CHECK(s.read(Bytes{1, 2, 3}).has_value());  // barrier
  auto st = s.stats();
  CHECK(st.wal_bytes < 64 * 1024);  // compaction ran
  for (int i = 0; i < 32; i++) {
    auto got = s.read(key_of(i));
    CHECK(got.has_value());
    CHECK(*got == Bytes(256, uint8_t(i)));
  }
  std::system(("rm -rf " + path).c_str());
}

TEST(channel_send_until_no_consume_on_timeout) {
  // Foundation of Store::try_write's failure contract: a send_until
  // that times out on a full channel must leave *value intact (moved
  // back nowhere - never consumed), so the caller can divert the bytes
  // to an overflow lane.
  auto ch = make_channel<Bytes>(1);
  CHECK(ch->try_send(Bytes{1}));  // fill to capacity
  Bytes v(1024, 42);
  auto st = ch->send_until(&v, std::chrono::steady_clock::now());
  CHECK(st == RecvStatus::kTimeout);
  CHECK(v == Bytes(1024, 42));  // untouched
  Bytes drained;
  CHECK(ch->try_recv(&drained));
  st = ch->send_until(&v, std::chrono::steady_clock::now());
  CHECK(st == RecvStatus::kOk);  // space freed: consumed now
}

TEST(try_write_moves_and_lands) {
  // The reactor-thread write path: non-blocking, and the value is MOVED
  // (a peer batch is ~500 KB; a copy on the event loop would be the
  // exact cost the inline path exists to avoid).  The
  // value-intact-on-failure half of the contract rides on
  // channel::send_until's no-consume-on-timeout guarantee, which the
  // channel tests pin down.
  Store s = Store::open("");
  Bytes v{9, 9, 9};
  CHECK(s.try_write(Bytes{1}, &v));
  auto got = s.read(Bytes{1});
  CHECK(got.has_value());
  CHECK(*got == (Bytes{9, 9, 9}));

  Bytes big(512 * 1024, 7);
  CHECK(s.try_write(Bytes{2}, &big));
  CHECK(big.empty());  // moved, not copied
  auto got2 = s.read(Bytes{2});
  CHECK(got2.has_value());
  CHECK(got2->size() == 512 * 1024);
}

int main() { return run_all(); }

// Store tests (store/src/tests/store_tests.rs:4-73 analogue): create,
// read/write, unknown key, notify_read wake-on-write, WAL persistence.
#include <cstdlib>
#include <thread>

#include "store/store.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;

TEST(create_store) {
  Store s = Store::open("");
  CHECK(s.valid());
}

TEST(read_write_value) {
  Store s = Store::open("");
  Bytes key{0, 1, 2}, value{3, 4, 5};
  s.write(key, value);
  auto got = s.read(key);
  CHECK(got.has_value());
  CHECK(*got == value);
}

TEST(read_unknown_key) {
  Store s = Store::open("");
  CHECK(!s.read(Bytes{9, 9, 9}).has_value());
}

TEST(read_notify) {
  Store s = Store::open("");
  Bytes key{0, 1, 2}, value{3, 4, 5};
  auto waiter = s.notify_read(key);
  CHECK(!waiter.ready());
  std::thread writer([&] {
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    s.write(key, value);
  });
  CHECK(waiter.wait() == value);
  writer.join();
  // already-present key resolves immediately
  auto instant = s.notify_read(key);
  CHECK(instant.wait_for(std::chrono::milliseconds(500)));
}

TEST(wal_persistence) {
  std::string path = "/tmp/.hs_test_store";
  std::system(("rm -rf " + path).c_str());
  Bytes key{1, 1}, value{2, 2, 2};
  {
    Store s = Store::open(path);
    s.write(key, value);
    // read-back forces the write to have been applied before scope exit
    CHECK(s.read(key).has_value());
  }
  Store s2 = Store::open(path);
  auto got = s2.read(key);
  CHECK(got.has_value());
  CHECK(*got == value);
  std::system(("rm -rf " + path).c_str());
}

int main() { return run_all(); }

// Store tests (store/src/tests/store_tests.rs:4-73 analogue): create,
// read/write, unknown key, notify_read wake-on-write, WAL persistence.
#include <cstdlib>
#include <thread>

#include <sys/stat.h>

#include "store/store.hpp"
#include "test_util.hpp"

using namespace hotstuff;
using namespace hotstuff::test;

TEST(create_store) {
  Store s = Store::open("");
  CHECK(s.valid());
}

TEST(read_write_value) {
  Store s = Store::open("");
  Bytes key{0, 1, 2}, value{3, 4, 5};
  s.write(key, value);
  auto got = s.read(key);
  CHECK(got.has_value());
  CHECK(*got == value);
}

TEST(read_unknown_key) {
  Store s = Store::open("");
  CHECK(!s.read(Bytes{9, 9, 9}).has_value());
}

TEST(read_notify) {
  Store s = Store::open("");
  Bytes key{0, 1, 2}, value{3, 4, 5};
  auto waiter = s.notify_read(key);
  CHECK(!waiter.ready());
  std::thread writer([&] {
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    s.write(key, value);
  });
  CHECK(waiter.wait() == value);
  writer.join();
  // already-present key resolves immediately
  auto instant = s.notify_read(key);
  CHECK(instant.wait_for(std::chrono::milliseconds(500)));
}

TEST(wal_persistence) {
  std::string path = "/tmp/.hs_test_store";
  std::system(("rm -rf " + path).c_str());
  Bytes key{1, 1}, value{2, 2, 2};
  {
    Store s = Store::open(path);
    s.write(key, value);
    // read-back forces the write to have been applied before scope exit
    CHECK(s.read(key).has_value());
  }
  Store s2 = Store::open(path);
  auto got = s2.read(key);
  CHECK(got.has_value());
  CHECK(*got == value);
  std::system(("rm -rf " + path).c_str());
}

TEST(wal_compaction_bounds_overwrites) {
  // 10k overwrites of one key with a tiny compaction threshold: the WAL
  // must stay near the live size (one record), not 10k records, and the
  // data must survive a reopen (RocksDB-compaction analogue).
  const std::string path = "/tmp/.hs_store_compact";
  std::system(("rm -rf " + path).c_str());
  Bytes key{9, 9};
  Bytes final_value;
  {
    Store s = Store::open(path, /*compact_bytes=*/4096);
    for (int i = 0; i < 10'000; i++) {
      Bytes value(64, uint8_t(i & 0xFF));
      final_value = value;
      s.write(key, value);
      if (i % 37 == 0) {
        // Unique never-rewritten keys sprinkled across compaction
        // boundaries: each must survive the snapshot+rename (a snapshot
        // taken before the triggering write is applied would drop one).
        Bytes ukey{8, uint8_t(i >> 8), uint8_t(i & 0xFF)};
        s.write(ukey, Bytes{uint8_t(i & 0xFF)});
      }
    }
    CHECK(s.read(key).has_value());  // barrier: all writes applied
  }
  struct ::stat st;
  CHECK(::stat((path + "/wal").c_str(), &st) == 0);
  // 10k uncompacted records would be ~780 KB; compacted stays within a
  // few threshold units (live size + the tail since the last rewrite).
  CHECK(st.st_size < 6 * 4096);
  Store s2 = Store::open(path);
  auto got = s2.read(key);
  CHECK(got.has_value());
  CHECK(*got == final_value);
  for (int i = 0; i < 10'000; i += 37) {
    Bytes ukey{8, uint8_t(i >> 8), uint8_t(i & 0xFF)};
    auto gu = s2.read(ukey);
    CHECK(gu.has_value());
    CHECK(*gu == (Bytes{uint8_t(i & 0xFF)}));
  }
  std::system(("rm -rf " + path).c_str());
}

int main() { return run_all(); }

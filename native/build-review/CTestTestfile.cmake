# CMake generated Testfile for 
# Source directory: /root/repo/native
# Build directory: /root/repo/native/build-review
# 
# This file includes the relevant testing commands required for 
# testing this directory and lists subdirectories to be tested as well.
add_test([=[serde]=] "/root/repo/native/build-review/test_serde")
set_tests_properties([=[serde]=] PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;71;add_test;/root/repo/native/CMakeLists.txt;0;")
add_test([=[crypto]=] "/root/repo/native/build-review/test_crypto")
set_tests_properties([=[crypto]=] PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;71;add_test;/root/repo/native/CMakeLists.txt;0;")
add_test([=[store]=] "/root/repo/native/build-review/test_store")
set_tests_properties([=[store]=] PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;71;add_test;/root/repo/native/CMakeLists.txt;0;")
add_test([=[network]=] "/root/repo/native/build-review/test_network")
set_tests_properties([=[network]=] PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;71;add_test;/root/repo/native/CMakeLists.txt;0;")
add_test([=[mempool]=] "/root/repo/native/build-review/test_mempool")
set_tests_properties([=[mempool]=] PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;71;add_test;/root/repo/native/CMakeLists.txt;0;")
add_test([=[consensus]=] "/root/repo/native/build-review/test_consensus")
set_tests_properties([=[consensus]=] PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;71;add_test;/root/repo/native/CMakeLists.txt;0;")
add_test([=[e2e]=] "/root/repo/native/build-review/test_e2e")
set_tests_properties([=[e2e]=] PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/native/CMakeLists.txt;71;add_test;/root/repo/native/CMakeLists.txt;0;")

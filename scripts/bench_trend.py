#!/usr/bin/env python3
"""graftscope bench-trajectory regression ledger.

Every driver round commits a ``BENCH_*.json`` artifact, but nothing ever
READ them as a sequence — a perf regression between rounds (a headline
that quietly halved, a sub-field that vanished) was invisible until a
human diffed the files.  This script parses every committed artifact
into one trajectory, ``results/trend.json``, and judges the latest live
numbers against the best on record:

  * per-headline-field best/latest (fields are the flattened numeric
    leaves of the emitted JSON line: ``value``,
    ``rlc.n256.rlc_sigs_per_s``, ``roofline.n1024.pallas...``, ...);
  * degraded runs flagged (``"degraded": true`` lines, non-zero driver
    rc, rounds that emitted nothing) and EXCLUDED from "best" and from
    the regression comparison — a CPU-fallback number regressing
    against a TPU best is backend noise, not a regression;
  * schema-tolerant across rounds: artifacts are driver wrappers with a
    ``parsed`` line (BENCH_r01..), bare headline objects
    (BENCH_surge_degraded), or wedged rounds with no line at all
    (BENCH_r04/r05 rc=124) — all land in the ledger.

``--check`` exits non-zero when the latest live headline ``value``
regressed more than ``--threshold`` (default 0.2 = 20%) below the best
live value on record.  CI runs it warn-only today (no live device
number has landed since round 2); the moment the first real device
headline lands, this ledger is what will defend it.

Usage:
    python scripts/bench_trend.py                # write results/trend.json
    python scripts/bench_trend.py --check        # + exit 1 on regression
    python scripts/bench_trend.py --check --threshold 0.1
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from glob import glob

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA = "bench-trend-v1"
# The one field --check judges: the headline sigs/sec number every
# round emits.
HEADLINE_FIELD = "value"
# The PRIMARY metric — the one that owns the un-namespaced field lanes
# and the --check judgement — is whatever the first numbered driver
# round declares (every committed round emits ``ed25519-batch-verify``;
# this constant is only the fallback for a history with no metric at
# all).  Artifacts declaring a DIFFERENT metric — the graftdag
# consensus-throughput headline (``dag-commit-tps``) is the first —
# land under ``<metric>:<path>`` lanes instead: their numbers trend
# with the same best/latest/degraded-excluded-from-best machinery, but
# a 5k tx/s commit rate can never masquerade as (or regress) a 39k
# sigs/s verify headline.  Artifacts with no ``metric`` key stay
# un-namespaced (legacy wedged rounds).
HEADLINE_METRIC = "ed25519-batch-verify"


def flatten_numeric(obj, prefix: str = "") -> dict:
    """JSON object -> {dotted.path: number} over its numeric leaves
    (bools excluded: ``"degraded": true`` is a flag, not a measurement).
    Lists are indexed; strings/None are skipped."""
    out: dict = {}
    if isinstance(obj, bool) or obj is None:
        return out
    if isinstance(obj, (int, float)):
        if prefix:
            out[prefix] = obj
        return out
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(flatten_numeric(v, key))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            out.update(flatten_numeric(v, f"{prefix}[{i}]"))
    return out


def parse_artifact(path: str) -> dict:
    """One BENCH_*.json -> a run record (never raises; unreadable files
    become flagged degraded runs with an error note)."""
    name = os.path.basename(path)
    run = {"file": name, "n": None, "rc": None, "degraded": True,
           "error": None, "metric": None, "fields": {}}
    m = re.search(r"_r(\d+)", name)
    if m:
        run["n"] = int(m.group(1))
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        run["error"] = f"unreadable: {e}"
        return run
    if not isinstance(doc, dict):
        run["error"] = "artifact is not a JSON object"
        return run
    # Driver-wrapper shape: {"n", "rc", "tail", "parsed": {...}|null}.
    # Bare-headline shape: {"metric", "value", ...}.
    parsed = doc.get("parsed") if "parsed" in doc else doc
    if isinstance(doc.get("n"), int):
        run["n"] = doc["n"]
    if isinstance(doc.get("rc"), int):
        run["rc"] = doc["rc"]
    if not isinstance(parsed, dict) or "value" not in parsed:
        run["error"] = "no parsed headline line (wedged round)"
        return run
    if isinstance(parsed.get("metric"), str):
        run["metric"] = parsed["metric"]
    run["fields"] = flatten_numeric(parsed)
    err = parsed.get("error") or parsed.get("note")
    if isinstance(err, str):
        run["error"] = err[:200]
    # A live run: the driver exited 0 (or the artifact has no rc), the
    # line is not self-flagged degraded, and it carried no error.
    run["degraded"] = bool(parsed.get("degraded")) \
        or (run["rc"] not in (None, 0)) \
        or bool(parsed.get("error")) \
        or parsed.get("value") in (0, None)
    return run


def build_trend(paths) -> dict:
    runs = [parse_artifact(p) for p in paths]
    # Round order: numbered rounds first (ascending), then the named
    # artifacts (degraded committed lines) in name order.
    runs.sort(key=lambda r: (r["n"] is None, r["n"] or 0, r["file"]))
    fields: dict = {}
    primary = next((r["metric"] for r in runs if r["metric"]),
                   HEADLINE_METRIC)
    for run in runs:
        # Foreign-metric artifacts get their own field namespace (see
        # HEADLINE_METRIC).
        ns = "" if run["metric"] in (None, primary) \
            else run["metric"] + ":"
        for path, val in run["fields"].items():
            entry = fields.setdefault(ns + path, {
                "best": None, "best_run": None,
                "latest": None, "latest_run": None,
                "latest_live": None, "latest_live_run": None,
                "latest_degraded": None})
            entry["latest"] = val
            entry["latest_run"] = run["file"]
            entry["latest_degraded"] = run["degraded"]
            if not run["degraded"]:
                entry["latest_live"] = val
                entry["latest_live_run"] = run["file"]
                if entry["best"] is None or val > entry["best"]:
                    entry["best"] = val
                    entry["best_run"] = run["file"]
    return {
        "schema": SCHEMA,
        "headline_metric": primary,
        "runs": [{k: v for k, v in r.items() if k != "fields"}
                 | {"value": r["fields"].get(HEADLINE_FIELD)}
                 for r in runs],
        "fields": fields,
    }


def judge(trend: dict, threshold: float) -> dict:
    """Regression verdict on the headline field: latest live value vs
    best live value on record.  Not judgeable (no live run, or only
    one) => ok with a reason — the gate must not fail on a repo whose
    only committed lines are degraded."""
    entry = trend["fields"].get(HEADLINE_FIELD) or {}
    best, latest = entry.get("best"), entry.get("latest_live")
    if best is None or latest is None:
        return {"ok": True, "judged": False, "threshold": threshold,
                "reason": "no live headline run on record"}
    if entry.get("best_run") == entry.get("latest_live_run"):
        return {"ok": True, "judged": False, "threshold": threshold,
                "reason": "latest live run IS the best on record"}
    floor = best * (1.0 - threshold)
    ok = latest >= floor
    return {"ok": ok, "judged": True, "threshold": threshold,
            "best": best, "best_run": entry["best_run"],
            "latest": latest, "latest_run": entry["latest_live_run"],
            "floor": round(floor, 3),
            "reason": None if ok else (
                f"latest live headline {latest:g} fell "
                f"{(1 - latest / best):.0%} below best {best:g} "
                f"({entry['best_run']}) — past the {threshold:.0%} "
                "threshold")}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=REPO,
                    help="repo root holding the BENCH_*.json artifacts")
    ap.add_argument("--glob", default="BENCH_*.json",
                    help="artifact pattern relative to --root")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="trajectory output (default "
                         "<root>/results/trend.json)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when the latest live headline regressed "
                         "past --threshold below the best on record")
    ap.add_argument("--threshold", type=float, default=0.2,
                    help="allowed relative regression (default 0.2)")
    args = ap.parse_args(argv)
    if not 0.0 <= args.threshold < 1.0:
        print("bench_trend: --threshold must be in [0, 1)",
              file=sys.stderr)
        return 2
    paths = sorted(glob(os.path.join(args.root, args.glob)))
    if not paths:
        print(f"bench_trend: no artifacts match {args.glob} under "
              f"{args.root}", file=sys.stderr)
        return 2
    trend = build_trend(paths)
    verdict = judge(trend, args.threshold)
    trend["check"] = verdict
    out = args.out or os.path.join(args.root, "results", "trend.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(trend, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, out)

    live = [r for r in trend["runs"] if not r["degraded"]]
    print(f"bench_trend: {len(trend['runs'])} run(s) "
          f"({len(live)} live, {len(trend['runs']) - len(live)} "
          f"degraded/wedged), {len(trend['fields'])} field(s) -> {out}")
    for r in trend["runs"]:
        tag = "live" if not r["degraded"] else "DEGRADED"
        val = f"{r['value']:g}" if isinstance(
            r["value"], (int, float)) else "-"
        note = f" [{r['error']}]" if r["error"] else ""
        print(f"  {r['file']}: value={val} ({tag}){note}")
    if verdict["judged"]:
        word = "ok" if verdict["ok"] else "REGRESSION"
        print(f"bench_trend: headline {word}: latest live "
              f"{verdict['latest']:g} vs best {verdict['best']:g} "
              f"(floor {verdict['floor']:g})")
    else:
        print(f"bench_trend: headline not judged: {verdict['reason']}")
    if args.check and not verdict["ok"]:
        print(f"bench_trend: CHECK FAILED: {verdict['reason']}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

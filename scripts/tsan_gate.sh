#!/usr/bin/env bash
# Tier-2 TSan gate: build the native tree under ThreadSanitizer and run
# the curated unit-test subset inside a bounded window.
#
#   scripts/tsan_gate.sh [test ...]
#
# Closes the ROADMAP item "TSan in the tier-2 gate: preset wired,
# runtime too slow for the CI window".  Two things made it fit:
#
#   1. The runtime was never the sanitizer — it was triage.  GCC 10's
#      libtsan has no pthread_cond_clockwait interceptor, and this
#      libstdc++ inlines that call for every steady-clock cv wait, so a
#      baseline run drowned in 617 false reports (every Channel/Oneshot
#      handoff as a double-lock + data races).  Thread-mode builds now
#      link native/sanitize/tsan_clockwait_shim.cpp, which reroutes the
#      wait through the intercepted pthread_cond_timedwait; the real
#      suite runs clean (see scripts/tsan.supp for the policy).
#   2. The curated subset is the six unit binaries (serde store crypto
#      network mempool consensus) — test_e2e spawns whole committees
#      and stays in the plain build, same curation as ASan/UBSan.
#      Measured on this container: ~2m20s cold (full instrumented
#      build), ~21s warm — both far inside the default 600 s budget.
#
# TSAN_GATE_BUDGET_S overrides the window; the gate FAILS (rc 124) if
# the budget is exceeded, so a runtime regression is a loud CI signal,
# never a silently-lengthening job.
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
BUDGET="${TSAN_GATE_BUDGET_S:-600}"
TESTS=("$@")
if [ ${#TESTS[@]} -eq 0 ]; then
  TESTS=(serde store crypto network mempool consensus)
fi

# exitcode=66 makes any report fatal at process exit even where the
# test harness would otherwise return 0; second_deadlock_stack gives
# both lock orders on a deadlock report.
export TSAN_OPTIONS="suppressions=$ROOT/scripts/tsan.supp \
exitcode=66 second_deadlock_stack=1 ${TSAN_OPTIONS:-}"

start=$(date +%s)
rc=0
timeout -k 10 "$BUDGET" \
    "$ROOT/scripts/native_sanitize.sh" thread "${TESTS[@]}" || rc=$?
if [ "$rc" -ne 0 ]; then
  if [ "$rc" -eq 124 ]; then
    echo "tsan_gate: exceeded the ${BUDGET}s budget" >&2
  else
    echo "tsan_gate: FAILED (rc=$rc)" >&2
  fi
  exit "$rc"
fi
end=$(date +%s)
echo "tsan_gate: clean in $((end - start))s (budget ${BUDGET}s; tests: ${TESTS[*]})"

"""A/B: f32 radix-2^8 conv multiply vs int8 radix-2^5 conv multiply.

Measures the slope (per-mul marginal cost) of K-long jitted mul chains
over a (1024, NLIMBS) batch on the default JAX device — the tunnel-
measurement discipline from scripts/PROFILE.md: per-dispatch fixed cost
is removed by differencing two chain lengths, and each timing is
best-of-trials so neighbor load doesn't pollute the comparison.

The Ed25519 ladder is mul-dominated, so the slope ratio here bounds the
end-to-end speedup the int8 redesign could deliver (PROFILE.md lever #1).

Prints one JSON line with both slopes and the ratio.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 1024
K_SHORT, K_LONG = 8, 40
TRIALS = 5


def chain(mod, k):
    def f(x):
        def body(acc, _):
            return mod.mul(acc, acc), None

        out, _ = jax.lax.scan(body, x, None, length=k)
        return out

    return jax.jit(f)


def best_seconds(fn, x):
    out = fn(x)
    jax.block_until_ready(out)  # compile + warm
    best = float("inf")
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(x))
        best = min(best, time.perf_counter() - t0)
    return best


def slope_us(mod):
    rng = np.random.default_rng(7)
    bound = 512 if mod.LIMB_BITS == 8 else 64
    x = jnp.asarray(rng.integers(0, bound, (BATCH, mod.NLIMBS)), jnp.int32)
    t_short = best_seconds(chain(mod, K_SHORT), x)
    t_long = best_seconds(chain(mod, K_LONG), x)
    return (t_long - t_short) / (K_LONG - K_SHORT) * 1e6


def main():
    from hotstuff_tpu.ops import field25519 as f32e
    from hotstuff_tpu.ops import field25519_int8 as i8e

    f32e.mul_selfcheck()
    i8e.mul_selfcheck()

    s_f32 = slope_us(f32e)
    s_i8 = slope_us(i8e)
    print(json.dumps({
        "backend": jax.default_backend(),
        "batch": BATCH,
        "f32_r8_us_per_mul": round(s_f32, 2),
        "int8_r5_us_per_mul": round(s_i8, 2),
        "int8_speedup": round(s_f32 / s_i8, 3) if s_i8 > 0 else None,
        "note": "slope of K-mul chains, best of %d trials; both engines "
                "passed exactness self-checks first" % TRIALS,
    }))


if __name__ == "__main__":
    main()

"""Is the axon tunnel's h2d bandwidth per-stream or physical?

Measures device_put throughput for the bench.py round payload (2.1 MB)
with 1 vs 2 concurrent transfer threads.  If the ~13 MB/s observed by
bench.py is a per-connection/TCP-window limit, two streams should scale
and bench.py's single-thread xfer pool is leaving ~2x headline
throughput on the table; if it is the link's physical rate, two streams
will split it and the current pipeline shape is already optimal.

Run only with a live tunnel: python scripts/exp_xfer_streams.py
"""

import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np


def main():
    import jax

    rng = np.random.default_rng(7)
    # Two distinct buffers per stream so caching can't fake a win.
    bufs = [rng.integers(0, 256, size=(16, 1024, 130), dtype=np.uint8)
            for _ in range(4)]
    mb = bufs[0].nbytes / 1e6

    jax.device_put(bufs[0]).block_until_ready()  # warm the path

    def put(buf):
        x = jax.device_put(buf)
        x.block_until_ready()
        return x

    for streams in (1, 2):
        best = 0.0
        for trial in range(4):
            with ThreadPoolExecutor(streams) as pool:
                t0 = time.perf_counter()
                futs = [pool.submit(put, bufs[(trial + i) % 4])
                        for i in range(2 * streams)]
                for f in futs:
                    f.result()
                dt = time.perf_counter() - t0
            rate = 2 * streams * mb / dt
            best = max(best, rate)
        print(f"streams={streams}: best {best:.1f} MB/s "
              f"({2 * streams} x {mb:.1f} MB)")


if __name__ == "__main__":
    main()

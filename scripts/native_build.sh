#!/usr/bin/env bash
# Plain (no-sanitizer) native build for containers without cmake: the same
# direct-g++ recipe scripts/native_sanitize.sh uses, producing
# native/build/{node,client,offchain_bench,test_*} with per-object mtime
# caching (any header edit rebuilds everything — no dep scanning).  With
# cmake available, prefer `cmake -S native -B native/build`.
#
#   scripts/native_build.sh [test ...]    # tests to build; default: all
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
NATIVE="$ROOT/native"
BUILD="$NATIVE/build"
mkdir -p "$BUILD"

CXX="${CXX:-g++}"
FLAGS=(-std=c++17 -Wall -Wextra -O2 -g -I"$NATIVE/src" -pthread)

LIBCRYPTO=""
for cand in /lib/x86_64-linux-gnu/libcrypto.so.3 \
            /usr/lib/x86_64-linux-gnu/libcrypto.so.3 \
            /lib/x86_64-linux-gnu/libcrypto.so.1.1 \
            /usr/lib/x86_64-linux-gnu/libcrypto.so.1.1; do
  if [ -e "$cand" ]; then LIBCRYPTO="$cand"; break; fi
done
if [ -z "$LIBCRYPTO" ]; then
  echo "native_build: no libcrypto found" >&2
  exit 1
fi

hdr_mtime=$(find "$NATIVE/src" -name '*.hpp' -printf '%T@\n' \
            | sort -rn | head -1 | cut -d. -f1)

stale() {  # stale <obj> <src>: needs rebuilding?
  [ ! -e "$1" ] && return 0
  [ "$2" -nt "$1" ] && return 0
  [ "$hdr_mtime" -gt "$(stat -c %Y "$1")" ] && return 0
  return 1
}

build_obj() {  # build_obj <src> <obj> [extra flags...]
  local src="$1" obj="$2"; shift 2
  if stale "$obj" "$src"; then
    echo "CXX $(basename "$obj")"
    "$CXX" "${FLAGS[@]}" "$@" -c "$src" -o "$obj" &
  fi
}

lib_objs=()
for src in "$NATIVE"/src/*/*.cpp; do
  obj="$BUILD/$(basename "$(dirname "$src")")_$(basename "$src").o"
  case "$src" in
    */node/main.cpp|*/node/client.cpp|*/node/offchain_bench.cpp) ;;
    *) lib_objs+=("$obj") ;;
  esac
  build_obj "$src" "$obj"
done

TESTS=("$@")
if [ ${#TESTS[@]} -eq 0 ]; then
  TESTS=(serde crypto store network mempool consensus client e2e)
fi
for t in "${TESTS[@]}"; do
  src="$NATIVE/tests/test_$t.cpp"
  [ -e "$src" ] && build_obj "$src" "$BUILD/test_$t.o" -I"$NATIVE/tests"
done
wait

link() {  # link <out> <main-obj>
  echo "LNK $(basename "$1")"
  "$CXX" "${FLAGS[@]}" "$2" "${lib_objs[@]}" "$LIBCRYPTO" -o "$1"
}

link "$BUILD/node" "$BUILD/node_main.cpp.o"
link "$BUILD/client" "$BUILD/node_client.cpp.o"
link "$BUILD/offchain_bench" "$BUILD/node_offchain_bench.cpp.o"
for t in "${TESTS[@]}"; do
  [ -e "$BUILD/test_$t.o" ] && link "$BUILD/test_$t" "$BUILD/test_$t.o"
done
echo "native_build: done"

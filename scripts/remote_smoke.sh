#!/bin/sh
# End-to-end smoke of the REAL remote-bench code path (harness/remote.py:
# config generation, scp upload, nohup/setsid background node+client
# launch over "ssh", kill, log download, parse) using the local-exec
# ssh/scp shims in fake_ssh/ — this image ships no ssh client or sshd.
# The "fleet" is four loopback IPs (127.0.0.1-4, distinct bind addresses
# on lo); each host gets its own fake home under .remote-smoke/<ip>/ with
# a repo/ "checkout" (binary symlinks), so collocated hosts cannot
# clobber each other's configs or logs.
set -e
cd "$(dirname "$0")/.."
cmake --build native/build -j > /dev/null
rm -rf .remote-smoke
for ip in 127.0.0.1 127.0.0.2 127.0.0.3 127.0.0.4; do
  mkdir -p ".remote-smoke/$ip/repo/logs"
  ln -sf "$PWD/native/build/node" ".remote-smoke/$ip/repo/node"
  ln -sf "$PWD/native/build/client" ".remote-smoke/$ip/repo/client"
done
FAKE_SSH_HOME_BASE="$PWD/.remote-smoke" \
  PATH="$PWD/scripts/fake_ssh:$PATH" exec python -m hotstuff_tpu.harness \
  remote --settings scripts/remote_smoke_settings.json \
  --nodes 4 --rate "${1:-7000}" --duration "${2:-15}"

"""Device-side profiling of the Ed25519 verify kernel (SURVEY §5.1 TPU add).

Times each stage of the verification pipeline separately on the real chip:
host preparation, H2D transfer, decompression, the digit unpack, the
256-step ladder, and the full fused program — to locate where the batch
latency actually goes before optimizing.  Run: python scripts/profile_verify.py
Optionally dumps a jax profiler trace with --trace (view offline).
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, reps=10, warmup=2):
    # Force a D2H copy to synchronize: through the axon tunnel,
    # block_until_ready() returns before the program actually finishes and
    # under-reports by 1000x (see scripts/PROFILE.md).
    for _ in range(warmup):
        out = fn(*args)
    np.asarray(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / reps


def main():
    from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
    from hotstuff_tpu.ops import ed25519 as E
    from hotstuff_tpu.ops import field25519 as F

    N = 1024
    rng = np.random.default_rng(7)
    msgs, pks, sigs = [], [], []
    for _ in range(N):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        m = rng.bytes(64)
        msgs.append(m)
        pks.append(pk)
        sigs.append(ref.sign(sk, m))

    # --- host prep ---
    t0 = time.perf_counter()
    prep = eddsa.prepare_batch(msgs, pks, sigs)
    t_prep = time.perf_counter() - t0
    print(f"host prepare_batch      : {t_prep*1e3:8.2f} ms  "
          f"({N/t_prep:,.0f} sigs/s host-bound)")

    packed_np = prep["packed"]

    # --- H2D transfer ---
    t = timeit(lambda x: jnp.asarray(x).block_until_ready(), packed_np)
    print(f"H2D transfer (128B/sig) : {t*1e3:8.2f} ms")

    packed = jnp.asarray(packed_np)
    ay, a_sign = E.split_y_sign(packed[:, 0:32].astype(jnp.int32))
    ry, r_sign = E.split_y_sign(packed[:, 32:64].astype(jnp.int32))

    # --- decompress (x2 points) ---
    dec = jax.jit(lambda y, s: E.decompress(y, s)[0])
    t = timeit(dec, ay, a_sign)
    print(f"decompress one point    : {t*1e3:8.2f} ms")

    # --- digit unpack ---
    unp = jax.jit(E.unpack_nibbles_msb)
    t = timeit(unp, packed[:, 96:128])
    print(f"unpack_nibbles_msb      : {t*1e3:8.2f} ms")

    # --- comb + ladder + final eq, given points ---
    s_digits = packed[:, 64:96].astype(jnp.int32)
    k_digits = unp(packed[:, 96:128])

    def ladder_only(ay, a_sign, ry, r_sign, s_digits, k_digits):
        return E.verify_prepared(ay, a_sign, ry, r_sign, s_digits, k_digits)

    t = timeit(jax.jit(ladder_only), ay, a_sign, ry, r_sign, s_digits,
               k_digits)
    print(f"verify_prepared (full)  : {t*1e3:8.2f} ms")

    # --- single field mul at batch (N,32) ---
    a = jnp.asarray(rng.integers(0, 512, (N, 32)), jnp.int32)
    b = jnp.asarray(rng.integers(0, 512, (N, 32)), jnp.int32)
    t = timeit(jax.jit(F.mul), a, b)
    print(f"one field mul (N,32)    : {t*1e6:8.1f} us")
    t4 = timeit(jax.jit(lambda x, y: F.mul(F.mul(x, y), F.mul(y, x))), a, b)
    print(f"three chained muls      : {t4*1e6:8.1f} us")

    # --- full verify_packed ---
    t = timeit(E.verify_packed_jit, packed)
    print(f"verify_packed (device)  : {t*1e3:8.2f} ms  "
          f"({N/t:,.0f} sigs/s device-bound)")

    if "--trace" in sys.argv:
        with jax.profiler.trace("/tmp/jax-trace"):
            E.verify_packed_jit(packed).block_until_ready()
        print("trace written to /tmp/jax-trace")


if __name__ == "__main__":
    main()

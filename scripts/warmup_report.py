#!/usr/bin/env python3
"""Cold-vs-warm sidecar boot report from the graftkern compile manifest.

Every device-mode sidecar boot records its warmup into
``results/compile_cache/manifest.json`` (utils/xla_cache.CompileTracker:
per-run manifest hits/misses + wall time, keyed on the kernel-source
hash).  This script prints the recorded runs and the headline the cache
exists for: the warmup wall time of the latest COLD boot (misses > 0)
next to the latest WARM boot (misses == 0) of the same kernel.

    scripts/warmup_report.py [--manifest PATH] [--stats PATH] [--json]

``--stats`` additionally folds in the ``compile`` section of a
harness-fetched OP_STATS snapshot (logs/sidecar-stats.json) — the same
numbers the LogParser surfaces as the "Sidecar compile cache" CONFIG
note.  Exit status: 0 with a report, 1 when the manifest is missing or
holds no runs (nothing to report is a finding, not a crash).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fmt_t(t: float) -> str:
    try:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t))
    except (OverflowError, OSError, ValueError):
        return "?"


def report(manifest: dict, stats: dict | None = None) -> dict:
    """The machine-readable report (also what --json prints): recorded
    runs, plus the cold-vs-warm comparison for the newest kernel that
    has both boot classes on record."""
    runs = [r for r in manifest.get("runs", []) if isinstance(r, dict)]
    out: dict = {"runs": runs, "comparison": None}
    # Newest-first by record order; compare within the newest kernel
    # hash that has both a cold and a warm run (a kernel edit resets
    # the story — cross-kernel comparisons would be apples to oranges).
    for run in reversed(runs):
        kernel = run.get("kernel")
        same = [r for r in runs if r.get("kernel") == kernel]
        cold = [r for r in same if r.get("misses", 0) > 0]
        warm = [r for r in same if r.get("misses", 0) == 0
                and r.get("hits", 0) > 0]
        if cold and warm:
            c, w = cold[-1], warm[-1]
            saved = c["wall_s"] - w["wall_s"]
            out["comparison"] = {
                "kernel": kernel,
                "cold_wall_s": c["wall_s"],
                "warm_wall_s": w["wall_s"],
                "saved_s": round(saved, 3),
                "saved_pct": round(100.0 * saved / c["wall_s"], 1)
                if c["wall_s"] else 0.0,
            }
            break
    if stats is not None:
        out["stats_compile"] = stats.get("compile")
    return out


def main(argv=None) -> int:
    from hotstuff_tpu.utils.xla_cache import default_manifest_path

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--manifest", default=default_manifest_path(),
                    help="compile manifest path (default: "
                         "results/compile_cache/manifest.json)")
    ap.add_argument("--stats", default=None, metavar="PATH",
                    help="also report the compile section of this "
                         "OP_STATS snapshot (logs/sidecar-stats.json)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report instead")
    args = ap.parse_args(argv)

    try:
        with open(args.manifest, encoding="utf-8") as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        print(f"warmup_report: no usable manifest at {args.manifest} "
              f"({e.__class__.__name__}) — run a device-mode sidecar "
              "boot first", file=sys.stderr)
        return 1
    stats = None
    if args.stats:
        try:
            with open(args.stats, encoding="utf-8") as f:
                stats = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warmup_report: --stats unreadable ({e!r:.80})",
                  file=sys.stderr)
            stats = {}

    doc = report(manifest, stats)
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0 if doc["runs"] else 1
    if not doc["runs"]:
        print("warmup_report: manifest holds no recorded warmup runs",
              file=sys.stderr)
        return 1
    print(f"warmup runs ({args.manifest}):")
    for r in doc["runs"]:
        boot = "warm" if r.get("misses", 0) == 0 and r.get("hits", 0) \
            else "cold"
        print(f"  {_fmt_t(r.get('t', 0))}  kernel {r.get('kernel', '?')}  "
              f"{boot:4s}  {r.get('hits', 0):3d} hit(s) "
              f"{r.get('misses', 0):3d} miss(es)  "
              f"wall {r.get('wall_s', 0):g} s")
    cmp_ = doc["comparison"]
    if cmp_:
        print(f"cold boot {cmp_['cold_wall_s']:g} s -> warm boot "
              f"{cmp_['warm_wall_s']:g} s "
              f"({cmp_['saved_pct']:g}% faster, kernel {cmp_['kernel']})")
    else:
        print("no cold+warm pair recorded for any one kernel yet "
              "(boot the sidecar twice against the same cache)")
    sc = doc.get("stats_compile")
    if sc:
        boot = "warm boot" if sc.get("warm_boot") else "cold boot"
        print(f"last OP_STATS compile section: {sc.get('hits', 0)} "
              f"hit(s), {sc.get('misses', 0)} miss(es) — {boot}, "
              f"warmup {sc.get('warmup_wall_s', 0):g} s")
    return 0


if __name__ == "__main__":
    sys.exit(main())

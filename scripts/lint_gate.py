#!/usr/bin/env python3
"""CI gate: run graftlint (python -m hotstuff_tpu.analysis) from anywhere.

Exit status is the number-of-findings truth: 0 clean, 1 findings, 2 bad
usage.  Every perf PR runs this before benching — the rules it enforces
are exactly the silent-degradation class (host syncs, retraces, wire
drift, unlocked sharing) that a green unit-test run does not catch.

All CLI flags pass through to the analysis module, so
``scripts/lint_gate.py --json-out findings.json`` emits the
machine-readable findings document next to the text output (CI and
tooling consume that instead of scraping lines).
"""

import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Suppression budget: every `graftlint: disable=` in shipped code is a
# hole in a checker, and holes must not accrete silently.  The budget is
# a RATCHET on suppressions with no same-line rationale — new disables
# must say why on the same line (the older preceding-comment style is
# grandfathered into the baseline, which may only shrink).
_SUPPRESS_SCAN_ROOTS = ("hotstuff_tpu", os.path.join("native", "src"),
                        "scripts", "bench.py")
_SUPPRESS_RE = re.compile(
    r"(?:#|//)\s*graftlint:\s*disable=([\w\-, ]+)(.*)")
_BASELINE = os.path.join(REPO, "scripts", "suppression_baseline.json")


def count_suppressions(repo):
    """(total, without_rationale, bare_sites) over the shipped tree —
    tests and fixtures are out of scope: a fixture's suppression is the
    thing under test, not a hole."""
    total, bare, sites = 0, 0, []
    for root in _SUPPRESS_SCAN_ROOTS:
        path = os.path.join(repo, root)
        if os.path.isfile(path):
            files = [path]
        else:
            files = [os.path.join(dp, f)
                     for dp, _dns, fns in os.walk(path)
                     for f in sorted(fns)
                     if f.endswith((".py", ".cpp", ".hpp", ".h"))]
        for fp in sorted(files):
            with open(fp, encoding="utf-8", errors="replace") as fh:
                for lineno, line in enumerate(fh, start=1):
                    m = _SUPPRESS_RE.search(line)
                    if not m:
                        continue
                    total += 1
                    if not m.group(2).strip():
                        bare += 1
                        sites.append(
                            f"{os.path.relpath(fp, repo)}:{lineno}")
    return total, bare, sites


def check_suppression_budget(repo, update=False):
    """0 if the bare-suppression count respects the baseline ratchet."""
    total, bare, sites = count_suppressions(repo)
    if update:
        with open(_BASELINE, encoding="utf-8") as fh:
            doc = json.load(fh)
        doc["total"], doc["without_rationale"] = total, bare
        with open(_BASELINE, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2)
            fh.write("\n")
        print(f"suppression baseline updated: total={total}, "
              f"without_rationale={bare}")
        return 0
    with open(_BASELINE, encoding="utf-8") as fh:
        budget = json.load(fh)["without_rationale"]
    if bare > budget:
        print(f"suppression budget exceeded: {bare} `graftlint: "
              f"disable=` line(s) without a same-line rationale "
              f"(baseline {budget}).  Add the why after the rule list "
              f"on the same line, or consciously refresh the baseline "
              f"with --update-suppression-baseline.", file=sys.stderr)
        for s in sites:
            print(f"  bare suppression: {s}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    from hotstuff_tpu.analysis.__main__ import main

    argv = sys.argv[1:]
    if "--update-suppression-baseline" in argv:
        sys.exit(check_suppression_budget(REPO, update=True))
    if not any(a == "--root" or a.startswith("--root=") for a in argv):
        argv += ["--root", REPO]
    if not any(a == "--must-cover" or a.startswith("--must-cover=")
               for a in argv):
        # Checker-qualified pins: the RLC scalar module and every
        # verifysched module must stay inside the HOTPATH scan (the
        # sockets checker also walking sidecar/ must not satisfy them),
        # and the graftchaos modules inside the SOCKETS scan.  The gate
        # fails if any of them ever moves out of its checker's target
        # set (or is deleted without this pin being updated consciously).
        for pin in ("hotpath:hotstuff_tpu/ops/scalar25519.py",
                    # graftkern: every Pallas kernel module stays inside
                    # BOTH the hot-path taint scan and the padshape scan
                    # (which carries the pallas-interpret-in-prod rule)
                    # — a kernel module that moves out of either loses
                    # the silent-degradation net this layer rides on.
                    "hotpath:hotstuff_tpu/ops/kern/__init__.py",
                    "hotpath:hotstuff_tpu/ops/kern/backend.py",
                    "hotpath:hotstuff_tpu/ops/kern/fieldops.py",
                    "hotpath:hotstuff_tpu/ops/kern/field_mul.py",
                    "hotpath:hotstuff_tpu/ops/kern/msm_accum.py",
                    "hotpath:hotstuff_tpu/ops/kern/scalar_mont.py",
                    "padshape:hotstuff_tpu/ops/kern/__init__.py",
                    "padshape:hotstuff_tpu/ops/kern/backend.py",
                    "padshape:hotstuff_tpu/ops/kern/fieldops.py",
                    "padshape:hotstuff_tpu/ops/kern/field_mul.py",
                    "padshape:hotstuff_tpu/ops/kern/msm_accum.py",
                    "padshape:hotstuff_tpu/ops/kern/scalar_mont.py",
                    "hotpath:hotstuff_tpu/parallel/shard_shapes.py",
                    # graftscale: the whole-backlog chunked mesh scan op
                    # lives in sharded_verify — it must stay inside BOTH
                    # the hot-path taint scan and the padshape scan
                    # (which carries the shard-misaligned-launch rule
                    # over its (g, rows) chunk arithmetic).
                    "hotpath:hotstuff_tpu/parallel/sharded_verify.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/__init__.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/classes.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/scheduler.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/shapes.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/stats.py",
                    "padshape:hotstuff_tpu/parallel/sharded_verify.py",
                    "padshape:hotstuff_tpu/sidecar/sched/shapes.py",
                    "sockets:hotstuff_tpu/chaos/__init__.py",
                    "sockets:hotstuff_tpu/chaos/plan.py",
                    "sockets:hotstuff_tpu/chaos/runner.py",
                    "sockets:hotstuff_tpu/chaos/recovery.py",
                    "sockets:hotstuff_tpu/chaos/netem.py",
                    "sockets:hotstuff_tpu/chaos/slo.py",
                    "sockets:hotstuff_tpu/harness/faults.py",
                    "sockets:hotstuff_tpu/harness/remote.py",
                    "sockets:hotstuff_tpu/harness/local.py",
                    "sockets:hotstuff_tpu/harness/logs.py",
                    # grafttrace: every obs module stays inside the span
                    # checker AND the timing checker's scans (the
                    # critical-path numbers those modules compute feed
                    # every future perf claim).
                    "obsspan:hotstuff_tpu/obs/__init__.py",
                    "obsspan:hotstuff_tpu/obs/spans.py",
                    "obsspan:hotstuff_tpu/obs/trace.py",
                    "obsspan:hotstuff_tpu/obs/sampler.py",
                    "obsspan:hotstuff_tpu/sidecar/service.py",
                    "timing:hotstuff_tpu/obs/trace.py",
                    "timing:hotstuff_tpu/obs/sampler.py",
                    # graftscope: both halves of each frozen node-log
                    # grammar (TRACE + METRICS) stay inside the
                    # obsgrammar cross-check — a side moving out of the
                    # scan is how a one-sided grammar edit ships.
                    "obsgrammar:hotstuff_tpu/obs/trace.py",
                    "obsgrammar:hotstuff_tpu/obs/sampler.py",
                    "obsgrammar:native/src/consensus/core.cpp",
                    "obsgrammar:native/src/common/metrics.cpp",
                    # graftsync: every threaded Python module stays
                    # inside the THREADS scan, and every annotated
                    # native file inside the CXXSYNC scan — a module
                    # that grows a thread (or a header that grows a
                    # mutex) outside these sets must consciously join
                    # the pin list.
                    "threads:hotstuff_tpu/sidecar/service.py",
                    "threads:hotstuff_tpu/sidecar/sched/scheduler.py",
                    "threads:hotstuff_tpu/sidecar/sched/classes.py",
                    # graftguard: the engine AND the supervisor must
                    # stay inside the unsupervised-launch scan (an
                    # engine wait moving out of it is how the next
                    # wedged-launch hang ships), and guard.py — which
                    # owns the monitor + disposable launch threads —
                    # inside the THREADS scan.
                    "guard:hotstuff_tpu/sidecar/service.py",
                    "guard:hotstuff_tpu/sidecar/guard.py",
                    "threads:hotstuff_tpu/sidecar/guard.py",
                    # graftcadence: the resident ring stays inside the
                    # ring checker's tick-body scan (unbounded waits /
                    # unwarmed-shape launches in the cadence loop), the
                    # guard scan (it shares the engine thread), the
                    # THREADS scan, and the hot-path taint scan.
                    "ring:hotstuff_tpu/sidecar/ring.py",
                    "guard:hotstuff_tpu/sidecar/ring.py",
                    "threads:hotstuff_tpu/sidecar/ring.py",
                    "hotpath:hotstuff_tpu/sidecar/ring.py",
                    # graftsurge: the admission controller and the load
                    # model stay inside the THREADS scan (both are
                    # called from multiple threads), and every surge
                    # module inside the new BOUNDED-INGRESS scan.
                    "threads:hotstuff_tpu/sidecar/sched/surge.py",
                    "ingress:hotstuff_tpu/sidecar/sched/surge.py",
                    "ingress:hotstuff_tpu/sidecar/sched/scheduler.py",
                    "ingress:hotstuff_tpu/sidecar/sched/classes.py",
                    "ingress:hotstuff_tpu/harness/loadgen.py",
                    "threads:hotstuff_tpu/obs/sampler.py",
                    "threads:hotstuff_tpu/chaos/runner.py",
                    "threads:hotstuff_tpu/harness/faults.py",
                    "threads:hotstuff_tpu/harness/local.py",
                    "cxxsync:native/src/network/event_loop.hpp",
                    "cxxsync:native/src/network/event_loop.cpp",
                    "cxxsync:native/src/network/reliable_sender.hpp",
                    "cxxsync:native/src/network/reliable_sender.cpp",
                    "cxxsync:native/src/store/store.hpp",
                    "cxxsync:native/src/crypto/sidecar_client.hpp",
                    "cxxsync:native/src/crypto/sidecar_client.cpp",
                    "cxxsync:native/src/consensus/mempool_driver.hpp",
                    "cxxsync:native/src/consensus/core.cpp",
                    # graftview: the optimistic timeout aggregator and
                    # the cascade-driving chaos modules stay inside
                    # their checkers' scans.
                    "cxxsync:native/src/consensus/aggregator.hpp",
                    "cxxsync:native/src/consensus/aggregator.cpp",
                    "cxxsync:native/src/mempool/ingress.hpp",
                    "cxxsync:native/src/common/metrics.hpp",
                    "cxxsync:native/src/common/metrics.cpp",
                    # grafttaint: the consensus core and the sidecar wire
                    # codec anchor the verification-gate provenance scan
                    # — either moving out of the TAINT target set means
                    # the no-unverified-bytes proof silently stops
                    # covering the paths it exists for.
                    "taint:native/src/consensus/core.cpp",
                    "taint:hotstuff_tpu/sidecar/protocol.py",
                    # graftingress: the admission-verify stage and the
                    # signed-tx codec twins must stay inside the taint
                    # and cxxsync scans — the tx-signature gate proof
                    # and the frame-constant cross-check both die
                    # silently if either side drops out.
                    "taint:native/src/mempool/tx_verify.cpp",
                    "taint:native/src/mempool/tx_verify.hpp",
                    "taint:hotstuff_tpu/crypto/txsign.py",
                    # graftdag: the certified-batch mempool modules stay
                    # inside the taint scan — the batch-certificate gate
                    # (signed-ACK assembly/verification) and the
                    # cert-driven prefetch sink both lose their
                    # provenance proof if any of these drops out.
                    "taint:native/src/mempool/messages.cpp",
                    "taint:native/src/mempool/quorum_waiter.cpp",
                    "taint:native/src/mempool/synchronizer.cpp",
                    "taint:native/src/consensus/mempool_driver.cpp",
                    "cxxsync:native/src/mempool/tx_verify.hpp",
                    "cxxsync:native/src/mempool/tx_verify.cpp",
                    # graftfleet: the tenant-lane implementation and the
                    # scheduler modules that consume it stay inside the
                    # tenant-unscoped-queue scan — a scheduler module
                    # moving out of it is how the next raw-deque bypass
                    # of the DRR fairness discipline ships.
                    "tenantq:hotstuff_tpu/sidecar/sched/tenantq.py",
                    "tenantq:hotstuff_tpu/sidecar/sched/scheduler.py",
                    "tenantq:hotstuff_tpu/sidecar/sched/classes.py",
                    "threads:hotstuff_tpu/sidecar/sched/tenantq.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/tenantq.py"):
            argv += ["--must-cover", pin]
    rc = main(argv)
    budget_rc = check_suppression_budget(REPO)
    sys.exit(rc or budget_rc)

#!/usr/bin/env python3
"""CI gate: run graftlint (python -m hotstuff_tpu.analysis) from anywhere.

Exit status is the number-of-findings truth: 0 clean, 1 findings, 2 bad
usage.  Every perf PR runs this before benching — the rules it enforces
are exactly the silent-degradation class (host syncs, retraces, wire
drift) that a green unit-test run does not catch.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    sys.path.insert(0, REPO)
    from hotstuff_tpu.analysis.__main__ import main

    argv = sys.argv[1:]
    if not any(a == "--root" or a.startswith("--root=") for a in argv):
        argv += ["--root", REPO]
    if not any(a == "--must-cover" or a.startswith("--must-cover=")
               for a in argv):
        # Checker-qualified pins: the RLC scalar module and every
        # verifysched module must stay inside the HOTPATH scan (the
        # sockets checker also walking sidecar/ must not satisfy them),
        # and the graftchaos modules inside the SOCKETS scan.  The gate
        # fails if any of them ever moves out of its checker's target
        # set (or is deleted without this pin being updated consciously).
        for pin in ("hotpath:hotstuff_tpu/ops/scalar25519.py",
                    "hotpath:hotstuff_tpu/parallel/shard_shapes.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/__init__.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/classes.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/scheduler.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/shapes.py",
                    "hotpath:hotstuff_tpu/sidecar/sched/stats.py",
                    "padshape:hotstuff_tpu/parallel/sharded_verify.py",
                    "padshape:hotstuff_tpu/sidecar/sched/shapes.py",
                    "sockets:hotstuff_tpu/chaos/__init__.py",
                    "sockets:hotstuff_tpu/chaos/plan.py",
                    "sockets:hotstuff_tpu/chaos/runner.py",
                    "sockets:hotstuff_tpu/chaos/recovery.py",
                    "sockets:hotstuff_tpu/chaos/netem.py",
                    "sockets:hotstuff_tpu/chaos/slo.py",
                    "sockets:hotstuff_tpu/harness/faults.py",
                    "sockets:hotstuff_tpu/harness/remote.py",
                    "sockets:hotstuff_tpu/harness/local.py",
                    "sockets:hotstuff_tpu/harness/logs.py",
                    # grafttrace: every obs module stays inside the span
                    # checker AND the timing checker's scans (the
                    # critical-path numbers those modules compute feed
                    # every future perf claim).
                    "obsspan:hotstuff_tpu/obs/__init__.py",
                    "obsspan:hotstuff_tpu/obs/spans.py",
                    "obsspan:hotstuff_tpu/obs/trace.py",
                    "obsspan:hotstuff_tpu/obs/sampler.py",
                    "obsspan:hotstuff_tpu/sidecar/service.py",
                    "timing:hotstuff_tpu/obs/trace.py",
                    "timing:hotstuff_tpu/obs/sampler.py"):
            argv += ["--must-cover", pin]
    sys.exit(main(argv))

#!/usr/bin/env python3
"""CI gate: run graftlint (python -m hotstuff_tpu.analysis) from anywhere.

Exit status is the number-of-findings truth: 0 clean, 1 findings, 2 bad
usage.  Every perf PR runs this before benching — the rules it enforces
are exactly the silent-degradation class (host syncs, retraces, wire
drift) that a green unit-test run does not catch.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    sys.path.insert(0, REPO)
    from hotstuff_tpu.analysis.__main__ import main

    argv = sys.argv[1:]
    if not any(a == "--root" or a.startswith("--root=") for a in argv):
        argv += ["--root", REPO]
    if not any(a == "--must-cover" or a.startswith("--must-cover=")
               for a in argv):
        # The RLC scalar module is device hot-path code, and every
        # verifysched module is engine-thread control plane: the gate
        # fails if any of them ever moves out of the scanned target set
        # (or is deleted without this pin being updated consciously).
        for pin in ("hotstuff_tpu/ops/scalar25519.py",
                    "hotstuff_tpu/sidecar/sched/__init__.py",
                    "hotstuff_tpu/sidecar/sched/classes.py",
                    "hotstuff_tpu/sidecar/sched/scheduler.py",
                    "hotstuff_tpu/sidecar/sched/shapes.py",
                    "hotstuff_tpu/sidecar/sched/stats.py"):
            argv += ["--must-cover", pin]
    sys.exit(main(argv))

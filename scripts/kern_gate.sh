#!/usr/bin/env bash
# Tier-2 graftkern gate: run the interpret-mode Pallas kernel suite —
# including the slow lane (engine-path RLC bisection under
# HOTSTUFF_TPU_KERN=pallas, and the n=1024 window-accumulator agreement
# sweep) — inside a bounded window.
#
#   scripts/kern_gate.sh [pytest-args ...]
#
# What fits the window and why (measured on this container, cold):
#
#   1. The per-kernel property sweeps are cheap (~30 s total): each
#      kernel is ONE pallas trace per shape thanks to the jit-in-jit
#      wrapping (see ops/kern/__init__.py), so the interpreter cost is
#      a handful of compiles, not one per call site.
#   2. The slow lane is compile-bound, not run-bound: the full RLC
#      program with every field mul routed through the interpreter
#      compiles in ~70 s at n=8 plus ~55 s for its bisection floor, and
#      the B=1024 window-accumulator agreement costs ~90 s — ~4 min
#      total, far inside the default 900 s budget.
#
# KERN_GATE_BUDGET_S overrides the window; the gate FAILS (rc 124) if
# the budget is exceeded, so a kernel-compile-time regression is a loud
# CI signal, never a silently-lengthening job (same contract as
# scripts/tsan_gate.sh).
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
BUDGET="${KERN_GATE_BUDGET_S:-900}"

# pytest only puts the CALLER's cwd on sys.path: run from the repo root
# so tests/conftest.py can import hotstuff_tpu from any invocation dir.
cd "$ROOT"

start=$(date +%s)
rc=0
timeout -k 10 "$BUDGET" env JAX_PLATFORMS=cpu HOTSTUFF_TPU_SLOW_TESTS=1 \
    python -m pytest "$ROOT/tests/test_kern.py" -q \
    -p no:cacheprovider "$@" || rc=$?
if [ "$rc" -ne 0 ]; then
  if [ "$rc" -eq 124 ]; then
    echo "kern_gate: exceeded the ${BUDGET}s budget" >&2
  else
    echo "kern_gate: FAILED (rc=$rc)" >&2
  fi
  exit "$rc"
fi
end=$(date +%s)
echo "kern_gate: clean in $((end - start))s (budget ${BUDGET}s)"

#!/usr/bin/env bash
# Build the native tree under a sanitizer and run the unit test binaries.
#
#   scripts/native_sanitize.sh [address|undefined|thread] [test ...]
#
# Default tests: the unit paths (serde crypto store network mempool
# consensus); test_e2e spawns whole committees and is left to the plain
# build.  With cmake available this is `-DGRAFT_SANITIZE=<mode>` + ctest;
# this container has no cmake, so the fallback drives g++ directly with
# the same flags the CMake preset pins (-fsanitize=<mode>
# -fno-omit-frame-pointer -g -O1, plus -fno-sanitize-recover=undefined
# so UBSan reports are fatal).  Objects are cached per mode under
# native/build-sanitize-<mode>/ and rebuilt when their source is newer.
set -euo pipefail

MODE="${1:-address}"
shift || true
case "$MODE" in
  address|undefined|thread) ;;
  *) echo "usage: $0 [address|undefined|thread] [test ...]" >&2; exit 2 ;;
esac
TESTS=("$@")
if [ ${#TESTS[@]} -eq 0 ]; then
  TESTS=(serde crypto store network mempool consensus client)
fi

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
NATIVE="$ROOT/native"
BUILD="$NATIVE/build-sanitize-$MODE"
mkdir -p "$BUILD"

if command -v cmake >/dev/null 2>&1; then
  cmake -S "$NATIVE" -B "$BUILD" -DGRAFT_SANITIZE="$MODE" >/dev/null
  cmake --build "$BUILD" -j "$(nproc)"
  (cd "$BUILD" && ctest --output-on-failure -R "$(IFS='|'; echo "${TESTS[*]}")")
  exit $?
fi

echo "native_sanitize: no cmake; driving g++ -fsanitize=$MODE directly"
CXX="${CXX:-g++}"
FLAGS=(-std=c++17 -Wall -Wextra -fsanitize="$MODE"
       -fno-omit-frame-pointer -g -O1 -I"$NATIVE/src" -pthread)
if [ "$MODE" = undefined ]; then
  FLAGS+=(-fno-sanitize-recover=undefined)
fi

# The image ships libcrypto without dev symlinks; link the versioned
# object directly, preferring 3.x (what CMakeLists pins) over 1.1.
LIBCRYPTO=""
for cand in /lib/x86_64-linux-gnu/libcrypto.so.3 \
            /usr/lib/x86_64-linux-gnu/libcrypto.so.3 \
            /lib/x86_64-linux-gnu/libcrypto.so.1.1 \
            /usr/lib/x86_64-linux-gnu/libcrypto.so.1.1; do
  if [ -e "$cand" ]; then LIBCRYPTO="$cand"; break; fi
done
if [ -z "$LIBCRYPTO" ]; then
  echo "native_sanitize: no libcrypto found" >&2
  exit 1
fi

# Core sources (everything but the executables' main() files).
mapfile -t SRCS < <(find "$NATIVE/src" -name '*.cpp' \
  ! -name main.cpp ! -name client.cpp ! -name offchain_bench.cpp | sort)
if [ "$MODE" = thread ]; then
  # GCC 10's libtsan lacks the pthread_cond_clockwait interceptor this
  # libstdc++ inlines for steady-clock cv waits; without the shim every
  # Channel/Oneshot handoff reports as a false double-lock + data race.
  SRCS+=("$NATIVE/sanitize/tsan_clockwait_shim.cpp")
fi

compile() {  # compile $1 into $2 unless the object is current
  local src="$1" obj="$2"
  # An object is stale if its source OR any header changed — headers are
  # not tracked per-object, so any newer .hpp rebuilds (cheap vs a
  # sanitizer gate passing on a never-reinstrumented binary).
  if [ -e "$obj" ] && [ "$obj" -nt "$src" ] && \
     [ -z "$(find "$NATIVE/src" "$NATIVE/tests" -name '*.hpp' \
             -newer "$obj" -print -quit)" ]; then
    return 0
  fi
  "$CXX" "${FLAGS[@]}" -c "$src" -o "$obj"
}

OBJS=()
for src in "${SRCS[@]}"; do
  obj="$BUILD/$(echo "${src#"$NATIVE/src/"}" | tr / _).o"
  compile "$src" "$obj" &
  OBJS+=("$obj")
  # bound parallelism to the core count
  while [ "$(jobs -r | wc -l)" -ge "$(nproc)" ]; do wait -n; done
done
wait

FAILURES=0
for t in "${TESTS[@]}"; do
  src="$NATIVE/tests/test_$t.cpp"
  bin="$BUILD/test_$t"
  obj="$bin.o"
  compile "$src" "$obj"
  # Always relink: linking is seconds, and a stale binary would let the
  # sanitizer gate pass on code it never ran.
  "$CXX" "${FLAGS[@]}" "$obj" "${OBJS[@]}" "$LIBCRYPTO" -o "$bin"
  echo "== $MODE: test_$t"
  if ! "$bin"; then
    echo "native_sanitize: test_$t FAILED under $MODE" >&2
    FAILURES=$((FAILURES + 1))
  fi
done

if [ "$FAILURES" -gt 0 ]; then
  echo "native_sanitize: $FAILURES test binary(ies) failed under $MODE" >&2
  exit 1
fi
echo "native_sanitize: all tests clean under $MODE"

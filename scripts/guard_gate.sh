#!/usr/bin/env bash
# Tier-2 graftguard gate: the structure-aware protocol fuzz corpus plus
# the slow wedge-recovery lane (the full supervisor ladder through a
# live SidecarServer: scripted wedge -> host-fallback masks -> BUSY for
# bulk -> crash-only reboot -> canary -> poison bisection) inside a
# bounded window.
#
#   scripts/guard_gate.sh [pytest-args ...]
#
# What fits the window and why (measured on this container, cold):
#
#   1. The fuzz corpus is cheap (~20 s): decode-level cases are pure
#      byte pushing, and the live-handler cases each pay one socket
#      round trip against a host-mode server with short timeouts.
#   2. The wedge lanes are deadline-bound by construction: guard
#      deadlines in the tests are tens of milliseconds, so a full
#      wedge -> reboot -> bisect cycle costs well under a second; the
#      slow e2e lane (live server + chaos plan + parser round trip)
#      adds a few seconds of real traffic.
#   3. The graftcadence ring lane (tests/test_ring.py) rides the same
#      bound: generation-tag lifecycle on a virtual clock, plus the
#      ring wedge-recovery drill — a forced wedge mid-cadence must drop
#      the ring back to the staged engine through the ladder with
#      bit-identical masks and no double reply.
#   4. The graftingress signed-tx lane (tests/test_ingress_tier.py,
#      plus the tx-frame fuzz corpus inside test_fuzz.py) is pure
#      python-side work: frame/key derivation, parser accounting and
#      the small-population users probe — a few seconds total.
#   5. The graftdag lane (tests/test_dag.py) pins the certified-batch
#      mempool's Python contracts: the dagwire constant mirror against
#      native/src/mempool/messages.hpp, the dagack domain-separated
#      preimage, and the full-engine proof that quorum-sized
#      certificate ACK batches land on the warmed RLC bucket with
#      verdict masks bit-identical to per-signature verify_batch
#      (warm-cache: tens of seconds, dominated by the shared RLC
#      warmup compiles the verifysched lane also pays).
#   6. The graftfleet lane (tests/test_fleet.py) adds the two scripted
#      drills on top of its fast DRR/HELLO/dedup coverage: the
#      2-sidecar kill-primary failover e2e (real subprocesses, sticky
#      re-home, strict sidecar-failover SLO parse) and the seeded
#      greedy-tenant flood (tenant_starvation == 0 plus the victim
#      queue-wait 2x bound, judged strict).  The sidecar boots
#      dominate (~30-60 s each for the JAX import); the drills
#      themselves are a few seconds of traffic.
#
# GUARD_GATE_BUDGET_S overrides the window; the gate FAILS (rc 124) if
# the budget is exceeded, so a supervisor-latency regression is a loud
# CI signal, never a silently-lengthening job (same contract as
# scripts/kern_gate.sh and scripts/tsan_gate.sh).
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
BUDGET="${GUARD_GATE_BUDGET_S:-600}"

# pytest only puts the CALLER's cwd on sys.path: run from the repo root
# so tests/conftest.py can import hotstuff_tpu from any invocation dir.
cd "$ROOT"

start=$(date +%s)
rc=0
timeout -k 10 "$BUDGET" env JAX_PLATFORMS=cpu HOTSTUFF_TPU_SLOW_TESTS=1 \
    python -m pytest "$ROOT/tests/test_fuzz.py" "$ROOT/tests/test_guard.py" \
    "$ROOT/tests/test_ring.py" "$ROOT/tests/test_ingress_tier.py" \
    "$ROOT/tests/test_fleet.py" "$ROOT/tests/test_dag.py" \
    -q -p no:cacheprovider "$@" || rc=$?
if [ "$rc" -ne 0 ]; then
  if [ "$rc" -eq 124 ]; then
    echo "guard_gate: exceeded the ${BUDGET}s budget" >&2
  else
    echo "guard_gate: FAILED (rc=$rc)" >&2
  fi
  exit "$rc"
fi
end=$(date +%s)
echo "guard_gate: clean in $((end - start))s (budget ${BUDGET}s)"

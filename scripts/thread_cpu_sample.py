"""Sample per-thread CPU over a window and attribute it by thread name.

Usage: python scripts/thread_cpu_sample.py <seconds> [pattern]

Walks /proc/<pid>/task/<tid>/stat for every process whose cmdline matches
`pattern` (default: "./node run" benchmark processes), takes two snapshots
<seconds> apart, and prints CPU-seconds consumed per thread comm — the
attribution that tells a 100-validator single-host run where its one vCPU
actually went (threads are named at spawn via set_thread_name, see
native/src/common/log.cpp).
"""

import os
import sys
import time
from collections import defaultdict


def match_pids(pattern: str):
    pids = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode()
        except OSError:
            continue
        if pattern in cmd:
            pids.append(int(pid))
    return pids


def snapshot(pids):
    """comm -> cumulative (utime+stime) jiffies over all matching threads."""
    acc = defaultdict(int)
    nthreads = 0
    for pid in pids:
        try:
            tids = os.listdir(f"/proc/{pid}/task")
        except OSError:
            continue
        for tid in tids:
            try:
                with open(f"/proc/{pid}/task/{tid}/stat") as f:
                    raw = f.read()
            except OSError:
                continue
            # comm is parenthesised and may contain spaces; split around it.
            lp, rp = raw.find("("), raw.rfind(")")
            comm = raw[lp + 1:rp]
            fields = raw[rp + 2:].split()
            utime, stime = int(fields[11]), int(fields[12])
            acc[comm] += utime + stime
            nthreads += 1
    return acc, nthreads


def main():
    seconds = float(sys.argv[1]) if len(sys.argv) > 1 else 20.0
    # Default avoids a literal "./node" in OUR argv: the harness sweeps
    # stale benchmark processes with `pkill -f "\./node run"`, and a
    # pattern argument containing that string makes the sampler (or the
    # shell that launched it) collateral damage of the sweep.
    pattern = sys.argv[2] if len(sys.argv) > 2 else "node run --keys"
    hz = os.sysconf("SC_CLK_TCK")

    # Launch this BEFORE the benchmark window: on a saturated 1-vCPU host
    # a fresh Python interpreter can take minutes just to start, so the
    # sampler must already be resident, polling for its targets.  Raise
    # priority so the sampling itself isn't starved by the processes it
    # measures.
    try:
        os.nice(-10)
    except OSError:
        pass
    deadline = time.monotonic() + 900
    while True:
        pids = match_pids(pattern)
        if pids:
            break
        if time.monotonic() > deadline:
            print(f"no processes match {pattern!r}", file=sys.stderr)
            sys.exit(1)
        time.sleep(2)
    # Let the run reach steady state before the measured window.
    time.sleep(20)
    before, nt0 = snapshot(pids)
    t0 = time.monotonic()
    time.sleep(seconds)
    after, nt1 = snapshot(match_pids(pattern))
    dt = time.monotonic() - t0

    deltas = {c: (after.get(c, 0) - before.get(c, 0)) / hz
              for c in set(after) | set(before)}
    total = sum(deltas.values())
    print(f"# {len(pids)} procs, {nt1} threads, window {dt:.1f}s, "
          f"total CPU {total:.2f}s ({100 * total / dt:.0f}% of one core)")
    for comm, cpu in sorted(deltas.items(), key=lambda kv: -kv[1]):
        if cpu <= 0:
            continue
        print(f"{comm:18s} {cpu:8.2f}s  {100 * cpu / max(total, 1e-9):5.1f}%")


if __name__ == "__main__":
    main()

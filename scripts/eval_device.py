"""Reliable device-cost eval for verify_packed: slope between G=2 and G=10
chunked-scan calls (cancels fixed tunnel overhead), min over trials
(cancels latency spikes).  Prints one number: device ms per 1024-batch.

--trace DIR additionally captures a jax.profiler trace of one chunked
dispatch (SURVEY §5.1: device-side profiling for the verify kernel) for
TensorBoard / xprof inspection.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
from hotstuff_tpu.ops import ed25519 as E

N = 1024


def make_big(packed_np, G):
    return jnp.asarray(np.broadcast_to(packed_np, (G, N, 128)).copy())


def measure(packed_np, G, trials=5, reps=3):
    verify_chunked = E.verify_packed_chunked_jit  # the shipped program

    big = make_big(packed_np, G)
    out = verify_chunked(big)
    assert np.asarray(out).all()
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = verify_chunked(big)
        np.asarray(out)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", metavar="DIR",
                    help="also write a jax.profiler trace of one chunked "
                         "dispatch to DIR")
    args = ap.parse_args()

    rng = np.random.default_rng(7)
    msgs, pks, sigs = [], [], []
    for _ in range(N):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        m = rng.bytes(64)
        msgs.append(m)
        pks.append(pk)
        sigs.append(ref.sign(sk, m))
    prep = eddsa.prepare_batch(msgs, pks, sigs)
    packed_np = prep["packed"]

    t2 = measure(packed_np, 2)
    t10 = measure(packed_np, 10)
    slope = (t10 - t2) / 8
    print(f"G2 {t2*1e3:.2f} ms, G10 {t10*1e3:.2f} ms")
    print(f"DEVICE {slope*1e3:.2f} ms/1024  ({N/slope:,.0f} sigs/s ceiling)")

    if args.trace:
        # Trace the G=10 shape measure() already compiled, so the capture
        # holds ONE warm device dispatch — not a cold XLA compile.
        big = make_big(packed_np, 10)
        with jax.profiler.trace(args.trace):
            np.asarray(E.verify_packed_chunked_jit(big))
        print(f"profiler trace written to {args.trace}")


if __name__ == "__main__":
    main()

"""Where does verify_packed's device time go?  Repeat each stage R times
inside one program (chained so XLA can't dedupe) and fit slope between two R
values — tunnel-noise-immune device cost per stage at batch 1024.

Stages: decompress(A), ladder (64x4dbl+add vs table), comb (32 adds + gather),
final combine+eq.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
from hotstuff_tpu.ops import ed25519 as E
from hotstuff_tpu.ops import field25519 as F


def timeit(fn, reps=8):
    np.asarray(fn())
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    np.asarray(out)
    return (time.perf_counter() - t0) / reps


def slope(make, lo=1, hi=5):
    f_lo, f_hi = make(lo), make(hi)
    t_lo = timeit(lambda: f_lo())
    t_hi = timeit(lambda: f_hi())
    return (t_hi - t_lo) / (hi - lo), t_lo, t_hi


def main():
    N = 1024
    rng = np.random.default_rng(7)
    msgs, pks, sigs = [], [], []
    for _ in range(N):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        m = rng.bytes(64)
        msgs.append(m)
        pks.append(pk)
        sigs.append(ref.sign(sk, m))
    prep = eddsa.prepare_batch(msgs, pks, sigs)
    packed = jnp.asarray(prep["packed"])
    ay, a_sign = E.split_y_sign(packed[:, 0:32].astype(jnp.int32))
    s_digits = packed[:, 64:96].astype(jnp.int32)
    k_digits = E.unpack_nibbles_msb(packed[:, 96:128])
    ay = jnp.asarray(ay)
    a_pt, _ = jax.jit(E.decompress)(ay, a_sign)
    a_pt = jnp.asarray(np.asarray(a_pt))

    # --- stage: decompress, chained via feeding x back as y ---------------
    def mk_dec(R):
        @jax.jit
        def f(y, s):
            def body(y, _):
                pt, _ok = E.decompress(y, s)
                # feed the X row back (depends on the full pow chain); the
                # Y row is the input verbatim and would let XLA DCE the
                # whole stage
                return pt[..., 0, :] & 0xFF, None
            out, _ = jax.lax.scan(body, y, None, length=R)
            return out
        return lambda: f(ay, a_sign)
    s_, lo, hi = slope(mk_dec)
    print(f"decompress      : {s_*1e3:8.3f} ms/stage (R1 {lo*1e3:.2f}, R5 {hi*1e3:.2f})")

    # --- stage: ladder ----------------------------------------------------
    def mk_ladder(R):
        @jax.jit
        def f(pt, kd):
            def body(p0, _):
                ax, ay_l, az, at = p0[..., 0, :], p0[..., 1, :], p0[..., 2, :], p0[..., 3, :]
                neg_a_ext = jnp.stack([F.neg(ax), ay_l, az, F.neg(at)], axis=-2)
                neg_a_cached = E.to_cached(neg_a_ext)
                entries = [E.identity_ext((N,)), neg_a_ext]
                for _ in range(2, 16):
                    entries.append(E.point_add(entries[-1], neg_a_cached))
                table = jnp.stack([E.to_cached(e) for e in entries], axis=-3)

                def ladder_body(p, digit_row):
                    p = E.point_dbl(p, with_t=False)
                    p = E.point_dbl(p, with_t=False)
                    p = E.point_dbl(p, with_t=False)
                    p = E.point_dbl(p)
                    p = E.point_add(p, E._digit_select(table, digit_row))
                    return p, None

                ka, _ = jax.lax.scan(ladder_body, E.identity_ext((N,)),
                                     jnp.moveaxis(kd, -1, 0))
                return ka, None
            out, _ = jax.lax.scan(body, pt, None, length=R)
            return out
        return lambda: f(a_pt, k_digits)
    s_, lo, hi = slope(mk_ladder, 1, 3)
    print(f"ladder+table    : {s_*1e3:8.3f} ms/stage (R1 {lo*1e3:.2f}, R3 {hi*1e3:.2f})")

    # --- stage: comb ------------------------------------------------------
    def mk_comb(R):
        comb = jnp.asarray(E.comb_table())
        @jax.jit
        def f(sd):
            def body(acc0, _):
                def comb_body(acc, xs):
                    comb_j, digit_row = xs
                    entry = jnp.take(comb_j, digit_row, axis=0)
                    return E.point_add(acc, entry), None
                sb, _ = jax.lax.scan(comb_body, acc0,
                                     (comb, jnp.moveaxis(sd, -1, 0)))
                return sb, None
            out, _ = jax.lax.scan(body, E.identity_ext((N,)), None, length=R)
            return out
        return lambda: f(s_digits)
    s_, lo, hi = slope(mk_comb)
    print(f"comb (32 gthr+add): {s_*1e3:7.3f} ms/stage (R1 {lo*1e3:.2f}, R5 {hi*1e3:.2f})")


if __name__ == "__main__":
    main()

"""graftfleet tests: per-tenant DRR fairness, tenant admission caps,
the protocol-v6 HELLO identity, cross-tenant verdict dedup, indexed
``sidecar:<i>`` chaos targets + the ``sidecar-failover`` SLO class,
LogParser failover/starvation/dedup mining with the strict-mode
invariants, and two slow drills: the 2-sidecar kill-primary failover
e2e (re-home to the survivor, zero host-path verifies while it lives,
masks bit-identical) and the seeded greedy-tenant flood (starvation
counter stays 0, victim queue-wait p99 within the strict bound).
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
from hotstuff_tpu.sidecar import protocol as proto
from hotstuff_tpu.sidecar.client import SidecarClient, SidecarOverloaded
from hotstuff_tpu.sidecar.sched.classes import BULK, LATENCY, ClassQueue, \
    Pending
from hotstuff_tpu.sidecar.sched.tenantq import TenantLanes
from hotstuff_tpu.sidecar.service import SidecarServer, VerifyEngine

from test_harness import GOLDEN_CLIENT, GOLDEN_NODE


def _sigs(n, tamper=(), seed=7):
    rng = np.random.default_rng(seed)
    msgs, pks, sigs = [], [], []
    for i in range(n):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        sig = ref.sign(sk, msg)
        if i in tamper:
            sig = sig[:1] + bytes([sig[1] ^ 0xFF]) + sig[2:]
        msgs.append(msg)
        pks.append(pk)
        sigs.append(sig)
    return msgs, pks, sigs


def _pending(tenant, n=4, cls=LATENCY):
    req = SimpleNamespace(msgs=[b"m"] * n, pks=[b"p"] * n, sigs=[b"s"] * n)
    return Pending(req, lambda *_: None, cls=cls, tenant=tenant)


# ---------------------------------------------------------------------------
# tenant lanes: DRR drain order + the fairness mechanics
# ---------------------------------------------------------------------------

def test_single_tenant_lane_is_the_old_fifo():
    lanes = TenantLanes(quantum_sigs=8)
    items = [_pending("default", n=3) for _ in range(5)]
    for p in items:
        lanes._offer_locked(p)
    drained = [lanes.pop_next_locked() for _ in range(5)]
    assert drained == items  # arrival order, byte-for-byte
    assert lanes.head_locked() is None
    assert not lanes


def test_drr_interleaves_a_deep_backlog_with_other_tenants():
    # greedy queues 10x the victim's records; the quantum forces the
    # ring to rotate, so the victim is served every round instead of
    # waiting out the whole greedy backlog.
    lanes = TenantLanes(quantum_sigs=8)
    greedy = [_pending("greedy", n=4) for _ in range(20)]
    victim = [_pending("victim", n=4) for _ in range(2)]
    for p in greedy[:10]:
        lanes._offer_locked(p)
    for p in victim:
        lanes._offer_locked(p)
    for p in greedy[10:]:
        lanes._offer_locked(p)
    order = []
    while lanes:
        order.append(lanes.pop_next_locked().tenant)
    # The victim's two requests both drain within the first two DRR
    # rounds (quantum 8 = two 4-sig greedy pops per round), not after
    # the 20-deep greedy backlog.
    assert order.index("victim") < 4
    assert [t for t in order if t == "victim"] == ["victim", "victim"]
    assert order.count("greedy") == 20
    first_victim_done = len(order) - 1 - order[::-1].index("victim")
    assert first_victim_done < 8, order


def test_drr_preserves_arrival_order_within_a_tenant():
    lanes = TenantLanes(quantum_sigs=4)
    a = [_pending("a", n=2) for _ in range(6)]
    b = [_pending("b", n=2) for _ in range(6)]
    for pa, pb in zip(a, b):
        lanes._offer_locked(pa)
        lanes._offer_locked(pb)
    drained = {"a": [], "b": []}
    while lanes:
        p = lanes.pop_next_locked()
        drained[p.tenant].append(p)
    assert drained["a"] == a
    assert drained["b"] == b


def test_any_over_cap_is_unreachable_through_admission():
    import threading

    lock = threading.Condition()
    q = ClassQueue(cap_sigs=64, lock=lock, tenant_cap_sigs=16,
                   quantum_sigs=8)
    # Two tenants: the flooding tenant sheds on ITS cap while the other
    # keeps admitting — and no lane ever exceeds the tenant share.
    assert q.offer(_pending("victim", n=4))
    admitted = 0
    for _ in range(10):
        if q.offer(_pending("greedy", n=4)):
            admitted += 1
    assert admitted == 4  # 16-sig share / 4-sig requests
    assert q.last_refusal == "tenant-cap"
    assert q.offer(_pending("victim", n=4))  # victim unaffected
    with lock:
        assert not q.lanes.any_over_cap_locked(16)
        assert q.lanes.occupancy_locked() == {"victim": 8, "greedy": 16}


def test_single_tenant_keeps_the_class_cap_policy():
    import threading

    lock = threading.Condition()
    q = ClassQueue(cap_sigs=16, lock=lock, tenant_cap_sigs=8)
    # One tenant (the pre-fleet topology): the tenant share never
    # engages, so admission is governed by the class cap alone.
    assert q.offer(_pending("default", n=8))
    assert q.offer(_pending("default", n=8))
    assert not q.offer(_pending("default", n=8))
    assert q.last_refusal == "class-cap"


# ---------------------------------------------------------------------------
# protocol v6 HELLO + tenant identity
# ---------------------------------------------------------------------------

def test_hello_roundtrip_and_tenant_validation():
    wire = proto.encode_hello_request(3, "node-7")
    opcode, req = proto.decode_request(wire[4:])
    assert opcode == proto.OP_HELLO
    assert req.tenant == "node-7"
    assert req.version == proto.PROTOCOL_VERSION
    reply = proto.encode_hello_reply(3, "node-7")
    # reply frame: len prefix + reply header + [server version][tenant]
    version, tenant = proto.decode_hello_body(
        bytes(reply)[4 + proto._REPLY_HDR.size:])
    assert version == proto.PROTOCOL_VERSION and tenant == "node-7"
    for bad in ("", "x" * (proto.TENANT_MAX_LEN + 1), "bad tenant",
                "no/slash", "nul\x00"):
        with pytest.raises(ValueError):
            proto.validate_tenant(bad)


@pytest.fixture(scope="module")
def fleet_server():
    engine = VerifyEngine(use_host=True)
    srv = SidecarServer(("127.0.0.1", 0), engine)
    t = threading.Thread(target=srv.serve_forever,
                         kwargs=dict(poll_interval=0.1), daemon=True)
    t.start()
    yield srv, engine
    srv.shutdown()
    engine.stop()
    srv.server_close()


def test_hello_tags_scheduling_tenant_in_stats(fleet_server):
    srv, engine = fleet_server
    port = srv.server_address[1]
    with SidecarClient(port=port, timeout=10.0) as client:
        assert client.hello("stats-tenant") == "stats-tenant"
        msgs, pks, sigs = _sigs(4, tamper={1}, seed=41)
        assert client.verify_batch(msgs, pks, sigs) == \
            [True, False, True, True]
    snap = engine.stats_snapshot()
    rec = snap["tenants"]["stats-tenant"]
    assert rec["admitted"].get(LATENCY, 0) >= 1
    assert snap["surge"].get("tenant_starvation", 0) == 0


def test_cross_tenant_dedup_shares_verdicts(fleet_server):
    srv, engine = fleet_server
    port = srv.server_address[1]
    # The SAME records verified by two tenants: the second tenant's
    # request answers from the shared verdict cache — the QC gossiped
    # to N replicas is device-verified once fleet-wide.
    msgs, pks, sigs = _sigs(6, tamper={3}, seed=57)
    expect = [True, True, True, False, True, True]
    for tenant in ("replica-0", "replica-1"):
        with SidecarClient(port=port, timeout=10.0) as client:
            assert client.hello(tenant) == tenant
            assert client.verify_batch(msgs, pks, sigs) == expect
    snap = engine.stats_snapshot()
    dedup = snap["dedup"]
    assert dedup["cache_hits"] >= 6
    assert dedup["hit_rate"] > 0

    # ... and the parser surfaces the hit rate as a note.
    from hotstuff_tpu.harness import LogParser

    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    parser.note_sidecar_stats(json.loads(json.dumps(snap)))
    note = next(n for n in parser.notes if n.startswith("Sidecar dedup:"))
    assert "hit rate" in note
    assert parser.sidecar_dedup["cache_hits"] >= 6


# ---------------------------------------------------------------------------
# chaos plan: indexed sidecar targets + the sidecar-failover SLO class
# ---------------------------------------------------------------------------

def test_plan_parses_indexed_sidecar_targets():
    from hotstuff_tpu.chaos.plan import parse_plan, sidecar_index

    plan = parse_plan("5 sidecar:0 kill; 10 sidecar:1 wedge")
    assert plan.sidecar_indices() == {0, 1}
    assert sidecar_index("sidecar:3") == 3
    assert sidecar_index("sidecar") is None
    assert sidecar_index("node:1") is None


def test_indexed_kill_classifies_as_sidecar_failover():
    from hotstuff_tpu.chaos.slo import DEFAULT_SLO_MS, fault_class

    assert fault_class({"target": "sidecar:0", "action": "kill"}) == \
        "sidecar-failover"
    # Bare-target kills and non-kill indexed actions keep their classes:
    # only the fleet-member kill is judged on the re-home budget.
    assert fault_class({"target": "sidecar", "action": "kill"}) == \
        "sidecar-kill"
    assert fault_class({"target": "sidecar:1", "action": "wedge"}) == \
        "sidecar-wedge"
    assert DEFAULT_SLO_MS["sidecar-failover"] <= \
        DEFAULT_SLO_MS["sidecar-kill"]


def test_local_bench_validates_fleet_plan_targets():
    from hotstuff_tpu.harness.config import BenchParameters
    from hotstuff_tpu.harness.local import LocalBench
    from hotstuff_tpu.harness.utils import BenchError

    params = {"faults": 0, "nodes": 4, "rate": 1000, "tx_size": 512,
              "duration": 60, "sidecar_host_crypto": True,
              "sidecar_fleet": 2, "fault_plan": "5 sidecar:1 kill"}
    LocalBench(BenchParameters(params))._check_fault_plan()

    params["fault_plan"] = "5 sidecar:2 kill"  # index beyond the fleet
    with pytest.raises(BenchError) as exc:
        LocalBench(BenchParameters(params))._check_fault_plan()
    assert "sidecar_fleet" in str(exc.value)

    params["fault_plan"] = "5 sidecar:0 kill"
    params["sidecar_fleet"] = 0
    params["sidecar_host_crypto"] = False
    with pytest.raises(BenchError):  # no sidecar booted at all
        LocalBench(BenchParameters(params))._check_fault_plan()


def test_wan_links_reject_multi_sidecar_fleet():
    from hotstuff_tpu.harness.config import BenchParameters
    from hotstuff_tpu.harness.local import LocalBench
    from hotstuff_tpu.harness.utils import BenchError

    params = {"faults": 0, "nodes": 4, "rate": 1000, "tx_size": 512,
              "duration": 60, "sidecar_host_crypto": True,
              "sidecar_fleet": 2,
              "wan": "node:0>sidecar latency_ms=10"}
    with pytest.raises(BenchError) as exc:
        LocalBench(BenchParameters(params))
    assert "single-sidecar" in str(exc.value)


# ---------------------------------------------------------------------------
# LogParser: failover evidence mining + strict invariants
# ---------------------------------------------------------------------------

FAILOVER_NODE_LOG = GOLDEN_NODE + """\
[2026-07-29T14:54:56.700Z INFO crypto::sidecar] HELLO accepted by endpoint 0: tenant node (protocol v6)
[2026-07-29T14:54:56.910Z WARN crypto::sidecar] sidecar failover: endpoint 0 failed in flight, resubmitting to endpoint 1
[2026-07-29T14:54:56.920Z WARN crypto::sidecar] sidecar failover: endpoint 0 unhealthy, re-homed to endpoint 1 (127.0.0.1:7101)
[2026-07-29T14:54:56.921Z INFO crypto::sidecar] HELLO accepted by endpoint 1: tenant node (protocol v6)
"""


def test_parser_mines_failover_evidence():
    from hotstuff_tpu.harness import LogParser

    parser = LogParser([GOLDEN_CLIENT], [FAILOVER_NODE_LOG], faults=0)
    assert parser.failover == {
        "rehomes": 1, "resubmits": 1, "hello_accepts": 2,
        "endpoints": [0, 1], "tenants": ["node"]}
    note = next(n for n in parser.notes
                if n.startswith("Sidecar fleet:"))
    assert "re-home" in note


def test_strict_fleet_kill_without_rehome_raises():
    from hotstuff_tpu.harness import LogParser
    from hotstuff_tpu.harness.logs import ParseError

    events = [{"t": 5.0, "target": "sidecar:0", "action": "kill",
               "ok": True,
               "wall": LogParser._to_posix("2026-07-29T14:54:56.900Z")}]
    # No failover lines in the node logs: the strict drill must fail.
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    with pytest.raises(ParseError) as exc:
        parser.note_chaos_events(json.loads(json.dumps(events)),
                                 strict=True)
    assert "re-home" in str(exc.value)

    # With the evidence present, the same events pass and the failover
    # SLO class is judged.
    parser = LogParser([GOLDEN_CLIENT], [FAILOVER_NODE_LOG], faults=0)
    parser.note_chaos_events(json.loads(json.dumps(events)), strict=True)
    slo_note = next(n for n in parser.notes
                    if n.startswith("Chaos SLO sidecar-failover:"))
    assert slo_note.endswith("PASS")


def test_strict_tenant_starvation_raises():
    from hotstuff_tpu.harness import LogParser
    from hotstuff_tpu.harness.logs import ParseError

    stats = {"launches": 3,
             "surge": {"shed": {}, "admitted": {"latency": 3},
                       "tenant_starvation": 2}}
    # Strictness rides on the parser's chaos mode (strict_chaos=True):
    # a scripted run must hold the invariant, a plain bench only notes.
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0,
                       strict_chaos=True)
    with pytest.raises(ParseError) as exc:
        parser.note_sidecar_stats(stats)
    assert "tenant fairness violated" in str(exc.value)
    # Non-strict: the same stats surface as a note instead.
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    parser.note_sidecar_stats(stats)
    assert any("starvation" in n for n in parser.notes)


def test_parser_prefixes_per_endpoint_stats_notes():
    from hotstuff_tpu.harness import LogParser

    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    parser.note_sidecar_stats({
        "launches": 2, "sigs_launched": 64, "pad_sigs": 0,
        "_endpoint": "127.0.0.1:7101"})
    assert any(n.startswith("[127.0.0.1:7101] ") for n in parser.notes)


def test_tenant_flood_verdict_shapes():
    from hotstuff_tpu.harness import LogParser
    from hotstuff_tpu.harness.logs import ParseError

    def snap(p99, n=10, starvation=0):
        return {"tenants": {"victim": {"queue_wait": {
                    "latency": {"n": n, "p50_ms": p99 / 2,
                                "p99_ms": p99}}}},
                "surge": {"tenant_starvation": starvation}}

    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    parser.note_tenant_flood(snap(1.0), snap(1.5), "victim", strict=True)
    assert parser.tenant_flood["ok"] and parser.tenant_flood["judged"]

    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    with pytest.raises(ParseError) as exc:
        parser.note_tenant_flood(snap(1.0), snap(2.5), "victim",
                                 strict=True)
    assert "isolation violated" in str(exc.value)

    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    with pytest.raises(ParseError) as exc:
        parser.note_tenant_flood(snap(1.0), snap(1.1, starvation=1),
                                 "victim", strict=True)
    assert "starvation" in str(exc.value)


# ---------------------------------------------------------------------------
# slow drill 1: 2-sidecar kill-primary failover e2e
# ---------------------------------------------------------------------------

def _wait_port(port, deadline_s, proc=None):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        if proc is not None and proc.poll() is not None:
            raise AssertionError(
                f"sidecar on port {port} died at boot "
                f"(rc={proc.returncode})")
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=1.0):
                return
        except OSError:
            time.sleep(0.25)
    raise AssertionError(f"sidecar on port {port} never came up")


class _FleetClient:
    """Python mirror of the C++ endpoint ladder (sticky-until-unhealthy
    + ordered failover), emitting the SAME log lines the parser mines —
    driven here by the real kill, so the mined evidence records a real
    re-home.  Host fallback is the LAST rung and the drill asserts it
    never fires while the secondary lives."""

    def __init__(self, ports, tenant="node"):
        self.ports = ports
        self.tenant = tenant
        self.active = 0
        self.host_fallbacks = 0
        self.log_lines = []
        self._clients = {}

    def _client(self, ix):
        c = self._clients.get(ix)
        if c is None:
            c = SidecarClient(port=self.ports[ix], timeout=10.0)
            self._clients[ix] = c
            c.hello(self.tenant)
            self.log_lines.append(
                f"[2026-07-29T14:54:56.700Z INFO crypto::sidecar] HELLO "
                f"accepted by endpoint {ix}: tenant {self.tenant} "
                f"(protocol v{c.server_version})")
        return c

    def verify(self, msgs, pks, sigs):
        order = [self.active] + [i for i in range(len(self.ports))
                                 if i != self.active]
        for ix in order:
            try:
                mask = self._client(ix).verify_batch(msgs, pks, sigs)
            except (OSError, ConnectionError, socket.timeout):
                self._clients.pop(ix, None)
                continue
            if ix != self.active:
                self.log_lines.append(
                    f"[2026-07-29T14:54:56.920Z WARN crypto::sidecar] "
                    f"sidecar failover: endpoint {self.active} "
                    f"unhealthy, re-homed to endpoint {ix} "
                    f"(127.0.0.1:{self.ports[ix]})")
                self.active = ix
            return mask
        self.host_fallbacks += 1
        return [bool(b) for b in eddsa.verify_batch(msgs, pks, sigs)]

    def close(self):
        for c in self._clients.values():
            c.close()


@pytest.mark.slow
def test_fleet_failover_e2e(tmp_path):
    """Acceptance: a 2-sidecar fleet with ``sidecar:0 kill`` injected
    mid-traffic re-homes every verify to sidecar 1 (zero host-path
    verifies while it is alive), keeps masks bit-identical across the
    failover, and passes the ``sidecar-failover`` SLO under the strict
    parser (which also demands the mined re-home evidence)."""
    from hotstuff_tpu.chaos import PlanRunner, parse_plan
    from hotstuff_tpu.harness import LogParser
    from hotstuff_tpu.harness.faults import LocalFaultInjector

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Two real sidecar processes (host crypto: the drill tests the
    # transport ladder, not the device) on consecutive ports.
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        base = probe.getsockname()[1]
    ports = [base, base + 1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = {}
    logs = {}
    try:
        for i, port in enumerate(ports):
            logs[i] = open(tmp_path / f"sidecar-{i}.log", "wb")
            procs[i] = subprocess.Popen(
                [sys.executable, "-m", "hotstuff_tpu.sidecar",
                 "--host-crypto", "--port", str(port)],
                cwd=repo, env=env, stdout=logs[i], stderr=logs[i],
                start_new_session=True)
        for i, port in enumerate(ports):
            _wait_port(port, deadline_s=180, proc=procs[i])

        fc = _FleetClient(ports)
        masks, expects, errors = [], [], []
        stop = threading.Event()
        killed = threading.Event()
        post_kill_verifies = []

        def traffic():
            i = 0
            try:
                while not stop.is_set() and i < 2000:
                    m, p, s = _sigs(4, tamper={i % 4}, seed=3000 + i)
                    expect = [bool(b) for b in eddsa.verify_batch(m, p, s)]
                    mask = fc.verify(m, p, s)
                    masks.append(mask)
                    expects.append(expect)
                    if killed.is_set():
                        post_kill_verifies.append(mask)
                    i += 1
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()

        # The injector sees the same bench surface LocalBench exposes.
        bench = SimpleNamespace(SIDECAR_PORT=base,
                                _sidecar_procs=dict(procs),
                                _sidecar_cmds={}, _sidecar_proc=procs[0])
        plan = parse_plan("0.5 sidecar:0 kill")
        base_wall = LogParser._to_posix("2026-07-29T14:54:56.900Z")
        runner = PlanRunner(plan, LocalFaultInjector(bench),
                            wall=lambda: base_wall)
        runner.start()
        runner.join(timeout=30.0)
        killed.set()

        # Let traffic run across the failover, then wind down.
        deadline = time.monotonic() + 30.0
        while len(post_kill_verifies) < 20 and \
                time.monotonic() < deadline and t.is_alive():
            time.sleep(0.1)
        stop.set()
        t.join(timeout=60.0)

        assert not errors, errors
        assert len(post_kill_verifies) >= 20, \
            "traffic never resumed after the kill"
        assert masks == expects, \
            "a verify answered with a non-bit-identical mask"
        # Zero host-path verifies while the healthy secondary exists.
        assert fc.host_fallbacks == 0
        assert fc.active == 1

        # Survivor's OP_STATS: the strict parser folds them per-endpoint.
        with SidecarClient(port=ports[1], timeout=10.0) as c:
            survivor_stats = c.stats()
        fc.close()

        events = json.loads(json.dumps(runner.events()))
        assert events and events[0]["ok"], events

        node_log = GOLDEN_NODE + "".join(
            line + "\n" for line in fc.log_lines)
        parser = LogParser([GOLDEN_CLIENT], [node_log], faults=0,
                           strict_chaos=True)
        assert parser.failover and parser.failover["rehomes"] >= 1
        parser.note_sidecar_stats(
            dict(survivor_stats, _endpoint=f"127.0.0.1:{ports[1]}"))
        parser.note_chaos_events(events, strict=True)
        slo_note = next(n for n in parser.notes
                        if n.startswith("Chaos SLO sidecar-failover:"))
        assert slo_note.endswith("PASS")
    finally:
        import signal

        for proc in procs.values():
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            proc.wait(timeout=10)
        for fh in logs.values():
            fh.close()


# ---------------------------------------------------------------------------
# slow drill 2: seeded greedy-tenant flood through the real scheduler
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_greedy_tenant_flood_isolation():
    """Acceptance: a seeded greedy-tenant flood against a live engine
    leaves ``tenant_starvation == 0`` and the victim tenant's
    latency-class queue-wait p99 within the strict 2x bound — the
    strict-mode verdict raises ParseError otherwise."""
    from hotstuff_tpu.harness import LogParser

    engine = VerifyEngine(use_host=True)
    srv = SidecarServer(("127.0.0.1", 0), engine)
    st = threading.Thread(target=srv.serve_forever,
                          kwargs=dict(poll_interval=0.1), daemon=True)
    st.start()
    port = srv.server_address[1]
    errors = []

    def victim(stop, period_s=0.01):
        try:
            with SidecarClient(port=port, timeout=30.0) as c:
                c.hello("victim")
                i = 0
                while not stop.is_set():
                    m, p, s = _sigs(4, seed=9000 + i)
                    mask = c.verify_batch(m, p, s)
                    assert mask == [True] * 4
                    i += 1
                    time.sleep(period_s)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    def greedy(stop, seed, batch=64):
        try:
            with SidecarClient(port=port, timeout=30.0) as c:
                c.hello("greedy")
                i = 0
                while not stop.is_set():
                    m, p, s = _sigs(batch, seed=seed * 10000 + i)
                    try:
                        c.verify_batch(m, p, s)
                    except SidecarOverloaded:
                        time.sleep(0.002)  # shed on the tenant cap: retry
                    i += 1
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    try:
        # Pre-flood phase: victim + ONE moderate greedy worker, enough
        # traffic that the victim's queue-wait reservoir has samples.
        stop_pre = threading.Event()
        pre_threads = [threading.Thread(target=victim, args=(stop_pre,),
                                        daemon=True),
                       threading.Thread(target=greedy,
                                        args=(stop_pre, 1), daemon=True)]
        for t in pre_threads:
            t.start()
        time.sleep(2.0)
        stop_pre.set()
        for t in pre_threads:
            t.join(timeout=30.0)
        assert not errors, errors
        pre = json.loads(json.dumps(engine.stats_snapshot()))
        assert pre["tenants"]["victim"]["queue_wait"]["latency"]["n"] > 0

        # Flood phase: the greedy tenant multiplies its load 4x while
        # the victim keeps its cadence.
        stop_flood = threading.Event()
        flood_threads = [threading.Thread(target=victim,
                                          args=(stop_flood,),
                                          daemon=True)]
        flood_threads += [
            threading.Thread(target=greedy, args=(stop_flood, k, 128),
                             daemon=True)
            for k in range(2, 6)]
        for t in flood_threads:
            t.start()
        time.sleep(3.0)
        stop_flood.set()
        for t in flood_threads:
            t.join(timeout=30.0)
        assert not errors, errors
        post = json.loads(json.dumps(engine.stats_snapshot()))

        assert post["surge"].get("tenant_starvation", 0) == 0
        # The strict verdict: starvation == 0 AND victim p99 within 2x.
        parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
        parser.note_tenant_flood(pre, post, "victim", strict=True)
        assert parser.tenant_flood["ok"], parser.tenant_flood
        note = next(n for n in parser.notes
                    if n.startswith("Tenant flood:"))
        assert "isolated" in note
    finally:
        srv.shutdown()
        engine.stop()
        srv.server_close()

"""Property tests for GF(2^255-19) limb arithmetic against python-int ground
truth, including adversarial all-max-limb values.

Mirrors the role of the reference's crypto unit tests
(crypto/src/tests/crypto_tests.rs) at the field-arithmetic layer the TPU
build introduces.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hotstuff_tpu.ops import field25519 as F

P = F.P
rng = np.random.default_rng(1234)


def rand_ints(n):
    return [int.from_bytes(rng.bytes(32), "little") % P for _ in range(n)]


def weak_rand_limbs(n):
    """Adversarial weak-form inputs: limbs anywhere in [0, 512)."""
    return np.asarray(rng.integers(0, 512, size=(n, F.NLIMBS)), dtype=np.int32)


def limb_value(arr):
    return [v % P for v in F.batch_from_limbs(arr)]


def test_limb_roundtrip():
    xs = rand_ints(16)
    limbs = F.batch_to_limbs(xs)
    assert F.batch_from_limbs(limbs) == xs


@pytest.mark.parametrize("op,pyop", [
    (F.add, lambda a, b: (a + b) % P),
    (F.sub, lambda a, b: (a - b) % P),
    (F.mul, lambda a, b: (a * b) % P),
])
def test_binary_ops_random(op, pyop):
    a, b = rand_ints(64), rand_ints(64)
    got = limb_value(np.asarray(op(jnp.asarray(F.batch_to_limbs(a)),
                                   jnp.asarray(F.batch_to_limbs(b)))))
    assert got == [pyop(x, y) for x, y in zip(a, b)]


@pytest.mark.parametrize("op,pyop", [
    (F.add, lambda a, b: (a + b) % P),
    (F.sub, lambda a, b: (a - b) % P),
    (F.mul, lambda a, b: (a * b) % P),
])
def test_binary_ops_weak_adversarial(op, pyop):
    """Ops must be correct AND restore the weak invariant for any weak input."""
    a, b = weak_rand_limbs(64), weak_rand_limbs(64)
    # include the all-max corner
    a[0, :] = 511
    b[0, :] = 511
    av, bv = limb_value(a), limb_value(b)
    out = np.asarray(op(jnp.asarray(a), jnp.asarray(b)))
    assert out.min() >= 0 and out.max() < 512, "weak invariant violated"
    assert limb_value(out) == [pyop(x, y) for x, y in zip(av, bv)]


def test_mul_chain_stays_correct():
    """Long chains of muls/adds/subs (like a scalar ladder) stay exact."""
    a, b = rand_ints(8), rand_ints(8)
    la, lb = jnp.asarray(F.batch_to_limbs(a)), jnp.asarray(F.batch_to_limbs(b))
    pa, pb = list(a), list(b)
    for i in range(50):
        la, lb = F.mul(la, lb), F.add(F.sub(la, lb), la)
        pa, pb = [x * y % P for x, y in zip(pa, pb)], \
                 [((x - y) + x) % P for x, y in zip(pa, pb)]
    assert limb_value(np.asarray(la)) == pa
    assert limb_value(np.asarray(lb)) == pb


def test_canonical_and_eq():
    xs = rand_ints(32)
    limbs = jnp.asarray(F.batch_to_limbs(xs))
    # x + p and x must compare equal; x and x+1 must not.
    xp = jnp.asarray(F.batch_to_limbs([x + P for x in xs]))
    one = jnp.broadcast_to(F.constant(1), limbs.shape)
    assert bool(jnp.all(F.eq(limbs, xp)))
    assert not bool(jnp.any(F.eq(limbs, F.add(limbs, one))))
    canon = np.asarray(F.canonical(xp))
    assert canon.max() < 256
    assert F.batch_from_limbs(canon) == xs


def test_canonical_edges():
    for v in [0, 1, 19, P - 1, P, P + 1, 2 * P - 1, 2 * P, 2**255 - 1, 2**256 - 1]:
        limbs = jnp.asarray(F.to_limbs(v))[None, :]
        got = F.batch_from_limbs(np.asarray(F.canonical(limbs)))[0]
        assert got == v % P, v


def test_parity_and_zero():
    xs = [0, 1, 2, P - 1, P, 12345]
    limbs = jnp.asarray(F.batch_to_limbs(xs))
    assert list(np.asarray(F.parity(limbs))) == [x % P % 2 for x in xs]
    assert list(np.asarray(F.is_zero(limbs))) == [x % P == 0 for x in xs]


def test_inv_and_pow():
    xs = rand_ints(8)
    limbs = jnp.asarray(F.batch_to_limbs(xs))
    got = limb_value(np.asarray(F.inv(limbs)))
    assert got == [pow(x, P - 2, P) for x in xs]
    got58 = limb_value(np.asarray(F.pow_p58(limbs)))
    assert got58 == [pow(x, (P - 5) // 8, P) for x in xs]


def test_ops_jit_and_vmap():
    a, b = rand_ints(16), rand_ints(16)
    la, lb = jnp.asarray(F.batch_to_limbs(a)), jnp.asarray(F.batch_to_limbs(b))
    jitted = jax.jit(lambda x, y: F.mul(x, y))
    assert limb_value(np.asarray(jitted(la, lb))) == [x * y % P for x, y in zip(a, b)]
    vmapped = jax.vmap(F.mul)
    assert limb_value(np.asarray(vmapped(la, lb))) == [x * y % P for x, y in zip(a, b)]

"""Off-chain suite tests: secp256k1 ECDSA cross-checked against OpenSSL,
Schnorr roundtrips, BLS12-381 pairing algebra + signatures + aggregation,
and the benchmark harness plumbing."""

import hashlib
import pytest


from hotstuff_tpu.offchain import bls12381 as bls
from hotstuff_tpu.offchain import ecdsa, eddsa, schnorr, secp256k1


# ---------------------------------------------------------------------------
# secp256k1
# ---------------------------------------------------------------------------

def test_secp256k1_point_arithmetic():
    g = (secp256k1.GX, secp256k1.GY)
    assert secp256k1.on_curve(g)
    assert secp256k1.point_mul(secp256k1.N) is None  # group order
    two_g = secp256k1.point_add(g, g)
    assert two_g == secp256k1.point_mul(2)
    assert secp256k1.on_curve(two_g)
    # encode/decode roundtrip, both parities
    for k in (2, 3, 12345):
        p = secp256k1.point_mul(k)
        assert secp256k1.point_decode(secp256k1.point_encode(p)) == p


def test_ecdsa_roundtrip_and_tamper():
    sk, pk = ecdsa.key_gen(b"seed")
    sig = ecdsa.sign(sk, b"hello")
    assert ecdsa.verify(pk, b"hello", sig)
    assert not ecdsa.verify(pk, b"world", sig)
    r, s = sig
    assert not ecdsa.verify(pk, b"hello", (r, (s + 1) % secp256k1.N))
    _, pk2 = ecdsa.key_gen(b"other")
    assert not ecdsa.verify(pk2, b"hello", sig)


def test_ecdsa_cross_check_openssl():
    """Our signatures must verify under OpenSSL's secp256k1 and vice
    versa (DER interchange)."""
    pytest.importorskip(
        "cryptography",
        reason="third-party `cryptography` (OpenSSL binding) not "
               "installed on this image; the DER interchange check "
               "needs it as the independent side")
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
    )

    msg = b"cross-check message"
    sk, pk = ecdsa.key_gen(b"xseed")
    sig = ecdsa.sign(sk, msg)

    # ours -> OpenSSL
    ossl_pk = ec.EllipticCurvePublicNumbers(
        pk[0], pk[1], ec.SECP256K1()).public_key()
    ossl_pk.verify(secp256k1.ecdsa_sig_to_der(sig), msg,
                   ec.ECDSA(hashes.SHA256()))

    # OpenSSL -> ours
    ossl_sk = ec.derive_private_key(sk, ec.SECP256K1())
    der = ossl_sk.sign(msg, ec.ECDSA(hashes.SHA256()))
    r, s = decode_dss_signature(der)
    if s > secp256k1.N // 2:
        s = secp256k1.N - s  # our verifier accepts either; normalize anyway
    assert ecdsa.verify(pk, msg, (r, s))


def test_schnorr_roundtrip_and_tamper():
    sk, pk = schnorr.key_gen(b"seed")
    sig = schnorr.sign(sk, b"msg")
    assert schnorr.verify(pk, b"msg", sig)
    assert not schnorr.verify(pk, b"other", sig)
    R, s = sig
    assert not schnorr.verify(pk, b"msg", (R, (s + 1) % secp256k1.N))


# ---------------------------------------------------------------------------
# BLS12-381
# ---------------------------------------------------------------------------

def test_bls_pairing_bilinearity():
    g1, g2 = bls.g1_generator(), bls.g2_generator()
    e = bls.pairing(g1, g2)
    assert e != bls.FQ12_ONE  # non-degenerate
    assert bls.fq12_pow(e, bls.R) == bls.FQ12_ONE  # order divides r
    assert bls.pairing(bls.g1_mul(g1, 2), g2) == bls.fq12_mul(e, e)
    assert bls.pairing(g1, bls.g2_mul(g2, 3)) == bls.fq12_pow(e, 3)
    # e(aP, bQ) = e(P, Q)^(ab)
    assert bls.pairing(bls.g1_mul(g1, 5),
                       bls.g2_mul(g2, 7)) == bls.fq12_pow(e, 35)


def test_bls_hash_to_g2_in_subgroup():
    H = bls.hash_to_g2(b"x")
    assert bls.g2_on_curve(H)
    eH = bls.pairing(bls.g1_generator(), H)
    # bilinearity with a hashed point proves subgroup membership
    assert bls.pairing(bls.g1_mul(bls.g1_generator(), 2),
                       H) == bls.fq12_mul(eH, eH)
    # deterministic
    assert bls.hash_to_g2(b"x") == H
    assert bls.hash_to_g2(b"y") != H


def test_bls_sign_verify():
    sk, pk = bls.key_gen(b"seed")
    sig = bls.sign(sk, b"msg")
    assert bls.verify(pk, b"msg", sig)
    assert not bls.verify(pk, b"other", sig)
    _, pk2 = bls.key_gen(b"seed2")
    assert not bls.verify(pk2, b"msg", sig)


def test_bls_aggregate():
    keys = [bls.key_gen(bytes([i])) for i in range(3)]
    msgs = [b"m0", b"m1", b"m2"]
    agg = bls.aggregate([bls.sign(sk, m) for (sk, _), m in zip(keys, msgs)])
    pks = [pk for _, pk in keys]
    assert bls.verify_aggregate(pks, msgs, agg)
    assert not bls.verify_aggregate(pks, [b"m0", b"m1", b"bad"], agg)

    # common-message fast path (QC shape: 2 Miller loops for any quorum)
    aggc = bls.aggregate([bls.sign(sk, b"common") for sk, _ in keys])
    assert bls.verify_aggregate_common(pks, b"common", aggc)
    assert not bls.verify_aggregate_common(pks[:2], b"common", aggc)


def test_bls_encoding_roundtrip():
    sk, pk = bls.key_gen(b"enc")
    sig = bls.sign(sk, b"m")
    assert bls.g1_decode(bls.g1_encode(pk)) == pk
    assert bls.g2_decode(bls.g2_encode(sig)) == sig
    assert len(bls.g1_encode(pk)) == 96
    assert len(bls.g2_encode(sig)) == 192


def _g1_curve_point_outside_subgroup():
    x = 5
    while True:
        rhs = (x * x * x + 4) % bls.Q
        y = pow(rhs, (bls.Q + 1) // 4, bls.Q)
        if y * y % bls.Q == rhs:
            pt = (x, y)
            if not bls.g1_in_subgroup(pt):
                return pt
        x += 1


def _g2_curve_point_outside_subgroup():
    xa = 1
    while True:
        xx = (xa, 0)
        rhs = bls.fq2_add(bls.fq2_mul(bls.fq2_mul(xx, xx), xx), bls._fq2.b)
        y = bls._fq2_sqrt(rhs)
        if y is not None:
            pt = (xx, y)
            if not bls.g2_in_subgroup(pt):
                return pt
        xa += 1


def test_bls_wrong_subgroup_rejected_on_decode():
    """filecoin bls-signatures parity (production/Cargo.toml:10): on-curve
    points with a cofactor component must fail deserialization — aggregate
    verification over them is undefined."""
    g1_rogue = _g1_curve_point_outside_subgroup()
    assert bls.g1_on_curve(g1_rogue)
    with pytest.raises(ValueError, match="subgroup"):
        bls.g1_decode(bls.g1_encode(g1_rogue))
    # cofactor-clearing the same point makes it decodable
    h1 = 0x396C8C005555E1568C00AAAB0000AAAB  # (x-1)^2 / 3
    cleared = bls._jac_mul(g1_rogue, h1, bls._fq)
    assert bls.g1_decode(bls.g1_encode(cleared)) == cleared

    g2_rogue = _g2_curve_point_outside_subgroup()
    assert bls.g2_on_curve(g2_rogue)
    with pytest.raises(ValueError, match="subgroup"):
        bls.g2_decode(bls.g2_encode(g2_rogue))
    cleared2 = bls._jac_mul(g2_rogue, bls._G2_COFACTOR, bls._fq2)
    assert bls.g2_decode(bls.g2_encode(cleared2)) == cleared2

    # infinity encodings still decode to None
    assert bls.g1_decode(bls.g1_encode(None)) is None
    assert bls.g2_decode(bls.g2_encode(None)) is None


def test_bls_jacobian_mul_matches_affine():
    """Pin the inversion-free Jacobian ladder (used by the subgroup checks)
    to the affine reference arithmetic."""
    for ops, gen in ((bls._fq, bls.g1_generator()),
                     (bls._fq2, bls.g2_generator())):
        for k in (1, 2, 3, 5, 255, 65537, 2**64 + 3, bls.R - 1):
            assert bls._jac_mul(gen, k, ops) == bls._mul(gen, k, ops)
        assert bls._jac_mul(gen, bls.R, ops) is None
        assert bls._jac_mul(gen, 0, ops) is None
        assert bls._jac_mul(None, 7, ops) is None


# ---------------------------------------------------------------------------
# EdDSA wrapper + bench plumbing
# ---------------------------------------------------------------------------

def test_eddsa_wrapper_paths_agree():
    msgs, pks, sigs = [], [], []
    for i in range(4):
        sk, pk = eddsa.key_gen(hashlib.sha256(bytes([i])).digest())
        msg = b"msg-%d" % i
        sig = eddsa.sign(sk, msg)
        msgs.append(msg)
        pks.append(pk)
        sigs.append(sig)
    sigs[2] = sigs[2][:10] + bytes([sigs[2][10] ^ 1]) + sigs[2][11:]
    expect = [True, True, False, True]
    assert eddsa.verify_batch_host(msgs, pks, sigs) == expect
    assert eddsa.verify_batch_tpu(msgs, pks, sigs) == expect


def test_bench_measure_single_smoke():
    from hotstuff_tpu.offchain import bench

    rows = bench.measure_single(iters=2, schemes=("eddsa", "schnorr"))
    assert {r["scheme"] for r in rows} == {"eddsa", "schnorr"}
    assert all(r["verify_ms"] > 0 for r in rows)


def test_bench_measure_batch_smoke():
    from hotstuff_tpu.offchain import bench

    # tpu_bls=False: the device pairing program is a multi-minute XLA
    # compile, exercised by tests/test_bls381.py's slow-gated test instead.
    rows = bench.measure_batch(sizes=(8,), tpu=True, tpu_bls=False)
    assert rows[0]["n"] == 8
    assert rows[0]["eddsa_tpu_ms"] > 0
    assert rows[0]["bls_aggregate_ms"] > 0

"""Harness tests: log parser against golden logs in the frozen grammar,
committee/parameters writers against the C++ readers' expectations, and
aggregation math. (The reference has no harness tests — SURVEY.md §4 —
but the parser's regex dependence on exact phrasing makes golden-log
coverage essential here.)
"""

import json
import os

import pytest

from hotstuff_tpu.harness import (
    BenchParameters,
    ConfigError,
    LocalCommittee,
    LogParser,
    NodeParameters,
    ParseError,
)

GOLDEN_CLIENT = """\
[2026-07-29T14:54:56.456Z INFO client] Node address: 127.0.0.1:9701
[2026-07-29T14:54:56.456Z INFO client] Transactions size: 512 B
[2026-07-29T14:54:56.456Z INFO client] Transactions rate: 2000 tx/s
[2026-07-29T14:54:56.456Z INFO client] Waiting for all nodes to be online...
[2026-07-29T14:54:54.525Z INFO client] Waiting for all nodes to be synchronized...
[2026-07-29T14:54:56.525Z INFO client] Start sending transactions
[2026-07-29T14:54:56.577Z INFO client] Sending sample transaction 0
[2026-07-29T14:54:56.627Z INFO client] Sending sample transaction 1
"""

GOLDEN_NODE = """\
[2026-07-29T14:54:55.100Z INFO mempool::config] Garbage collection depth set to 50 rounds
[2026-07-29T14:54:55.100Z INFO mempool::config] Sync retry delay set to 5000 ms
[2026-07-29T14:54:55.100Z INFO mempool::config] Sync retry nodes set to 3 nodes
[2026-07-29T14:54:55.100Z INFO mempool::config] Batch size set to 15000 B
[2026-07-29T14:54:55.100Z INFO mempool::config] Max batch delay set to 100 ms
[2026-07-29T14:54:55.101Z INFO consensus::config] Timeout delay set to 1000 ms
[2026-07-29T14:54:55.101Z INFO consensus::config] Sync retry delay set to 10000 ms
[2026-07-29T14:54:55.102Z INFO node::node] Node abc= successfully booted
[2026-07-29T14:54:56.577Z INFO mempool::batch_maker] Batch 2hHolx56fF0YIblphIzIeT2IHMTpt2ISKPP/4qqCsaU= contains sample tx 0
[2026-07-29T14:54:56.578Z INFO mempool::batch_maker] Batch 2hHolx56fF0YIblphIzIeT2IHMTpt2ISKPP/4qqCsaU= contains 15360 B
[2026-07-29T14:54:56.627Z INFO mempool::batch_maker] Batch 8obhcmwCu1dRnxvU+n/mr/KqNZ5OWZueM4no1X1NNCo= contains sample tx 1
[2026-07-29T14:54:56.628Z INFO mempool::batch_maker] Batch 8obhcmwCu1dRnxvU+n/mr/KqNZ5OWZueM4no1X1NNCo= contains 15360 B
[2026-07-29T14:54:56.700Z INFO consensus::proposer] Created B2
[2026-07-29T14:54:56.700Z INFO consensus::proposer] Created B2 -> 2hHolx56fF0YIblphIzIeT2IHMTpt2ISKPP/4qqCsaU=
[2026-07-29T14:54:56.750Z INFO consensus::proposer] Created B3
[2026-07-29T14:54:56.750Z INFO consensus::proposer] Created B3 -> 8obhcmwCu1dRnxvU+n/mr/KqNZ5OWZueM4no1X1NNCo=
[2026-07-29T14:54:57.000Z INFO consensus::core] Committed B2
[2026-07-29T14:54:57.000Z INFO consensus::core] Committed B2 -> 2hHolx56fF0YIblphIzIeT2IHMTpt2ISKPP/4qqCsaU=
[2026-07-29T14:54:57.200Z INFO consensus::core] Committed B3
[2026-07-29T14:54:57.200Z INFO consensus::core] Committed B3 -> 8obhcmwCu1dRnxvU+n/mr/KqNZ5OWZueM4no1X1NNCo=
"""


def test_parser_mines_optional_pacemaker_config():
    """graftview pacemaker knobs are OPTIONAL config lines: logs
    predating the backoff pacemaker parse exactly as before, and logs
    carrying them surface the values machine-readably."""
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    assert "timeout_backoff_factor_pct" not in parser.configs[0]["consensus"]

    node = GOLDEN_NODE + (
        "[2026-07-29T14:54:55.101Z INFO consensus::config] Timeout "
        "backoff factor set to 200 pct\n"
        "[2026-07-29T14:54:55.101Z INFO consensus::config] Timeout "
        "backoff cap set to 60000 ms\n"
        "[2026-07-29T14:54:55.101Z INFO consensus::config] Timeout "
        "jitter set to 10 pct\n"
        "[2026-07-29T14:54:55.101Z INFO consensus::config] Timeout "
        "future horizon set to 1000 rounds\n")
    parser = LogParser([GOLDEN_CLIENT], [node], faults=0)
    cons = parser.configs[0]["consensus"]
    assert cons["timeout_backoff_factor_pct"] == 200
    assert cons["timeout_backoff_cap"] == 60_000
    assert cons["timeout_jitter_pct"] == 10
    assert cons["timeout_future_horizon"] == 1_000
    # a quiet run (no TC/eject/drop lines) adds no view-change notes
    assert parser.viewchange["tc_rounds"] == []
    assert not any("View change" in n for n in parser.notes)


def test_node_parameters_validate_pacemaker_knobs():
    from hotstuff_tpu.harness import ConfigError, NodeParameters

    data = NodeParameters.default().json
    data["consensus"]["timeout_backoff_factor_pct"] = 300
    data["consensus"]["timeout_future_horizon"] = 500
    NodeParameters(data)  # valid overrides pass through
    for key, bad in (("timeout_backoff_factor_pct", 50),
                     ("timeout_backoff_factor_pct", "2x"),
                     ("timeout_jitter_pct", 101),
                     ("timeout_backoff_cap", 0),
                     ("timeout_future_horizon", 0)):
        broken = NodeParameters.default().json
        broken["consensus"][key] = bad
        with pytest.raises(ConfigError):
            NodeParameters(broken)


def test_aggregate_quotes_runs_and_bands(tmp_path, monkeypatch):
    """Multi-run same-settings result files aggregate into a band that
    SAYS how many runs back it (VERDICT r5 "do this" #4): the plot-file
    grammar keeps its frozen TPS prefix, matrix cells carry the run
    count, and bands() lists every repeated configuration."""
    from hotstuff_tpu.harness.aggregate import LogAggregator, Result
    from hotstuff_tpu.harness.utils import PathMaker

    summary = (
        "-----------------------------------------\n"
        " SUMMARY:\n"
        "-----------------------------------------\n"
        " + CONFIG:\n"
        " Faults: 0 nodes\n"
        " Committee size: 100 nodes\n"
        " Input rate: 1,600 tx/s\n"
        " Transaction size: 512 B\n"
        " Execution time: 60 s\n"
        " + RESULTS:\n"
        " End-to-end TPS: {tps} tx/s\n"
        " End-to-end BPS: 1 B/s\n"
        " End-to-end latency: {lat} ms\n")
    results = tmp_path / "results"
    results.mkdir()
    # one file holding two same-settings runs + a second single-run file
    (results / "bench-0-100-1600-512.txt").write_text(
        summary.format(tps="1,189", lat="19,000")
        + summary.format(tps="703", lat="45,000"))
    (results / "bench-0-100-1600b-512.txt").write_text(
        summary.format(tps="946", lat="32,000"))
    monkeypatch.setattr(PathMaker, "results_path",
                        staticmethod(lambda: str(results)))
    monkeypatch.setattr(PathMaker, "plot_path",
                        staticmethod(lambda: str(tmp_path / "plots")))
    agg = LogAggregator(max_latencies=[60_000])
    (result,) = agg.records.values()
    assert result.runs == 3
    assert result.mean_tps == round((1189 + 703 + 946) / 3)
    assert result.std_tps > 0
    # frozen plot grammar prefix + the run count riding behind it
    text = str(result)
    import re

    assert re.search(r"TPS: (\d+) \+/- (\d+)", text)  # plot.py's regex
    assert "over 3 run(s)" in text
    (band,) = agg.bands()
    assert band["nodes"] == 100 and band["runs"] == 3
    assert agg.bands(min_runs=4) == []
    cell = agg.matrix()[(0, 512)]["cells"][(100, 1600)]
    assert cell["runs"] == 3
    # single runs stay point estimates, honestly labelled
    assert Result(100, 200).runs == 1


def test_parser_mines_golden_logs():
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    # Both batches committed, 15360 B each at 512 B/tx = 60 tx.
    assert len(parser.commits) == 2
    assert len(parser.proposals) == 2
    assert sum(parser.sizes.values()) == 2 * 15360
    # Consensus latency: commits at +300ms and +450ms after proposals.
    lat = parser._consensus_latency()
    assert 0.3 < lat < 0.5
    # e2e latency: sample 0 sent 14:54:56.577, its batch committed .000 ->
    # 423ms; sample 1: .627 -> 57.200 = 573ms; mean ~498ms.
    e2e = parser._end_to_end_latency()
    assert 0.4 < e2e < 0.6
    out = parser.result()
    assert "End-to-end TPS" in out
    assert "Consensus latency" in out


def test_parser_folds_sidecar_stats_into_notes():
    """The verifysched OP_STATS snapshot renders as CONFIG notes — and
    the labelled RESULTS grammar the aggregator parses is untouched."""
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    parser.note_sidecar_stats({
        "launches": 42,
        "launches_by_class": {"latency": 40, "bulk": 2},
        "paths": {"rlc_sharded": 30, "ladder_sharded": 10,
                  "rlc_bisect": 2},
        "queue_wait": {"latency": {"n": 40, "p50_ms": 0.4, "p99_ms": 2.1},
                       "bulk": {"n": 2, "p50_ms": 9.0, "p99_ms": 9.5}},
        "bulk_fill_sigs": 128,
        "pad_waste_sigs": 300,
        "queue_full": {"bulk": 3},
        "mesh": {"sharded_launches": 40,
                 "shard_buckets": {"2": 30, "4": 10}},
        "scan": {"launches": 3, "sigs": 42_000,
                 "chunk_hist": {"4": 1, "16": 2},
                 "slices_avoided": 38},
        "pipeline": {"pack_ms": 120.5, "pack_hidden_ms": 90.4,
                     "overlap_ratio": 0.75},
        "compile": {"kernel": "abcd1234", "hits": 11, "misses": 0,
                    "warm_boot": True, "warmup_wall_s": 3.5},
    })
    out = parser.result()
    assert "Sidecar launches: 42 (latency 40, bulk 2)" in out
    assert ("Sidecar compile cache: 11 hit(s), 0 miss(es) — warm boot, "
            "warmup 3.5 s (kernel abcd1234)") in out
    assert "rlc_sharded=30" in out and "rlc_bisect=2" in out
    assert "latency p50 0.4 ms / p99 2.1 ms" in out
    assert "Sidecar pad fill: 128 sigs (waste 300)" in out
    assert "Sidecar mesh launches: 40 (per-shard buckets 2x30, 4x10)" \
        in out
    assert ("Sidecar whole-backlog scans: 3 (42,000 sigs, chunks 4x1, "
            "16x2), 38 slice(s) avoided") in out
    assert "Sidecar pack overlap: 75% of 120.5 ms packing hidden" in out
    assert "Sidecar queue-full sheds: bulk=3" in out
    # labelled grammar intact
    assert "End-to-end TPS" in out and "Consensus latency" in out
    # an idle / absent snapshot adds nothing
    quiet = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    quiet.note_sidecar_stats({})
    quiet.note_sidecar_stats({"launches": 0})
    assert quiet.notes == []
    # hostile value types (version-skewed sidecar, truncated writer):
    # no exception, no partial note block
    quiet.note_sidecar_stats({"launches": 1, "paths": {"rlc": None}})
    quiet.note_sidecar_stats({"launches": "what", "queue_wait": 3})
    assert quiet.notes == []


def test_parser_process_reads_sidecar_stats_file(tmp_path):
    import json

    (tmp_path / "client-0.log").write_text(GOLDEN_CLIENT)
    (tmp_path / "node-0.log").write_text(GOLDEN_NODE)
    (tmp_path / "sidecar-stats.json").write_text(json.dumps({
        "launches": 7, "launches_by_class": {"latency": 7},
        "bulk_fill_sigs": 0, "pad_waste_sigs": 11}))
    parser = LogParser.process(str(tmp_path), faults=0)
    assert any("Sidecar launches: 7" in n for n in parser.notes)
    # garbage file: telemetry is best-effort, parsing must survive
    (tmp_path / "sidecar-stats.json").write_text("{not json")
    parser = LogParser.process(str(tmp_path), faults=0)
    assert parser.notes == []


def test_parser_rejects_client_error():
    # The two fatal shapes the C++ client can emit.
    bad = GOLDEN_CLIENT + \
        "[2026-07-29T14:55:00.000Z ERROR client] something exploded\n"
    with pytest.raises(ParseError):
        LogParser([bad], [GOLDEN_NODE], faults=0)
    bad = GOLDEN_CLIENT + \
        "[2026-07-29T14:55:00.000Z WARN client] Failed to send transaction\n"
    with pytest.raises(ParseError):
        LogParser([bad], [GOLDEN_NODE], faults=0)


def test_parser_rejects_node_error():
    bad = GOLDEN_NODE + \
        "[2026-07-29T14:55:00.000Z ERROR node::main] uncaught exception\n"
    with pytest.raises(ParseError):
        LogParser([GOLDEN_CLIENT], [bad], faults=0)


def test_parser_real_logs_match_grammar(tmp_path):
    """End-to-end grammar lock: logs produced by the actual C++ binaries
    (committed fixtures from a real 4-node run) must parse."""
    import pathlib

    fixture = pathlib.Path(__file__).parent / "golden_logs"
    if not fixture.exists():
        pytest.skip("golden log fixtures not generated yet")
    parser = LogParser.process(str(fixture), faults=0)
    assert parser.commits, "no commits mined from real logs"
    assert parser._end_to_end_latency() > 0


def test_local_committee_layout(tmp_path):
    names = ["a=", "b=", "c=", "d="]
    committee = LocalCommittee(names, 9000)
    f = tmp_path / "committee.json"
    committee.print(str(f))
    data = json.loads(f.read_text())
    assert set(data) == {"consensus", "mempool"}
    cons = data["consensus"]["authorities"]
    memp = data["mempool"]["authorities"]
    assert cons["a="]["address"] == "127.0.0.1:9000"
    assert memp["a="]["transactions_address"] == "127.0.0.1:9004"
    assert memp["a="]["mempool_address"] == "127.0.0.1:9008"
    assert all(cons[n]["stake"] == 1 for n in names)


def test_node_parameters_roundtrip(tmp_path):
    params = NodeParameters.default(tpu_sidecar="127.0.0.1:7100")
    f = tmp_path / "parameters.json"
    params.print(str(f))
    data = json.loads(f.read_text())
    assert data["consensus"]["timeout_delay"] == 5000
    assert data["mempool"]["batch_size"] == 500_000
    assert data["tpu_sidecar"] == "127.0.0.1:7100"
    # malformed params rejected
    with pytest.raises(ConfigError):
        NodeParameters({"consensus": {}})


def test_bench_parameters_validation():
    ok = BenchParameters({
        "faults": 1, "nodes": 4, "rate": [10_000], "tx_size": 512,
        "duration": 20,
    })
    assert ok.nodes == [4] and ok.rate == [10_000]
    with pytest.raises(ConfigError):
        BenchParameters({
            "faults": 4, "nodes": 4, "rate": 1000, "tx_size": 512,
            "duration": 20,
        })


def test_node_parameters_chain_depth():
    """chain_depth: absent -> fine (2-chain default); 3 -> fine; 4 -> error
    (native/src/consensus/config.hpp accepts only 2 or 3)."""
    import pytest

    data = NodeParameters.default().json
    data["consensus"]["chain_depth"] = 3
    NodeParameters(dict(data))
    data["consensus"]["chain_depth"] = 4
    with pytest.raises(Exception):
        NodeParameters(dict(data))


# ---------------------------------------------------------------------------
# Sidecar lifecycle (round-3 verdict: a failed readiness wait leaked a hung
# sidecar process; the device sidecar must degrade to host crypto)
# ---------------------------------------------------------------------------

@pytest.mark.skipif(
    os.environ.get("HOTSTUFF_TPU_NO_PKILL_TESTS") == "1",
    reason="machine-wide pkill sweep; opt out on shared machines running "
           "a real bench/sidecar")
def test_kill_nodes_sweeps_orphaned_sidecar():
    """_kill_nodes must reap sidecar processes it no longer tracks (a
    wedged device leaves them hung past their process group's SIGTERM)."""
    import subprocess
    import sys
    import time

    from hotstuff_tpu.harness.local import LocalBench

    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(300)",
         "hotstuff_tpu.sidecar"])
    try:
        bench = LocalBench.__new__(LocalBench)
        bench._procs = []
        bench._kill_nodes()
        deadline = time.time() + 5
        while proc.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        assert proc.poll() is not None, "orphaned sidecar survived the sweep"
    finally:
        if proc.poll() is None:
            proc.kill()


def test_sidecar_boot_degrades_to_host_crypto():
    """Readiness failure on the device sidecar kills it and reboots with
    --host-crypto; a second failure propagates."""
    from hotstuff_tpu.harness.local import LocalBench
    from hotstuff_tpu.harness.utils import BenchError

    bench = LocalBench.__new__(LocalBench)
    bench.scheme = "ed25519"
    bench._degraded = False
    bench.nodes = 4
    bench.rate = 1000
    bench.fault_plan = None
    booted, waits, kills = [], [], []
    bench._background_run = \
        lambda cmd, log, append=False: booted.append(cmd)
    bench._kill_nodes = lambda: kills.append(True)

    def wait(deadline_s):
        waits.append(deadline_s)
        if len(waits) == 1:
            raise BenchError("not ready", TimeoutError())

    bench._wait_sidecar_ready = wait
    bench._boot_sidecar(host_crypto=False)
    assert len(booted) == 2
    assert "--host-crypto" not in booted[0]
    assert "--host-crypto" in booted[1]
    assert kills, "failed sidecar was not killed before the retry"

    # host-crypto boot that still fails must raise, after a sweep
    booted.clear(), waits.clear(), kills.clear()

    def wait_fail(deadline_s):
        raise BenchError("still not ready", TimeoutError())

    bench._wait_sidecar_ready = wait_fail
    with pytest.raises(BenchError):
        bench._boot_sidecar(host_crypto=True)
    assert kills


# ---------------------------------------------------------------------------
# bench.py headline emit: the live measurement is always the headline and
# the cache is namespaced by the kernel-source hash (round-5 ADVICE.md
# high: the old final emit was a monotonic ratchet a regression could
# never lower).
# ---------------------------------------------------------------------------


@pytest.fixture()
def bench_mod(tmp_path, monkeypatch):
    import bench

    monkeypatch.setattr(bench, "CACHE_PATH",
                        str(tmp_path / "headline_cache.json"))
    monkeypatch.setattr(bench, "_LINE_CACHE_PATH",
                        str(tmp_path / "last_line.json"))
    return bench


def _emitted_lines(capsys):
    return [json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()]


def test_final_emit_headline_is_live_measurement(bench_mod, capsys):
    bench_mod.save_cache(100_000.0, 10.0, 10_000.0)  # best on record
    bench_mod.emit_final(60_000.0, 10_000.0)         # live run regressed
    (line,) = _emitted_lines(capsys)
    assert line["value"] == 60_000.0, "headline must be the live reading"
    assert line["vs_baseline"] == 6.0
    assert line["best_on_record"] == 100_000.0
    assert "source" not in line  # not a cached-measurement line


def test_final_emit_no_secondary_when_live_is_best(bench_mod, capsys):
    bench_mod.save_cache(50_000.0, 5.0, 10_000.0)
    bench_mod.emit_final(60_000.0, 10_000.0)
    (line,) = _emitted_lines(capsys)
    assert line["value"] == 60_000.0
    assert "best_on_record" not in line


def test_cache_namespaced_by_kernel_hash(bench_mod):
    bench_mod.save_cache(100_000.0, 10.0, 10_000.0)
    assert bench_mod.load_cache()["value"] == 100_000.0
    # A best recorded by different kernel sources must not answer for
    # this tree: stamp a foreign kernel hash and reload.
    with open(bench_mod.CACHE_PATH) as f:
        cached = json.load(f)
    cached["kernel"] = "0" * 16
    with open(bench_mod.CACHE_PATH, "w") as f:
        json.dump(cached, f)
    assert bench_mod.load_cache() is None
    # ... and save_cache starts fresh rather than comparing against it.
    bench_mod.save_cache(10_000.0, 1.0, 10_000.0)
    assert bench_mod.load_cache()["value"] == 10_000.0


def test_save_cache_keeps_best_for_same_kernel(bench_mod):
    bench_mod.save_cache(100_000.0, 10.0, 10_000.0)
    bench_mod.save_cache(60_000.0, 6.0, 10_000.0)  # lower: not stored
    assert bench_mod.load_cache()["value"] == 100_000.0


# ---------------------------------------------------------------------------
# grafttrace: torn-line tolerance, critical-path notes, metrics series,
# sampled-stats fallback (PR 7)
# ---------------------------------------------------------------------------


def test_parser_tolerates_torn_log_lines():
    """Torn/interleaved lines from concurrent writers are skipped and
    counted — including a fragment that would otherwise fake a fatal
    ' ERROR ' hit — and never raise in non-strict mode."""
    torn = (GOLDEN_NODE
            + "mpool::batch_maker] torn tail with ERROR inside\n"
            + "[2026-07-29T14:5[2026-07-29T14:54:58.000Z INFO x] mix\n")
    parser = LogParser([GOLDEN_CLIENT], [torn], faults=0)
    assert parser.malformed_lines == 2
    assert any("skipped 2 torn/malformed log line(s)" in n
               for n in parser.notes)
    assert len(parser.commits) == 2  # metrics unaffected
    with pytest.raises(ParseError):
        LogParser([GOLDEN_CLIENT], [torn], faults=0, strict_lines=True)


def test_parser_keeps_crash_evidence_through_sanitizer():
    """libstdc++ prints 'terminate called ...' with NO log prefix; the
    torn-line sanitizer must keep such lines so a crashed replica still
    raises 'Node(s) failed' instead of parsing as a clean run."""
    crashed = (GOLDEN_NODE
               + "terminate called after throwing an instance of "
               "'std::runtime_error'\n"
               + "  what():  store wedged\n")
    with pytest.raises(ParseError, match="Node"):
        LogParser([GOLDEN_CLIENT], [crashed], faults=0)


def test_parser_notes_commit_critical_path():
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    parser.note_trace({
        "blocks": 5, "complete": 4,
        "segments": {
            "proposal->verify_submit": {"n": 4, "p50_ms": 1.5,
                                        "p99_ms": 3.0},
            "verify_submit->verify_reply": {"n": 4, "p50_ms": 22.0,
                                            "p99_ms": 41.0},
            "verify_reply->commit": {"n": 4, "p50_ms": 9.0,
                                     "p99_ms": 12.0},
            "proposal->commit": {"n": 5, "p50_ms": 50.0, "p99_ms": 80.0},
        },
        "sidecar": {"queue": {"n": 9, "p50_ms": 0.8, "p99_ms": 2.0},
                    "device": {"n": 9, "p50_ms": 17.0, "p99_ms": 25.0},
                    "reply": {"n": 9, "p50_ms": 0.1, "p99_ms": 0.2}},
    })
    out = parser.result()
    assert "Commit critical path (5 block(s), 4 fully traced)" in out
    assert "verify_submit->verify_reply p50 22 ms / p99 41 ms" in out
    assert "proposal->commit p50 50 ms / p99 80 ms" in out
    assert "Sidecar stage latency: device p50 17 ms / p99 25 ms; " \
           "queue p50 0.8 ms / p99 2 ms" in out
    assert parser.trace is not None
    # labelled RESULTS grammar untouched
    assert "End-to-end TPS" in out
    # hostile summaries add nothing and never raise
    quiet = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    quiet.note_trace({"segments": {"proposal->commit": {"n": 1}}})
    quiet.note_trace("garbage")
    quiet.note_trace({"segments": None})
    assert quiet.notes == [] and quiet.trace is None


def test_parser_process_builds_trace_artifact(tmp_path):
    """End-to-end: TRACE lines in a node log -> trace.json artifact +
    'Commit critical path' note out of LogParser.process."""
    trace_lines = "\n".join([
        "[2026-07-29T14:54:56.800Z INFO consensus::core] TRACE "
        "stage=proposal block=xyz= round=2",
        "[2026-07-29T14:54:56.820Z INFO consensus::core] TRACE "
        "stage=verify_submit block=xyz= round=2",
        "[2026-07-29T14:54:56.860Z INFO consensus::core] TRACE "
        "stage=verify_reply block=xyz= round=2",
        "[2026-07-29T14:54:56.900Z INFO consensus::core] TRACE "
        "stage=commit block=xyz= round=2",
    ])
    (tmp_path / "client-0.log").write_text(GOLDEN_CLIENT)
    (tmp_path / "node-0.log").write_text(GOLDEN_NODE + trace_lines + "\n")
    parser = LogParser.process(str(tmp_path), faults=0)
    assert parser.trace is not None
    assert parser.trace["segments"]["proposal->commit"]["n"] == 1
    assert any("Commit critical path" in n for n in parser.notes)
    with open(tmp_path / "trace.json") as f:
        chrome = json.load(f)
    assert any(e.get("ph") == "X" for e in chrome["traceEvents"])


def test_parser_notes_metrics_and_chaos_recovery_curve():
    """The sampled time series lands as a CONFIG note, and under a
    chaos plan each event's verdict cites the telemetry recovery curve
    (resumed N ms after the event, M failed ticks) instead of only the
    first post-fault commit scalar."""
    wall = LogParser._to_posix("2026-07-29T14:54:56.800Z")
    events = [{"t": 5.0, "target": "sidecar", "action": "kill",
               "wall": wall, "ok": True}]
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0,
                       chaos_events=events, strict_chaos=True)
    samples = [
        {"t": wall - 1.0, "ok": True, "stats": {"launches": 3}},
        {"t": wall + 0.5, "ok": False, "error": "down"},
        {"t": wall + 1.5, "ok": True, "stats": {"launches": 4}},
    ]
    parser.note_metrics(samples, malformed=1)
    out = parser.result()
    assert "Sidecar metrics: 3 sample(s) (2 ok) over 2.5 s" in out
    assert "1 torn line(s) skipped" in out
    assert "telemetry resumed 1500 ms after event (1 failed tick(s))" \
        in out
    assert parser.chaos["events"][0]["telemetry"]["resumed"] is True
    # without samples: nothing added
    quiet = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    quiet.note_metrics([])
    assert quiet.notes == [] and quiet.metrics is None


def test_parser_notes_sampled_stats_fallback():
    """A sidecar-stats.json recovered from the periodic sampler (the
    sidecar was chaos-killed before teardown) says so in the notes."""
    parser = LogParser([GOLDEN_CLIENT], [GOLDEN_NODE], faults=0)
    parser.note_sidecar_stats({
        "launches": 7, "launches_by_class": {"latency": 7},
        "bulk_fill_sigs": 0, "pad_waste_sigs": 0,
        "_from_sample_at": 1753800000.0})
    out = parser.result()
    assert "Sidecar stats from last sample @ 2025-07-29T" in out
    assert "(sidecar unreachable at teardown)" in out
    assert "Sidecar launches: 7" in out


def test_fetch_sidecar_stats_falls_back_to_last_sample(tmp_path,
                                                       monkeypatch):
    """LocalBench._fetch_sidecar_stats: when the live OP_STATS fetch
    fails (dead sidecar), the sampler's last good snapshot is persisted
    with the _from_sample_at marker instead of dropping the section."""
    import hotstuff_tpu.harness.local as local_mod
    from hotstuff_tpu.harness.local import LocalBench
    from hotstuff_tpu.harness.utils import PathMaker

    monkeypatch.chdir(tmp_path)
    (tmp_path / "logs").mkdir()
    bench = LocalBench.__new__(LocalBench)
    bench.SIDECAR_PORT = 1  # nothing listens: the fetch must fail

    class _Sampler:
        last = (1753800123.0, {"launches": 5, "sigs_launched": 640})

    bench._sampler = _Sampler()
    bench._fetch_sidecar_stats()
    with open(PathMaker.sidecar_stats_file()) as f:
        stats = json.load(f)
    assert stats["launches"] == 5
    assert stats["_from_sample_at"] == 1753800123.0

    # No sampler snapshot at all: nothing written, no exception.
    (tmp_path / "logs" / "sidecar-stats.json").unlink()
    bench._sampler = None
    bench._fetch_sidecar_stats()
    assert not (tmp_path / "logs" / "sidecar-stats.json").exists()


def test_trace_headline_probe_schema(bench_mod):
    """The headline `trace` field: known skew recovered, partial trace
    tolerated, the graftscope ctx join accounted (one joined block, one
    verify-traced block with no chain -> join_rate 0.5), Chrome round
    trip intact (the field rides the degraded line too, so this schema
    is what a no-device run publishes)."""
    out = bench_mod.trace_headline_probe()
    assert out["roundtrip_ok"] is True
    assert out["blocks"] == 3 and out["complete"] == 2
    assert out["offset_applied_ms"] == pytest.approx(125.0)
    segs = out["segments"]
    # replica 1's skewed observations aligned BEHIND replica 0's, so
    # the earliest-wins totals are replica 0's own
    assert segs["proposal->commit"]["n"] == 3
    assert segs["proposal->commit"]["p50_ms"] == pytest.approx(60.0)
    assert segs["verify_submit->verify_reply"]["p50_ms"] == \
        pytest.approx(20.0)
    # graftscope: device time nested as the verify:device sub-segment,
    # join accounting on the line
    assert segs["verify:device"]["p50_ms"] == pytest.approx(18.0)
    assert out["join"] == {"committed": 3, "with_verify": 2,
                           "joined": 1, "rate": 0.5}
    assert out["join_rate"] == 0.5
    assert out["chrome_events"] > 0


def test_committee_scale_probe_schema(bench_mod):
    """The headline `committee_scale` field (graftscale): QC-shaped
    batches of 2f+1 votes per committee size through all three
    engine-path mesh entries, keyed N<committee>, sigs/sec/chip per
    route — the schema both the live and degraded lines publish.
    Fixture-scale committees keep the CPU compiles tiny; the real
    sweep (100/300/1000) runs in the bench's forced-host subprocess."""
    out = bench_mod.committee_scale_probe(committees=(10, 22),
                                          repeats=1, budget_s=600.0)
    assert set(out) == {"N10", "N22"}
    for key, committee in (("N10", 10), ("N22", 22)):
        stats = out[key]
        assert stats["quorum"] == 2 * committee // 3 + 1
        for route in ("per_sig_sharded", "rlc_sharded", "scan"):
            assert stats[f"{route}_sigs_per_s_chip"] > 0, (key, route)
        assert stats["rlc_speedup"] > 0
    # An exhausted budget marks remaining committees skipped instead of
    # stalling the stage (the degraded-line discipline).
    out = bench_mod.committee_scale_probe(committees=(10,), repeats=1,
                                          budget_s=0.0)
    assert out["N10"] == {"quorum": 7, "skipped": True}


def test_sched_probe_carries_scan_section(bench_mod):
    """The bench `sched` field round-trips the OP_STATS snapshot over
    the real wire encoding — the graftscale ``scan`` section rides it
    (zeros on the host-mode probe engine, but the schema is what a
    mesh run's headline publishes)."""
    out = bench_mod.sched_headline_probe()
    assert out["scan"] == {"launches": 0, "sigs": 0, "chunk_hist": {},
                           "slices_avoided": 0}
    assert out["shapes"]["mesh_chunks"] == []
    assert out["shapes"]["scan_rows"] == 0

"""graftdag certified-batch mempool: Python-side contracts.

Two halves:

  * wire mirror — ``analysis/dagwire.py`` must agree with the native
    authority (``native/src/mempool/messages.hpp``) on every
    BatchCertificate constant, and its ``ack_digest`` helper must
    reproduce the exact domain-separated preimage the node signs.
  * engine routing — a certificate's ACK batch is QC-shaped (2f+1
    signatures over one common ack digest), so a quorum-sized cert
    batch must land on the warmed RLC one-MSM path of the verify
    engine with a verdict mask bit-identical to per-signature
    ``verify_batch`` — including the bisection path when one ACK is a
    domain-separation replay (a signature over the bare batch digest).
"""

import hashlib
import os
import threading

import pytest

from hotstuff_tpu.analysis import dagwire, wirecheck
from hotstuff_tpu.crypto import eddsa, ref_ed25519 as ref
from hotstuff_tpu.sidecar import protocol as proto
from hotstuff_tpu.sidecar import sched as vsched
from hotstuff_tpu.sidecar import service
from hotstuff_tpu.sidecar.sched.shapes import quorum_sigs
from hotstuff_tpu.sidecar.service import VerifyEngine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Wire mirror: dagwire.py vs native/src/mempool/messages.hpp
# ---------------------------------------------------------------------------

def _native_constants():
    with open(os.path.join(REPO, wirecheck.MEMPOOL_MSG_HPP),
              encoding="utf-8") as fh:
        src = fh.read()
    cpp = wirecheck.cpp_int_constants(src)
    cpp.update(wirecheck.cpp_typed_enum_constants(src, "Kind"))
    return src, cpp


def test_constants_match_native_header():
    _, cpp = _native_constants()
    assert cpp["kBatchAckTag"] == dagwire.BATCH_ACK_TAG
    assert cpp["kBatchAckDomain"] == dagwire.BATCH_ACK_DOMAIN
    assert cpp["kCertVoteLen"] == dagwire.CERT_VOTE_LEN
    assert cpp["kBatch"] == dagwire.MEMPOOL_KIND_BATCH
    assert cpp["kBatchRequest"] == dagwire.MEMPOOL_KIND_BATCH_REQUEST
    assert cpp["kAck"] == dagwire.MEMPOOL_KIND_ACK
    # the ACK rides the MempoolMessage Kind field
    assert dagwire.BATCH_ACK_TAG == dagwire.MEMPOOL_KIND_ACK


def test_cert_vote_len_is_pk_plus_sig():
    assert dagwire.CERT_VOTE_LEN == dagwire.ED_PK_LEN + dagwire.ED_SIG_LEN
    assert dagwire.CERT_VOTE_LEN == 96


def test_ack_domain_spells_dagack_little_endian():
    raw = dagwire.BATCH_ACK_DOMAIN.to_bytes(8, "little")
    assert raw.rstrip(b"\x00") == b"dagack"


def test_ack_digest_recipe_and_domain_separation():
    batch_digest = hashlib.sha512(b"graftdag batch").digest()[:32]
    want = hashlib.sha512(
        batch_digest
        + dagwire.BATCH_ACK_DOMAIN.to_bytes(8, "little")).digest()[:32]
    got = dagwire.ack_digest(batch_digest)
    assert got == want
    assert len(got) == dagwire.DIGEST_LEN
    # the whole point of the domain: an ACK preimage is never the batch
    # digest itself, so a batch ACK cannot be replayed as another vote
    assert got != batch_digest
    with pytest.raises(ValueError):
        dagwire.ack_digest(b"short")


def test_certframe_lint_rule_is_clean():
    """The graftlint certframe cross-check (the CI pin for these
    constants) passes on this checkout."""
    findings = [f for f in wirecheck.check(REPO)
                if f.rule == "certframe-mismatch"]
    assert findings == [], [f.render() for f in findings]


# ---------------------------------------------------------------------------
# Engine routing: quorum-sized cert ACK batches on the warmed RLC path
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def rlc_engine():
    """Device-path engine (CPU backend) with per-signature and RLC
    shapes warmed to 32 via the real warmup entry points — the same
    registry state ``--warm-rlc`` produces, and the same shapes the
    node's certificate verifies dispatch onto."""
    engine = VerifyEngine()
    service._warmup(engine, warm_max=32)
    service._warmup_rlc(engine, warm_max=32)
    yield engine
    engine.stop()


def _engine_mask(engine, msgs, pks, sigs):
    done = []
    cond = threading.Condition()

    def reply(mask):
        with cond:
            done.append(mask)
            cond.notify()

    assert engine.submit(proto.VerifyRequest(1, msgs, pks, sigs), reply)
    with cond:
        assert cond.wait_for(lambda: done, timeout=120.0)
    return done[0]


def _cert_votes(n, seed=77, batch_tag=b"graftdag cert batch"):
    """n signed ACKs over one certified batch: QC-shaped (one common
    ack digest), exactly what BatchCertificate::vote_items yields."""
    batch_digest = hashlib.sha512(batch_tag).digest()[:32]
    ack = dagwire.ack_digest(batch_digest)
    import numpy as np
    r = np.random.default_rng(seed)
    msgs, pks, sigs = [], [], []
    for _ in range(n):
        sk = r.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msgs.append(ack)
        pks.append(pk)
        sigs.append(ref.sign(sk, ack))
    return batch_digest, msgs, pks, sigs


def test_quorum_cert_batch_routes_onto_warmed_rlc_bucket(rlc_engine):
    """A 25-replica committee's quorum certificate (2f+1 = 17 ACKs)
    lands on the warmed RLC bucket through the full engine path, with a
    verdict mask bit-identical to per-signature verify_batch."""
    engine = rlc_engine
    n = quorum_sigs(25)
    assert n == 17
    # the routing decision itself: quorum-size is past the RLC floor and
    # its pow2 bucket (32) was warmed, so the registry routes it to the
    # one-MSM program — the same decision the node's cert dispatch hits
    assert engine._shapes.route(n) == vsched.PATH_RLC
    before = engine.stats_snapshot()["paths"].get("rlc", 0)
    _, msgs, pks, sigs = _cert_votes(n)
    got = _engine_mask(engine, msgs, pks, sigs)
    want = eddsa.verify_batch(msgs, pks, sigs)
    assert got == [bool(b) for b in want]
    assert got == [True] * n
    assert engine.stats_snapshot()["paths"].get("rlc", 0) == before + 1


def test_replayed_consensus_sig_pinpointed_by_bisection(rlc_engine):
    """One 'ACK' signed over the bare batch digest (the replay the
    dagack domain exists to kill) inside an otherwise-valid quorum
    batch: the RLC combined check fails, bisection pinpoints exactly
    the forged slot, and the mask stays bit-identical to
    per-signature verify_batch."""
    engine = rlc_engine
    n = quorum_sigs(25)
    batch_digest, msgs, pks, sigs = _cert_votes(n, seed=78)
    import numpy as np
    r = np.random.default_rng(5)
    sk = r.bytes(32)
    _, pk = ref.generate_keypair(sk)
    forged = 6
    pks[forged] = pk
    sigs[forged] = ref.sign(sk, batch_digest)  # wrong preimage: no domain
    before = engine.stats_snapshot()["paths"].get("rlc_bisect", 0)
    got = _engine_mask(engine, msgs, pks, sigs)
    want = eddsa.verify_batch(msgs, pks, sigs)
    assert got == [bool(b) for b in want]
    assert got == [i != forged for i in range(n)]
    assert engine.stats_snapshot()["paths"].get("rlc_bisect", 0) > before


def test_small_committee_cert_stays_per_sig_and_bit_identical(rlc_engine):
    """The 4-replica fixture committee's cert (3 ACKs) is below the RLC
    launch floor — it takes the per-signature ladder, still through
    warmed buckets, still bit-identical."""
    engine = rlc_engine
    n = quorum_sigs(4)
    assert n == 3
    assert engine._shapes.route(n) == vsched.PATH_PER_SIG
    _, msgs, pks, sigs = _cert_votes(n, seed=79)
    got = _engine_mask(engine, msgs, pks, sigs)
    assert got == [bool(b) for b in eddsa.verify_batch(msgs, pks, sigs)]
    assert got == [True] * n

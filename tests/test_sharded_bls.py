"""Mesh-sharded BLS multi-digest verification (parallel/sharded_bls.py)
on the virtual CPU mesh: verdict parity with the host reference and the
single-chip device path, across padding shapes."""


import pytest

from hotstuff_tpu.offchain import bls12381 as host
from hotstuff_tpu.parallel.mesh import make_mesh

pytestmark = pytest.mark.slow  # multi-minute Miller-loop compile on CPU


def test_sharded_multi_digest_matches_host():
    from hotstuff_tpu.parallel.sharded_bls import (
        verify_aggregate_multi_sharded,
    )

    mesh = make_mesh(8)
    # 5 votes + the -g1/agg row = 6 pairing rows -> pads to 8 (one per
    # device, with masked identity rows).
    sks, pks = zip(*[host.key_gen(bytes([i]) * 32) for i in range(1, 6)])
    msgs = [bytes([i]) * 32 for i in range(5)]
    sigs = [host.sign(sk, m) for sk, m in zip(sks, msgs)]
    agg = host.aggregate(sigs)

    from hotstuff_tpu.ops import bls381 as D

    assert verify_aggregate_multi_sharded(mesh, list(pks), msgs, agg)
    assert host.verify_aggregate(list(pks), msgs, agg)
    # parity with the single-chip device path on the same statement
    assert D.verify_aggregate_multi(list(pks), msgs, agg)

    # one vote over the wrong digest breaks the sharded product too
    bad = host.aggregate(sigs[:4] + [host.sign(sks[4], b"x" * 32)])
    assert not verify_aggregate_multi_sharded(mesh, list(pks), msgs, bad)
    assert not D.verify_aggregate_multi(list(pks), msgs, bad)

    # malformed inputs reject without device work
    assert not verify_aggregate_multi_sharded(mesh, list(pks), msgs[:4],
                                              agg)
    assert not verify_aggregate_multi_sharded(mesh, [], [], agg)

"""Structure-aware protocol fuzz suite (graftguard satellite).

A seeded corpus of malformed protocol-v5 frames — truncated headers,
oversized and lying length prefixes, hostile counts, bad opcodes,
ctx-tag/record-boundary aliasing attempts, malformed JSON bodies, and
mid-frame disconnects — driven two ways:

  * straight into ``protocol.decode_request`` (the contract: every
    malformed frame raises ValueError, nothing else escapes);
  * over a real socket into a live ``_Handler`` (the contract: an error
    reply or a clean connection drop, NEVER a hang or a crash — and the
    server still serves correct verdicts to the next client).

Every socket op is timeout-bounded, so a regression that turns a
malformed frame into a hang fails the test instead of wedging the
suite.  Wired into tier-1; scripts/guard_gate.sh re-runs it in CI next
to the wedge-recovery lane.
"""

from __future__ import annotations

import random
import socket
import struct
import threading

import numpy as np
import pytest

from hotstuff_tpu.crypto import ref_ed25519 as ref
from hotstuff_tpu.sidecar import protocol as proto
from hotstuff_tpu.sidecar.client import SidecarClient
from hotstuff_tpu.sidecar.service import SidecarServer, VerifyEngine

SEED = 0xF022
_HDR_SIZE = proto._HDR.size


def _sigs(n, tamper=(), seed=7):
    rng = np.random.default_rng(seed)
    msgs, pks, sigs = [], [], []
    for i in range(n):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        sig = ref.sign(sk, msg)
        if i in tamper:
            sig = sig[:1] + bytes([sig[1] ^ 0xFF]) + sig[2:]
        msgs.append(msg)
        pks.append(pk)
        sigs.append(sig)
    return msgs, pks, sigs


def _frame(payload: bytes) -> bytes:
    return struct.pack(">I", len(payload)) + payload


def corpus(seed: int = SEED) -> list:
    """The seeded malformed-frame corpus: ``(label, wire_bytes)`` pairs.
    Deterministic — a CI failure names the exact case."""
    rng = random.Random(seed)
    msgs, pks, sigs = _sigs(2, seed=11)
    good = proto.encode_request(7, msgs, pks, sigs)
    good_payload = good[4:]
    out = []
    # Truncated headers: every prefix of the fixed header.
    for k in range(_HDR_SIZE):
        out.append((f"truncated-header-{k}", _frame(good_payload[:k])))
    # Truncated records: cut mid-record at a few seeded offsets.
    for _ in range(6):
        k = rng.randrange(_HDR_SIZE + 1, len(good_payload))
        out.append((f"truncated-record-{k}", _frame(good_payload[:k])))
    # Oversized length prefix: header promises more than MAX_FRAME.
    out.append(("oversized-length",
                struct.pack(">I", proto.MAX_FRAME + 1) + b"\x00" * 64))
    # Lying length prefix: promises bytes that never arrive (the peer
    # just stops) — covered live as a mid-frame disconnect.
    out.append(("lying-length-short-body",
                struct.pack(">I", 4096) + good_payload[:32]))
    # Hostile counts: u32 max, count disagreeing with the byte length.
    for n in (0xFFFFFFFF, 1000, 3):
        hdr = proto._HDR.pack(proto.OP_VERIFY_BATCH, 9, n, 32)
        out.append((f"count-{n}-no-records", _frame(hdr)))
    # Negative-ish msg_len aliasing: msg_len u16 max with one record.
    hdr = proto._HDR.pack(proto.OP_VERIFY_BATCH, 9, 1, 0xFFFF)
    out.append(("msglen-max", _frame(hdr + b"\x00" * 64)))
    # Bad opcodes (0 and a seeded sample above the known set — which
    # now includes OP_HELLO=11, so the sample starts at 12).
    for op in [0] + sorted(rng.sample(range(12, 256), 6)):
        hdr = struct.pack("<BIIH", op, 1, 0, 0)
        out.append((f"bad-opcode-{op}", _frame(hdr)))
    # OP_BUSY is reply-only: as a request it must be rejected.
    out.append(("busy-as-request",
                _frame(struct.pack("<BIIH", proto.OP_BUSY, 1, 2, 0)
                       + b"\x10\x00")))
    # ctx-tag / record-boundary aliasing: a tagged frame's length is
    # exactly header + 32 + n*rec; every nearby length must be
    # rejected, never mis-split into records.
    rec = 32 + proto.ED_PK_LEN + proto.ED_SIG_LEN
    base = _HDR_SIZE + proto.CTX_LEN + 2 * rec
    tagged = proto.encode_request(7, msgs, pks, sigs, ctx=b"\xAA" * 32)
    for delta in (-33, -31, -16, -1, 1, 16, 31, 33):
        payload = tagged[4:] + b"\x00" * max(0, delta)
        payload = payload[:base + delta]
        out.append((f"ctx-alias-delta{delta:+d}", _frame(payload)))
    # Malformed HELLO frames (protocol v6 tenant handshake): truncated
    # bodies, a tenant longer than TENANT_MAX_LEN, charset garbage,
    # non-UTF-8 bytes, an empty tenant, and a lying msg_len.  Contract:
    # ValueError at decode (or an error reply live), never a silently
    # truncated or mangled tenant id reaching the scheduler lanes.
    hello = proto.encode_hello_request(3, "fuzz-tenant")[4:]
    for k in (1, 5, len(hello) - 4, len(hello) - 1):
        out.append((f"hello-truncated-{k}", _frame(hello[:k])))
    long_tenant = b"t" * (proto.TENANT_MAX_LEN + 1)
    hdr = proto._HDR.pack(proto.OP_HELLO, 3, proto.PROTOCOL_VERSION,
                          len(long_tenant))
    out.append(("hello-oversized-tenant", _frame(hdr + long_tenant)))
    for label, body in (("charset", b"ten ant!"), ("empty", b""),
                        ("non-utf8", b"\xff\xfe\xfd\x00bad"),
                        ("slash", b"../escape")):
        hdr = proto._HDR.pack(proto.OP_HELLO, 3, proto.PROTOCOL_VERSION,
                              len(body))
        out.append((f"hello-garbage-{label}", _frame(hdr + body)))
    hdr = proto._HDR.pack(proto.OP_HELLO, 3, proto.PROTOCOL_VERSION, 200)
    out.append(("hello-lying-msglen", _frame(hdr + b"tenant")))
    # Malformed JSON bodies on the JSON-carrying opcodes.
    for label, op in (("chaos", proto.OP_CHAOS),):
        body = b"{not json"
        hdr = proto._HDR.pack(op, 3, len(body), 0)
        out.append((f"bad-{label}-json", _frame(hdr + body)))
        hdr = proto._HDR.pack(op, 3, len(body) + 50, 0)  # lying count
        out.append((f"bad-{label}-count", _frame(hdr + body)))
    # BLS frames with wrong record arithmetic.
    hdr = proto._HDR.pack(proto.OP_BLS_VERIFY_VOTES, 4, 3, 32)
    out.append(("bls-votes-short", _frame(hdr + b"\x00" * 40)))
    hdr = proto._HDR.pack(proto.OP_BLS_VERIFY_MULTI, 4, 2, 32)
    out.append(("bls-multi-short", _frame(hdr + b"\x00" * 100)))
    hdr = proto._HDR.pack(proto.OP_BLS_SIGN, 4, 1, 8)
    out.append(("bls-sign-short", _frame(hdr + b"\x00" * 10)))
    # Pure noise at seeded lengths (framed, so only the decoder sees it).
    for i, size in enumerate((1, 13, 97, 512)):
        out.append((f"noise-{i}", _frame(rng.randbytes(size))))
    return out


def test_corpus_is_seeded_and_stable():
    a = [(label, bytes(b)) for label, b in corpus()]
    b = [(label, bytes(b)) for label, b in corpus()]
    assert a == b
    assert len(a) > 30


def test_decode_request_never_hangs_or_leaks_exceptions():
    """decode_request's contract over the whole corpus: ValueError or a
    decoded request — no other exception type, ever."""
    for label, wire in corpus():
        payload = wire[4:]
        try:
            opcode, req = proto.decode_request(payload)
        except ValueError:
            continue
        except Exception as e:  # noqa: BLE001 — the assertion
            raise AssertionError(
                f"{label}: decode_request leaked {e!r}")
        # A case that decodes is fine (some truncations are legal
        # shorter frames) as long as it decoded to a known shape.
        assert opcode in (proto.OP_VERIFY_BATCH, proto.OP_VERIFY_BULK,
                          proto.OP_PING, proto.OP_STATS, proto.OP_CHAOS,
                          proto.OP_BLS_VERIFY_AGG, proto.OP_BLS_SIGN,
                          proto.OP_BLS_VERIFY_VOTES,
                          proto.OP_BLS_VERIFY_MULTI,
                          proto.OP_HELLO), label
        if opcode == proto.OP_HELLO:
            # A HELLO that decodes must carry a VALIDATED tenant —
            # charset-checked and length-bounded, never a raw slice.
            assert req.tenant == proto.validate_tenant(req.tenant), label


def test_ctx_alias_boundary_is_exact():
    """Only the EXACT +CTX_LEN length decodes as a tagged frame; the
    tag can never alias into (or out of) the record array."""
    msgs, pks, sigs = _sigs(2, seed=13)
    tagged = proto.encode_request(5, msgs, pks, sigs, ctx=b"\xAB" * 32)
    opcode, req = proto.decode_request(tagged[4:])
    assert req.ctx == b"\xAB" * 32
    assert req.msgs == msgs and req.sigs == sigs
    untagged = proto.encode_request(5, msgs, pks, sigs)
    opcode, req = proto.decode_request(untagged[4:])
    assert req.ctx is None and req.msgs == msgs
    for delta in (-1, 1, 16, 31, 33):
        payload = tagged[4:] + b"\x00" * max(0, delta)
        payload = payload[:len(tagged) - 4 + delta]
        with pytest.raises(ValueError):
            proto.decode_request(payload)


# -- graftingress signed-tx frame corpus ----------------------------------
#
# The admission path feeds raw client bytes into the signed-frame parser
# on both sides (txsign.parse_signed_tx here, tx_frame.hpp's
# parse_signed_tx in native/tests/test_mempool.cpp).  Contract: every
# malformed frame raises TxFrameError with a named reason — truncation,
# lying payload lengths, and pubkey/sig boundary aliasing can NEVER
# mis-slice — and a forged-signature frame with valid structure parses
# cleanly and dies at verify, never at parse.

def _tx_keypair(user: int = 0):
    from hotstuff_tpu.crypto import txsign
    return txsign.derive_user_keypair(5, user)


def tx_corpus(seed: int = SEED) -> list:
    """Seeded malformed signed-tx frames: ``(label, frame_bytes)``."""
    from hotstuff_tpu.crypto import txsign

    rng = random.Random(seed)
    kp = _tx_keypair()
    payload = txsign.build_payload(txsign.TX_MARKER_FILLER, 42, size=32)
    good = txsign.build_signed_tx(kp, nonce=9, payload=payload)
    out = []
    # Truncations: every cut inside the header, seeded cuts mid-payload
    # and mid-signature.
    for k in range(txsign.TX_FRAME_HEADER_LEN):
        out.append((f"tx-truncated-header-{k}", good[:k]))
    for _ in range(6):
        k = rng.randrange(txsign.TX_FRAME_HEADER_LEN, len(good) - 1)
        out.append((f"tx-truncated-{k}", good[:k]))
    # Lying payload_len: declared length disagrees with the frame (short
    # and long), including the pubkey/sig boundary aliasing attempts —
    # a length off by ±1/±32/±64 would slide the signature window over
    # payload bytes (or padding) if the parser trusted it.
    for delta in (-64, -32, -1, 1, 32, 64):
        lying = bytearray(good)
        plen = len(payload) + delta
        if plen < 0:
            continue
        lying[41:45] = plen.to_bytes(4, "big")
        out.append((f"tx-lying-len{delta:+d}", bytes(lying)))
    # Same aliasing from the other side: frame padded/cut while the
    # declared length stays honest.
    for delta in (-64, -1, 1, 64):
        if delta < 0:
            out.append((f"tx-frame-cut{delta:+d}", good[:delta]))
        else:
            out.append((f"tx-frame-pad{delta:+d}",
                        good + bytes(rng.randbytes(delta))))
    # Oversized: declared payload_len beyond TX_MAX_PAYLOAD (the 1 MiB
    # admission bound), and below TX_MIN_PAYLOAD.
    for plen in (txsign.TX_MAX_PAYLOAD + 1, 0xFFFFFFFF, 0,
                 txsign.TX_MIN_PAYLOAD - 1):
        lying = bytearray(good)
        lying[41:45] = plen.to_bytes(4, "big")
        out.append((f"tx-payload-len-{plen}", bytes(lying)))
    # Wrong version byte: legacy markers and seeded non-version values
    # must be classified not-signed, never parsed as signed frames.
    for v in [0, 1] + sorted(rng.sample(range(3, 256), 4)):
        wrong = bytes([v]) + good[1:]
        out.append((f"tx-version-{v}", wrong))
    out.append(("tx-empty", b""))
    # Pure noise at seeded lengths.
    for i, size in enumerate((1, 45, 109, 118, 500)):
        out.append((f"tx-noise-{i}", bytes(rng.randbytes(size))))
    return out


def test_tx_corpus_is_seeded_and_stable():
    a = [(label, bytes(b)) for label, b in tx_corpus()]
    b = [(label, bytes(b)) for label, b in tx_corpus()]
    assert a == b
    assert len(a) > 40


def test_tx_parse_never_crashes_or_misparses():
    """parse_signed_tx over the whole corpus: TxFrameError with a named
    reason, or (for the rare structurally-valid mutant) a parse whose
    slices are exact — no other exception, no mis-slicing, and nothing
    malformed survives to verify as authentic."""
    from hotstuff_tpu.crypto import txsign

    reasons = {"not-signed", "truncated", "bad-payload-len"}
    for label, frame in tx_corpus():
        try:
            tx = txsign.parse_signed_tx(frame)
        except txsign.TxFrameError as e:
            assert e.reason in reasons, label
            continue
        except Exception as e:  # noqa: BLE001 — the assertion
            raise AssertionError(f"{label}: parse leaked {e!r}")
        # Structurally valid (e.g. padding absorbed into a longer
        # declared payload): slices must be exact and the signature
        # must NOT verify — a mutant can parse but never authenticate.
        assert len(tx.pk) == txsign.TX_PK_LEN, label
        assert len(tx.sig) == txsign.TX_SIG_LEN, label
        assert not txsign.verify_tx(frame), label


def test_tx_forged_signature_dies_at_verify_not_parse():
    """The seeded forgery mix's contract: a flipped-signature frame is
    structurally INDISTINGUISHABLE from an honest one (same parse, same
    slices) and fails only signature verification."""
    from hotstuff_tpu.crypto import txsign

    kp = _tx_keypair(3)
    payload = txsign.build_payload(txsign.TX_MARKER_FORGED, 7)
    honest = txsign.build_signed_tx(kp, nonce=1, payload=payload)
    forged = txsign.build_signed_tx(kp, nonce=1, payload=payload,
                                    flip_sig_bit=True)
    h, f = txsign.parse_signed_tx(honest), txsign.parse_signed_tx(forged)
    assert h.pk == f.pk and h.payload == f.payload and h.nonce == f.nonce
    assert h.sig != f.sig
    assert txsign.verify_tx(honest)
    assert not txsign.verify_tx(forged)
    # The admission record (digest, pk, sig) is identical up to the
    # signature — the digest covers only the signed prefix, so the
    # forgery is invisible until the sidecar verdict.
    dh, pkh, _ = txsign.admission_record(honest)
    df, pkf, _ = txsign.admission_record(forged)
    assert dh == df and pkh == pkf


@pytest.fixture(scope="module")
def fuzz_server():
    engine = VerifyEngine(use_host=True)
    srv = SidecarServer(("127.0.0.1", 0), engine)
    t = threading.Thread(target=srv.serve_forever,
                         kwargs=dict(poll_interval=0.1), daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    engine.stop()
    srv.server_close()


def _poke(port: int, wire: bytes, label: str, disconnect_at=None):
    """Write hostile bytes; the server must reply or drop the
    connection within the timeout — never hang."""
    with socket.create_connection(("127.0.0.1", port), timeout=5.0) as s:
        s.settimeout(5.0)
        if disconnect_at is not None:
            s.sendall(wire[:disconnect_at])
            return  # mid-frame disconnect: close() IS the case
        s.sendall(wire)
        try:
            data = s.recv(4096)
        except socket.timeout:
            raise AssertionError(f"{label}: server hung on hostile frame")
        except OSError:
            return  # connection reset: a clean drop
        # b"" = server closed the connection (the malformed-frame
        # contract); anything else must be a well-formed reply frame.
        if data:
            assert len(data) >= 4, f"{label}: torn reply"


def _assert_serves(port: int, label: str):
    msgs, pks, sigs = _sigs(4, tamper={2}, seed=23)
    with SidecarClient(port=port, timeout=10.0) as client:
        mask = client.verify_batch(msgs, pks, sigs)
    assert mask == [True, True, False, True], \
        f"after {label}: server no longer serves correct verdicts"


def test_live_handler_survives_the_corpus(fuzz_server):
    port = fuzz_server.server_address[1]
    for label, wire in corpus():
        # A frame whose length prefix promises bytes that never arrive
        # is indistinguishable from a slow client while the connection
        # stays open — the server's documented read bound is peer
        # close (protocol._read_exact), so the hostile form of this
        # case is the disconnect, not a held-open half-frame.
        if label.startswith("lying-length"):
            _poke(port, wire, label, disconnect_at=len(wire))
        else:
            _poke(port, wire, label)
    _assert_serves(port, "the whole corpus")


def test_live_handler_survives_mid_frame_disconnects(fuzz_server):
    port = fuzz_server.server_address[1]
    msgs, pks, sigs = _sigs(3, seed=17)
    good = proto.encode_request(1, msgs, pks, sigs)
    rng = random.Random(SEED + 1)
    cuts = sorted(rng.sample(range(1, len(good)), 8))
    for cut in cuts:
        _poke(port, good, f"disconnect-at-{cut}", disconnect_at=cut)
    _assert_serves(port, "mid-frame disconnects")


def test_live_handler_interleaves_hostile_and_honest(fuzz_server):
    """Hostile frames on one connection never corrupt an honest
    pipelined client on another."""
    port = fuzz_server.server_address[1]
    errors = []

    def hostile():
        try:
            for label, wire in corpus()[:16]:
                _poke(port, wire, label)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    t = threading.Thread(target=hostile, daemon=True)
    t.start()
    for _ in range(4):
        _assert_serves(port, "interleaved hostile traffic")
    t.join(timeout=30.0)
    assert not t.is_alive(), "hostile writer hung"
    assert not errors, errors


def test_live_handler_survives_hostile_hellos(fuzz_server):
    """The graftfleet HELLO corpus live: every malformed tenant
    handshake gets an error reply or a clean drop — never a hang — and
    the server keeps serving correct verdicts afterwards."""
    port = fuzz_server.server_address[1]
    for label, wire in corpus():
        if label.startswith("hello-"):
            _poke(port, wire, label)
    _assert_serves(port, "hostile HELLOs")


def test_live_tenant_collision_shares_one_lane(fuzz_server):
    """Two connections HELLOing the SAME tenant id both get accepted
    (collision is by design: they share one DRR lane) and both still
    verify correctly — a collision can never wedge the handshake."""
    port = fuzz_server.server_address[1]
    for _ in range(2):
        with SidecarClient(port=port, timeout=10.0) as client:
            assert client.hello("collide-t0") == "collide-t0"
            assert client.server_version == proto.PROTOCOL_VERSION
            msgs, pks, sigs = _sigs(3, tamper={1}, seed=29)
            assert client.verify_batch(msgs, pks, sigs) == \
                [True, False, True]
    # And a tenant-less client on the same server is untouched.
    _assert_serves(port, "tenant collision")

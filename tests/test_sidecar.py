"""Sidecar service tests: protocol round-trip, server end-to-end, coalescing.

Analogue of the reference's SignatureService tests
(crypto/src/tests/crypto_tests.rs:118-132) at the process boundary.
"""

import threading

import numpy as np
import pytest

from hotstuff_tpu.crypto import ref_ed25519 as ref
from hotstuff_tpu.sidecar import protocol as proto
from hotstuff_tpu.sidecar.client import SidecarClient
from hotstuff_tpu.sidecar.service import SidecarServer, VerifyEngine


def _sigs(n, tamper=()):
    rng = np.random.default_rng(7)
    msgs, pks, sigs = [], [], []
    for i in range(n):
        sk = rng.bytes(32)
        _, pk = ref.generate_keypair(sk)
        msg = rng.bytes(32)
        sig = ref.sign(sk, msg)
        if i in tamper:
            sig = sig[:1] + bytes([sig[1] ^ 0xFF]) + sig[2:]
        msgs.append(msg)
        pks.append(pk)
        sigs.append(sig)
    return msgs, pks, sigs


def test_protocol_roundtrip():
    msgs, pks, sigs = _sigs(3)
    frame = proto.encode_request(42, msgs, pks, sigs)
    opcode, req = proto.decode_request(frame[4:])
    assert opcode == proto.OP_VERIFY_BATCH
    assert req.request_id == 42
    assert req.msgs == msgs and req.pks == pks and req.sigs == sigs

    reply = proto.encode_reply(proto.OP_VERIFY_BATCH, 42, [True, False, True])
    opcode, rid, mask = proto.decode_reply(reply[4:])
    assert (opcode, rid, mask) == (proto.OP_VERIFY_BATCH, 42,
                                   [True, False, True])


@pytest.fixture(scope="module")
def server():
    engine = VerifyEngine()
    srv = SidecarServer(("127.0.0.1", 0), engine)
    t = threading.Thread(target=srv.serve_forever,
                         kwargs=dict(poll_interval=0.1), daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    engine.stop()
    srv.server_close()


def test_sidecar_end_to_end(server):
    port = server.server_address[1]
    with SidecarClient(port=port) as client:
        assert client.ping()
        msgs, pks, sigs = _sigs(10, tamper={3, 7})
        mask = client.verify_batch(msgs, pks, sigs)
        assert mask == [i not in {3, 7} for i in range(10)]


def test_sidecar_concurrent_clients(server):
    port = server.server_address[1]
    results = {}

    def worker(idx):
        with SidecarClient(port=port) as client:
            tamper = {idx}
            msgs, pks, sigs = _sigs(5, tamper=tamper)
            results[idx] = client.verify_batch(msgs, pks, sigs)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for idx, mask in results.items():
        assert mask == [i != idx for i in range(5)]


def test_sidecar_empty_batch(server):
    port = server.server_address[1]
    with SidecarClient(port=port) as client:
        assert client.verify_batch([], [], []) == []


@pytest.fixture(scope="module")
def host_server():
    """Host-crypto server: exercises the BLS ops without device compiles."""
    engine = VerifyEngine(use_host=True)
    srv = SidecarServer(("127.0.0.1", 0), engine)
    t = threading.Thread(target=srv.serve_forever,
                         kwargs=dict(poll_interval=0.1), daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    engine.stop()
    srv.server_close()


def test_sidecar_bls_sign_and_aggregate_verify(host_server):
    """The scheme=bls wire surface: sidecar signing + common-message
    aggregate verification (the QC verify shape of the reference's bls
    branch)."""
    from hotstuff_tpu.offchain import bls12381 as bls

    port = host_server.server_address[1]
    msg = b"qc digest under bls"
    keys = [bls.key_gen(bytes([i]) * 32) for i in range(1, 4)]
    pk_enc = [bls.g1_encode(pk) for _, pk in keys]
    with SidecarClient(port=port) as client:
        sigs = [client.bls_sign(msg, sk.to_bytes(48, "big"))
                for sk, _ in keys]
        assert all(len(s) == 192 for s in sigs)
        agg = bls.g2_encode(bls.aggregate([bls.g2_decode(s) for s in sigs]))
        assert client.bls_verify_aggregate(msg, agg, pk_enc)
        # tampered aggregate rejects
        bad = bls.g2_encode(bls.aggregate(
            [bls.g2_decode(s) for s in sigs[:2]]
            + [bls.sign(keys[0][0], b"other")]))
        assert not client.bls_verify_aggregate(msg, bad, pk_enc)
        # garbage bytes reject instead of crashing the connection
        assert not client.bls_verify_aggregate(msg, b"\x01" * 192, pk_enc)
        assert client.ping()  # connection still healthy


def test_sidecar_bls_multi_digest_verify(host_server):
    """The TC wire shape (OP_BLS_VERIFY_MULTI): per-vote signatures over
    DISTINCT digests verified in one round-trip (round-3 verdict: this
    used to be N per-signature RPCs at view-change time)."""
    from hotstuff_tpu.offchain import bls12381 as bls

    port = host_server.server_address[1]
    keys = [bls.key_gen(bytes([i]) * 32) for i in range(1, 5)]
    msgs = [bytes([i]) * 32 for i in range(4)]  # distinct per-vote digests
    pk_enc = [bls.g1_encode(pk) for _, pk in keys]
    sig_enc = [bls.g2_encode(bls.sign(sk, m))
               for (sk, _), m in zip(keys, msgs)]
    with SidecarClient(port=port) as client:
        assert client.bls_verify_multi(msgs, pk_enc, sig_enc)
        # one signature over the wrong digest rejects the whole TC
        bad = list(sig_enc)
        bad[2] = bls.g2_encode(bls.sign(keys[2][0], b"wrong" * 7))
        assert not client.bls_verify_multi(msgs, pk_enc, bad)
        # signature order can't matter (the aggregate is a sum) ...
        assert client.bls_verify_multi(msgs, pk_enc,
                                       sig_enc[::-1])
        # ... but the pk<->digest pairing does: swapped keys reject
        swapped_pks = [pk_enc[1], pk_enc[0]] + pk_enc[2:]
        assert not client.bls_verify_multi(msgs, swapped_pks, sig_enc)
        # garbage signature bytes reject instead of crashing
        assert not client.bls_verify_multi(msgs, pk_enc,
                                           [b"\x02" * 192] * 4)
        assert client.ping()


def test_protocol_decode_survives_hostile_bytes():
    """Wire-decode fuzz (python counterpart of native test_serde's
    hostile-bytes pass): decode_request raises ValueError on EVERY
    malformed frame — truncations, trailing bytes, hostile counts,
    random garbage — and decodes intact frames; nothing else escapes."""
    import struct

    rng = np.random.default_rng(99)

    good_frames = [
        proto.encode_request(1, [b"m" * 32] * 3, [b"p" * 32] * 3,
                             [b"s" * 64] * 3),
        proto.encode_request(7, [b"m" * 32] * 3, [b"p" * 32] * 3,
                             [b"s" * 64] * 3,
                             opcode=proto.OP_VERIFY_BULK),
        proto.encode_bls_agg_request(3, b"d" * 32, b"g" * 192,
                                     [b"k" * 96] * 2),
        proto.encode_bls_sign_request(4, b"d" * 32, b"x" * 48),
        proto.encode_bls_votes_request(5, b"d" * 32, [b"k" * 96] * 2,
                                       [b"g" * 192] * 2),
        proto.encode_bls_multi_request(6, [b"d" * 32] * 2, [b"k" * 96] * 2,
                                       [b"g" * 192] * 2),
    ]
    for frame in good_frames:
        payload = frame[4:]
        opcode, req = proto.decode_request(payload)  # intact decodes
        assert req.request_id == opcode  # encoders above used rid == op
        # every strict truncation and any trailing garbage must reject
        for cut in range(len(payload)):
            with pytest.raises(ValueError):
                proto.decode_request(payload[:cut])
        with pytest.raises(ValueError):
            proto.decode_request(payload + b"\x00" * 5)

    # PING/STATS carry no records; trailing bytes are explicitly tolerated
    opcode, req = proto.decode_request(proto.encode_ping(2)[4:] + b"\x00")
    assert opcode == proto.OP_PING
    opcode, req = proto.decode_request(
        proto.encode_stats_request(8)[4:] + b"\x00")
    assert opcode == proto.OP_STATS

    # hostile stats bodies reject instead of crashing the client
    with pytest.raises(ValueError):
        proto.decode_stats_body(b"\xff\xfe not json")
    with pytest.raises(ValueError):
        proto.decode_stats_body(b"[1, 2, 3]")
    assert proto.decode_stats_body(b"{\"launches\": 3}") == {"launches": 3}

    # random garbage: ValueError or (rarely) a well-formed parse, nothing else
    for size in (0, 1, 4, 10, 11, 64, 333):
        try:
            proto.decode_request(bytes(rng.bytes(size)))
        except ValueError:
            pass

    # hostile record counts far beyond the actual frame size must reject
    # BEFORE any allocation sized by the count (uses the real header
    # struct so this tracks wire-format changes)
    for op in (proto.OP_VERIFY_BATCH, proto.OP_VERIFY_BULK,
               proto.OP_BLS_VERIFY_AGG, proto.OP_BLS_VERIFY_VOTES,
               proto.OP_BLS_VERIFY_MULTI):
        hostile = proto._HDR.pack(op, 7, 0xFFFFFF, 32) + b"\x01" * 64
        with pytest.raises(ValueError):
            proto.decode_request(hostile)


def test_engine_mesh_mode_buckets_to_warmed_shapes(monkeypatch):
    """VerifyEngine(mesh_devices=8) on the virtual CPU mesh: requests of
    awkward sizes must verify correctly AND pad to power-of-two per-shard
    shapes (the round-3 advisor's mid-traffic compile hazard — only
    warmed shapes may reach the device program)."""
    from hotstuff_tpu.parallel import sharded_verify as sv

    # Spy on the pack-stage h2d seam (_shard_put): every mesh launch
    # ships its padded per-record arrays through it, so the row counts
    # it sees ARE the launched shapes.  (The verifier factories are
    # functools.cached across the test session and can't be spied.)
    launched = []
    real_put = sv._shard_put

    def spying(mesh, arr):
        launched.append(arr.shape[0])
        return real_put(mesh, arr)

    monkeypatch.setattr(sv, "_shard_put", spying)
    engine = VerifyEngine(mesh_devices=8)
    try:
        # n=3 -> per-shard 1 (floored at _MIN_BUCKET/8) -> m=8;
        # n=13 -> per-shard 2 -> m=16: always n_dev * power-of-two.
        for n, tamper, want_m in ((3, {1}, 8), (8, set(), 8),
                                  (13, {0, 12}, 16)):
            launched.clear()
            msgs, pks, sigs = _sigs(n, tamper=tamper)
            got = engine._verify(msgs, pks, sigs)
            assert list(got) == [i not in tamper for i in range(n)]
            # One ladder launch = the five packed arrays, all at the
            # shard-aligned row count.
            assert launched == [want_m] * 5, (n, launched)
    finally:
        engine.stop()


def test_bls_verdict_cache_dedups_pairings(host_server):
    """N replicas verifying one certificate must cost one pairing: the
    second identical BLS verify answers from the verdict cache (on the
    connection thread - no engine hop), for positive AND negative
    verdicts, without poisoning different requests."""
    from unittest.mock import patch

    from hotstuff_tpu.offchain import bls12381 as bls

    port = host_server.server_address[1]
    engine = host_server.engine
    keys = [bls.key_gen(bytes([40 + i]) * 32) for i in range(1, 4)]
    msg = b"cache me" * 4
    pk_enc = [bls.g1_encode(pk) for _, pk in keys]
    agg = bls.g2_encode(bls.aggregate(
        [bls.sign(sk, msg) for sk, _ in keys]))
    with SidecarClient(port=port) as client:
        assert client.bls_verify_aggregate(msg, agg, pk_enc)
        # Replay: the engine must not pair again.  verify_aggregate_common
        # is the host pairing entry - a second call would go through it.
        with patch.object(bls, "verify_aggregate_common",
                          side_effect=AssertionError("paired twice")):
            assert client.bls_verify_aggregate(msg, agg, pk_enc)
        # Negative verdicts cache too, and only for their exact bytes.
        bad = bls.g2_encode(bls.sign(keys[0][0], b"forged" * 5))
        assert not client.bls_verify_aggregate(msg, bad, pk_enc)
        with patch.object(bls, "verify_aggregate_common",
                          side_effect=AssertionError("paired twice")):
            assert not client.bls_verify_aggregate(msg, bad, pk_enc)
        # Distinct request still verifies correctly (cache miss).
        msg2 = b"other msg" * 3
        agg2 = bls.g2_encode(bls.aggregate(
            [bls.sign(sk, msg2) for sk, _ in keys]))
        assert client.bls_verify_aggregate(msg2, agg2, pk_enc)
    assert any(k and isinstance(k, tuple) and k[0] == "ba"
               for k in engine._verdicts)


def test_bls_transient_failure_replies_none_and_never_caches(host_server):
    """The verdict cache is shared by every replica, so a TRANSIENT
    failure (wedged device, backend exception) must reply None and leave
    the cache untouched — a cached [False] would reject a valid
    certificate fleet-wide.  Verdicts enter the cache only at the
    explicit cacheable=True sites in _execute_bls."""
    from unittest.mock import patch

    from hotstuff_tpu.offchain import bls12381 as bls
    from hotstuff_tpu.sidecar import service

    engine = host_server.engine
    keys = [bls.key_gen(bytes([60 + i]) * 32) for i in range(1, 4)]
    msg = b"transient" * 4
    pk_enc = [bls.g1_encode(pk) for _, pk in keys]
    agg = bls.g2_encode(bls.aggregate([bls.sign(sk, msg)
                                       for sk, _ in keys]))
    req = proto.BlsAggRequest(9, msg, agg, pk_enc)
    key = engine.bls_cache_key(req)
    assert key not in engine._verdicts

    # Engine-thread behavior under a transient backend failure: the
    # exception is contained INSIDE _execute_bls, which answers None
    # through its single idempotent reply helper (graftview satellite:
    # _run installs no backstop reply any more, so a path that both
    # replied and raised can no longer double-reply).
    replies = []
    with patch.object(bls, "verify_aggregate_common",
                      side_effect=RuntimeError("device wedged")):
        engine._execute_bls(service._Pending(req, replies.append))
    assert replies == [None], "transient failure must reply exactly None"
    assert key not in engine._verdicts, "transient failure poisoned cache"

    # A retry without the fault verifies and NOW caches the true verdict.
    engine._execute_bls(service._Pending(req, replies.append))
    assert replies == [None, [True]]
    assert engine._verdicts[key] is True


def test_bls_single_reply_discipline_suppresses_double_reply(host_server):
    """Every BLS path answers EXACTLY once: an exception escaping AFTER
    a successful reply (the wedged-then-completing shape the guard will
    produce once BLS launches are supervised, ROADMAP item 3) must not
    drive the error path into a second reply — the idempotent helper
    suppresses it."""
    from hotstuff_tpu.offchain import bls12381 as bls
    from hotstuff_tpu.sidecar import service

    engine = host_server.engine
    sk, pk = bls.key_gen(bytes([55]) * 32)
    msg = b"once" * 8
    sig = bls.g2_encode(bls.sign(sk, msg))
    req = proto.BlsVotesRequest(11, msg, [bls.g1_encode(pk)], [sig])

    attempts = []

    def reply_then_die(payload):
        attempts.append(payload)
        raise BrokenPipeError("client went away mid-reply")

    # The reply itself raises: _execute_bls's exception handler runs
    # with replied already set — its None is suppressed, and exactly one
    # reply attempt (the real verdict) was made.
    engine._execute_bls(service._Pending(req, reply_then_die))
    assert attempts == [[True]]


def test_bls_decode_failure_is_cacheable_false(host_server):
    """Decode failures are a pure function of the request bytes, so they
    cache as False (same request -> same rejection, no pairing)."""
    from hotstuff_tpu.sidecar import service

    engine = host_server.engine
    req = proto.BlsAggRequest(11, b"m" * 32, b"\x01" * 192, [b"\x02" * 96])
    replies = []
    engine._execute_bls(service._Pending(req, replies.append))
    assert replies == [[False]]
    assert engine._verdicts[engine.bls_cache_key(req)] is False


# ---------------------------------------------------------------------------
# graftchaos: the protocol v3 OP_CHAOS hook (service.ChaosState)
# ---------------------------------------------------------------------------


def test_protocol_chaos_roundtrip_and_hostile_bytes():
    frame = proto.encode_chaos_request(5, {"delay_ms": 100, "shed": 2})
    opcode, req = proto.decode_request(frame[4:])
    assert opcode == proto.OP_CHAOS
    assert req.request_id == 5
    assert req.spec == {"delay_ms": 100, "shed": 2}
    # body length must match the count field; garbage JSON raises
    import struct

    bad = proto._HDR.pack(proto.OP_CHAOS, 1, 4, 0) + b"{}"
    with pytest.raises(ValueError):
        proto.decode_request(bad)
    bad = proto._HDR.pack(proto.OP_CHAOS, 1, 5, 0) + b"{nope"
    with pytest.raises(ValueError):
        proto.decode_request(bad)
    bad = proto._HDR.pack(proto.OP_CHAOS, 1, 2, 0) + b"[]"
    with pytest.raises(ValueError):
        proto.decode_request(bad)
    assert struct.unpack(">I", frame[:4])[0] == len(frame) - 4


@pytest.fixture(scope="module")
def chaos_server():
    """Host-crypto server with the chaos hook armed (--chaos)."""
    from hotstuff_tpu.sidecar.service import ChaosState

    engine = VerifyEngine(use_host=True)
    srv = SidecarServer(("127.0.0.1", 0), engine, chaos=ChaosState())
    t = threading.Thread(target=srv.serve_forever,
                         kwargs=dict(poll_interval=0.1), daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    engine.stop()
    srv.server_close()


def test_chaos_refused_without_flag(host_server):
    port = host_server.server_address[1]
    with SidecarClient(port=port) as client:
        assert client.chaos(shed=1) is False
        # ... and nothing was configured: verifies run normally
        msgs, pks, sigs = _sigs(3)
        assert client.verify_batch(msgs, pks, sigs) == [True] * 3


def test_chaos_forced_shed_then_recovers(chaos_server):
    from hotstuff_tpu.sidecar.client import SidecarOverloaded

    port = chaos_server.server_address[1]
    with SidecarClient(port=port) as client:
        assert client.chaos(shed=2) is True
        msgs, pks, sigs = _sigs(4)
        for _ in range(2):
            with pytest.raises(SidecarOverloaded):
                client.verify_batch(msgs, pks, sigs)
        # budget consumed: the next verify is honest again
        assert client.verify_batch(msgs, pks, sigs) == [True] * 4


def test_chaos_bounded_delay_applies_and_clears(chaos_server):
    import threading
    import time

    port = chaos_server.server_address[1]
    with SidecarClient(port=port) as client:
        msgs, pks, sigs = _sigs(2)
        client.verify_batch(msgs, pks, sigs)  # warm: engine, not chaos
        assert client.chaos(delay_ms=300) is True
        t0 = time.monotonic()
        assert client.verify_batch(msgs, pks, sigs) == [True] * 2
        assert time.monotonic() - t0 >= 0.3
        # PING is exempt EVEN when pipelined behind a delayed verify on
        # the same connection: delays reschedule onto a timer, the
        # reader thread keeps draining (readiness probes stay honest).
        done = {}

        def delayed_verify():
            done["mask"] = client.verify_batch(msgs, pks, sigs)

        t = threading.Thread(target=delayed_verify)
        t.start()
        time.sleep(0.05)  # verify request is in flight, reply delayed
        t0 = time.monotonic()
        assert client.ping()
        assert time.monotonic() - t0 < 0.25
        t.join(timeout=10)
        assert done["mask"] == [True] * 2
        assert client.chaos(clear=True) is True
        t0 = time.monotonic()
        assert client.verify_batch(msgs, pks, sigs) == [True] * 2
        assert time.monotonic() - t0 < 0.25


def test_chaos_delay_capped_at_maximum(chaos_server):
    from hotstuff_tpu.sidecar.service import ChaosState

    state = chaos_server.chaos
    state.configure({"delay_ms": 10 ** 9})
    assert state.delay_ms == ChaosState.MAX_DELAY_MS
    state.configure({"clear": True})
    assert state.delay_ms == 0
    with pytest.raises(ValueError):
        state.configure({"explode": 1})
    with pytest.raises(ValueError):
        state.configure({"shed": -1})
    with pytest.raises(ValueError):
        state.configure({"shed": True})


def test_chaos_connection_drop(chaos_server):
    port = chaos_server.server_address[1]
    with SidecarClient(port=port) as control:
        assert control.chaos(drop=1) is True
        msgs, pks, sigs = _sigs(2)
        # The victim connection dies on its next verify...
        with SidecarClient(port=port) as victim:
            with pytest.raises((ConnectionError, OSError)):
                victim.verify_batch(msgs, pks, sigs)
        # ...and the server is healthy for the connection after it.
        assert control.verify_batch(msgs, pks, sigs) == [True] * 2
